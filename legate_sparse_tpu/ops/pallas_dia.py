# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Pallas TPU kernel for banded (DIA) SpMV — the roofline hot path.

Why this kernel exists (measured on the target v5e chip, 2^24 rows,
11 diagonals, f32, loop-amortized timing):

=====================================  ==========
formulation                            bandwidth
=====================================  ==========
XLA ``.at[lo:hi].add`` shifted adds     51 GB/s
XLA pad + slice shifted adds            84 GB/s
XLA ``jnp.roll`` shifted adds           74 GB/s
MXU shift-matmul                        49 GB/s
**this kernel**                        **622 GB/s**
chip HBM roofline (v5e)                 819 GB/s
=====================================  ==========

Every XLA formulation of the stencil shift pays a full lane-relayout
per diagonal (a flat shift by ±1 moves every element across the
(8, 128) tiled layout), so the op runs ~10x under roofline.  The
Mosaic-level fix: keep the shift *inside* VMEM as register rotates —
``pltpu.roll`` on the lane and sublane axes plus a lane-boundary
select — so HBM sees only perfectly aligned streaming loads.

Design (role parity with the reference's hand-tuned SpMV leaf,
``src/sparse/array/csr/spmv.cu:62-152``):

- **Row-aligned band layout**: ``rdata[d, i] = A[i, i + off_d]``
  (vs scipy DIA's column-aligned ``data[d, j] = A[j - off_d, j]``), so
  the kernel's data tile multiplies an x window shifted by ``off_d``
  with no data-side shift.  Out-of-range and hole slots hold 0.
- The x vector is viewed as three aligned neighbor tiles
  (prev/center/next, clamped at the edges) so a shifted window never
  needs a misaligned HBM load; Mosaic requires dynamic vector loads to
  be 1024-element aligned, which is exactly what this avoids.
- A flat shift by ``s = q*L + r`` (floor divmod, lane width L=128)
  becomes: sublane-roll by ``q`` (and ``q+1``), lane-roll by ``r``,
  then a lane-index select between the two — three register ops, no
  relayout.
- IEEE invariant: shifted x values are zeroed *before* the multiply at
  out-of-range slots and band holes (explicit-entry mask), so a
  non-finite x entry a row never references cannot inject NaN —
  matching CSR semantics exactly (same contract as ``ops/spmv.py``).

Supported: f32/bf16 values (f64 is rejected — Mosaic has no 64-bit
vectors; the XLA path in ``ops/dia_ops.py`` is the f64 fallback),
``max|offset| <= tile`` (tile auto-grows to 2^17), any rectangular
shape.  The wrapper returns None when unsupported and the caller falls
back to the XLA kernels.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs

L = 128                 # TPU lane width
TILE_MIN = 1 << 14      # default rows per grid step (multiple of 1024)
TILE_MAX = 1 << 17      # beyond this the VMEM working set is too large
# VMEM budget for one grid step (bytes); conservative vs the ~128 MB/core.
_VMEM_BUDGET = 96 << 20


def _tile_override() -> Optional[int]:
    """Operator-forced rows-per-grid-step (``LEGATE_SPARSE_TPU_PALLAS_TILE``,
    power of two in [2^10, TILE_MAX]).  Exists for on-chip tuning and
    fault isolation: the tile sets the grid length (2^24 rows = 1024
    steps at the default 2^14), and a grid-length-dependent fault looks
    exactly like the r3 loop-composition crash.  Read at dispatch
    time; invalid values are ignored with a warning."""
    v = os.environ.get("LEGATE_SPARSE_TPU_PALLAS_TILE")
    if not v:
        return None
    try:
        t = int(v)
        if t >= 1024 and t <= TILE_MAX and (t & (t - 1)) == 0:
            return t
    except ValueError:
        pass
    import sys

    sys.stderr.write(
        f"legate_sparse_tpu: ignoring invalid "
        f"LEGATE_SPARSE_TPU_PALLAS_TILE={v!r}\n"
    )
    return None


def _auto_tile(max_abs_off: int) -> Optional[int]:
    """Smallest default tile covering the band reach, or None."""
    tile = TILE_MIN
    while tile < max_abs_off and tile < TILE_MAX:
        tile *= 2
    return tile if max_abs_off <= tile else None


def choose_tile(max_abs_off: int) -> Optional[int]:
    """Smallest supported tile covering the band reach, or None.
    An operator override wins when it covers the reach."""
    forced = _tile_override()
    if forced is not None and max_abs_off <= forced:
        return forced
    return _auto_tile(max_abs_off)


def supported(offsets: Tuple[int, ...], dtype, masked: bool) -> Optional[int]:
    """Return the tile size to use, or None when the kernel can't run."""
    if np.dtype(dtype) not in (np.dtype(np.float32),
                               np.dtype(jnp.bfloat16)):
        return None
    if not offsets:
        return None
    nd = len(offsets)
    itemsize = np.dtype(dtype).itemsize

    def vmem_of(t: int) -> int:
        return t * itemsize * (3 + 1) + nd * t * (itemsize + masked)

    tile = choose_tile(max(abs(o) for o in offsets))
    if tile is None:
        return None
    if vmem_of(tile) > _VMEM_BUDGET:
        if _tile_override() == tile:
            # A forced tile that blows the VMEM budget must degrade to
            # the auto choice (warned), not silently disable the
            # kernel — same contract as an invalid override value.
            import sys

            auto = _auto_tile(max(abs(o) for o in offsets))
            if auto is not None and vmem_of(auto) <= _VMEM_BUDGET:
                sys.stderr.write(
                    f"legate_sparse_tpu: LEGATE_SPARSE_TPU_PALLAS_TILE="
                    f"{tile} exceeds the VMEM budget for this band; "
                    f"using tile {auto}\n"
                )
                return auto
        return None
    return tile


@partial(jax.jit, static_argnames=("offsets", "shape", "tile", "with_mask"))
def row_align(dia_data, offsets: Tuple[int, ...], shape: Tuple[int, int],
              tile: int, mask=None, with_mask: bool = False):
    """Repack scipy-layout DIA storage into the kernel's row-aligned,
    tile-padded 2-D block layout.

    Returns ``(rdata, rmask)``: rdata is (nd, rows_pad // L, L) with
    ``rdata[d, i] = dia_data[d, i + off_d]`` for in-range slots else 0;
    rmask (int8, same blocking) is all-1 at explicit entries when
    ``with_mask`` else None.  Runs once per matrix at structure-cache
    build (the analog of Legion caching image partitions, ref §3.2).
    """
    rows, cols = shape
    rows_pad = -(-rows // tile) * tile
    width = dia_data.shape[1]

    def shift_one(row, off):
        # out[i] = row[i + off] for 0 <= i + off < width, else 0.
        # Right pad covers tall matrices (rows_pad > width) so the
        # slice end tile+off+rows_pad always stays in range.
        padded = jnp.pad(row, (tile, tile + rows_pad))
        return jax.lax.dynamic_slice(padded, (tile + off,), (rows_pad,))

    parts = []
    mparts = []
    i = jnp.arange(rows_pad, dtype=jnp.int32)
    for d, off in enumerate(offsets):
        valid = (
            (i + off >= 0) & (i + off < min(cols, width)) & (i < rows)
        )
        shifted = shift_one(dia_data[d], off)
        parts.append(jnp.where(valid, shifted, 0).reshape(-1, L))
        if with_mask:
            ms = shift_one(mask[d].astype(jnp.int8), off)
            mparts.append(
                jnp.where(valid, ms, 0).astype(jnp.int8).reshape(-1, L)
            )
    rdata = jnp.stack(parts)
    rmask = jnp.stack(mparts) if with_mask else None
    return rdata, rmask


def _use_mosaic_roll() -> bool:
    """Roll lowering inside the kernels: ``pltpu.roll`` (default) or
    plain ``jnp.roll`` with ``LEGATE_SPARSE_TPU_PALLAS_ROLL=xla``.
    Both operate on VMEM-resident tiles, so the jnp variant's relayout
    is VPU shuffle work, not HBM traffic — a fallback lowering in case
    the Mosaic roll primitive is implicated in the on-chip worker
    fault (fault_isolate's ``pallas-jroll`` mode probes it).

    Read at kernel TRACE time and not part of the jit key: set it
    before the first banded op of the process (the isolation harness
    uses one subprocess per probe, so each reads it fresh)."""
    return os.environ.get("LEGATE_SPARSE_TPU_PALLAS_ROLL", "tpu") != "xla"


def _distinct_inputs() -> bool:
    """Band-kernel neighbor-tile inputs (SpMV, SpMM, and the banded
    SpGEMM): pass the SAME padded buffer three times with clamped
    index maps (default, zero-copy), or three DISTINCT tile-shifted
    copies with plain index maps
    (``LEGATE_SPARSE_TPU_PALLAS_INPUTS=distinct``).

    The distinct mode exists as a fault-isolation rung: the r3 on-chip
    worker fault appears only when the kernel is embedded in a jitted
    fori_loop (eager launches at full size pass), and the loop is
    exactly where XLA's buffer reuse interacts with the three aliased
    operands + min/max index maps.  Distinct copies cost one extra
    pass over x per call (~15% of the band traffic at the bench
    shape) and remove both structural suspects at once.

    Read at kernel TRACE time, not part of the jit key — set before
    the first banded op of the process (the isolation harness and the
    bench canary ladder run one subprocess per variant)."""
    return os.environ.get(
        "LEGATE_SPARSE_TPU_PALLAS_INPUTS", "alias") == "distinct"


def _shifted_triple(buf, blocks: int, axis: int):
    """(minus, center, plus): DISTINCT tile-shifted copies of ``buf``
    along ``axis`` (shift unit = ``blocks`` rows), zero edge tiles,
    separated by an optimization barrier so XLA cannot re-alias them —
    the shared construction for the de-aliased input mode."""
    shape = list(buf.shape)
    shape[axis] = blocks
    z = jnp.zeros(shape, buf.dtype)
    def take(lo, hi):
        idx = [slice(None)] * buf.ndim
        idx[axis] = slice(lo, hi)
        return buf[tuple(idx)]
    minus = jnp.concatenate([z, take(None, -blocks)], axis=axis)
    plus = jnp.concatenate([take(blocks, None), z], axis=axis)
    return jax.lax.optimization_barrier((minus, buf, plus))


def _flat_shift(w, s: int, lane, interpret: bool, axis: int = 0):
    """xs with ``xs_flat[p] = w_flat[p + s]`` along the flattened last
    two dims of ``w`` (.., R, L); leading dims (axis base > 0) are
    batch.  Rows wrap modulo R — callers only read rows whose sources
    stay in bounds.  Lowered as sublane+lane rolls plus a lane select
    against the caller-built ``lane`` iota (same shape as ``w``)."""
    R = w.shape[axis]
    q, r = divmod(s, L)

    if interpret or not _use_mosaic_roll():
        roll = lambda a, amt, ax: jnp.roll(a, amt, ax)
    else:
        from jax.experimental.pallas import tpu as pltpu

        # The shift operand must be i32: a plain Python int binds as a
        # weak i64 constant in an x64-enabled process, which
        # tpu.dynamic_rotate rejects at Mosaic verification (caught by
        # the off-chip TPU-export regression tests; on-chip processes
        # run x64-off so the lowering there is unchanged).
        roll = lambda a, amt, ax: pltpu.roll(a, np.int32(amt), ax)

    def rowroll(q_):
        amt = (R - q_) % R
        return roll(w, amt, axis) if amt else w

    if r == 0:
        return rowroll(q)
    a = roll(rowroll(q), L - r, axis + 1)
    b = roll(rowroll(q + 1), L - r, axis + 1)
    return jnp.where(lane < L - r, a, b)


def _make_kernel(offsets: Tuple[int, ...], rows: int, cols: int,
                 tile: int, masked: bool, interpret: bool):
    Rt = tile // L

    def kernel(*refs):
        if masked:
            xm_ref, xc_ref, xp_ref, d_ref, m_ref, y_ref = refs
        else:
            xm_ref, xc_ref, xp_ref, d_ref, y_ref = refs
            m_ref = None
        import jax.experimental.pallas as pl

        base = pl.program_id(0) * tile
        w = jnp.concatenate([xm_ref[:], xc_ref[:], xp_ref[:]], axis=0)
        lane3 = jax.lax.broadcasted_iota(jnp.int32, (3 * Rt, L), 1)
        row_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, L), 0)
        lane_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, L), 1)
        gi = base + row_t * L + lane_t            # global output row
        dtype = d_ref.dtype
        acc_dtype = jnp.float32 if dtype != jnp.float64 else dtype
        acc = jnp.zeros((Rt, L), acc_dtype)
        for di, off in enumerate(offsets):
            xs = _flat_shift(w, off, lane3, interpret)[Rt: 2 * Rt]
            valid = (gi + off >= 0) & (gi + off < cols) & (gi < rows)
            if masked:
                valid = valid & (m_ref[di] > 0)
            xsafe = jnp.where(valid, xs, jnp.zeros((), xs.dtype))
            acc = acc + (d_ref[di] * xsafe).astype(acc_dtype)
        y_ref[:] = acc.astype(dtype)

    return kernel


@partial(jax.jit,
         static_argnames=("offsets", "shape", "tile", "interpret"))
def pallas_dia_spmv(rdata, rmask, x, offsets: Tuple[int, ...],
                    shape: Tuple[int, int], tile: int,
                    interpret: bool = False):
    """y = A @ x over the row-aligned band layout (see ``row_align``).

    ``rdata``/``rmask`` blocked (nd, rows_pad//L, L); x of length cols.
    """
    import jax.experimental.pallas as pl

    rows, cols = shape
    Rt = tile // L
    nd = len(offsets)
    rows_pad = rdata.shape[1] * L
    nt = rows_pad // tile
    # x padded so every clamped neighbor-tile view is in range.
    x_pad = -(-max(cols, rows_pad) // tile) * tile
    ntx = x_pad // tile
    xv = jnp.pad(x, (0, x_pad - cols)).reshape(-1, L)

    masked = rmask is not None
    kernel = _make_kernel(offsets, rows, cols, tile, masked, interpret)

    if _distinct_inputs():
        # Three separate tile-shifted buffers, plain index maps.  The
        # zero edge tiles are safe: every read whose global source row
        # is out of range is masked by `valid` inside the kernel.
        xm_b, xc_b, xp_b = _shifted_triple(xv, Rt, axis=0)
        in_specs = [
            pl.BlockSpec((Rt, L), lambda i: (i, 0)),
            pl.BlockSpec((Rt, L), lambda i: (i, 0)),
            pl.BlockSpec((Rt, L), lambda i: (i, 0)),
            pl.BlockSpec((nd, Rt, L), lambda i: (0, i, 0)),
        ]
        args = [xm_b, xc_b, xp_b, rdata]
    else:
        in_specs = [
            pl.BlockSpec((Rt, L), lambda i: (jnp.maximum(i - 1, 0), 0)),
            pl.BlockSpec((Rt, L), lambda i: (jnp.minimum(i, ntx - 1), 0)),
            pl.BlockSpec((Rt, L),
                         lambda i: (jnp.minimum(i + 1, ntx - 1), 0)),
            pl.BlockSpec((nd, Rt, L), lambda i: (0, i, 0)),
        ]
        args = [xv, xv, xv, rdata]
    if masked:
        in_specs.append(pl.BlockSpec((nd, Rt, L), lambda i: (0, i, 0)))
        args.append(rmask)

    y2 = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_pad // L, L), rdata.dtype),
        grid=(nt,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((Rt, L), lambda i: (i, 0)),
        interpret=interpret,
    )(*args)
    return y2.reshape(-1)[:rows]


def _make_spmm_kernel(offsets: Tuple[int, ...], rows: int, cols: int,
                      tile: int, masked: bool, interpret: bool):
    """SpMM (dense multi-RHS) variant: X tiles are (tile, k), shifts
    move whole rows — a pure sublane roll, no lane decomposition."""

    def kernel(*refs):
        if masked:
            xm_ref, xc_ref, xp_ref, d_ref, m_ref, y_ref = refs
        else:
            xm_ref, xc_ref, xp_ref, d_ref, y_ref = refs
            m_ref = None
        import jax.experimental.pallas as pl

        if interpret or not _use_mosaic_roll():
            roll = lambda a, amt: jnp.roll(a, amt, 0)
        else:
            from jax.experimental.pallas import tpu as pltpu

            # i32 shift for the same reason as _flat_shift's roll.
            roll = lambda a, amt: pltpu.roll(a, np.int32(amt), 0)

        base = pl.program_id(0) * tile
        w = jnp.concatenate([xm_ref[:], xc_ref[:], xp_ref[:]], axis=0)
        R3 = 3 * tile
        gi = base + jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
        dtype = d_ref.dtype
        acc_dtype = jnp.float32 if dtype != jnp.float64 else dtype
        acc = jnp.zeros((tile, w.shape[1]), acc_dtype)
        for di, off in enumerate(offsets):
            xs = roll(w, (R3 - (tile + off)) % R3)[:tile]
            valid = (gi + off >= 0) & (gi + off < cols) & (gi < rows)
            if masked:
                valid = valid & (m_ref[di] > 0)
            xsafe = jnp.where(valid, xs, jnp.zeros((), xs.dtype))
            acc = acc + (d_ref[di] * xsafe).astype(acc_dtype)
        y_ref[:] = acc.astype(dtype)

    return kernel


# Widest dense X the SpMM kernel takes before falling back (VMEM: the
# three neighbor tiles + output at k lanes each).
SPMM_MAX_K = 1024


@partial(jax.jit,
         static_argnames=("offsets", "shape", "tile", "interpret"))
def pallas_dia_spmm(rdata, rmask, X, offsets: Tuple[int, ...],
                    shape: Tuple[int, int], tile: int,
                    interpret: bool = False):
    """Y = A @ X for dense X (cols, k) over the row-aligned band pack.

    Row shifts of a 2-D X are sublane-dimension rolls — cheaper than
    the SpMV case, which must also decompose across lanes.
    """
    import jax.experimental.pallas as pl

    rows, cols = shape
    nd = len(offsets)
    k = X.shape[1]
    rows_pad = rdata.shape[1] * rdata.shape[2]
    nt = rows_pad // tile
    x_pad = -(-max(cols, rows_pad) // tile) * tile
    ntx = x_pad // tile
    Xv = jnp.pad(X, ((0, x_pad - cols), (0, 0)))
    # Row-vector view of the band data: (nd, rows_pad, 1) broadcasts
    # over X's k columns (bitcast-compatible reshape of the SpMV pack).
    rd = rdata.reshape(nd, rows_pad, 1)
    rm = rmask.reshape(nd, rows_pad, 1) if rmask is not None else None

    masked = rm is not None
    kernel = _make_spmm_kernel(offsets, rows, cols, tile, masked,
                               interpret)
    if _distinct_inputs():
        # De-aliased variant (see the SpMV case in pallas_dia_spmv):
        # three separate tile-shifted X buffers, plain index maps.
        Xm, Xc, Xp = _shifted_triple(Xv, tile, axis=0)
        in_specs = [
            pl.BlockSpec((tile, k), lambda i: (i, 0)),
            pl.BlockSpec((tile, k), lambda i: (i, 0)),
            pl.BlockSpec((tile, k), lambda i: (i, 0)),
            pl.BlockSpec((nd, tile, 1), lambda i: (0, i, 0)),
        ]
        args = [Xm, Xc, Xp, rd]
    else:
        in_specs = [
            pl.BlockSpec((tile, k), lambda i: (jnp.maximum(i - 1, 0), 0)),
            pl.BlockSpec((tile, k),
                         lambda i: (jnp.minimum(i, ntx - 1), 0)),
            pl.BlockSpec((tile, k),
                         lambda i: (jnp.minimum(i + 1, ntx - 1), 0)),
            pl.BlockSpec((nd, tile, 1), lambda i: (0, i, 0)),
        ]
        args = [Xv, Xv, Xv, rd]
    if masked:
        in_specs.append(pl.BlockSpec((nd, tile, 1), lambda i: (0, i, 0)))
        args.append(rm)

    Y = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_pad, k), rdata.dtype),
        grid=(nt,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tile, k), lambda i: (i, 0)),
        interpret=interpret,
    )(*args)
    return Y[:rows]


_SPMM_FAILED: set = set()
_SPMM_OK: set = set()


def _spmm_tile(packed, k: int) -> Optional[int]:
    """Row-tile for the SpMM kernel: VMEM scales with k, so it is
    chosen per (band, k) — a power-of-two divisor of the SpMV tile that
    still covers the band reach and fits the budget."""
    max_off = max(abs(o) for o in packed.offsets)
    tile = 1024
    while tile < max_off:
        tile *= 2
    if tile > packed.tile:
        return None
    itemsize = np.dtype(packed.rdata.dtype).itemsize
    nd = len(packed.offsets)
    vmem = 4 * tile * k * itemsize + nd * tile * (itemsize + 1)
    return tile if vmem <= _VMEM_BUDGET else None


def dia_spmm_maybe_pallas(packed, X):
    """SpMM through the Pallas kernel, or None for the XLA fallback."""
    mode = _mode()
    if mode == "0" or packed is None:
        return None
    k = X.shape[1]
    if k == 0 or k > SPMM_MAX_K:
        return None
    interpret = mode == "interpret"
    if not interpret:
        try:
            if jax.devices()[0].platform != "tpu":
                return None
        except Exception:
            return None
    tile = _spmm_tile(packed, k)
    if tile is None:
        return None
    key = (packed.offsets, tile, k, str(packed.rdata.dtype),
           packed.rmask is not None, packed.shape, interpret)
    if key in _SPMM_FAILED:
        return None
    # Never FIRST-attempt inside an outer trace (compile errors there
    # escape this except with no fallback); eager calls prove the key.
    if key not in _SPMM_OK:
        try:
            from jax._src.core import trace_state_clean

            if not trace_state_clean():
                return None
        except ImportError:
            return None
    try:
        with _obs.span("pallas.spmm", tile=tile, k=int(k),
                       num_diags=len(packed.offsets)):
            y = pallas_dia_spmm(
                packed.rdata, packed.rmask, X, packed.offsets,
                packed.shape, tile, interpret=interpret,
            )
        _SPMM_OK.add(key)
        return y
    except Exception as e:
        import sys

        sys.stderr.write(
            f"legate_sparse_tpu: pallas DIA SpMM unavailable "
            f"({e!r:.200}); using XLA path\n"
        )
        _obs.inc("op.pallas_fallback.spmm")
        _obs.event("pallas.fallback", kernel="spmm",
                   error=repr(e)[:200])
        _SPMM_FAILED.add(key)
        return None


def _make_spgemm_kernel(offs_a: Tuple[int, ...], offs_b: Tuple[int, ...],
                        offs_c: Tuple[int, ...], shape_a, shape_b,
                        tile: int, interpret: bool):
    """Banded SpGEMM: C[oc, j] += A[oa, j-ob] * B[ob, j] over all
    (oa, ob) pairs.  B and C are j-aligned; only A needs the roll-shift
    (by -ob), so per B-diagonal ALL of A's diagonals shift together.
    Exact bands only (the dispatch gates on no hole masks), so validity
    is the static per-pair range [j_lo, j_hi)."""
    m, k = shape_a
    _, n = shape_b
    Rt = tile // L
    idx_c = {o: i for i, o in enumerate(offs_c)}

    def kernel(am_ref, ac_ref, ap_ref, b_ref, c_ref):
        import jax.experimental.pallas as pl

        base = pl.program_id(0) * tile
        wA = jnp.concatenate([am_ref[:], ac_ref[:], ap_ref[:]], axis=1)
        lane3 = jax.lax.broadcasted_iota(
            jnp.int32, (wA.shape[0], 3 * Rt, L), 2
        )
        row_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, L), 0)
        lane_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, L), 1)
        gj = base + row_t * L + lane_t           # global output column
        dtype = b_ref.dtype
        acc_dtype = jnp.float32 if dtype != jnp.float64 else dtype
        accs = [jnp.zeros((Rt, L), acc_dtype) for _ in offs_c]
        for b_i, ob in enumerate(offs_b):
            # One shift serves every A diagonal for this ob.
            xsA = _flat_shift3(wA, -ob, lane3, interpret)[:, Rt: 2 * Rt, :]
            bt = b_ref[b_i]
            for a_i, oa in enumerate(offs_a):
                oc = oa + ob
                j_lo = max(0, ob, oc)
                j_hi = min(n, k + ob, m + oc)
                if j_hi <= j_lo:
                    continue
                valid = (gj >= j_lo) & (gj < j_hi)
                contrib = jnp.where(valid, xsA[a_i] * bt,
                                    jnp.zeros((), dtype))
                ci = idx_c[oc]
                accs[ci] = accs[ci] + contrib.astype(acc_dtype)
        c_ref[:] = jnp.stack(accs).astype(dtype)

    return kernel


def _flat_shift3(w3, s: int, lane3, interpret: bool):
    """Batched ``_flat_shift`` over a (nd, R, L) stack (axis base 1)."""
    return _flat_shift(w3, s, lane3, interpret, axis=1)


@partial(jax.jit, static_argnames=("offs_a", "offs_b", "offs_c",
                                   "shape_a", "shape_b", "tile",
                                   "interpret"))
def pallas_dia_spgemm(a_data, b_data, offs_a: Tuple[int, ...],
                      offs_b: Tuple[int, ...], offs_c: Tuple[int, ...],
                      shape_a: Tuple[int, int],
                      shape_b: Tuple[int, int], tile: int,
                      interpret: bool = False):
    """C_dia = A_dia @ B_dia (scipy column-aligned layout in and out,
    C width = cols of B), Mosaic-rolled — the banded-SpGEMM analog of
    ``pallas_dia_spmv``.  Returns (ndc, n)."""
    import jax.experimental.pallas as pl

    _, k = shape_a
    n = shape_b[1]
    Rt = tile // L
    nda, ndb, ndc = len(offs_a), len(offs_b), len(offs_c)

    # Pad both bands' widths to tile multiples; A's far enough that a
    # clamped neighbor view always exists for the C grid.
    pc = -(-n // tile) * tile
    pa = -(-max(k, pc) // tile) * tile
    nta = pa // tile
    av = jnp.pad(a_data, ((0, 0), (0, pa - k))).reshape(nda, -1, L)
    bv = jnp.pad(b_data, ((0, 0), (0, pc - n))).reshape(ndb, -1, L)

    kernel = _make_spgemm_kernel(offs_a, offs_b, offs_c, shape_a,
                                 shape_b, tile, interpret)
    if _distinct_inputs():
        # De-aliased variant (see pallas_dia_spmv): tile-shifted A-band
        # copies along the blocked width axis, plain index maps.
        am, ac, ap = _shifted_triple(av, Rt, axis=1)
        a_specs = [
            pl.BlockSpec((nda, Rt, L), lambda i: (0, i, 0)),
            pl.BlockSpec((nda, Rt, L), lambda i: (0, i, 0)),
            pl.BlockSpec((nda, Rt, L), lambda i: (0, i, 0)),
        ]
        a_args = [am, ac, ap]
    else:
        a_specs = [
            pl.BlockSpec((nda, Rt, L),
                         lambda i: (0, jnp.maximum(i - 1, 0), 0)),
            pl.BlockSpec((nda, Rt, L),
                         lambda i: (0, jnp.minimum(i, nta - 1), 0)),
            pl.BlockSpec((nda, Rt, L),
                         lambda i: (0, jnp.minimum(i + 1, nta - 1), 0)),
        ]
        a_args = [av, av, av]
    C = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((ndc, pc // L, L), b_data.dtype),
        grid=(pc // tile,),
        in_specs=[*a_specs,
                  pl.BlockSpec((ndb, Rt, L), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((ndc, Rt, L), lambda i: (0, i, 0)),
        interpret=interpret,
    )(*a_args, bv)
    return C.reshape(ndc, -1)[:, :n]


_SPGEMM_FAILED: set = set()
_SPGEMM_OK: set = set()


def _spgemm_tile(offs_b, nda, ndb, ndc, dtype) -> Optional[int]:
    """Tile for the banded SpGEMM kernel: must cover the B-offset
    reach (A is shifted by -ob) and fit the working set in VMEM."""
    max_ob = max(abs(o) for o in offs_b) if offs_b else 0
    tile = choose_tile(max_ob)
    if tile is None:
        return None
    itemsize = np.dtype(dtype).itemsize
    vmem = (3 * nda + ndb + 2 * ndc) * tile * itemsize
    while vmem > _VMEM_BUDGET and tile > TILE_MIN:
        tile //= 2
        vmem //= 2
    if tile < max_ob or vmem > _VMEM_BUDGET:
        return None
    return tile


def dia_spgemm_maybe_pallas(a_data, b_data, offs_a, offs_b, offs_c,
                            shape_a, shape_b):
    """Banded SpGEMM through the Pallas kernel, or None (XLA path)."""
    mode = _mode()
    if mode == "0":
        return None
    if np.dtype(a_data.dtype) not in (np.dtype(np.float32),
                                      np.dtype(jnp.bfloat16)):
        return None
    if a_data.dtype != b_data.dtype:
        # The XLA fallback promotes to result_type(a, b); the kernel
        # emits b's dtype — mixed inputs must not change result dtype
        # by backend.
        return None
    interpret = mode == "interpret"
    if not interpret:
        try:
            if jax.devices()[0].platform != "tpu":
                return None
        except Exception:
            return None
    tile = _spgemm_tile(offs_b, len(offs_a), len(offs_b), len(offs_c),
                        a_data.dtype)
    if tile is None:
        return None
    key = (offs_a, offs_b, tile, str(a_data.dtype), shape_a, shape_b,
           interpret)
    if key in _SPGEMM_FAILED:
        return None
    if key not in _SPGEMM_OK:
        try:
            from jax._src.core import trace_state_clean

            if not trace_state_clean():
                return None
        except ImportError:
            return None
    try:
        with _obs.span("pallas.spgemm", tile=tile,
                       num_diags_c=len(offs_c)):
            C = pallas_dia_spgemm(a_data, b_data, offs_a, offs_b,
                                  offs_c, shape_a, shape_b, tile,
                                  interpret=interpret)
        _SPGEMM_OK.add(key)
        return C
    except Exception as e:
        import sys

        sys.stderr.write(
            f"legate_sparse_tpu: pallas DIA SpGEMM unavailable "
            f"({e!r:.200}); using XLA path\n"
        )
        _obs.inc("op.pallas_fallback.spgemm")
        _obs.event("pallas.fallback", kernel="spgemm",
                   error=repr(e)[:200])
        _SPGEMM_FAILED.add(key)
        return None


# Runtime dispatch gate: default ON for TPU backends (the measured 7.5x
# over the XLA path), opt out with LEGATE_SPARSE_TPU_PALLAS_DIA=0.
# "interpret" forces the interpret-mode kernel on CPU (differential
# testing of the exact kernel logic without a chip).
_FAILED: set = set()


def _mode() -> str:
    return os.environ.get("LEGATE_SPARSE_TPU_PALLAS_DIA", "1")


def pallas_dist_mode() -> str:
    """Mode for the *distributed* per-shard Pallas route: env override
    (``LEGATE_SPARSE_TPU_PALLAS_DIST`` = 0|1|interpret), else default-on
    on TPU and off elsewhere (interpret mode is pure-Python slow; tests
    opt in explicitly)."""
    v = os.environ.get("LEGATE_SPARSE_TPU_PALLAS_DIST")
    if v is not None:
        return v
    try:
        return "1" if jax.devices()[0].platform == "tpu" else "0"
    except Exception:
        return "0"


def pallas_dia_active() -> bool:
    """Cheap pre-check so callers skip building the row-aligned pack
    (which doubles band storage) when the kernel can never run."""
    mode = _mode()
    if mode == "0":
        return False
    if mode == "interpret":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def dia_spmv_maybe_pallas(packed, x):
    """Run the Pallas kernel from a ``PackedBand``, or return None so
    the caller uses the XLA fallback path."""
    mode = _mode()
    if mode == "0" or packed is None:
        return None
    interpret = mode == "interpret"
    if not interpret:
        try:
            if jax.devices()[0].platform != "tpu":
                return None
        except Exception:
            return None
    key = (packed.offsets, packed.tile, str(packed.rdata.dtype), interpret)
    if key in _FAILED:
        return None
    try:
        with _obs.span("pallas.spmv", tile=packed.tile,
                       num_diags=len(packed.offsets)):
            return pallas_dia_spmv(
                packed.rdata, packed.rmask, x, packed.offsets,
                packed.shape, packed.tile, interpret=interpret,
            )
    except Exception as e:  # lowering/compile failure -> XLA fallback
        import sys

        sys.stderr.write(
            f"legate_sparse_tpu: pallas DIA kernel unavailable "
            f"({e!r:.200}); using XLA path\n"
        )
        _obs.inc("op.pallas_fallback.spmv")
        _obs.event("pallas.fallback", kernel="spmv",
                   error=repr(e)[:200])
        _FAILED.add(key)
        return None


class PackedBand:
    """Cached row-aligned band pack (built once per matrix structure)."""

    __slots__ = ("rdata", "rmask", "offsets", "shape", "tile")

    def __init__(self, rdata, rmask, offsets, shape, tile):
        self.rdata = rdata
        self.rmask = rmask
        self.offsets = offsets
        self.shape = shape
        self.tile = tile


def pack_band(dia_data, offsets: Tuple[int, ...], shape: Tuple[int, int],
              mask=None) -> Optional[PackedBand]:
    """Build the kernel's layout from the scipy-layout DIA cache
    (``csr_array._get_dia()`` output).  None when unsupported, or when
    this band signature already failed to lower (skipping the pack: it
    doubles band storage and would never be used)."""
    tile = supported(offsets, dia_data.dtype, mask is not None)
    if tile is None:
        return None
    interpret = _mode() == "interpret"
    key = (offsets, tile, str(dia_data.dtype), interpret)
    if key in _FAILED:
        return None
    rdata, rmask = row_align(
        dia_data, offsets, shape, tile,
        mask=mask, with_mask=mask is not None,
    )
    packed = PackedBand(rdata, rmask, offsets, shape, tile)
    # Validate the kernel lowers/compiles NOW, eagerly: a Mosaic failure
    # surfacing later inside an outer jit (the solvers trace the whole
    # solve as one while_loop) would escape dia_spmv_maybe_pallas's
    # except and crash the solve with no fallback.  pack_band only runs
    # outside traces (csr.py gates on _can_build_cache), so one eager
    # probe matvec here is safe and costs a single kernel launch.  Only
    # the real-chip compile needs this; direct interpret-mode users
    # (tests) see failures at their own call site, and on non-TPU
    # platforms the dispatch never uses the pack.
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        on_tpu = False
    if on_tpu and not interpret:
        try:
            x_probe = jnp.zeros((shape[1],), rdata.dtype)
            pallas_dia_spmv(rdata, rmask, x_probe, offsets, shape, tile,
                            interpret=False)
        except Exception as e:
            import sys

            sys.stderr.write(
                f"legate_sparse_tpu: pallas DIA kernel failed validation "
                f"({e!r:.200}); using XLA path\n"
            )
            _FAILED.add(key)
            return None
    return packed
