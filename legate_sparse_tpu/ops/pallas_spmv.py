# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Pallas TPU kernel for ELL SpMV (the L1 hot-loop analog).

Role parity with the reference's hand-tuned SpMV leaf
(``src/sparse/array/csr/spmv.cu:62-152``): the XLA ELL path
(``ops/spmv.py``) is the default; this kernel is the hand-scheduled
alternative for the case where XLA's fusion leaves bandwidth on the
table.  Design:

- x resides **whole in VMEM** (a 2^20-row f32 x is 4 MB; the kernel is
  for single-chip/shard-local SpMV where x — or the halo window — fits).
- The (rows, W) ELL value/column blocks stream through VMEM in
  ``(TILE_R, W)`` tiles over a 1-D grid; each tile does one VPU gather
  ``x[cols]``, a masked multiply, and a W-width row reduction — the
  whole tile's HBM traffic is touched exactly once.
- Padded slots are masked via per-row counts (products, not operands,
  so non-finite x never injects NaN — the same IEEE invariant as
  ``ell_spmv``).

Opt-in: ``LEGATE_SPARSE_TPU_PALLAS=1`` routes ``csr_array @ x`` through
this kernel on TPU (with transparent fallback if lowering fails);
``interpret=True`` is used on CPU for differential testing.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

TILE_R = 256


def _kernel(x_ref, data_ref, cols_ref, counts_ref, y_ref):
    data = data_ref[:]                    # (TILE_R, W)
    cols = cols_ref[:]                    # (TILE_R, W) int32
    counts = counts_ref[:]                # (TILE_R, 1)
    x = x_ref[:]                          # (n_pad, 1) whole vector
    W = data.shape[1]
    # Per-slot 2-D gathers (operand and indices both 2-D): the form
    # Mosaic can lower, unlike a flat 1-D-operand gather with 2-D
    # indices ("Only 2D gather is supported").  W is small (ELL width),
    # so the static unroll stays cheap; every gather reads VMEM.
    acc = jnp.zeros((data.shape[0], 1), dtype=data.dtype)
    for w in range(W):
        g = jnp.take_along_axis(
            x, cols[:, w : w + 1].astype(jnp.int32), axis=0
        )                                  # (TILE_R, 1)
        valid = counts > w                 # (TILE_R, 1)
        acc = acc + jnp.where(valid, data[:, w : w + 1] * g,
                              jnp.zeros((), data.dtype))
    y_ref[:] = acc


@partial(jax.jit, static_argnames=("interpret",))
def pallas_ell_spmv(ell_data, ell_cols, ell_counts, x,
                    interpret: bool = False):
    """y = A @ x over ELL blocks via one Pallas pass (rows padded to a
    TILE_R multiple by the caller wrapper below)."""
    from jax.experimental import pallas as pl

    rows, W = ell_data.shape
    assert rows % TILE_R == 0, rows
    n = x.shape[0]
    grid = (rows // TILE_R,)

    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 1), ell_data.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n, 1), lambda i: (0, 0)),          # x, whole
            pl.BlockSpec((TILE_R, W), lambda i: (i, 0)),     # data tile
            pl.BlockSpec((TILE_R, W), lambda i: (i, 0)),     # cols tile
            pl.BlockSpec((TILE_R, 1), lambda i: (i, 0)),     # counts
        ],
        out_specs=pl.BlockSpec((TILE_R, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(x.reshape(-1, 1), ell_data, ell_cols,
      ell_counts.reshape(-1, 1).astype(jnp.int32))[:, 0]


_PALLAS_OK: dict = {}


def ell_spmv_maybe_pallas(ell_data, ell_cols, ell_counts, x):
    """Route through the Pallas kernel when enabled and lowerable;
    pad rows to TILE_R and truncate the result.  Returns None when the
    route is unavailable (caller uses the XLA path)."""
    if os.environ.get("LEGATE_SPARSE_TPU_PALLAS", "0") != "1":
        return None
    platform = jax.devices()[0].platform
    interpret = platform == "cpu"
    rows, W = ell_data.shape
    rows_p = -(-rows // TILE_R) * TILE_R
    key = (rows_p, W, str(ell_data.dtype), interpret)
    if _PALLAS_OK.get(key) is False:
        return None
    if _PALLAS_OK.get(key) is None:
        # Never make the FIRST attempt from inside an outer trace (the
        # solvers jit whole iteration loops): a Mosaic compile failure
        # would surface at the outer jit's compile, outside this except,
        # with no fallback.  Defer to the XLA path until an eager call
        # proves the kernel; same policy as pallas_dia.pack_band.
        try:
            from jax._src.core import trace_state_clean

            if not trace_state_clean():
                return None
        except ImportError:  # jax internals moved; be conservative
            return None
    pad = rows_p - rows
    if pad:
        zd = jnp.zeros((pad, W), ell_data.dtype)
        zc = jnp.zeros((pad, W), ell_cols.dtype)
        ell_data = jnp.concatenate([ell_data, zd])
        ell_cols = jnp.concatenate([ell_cols, zc])
        ell_counts = jnp.concatenate(
            [ell_counts, jnp.zeros((pad,), ell_counts.dtype)]
        )
    try:
        y = pallas_ell_spmv(ell_data, ell_cols, ell_counts, x,
                            interpret=interpret)
        _PALLAS_OK[key] = True
        return y[:rows]
    except Exception:
        _PALLAS_OK[key] = False
        return None
