# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SpGEMM: C = A @ B for CSR operands, expand-sort-compress (ESC).

TPU-native replacement for the reference's Gustavson two-phase CPU/OMP
tasks (reference: ``src/sparse/array/csr/spgemm_csr_csr_csr.cc:26-160``
symbolic + numeric phases with dense workspaces) and the cuSPARSE
single-phase GPU path (``spgemm_csr_csr_csr.cu``).

Gustavson's per-row hash/dense accumulator is a scalar-loop algorithm —
hostile to the TPU's vector units.  ESC instead:

1. **Expand**: for every nonzero A[i,k], emit the products against row k
   of B -> T = sum over A-nnz of nnz(B row k) triplets (i, j, a*b).
2. **Sort** the triplets by (i, j) — one XLA two-key sort (keys stay in
   the native index dtype; no fused int64 key, so this is safe for any
   rows*cols and under 32-bit-only configurations).
3. **Compress**: segment-sum runs of equal (i, j), compact to nnz(C).

Shape discipline: T and nnz(C) are data-dependent, so this module exposes
host-level size oracles (``spgemm_num_products``, phase-1 output) that
the caller materializes before invoking the jitted phases — exactly the
role of the reference's blocking ``int(nnz)`` between its two phases
(``csr.py:714``) and the NCCL allgather of local nnz on GPU
(``spgemm_csr_csr_csr.cu:43-62``).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..types import coord_dtype_for, nnz_ty
from .convert import row_ids_from_indptr, indptr_from_row_ids


def spgemm_num_products(a_indices, a_indptr, b_indptr) -> int:
    """T = total expanded products (host-blocking size oracle)."""
    counts = jnp.diff(b_indptr)[a_indices]
    return int(jnp.sum(counts))


@partial(jax.jit, static_argnames=("num_products", "m"))
def _expand(a_data, a_indices, a_indptr, b_data, b_indices, b_indptr,
            num_products: int, m: int):
    """Emit all (row, col, value) product triplets, ordered by A nonzero."""
    nnz_a = a_data.shape[0]
    a_rows = row_ids_from_indptr(a_indptr, nnz_a)
    # Products contributed by each A-nonzero = nnz of the B row it selects.
    b_row_nnz = jnp.diff(b_indptr)[a_indices]
    starts = jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_ty), jnp.cumsum(b_row_nnz).astype(nnz_ty)]
    )
    # For product t: owning A-nonzero e(t) and offset within its B row.
    t = jnp.arange(num_products, dtype=nnz_ty)
    e = jnp.searchsorted(starts[1:-1], t, side="right").astype(nnz_ty)
    within = t - starts[e]
    b_pos = b_indptr[a_indices[e]].astype(nnz_ty) + within
    rows = a_rows[e].astype(b_indices.dtype)
    cols = b_indices[b_pos]
    vals = a_data[e] * b_data[b_pos]
    return rows, cols, vals


@jax.jit
def sort_coo(rows, cols, vals):
    """Sort triplets by (row, col): one two-key XLA sort."""
    return jax.lax.sort([rows, cols, vals], num_keys=2)


@jax.jit
def run_heads(rows, cols):
    """Mask marking the first triplet of each distinct (row, col) run."""
    if rows.shape[0] == 0:
        return jnp.zeros((0,), dtype=bool)
    change = jnp.logical_or(rows[1:] != rows[:-1], cols[1:] != cols[:-1])
    return jnp.concatenate([jnp.ones((1,), dtype=bool), change])


@partial(jax.jit, static_argnames=("nnz_c", "m"))
def compress_coo(rows, cols, vals, heads, nnz_c: int, m: int):
    """Segment-sum duplicate (row, col) runs and compact to nnz_c triplets."""
    seg = jnp.cumsum(heads.astype(nnz_ty)) - 1  # output slot per triplet
    out_vals = jnp.zeros((nnz_c,), dtype=vals.dtype).at[seg].add(vals)
    head_idx = jnp.nonzero(heads, size=nnz_c, fill_value=0)[0]
    out_rows = rows[head_idx]
    out_cols = cols[head_idx]
    indptr = indptr_from_row_ids(out_rows, m)
    return out_vals, out_cols, indptr


def coalesce_coo(rows, cols, vals, m: int):
    """Sort + merge duplicate coordinates; returns CSR triple.

    Shared by SpGEMM, sparse add/sub, and DIA->CSR conversion (one host
    sync for the output nnz).
    """
    rows, cols, vals = sort_coo(rows, cols, vals)
    heads = run_heads(rows, cols)
    nnz_c = int(jnp.sum(heads))
    return compress_coo(rows, cols, vals, heads, nnz_c, m)


def spgemm_csr_csr_csr_impl(
    a_data, a_indices, a_indptr,
    b_data, b_indices, b_indptr,
    m: int, k: int, n: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full ESC SpGEMM.  Two host syncs (T, nnz_C) bracket the jitted
    phases — the XLA analog of the reference's two-phase launch structure
    (``csr.py:686-748``)."""
    num_products = spgemm_num_products(a_indices, a_indptr, b_indptr)
    if num_products == 0:
        cdt = coord_dtype_for(max(m, n))
        return (
            jnp.zeros((0,), dtype=jnp.result_type(a_data.dtype, b_data.dtype)),
            jnp.zeros((0,), dtype=cdt),
            jnp.zeros((m + 1,), dtype=nnz_ty),
        )
    rows, cols, vals = _expand(
        a_data, a_indices, a_indptr, b_data, b_indices, b_indptr,
        num_products, m,
    )
    return coalesce_coo(rows, cols, vals, m)
