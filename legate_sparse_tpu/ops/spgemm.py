# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SpGEMM: C = A @ B for CSR operands, expand-sort-compress (ESC).

TPU-native replacement for the reference's Gustavson two-phase CPU/OMP
tasks (reference: ``src/sparse/array/csr/spgemm_csr_csr_csr.cc:26-160``
symbolic + numeric phases with dense workspaces) and the cuSPARSE
single-phase GPU path (``spgemm_csr_csr_csr.cu``).

Gustavson's per-row hash/dense accumulator is a scalar-loop algorithm —
hostile to the TPU's vector units.  ESC instead:

1. **Expand**: for every nonzero A[i,k], emit the products against row k
   of B -> T = sum over A-nnz of nnz(B row k) triplets (i, j, a*b).
2. **Sort** the triplets by (i, j) — one XLA two-key sort (keys stay in
   the native index dtype; no fused int64 key, so this is safe for any
   rows*cols and under 32-bit-only configurations).
3. **Compress**: segment-sum runs of equal (i, j), compact to nnz(C).

Shape discipline: T and nnz(C) are data-dependent, so this module exposes
host-level size oracles (``spgemm_num_products``, phase-1 output) that
the caller materializes before invoking the jitted phases — exactly the
role of the reference's blocking ``int(nnz)`` between its two phases
(``csr.py:714``) and the NCCL allgather of local nnz on GPU
(``spgemm_csr_csr_csr.cu:43-62``).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..types import coord_dtype_for, index_dtype, nnz_dtype
from .convert import row_ids_from_indptr, indptr_from_row_ids


def spgemm_num_products(a_indices, a_indptr, b_indptr) -> int:
    """T = total expanded products (host-blocking size oracle)."""
    counts = jnp.diff(b_indptr)[a_indices]
    _obs.inc("transfer.host_sync.spgemm_T")
    return int(jnp.sum(counts))


@partial(jax.jit, static_argnames=("num_products", "m"))
def _expand(a_data, a_indices, a_indptr, b_data, b_indices, b_indptr,
            num_products: int, m: int):
    """Emit all (row, col, value) product triplets, ordered by A nonzero."""
    _obs.inc("trace.spgemm_expand")
    nnz_a = a_data.shape[0]
    a_rows = row_ids_from_indptr(a_indptr, nnz_a)
    # Products contributed by each A-nonzero = nnz of the B row it selects.
    b_row_nnz = jnp.diff(b_indptr)[a_indices]
    starts = jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_dtype()), jnp.cumsum(b_row_nnz).astype(nnz_dtype())]
    )
    # For product t: owning A-nonzero e(t) and offset within its B row.
    t = jnp.arange(num_products, dtype=nnz_dtype())
    e = jnp.searchsorted(starts[1:-1], t, side="right").astype(nnz_dtype())
    within = t - starts[e]
    b_pos = b_indptr[a_indices[e]].astype(nnz_dtype()) + within
    rows = a_rows[e].astype(b_indices.dtype)
    cols = b_indices[b_pos]
    vals = a_data[e] * b_data[b_pos]
    return rows, cols, vals


@jax.jit
def sort_coo(rows, cols, vals):
    """Sort triplets by (row, col): one two-key XLA sort."""
    return jax.lax.sort([rows, cols, vals], num_keys=2)


@jax.jit
def run_heads(rows, cols):
    """Mask marking the first triplet of each distinct (row, col) run."""
    if rows.shape[0] == 0:
        return jnp.zeros((0,), dtype=bool)
    change = jnp.logical_or(rows[1:] != rows[:-1], cols[1:] != cols[:-1])
    return jnp.concatenate([jnp.ones((1,), dtype=bool), change])


@partial(jax.jit, static_argnames=("cap",))
def _compress_chunk(rows, cols, vals, heads, cap: int):
    """Merge duplicate runs into padded (cap,) triplet arrays (chunked
    mode's compress: ``cap`` is the shared static capacity so every
    chunk reuses one compilation; the caller slices the valid prefix)."""
    seg = jnp.clip(jnp.cumsum(heads.astype(index_dtype())) - 1, 0, cap - 1)
    # Sentinel (padding) entries carry value 0, so scatter-adding every
    # slot is harmless wherever their clipped seg lands.
    out_vals = jnp.zeros((cap,), dtype=vals.dtype).at[seg].add(vals)
    head_idx = jnp.nonzero(heads, size=cap, fill_value=0)[0]
    return rows[head_idx], cols[head_idx], out_vals


@partial(jax.jit, static_argnames=("nnz_c", "m"))
def compress_coo(rows, cols, vals, heads, nnz_c: int, m: int):
    """Segment-sum duplicate (row, col) runs and compact to nnz_c triplets."""
    seg = jnp.cumsum(heads.astype(nnz_dtype())) - 1  # output slot per triplet
    out_vals = jnp.zeros((nnz_c,), dtype=vals.dtype).at[seg].add(vals)
    head_idx = jnp.nonzero(heads, size=nnz_c, fill_value=0)[0]
    out_rows = rows[head_idx]
    out_cols = cols[head_idx]
    indptr = indptr_from_row_ids(out_rows, m)
    return out_vals, out_cols, indptr


def coalesce_coo(rows, cols, vals, m: int):
    """Sort + merge duplicate coordinates; returns CSR triple.

    Shared by SpGEMM, sparse add/sub, and DIA->CSR conversion (one host
    sync for the output nnz).
    """
    rows, cols, vals = sort_coo(rows, cols, vals)
    heads = run_heads(rows, cols)
    _obs.inc("transfer.host_sync.spgemm_nnz")
    nnz_c = int(jnp.sum(heads))
    return compress_coo(rows, cols, vals, heads, nnz_c, m)


# Diagnostic: number of expand chunks used by the most recent SpGEMM
# (1 = single-shot ALG1-analog path).  Read by tests.
_last_num_chunks = 1


def _chunk_bounds(a_indices, b_indptr, num_products: int,
                  chunk_products: int):
    """Split the A-nonzero axis so each chunk emits <= chunk_products
    products (single A-nonzeros emitting more get their own chunk)."""
    counts = np.asarray(jnp.diff(b_indptr))[np.asarray(a_indices)]
    starts = np.concatenate([[0], np.cumsum(counts, dtype=np.int64)])
    bounds = [0]
    while starts[bounds[-1]] < num_products:
        nxt = int(
            np.searchsorted(
                starts, starts[bounds[-1]] + chunk_products, side="right"
            ) - 1
        )
        nxt = max(nxt, bounds[-1] + 1)           # always make progress
        bounds.append(min(nxt, len(starts) - 1))
    return bounds, starts


@partial(jax.jit, static_argnames=("cap", "span", "m"))
def _expand_range(a_data, a_indices, a_indptr, b_data, b_indices, b_indptr,
                  cap: int, span: int, m: int, e_lo, e_len):
    """Expand products for A-nonzeros [e_lo, e_lo + e_len) (chunked mode).

    ``cap``/``span`` are the padded product/nonzero capacities shared by
    every chunk (``e_lo``/``e_len`` stay dynamic, so all chunks reuse
    ONE compilation).  Surplus slots carry row sentinel ``m`` (sorts
    last) and value 0.
    """
    nnz_a = a_data.shape[0]
    a_rows = row_ids_from_indptr(a_indptr, nnz_a)
    s = jnp.arange(span, dtype=nnz_dtype())
    valid_e = s < e_len
    idx = jnp.clip(e_lo + s, 0, nnz_a - 1)
    a_idx_c = a_indices[idx]
    b_row_nnz = jnp.where(valid_e, jnp.diff(b_indptr)[a_idx_c], 0)
    starts = jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_dtype()), jnp.cumsum(b_row_nnz).astype(nnz_dtype())]
    )
    t_local = starts[-1]
    t = jnp.arange(cap, dtype=nnz_dtype())
    e = jnp.clip(jnp.searchsorted(starts, t, side="right") - 1, 0, span - 1)
    valid = t < t_local
    within = t - starts[e]
    b_pos = jnp.clip(
        b_indptr[a_idx_c[e]].astype(nnz_dtype()) + within, 0,
        max(b_data.shape[0] - 1, 0),
    )
    rows = jnp.where(valid, a_rows[idx[e]], m).astype(b_indices.dtype)
    cols = jnp.where(valid, b_indices[b_pos], 0)
    vals = jnp.where(valid, a_data[idx[e]] * b_data[b_pos],
                     jnp.zeros((), a_data.dtype))
    return rows, cols, vals


def spgemm_csr_csr_csr_impl(
    a_data, a_indices, a_indptr,
    b_data, b_indices, b_indptr,
    m: int, k: int, n: int,
    chunk_products: int | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full ESC SpGEMM.  Two host syncs (T, nnz_C) bracket the jitted
    phases — the XLA analog of the reference's two-phase launch structure
    (``csr.py:686-748``).

    Memory modes (reference ``settings.py:35-45``, cuSPARSE ALG1 vs ALG3
    in ``spgemm_csr_csr_csr.cu:196-216``): by default the expansion is
    one (T,)-sized pass; when ``chunk_products`` is set (from
    ``settings.spgemm_chunk_products`` unless ``settings.fast_spgemm``)
    and T exceeds it, the expansion runs in bounded chunks along the
    A-nonzero axis whose partial products are coalesced incrementally —
    peak memory O(chunk + nnz_C) instead of O(T).
    """
    global _last_num_chunks
    from ..settings import settings

    if chunk_products is None and not settings.fast_spgemm:
        chunk_products = settings.spgemm_chunk_products

    num_products = spgemm_num_products(a_indices, a_indptr, b_indptr)
    val_dtype = jnp.result_type(a_data.dtype, b_data.dtype)
    if num_products == 0:
        _last_num_chunks = 1
        cdt = coord_dtype_for(max(m, n))
        return (
            jnp.zeros((0,), dtype=val_dtype),
            jnp.zeros((0,), dtype=cdt),
            jnp.zeros((m + 1,), dtype=nnz_dtype()),
        )

    if chunk_products is not None and num_products > chunk_products:
        bounds, starts = _chunk_bounds(
            a_indices, b_indptr, num_products, chunk_products
        )
        _last_num_chunks = len(bounds) - 1
        # Pad every chunk to one (cap, span) -> one compiled expand.
        cap = int(
            max(starts[b1] - starts[b0]
                for b0, b1 in zip(bounds[:-1], bounds[1:]))
        )
        span = int(max(b1 - b0 for b0, b1 in zip(bounds[:-1], bounds[1:])))
        acc_r = acc_c = acc_v = None
        for b0, b1 in zip(bounds[:-1], bounds[1:]):
            r, c, v = _expand_range(
                a_data, a_indices, a_indptr, b_data, b_indices, b_indptr,
                cap, span, m, int(b0), int(b1 - b0),
            )
            r, c, v = sort_coo(r, c, v)
            # Merge within the chunk (sentinel rows sort last; one
            # shared-capacity compile), slice the valid prefix, fold.
            heads = jnp.logical_and(run_heads(r, c), r < m)
            nnz_chunk = int(jnp.sum(heads))
            if nnz_chunk == 0:
                continue
            r2, c2, v2 = _compress_chunk(r, c, v, heads, cap)
            r2, c2, v2 = (
                r2[:nnz_chunk].astype(index_dtype()), c2[:nnz_chunk],
                v2[:nnz_chunk],
            )
            if acc_r is None:
                acc_r, acc_c, acc_v = r2, c2, v2
            else:
                acc_r = jnp.concatenate([acc_r, r2])
                acc_c = jnp.concatenate([acc_c, c2])
                acc_v = jnp.concatenate([acc_v, v2])
            # Fold the accumulator whenever it outgrows the chunk budget
            # so peak memory stays O(chunk + nnz_C), as documented.
            if acc_r.shape[0] > max(chunk_products, cap):
                f_vals, f_cols, f_indptr = coalesce_coo(
                    acc_r, acc_c, acc_v, m
                )
                acc_r = row_ids_from_indptr(
                    f_indptr, f_cols.shape[0]
                ).astype(index_dtype())
                acc_c = f_cols
                acc_v = f_vals
        if acc_r is None:
            cdt = coord_dtype_for(max(m, n))
            return (
                jnp.zeros((0,), dtype=val_dtype),
                jnp.zeros((0,), dtype=cdt),
                jnp.zeros((m + 1,), dtype=nnz_dtype()),
            )
        return coalesce_coo(acc_r, acc_c, acc_v, m)

    _last_num_chunks = 1
    rows, cols, vals = _expand(
        a_data, a_indices, a_indptr, b_data, b_indices, b_indptr,
        num_products, m,
    )
    return coalesce_coo(rows, cols, vals, m)
