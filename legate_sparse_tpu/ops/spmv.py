# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sparse matrix-vector / matrix-matrix products.

TPU-native replacement for the reference's CSR SpMV row-split task family
(reference: ``src/sparse/array/csr/spmv.cc:36-44`` CPU loop,
``spmv_omp.cc:36-45``, ``spmv.cu:62-152`` cuSPARSE with the
shifted-pointer trick).  The row-block distribution strategy
(``csr.py:562-593`` align + image constraints) lives in
``parallel/dist_csr.py``; this module is the single-shard kernel.

Kernel choice on TPU:
- General CSR: gather x by column index, multiply, ``segment_sum`` by row.
  XLA lowers the gather + segmented reduction onto the VPU; no scalar
  loops, no dynamic shapes.
- Structured (banded/DIA) matrices keep the gather-free shifted-add
  kernels in ``ops/dia_ops.py`` (use ``dia_array.dot``).

Observability: each jitted kernel body bumps a ``trace.<kernel>``
counter — the body only executes on a jit cache miss, so the counter
IS the retrace/compile count for that kernel (``obs/counters.py``
naming contract).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from .convert import row_ids_from_indptr


@partial(jax.jit, static_argnames=("rows",))
def csr_spmv(data, indices, indptr, x, rows: int):
    """y[i] = sum_j data[j] * x[indices[j]] over row i's extent.

    Matches the reference leaf computation (``spmv.cc:36-44``) as one
    gather-multiply-segment_sum.  Prefer ``csr_spmv_rowids`` /
    ``ell_spmv`` (cached-structure paths) in iterative callers: they skip
    the per-call ``searchsorted`` the same way Legion caches partitions
    across solver iterations (reference §3.2 partition-caching note).
    """
    _obs.inc("trace.csr_spmv")
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    prod = data * x[indices]
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows",))
def csr_spmv_rowids(data, indices, row_ids, x, rows: int):
    """SpMV with precomputed per-nnz row ids (static matrix structure)."""
    _obs.inc("trace.csr_spmv_rowids")
    prod = data * x[indices]
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows",))
def csr_spmv_rowids_masked(data, indices, row_ids, valid_nnz, x, rows: int):
    """SpMV over a zero-padded nonzero suffix: slots >= ``valid_nnz``
    contribute an exact 0 (masked product, not 0*x — preserves IEEE
    semantics against non-finite x, same invariant as ``ell_spmv``)."""
    _obs.inc("trace.csr_spmv_rowids_masked")
    nnz = data.shape[0]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    prod = jnp.where(
        slot < valid_nnz, data * x[indices],
        jnp.zeros((1,), dtype=data.dtype),
    )
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows",))
def csr_spmm_rowids_masked(data, indices, row_ids, valid_nnz, X, rows: int):
    """SpMM over a zero-padded nonzero suffix (the engine's bucketed
    batch kernel): slots >= ``valid_nnz`` contribute an exact 0 via a
    masked product — identical IEEE semantics to
    ``csr_spmv_rowids_masked`` column by column, so a stacked dispatch
    of k requests is bit-for-bit the k individual dispatches."""
    _obs.inc("trace.csr_spmm_rowids_masked")
    nnz = data.shape[0]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    prod = jnp.where(
        (slot < valid_nnz)[:, None], data[:, None] * X[indices, :],
        jnp.zeros((1, 1), dtype=data.dtype),
    )
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows", "b"))
def csr_multi_spmv_rowids_masked(data, indices, row_ids, valid_nnz, X,
                                 rows: int, b: int):
    """``b`` independent masked SpMVs in ONE stacked dispatch (the
    gateway's cross-tenant batch kernel): operand slot ``i`` holds
    matrix ``i``'s padded pack and its own x vector.

    Each matrix's segment ids are offset by ``i * (rows + 1)`` — the
    ``+ 1`` keeps the pack's out-of-bounds padding row id (``rows``)
    inside matrix ``i``'s own discarded segment instead of aliasing
    matrix ``i+1``'s row 0.  Per matrix this performs exactly the
    masked product and in-order segment reduction of
    :func:`csr_spmv_rowids_masked`, so packing requests from
    different tenants/matrices is bit-for-bit invisible to each of
    them.  A slot with ``valid_nnz == 0`` (batch padding up to the
    bucketed width) contributes only exact zeros."""
    _obs.inc("trace.csr_multi_spmv_rowids_masked")
    nnz = data.shape[1]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    gathered = jnp.take_along_axis(X, indices, axis=1)
    prod = jnp.where(
        slot[None, :] < valid_nnz[:, None], data * gathered,
        jnp.zeros((1, 1), dtype=data.dtype),
    )
    offs = jnp.arange(b, dtype=jnp.int32)[:, None] * (rows + 1)
    seg = (row_ids + offs).reshape(-1)
    out = jax.ops.segment_sum(
        prod.reshape(-1), seg, num_segments=b * (rows + 1),
        indices_are_sorted=True,
    )
    return out.reshape(b, rows + 1)[:, :rows]


@partial(jax.jit, static_argnames=("rows",))
def coo_spmv_segment(data, row_ids, col_ids, valid_nnz, x, rows: int):
    """Masked COO SpMV over a pow2-padded update buffer (the delta
    layer's serving kernel, docs/MUTATION.md): slots >= ``valid_nnz``
    contribute an exact 0 via the masked product (never ``0*x`` — the
    same IEEE discipline as ``csr_spmv_rowids_masked``), and padded
    ``row_ids`` carry the out-of-range sentinel ``rows`` so
    ``segment_sum`` drops them (the engine-pack padding contract).
    The buffer is padded to a pow2 capacity bucket by the caller, so
    streaming mutation never retraces — one compile per bucket."""
    _obs.inc("trace.coo_spmv_segment")
    nnz = data.shape[0]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    prod = jnp.where(
        slot < valid_nnz, data * x[col_ids],
        jnp.zeros((1,), dtype=data.dtype),
    )
    # The delta layer ingests entries sorted by (row, col); the padded
    # sentinel tail (row id == rows) sorts after every valid id.
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@jax.jit
def ell_spmv(ell_data, ell_cols, ell_counts, x):
    """SpMV over ELL-packed structure: the TPU fast path.

    ``ell_data``/``ell_cols`` are (rows, W); ``ell_counts`` is the
    per-row nnz (rows,), masking padded slots' *products* so non-finite
    x entries behave exactly as in the segment-sum path (0*inf must not
    inject NaN).  One 2-D gather + a W-width masked row reduction — no
    scatter, no searchsorted; measured ~HBM-roofline on TPU where flat
    scatter-based SpMV is orders of magnitude slower.
    """
    _obs.inc("trace.ell_spmv")
    W = ell_data.shape[1]
    slot = jnp.arange(W, dtype=ell_counts.dtype)
    valid = slot[None, :] < ell_counts[:, None]
    prod = jnp.where(valid, ell_data * x[ell_cols],
                     jnp.zeros((1, 1), dtype=ell_data.dtype))
    return jnp.sum(prod, axis=1)


def sliced_ell_pack(data, indices, indptr, rows: int):
    """Row-binned ("sliced") ELL pack: rows grouped by next-pow2 row
    length, one (rows_bin, W_bin) ELL block per bin.

    Flat ELL pads every row to the matrix max W, so one heavy row in a
    power-law matrix blows the ``ell_max_expand`` budget and the whole
    matrix falls back to the gather/segment-sum path.  Binning rows by
    ``next_pow2(len)`` bounds padding at < 2x the true nnz regardless
    of skew (each row pads to at most twice its own length), at the
    cost of one masked-row-reduction dispatch per occupied bin
    (<= log2(max row length) bins).

    Returns a tuple of ``(ell_data, ell_cols, ell_counts, row_idx)``
    bins — ``row_idx`` maps each bin row back to its original row —
    or None for an empty matrix.  Padded slots replicate the row's
    last valid column with value 0, exactly like :func:`ell_pack`;
    the kernel masks padded *products* so non-finite x entries cannot
    inject NaN through padding.  Bin membership is computed on host
    from the (rows+1,) indptr; the block gathers run on device.
    """
    nnz = int(indices.shape[0])
    if nnz == 0 or rows == 0:
        return None
    indptr_h = np.asarray(indptr)
    counts = (indptr_h[1:] - indptr_h[:-1]).astype(np.int64)
    nzr = counts > 0
    # next_pow2 per row; float64 log2 is exact for the int32-bounded
    # row lengths a single shard can hold.
    widths = np.ones_like(counts)
    widths[nzr] = (
        2 ** np.ceil(np.log2(counts[nzr])).astype(np.int64))
    indptr_d = jnp.asarray(indptr)
    bins = []
    for W in np.unique(widths[nzr]):
        sel = np.nonzero(nzr & (widths == W))[0]
        W = int(W)
        row_idx = jnp.asarray(sel.astype(np.int32))
        cnt = jnp.asarray(counts[sel].astype(np.int32))
        row_start = indptr_d[row_idx].astype(jnp.int32)
        row_last = jnp.clip(
            indptr_d[row_idx + 1].astype(jnp.int32) - 1, 0, nnz - 1)
        slot = jnp.arange(W, dtype=jnp.int32)
        src = jnp.minimum(row_start[:, None] + slot[None, :],
                          row_last[:, None])
        valid = slot[None, :] < cnt[:, None]
        ell_cols = indices[src]
        ell_data = jnp.where(valid, data[src],
                             jnp.zeros((1, 1), dtype=data.dtype))
        bins.append((ell_data, ell_cols, cnt, row_idx))
    return tuple(bins)


@partial(jax.jit, static_argnames=("rows",))
def sliced_ell_spmv(bins, x, rows: int):
    """SpMV over a :func:`sliced_ell_pack` structure.

    One masked ELL row-reduction per bin (same IEEE masking contract
    as :func:`ell_spmv`), scattered back to original row order with a
    unique-sorted ``.at[].set`` — rows with zero stored entries keep
    the exact-0 initial value.  The bin tuple is a pytree argument, so
    one compiled program covers a matrix's pack; a different bin
    structure retraces (counted below)."""
    _obs.inc("trace.sliced_ell_spmv")
    out_dtype = jnp.result_type(bins[0][0].dtype, x.dtype)
    y = jnp.zeros((rows,), dtype=out_dtype)
    for ell_data, ell_cols, cnt, row_idx in bins:
        W = ell_data.shape[1]
        slot = jnp.arange(W, dtype=cnt.dtype)
        valid = slot[None, :] < cnt[:, None]
        prod = jnp.where(valid, ell_data * x[ell_cols],
                         jnp.zeros((1, 1), dtype=ell_data.dtype))
        y = y.at[row_idx].set(
            jnp.sum(prod, axis=1).astype(out_dtype),
            indices_are_sorted=True, unique_indices=True)
    return y


# --- Low-precision-storage variants (f32 accumulation) ------------------
#
# SpMV is bandwidth-bound on every lane this repo targets, so bf16/f16
# value storage halves the dominant byte stream.  Each variant widens
# the gathered *product* to f32 BEFORE the reduction (the paper's
# "narrow storage, wide accumulate" contract), then narrows the result
# to ``result_type(data, x)`` — bf16 in/bf16 out, while an f32 x
# promotes the output to f32 with no intermediate copy of the matrix.
# The IEEE masking contract is unchanged: padded slots mask the
# product, never the operand.


@partial(jax.jit, static_argnames=("rows",))
def csr_spmv_rowids_f32acc(data, indices, row_ids, x, rows: int):
    """Low-byte-storage SpMV (precomputed row ids): bf16/f16 values,
    f32 ``segment_sum`` accumulation, ``result_type(data, x)`` out."""
    _obs.inc("trace.csr_spmv_rowids_f32acc")
    out_dtype = jnp.result_type(data.dtype, x.dtype)
    prod = data.astype(jnp.float32) * x[indices].astype(jnp.float32)
    y = jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )
    return y.astype(out_dtype)


@partial(jax.jit, static_argnames=("rows",))
def csr_spmv_rowids_masked_f32acc(data, indices, row_ids, valid_nnz, x,
                                  rows: int):
    """Masked low-byte SpMV (zero-padded nonzero suffix): the 2-D
    block-sharded panel kernel for bf16 panels.  Same masked-product
    IEEE contract as :func:`csr_spmv_rowids_masked`, accumulated in
    f32."""
    _obs.inc("trace.csr_spmv_rowids_masked_f32acc")
    out_dtype = jnp.result_type(data.dtype, x.dtype)
    nnz = data.shape[0]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    prod = jnp.where(
        slot < valid_nnz,
        data.astype(jnp.float32) * x[indices].astype(jnp.float32),
        jnp.zeros((1,), dtype=jnp.float32),
    )
    y = jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )
    return y.astype(out_dtype)


@partial(jax.jit, static_argnames=("rows",))
def csr_spmm_rowids_f32acc(data, indices, row_ids, X, rows: int):
    """Low-byte-storage SpMM: bf16/f16 values, f32 accumulation."""
    _obs.inc("trace.csr_spmm_rowids_f32acc")
    out_dtype = jnp.result_type(data.dtype, X.dtype)
    prod = data.astype(jnp.float32)[:, None] \
        * X[indices, :].astype(jnp.float32)
    Y = jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )
    return Y.astype(out_dtype)


@jax.jit
def ell_spmv_f32acc(ell_data, ell_cols, ell_counts, x):
    """Low-byte-storage ELL SpMV: masked f32 products, f32 row
    reduction, ``result_type(ell_data, x)`` out."""
    _obs.inc("trace.ell_spmv_f32acc")
    out_dtype = jnp.result_type(ell_data.dtype, x.dtype)
    W = ell_data.shape[1]
    slot = jnp.arange(W, dtype=ell_counts.dtype)
    valid = slot[None, :] < ell_counts[:, None]
    prod = jnp.where(
        valid,
        ell_data.astype(jnp.float32) * x[ell_cols].astype(jnp.float32),
        jnp.zeros((1, 1), dtype=jnp.float32),
    )
    return jnp.sum(prod, axis=1).astype(out_dtype)


@partial(jax.jit, static_argnames=("rows",))
def sliced_ell_spmv_f32acc(bins, x, rows: int):
    """Low-byte-storage sliced-ELL SpMV: per-bin masked f32 products
    and f32 row reductions, scattered back in original row order
    (same unique-sorted ``.at[].set`` as :func:`sliced_ell_spmv`)."""
    _obs.inc("trace.sliced_ell_spmv_f32acc")
    out_dtype = jnp.result_type(bins[0][0].dtype, x.dtype)
    y = jnp.zeros((rows,), dtype=out_dtype)
    for ell_data, ell_cols, cnt, row_idx in bins:
        W = ell_data.shape[1]
        slot = jnp.arange(W, dtype=cnt.dtype)
        valid = slot[None, :] < cnt[:, None]
        prod = jnp.where(
            valid,
            ell_data.astype(jnp.float32)
            * x[ell_cols].astype(jnp.float32),
            jnp.zeros((1, 1), dtype=jnp.float32),
        )
        y = y.at[row_idx].set(
            jnp.sum(prod, axis=1).astype(out_dtype),
            indices_are_sorted=True, unique_indices=True)
    return y


# --- Semiring-generalized kernels (graph/semiring.py catalog) -----------
#
# Graph traversal is SpMV with the (add, multiply) pair swapped
# (min-plus relaxation, or-and frontier push, max-times best path —
# docs/GRAPH.md).  These kernels are the plus-times masked kernels
# with two static strings threaded through: ``add`` picks the segment
# reduction, ``mul`` the product.  The masking contract generalizes
# verbatim: a padded slot's *product* is replaced by the semiring's
# additive identity (== its multiplicative annihilator: 0 / +-inf /
# False), so the reduction absorbs it exactly as the plus-times
# kernels absorb an exact 0 — and the empty-segment fill of
# ``segment_min``/``segment_max`` (+inf / -inf) is that same identity,
# so rows with no stored entries come out right for free.

_SEG_REDUCE = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}

_ROW_REDUCE = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}


def semiring_identity(add: str, dtype):
    """Additive identity of a catalog add-op as a rank-0 ``dtype``
    array — the padded-slot masking value (sum: 0; min: +inf; max:
    -inf; booleans: or IS max, identity False)."""
    dtype = jnp.dtype(dtype)
    if add == "sum":
        return jnp.zeros((), dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(add == "min", dtype=dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if add == "min" else -jnp.inf,
                           dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if add == "min" else info.min,
                       dtype=dtype)


def _semiring_product(mul: str, vals, gathered):
    """The per-slot product.  ``and`` is structural (a stored entry IS
    an edge — csgraph's explicit-zero convention), so the product is
    the gathered frontier bit, independent of the stored value."""
    if mul == "times":
        return vals * gathered
    if mul == "plus":
        return vals + gathered
    if mul == "and":
        return gathered.astype(jnp.bool_)
    raise ValueError(f"unknown semiring multiply {mul!r}")


@partial(jax.jit, static_argnames=("rows", "add", "mul"))
def csr_semiring_spmv_rowids_masked(data, indices, row_ids, valid_nnz,
                                    x, rows: int, add: str, mul: str):
    """Semiring SpMV over a padded nonzero suffix: the
    ``csr_spmv_rowids_masked`` program with the reduction and product
    generalized to the (add, mul) pair.  ``add="sum", mul="times"``
    is bit-identical to the plus-times kernel (same gather, same
    in-order segment reduction)."""
    _obs.inc("trace.csr_semiring_spmv_rowids_masked")
    nnz = data.shape[0]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    prod = _semiring_product(mul, data, x[indices])
    prod = jnp.where(slot < valid_nnz, prod,
                     semiring_identity(add, prod.dtype))
    return _SEG_REDUCE[add](
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows", "add", "mul"))
def csr_semiring_spmm_rowids_masked(data, indices, row_ids, valid_nnz,
                                    X, rows: int, add: str, mul: str):
    """Batched semiring SpMV (k stacked operand columns in one
    dispatch — the multi-source frontier kernel, the semiring arm of
    the PR-8 stacked ``multi_matvec`` packing): column by column this
    is exactly :func:`csr_semiring_spmv_rowids_masked`, so a batch of
    k sources is bit-for-bit the k individual sweeps."""
    _obs.inc("trace.csr_semiring_spmm_rowids_masked")
    nnz = data.shape[0]
    slot = jnp.arange(nnz, dtype=jnp.int32)
    prod = _semiring_product(mul, data[:, None], X[indices, :])
    prod = jnp.where((slot < valid_nnz)[:, None], prod,
                     semiring_identity(add, prod.dtype))
    return _SEG_REDUCE[add](
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("add", "mul"))
def ell_semiring_spmv(ell_data, ell_cols, ell_counts, x, add: str,
                      mul: str):
    """Semiring SpMV over ELL-packed structure (the :func:`ell_spmv`
    program generalized): padded slots' products masked to the
    semiring identity, W-width row reduction by the add-op."""
    _obs.inc("trace.ell_semiring_spmv")
    W = ell_data.shape[1]
    slot = jnp.arange(W, dtype=ell_counts.dtype)
    valid = slot[None, :] < ell_counts[:, None]
    prod = _semiring_product(mul, ell_data, x[ell_cols])
    prod = jnp.where(valid, prod, semiring_identity(add, prod.dtype))
    return _ROW_REDUCE[add](prod, axis=1)


@partial(jax.jit, static_argnames=("add", "mul"))
def ell_semiring_spmm(ell_data, ell_cols, ell_counts, X, add: str,
                      mul: str):
    """Batched semiring SpMV over ELL structure (dense (cols, k)
    operand — the distributed multi-source frontier's per-shard
    kernel).  Frontier batches are narrow, so the (rows, W, k)
    product is materialized in one fused pass (no
    ``_ELL_SPMM_MATERIALIZE_CAP`` loop arm)."""
    _obs.inc("trace.ell_semiring_spmm")
    W = ell_data.shape[1]
    slot = jnp.arange(W, dtype=ell_counts.dtype)
    valid = slot[None, :] < ell_counts[:, None]
    prod = _semiring_product(mul, ell_data[:, :, None], X[ell_cols, :])
    prod = jnp.where(valid[:, :, None], prod,
                     semiring_identity(add, prod.dtype))
    return _ROW_REDUCE[add](prod, axis=1)


@partial(jax.jit, static_argnames=("rows", "add", "mul"))
def sliced_ell_semiring_spmv(bins, x, rows: int, add: str, mul: str):
    """Semiring SpMV over a :func:`sliced_ell_pack` structure: one
    masked ELL reduction per bin scattered back in original row order
    (same unique-sorted ``.at[].set`` as :func:`sliced_ell_spmv`).
    Rows outside every bin (zero stored entries) keep the semiring
    identity — the empty-segment value of the rowids kernels."""
    _obs.inc("trace.sliced_ell_semiring_spmv")
    probe = _semiring_product(mul, bins[0][0][:1, :1],
                              x[bins[0][1][:1, :1]])
    out_dtype = probe.dtype
    y = jnp.full((rows,), semiring_identity(add, out_dtype),
                 dtype=out_dtype)
    for ell_data, ell_cols, cnt, row_idx in bins:
        W = ell_data.shape[1]
        slot = jnp.arange(W, dtype=cnt.dtype)
        valid = slot[None, :] < cnt[:, None]
        prod = _semiring_product(mul, ell_data, x[ell_cols])
        prod = jnp.where(valid, prod,
                         semiring_identity(add, prod.dtype))
        y = y.at[row_idx].set(
            _ROW_REDUCE[add](prod, axis=1).astype(out_dtype),
            indices_are_sorted=True, unique_indices=True)
    return y


# Above this many intermediate elements (rows*W*k), ell_spmm switches to
# a W-slice accumulation loop instead of materializing the full
# (rows, W, k) product tensor (~512 MB of f32 at the default cap).
_ELL_SPMM_MATERIALIZE_CAP = 1 << 27


@jax.jit
def ell_spmm(ell_data, ell_cols, ell_counts, X):
    """Y = A @ X (dense X, shape (cols, k)) over ELL-packed structure.

    Shapes are static under jit, so the memory strategy is picked at
    trace time: one fused (rows, W, k) pass when it fits, else a
    fori_loop accumulating one W-slice at a time (transient memory
    O(rows*k) instead of O(rows*W*k))."""
    _obs.inc("trace.ell_spmm")
    rows, W = ell_data.shape
    k = X.shape[1]
    slot = jnp.arange(W, dtype=ell_counts.dtype)
    valid = slot[None, :] < ell_counts[:, None]
    if rows * W * k <= _ELL_SPMM_MATERIALIZE_CAP:
        prod = jnp.where(valid[:, :, None],
                         ell_data[:, :, None] * X[ell_cols, :],
                         jnp.zeros((1, 1, 1), dtype=ell_data.dtype))
        return jnp.sum(prod, axis=1)

    def body(w, Y):
        v = jax.lax.dynamic_slice_in_dim(valid, w, 1, axis=1)       # (rows,1)
        d = jax.lax.dynamic_slice_in_dim(ell_data, w, 1, axis=1)
        c = jax.lax.dynamic_slice_in_dim(ell_cols, w, 1, axis=1)[:, 0]
        contrib = jnp.where(v, d * X[c, :],
                            jnp.zeros((1, 1), dtype=ell_data.dtype))
        return Y + contrib

    Y0 = jnp.zeros((rows, k), dtype=ell_data.dtype)
    return jax.lax.fori_loop(0, W, body, Y0)


def ell_within_budget(rows: int, W: int, nnz: int,
                      max_expand: float) -> bool:
    """Shared ELL padding-budget predicate (single-chip + distributed)."""
    return max_expand > 0 and rows * W <= max_expand * max(nnz, 1)


def ell_pack(data, indices, indptr, rows: int, W: int, xp=jnp):
    """Pack CSR into ELL blocks; works on jnp *or* numpy (xp).

    Returns ``(ell_data, ell_cols, ell_counts)``: (rows, W) value and
    column blocks plus the (rows,) per-row nnz.  W is the matrix's max
    nonzeros-per-row.  Padded slots replicate the row's last valid
    column (keeping the gather local) with value 0; the SpMV kernels
    mask padded *products* with ``ell_counts`` so padded slots
    contribute an exact 0 even against non-finite x.

    The structure analog of the reference's cached image partitions:
    computed once per matrix, reused every SpMV.
    """
    nnz = indices.shape[0]
    counts = (indptr[1:] - indptr[:-1]).astype(xp.int32)
    if nnz == 0:
        return (
            xp.zeros((rows, W), dtype=data.dtype),
            xp.zeros((rows, W), dtype=indices.dtype),
            counts,
        )
    slot = xp.arange(W, dtype=indptr.dtype)
    row_start = indptr[:-1, None]
    row_last = xp.clip(indptr[1:, None] - 1, 0, nnz - 1)
    src = xp.minimum(row_start + slot[None, :], row_last)
    valid = slot[None, :] < counts[:, None]
    ell_cols = indices[src]
    ell_data = xp.where(valid, data[src], xp.zeros((1, 1), dtype=data.dtype))
    return ell_data, ell_cols, counts


@partial(jax.jit, static_argnames=("rows", "W"))
def ell_pack_device(data, indices, indptr, rows: int, W: int):
    """Device-side ELL pack (one fused gather; no host round trip)."""
    return ell_pack(data, indices, indptr, rows, W, xp=jnp)


@partial(jax.jit, static_argnames=("rows",))
def csr_spmm_rowids(data, indices, row_ids, X, rows: int):
    """SpMM with precomputed per-nnz row ids (static matrix structure)."""
    _obs.inc("trace.csr_spmm_rowids")
    prod = data[:, None] * X[indices, :]
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows",))
def csr_spmm(data, indices, indptr, X, rows: int):
    """Y = A @ X for dense X of shape (cols, k) — column-batched SpMV.

    The reference reaches this through repeated SpMV dispatch; on TPU the
    whole k-wide gather feeds the VPU in one pass.
    """
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    prod = data[:, None] * X[indices, :]
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("cols",))
def csr_rmatvec(data, indices, indptr, x, cols: int):
    """y = A.T @ x without materializing the transpose: scatter-add
    x[row]*val into column bins (used by ``sum(axis=0)`` and rmatvec
    fallbacks; the reference instead materializes ``A.T.conj()`` —
    ``linalg.py:375-390``)."""
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    contrib = data * x[row_ids]
    return jnp.zeros((cols,), dtype=contrib.dtype).at[indices].add(contrib)
