# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sparse matrix-vector / matrix-matrix products.

TPU-native replacement for the reference's CSR SpMV row-split task family
(reference: ``src/sparse/array/csr/spmv.cc:36-44`` CPU loop,
``spmv_omp.cc:36-45``, ``spmv.cu:62-152`` cuSPARSE with the
shifted-pointer trick).  The row-block distribution strategy
(``csr.py:562-593`` align + image constraints) lives in
``parallel/dist_csr.py``; this module is the single-shard kernel.

Kernel choice on TPU:
- General CSR: gather x by column index, multiply, ``segment_sum`` by row.
  XLA lowers the gather + segmented reduction onto the VPU; no scalar
  loops, no dynamic shapes.
- Structured (banded/DIA) matrices keep the gather-free shifted-add
  kernels in ``ops/dia_ops.py`` (use ``dia_array.dot``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .convert import row_ids_from_indptr


@partial(jax.jit, static_argnames=("rows",))
def csr_spmv(data, indices, indptr, x, rows: int):
    """y[i] = sum_j data[j] * x[indices[j]] over row i's extent.

    Matches the reference leaf computation (``spmv.cc:36-44``) as one
    fused gather-multiply-segment_sum; XLA fuses the three into a single
    HBM pass over (data, indices).
    """
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    prod = data * x[indices]
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("rows",))
def csr_spmm(data, indices, indptr, X, rows: int):
    """Y = A @ X for dense X of shape (cols, k) — column-batched SpMV.

    The reference reaches this through repeated SpMV dispatch; on TPU the
    whole k-wide gather feeds the VPU in one pass.
    """
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    prod = data[:, None] * X[indices, :]
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows, indices_are_sorted=True
    )


@partial(jax.jit, static_argnames=("cols",))
def csr_rmatvec(data, indices, indptr, x, cols: int):
    """y = A.T @ x without materializing the transpose: scatter-add
    x[row]*val into column bins (used by ``sum(axis=0)`` and rmatvec
    fallbacks; the reference instead materializes ``A.T.conj()`` —
    ``linalg.py:375-390``)."""
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    contrib = data * x[row_ids]
    return jnp.zeros((cols,), dtype=contrib.dtype).at[indices].add(contrib)
