# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distribution layer: meshes, row-block sharded CSR, collective SpMV.

The TPU-native replacement for the reference's Legion partitioning
machinery (reference: align/image constraints at ``csr.py:580-593``,
NCCL communicator at ``csr.py:637``, projection functors
``projections.cc:23-64``): a 1-D ``jax.sharding.Mesh`` over the row
dimension, ``shard_map``-ped kernels, and explicit ICI collectives
(``all_gather``/``psum``/``ppermute``).

``shard_csr`` takes a first-class ``layout`` strategy (``1d-row`` /
``1d-col`` / ``2d-block`` / ``auto`` — docs/DIST.md): 2-d-block
partitions over a ``make_grid_mesh(R, C)`` grid with x panels
broadcast along mesh rows and partial products reduce-scattered along
mesh columns, and ``auto`` routes by predicted interconnect bytes.
"""

from .mesh import (  # noqa: F401
    LAYOUT_1D_COL,
    LAYOUT_1D_ROW,
    LAYOUT_2D_BLOCK,
    LAYOUT_AUTO,
    LAYOUTS,
    factor_grid,
    init_distributed,
    make_grid_mesh,
    make_row_mesh,
    resolve_layout,
    row_spec,
    survivor_mesh,
)
from .dist_csr import (  # noqa: F401
    DistCSR,
    shard_csr,
    shard_dense,
    dist_spmv,
    dist_spmm,
    dist_cg,
    dist_gmres,
    dist_bicgstab,
    dist_minres,
    dist_eigsh,
    dist_plan_fingerprint,
    mesh_fingerprint,
)
from .reshard import chunk_permute_plan, reshard, reshard_vector  # noqa: F401
from .dist_spgemm import dist_spgemm  # noqa: F401
from .dist_csr import dist_diagonal  # noqa: F401
from .dist_build import dist_diags, dist_poisson2d  # noqa: F401
from .dist_gmg import DistGMG  # noqa: F401
