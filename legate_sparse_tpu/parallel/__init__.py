# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distribution layer: meshes, row-block sharded CSR, collective SpMV.

The TPU-native replacement for the reference's Legion partitioning
machinery (reference: align/image constraints at ``csr.py:580-593``,
NCCL communicator at ``csr.py:637``, projection functors
``projections.cc:23-64``): a 1-D ``jax.sharding.Mesh`` over the row
dimension, ``shard_map``-ped kernels, and explicit ICI collectives
(``all_gather``/``psum``/``ppermute``).
"""

from .mesh import (  # noqa: F401
    factor_grid,
    init_distributed,
    make_grid_mesh,
    make_row_mesh,
    row_spec,
)
from .dist_csr import (  # noqa: F401
    DistCSR,
    shard_csr,
    shard_dense,
    dist_spmv,
    dist_spmm,
    dist_cg,
    dist_gmres,
    dist_bicgstab,
    dist_minres,
    dist_eigsh,
    dist_plan_fingerprint,
    mesh_fingerprint,
)
from .dist_spgemm import dist_spgemm  # noqa: F401
from .dist_csr import dist_diagonal  # noqa: F401
from .dist_build import dist_diags, dist_poisson2d  # noqa: F401
from .dist_gmg import DistGMG  # noqa: F401
