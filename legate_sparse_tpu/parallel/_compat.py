# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""jax version compatibility shims for the parallel layer.

``shard_map`` moved twice across the jax versions this package must
run on: ``jax.experimental.shard_map.shard_map`` (<= 0.4.x, keyword
``check_rep``), then ``jax.shard_map`` (>= 0.6, keyword ``check_vma``).
A bare ``from jax import shard_map`` at module import time kills
collection of the ENTIRE test suite on older jax (the r5 seed failure
mode), so every parallel module imports the resolved symbol from here
instead.

The wrapper normalizes on the NEW keyword spelling (``check_vma``) and
translates for the experimental API, so call sites are written once
against the modern surface.
"""

from __future__ import annotations

import jax as _jax

_NATIVE = getattr(_jax, "shard_map", None)

if _NATIVE is not None:
    shard_map = _NATIVE
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  **kwargs):
        """``jax.shard_map``-shaped facade over the experimental API
        (``check_vma`` maps onto the old ``check_rep`` flag)."""
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kwargs,
        )


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis, inside ``shard_map``.

    ``jax.lax.axis_size`` only exists on newer jax; on 0.4.x the axis
    environment exposes the same static value through
    ``jax.core.axis_frame`` (which returns the bare size there)."""
    fn = getattr(_jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    import jax.core as _jc

    return int(_jc.axis_frame(axis_name))


__all__ = ["shard_map", "axis_size"]
