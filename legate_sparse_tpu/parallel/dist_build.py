# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sharded matrix construction: build each row block on its own shard.

Kills the host-assembly bottleneck the reference acknowledges for its
dense→CSR path (reference ``legate_sparse/csr.py:134-145`` runs a
single-process manual task) and that round 1's ``shard_csr`` reproduced
(host numpy build of the full CSR before sharding).  Here a banded
matrix never exists as a host CSR: each shard computes its (rps, W) ELL
blocks directly on device from the diagonal *descriptions* — scalars,
per-diagonal value arrays (sliced per shard, never concatenated into a
global CSR), or jit-traceable callables (zero host data at any size).

At 1e8 rows (BASELINE.md north star) this is the difference between a
multi-minute single-host build and an O(nnz/R)-per-device one.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..types import index_dtype
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .dist_csr import DistCSR
from .mesh import ROW_AXIS, make_row_mesh

DiagSpec = Union[float, int, np.ndarray, Callable]


def band_ell_local(vals_by_diag, offs_dev, n: int, rps: int, halo: int,
                   start, r, r_l):
    """Per-shard full-band -> ELL assembly (shared by ``dist_diags`` and
    the banded distributed SpGEMM): given row-indexed diagonal values
    ``vals_by_diag`` (W, rps) and sorted ``offs_dev``, produce
    (ell_data, ell_cols, cnt) with the standard padded-slot conventions
    (padding replicates the clamped column with value 0; cols rebased to
    the halo window when ``halo >= 0``)."""
    dtype = vals_by_diag.dtype
    W = vals_by_diag.shape[0]
    # Valid diagonal range per row: o in [-r, n-1-r].
    lo = jnp.searchsorted(offs_dev, -r, side="left")
    hi = jnp.searchsorted(offs_dev, n - r, side="left")
    cnt = jnp.where(r < n, hi - lo, 0).astype(jnp.int32)
    slot = jnp.arange(W, dtype=jnp.int32)
    valid = slot[None, :] < cnt[:, None]
    d_idx = jnp.clip(
        lo[:, None] + jnp.minimum(slot[None, :],
                                  jnp.maximum(cnt[:, None] - 1, 0)),
        0, W - 1,
    )
    col = jnp.clip(r[:, None] + offs_dev[d_idx], 0, n - 1)
    ell_data = jnp.where(
        valid, vals_by_diag[d_idx, r_l[:, None]], jnp.zeros((), dtype)
    )
    if halo >= 0:
        ell_cols = jnp.clip(
            col - (start - halo), 0, rps + 2 * halo - 1
        ).astype(jnp.int32)
    else:
        from ..types import coord_dtype_for

        ell_cols = col.astype(coord_dtype_for(n))
    return ell_data, ell_cols, cnt


def dist_diags(
    diagonals: Sequence[DiagSpec],
    offsets: Sequence[int],
    shape,
    mesh: Optional[Mesh] = None,
    dtype=np.float64,
    materialize_ell: bool = True,
) -> DistCSR:
    """Banded ``DistCSR`` built shard-locally (scipy ``diags`` semantics).

    Each diagonal may be:

    - a **scalar** — constant band (no host data at all);
    - a **callable** ``f(i)`` mapping the diagonal's element indices
      (a traced jnp int array, scipy ``diags`` indexing: element ``i``
      sits at ``(i, i+k)`` for ``k>=0``, ``(i-k, i)`` for ``k<0``) to
      values — evaluated on device per shard;
    - an **array** of length ``n - |k|`` — sliced per shard on host
      (views + one (rps,) copy per shard; the global CSR is never
      materialized).

    The result is the ELL layout ``shard_csr`` would pick for a banded
    matrix, with the same halo/rebase invariants, plus DIA fast-path
    blocks in halo mode.  ``materialize_ell=False`` (halo mode only)
    skips the ELL blocks entirely — the memory-lean scale path: the
    matrix then supports ``dist_spmv``/``dist_diagonal``/``to_csr``
    (solvers) but not block consumers like ``dist_spgemm``.
    """
    if mesh is None:
        mesh = make_row_mesh()
    rows, cols = int(shape[0]), int(shape[1])
    if rows != cols:
        raise NotImplementedError("dist_diags requires a square shape")
    n = rows
    order = np.argsort(np.asarray(offsets, dtype=np.int64), kind="stable")
    offs = np.asarray(offsets, dtype=np.int64)[order]
    diags_sorted = [diagonals[i] for i in order]
    if len(set(offs.tolist())) != len(offs):
        raise ValueError("duplicate offsets")
    W = len(offs)
    # Row-shard count: the size of the "rows" axis only (a 2-D
    # grid mesh replicates the matrix along "cols").
    R = int(mesh.shape[ROW_AXIS])
    rps = math.ceil(n / R) if n else 1
    rows_p = R * rps
    starts = np.minimum(np.arange(R) * rps, n)

    # Halo decision mirrors shard_csr: every window reach must fit one
    # neighbor block on each side.
    reach = int(max(offs.max(initial=0), -offs.min(initial=0)))
    halo = reach if reach <= rps else -1
    if not materialize_ell and halo < 0:
        raise ValueError(
            "materialize_ell=False requires halo mode "
            f"(band reach {reach} > rows-per-shard {rps})"
        )

    dtype = np.dtype(dtype)

    # Host-array diagonals -> per-shard (rps,) windows, stacked (R, rps).
    # block[s, r_l] = value of this diagonal at global row start+r_l
    # (row-indexed for k>=0, column-indexed source i = r+k for k<0).
    array_blocks = {}
    for d, (k, spec) in enumerate(zip(offs.tolist(), diags_sorted)):
        if np.isscalar(spec) or callable(spec):
            continue
        arr = np.asarray(spec, dtype=dtype)
        L = n - abs(k)
        if arr.ndim == 0:
            continue
        if arr.shape[0] != L:
            raise ValueError(
                f"diagonal {k} has length {arr.shape[0]}, expected {L}"
            )
        block = np.zeros((R, rps), dtype=dtype)
        for s in range(R):
            # Source index for local row r_l: i = r (k>=0) or r+k (k<0).
            i_lo = starts[s] + (0 if k >= 0 else k)
            i_hi = i_lo + rps
            o_lo, o_hi = max(i_lo, 0), min(i_hi, L)
            if o_hi > o_lo:
                block[s, o_lo - i_lo : o_hi - i_lo] = arr[o_lo:o_hi]
        array_blocks[d] = jax.device_put(
            jnp.asarray(block), NamedSharding(mesh, P(ROW_AXIS))
        )

    offs_dev = jnp.asarray(offs)

    def kernel(*blocks):
        shard = jax.lax.axis_index(ROW_AXIS)
        start = shard.astype(index_dtype()) * rps
        r_l = jnp.arange(rps, dtype=index_dtype())
        r = start + r_l

        # vals_by_diag[d, r_l] = value of diagonal d at global row r.
        vals = []
        b_iter = iter(blocks)
        for d, (k, spec) in enumerate(zip(offs.tolist(), diags_sorted)):  # lint: disable=trace-purity — offs is a host np array; static per-diag unroll at trace time is deliberate
            if d in array_blocks:
                vals.append(next(b_iter)[0])
            elif callable(spec):
                i = r + min(k, 0)
                i = jnp.clip(i, 0, max(n - abs(k) - 1, 0))
                vals.append(jnp.asarray(spec(i), dtype=dtype))
            else:
                vals.append(
                    jnp.full((rps,), float(spec), dtype=dtype)
                )
        vals_by_diag = jnp.stack(vals)                      # (W, rps)

        outs = ()
        if materialize_ell:
            ell_data, ell_cols, cnt = band_ell_local(
                vals_by_diag, offs_dev, n, rps, halo, start, r, r_l
            )
            outs += (ell_data[None], ell_cols[None], cnt[None])
        if halo >= 0:
            # DIA fast-path blocks (gather-free dist_spmv): value of
            # diagonal d at local row r, zeroed outside the matrix.
            tgt = r[:, None] + offs_dev[None, :]
            in_range = jnp.logical_and(
                jnp.logical_and(tgt >= 0, tgt < n), r[:, None] < n
            )                                            # (rps, W)
            dia_block = jnp.where(
                in_range.T, vals_by_diag, jnp.zeros((), dtype)
            )
            outs += (dia_block[None],)
        return outs

    blocks = tuple(array_blocks[d] for d in sorted(array_blocks))
    in_specs = tuple(P(ROW_AXIS, None) for _ in blocks)
    ell_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                 P(ROW_AXIS, None))
    out_specs = (ell_specs if materialize_ell else ()) + (
        (P(ROW_AXIS, None, None),) if halo >= 0 else ()
    )
    results = shard_map(
        kernel, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )(*blocks)

    data = cols_b = counts = dia_data = None
    if materialize_ell:
        data, cols_b, counts = results[:3]
        results = results[3:]
    if halo >= 0:
        (dia_data,) = results

    from .dist_csr import attach_dia_prepack

    return attach_dia_prepack(DistCSR(
        data=data, cols=cols_b, counts=counts, row_ids=None,
        shape=(n, n), rows_per_shard=rps, halo=halo, ell=True, mesh=mesh,
        dia_data=dia_data,
        dia_offsets=(tuple(int(o) for o in offs.tolist())
                     if halo >= 0 else None),
        # Stored entries = every in-range band slot (explicit zeros
        # from callable diagonals included — they occupy ELL slots).
        nnz_hint=sum(n - abs(int(k)) for k in offs.tolist()),
    ))


def dist_poisson2d(N: int, mesh: Optional[Mesh] = None,
                   dtype=np.float64,
                   materialize_ell: bool = True) -> DistCSR:
    """5-point 2-D Poisson operator, built entirely on device (no host
    data of any size — the boundary pattern is a traced callable)."""
    n = N * N

    def off1(i):
        # Coupling (i, i+1) is zero across grid-row boundaries.
        return jnp.where((i + 1) % N == 0, 0.0, -1.0)

    return dist_diags(
        [4.0, off1, off1, -1.0, -1.0],
        [0, 1, -1, N, -N],
        shape=(n, n), mesh=mesh, dtype=dtype,
        materialize_ell=materialize_ell,
    )
