# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Row-block distributed CSR and collective SpMV / CG.

TPU-native re-expression of the reference's entire distribution story
(reference, §2.3 of SURVEY):

- Row-block data parallelism — ``align(y, A_pos)`` equi-partitioning of
  rows (reference ``csr.py:580-593``) becomes a 1-D mesh with the three
  CSR arrays laid out as (num_shards, ...) blocks sharded on axis 0.
- Image partitioning — ``image(crd, x, MIN_MAX)`` bounding-box gathers
  (reference ``csr.py:587-591``, ``fast_image_partition.cu:29-55``)
  become build-time column-window computation; at solve time each shard
  either slices an ``all_gather``-ed x or exchanges fixed-width halos
  with mesh neighbors over ICI via ``ppermute`` (banded matrices).
- NCCL allgather of local nnz (reference ``spgemm_csr_csr_csr.cu:43-62``)
  becomes host-side padding to the max local nnz: XLA's static-shape
  analog of unbound stores.

Padding invariants: rows are padded to a multiple of the shard count and
each shard's nonzeros are padded to the per-shard max with
(index=last-valid, value=0) entries, which contribute zeros to the last
local row — semantics are exact, no masking needed.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..csr import csr_array
from ..types import nnz_ty
from .mesh import ROW_AXIS, make_row_mesh


@dataclass
class DistCSR:
    """Row-block sharded CSR matrix.

    Arrays are (R, ...) blocks sharded over mesh axis ``rows``:

    - ``data``/``indices``: (R, nnz_max) value / global column index
    - ``indices_rebased``: (R, nnz_max) column index rebased to the
      shard's halo-extended x window (valid when ``halo >= 0``)
    - ``indptr``: (R, rows_per_shard + 1) local row pointers
    """

    data: jax.Array
    indices: jax.Array
    indices_rebased: Optional[jax.Array]
    indptr: jax.Array
    shape: Tuple[int, int]
    rows_per_shard: int
    halo: int           # -1 = halo exchange not applicable -> all_gather
    mesh: Mesh

    @property
    def num_shards(self) -> int:
        return self.data.shape[0]

    @property
    def rows_padded(self) -> int:
        return self.num_shards * self.rows_per_shard

    @property
    def dtype(self):
        return np.dtype(self.data.dtype)

    def matvec_fn(self):
        """A jittable ``x_padded -> y_padded`` closure for solver loops."""
        return partial(dist_spmv, self)


def shard_csr(A: csr_array, mesh: Optional[Mesh] = None,
              force_all_gather: bool = False) -> DistCSR:
    """Partition a csr_array into row blocks over a 1-D mesh.

    Host-side build step (the analog of Legion solving partition
    constraints once and caching them across solver iterations —
    reference §3.2 note on partition caching).  Computes each shard's
    column window min/max — the FAST_IMAGE_RANGE analog
    (``fast_image_partition.cu:29-55``) — and picks halo-exchange when
    every window fits within one neighbor shard on each side.
    """
    if mesh is None:
        mesh = make_row_mesh()
    R = int(np.prod(mesh.devices.shape))
    rows, cols = A.shape
    rps = math.ceil(rows / R) if rows else 1
    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)

    starts = np.minimum(np.arange(R) * rps, rows)
    ends = np.minimum(starts + rps, rows)
    lo = indptr[starts]
    hi = indptr[ends]
    local_nnz = hi - lo
    nnz_max = max(int(local_nnz.max()), 1) if A.nnz else 1

    data_b = np.zeros((R, nnz_max), dtype=data.dtype)
    idx_b = np.zeros((R, nnz_max), dtype=indices.dtype)
    ptr_b = np.zeros((R, rps + 1), dtype=indptr.dtype)
    col_min = np.zeros(R, dtype=np.int64)
    col_max = np.zeros(R, dtype=np.int64)
    for s in range(R):
        ln = int(local_nnz[s])
        data_b[s, :ln] = data[lo[s] : hi[s]]
        idx_b[s, :ln] = indices[lo[s] : hi[s]]
        # Padding entries keep index 0 / value 0 (contribute 0 to last row).
        nrows_s = ends[s] - starts[s]
        ptr_b[s, : nrows_s + 1] = indptr[starts[s] : ends[s] + 1] - lo[s]
        ptr_b[s, nrows_s + 1 :] = ln
        if ln:
            col_min[s] = idx_b[s, :ln].min()
            col_max[s] = idx_b[s, :ln].max()
        else:
            col_min[s] = starts[s] if starts[s] < cols else 0
            col_max[s] = col_min[s]

    # Halo width: how far each shard's window reaches outside its own
    # row block (square matrices only — halo mode needs x and rows to be
    # conformally sharded).
    halo = -1
    indices_rebased = None
    if rows == cols and not force_all_gather:
        left_reach = np.maximum(starts - col_min, 0)
        right_reach = np.maximum(col_max + 1 - ends, 0)
        h = int(max(left_reach.max(), right_reach.max()))
        if h <= rps:
            halo = h
            # Rebase: local index = global - (start - h).
            reb = idx_b - (starts - h)[:, None]
            reb = np.clip(reb, 0, rps + 2 * h - 1)
            indices_rebased = reb.astype(idx_b.dtype)

    spec = NamedSharding(mesh, P(ROW_AXIS))
    put = lambda arr: jax.device_put(jnp.asarray(arr), spec)
    return DistCSR(
        data=put(data_b),
        indices=put(idx_b),
        indices_rebased=put(indices_rebased) if indices_rebased is not None else None,
        indptr=put(ptr_b),
        shape=(rows, cols),
        rows_per_shard=rps,
        halo=halo,
        mesh=mesh,
    )


def shard_vector(x, mesh: Mesh, rows_padded: int) -> jax.Array:
    """Pad a global vector to the sharded length and lay it out row-block."""
    x = jnp.asarray(x)
    pad = rows_padded - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), dtype=x.dtype)])
    return jax.device_put(x, NamedSharding(mesh, P(ROW_AXIS)))


def _local_row_ids(indptr_local, nnz_max: int):
    return jnp.searchsorted(
        indptr_local[1:-1], jnp.arange(nnz_max, dtype=indptr_local.dtype),
        side="right",
    )


def _spmv_kernel_allgather(data, indices, indptr, x_local, rows_per_shard):
    """Per-shard body: gather the full x over ICI, then local SpMV.

    The ``all_gather`` is the general-case image realization (reference's
    Realm copies for MIN_MAX images spanning many shards).
    """
    x_full = jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)
    d = data[0]
    prod = d * x_full[indices[0]]
    row_ids = _local_row_ids(indptr[0], d.shape[0])
    y = jax.ops.segment_sum(
        prod, row_ids, num_segments=rows_per_shard, indices_are_sorted=True
    )
    return y


def _spmv_kernel_halo(data, indices_rebased, indptr, x_local,
                      rows_per_shard, halo):
    """Per-shard body: fixed-width neighbor halo exchange over ICI.

    Structurally the ring/context-parallel neighbor pattern: each shard
    ppermutes its boundary slices left/right, never materializing the
    global x — this is what makes 1e8-row weak scaling possible where
    ``all_gather`` would not (SURVEY §7 hard part #4).
    """
    axis_size = jax.lax.axis_size(ROW_AXIS)
    d = data[0]
    if halo > 0:
        right_perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        left_perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
        from_left = jax.lax.ppermute(x_local[-halo:], ROW_AXIS, right_perm)
        from_right = jax.lax.ppermute(x_local[:halo], ROW_AXIS, left_perm)
        x_ext = jnp.concatenate([from_left, x_local, from_right])
    else:
        x_ext = x_local
    prod = d * x_ext[indices_rebased[0]]
    row_ids = _local_row_ids(indptr[0], d.shape[0])
    return jax.ops.segment_sum(
        prod, row_ids, num_segments=rows_per_shard, indices_are_sorted=True
    )


def dist_spmv(A: DistCSR, x: jax.Array) -> jax.Array:
    """y = A @ x with row-block parallelism (jittable).

    ``x`` and the result are row-block sharded vectors of length
    ``A.rows_padded``.  The distribution contract matches the reference
    SpMV task (``csr.py:562-593``): y aligned with the row partition,
    x gathered per the column image.
    """
    from jax import shard_map

    if A.halo >= 0 and A.indices_rebased is not None:
        kernel = partial(
            _spmv_kernel_halo,
            rows_per_shard=A.rows_per_shard,
            halo=A.halo,
        )
        args = (A.data, A.indices_rebased, A.indptr, x)
        in_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None), P(ROW_AXIS, None),
                    P(ROW_AXIS))
    else:
        kernel = partial(
            _spmv_kernel_allgather, rows_per_shard=A.rows_per_shard
        )
        args = (A.data, A.indices, A.indptr, x)
        in_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None), P(ROW_AXIS, None),
                    P(ROW_AXIS))
    return shard_map(
        kernel, mesh=A.mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    )(*args)


def dist_cg(
    A: DistCSR,
    b,
    x0=None,
    tol=None,
    maxiter: Optional[int] = None,
    atol: float = 0.0,
    rtol: float = 1e-5,
    conv_test_iters: int = 25,
):
    """Distributed CG: one jitted while_loop over sharded state.

    Global reductions (rho, pq, convergence norm) are jnp.vdot on sharded
    vectors — GSPMD lowers them to local dots + ``psum`` over ICI,
    replacing the reference's future-based scalar plumbing
    (``linalg.py:507-533``).  Returns the solution truncated to the
    unpadded length, plus the iteration count.
    """
    from ..linalg import _cg_loop, _get_atol_rtol

    rows = A.shape[0]
    b_sh = shard_vector(b, A.mesh, A.rows_padded)
    x0_sh = (
        shard_vector(jnp.asarray(x0, dtype=b_sh.dtype), A.mesh, A.rows_padded)
        if x0 is not None
        else jnp.zeros_like(b_sh)
    )
    bnrm2 = float(jnp.linalg.norm(b_sh))
    atol, _ = _get_atol_rtol(bnrm2, tol, atol, rtol)
    if maxiter is None:
        maxiter = rows * 10
    x, iters = _cg_loop(
        A.matvec_fn(), lambda r: r, b_sh, x0_sh, atol, int(maxiter),
        int(conv_test_iters),
    )
    return x[:rows], iters
