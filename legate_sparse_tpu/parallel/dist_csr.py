# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Row-block distributed CSR and collective SpMV / CG.

TPU-native re-expression of the reference's entire distribution story
(reference, §2.3 of SURVEY):

- Row-block data parallelism — ``align(y, A_pos)`` equi-partitioning of
  rows (reference ``csr.py:580-593``) becomes a 1-D mesh with the CSR
  arrays laid out as (num_shards, ...) blocks sharded on axis 0.
- Image partitioning — ``image(crd, x, MIN_MAX)`` bounding-box gathers
  (reference ``csr.py:587-591``, ``fast_image_partition.cu:29-55``)
  become build-time column-window computation; at solve time each shard
  either slices an ``all_gather``-ed x or exchanges fixed-width halos
  with mesh neighbors over ICI via ``ppermute`` (banded matrices).
- NCCL allgather of local nnz (reference ``spgemm_csr_csr_csr.cu:43-62``)
  becomes host-side padding to the max local nnz: XLA's static-shape
  analog of unbound stores.

Layout: each shard's rows are packed **ELL-style** — (rows_per_shard, W)
value/column blocks, W = the matrix's max nonzeros-per-row — so the
per-shard SpMV is one rectangular gather + a W-width masked row
reduction.  On TPU this runs at HBM roofline where flat
scatter/segment-sum kernels do not (the vector units consume the
(rows, W) tile directly; no scatter, no searchsorted).  Matrices whose
max row width would blow the padding budget fall back to padded-CSR
blocks + segment_sum.

Padding invariants: rows are padded to a multiple of the shard count
(appended rows have count 0).  Padded ELL slots replicate the row's
last valid column with value 0 and are masked out of the product by the
per-row counts (see ``ops.spmv.ell_pack``); padded CSR slots map to the
last local row with value 0.
"""

from __future__ import annotations

import contextlib
import math
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..obs import context as _tctx
from ..obs import latency as _lat
from ..engine import engine_enabled as _engine_enabled
from ..engine import get_engine as _get_engine
from ..resilience import checkpoint as _rckpt
from ..resilience import faults as _rfaults
from ..resilience import guarded_call as _resil_guarded
from ..resilience.outcomes import ChecksumError, DeviceLost
from ..settings import settings as _rsettings
from ..types import index_dtype
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..csr import csr_array
from .mesh import (
    COL_AXIS, LAYOUT_1D_COL, LAYOUT_1D_ROW, LAYOUT_2D_BLOCK,
    LAYOUT_AUTO, ROW_AXIS, factor_grid, make_grid_mesh, make_row_mesh,
    resolve_layout, survivor_mesh,
)


@dataclass
class DistCSR:
    """Row-block sharded sparse matrix (ELL or padded-CSR layout).

    ELL layout (``ell=True``): ``data``/``cols`` are (R, rows_per_shard,
    W) and ``counts`` is (R, rows_per_shard) per-row nnz; ``cols`` holds
    *rebased* indices into the shard's halo-extended x window when
    ``halo >= 0``, else global indices.

    Padded-CSR layout: ``data``/``cols`` are (R, nnz_max) with
    ``row_ids`` (R, nnz_max) static local row ids and ``counts`` the
    (R,) per-shard valid nnz (padding suffix masked in-kernel).
    """

    # ELL/padded-CSR blocks.  May be None for a DIA-only matrix
    # (``dist_diags(materialize_ell=False)`` — the memory-lean scale
    # path): then only ``dia_*`` consumers (dist_spmv, dist_diagonal,
    # to_csr) work and block-consuming ops raise with guidance.
    data: Optional[jax.Array]
    cols: Optional[jax.Array]
    counts: Optional[jax.Array]
    row_ids: Optional[jax.Array]
    shape: Tuple[int, int]
    rows_per_shard: int
    halo: int           # -1 = no halo window -> all_gather realization
    ell: bool
    mesh: Mesh
    # Precise-image gather plan (LEGATE_SPARSE_PRECISE_IMAGES): (R, R, C)
    # local x indices shard ``src`` sends to ``dst`` via all_to_all; cols
    # are then rebased into the compact (R*C,) receive buffer.  None =
    # halo/all_gather realization.  (Reference ``settings.py:23-33``.)
    gather_idx: Optional[jax.Array] = None
    # Inverse map, sharded by *destination*: gather_globals[s, t, p] =
    # global column of compact position t*C+p on shard s.  Lets every
    # consumer (diagonal, SpGEMM, to_csr) recover global columns with
    # one flat lookup.
    gather_globals: Optional[jax.Array] = None
    cols_per_shard: int = 0
    # Banded fast path (exactly-banded matrices in halo mode): per-shard
    # DIA blocks (R, num_diags, rps) + static offsets.  ``dist_spmv``
    # then runs gather-free shifted-adds on the halo-extended x — HBM
    # gathers run far below roofline on TPU, shifted-add streams hit it.
    # Auxiliary to the ELL/CSR blocks (which all other consumers use).
    dia_data: Optional[jax.Array] = None
    dia_offsets: Optional[Tuple[int, ...]] = None
    # Explicit-entry mask blocks (R, num_diags, rps) for *holey* bands
    # (None = exact band, validity derivable from the offsets alone).
    dia_mask: Optional[jax.Array] = None
    # Pre-blocked Mosaic layout for the per-shard Pallas band kernel,
    # built once at shard time (the cached-partition analog — the shard
    # body does zero packing per call): pdia_data (R, nd, rps_pad) is
    # the tile-padded band, pdia_mask (int8, same shape) merges global
    # bounds, padding rows and band holes.  ``pdia_tile`` is the grid
    # tile (0 = no prepack -> XLA shifted-add branch).
    pdia_data: Optional[jax.Array] = None
    pdia_mask: Optional[jax.Array] = None
    pdia_tile: int = 0
    # Per-shard block-sparse pack for irregular (all_gather) matrices
    # (``attach_bsr_prepack``): (R, nb_max, 128, 128) transposed
    # blocks + (R, nb_max) block coordinates; ``bsr_grid`` = (nbr, nbc)
    # of the per-shard block grid (None = no BSR route).
    bsr_blocks: Optional[jax.Array] = None
    bsr_brow: Optional[jax.Array] = None
    bsr_bcol: Optional[jax.Array] = None
    bsr_grid: Optional[Tuple[int, int]] = None
    bsr_tried: bool = False
    # Host-side stored-entry count, set by the builders that know it
    # (shard_csr, dist_diags, dist_spgemm).  -1 = unknown; consumers
    # that need it (the sparsity-aware window-decline key) fall back to
    # ``global_nnz`` once and memoize here — keeping the device->host
    # counts fetch off every later call.
    nnz_hint: int = -1
    # Partition layout strategy (docs/DIST.md).  "1d-row" is the
    # historical row-block layout described above.  ``grid`` is set for
    # the 2-d family ("2d-block" / "1d-col" = a (1, R) grid): blocks
    # are (Rr, Rc, nnz_max) padded-CSR sharded P(rows, cols, None)
    # with BLOCK-LOCAL column indices (global - j*cols_per_shard),
    # counts (Rr, Rc) per-block valid nnz, and vectors sharded
    # P((rows, cols)) in row-major grid chunks.
    layout: str = LAYOUT_1D_ROW
    grid: Optional[Tuple[int, int]] = None

    @property
    def num_shards(self) -> int:
        if self.grid is not None:
            return self.grid[0] * self.grid[1]
        blocks = self.data if self.data is not None else self.dia_data
        return blocks.shape[0]

    @property
    def rows_padded(self) -> int:
        # 2-d grids: the row dimension is split over grid[0] mesh rows
        # only (each row block further column-split over grid[1]).
        if self.grid is not None:
            return self.grid[0] * self.rows_per_shard
        return self.num_shards * self.rows_per_shard

    @property
    def cols_padded(self) -> int:
        """Padded column count (2-d layouts; equals the padded x
        length the SpMV consumes)."""
        if self.grid is not None:
            return self.grid[1] * self.cols_per_shard
        return self.shape[1]

    # ---- int32-local / int64-global index split (SURVEY §7 hard part
    # 5; reference runs coord_ty = int64 throughout,
    # ``legate_sparse/types.py:20-25``).  Device-side structures are
    # shard-LOCAL int32 (column windows, local row ids, per-shard
    # counts); everything GLOBAL — row offsets, total nnz — lives here
    # as host-side int64/Python ints, never as device arrays, so a
    # no-x64 TPU process handles matrices whose *global* nnz exceeds
    # 2^31 while every shard stays within int32.  ``coord_dtype_for``'s
    # OverflowError remains the single-device (host-CSR) boundary only.

    @property
    def shard_row_starts(self) -> np.ndarray:
        """Global first-row of each shard, host-side int64."""
        return (np.arange(self.num_shards, dtype=np.int64)
                * np.int64(self.rows_per_shard))

    @property
    def global_nnz(self) -> int:
        """Total stored entries across shards, as a host Python int
        (exact past 2^31 with int32 device counts — the summation never
        touches a device-wide int64 array)."""
        if self.counts is not None:
            # ELL: (R, rps) per-row counts (padding rows are 0);
            # padded-CSR: (R,) per-shard totals.  Same exact int64 sum.
            return int(np.asarray(self.counts).astype(np.int64).sum())
        # DIA-only matrix.  Masked bands: the mask is 0 outside the
        # global range by construction, so its sum is the count.
        if self.dia_mask is not None:
            return int(np.asarray(self.dia_mask).astype(np.int64).sum())
        # Exact bands: per-diagonal in-range slot count, Python ints
        # (exact at any size).
        rows, cols = self.shape
        return sum(
            max(0, min(rows, cols - o) - max(0, -o))
            for o in self.dia_offsets
        )

    @property
    def dtype(self):
        blocks = self.data if self.data is not None else self.dia_data
        return np.dtype(blocks.dtype)

    def _require_blocks(self, op: str) -> None:
        if self.data is None:
            raise ValueError(
                f"{op} needs ELL/CSR blocks, but this DistCSR is "
                "DIA-only (built with materialize_ell=False); rebuild "
                "with materialize_ell=True"
            )

    def matvec_fn(self):
        """A jittable ``x_padded -> y_padded`` closure for solver loops."""
        return partial(dist_spmv, self)

    def to_csr(self):
        """Gather the distributed matrix back to a host csr_array.

        Test/inspection utility (the analog of the reference pulling a
        store through ``store_to_cupynumeric_array``); O(global nnz) on
        the host — not a scale path.
        """
        from ..csr import csr_array

        rows, cols = self.shape
        R = self.num_shards
        rps = self.rows_per_shard
        if self.data is None:
            return self._dia_to_csr_host()
        if self.grid is not None:
            return self._grid_to_csr_host()
        starts = np.arange(R) * rps
        data_b = np.asarray(self.data)
        cols_b = np.asarray(self.cols)
        ggl = (np.asarray(self.gather_globals)
               if self.gather_globals is not None else None)

        def to_global(s, col_local):
            if ggl is not None:      # precise: compact buffer position
                base = ggl[s].reshape(-1)
                rc = base.shape[0]
                col_local = col_local.astype(np.int64)
                own = col_local - rc + s * self.cols_per_shard
                return np.where(
                    col_local < rc, base[np.clip(col_local, 0, rc - 1)],
                    own,
                )
            if self.halo >= 0:
                return col_local.astype(np.int64) + (starts[s] - self.halo)
            return col_local.astype(np.int64)

        coo_r, coo_c, coo_v = [], [], []
        if self.ell:
            counts = np.asarray(self.counts)          # (R, rps)
            for s in range(R):
                for_r = np.arange(rps)[:, None]
                W = cols_b.shape[-1]
                slot = np.arange(W)[None, :]
                valid = slot < counts[s][:, None]
                gcol = to_global(s, cols_b[s])
                r_ids = np.broadcast_to(for_r + starts[s], (rps, W))
                coo_r.append(r_ids[valid])
                coo_c.append(gcol[valid])
                coo_v.append(data_b[s][valid])
        else:
            counts = np.asarray(self.counts)          # (R,)
            rids_b = np.asarray(self.row_ids)
            for s in range(R):
                ln = int(counts[s])
                gcol = to_global(s, cols_b[s, :ln])
                coo_r.append(rids_b[s, :ln].astype(np.int64) + starts[s])
                coo_c.append(gcol)
                coo_v.append(data_b[s, :ln])
        coo_r = np.concatenate(coo_r) if coo_r else np.zeros(0, np.int64)
        coo_c = np.concatenate(coo_c) if coo_c else np.zeros(0, np.int64)
        coo_v = (np.concatenate(coo_v) if coo_v
                 else np.zeros(0, self.dtype))
        keep = coo_r < rows  # drop padding rows
        return csr_array(
            (coo_v[keep], (coo_r[keep], coo_c[keep])), shape=self.shape
        )

    def _grid_to_csr_host(self):
        """2-d-block matrix back to a host csr_array (test/inspection;
        O(global nnz) on the host — not a scale path)."""
        from ..csr import csr_array

        Rr, Rc = self.grid
        rps = self.rows_per_shard
        cps = self.cols_per_shard
        data_b = np.asarray(self.data)        # (Rr, Rc, nnz_max)
        cols_b = np.asarray(self.cols)
        rids_b = np.asarray(self.row_ids)
        counts = np.asarray(self.counts)      # (Rr, Rc)
        coo_r, coo_c, coo_v = [], [], []
        for i in range(Rr):
            for j in range(Rc):
                ln = int(counts[i, j])
                coo_r.append(rids_b[i, j, :ln].astype(np.int64)
                             + i * rps)
                coo_c.append(cols_b[i, j, :ln].astype(np.int64)
                             + j * cps)
                coo_v.append(data_b[i, j, :ln])
        coo_r = np.concatenate(coo_r)
        coo_c = np.concatenate(coo_c)
        coo_v = np.concatenate(coo_v)
        keep = (coo_r < self.shape[0]) & (coo_c < self.shape[1])
        return csr_array(
            (coo_v[keep], (coo_r[keep], coo_c[keep])), shape=self.shape
        )

    def _dia_to_csr_host(self):
        """DIA-only matrix back to a host csr_array (test/inspection).

        Faithful: exact bands carry every in-range slot explicitly,
        masked bands use the stored explicit-entry mask — so explicit
        zeros and holes round-trip correctly."""
        from ..csr import csr_array

        rows, cols = self.shape
        R, nd, rps = self.dia_data.shape
        ddata = np.asarray(self.dia_data)
        dmask = (np.asarray(self.dia_mask)
                 if self.dia_mask is not None else None)
        r_pad = np.arange(R * rps, dtype=np.int64)
        coo_r, coo_c, coo_v = [], [], []
        for d, o in enumerate(self.dia_offsets):
            col = r_pad + o
            valid = (col >= 0) & (col < cols) & (r_pad < rows)
            if dmask is not None:
                valid &= dmask[:, d, :].reshape(-1)
            coo_r.append(r_pad[valid])
            coo_c.append(col[valid])
            coo_v.append(ddata[:, d, :].reshape(-1)[valid])
        return csr_array(
            (np.concatenate(coo_v), (np.concatenate(coo_r),
                                     np.concatenate(coo_c))),
            shape=self.shape,
        )

    def toscipy(self):
        return self.to_csr().toscipy()


def attach_dia_prepack(dist: DistCSR) -> DistCSR:
    """Pre-block the Mosaic band layout on a banded DistCSR, in place.

    Built once per matrix — the shard body of the Pallas dist SpMV then
    does zero packing per call (the cached-partition analog).  Shared
    by every banded builder (``shard_csr``, ``dist_diags``, the banded
    ``dist_spgemm`` product).  No-op when already built, not banded,
    over the Mosaic budget (``supported``), or the Pallas dist route is
    off (``pallas_dist_mode() == "0"`` — the default off-TPU — so pure
    XLA runs never pay the doubled band memory).

    The int8 mask merges global row/column bounds, padding rows and
    band holes, so the ring-wrapped halo never injects non-finite
    values (same IEEE invariant as the XLA branch).
    """
    from ..ops.pallas_dia import pallas_dist_mode, supported

    if (dist.pdia_tile or dist.dia_data is None or dist.halo < 0
            or dist.dia_offsets is None or pallas_dist_mode() == "0"):
        return dist
    offsets = dist.dia_offsets
    offs2 = tuple(int(o) + dist.halo for o in offsets)
    tile = supported(offs2, dist.dtype, True)
    if tile is None:
        return dist
    R, nd, rps = dist.dia_data.shape
    n_rows = dist.shape[0]
    rps_pad = -(-rps // tile) * tile
    r_g = jnp.arange(R * rps, dtype=jnp.int32).reshape(R, 1, rps)
    offs_a = jnp.asarray(offsets, dtype=jnp.int32).reshape(1, nd, 1)
    valid = ((r_g + offs_a >= 0) & (r_g + offs_a < n_rows)
             & (r_g < n_rows))
    if dist.dia_mask is not None:
        valid = valid & (jnp.asarray(dist.dia_mask) != 0)
    pad = ((0, 0), (0, 0), (0, rps_pad - rps))
    spec = NamedSharding(dist.mesh, P(ROW_AXIS, None, None))
    dist.pdia_data = jax.device_put(
        jnp.pad(jnp.asarray(dist.dia_data), pad), spec
    )
    dist.pdia_mask = jax.device_put(
        jnp.pad(valid.astype(jnp.int8), pad), spec
    )
    dist.pdia_tile = tile
    return dist


def _precise_gather_plan(indices, indptr, starts, ends, R, cps, cols):
    """Per-shard precise image: exactly the x entries each shard reads
    (reference precise images, ``settings.py:23-33``), as an all_to_all
    send plan + a rebase map global col -> compact buffer position.

    A shard's *own* x block never rides the collective — the compact
    buffer is ``concat(recv.flat (R*C), x_local (cps))``, so C is the
    max count over *off-shard* pairs only (for a banded matrix with one
    long-range row, C stays O(1) instead of O(rps)).

    Returns (gather_idx (R_src, R_dst, C), gather_globals (R_dst, R_src,
    C), rebase: (shard, global cols) -> compact positions).
    """
    needed = []     # needed[s][t] = sorted unique cols shard s reads from t
    C = 1
    for s in range(R):
        win = np.unique(indices[indptr[starts[s]] : indptr[ends[s]]])
        per_t = []
        for t in range(R):
            sub = win[(win >= t * cps) & (win < (t + 1) * cps)]
            per_t.append(sub)
            if t != s:
                C = max(C, sub.shape[0])
        needed.append(per_t)
    gather_idx = np.zeros((R, R, C), dtype=np.int32)
    for s in range(R):
        for t in range(R):
            if t == s:
                continue
            sub = needed[s][t]
            gather_idx[t, s, : sub.shape[0]] = sub - t * cps
    gather_globals = (
        np.transpose(gather_idx, (1, 0, 2)).astype(np.int64)
        + (np.arange(R, dtype=np.int64) * cps)[None, :, None]
    )

    def rebase(s, cols_global):
        flat = cols_global.reshape(-1)
        t_of = np.clip(flat // cps, 0, R - 1)
        res = np.empty(flat.shape[0], dtype=np.int64)
        for t in range(R):
            m = t_of == t
            if not m.any():
                continue
            if t == s:     # own block: appended local region
                res[m] = R * C + (flat[m] - s * cps)
            else:
                res[m] = t * C + np.searchsorted(needed[s][t], flat[m])
        return np.clip(res.reshape(cols_global.shape), 0, R * C + cps - 1)

    return gather_idx, gather_globals, rebase


def _dia_shard_blocks(offs, dia_global, R, rps, rows, cols, dtype):
    """Per-shard DIA blocks: block[s, d, r] = A[start_s+r, start_s+r+o_d]
    (0 where out of range / padding rows)."""
    rows_p = R * rps
    nd = offs.shape[0]
    out = np.zeros((R, nd, rps), dtype=dtype)
    r_pad = np.arange(rows_p, dtype=np.int64)
    for d, o in enumerate(offs.tolist()):
        src = r_pad + o
        valid = (src >= 0) & (src < cols) & (r_pad < rows)
        tmp = np.zeros(rows_p, dtype=dtype)
        tmp[valid] = dia_global[d, src[valid]]
        out[:, d, :] = tmp.reshape(R, rps)
    return out


def _device_put_sharded(arr, sharding):
    """``jax.device_put`` onto a (possibly process-spanning) sharding.

    In multi-controller runs, plain ``device_put`` of a host array
    onto a NamedSharding that spans non-addressable devices performs a
    cross-host equality check that the installed jax cannot run on the
    CPU backend ("Multiprocess computations aren't implemented");
    ``make_array_from_callback`` sidesteps it and materializes only
    each process's addressable shards — which is also the right memory
    behavior at scale.  Single-process behavior is unchanged."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def _grid_of(mesh: Optional[Mesh], layout: str) -> Tuple[int, int]:
    """Resolve the (Rr, Rc) grid a 2-d-family layout would use on
    ``mesh`` (or on all devices when None): "1d-col" is the (1, N)
    degenerate grid, "2d-block" the mesh's own 2-D shape or the
    near-square factorization."""
    n = int(np.prod(mesh.devices.shape)) if mesh is not None \
        else len(jax.devices())
    if layout == LAYOUT_1D_COL:
        return (1, n)
    if (mesh is not None and len(mesh.devices.shape) == 2
            and int(mesh.shape[ROW_AXIS]) > 1):
        return (int(mesh.shape[ROW_AXIS]), int(mesh.shape[COL_AXIS]))
    return factor_grid(n)


def _grid_mesh_for(mesh: Optional[Mesh], grid: Tuple[int, int]) -> Mesh:
    """A (rows, cols) mesh of shape ``grid`` over ``mesh``'s devices
    (all devices when None), reusing ``mesh`` itself when it already
    has that shape."""
    if mesh is not None:
        if (tuple(mesh.axis_names) == (ROW_AXIS, COL_AXIS)
                and tuple(mesh.devices.shape) == tuple(grid)):
            return mesh
        return make_grid_mesh(list(mesh.devices.flat), shape=grid)
    return make_grid_mesh(shape=grid)


def _predict_1d_spmv_bytes(rows: int, cols: int, indptr, indices,
                           R: int, itemsize: int) -> int:
    """Predicted per-call x-realization bytes of the 1d-row SpMV at
    shard count ``R`` — the same halo-vs-all_gather analysis
    ``shard_csr`` performs, priced by ``obs.comm`` (precise images are
    ignored: auto routing compares the default realizations)."""
    from ..obs import comm as _comm

    rps = math.ceil(rows / R) if rows else 1
    if rows == cols and rows:
        starts = np.minimum(np.arange(R) * rps, rows)
        ends = np.minimum(starts + rps, rows)
        lo, hi = indptr[starts], indptr[ends]
        h = 0
        for s in range(R):
            if hi[s] > lo[s]:
                win = indices[lo[s]:hi[s]]
                h = max(h, int(max(starts[s] - win.min(),
                                   win.max() + 1 - ends[s], 0)))
        if h <= rps:
            return _comm.halo_exchange_bytes(h, itemsize, R)
    return _comm.all_gather_bytes(rps, itemsize, R)


def _route_layout(A: csr_array, mesh: Optional[Mesh]) -> str:
    """Evidence-based "auto" routing: pick 2d-block only when its
    predicted per-SpMV interconnect bytes strictly beat the 1d-row
    prediction at EQUAL device count, and record the decision (with
    both predictions) as a ``shard_csr.routing`` obs event — the
    layout analog of the SpGEMM window-vs-all_gather probe."""
    from ..obs import comm as _comm

    rows, cols = A.shape
    grid = _grid_of(mesh, LAYOUT_2D_BLOCK)
    Rr, Rc = grid
    N = Rr * Rc
    item = np.dtype(A.data.dtype).itemsize
    bytes_1d = _predict_1d_spmv_bytes(
        rows, cols, np.asarray(A.indptr), np.asarray(A.indices), N, item
    )
    rows_p = N * max(-(-rows // N), 1)
    cols_p = N * max(-(-cols // N), 1)
    vols_2d = _comm.spmv_volumes_2d(
        grid_rows=Rr, grid_cols=Rc, spc=cols_p // N,
        rps=rows_p // Rr, itemsize=item,
    )
    bytes_2d = _comm.total(vols_2d)
    choice = LAYOUT_2D_BLOCK if bytes_2d < bytes_1d else LAYOUT_1D_ROW
    _obs.event("shard_csr.routing", layout=choice, shards=N,
               grid=grid, rows=rows, nnz=int(A.indptr[-1]),
               predicted_1d_bytes=bytes_1d, predicted_2d_bytes=bytes_2d)
    return choice


def _shard_csr_2d(A: csr_array, mesh: Optional[Mesh],
                  layout: str) -> DistCSR:
    """2-d block partitioning: block (i, j) of the (Rr, Rc) grid holds
    rows [i*rps, (i+1)*rps) x cols [j*cps, (j+1)*cps) as padded-CSR
    with BLOCK-LOCAL column indices.  Rows/cols are padded to a
    multiple of Rr*Rc so the flat vector chunks (P((rows, cols))
    sharding, row-major grid order) divide evenly on both ends of the
    SpMV, and so the same blocks feed the SUMMA-style ``dist_spgemm``
    panels (A row panels gathered along mesh columns, B column panels
    staged along mesh rows) with no re-partitioning."""
    grid = _grid_of(mesh, layout)
    mesh = _grid_mesh_for(mesh, grid)
    Rr, Rc = grid
    N = Rr * Rc
    rows, cols = A.shape
    rows_p = N * max(-(-rows // N), 1)
    cols_p = N * max(-(-cols // N), 1)
    rps, cps = rows_p // Rr, cols_p // Rc

    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    nnz = int(indptr[-1])
    r_of = np.repeat(np.arange(rows, dtype=np.int64),
                     np.diff(indptr)) if nnz else np.zeros(0, np.int64)
    c_of = indices.astype(np.int64)

    def blocks_of(bid, row_local, col_local, n_blocks, rid_pad):
        """Pack entries into (n_blocks, nnz_max) padded-CSR arrays by
        block id (CSR traversal order stays row-sorted per block)."""
        per = np.bincount(bid, minlength=n_blocks) if nnz \
            else np.zeros(n_blocks, np.int64)
        cap = max(int(per.max()), 1) if nnz else 1
        d_b = np.zeros((n_blocks, cap), dtype=data.dtype)
        # Block-local column values live in [0, cps): whenever the
        # block width fits, the static index payload ships as int16 —
        # the panel gather upcasts in-register, so the narrow width is
        # pure HBM/interconnect savings (docs/DIST.md storage note).
        col_dt = (np.int16 if cps - 1 <= np.iinfo(np.int16).max
                  else np.int32)
        c_b = np.zeros((n_blocks, cap), dtype=col_dt)
        r_b = np.full((n_blocks, cap), rid_pad, dtype=np.int32)
        for g in range(n_blocks):
            m = bid == g
            ln = int(per[g])
            if ln:
                d_b[g, :ln] = data[m]
                c_b[g, :ln] = col_local[m]
                r_b[g, :ln] = row_local[m]
        return d_b, c_b, r_b, per.astype(np.int32)

    # Main blocks: grid-block id i*Rc + j.
    bi, bj = r_of // rps, c_of // cps
    d_b, c_b, r_b, cnt = blocks_of(
        bi * Rc + bj, r_of - bi * rps, c_of - bj * cps, N,
        max(rps - 1, 0),
    )
    spec3 = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS, None))
    spec2 = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def put(arr, spec):
        a = jnp.asarray(arr)
        _obs.inc("transfer.shard_upload")
        _obs.inc("transfer.shard_upload_bytes",
                 int(a.size) * a.dtype.itemsize)
        return _device_put_sharded(a, spec)

    def grid3(arr):
        return put(arr.reshape(Rr, Rc, -1), spec3)

    _obs.event("shard_csr.layout", layout=layout, halo=-1,
               precise=False, shards=N, rows=rows, nnz=nnz,
               banded=False, grid=grid)
    return DistCSR(
        data=grid3(d_b), cols=grid3(c_b), counts=put(
            cnt.reshape(Rr, Rc), spec2),
        row_ids=grid3(r_b), shape=(rows, cols), rows_per_shard=rps,
        halo=-1, ell=False, mesh=mesh, cols_per_shard=cps,
        nnz_hint=nnz, layout=layout, grid=grid,
    )


def shard_csr(A: csr_array, mesh: Optional[Mesh] = None,
              force_all_gather: bool = False,
              ell_max_expand: Optional[float] = None,
              precise: Optional[bool] = None,
              layout: Optional[str] = None) -> DistCSR:
    """Partition a csr_array over a device mesh per a layout strategy.

    ``layout`` picks the partition strategy (docs/DIST.md): "1d-row"
    (the historical default — row blocks, x realized via
    halo/all_gather/precise), "1d-col" / "2d-block" (the 2-d block
    family — x broadcast per mesh column, partial products
    reduce-scattered along mesh columns), or "auto" (route by
    predicted interconnect bytes, recorded as a ``shard_csr.routing``
    event).  Precedence is explicit: argument > the
    ``LEGATE_SPARSE_TPU_DIST_LAYOUT`` env knob > "1d-row".

    The 1d-row build is the host-side analog of Legion solving
    partition constraints once and caching them across solver
    iterations (reference §3.2 note on partition caching): it computes
    each shard's column window min/max — the FAST_IMAGE_RANGE analog
    (``fast_image_partition.cu:29-55``) — and picks halo-exchange when
    every window fits within one neighbor shard on each side.
    """
    from ..settings import settings

    _obs.inc("op.shard_csr")
    if precise and force_all_gather:
        # Both knobs name an x realization and they contradict: honor
        # neither silently (satellite of the argument>env precedence
        # contract — see tests/test_dist_layout.py).
        raise ValueError(
            "shard_csr: precise=True conflicts with "
            "force_all_gather=True — the two request different x "
            "realizations; pass at most one"
        )
    lay = resolve_layout(layout)
    if lay == LAYOUT_AUTO:
        lay = _route_layout(A, mesh)
    if lay in (LAYOUT_2D_BLOCK, LAYOUT_1D_COL):
        if precise:
            raise ValueError(
                f"shard_csr: precise images are a 1d-row realization; "
                f"not supported with layout={lay!r}"
            )
        dist = _shard_csr_2d(A, mesh, lay)
        dist._src_csr = A
        return dist
    if ell_max_expand is None:
        ell_max_expand = settings.ell_max_expand
    if precise is None:
        # Env default; an explicit force_all_gather argument wins over it
        # (explicit precise=True is a conflict, rejected above).
        precise = settings.precise_images and not force_all_gather
    if mesh is None:
        mesh = make_row_mesh()
    # Row-shard count: the size of the "rows" axis only (a 2-D
    # grid mesh replicates the matrix along "cols").
    R = int(mesh.shape[ROW_AXIS])
    rows, cols = A.shape
    rps = math.ceil(rows / R) if rows else 1
    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    counts = np.diff(indptr)
    nnz = int(indptr[-1])

    starts = np.minimum(np.arange(R) * rps, rows)
    ends = np.minimum(starts + rps, rows)

    # Column windows per shard (FAST_IMAGE_RANGE analog).
    col_min = np.zeros(R, dtype=np.int64)
    col_max = np.zeros(R, dtype=np.int64)
    lo = indptr[starts]
    hi = indptr[ends]
    for s in range(R):
        if hi[s] > lo[s]:
            win = indices[lo[s] : hi[s]]
            col_min[s] = win.min()
            col_max[s] = win.max()
        else:
            col_min[s] = min(starts[s], max(cols - 1, 0))
            col_max[s] = col_min[s]

    # Precise images replace the min/max window realization outright.
    gather_idx = gather_globals = rebase_precise = None
    cps = math.ceil(cols / R) if cols else 1   # x column-block size
    if precise:
        gather_idx, gather_globals, rebase_precise = _precise_gather_plan(
            indices, indptr, starts, ends, R, cps, cols
        )

    # Halo width: how far each shard's window reaches outside its own
    # row block (square matrices only — halo mode needs x and rows to be
    # conformally sharded).
    halo = -1
    if rows == cols and not force_all_gather and not precise:
        left_reach = np.maximum(starts - col_min, 0)
        right_reach = np.maximum(col_max + 1 - ends, 0)
        h = int(max(left_reach.max(), right_reach.max()))
        if h <= rps:
            halo = h
        else:
            # The global max-window is blown (e.g. one long-range row —
            # the reference's per-shard images keep every *other* shard
            # narrow, ``csr.py:587-591``).  Try the precise plan and keep
            # it if its buffer beats a full all_gather realization.
            gi, gg, rb = _precise_gather_plan(
                indices, indptr, starts, ends, R, cps, cols
            )
            if R * gi.shape[-1] + cps < R * rps:
                precise = True
                gather_idx, gather_globals, rebase_precise = gi, gg, rb

    # Banded fast path: banded matrices in halo mode also carry
    # per-shard DIA blocks so dist_spmv runs gather-free shifted-adds.
    # Detection, budgets and the exact/masked split all live in
    # ``csr_array._get_dia`` (single source of truth; this also warms
    # A's own single-chip cache).
    dia_offs = dia_blocks = dia_mask_blocks = None
    if halo >= 0:
        dia_cache = A._get_dia()
        if dia_cache is not None:
            dia_dev, offs_t, mask_dev = dia_cache
            offs_b = np.asarray(offs_t, dtype=np.int64)
            mo = int(max(offs_b.max(initial=0), -offs_b.min(initial=0)))
            if mo <= rps:
                halo = max(halo, mo)
                dia_offs = offs_t
                dia_blocks = _dia_shard_blocks(
                    offs_b, np.asarray(dia_dev), R, rps, rows, cols,
                    data.dtype,
                )
                if mask_dev is not None:
                    dia_mask_blocks = _dia_shard_blocks(
                        offs_b, np.asarray(mask_dev), R, rps, rows,
                        cols, bool,
                    )

    from ..ops.spmv import ell_pack, ell_within_budget

    rows_p = R * rps
    W = max(int(counts.max()), 1) if rows and nnz else 1
    # Budget uses the *padded* row count — what actually gets allocated.
    use_ell = ell_within_budget(rows_p, W, nnz, ell_max_expand)

    spec = NamedSharding(mesh, P(ROW_AXIS))

    def put(arr):
        a = jnp.asarray(arr)
        _obs.inc("transfer.shard_upload")
        _obs.inc("transfer.shard_upload_bytes",
                 int(a.size) * a.dtype.itemsize)
        return _device_put_sharded(a, spec)

    if use_ell:
        # Shared (rows, W) ELL pack, padded to R*rps rows, then reshaped
        # to (R, rps, W) row blocks.
        ell_data, ell_cols, ell_counts = ell_pack(
            data, indices, indptr, rows, W, xp=np
        )
        if rows_p > rows:
            pad = rows_p - rows
            ell_data = np.concatenate(
                [ell_data, np.zeros((pad, W), dtype=ell_data.dtype)]
            )
            ell_cols = np.concatenate(
                [ell_cols, np.zeros((pad, W), dtype=ell_cols.dtype)]
            )
            ell_counts = np.concatenate(
                [ell_counts, np.zeros((pad,), dtype=ell_counts.dtype)]
            )
        ell_cols = ell_cols.reshape(R, rps, W)
        ell_data = ell_data.reshape(R, rps, W)
        ell_counts = ell_counts.reshape(R, rps)
        if precise:
            ell_cols = np.stack(
                [rebase_precise(s, ell_cols[s]) for s in range(R)]
            ).astype(np.int32)
        elif halo >= 0:
            # Rebase to the halo-extended window: local = global-(start-h).
            reb = ell_cols - (starts - halo)[:, None, None]
            ell_cols = np.clip(reb, 0, rps + 2 * halo - 1).astype(
                indices.dtype
            )
        _obs.event("shard_csr.layout", layout="ell", halo=halo,
                   precise=bool(precise), shards=R, rows=rows, nnz=nnz,
                   banded=dia_offs is not None)
        dist = attach_dia_prepack(DistCSR(
            data=put(ell_data), cols=put(ell_cols), counts=put(ell_counts),
            row_ids=None, shape=(rows, cols), rows_per_shard=rps,
            halo=halo, ell=True, mesh=mesh,
            gather_idx=(put(gather_idx) if precise else None),
            gather_globals=(put(gather_globals) if precise else None),
            cols_per_shard=cps,
            dia_data=(put(dia_blocks) if dia_blocks is not None else None),
            dia_offsets=dia_offs,
            dia_mask=(put(dia_mask_blocks)
                      if dia_mask_blocks is not None else None),
            nnz_hint=nnz,
        ))
        # Retain the host source for parallel/reshard.py's repartition
        # path (recovery ladder: survivor-mesh re-shard after a device
        # loss) — a host reference, not a device copy.
        dist._src_csr = A
        return dist

    # Padded-CSR fallback: (R, nnz_max) + static row ids.
    local_nnz = hi - lo
    nnz_max = max(int(local_nnz.max()), 1) if nnz else 1
    data_b = np.zeros((R, nnz_max), dtype=data.dtype)
    idx_b = np.zeros((R, nnz_max), dtype=indices.dtype)
    rid_b = np.zeros((R, nnz_max), dtype=np.int32)
    for s in range(R):
        ln = int(local_nnz[s])
        data_b[s, :ln] = data[lo[s] : hi[s]]
        idx_b[s, :ln] = indices[lo[s] : hi[s]]
        local_counts = counts[starts[s] : ends[s]]
        rid = np.repeat(
            np.arange(ends[s] - starts[s], dtype=np.int32), local_counts
        )
        rid_b[s, :ln] = rid
        rid_b[s, ln:] = max(rps - 1, 0)  # padding -> last row, value 0
    if precise:
        idx_b = np.stack(
            [rebase_precise(s, idx_b[s]) for s in range(R)]
        ).astype(np.int32)
    elif halo >= 0:
        reb = idx_b - (starts - halo)[:, None]
        idx_b = np.clip(reb, 0, rps + 2 * halo - 1).astype(indices.dtype)
    _obs.event("shard_csr.layout", layout="padded-csr", halo=halo,
               precise=bool(precise), shards=R, rows=rows, nnz=nnz,
               banded=dia_offs is not None)
    dist = attach_dia_prepack(DistCSR(
        data=put(data_b), cols=put(idx_b),
        counts=put(local_nnz.astype(np.int32)), row_ids=put(rid_b),
        shape=(rows, cols), rows_per_shard=rps, halo=halo, ell=False,
        mesh=mesh,
        gather_idx=(put(gather_idx) if precise else None),
        gather_globals=(put(gather_globals) if precise else None),
        cols_per_shard=cps,
        dia_data=(put(dia_blocks) if dia_blocks is not None else None),
        dia_offsets=dia_offs,
        dia_mask=(put(dia_mask_blocks)
                  if dia_mask_blocks is not None else None),
        nnz_hint=nnz,
    ))
    dist._src_csr = A
    return dist


def shard_vector(x, mesh: Mesh, rows_padded: int,
                 layout: str = LAYOUT_1D_ROW) -> jax.Array:
    """Pad a global vector to the sharded length and lay it out per the
    matrix layout: row-block (P(rows)) for 1d-row, flat row-major grid
    chunks (P((rows, cols))) for the 2-d family."""
    x = jnp.asarray(x)
    pad = rows_padded - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), dtype=x.dtype)])
    _obs.inc("transfer.shard_upload")
    _obs.inc("transfer.shard_upload_bytes",
             int(x.size) * x.dtype.itemsize)
    spec = (P((ROW_AXIS, COL_AXIS))
            if layout in (LAYOUT_2D_BLOCK, LAYOUT_1D_COL)
            else P(ROW_AXIS))
    return _device_put_sharded(x, NamedSharding(mesh, spec))


def mesh_fingerprint(mesh: Mesh, layout: Optional[str] = None) -> str:
    """Stable identity of the physical device set behind a mesh:
    axis names/shape plus every device's (platform, id).

    The engine's plan-cache key term for distributed plans
    (``docs/ENGINE.md``): a compiled collective program is only
    reusable on the exact device topology it was lowered for, and two
    meshes over the same devices in the same order ARE the same
    topology even when the ``Mesh`` objects differ.

    ``layout`` optionally folds the partition strategy into the
    fingerprint: a 1d-row and a 2d-block partition over the SAME
    device grid lower to different collective programs, so the
    dist-plan ledger must not alias them."""
    import hashlib

    devs = tuple(
        (getattr(d, "platform", "?"), int(getattr(d, "id", -1)))
        for d in mesh.devices.flat
    )
    desc = repr((tuple(mesh.axis_names), tuple(mesh.devices.shape),
                 devs) + ((layout,) if layout is not None else ()))
    return hashlib.sha1(desc.encode()).hexdigest()[:16]


def dist_plan_fingerprint(A: DistCSR) -> str:
    """Mesh fingerprint + the layout terms the ``lru_cache``'d
    shard_map builders key on (partition strategy/grid, halo, ELL vs
    padded-CSR, precise gather, rows-per-shard, banded prepack): two
    DistCSRs with equal fingerprints reuse one compiled distributed
    program, and the engine's ``dist_spmv`` plan entries record
    exactly that reuse."""
    precise = A.gather_idx is not None
    grid = "-" if A.grid is None else f"{A.grid[0]}x{A.grid[1]}"
    return (f"{mesh_fingerprint(A.mesh, layout=A.layout)}"
            f":h{A.halo}:e{int(A.ell)}"
            f":p{int(precise)}:r{A.rows_per_shard}"
            f":d{int(A.dia_data is not None)}"
            f":t{A.pdia_tile}:g{grid}")


def _extend_x(x_local, halo: int, axis: int = 0):
    """Halo exchange: ppermute boundary slices to/from ring neighbors
    along ``axis`` of the local block.

    Structurally the ring/context-parallel neighbor pattern: each shard
    never materializes the global x — this is what makes 1e8-row weak
    scaling possible where ``all_gather`` would not (SURVEY §7 hard
    part #4).
    """
    if halo <= 0:
        return x_local
    from ._compat import axis_size as _axis_size

    axis_size = _axis_size(ROW_AXIS)
    right_perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    left_perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    n = x_local.shape[axis]
    tail = jax.lax.slice_in_dim(x_local, n - halo, n, axis=axis)
    head = jax.lax.slice_in_dim(x_local, 0, halo, axis=axis)
    from_left = jax.lax.ppermute(tail, ROW_AXIS, right_perm)
    from_right = jax.lax.ppermute(head, ROW_AXIS, left_perm)
    return jnp.concatenate([from_left, x_local, from_right], axis=axis)


@lru_cache(maxsize=256)
def _dia_spmv_fn(mesh: Mesh, offsets: Tuple[int, ...], halo: int,
                 rps: int, n_rows: int, has_mask: bool):
    """Cached shard_map callable for the banded dist SpMV (XLA
    shifted-add branch).

    Structure-keyed caching is the Legion partition-cache analog: a
    fresh closure per call would be a new jit identity, so repeated
    direct ``dist_spmv`` calls (microbenchmarks, user loops outside
    ``dist_cg``) would re-trace and recompile every time.
    """
    _obs.inc("jit_miss.dist_csr.dia_spmv_fn")
    from ._compat import shard_map

    def dia_kernel(ddata, x_local, *rest):
        x_ext = _extend_x(x_local, halo)
        dd = ddata[0]                               # (nd, rps)
        dm = rest[0][0] if has_mask else None
        shard = jax.lax.axis_index(ROW_AXIS)
        r_g = shard.astype(index_dtype()) * rps + jnp.arange(
            rps, dtype=index_dtype()
        )
        y = jnp.zeros((rps,), dtype=dd.dtype)
        for d, o in enumerate(offsets):
            seg = jax.lax.slice_in_dim(
                x_ext, halo + o, halo + o + rps
            )
            # Mask *products* outside the matrix (and band holes in
            # masked mode): ring-wrapped halo values, padding rows
            # and holes carry weight 0, but 0*inf must not inject
            # NaN (same IEEE invariant as ell_spmv).
            if has_mask:
                valid = dm[d]
            else:
                valid = jnp.logical_and(
                    jnp.logical_and(r_g + o >= 0, r_g + o < n_rows),
                    r_g < n_rows,
                )
            y = y + jnp.where(valid, dd[d] * seg,
                              jnp.zeros((), dd.dtype))
        return y

    in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS)) + (
        (P(ROW_AXIS, None, None),) if has_mask else ()
    )
    # jit wrapper: shard_map alone re-lowers per call; under jit the
    # compiled executable is cached on (this fn, shapes).
    return jax.jit(shard_map(
        dia_kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS), check_vma=False,
    ))


@lru_cache(maxsize=256)
def _dia_spmv_pallas_fn(mesh: Mesh, offsets: Tuple[int, ...], halo: int,
                        rps: int, tile: int, interpret: bool):
    """Cached shard_map callable for the banded dist SpMV through the
    per-shard Mosaic kernel over the **pre-blocked** layout
    (``DistCSR.pdia_data``/``pdia_mask``, built once at ``shard_csr``
    time — the cached-partition analog): the shard body is one halo
    ``ppermute`` plus one ``pallas_dia_spmv`` call, zero packing.

    The halo-extended window makes the local problem a rectangular band
    with offsets shifted by +halo; global bounds, ring-wrap and band
    holes are already merged into the int8 mask, so IEEE non-finite-x
    semantics match the XLA branch exactly.  The shard body runs inside
    shard_map's trace, so a Mosaic compile failure surfaces at the
    outer compile — callers gate on ``supported()`` having produced the
    prepack and on result-dtype equality.
    """
    _obs.inc("jit_miss.dist_csr.dia_spmv_pallas_fn")
    from ._compat import shard_map

    from ..ops.pallas_dia import L as _LANES
    from ..ops.pallas_dia import pallas_dia_spmv

    offs2 = tuple(int(o) + halo for o in offsets)
    nd = len(offsets)

    def dia_kernel(pdata, pmask, x_local):
        x_ext = _extend_x(x_local, halo)
        return pallas_dia_spmv(
            pdata[0].reshape(nd, -1, _LANES),
            pmask[0].reshape(nd, -1, _LANES),
            x_ext, offs2, (rps, x_ext.shape[0]), tile,
            interpret=interpret,
        )

    in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                P(ROW_AXIS))
    return jax.jit(shard_map(
        dia_kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS), check_vma=False,
    ))


@lru_cache(maxsize=256)
def _block_spmv_fn(mesh: Mesh, halo: int, precise: bool, ell: bool,
                   rps: int):
    """Cached shard_map callable for the ELL / padded-CSR dist SpMV
    (see ``_dia_spmv_fn`` for why caching matters)."""
    _obs.inc("jit_miss.dist_csr.block_spmv_fn")
    from ._compat import shard_map

    from ..ops import spmv as _spmv_ops

    def realize(x_local, gidx_local=None):
        """Per-shard x realization: precise all_to_all gather, halo
        ppermute, or tiled all_gather — the three image strategies."""
        if precise:
            parts = x_local[gidx_local]            # (R_dst, C) to send
            recv = jax.lax.all_to_all(
                parts, ROW_AXIS, split_axis=0, concat_axis=0, tiled=True
            )
            # pos = t*C + rank for off-shard cols; own block appended.
            return jnp.concatenate([recv.reshape(-1), x_local])
        if halo >= 0:
            return _extend_x(x_local, halo)
        return jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)

    if ell:
        if precise:
            def kernel(data, cols, counts, gidx, x_local):
                x_src = realize(x_local, gidx[0])
                return _spmv_ops.ell_spmv(data[0], cols[0], counts[0], x_src)

            in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                        P(ROW_AXIS, None), P(ROW_AXIS, None, None),
                        P(ROW_AXIS))
        else:
            def kernel(data, cols, counts, x_local):
                x_src = realize(x_local)
                return _spmv_ops.ell_spmv(data[0], cols[0], counts[0], x_src)

            in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                        P(ROW_AXIS, None), P(ROW_AXIS))
    else:
        if precise:
            def kernel(data, cols, row_ids, counts, gidx, x_local):
                x_src = realize(x_local, gidx[0])
                return _spmv_ops.csr_spmv_rowids_masked(
                    data[0], cols[0], row_ids[0], counts[0], x_src, rps
                )

            in_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None),
                        P(ROW_AXIS, None), P(ROW_AXIS),
                        P(ROW_AXIS, None, None), P(ROW_AXIS))
        else:
            def kernel(data, cols, row_ids, counts, x_local):
                x_src = realize(x_local)
                return _spmv_ops.csr_spmv_rowids_masked(
                    data[0], cols[0], row_ids[0], counts[0], x_src, rps
                )

            in_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None),
                        P(ROW_AXIS, None), P(ROW_AXIS), P(ROW_AXIS))
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    ))


def _transpose_perm(grid: Tuple[int, int]) -> Tuple[Tuple[int, int], ...]:
    """The chunk-transpose ppermute over the flattened (rows, cols)
    grid: device l = i*Rc + j must end up holding vector chunk
    k = j*Rr + i, so chunk k (living on device k) goes to linear
    destination (k % Rr) * Rc + k // Rr.  Identity (no collective
    emitted) when either grid axis is 1."""
    Rr, Rc = grid
    n = Rr * Rc
    return tuple((k, (k % Rr) * Rc + k // Rr) for k in range(n))


@lru_cache(maxsize=256)
def _block_spmv_2d_fn(mesh: Mesh, grid: Tuple[int, int], rps: int,
                      lowp: bool = False):
    """Cached shard_map callable for the 2-d-block dist SpMV: the
    communication-avoiding program the layout exists for —

    1. chunk-transpose ``ppermute`` over the flattened grid (input
       fixup; elided on degenerate 1-D grids),
    2. tiled ``all_gather`` along MESH ROWS only — x replicated per
       mesh column (the panel each block's columns read), never
       globally,
    3. local padded-CSR SpMV of block (i, j) against its panel,
    4. tiled ``psum_scatter`` along MESH COLUMNS — partial row-block
       products reduced and scattered straight into the row-major
       output chunks, half the bytes of a full ``psum``.

    ``lowp`` (bf16/f16 block values) swaps step 3 for the
    f32-accumulation kernel: partial products sum in f32 and narrow
    back to ``result_type(A, x)`` BEFORE the psum_scatter, so a bf16
    x panel moves half the bytes on every collective while the
    per-block reduction keeps f32 grade.  The flag is part of the
    lru_cache key — one compiled program per storage class.
    """
    _obs.inc("jit_miss.dist_csr.block_spmv_2d_fn")
    from ._compat import shard_map

    from ..ops import spmv as _spmv_ops

    Rr, Rc = grid
    perm = _transpose_perm(grid)
    skip_perm = all(s == d for s, d in perm)
    local_spmv = (_spmv_ops.csr_spmv_rowids_masked_f32acc if lowp
                  else _spmv_ops.csr_spmv_rowids_masked)

    def kernel(data, cols, row_ids, counts, x_local):
        if not skip_perm:
            x_local = jax.lax.ppermute(
                x_local, (ROW_AXIS, COL_AXIS), perm
            )
        x_panel = jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)
        y_part = local_spmv(
            data[0, 0], cols[0, 0], row_ids[0, 0], counts[0, 0],
            x_panel, rps,
        )
        return jax.lax.psum_scatter(
            y_part, COL_AXIS, scatter_dimension=0, tiled=True
        )

    in_specs = (P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS, None),
                P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS),
                P((ROW_AXIS, COL_AXIS)))
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P((ROW_AXIS, COL_AXIS)), check_vma=False,
    ))


@lru_cache(maxsize=128)
def _block_semiring_spmv_fn(mesh: Mesh, halo: int, precise: bool,
                            ell: bool, rps: int, add: str, mul: str):
    """Cached shard_map callable for the semiring dist SpMV over ELL /
    padded-CSR blocks: the ``_block_spmv_fn`` program with the local
    kernel generalized to the (add, mul) pair (graph/semiring.py).
    The x realization (precise all_to_all / halo ppermute / tiled
    all_gather) is semiring-independent — on 1-d layouts output rows
    live with the row partition, so no cross-shard output reduction
    exists and the collectives are byte-identical to plus-times."""
    _obs.inc("jit_miss.dist_csr.block_semiring_spmv_fn")
    from ._compat import shard_map

    from ..ops import spmv as _spmv_ops

    def realize(x_local, gidx_local=None):
        if precise:
            parts = x_local[gidx_local]
            recv = jax.lax.all_to_all(
                parts, ROW_AXIS, split_axis=0, concat_axis=0, tiled=True
            )
            return jnp.concatenate([recv.reshape(-1), x_local])
        if halo >= 0:
            return _extend_x(x_local, halo)
        return jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)

    if ell:
        if precise:
            def kernel(data, cols, counts, gidx, x_local):
                x_src = realize(x_local, gidx[0])
                return _spmv_ops.ell_semiring_spmv(
                    data[0], cols[0], counts[0], x_src, add, mul)

            in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                        P(ROW_AXIS, None), P(ROW_AXIS, None, None),
                        P(ROW_AXIS))
        else:
            def kernel(data, cols, counts, x_local):
                x_src = realize(x_local)
                return _spmv_ops.ell_semiring_spmv(
                    data[0], cols[0], counts[0], x_src, add, mul)

            in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                        P(ROW_AXIS, None), P(ROW_AXIS))
    else:
        if precise:
            def kernel(data, cols, row_ids, counts, gidx, x_local):
                x_src = realize(x_local, gidx[0])
                return _spmv_ops.csr_semiring_spmv_rowids_masked(
                    data[0], cols[0], row_ids[0], counts[0], x_src,
                    rps, add, mul)

            in_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None),
                        P(ROW_AXIS, None), P(ROW_AXIS),
                        P(ROW_AXIS, None, None), P(ROW_AXIS))
        else:
            def kernel(data, cols, row_ids, counts, x_local):
                x_src = realize(x_local)
                return _spmv_ops.csr_semiring_spmv_rowids_masked(
                    data[0], cols[0], row_ids[0], counts[0], x_src,
                    rps, add, mul)

            in_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None),
                        P(ROW_AXIS, None), P(ROW_AXIS), P(ROW_AXIS))
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    ))


@lru_cache(maxsize=128)
def _block_semiring_spmv_2d_fn(mesh: Mesh, grid: Tuple[int, int],
                               rps: int, add: str, mul: str):
    """Cached shard_map callable for the 2-d-block semiring dist SpMV.

    Steps 1-3 are ``_block_spmv_2d_fn`` verbatim (chunk-transpose
    ppermute, x panel all_gather along mesh rows, local semiring
    kernel).  Step 4 is where the semiring changes the wire program:
    ``psum_scatter`` only exists for sum, so the partial row blocks
    reduce with the semiring's add ALL-reduce along mesh columns
    (``jax.lax.pmin``/``pmax`` — lowered as a min/max ``all_reduce``)
    and each device then slices its own output chunk locally.  Ring
    cost is 2*(Rc-1)*rps elements per row group — twice the
    reduce-scatter half — priced under the semiring's collective kind
    (``comm.dist_spmv.pmin``/``pmax``/``por``)."""
    _obs.inc("jit_miss.dist_csr.block_semiring_spmv_2d_fn")
    from ._compat import shard_map

    from ..ops import spmv as _spmv_ops

    Rr, Rc = grid
    perm = _transpose_perm(grid)
    skip_perm = all(s == d for s, d in perm)
    reduce_op = {"min": jax.lax.pmin, "max": jax.lax.pmax}[add]
    chunk = rps // Rc

    def kernel(data, cols, row_ids, counts, x_local):
        if not skip_perm:
            x_local = jax.lax.ppermute(
                x_local, (ROW_AXIS, COL_AXIS), perm
            )
        x_panel = jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)
        y_part = _spmv_ops.csr_semiring_spmv_rowids_masked(
            data[0, 0], cols[0, 0], row_ids[0, 0], counts[0, 0],
            x_panel, rps, add, mul,
        )
        y_full = reduce_op(y_part, COL_AXIS)
        j = jax.lax.axis_index(COL_AXIS)
        return jax.lax.dynamic_slice_in_dim(y_full, j * chunk, chunk)

    in_specs = (P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS, None),
                P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS),
                P((ROW_AXIS, COL_AXIS)))
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P((ROW_AXIS, COL_AXIS)), check_vma=False,
    ))


# The distributed plan shapes this module can lower, as static
# (entry point, layout, realization) triples — enumerable WITHOUT
# devices or meshes, so the contract gates (``tools/verify`` and the
# sparselint ``plan-contract`` rule) can walk the catalog at
# import/AST time.  Every triple names one distinct lowered program
# family: the realization axis is the collective structure the
# dispatch branches on (``_dist_spmv_impl``), not a tuning knob.
# ``dist_cg``/``dist_gmres`` cover the solver iteration/cycle bodies
# over the corresponding SpMV realization ("1d-col" is the (1, R)
# degenerate grid of the 2-d panel program and adds no distinct
# solver body).  Grow this tuple when a new dispatch branch lands —
# the plan-contract rule fails until its contract is committed.
DIST_PLAN_SHAPES: Tuple[Tuple[str, str, str], ...] = (
    ("dist_spmv", "1d-row", "halo"),
    ("dist_spmv", "1d-row", "all_gather"),
    ("dist_spmv", "1d-row", "precise"),
    ("dist_spmv", "1d-col", "panel"),
    ("dist_spmv", "2d-block", "panel"),
    ("dist_spmv_semiring", "1d-row", "halo"),
    ("dist_spmv_semiring", "1d-row", "all_gather"),
    ("dist_spmv_semiring", "1d-row", "precise"),
    ("dist_spmv_semiring", "1d-col", "panel"),
    ("dist_spmv_semiring", "2d-block", "panel"),
    ("dist_spmm", "1d-row", "halo"),
    ("dist_spmm_semiring", "1d-row", "halo"),
    ("dist_cg", "1d-row", "halo"),
    ("dist_cg", "2d-block", "panel"),
    ("dist_gmres", "1d-row", "halo"),
    ("dist_reshard", "1d-row", "chunk-permute"),
)


def spmv_comm_volumes(A: DistCSR, x_local_elems: int, itemsize: int,
                      cols: int = 1):
    """Per-call collective interconnect volumes of one ``dist_spmv``
    (or ``dist_spmm`` with ``cols`` > 1) on ``A`` — the realization
    choice (2-d panel broadcast + reduce-scatter / precise all_to_all /
    halo ppermute / tiled all_gather) read from the same static fields
    the dispatch branches on, priced by ``obs.comm``.
    ``x_local_elems`` is the per-device x block size (already
    including ``cols`` for dense operands)."""
    from ..obs import comm as _comm

    if A.grid is not None:
        return _comm.spmv_volumes_2d(
            grid_rows=A.grid[0], grid_cols=A.grid[1],
            spc=x_local_elems, rps=A.rows_per_shard, itemsize=itemsize,
        )
    precise_C = (int(A.gather_idx.shape[-1])
                 if A.gather_idx is not None else None)
    return _comm.spmv_volumes(
        shards=A.num_shards, halo=A.halo, precise_C=precise_C,
        x_local_elems=x_local_elems, itemsize=itemsize, cols=cols,
    )


def cg_comm_volumes(A: DistCSR, itemsize: int, iters: int):
    """Predicted interconnect volumes of an ``iters``-iteration
    distributed CG on ``A``, mirroring the fused ``_cg_loop`` program
    exactly: ``iters + 1`` SpMV realizations (the initial residual
    plus one per iteration) and three scalar psums per iteration
    (rho, pq, and the unconditional residual-norm vdot — see
    ``obs.comm.cg_iteration_volumes``).  Returns ``(vols, calls)`` —
    bytes and collective-op counts per kind (a two-sided halo exchange
    counts as one collective phase).  Shared by the ``dist_cg`` ledger
    and ``bench.py``'s dist phase."""
    from ..obs import comm as _comm

    R = A.num_shards
    spmv = spmv_comm_volumes(A, A.rows_padded // R, itemsize)
    per_iter = _comm.cg_iteration_volumes(spmv, itemsize, R)
    vols = _comm.merge(_comm.scale(per_iter, iters), spmv)
    calls = {k: iters + 1 for k in spmv}
    # Additive, not an overwrite: the 2-d-block SpMV realization already
    # carries a "psum" entry (its psum_scatter output reduction) that
    # the scalar-reduction count must stack on top of.
    calls["psum"] = calls.get("psum", 0) + 3 * iters
    return vols, calls


def semiring_spmv_comm_volumes(A: DistCSR, x_itemsize: int,
                               y_itemsize: int, collective: str,
                               cols: int = 1):
    """Per-call collective volumes of one semiring ``dist_spmv`` (or
    ``dist_spmm`` with ``cols`` > 1) on ``A``.  1-d layouts realize x
    exactly as plus-times (no output collective exists), so the
    volumes are ``spmv_comm_volumes`` at the x itemsize; 2-d-block
    swaps the psum_scatter for the semiring add all-reduce
    (``obs.comm.spmv_volumes_2d_semiring``)."""
    from ..obs import comm as _comm

    x_local = A.rows_padded // A.num_shards
    if A.grid is not None:
        return _comm.spmv_volumes_2d_semiring(
            grid_rows=A.grid[0], grid_cols=A.grid[1],
            spc=x_local, rps=A.rows_per_shard,
            x_itemsize=x_itemsize, y_itemsize=y_itemsize,
            collective=collective,
        )
    precise_C = (int(A.gather_idx.shape[-1])
                 if A.gather_idx is not None else None)
    return _comm.spmv_volumes(
        shards=A.num_shards, halo=A.halo, precise_C=precise_C,
        x_local_elems=x_local * max(cols, 1), itemsize=x_itemsize,
        cols=max(cols, 1),
    )


def _dist_spmv_semiring(A: DistCSR, x: jax.Array, sr) -> jax.Array:
    """Semiring arm of ``dist_spmv`` (``sr`` a resolved non-plus-times
    :class:`~..graph.semiring.Semiring`): same accounting discipline
    as ``_dist_spmv_impl`` — comm volumes priced from static fields
    before dispatch, span with realization path — plus the ``graph.*``
    ledger row for the semiring family.  Structure-specialized
    plus-times paths (DIA/BSR) don't generalize, so dispatch goes
    straight to the ELL / padded-CSR block programs."""
    _obs.inc("op.dist_spmv")
    _obs.inc("graph.dist_spmv." + sr.name)
    from ..obs import comm as _comm

    x_item = jnp.dtype(x.dtype).itemsize
    y_item = (1 if sr.mul == "and"
              else jnp.dtype(jnp.result_type(A.dtype, x.dtype)).itemsize)
    vols = semiring_spmv_comm_volumes(A, x_item, y_item, sr.collective)
    comm_bytes = _comm.record("dist_spmv", vols, layout=A.layout)
    with _tctx.profiler_scope("dist_spmv"), \
            _lat.timer("lat.dist_spmv."
                       + _lat.shape_bucket(A.shape[0])), \
            _obs.span("dist_spmv", shards=A.num_shards, halo=A.halo,
                      comm_bytes=comm_bytes,
                      comm_calls=sum(1 for b in vols.values() if b > 0)
                      ) as sp:
        if A.grid is not None:
            fn = _block_semiring_spmv_2d_fn(
                A.mesh, A.grid, A.rows_per_shard, sr.add, sr.mul)
            if sp is not None:
                sp.set(path="2d-block", layout=A.layout,
                       semiring=sr.name)
            return fn(A.data, A.cols, A.row_ids, A.counts, x)
        A._require_blocks("dist_spmv")
        precise = A.gather_idx is not None
        fn = _block_semiring_spmv_fn(
            A.mesh, A.halo, precise, A.ell, A.rows_per_shard,
            sr.add, sr.mul)
        if A.ell:
            args = (A.data, A.cols, A.counts) + (
                (A.gather_idx,) if precise else ()
            ) + (x,)
        else:
            args = (A.data, A.cols, A.row_ids, A.counts) + (
                (A.gather_idx,) if precise else ()
            ) + (x,)
        if sp is not None:
            sp.set(path="ell" if A.ell else "padded-csr",
                   precise=precise, semiring=sr.name)
        return fn(*args)


def _resolve_semiring_arg(semiring):
    """None for plus-times/absent (the standard program IS that
    semiring), else the resolved catalog entry."""
    if semiring is None:
        return None
    from ..graph.semiring import resolve as _resolve_sr

    sr = _resolve_sr(semiring)
    if sr.add == "sum" and sr.mul == "times":
        return None
    return sr


def dist_spmv(A: DistCSR, x: jax.Array, semiring=None) -> jax.Array:
    """y = A (x) with row-block parallelism (jittable).

    ``semiring`` generalizes the product to any catalog entry
    (``graph/semiring.py``): ``None``/"plus-times" runs the standard
    y = A @ x program below; other semirings dispatch the generalized
    block kernels, with the 2-d-block cross-shard reduction swapped
    for the semiring's add collective (psum -> pmin/pmax/por) — see
    docs/GRAPH.md.

    ``x`` and the result are row-block sharded vectors of length
    ``A.rows_padded``.  The distribution contract matches the reference
    SpMV task (``csr.py:562-593``): y aligned with the row partition,
    x gathered per the column image (halo ppermute or all_gather).
    The underlying shard_map computations are structure-cached, so
    repeated calls on the same matrix structure reuse one compilation.

    Resilience (``LEGATE_SPARSE_TPU_RESIL``, docs/RESILIENCE.md):
    eager dispatches run under the ``dist.spmv`` site policy —
    injectable, and transient collective failures retried with
    backoff.  Calls staged inside an ambient trace (solver loops via
    ``matvec_fn``) bypass the wrapper: a retry there would re-stage
    the traced program, and the driver-level sites (``dist.cg``,
    ``solver.*.conv``) own recovery for those.
    """
    sr = _resolve_semiring_arg(semiring)
    if sr is not None:
        # ABFT's checksum identity sum(y) = <w, x> is plus-times
        # algebra; semiring dispatches retry under the same site
        # policy but run unverified.
        if _rsettings.resil and csr_array._can_build_cache(x):
            return _resil_guarded(
                "dist.spmv", lambda: _dist_spmv_semiring(A, x, sr))
        return _dist_spmv_semiring(A, x, sr)
    if _rsettings.resil and csr_array._can_build_cache(x):
        if _rsettings.resil_abft:
            return _resil_guarded("dist.spmv",
                                  lambda: _dist_spmv_abft(A, x))
        return _resil_guarded("dist.spmv",
                              lambda: _dist_spmv_impl(A, x))
    return _dist_spmv_impl(A, x)


def _abft_checksum_vector(A: DistCSR, xlen: int):
    """The sharded column-checksum vector w (w_j = sum_i A_ij) an
    ABFT-verified SpMV dots against x, built once per matrix from the
    retained host source and cached on ``A``.  None when the matrix
    cannot carry one (no retained source, or non-square — the padded
    x and y lengths then differ and the identity sum(y) = <w, x> has
    no shared sharding)."""
    cached = getattr(A, "_abft_w", None)
    if cached is not None and cached[0] == xlen:
        return cached[1]
    src = getattr(A, "_src_csr", None)
    rows, cols = A.shape
    if src is None or rows != cols:
        return None
    wv = np.zeros(cols, dtype=np.float64)
    np.add.at(wv, np.asarray(src.indices),
              np.asarray(src.data, dtype=np.float64))
    w = shard_vector(jnp.asarray(wv, dtype=A.dtype), A.mesh, xlen,
                     layout=A.layout)
    A._abft_w = (xlen, w)
    return w


def _dist_spmv_abft(A: DistCSR, x: jax.Array) -> jax.Array:
    """Opt-in ABFT-checksummed eager SpMV (``settings.resil_abft``):
    carry the column checksum w through the dispatch and verify
    sum(y) = <w, x> at the fetch.  The comparison tolerance scales
    with <|w|, |x|> (the condition of the checksum sum), and the
    NaN-safe ``not (diff <= tol)`` form turns a poisoned y into a
    detection rather than a silent pass.  A mismatch raises the
    retryable :class:`~..resilience.outcomes.ChecksumError` — the
    ``dist.spmv`` policy site re-dispatches from the intact operands,
    turning a corrupted collective into a typed, counted retry.
    Matrices without a checksum vector run unverified (documented in
    docs/RESILIENCE.md; traced solver loops are covered by the
    conv-fetch health monitors instead)."""
    w = _abft_checksum_vector(A, int(x.shape[0]))
    y = _dist_spmv_impl(A, x)
    if w is None:
        return y
    # Value-carrying drill site: a nonfinite arm poisons y exactly as
    # a corrupted collective would.
    y = _rfaults.fault_point("dist.spmv.abft", y)
    stats = jnp.stack([jnp.sum(y), jnp.vdot(w, x),
                       jnp.vdot(jnp.abs(w), jnp.abs(x))])
    observed, expected, scale = (float(v) for v in np.asarray(stats))
    eps = float(jnp.finfo(jnp.result_type(A.dtype, x.dtype)).eps)
    tol = 64.0 * eps * (abs(scale) + 1.0)
    _obs.inc("resil.abft.checks")
    if not (abs(observed - expected) <= tol):
        _obs.inc("resil.abft.mismatch")
        _obs.event("resil.abft.mismatch", observed=observed,
                   expected=expected, tol=tol)
        raise ChecksumError("dist.spmv.abft", observed, expected)
    return y


def _dist_spmv_impl(A: DistCSR, x: jax.Array) -> jax.Array:
    halo = A.halo
    precise = A.gather_idx is not None
    _obs.inc("op.dist_spmv")
    # Engine plan ledger (docs/ENGINE.md): with routing enabled, every
    # production dist dispatch records against its plan identity (mesh
    # fingerprint + layout + dtype + epoch) — the reuse evidence for
    # the lru_cache'd shard_map programs below.  Disabled (default),
    # this is one flag read.
    if _engine_enabled():
        _get_engine().record_dist_plan(A)
    # Comm ledger: the realization (and so the collective volume) is a
    # function of A's static fields alone — price it once per dispatch
    # and account it whatever kernel branch runs below.
    from ..obs import comm as _comm

    vols = spmv_comm_volumes(
        A, int(x.shape[0]) // A.num_shards,
        jnp.dtype(x.dtype).itemsize,
    )
    comm_bytes = _comm.record("dist_spmv", vols, layout=A.layout)

    # Obs v4: a request-scoped dispatch (the trace context set by the
    # gateway/executor) auto-tags this span with its trace id AND
    # annotates the jax.profiler timeline (dist_spmv[<trace-id>]), so
    # a future on-TPU profiler capture joins obs flow arcs to XLA
    # rows.  Without a context both are no-ops.
    with _tctx.profiler_scope("dist_spmv"), \
            _lat.timer("lat.dist_spmv."
                       + _lat.shape_bucket(A.shape[0])), \
            _obs.span("dist_spmv", shards=A.num_shards, halo=halo,
                      comm_bytes=comm_bytes,
                      comm_calls=sum(1 for b in vols.values() if b > 0)
                      ) as sp:
        if A.grid is not None:
            lowp = str(A.dtype) in ("bfloat16", "float16")
            fn = _block_spmv_2d_fn(A.mesh, A.grid, A.rows_per_shard,
                                   lowp)
            if sp is not None:
                sp.set(path="2d-block-bf16" if lowp else "2d-block",
                       layout=A.layout)
            return fn(A.data, A.cols, A.row_ids, A.counts, x)

        if A.dia_data is not None and halo >= 0 and not precise:
            # Banded fast path: halo exchange + static shifted-adds,
            # zero gathers (per-shard analog of ``ops.dia_ops.dia_spmv``).
            from ..ops.pallas_dia import pallas_dist_mode

            mode = pallas_dist_mode()
            if (mode != "0" and A.pdia_tile
                    and jnp.result_type(A.dtype, x.dtype) == A.dtype):
                # Mosaic route over the pre-blocked layout (default on
                # TPU).  The dtype gate keeps promotion semantics (e.g.
                # bf16 matrix * f32 x -> f32) identical to the XLA
                # branch.
                fn = _dia_spmv_pallas_fn(
                    A.mesh, A.dia_offsets, halo, A.rows_per_shard,
                    A.pdia_tile, mode == "interpret",
                )
                if sp is not None:
                    sp.set(path="dia-pallas")
                return fn(A.pdia_data, A.pdia_mask, x)
            has_mask = A.dia_mask is not None
            fn = _dia_spmv_fn(
                A.mesh, A.dia_offsets, halo, A.rows_per_shard,
                A.shape[0], has_mask,
            )
            args = (A.dia_data, x) + ((A.dia_mask,) if has_mask else ())
            if sp is not None:
                sp.set(path="dia-xla")
            return fn(*args)

        A._require_blocks("dist_spmv")
        if not A.bsr_tried and A.bsr_blocks is None:
            # Lazy build on first SpMV (mirrors csr_array._get_bsr):
            # other consumers (dist_spmm/dist_spgemm) never pay the
            # densification.
            attach_bsr_prepack(A)
        if (A.bsr_blocks is not None
                and jnp.result_type(A.dtype, x.dtype) == A.dtype):
            from ..ops.pallas_dia import pallas_dist_mode

            mode = pallas_dist_mode()
            if mode != "0":
                nbr, nbc = A.bsr_grid
                fn = _bsr_spmv_dist_fn(
                    A.mesh, A.rows_per_shard, nbr, nbc,
                    mode == "interpret",
                )
                if sp is not None:
                    sp.set(path="bsr")
                return fn(A.bsr_blocks, A.bsr_brow, A.bsr_bcol, x)
        fn = _block_spmv_fn(A.mesh, halo, precise, A.ell,
                            A.rows_per_shard)
        if A.ell:
            args = (A.data, A.cols, A.counts) + (
                (A.gather_idx,) if precise else ()
            ) + (x,)
        else:
            args = (A.data, A.cols, A.row_ids, A.counts) + (
                (A.gather_idx,) if precise else ()
            ) + (x,)
        if sp is not None:
            sp.set(path="ell" if A.ell else "padded-csr",
                   precise=precise)
        return fn(*args)


def shard_dense(X, mesh: Mesh, rows_padded: int) -> jax.Array:
    """Pad and shard a dense (rows, k) operand: rows over the "rows"
    axis; columns over the "cols" axis too when ``mesh`` is a 2-D grid
    (k padded to a multiple of the grid's column count)."""
    X = jnp.asarray(X)
    pad_r = rows_padded - X.shape[0]
    if pad_r:
        X = jnp.concatenate(
            [X, jnp.zeros((pad_r, X.shape[1]), X.dtype)]
        )
    if COL_AXIS in mesh.shape:
        C = int(mesh.shape[COL_AXIS])
        pad_c = (-X.shape[1]) % C
        if pad_c:
            X = jnp.concatenate(
                [X, jnp.zeros((X.shape[0], pad_c), X.dtype)], axis=1
            )
        return _device_put_sharded(
            X, NamedSharding(mesh, P(ROW_AXIS, COL_AXIS)))
    return _device_put_sharded(X, NamedSharding(mesh, P(ROW_AXIS, None)))


@lru_cache(maxsize=128)
def _block_spmm_fn(mesh: Mesh, halo: int, precise: bool, ell: bool,
                   rps: int, col_sharded: bool):
    """Cached shard_map callable for distributed SpMM (Y = A @ X).

    The 2-D-grid answer to the reference's projection functors
    (``projections.cc:23-64``): X's rows follow A's row partition (the
    same halo / all_gather / precise realizations as ``dist_spmv``, one
    axis up), while X's *columns* shard over the grid's "cols" axis —
    independent columns, so the column axis adds zero communication.
    """
    _obs.inc("jit_miss.dist_csr.block_spmm_fn")
    from ._compat import shard_map

    from ..ops import spmv as _spmv_ops

    xcol = COL_AXIS if col_sharded else None

    def realize(x_local, gidx_local=None):
        if precise:
            parts = x_local[gidx_local]          # (R_dst, C, k_loc)
            recv = jax.lax.all_to_all(
                parts, ROW_AXIS, split_axis=0, concat_axis=0, tiled=True
            )
            return jnp.concatenate(
                [recv.reshape(-1, x_local.shape[1]), x_local]
            )
        if halo >= 0:
            return _extend_x(x_local, halo)      # axis 0
        return jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)

    if ell:
        def kernel(data, cols, counts, *rest):
            gidx = rest[0][0] if precise else None
            X_local = rest[-1]
            X_src = realize(X_local, gidx)
            return _spmv_ops.ell_spmm(data[0], cols[0], counts[0], X_src)

        in_specs = (
            P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
            P(ROW_AXIS, None),
        ) + ((P(ROW_AXIS, None, None),) if precise else ()) + (
            P(ROW_AXIS, xcol),
        )
    else:
        def kernel(data, cols, row_ids, counts, *rest):
            gidx = rest[0][0] if precise else None
            X_local = rest[-1]
            X_src = realize(X_local, gidx)
            d, c, rid, cnt = data[0], cols[0], row_ids[0], counts[0]
            slot = jnp.arange(d.shape[0], dtype=jnp.int32)
            prod = jnp.where(
                (slot < cnt)[:, None], d[:, None] * X_src[c, :],
                jnp.zeros((1, 1), d.dtype),
            )
            return jax.ops.segment_sum(
                prod, rid, num_segments=rps, indices_are_sorted=True
            )

        in_specs = (
            P(ROW_AXIS, None), P(ROW_AXIS, None), P(ROW_AXIS, None),
            P(ROW_AXIS),
        ) + ((P(ROW_AXIS, None, None),) if precise else ()) + (
            P(ROW_AXIS, xcol),
        )
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS, xcol), check_vma=False,
    ))


@lru_cache(maxsize=128)
def _block_semiring_spmm_fn(mesh: Mesh, halo: int, precise: bool,
                            ell: bool, rps: int, col_sharded: bool,
                            add: str, mul: str):
    """Cached shard_map callable for distributed semiring SpMM — the
    batched multi-source frontier program (k stacked sources ride one
    dispatch, the distributed arm of the PR-8 ``multi_matvec``
    packing).  Structure is ``_block_spmm_fn`` with the local kernel
    generalized; x realization collectives are semiring-independent
    (1-d layouts only, like ``dist_spmm`` itself)."""
    _obs.inc("jit_miss.dist_csr.block_semiring_spmm_fn")
    from ._compat import shard_map

    from ..ops import spmv as _spmv_ops

    xcol = COL_AXIS if col_sharded else None

    def realize(x_local, gidx_local=None):
        if precise:
            parts = x_local[gidx_local]
            recv = jax.lax.all_to_all(
                parts, ROW_AXIS, split_axis=0, concat_axis=0, tiled=True
            )
            return jnp.concatenate(
                [recv.reshape(-1, x_local.shape[1]), x_local]
            )
        if halo >= 0:
            return _extend_x(x_local, halo)
        return jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)

    if ell:
        def kernel(data, cols, counts, *rest):
            gidx = rest[0][0] if precise else None
            X_local = rest[-1]
            X_src = realize(X_local, gidx)
            return _spmv_ops.ell_semiring_spmm(
                data[0], cols[0], counts[0], X_src, add, mul)

        in_specs = (
            P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
            P(ROW_AXIS, None),
        ) + ((P(ROW_AXIS, None, None),) if precise else ()) + (
            P(ROW_AXIS, xcol),
        )
    else:
        def kernel(data, cols, row_ids, counts, *rest):
            gidx = rest[0][0] if precise else None
            X_local = rest[-1]
            X_src = realize(X_local, gidx)
            return _spmv_ops.csr_semiring_spmm_rowids_masked(
                data[0], cols[0], row_ids[0], counts[0], X_src,
                rps, add, mul)

        in_specs = (
            P(ROW_AXIS, None), P(ROW_AXIS, None), P(ROW_AXIS, None),
            P(ROW_AXIS),
        ) + ((P(ROW_AXIS, None, None),) if precise else ()) + (
            P(ROW_AXIS, xcol),
        )
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS, xcol), check_vma=False,
    ))


@lru_cache(maxsize=128)
def _dia_spmm_dist_fn(mesh: Mesh, offsets: Tuple[int, ...], halo: int,
                      rps: int, tile: int, col_sharded: bool,
                      interpret: bool):
    """Cached shard_map callable: banded distributed SpMM through the
    per-shard Mosaic band kernel over the pre-blocked layout (the SpMM
    arm of ``_dia_spmv_pallas_fn``; row shifts of a 2-D X are sublane
    rolls — cheaper than the SpMV lane decomposition)."""
    _obs.inc("jit_miss.dist_csr.dia_spmm_dist_fn")
    from ._compat import shard_map

    from ..ops.pallas_dia import L as _LANES
    from ..ops.pallas_dia import pallas_dia_spmm

    offs2 = tuple(int(o) + halo for o in offsets)
    nd = len(offsets)
    xcol = COL_AXIS if col_sharded else None

    def kernel(pdata, pmask, X_local):
        X_ext = _extend_x(X_local, halo)            # axis 0
        return pallas_dia_spmm(
            pdata[0].reshape(nd, -1, _LANES),
            pmask[0].reshape(nd, -1, _LANES),
            X_ext, offs2, (rps, X_ext.shape[0]), tile,
            interpret=interpret,
        )

    in_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                P(ROW_AXIS, xcol))
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS, xcol), check_vma=False,
    ))


def dist_spmm(A: DistCSR, X: jax.Array, semiring=None) -> jax.Array:
    """Y = A @ X for a dense (rows_padded, k) operand (jittable).

    Same distribution contract as ``dist_spmv`` lifted one axis: X and
    Y are row-block sharded over "rows"; on a 2-D grid mesh
    (``make_grid_mesh``) their columns additionally shard over "cols",
    with the sparse blocks replicated along that axis.  Use
    ``shard_dense`` to lay out X.

    ``semiring`` generalizes the product exactly as in ``dist_spmv``
    — the batched multi-source frontier path (k stacked sources per
    dispatch; docs/GRAPH.md).  1-d layouts only, like the plus-times
    program.
    """
    if A.grid is not None:
        raise NotImplementedError(
            "dist_spmm: 2-d-block layouts are SpMV/SpGEMM-only; "
            "shard with layout='1d-row' for dense operands"
        )
    A._require_blocks("dist_spmm")
    precise = A.gather_idx is not None
    col_sharded = COL_AXIS in A.mesh.shape
    _obs.inc("op.dist_spmm")
    # Comm ledger: per-device column block widens every realization
    # slice; the column axis itself adds zero communication.
    from ..obs import comm as _comm

    k_loc = int(X.shape[1]) // (int(A.mesh.shape[COL_AXIS])
                                if col_sharded else 1)
    _comm.record("dist_spmm", spmv_comm_volumes(
        A, (int(X.shape[0]) // A.num_shards) * max(k_loc, 1),
        jnp.dtype(X.dtype).itemsize, cols=max(k_loc, 1),
    ))
    sr = _resolve_semiring_arg(semiring)
    if sr is not None:
        _obs.inc("graph.dist_spmm." + sr.name)
        fn = _block_semiring_spmm_fn(
            A.mesh, A.halo, precise, A.ell, A.rows_per_shard,
            col_sharded, sr.add, sr.mul)
        if A.ell:
            args = (A.data, A.cols, A.counts) + (
                (A.gather_idx,) if precise else ()
            ) + (X,)
        else:
            args = (A.data, A.cols, A.row_ids, A.counts) + (
                (A.gather_idx,) if precise else ()
            ) + (X,)
        return fn(*args)
    if (A.pdia_tile and A.halo >= 0 and not precise
            and jnp.result_type(A.dtype, X.dtype) == A.dtype):
        from ..ops.pallas_dia import _VMEM_BUDGET, pallas_dist_mode

        mode = pallas_dist_mode()
        nd = A.pdia_data.shape[1]
        item = np.dtype(A.dtype).itemsize
        # Per-grid-step VMEM: 3 X views + Y at (tile, k) plus the band.
        vmem = A.pdia_tile * item * (3 + 1) * max(k_loc, 1) \
            + nd * A.pdia_tile * (item + 1)
        if mode != "0" and 0 < k_loc and vmem <= _VMEM_BUDGET:
            fn = _dia_spmm_dist_fn(
                A.mesh, A.dia_offsets, A.halo, A.rows_per_shard,
                A.pdia_tile, col_sharded, mode == "interpret",
            )
            return fn(A.pdia_data, A.pdia_mask, X)
    fn = _block_spmm_fn(A.mesh, A.halo, precise, A.ell,
                        A.rows_per_shard, col_sharded)
    if A.ell:
        args = (A.data, A.cols, A.counts) + (
            (A.gather_idx,) if precise else ()
        ) + (X,)
    else:
        args = (A.data, A.cols, A.row_ids, A.counts) + (
            (A.gather_idx,) if precise else ()
        ) + (X,)
    return fn(*args)


def attach_bsr_prepack(dist: DistCSR, host_ell=None) -> DistCSR:
    """Per-shard block-sparse (BSR) pack for *irregular* distributed
    matrices, in place — the distributed arm of ``ops/bsr.py``.

    Applies to the all_gather realization (irregular matrices blow the
    halo window, and cols are then global — exactly the BSR pack's
    input).  Shards pack independently; block counts are padded to the
    max with all-zero blocks (zero data contributes nothing wherever
    its brow points).  Built only when the Pallas dist route is on and
    every shard stays within the densification budget; disabled under
    CHECK_BOUNDS like the single-chip BSR path (densified zeros
    multiply x — see ``csr_array._get_bsr``).

    ``host_ell`` is the (data, cols, counts) ELL pack as host numpy
    when the caller still holds it (``shard_csr`` does) — passing it
    avoids a device->host round trip of the whole pack.
    """
    from ..ops.bsr import MAX_BLOCKS, bsr_pack
    from ..ops.bsr import B as _B
    from ..ops.pallas_dia import pallas_dist_mode
    from ..settings import settings

    if (dist.bsr_blocks is not None or dist.bsr_tried
            or dist.data is None or not dist.ell or dist.halo >= 0
            or dist.gather_idx is not None
            or pallas_dist_mode() == "0"
            or settings.bsr_max_expand <= 0
            or settings.check_bounds
            or np.dtype(dist.dtype) not in (np.dtype(np.float32),)):
        return dist
    dist.bsr_tried = True
    R = dist.num_shards
    rps = dist.rows_per_shard
    cols = dist.shape[1]
    if host_ell is not None:
        data_b, cols_b, counts_b = (np.asarray(a) for a in host_ell)
    else:
        data_b = np.asarray(dist.data)      # (R, rps, W)
        cols_b = np.asarray(dist.cols)
        counts_b = np.asarray(dist.counts)  # (R, rps)
    packs = []
    for s in range(R):
        W = data_b.shape[2]
        slot = np.arange(W)[None, :]
        valid = slot < counts_b[s][:, None]
        indptr = np.zeros(rps + 1, np.int64)
        np.cumsum(counts_b[s], out=indptr[1:])
        pack = bsr_pack(
            data_b[s][valid], cols_b[s][valid].astype(np.int64),
            indptr, (rps, cols), settings.bsr_max_expand,
        )
        if pack is None:
            return dist
        packs.append(pack)
    nb_max = max(p[0].shape[0] for p in packs)
    if nb_max > MAX_BLOCKS:
        return dist
    nbr = packs[0][3]
    nbc = packs[0][4]
    blk = np.zeros((R, nb_max, _B, _B), np.float32)
    brow = np.zeros((R, nb_max), np.int32)
    bcol = np.zeros((R, nb_max), np.int32)
    for s, (bT, br, bc, _, _) in enumerate(packs):
        nb = bT.shape[0]
        blk[s, :nb] = bT
        brow[s, :nb] = br
        bcol[s, :nb] = bc
        # Padding blocks: zero data accumulated into the last block-row
        # (harmless), sorted order preserved.
        brow[s, nb:] = br[-1] if nb else 0
    spec3 = NamedSharding(dist.mesh, P(ROW_AXIS, None, None, None))
    spec2 = NamedSharding(dist.mesh, P(ROW_AXIS, None))
    dist.bsr_blocks = jax.device_put(jnp.asarray(blk), spec3)
    dist.bsr_brow = jax.device_put(jnp.asarray(brow), spec2)
    dist.bsr_bcol = jax.device_put(jnp.asarray(bcol), spec2)
    dist.bsr_grid = (int(nbr), int(nbc))
    return dist


@lru_cache(maxsize=128)
def _bsr_spmv_dist_fn(mesh: Mesh, rps: int, nbr: int, nbc: int,
                      interpret: bool):
    """Cached shard_map callable: all_gather x, then the per-shard
    Pallas BSR kernel over the pre-packed blocks."""
    _obs.inc("jit_miss.dist_csr.bsr_spmv_dist_fn")
    from ._compat import shard_map

    from ..ops.bsr import B as _B
    from ..ops.bsr import bsr_spmv_pallas

    def kernel(blk, brow, bcol, x_local):
        x_full = jax.lax.all_gather(x_local, ROW_AXIS, tiled=True)
        pad = nbc * _B - x_full.shape[0]
        if pad > 0:
            x_full = jnp.concatenate(
                [x_full, jnp.zeros((pad,), x_full.dtype)]
            )
        x2d = x_full[: nbc * _B].reshape(nbc, _B)
        y2d = bsr_spmv_pallas(blk[0], brow[0], bcol[0], x2d, nbr, nbc,
                              interpret=interpret)
        return y2d.ravel()[:rps]

    in_specs = (P(ROW_AXIS, None, None, None), P(ROW_AXIS, None),
                P(ROW_AXIS, None), P(ROW_AXIS))
    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    ))


def _padded_operator(A: DistCSR):
    """The distributed matrix as a LinearOperator over padded sharded
    vectors — lets every single-chip solver run distributed unchanged
    (the reference gets this transparency from Legion arrays; here the
    matvec is the shard_map'd ``dist_spmv`` and all reductions inside
    the jitted solver loops lower to ``psum`` over the mesh)."""
    from ..linalg import LinearOperator

    n = A.rows_padded
    return LinearOperator(shape=(n, n), matvec=A.matvec_fn(),
                          dtype=A.dtype)


def _padded_precond(M, A: DistCSR):
    if M is None or not callable(M):
        return M
    from ..linalg import LinearOperator

    n = A.rows_padded
    return LinearOperator(shape=(n, n), matvec=M, dtype=A.dtype)


def _shard_system(A: DistCSR, b, x0, maxiter, callback):
    """Shared solver preamble: shard b/x0 to the padded length, default
    the iteration budget, and truncate callback iterates to the true
    row count."""
    rows = A.shape[0]
    b_sh = shard_vector(b, A.mesh, A.rows_padded, layout=A.layout)
    x0_sh = (shard_vector(jnp.asarray(x0, dtype=b_sh.dtype), A.mesh,
                          A.rows_padded, layout=A.layout)
             if x0 is not None else None)
    if maxiter is None:
        maxiter = rows * 10
    cb = (None if callback is None
          else (lambda xk: callback(xk[:rows])))
    return rows, b_sh, x0_sh, maxiter, cb


@contextlib.contextmanager
def _maybe_ckpt_scope(site: str):
    """Open a checkpoint scope for a distributed solve when the knob
    asks for one (``settings.resil_ckpt_iters > 0``) and no caller
    scope is already bound — the caller's scope always wins (scopes do
    not compose; see resilience/checkpoint.py)."""
    if (_rsettings.resil and _rckpt.current() is None
            and _rsettings.resil_ckpt_iters > 0):
        with _rckpt.scope(site) as ck:
            yield ck
    else:
        yield _rckpt.current()


def _solve_with_recovery(site: str, A: "DistCSR", b, b_sh, x0_sh,
                         maxiter: int, solve_fn, guard: bool = True):
    """The device-loss recovery ladder (docs/RESILIENCE.md) around a
    distributed solve: **detect** (a ``DeviceLost`` escapes the retry
    policy un-retried, surfacing at the conv-fetch cadence) ->
    **shrink** (``survivor_mesh`` drops the lost flat ordinal) ->
    **reshard** (retained-source repartition of ``A`` onto the
    survivor grid) -> **restore** (the last checkpoint's iterate —
    else the original ``x0``) -> **resume** with the remaining
    iteration budget.  Converges to the same tolerance instead of
    raising.

    ``solve_fn(A_cur, b_sh_cur, x0_sh_cur, miter) -> (x, iters)``
    runs the solve over operands sharded for ``A_cur``; the ladder
    owns re-sharding ``b`` / the restart iterate after each shrink
    (from HOST state — the old mesh's arrays may be unreadable after
    a real loss, which is why the checkpoint path snapshots to host
    buffers).  ``guard=True`` wraps each attempt as the ``site``
    fault/retry site; gmres passes False (its cycle loop already owns
    the ``solver.gmres.conv`` site).  Recoveries are bounded by the
    shard count: each loss removes one device, and a single-shard
    solve has nothing to shrink to, so the ``DeviceLost`` re-raises.

    Accounting (pinned by tests): per recovery, one each of
    ``resil.recovery.attempts`` / ``.device_loss`` / ``.mesh_shrink``,
    ``resil.recovery.restored_iters`` by the checkpoint's credited
    iterations, ``resil.recovery.reshard_bytes`` by the measured
    ``transfer.shard_upload_bytes`` delta of the repartition, and one
    ``resil.recovery`` event; ``resil.recovery.succeeded`` once when
    a recovered solve completes.  Returns ``(x, total_iters, A_fin)``
    — iterations credited from restores count toward the total, and
    the comm ledger prices the final mesh.
    """
    from .reshard import reshard

    rows = A.shape[0]
    ck = _rckpt.current()
    A_cur, b_cur, x0_cur = A, b_sh, x0_sh
    miter = int(maxiter)
    base = 0          # iterations credited from restored checkpoints
    recovered = 0
    while True:
        try:
            if guard:
                x, iters = _resil_guarded(
                    site, partial(solve_fn, A_cur, b_cur, x0_cur,
                                  miter))
            else:
                x, iters = solve_fn(A_cur, b_cur, x0_cur, miter)
            if recovered:
                _obs.inc("resil.recovery.succeeded")
            return x, base + int(iters), A_cur
        except DeviceLost as e:
            if A_cur.num_shards <= 1:
                raise
            recovered += 1
            _obs.inc("resil.recovery.attempts")
            _obs.inc("resil.recovery.device_loss")
            survivors = survivor_mesh(A_cur.mesh, int(e.device))
            before = int(A_cur.num_shards)
            up0 = _obs.snapshot().get("transfer.shard_upload_bytes", 0)
            A_cur = reshard(A_cur, mesh=survivors, layout=A_cur.layout)
            moved = (_obs.snapshot().get("transfer.shard_upload_bytes",
                                         0) - up0)
            _obs.inc("resil.recovery.mesh_shrink")
            _obs.inc("resil.recovery.reshard_bytes", int(moved))
            b_cur = shard_vector(jnp.asarray(b), A_cur.mesh,
                                 A_cur.rows_padded, layout=A_cur.layout)
            snap = ck.restore() if ck is not None else None
            if snap is not None:
                it0, arrays = snap
                # Plain restart from the checkpointed x: r and p
                # re-derive from scratch, preserving convergence to
                # tolerance (not the exact iterate sequence).
                x_host = np.asarray(arrays[0])[:rows]
                base += int(it0)
                _obs.inc("resil.recovery.restored_iters", int(it0))
                ck.rebase()
            else:
                x_host = np.asarray(x0_sh)[:rows]
            x0_cur = shard_vector(jnp.asarray(x_host, dtype=b_cur.dtype),
                                  A_cur.mesh, A_cur.rows_padded,
                                  layout=A_cur.layout)
            miter = max(int(maxiter) - base, 1)
            _obs.event("resil.recovery", site=site,
                       device=int(e.device), shards_before=before,
                       shards_after=int(A_cur.num_shards),
                       restored_iters=(int(snap[0]) if snap else 0),
                       reshard_bytes=int(moved))


def dist_gmres(A: DistCSR, b, x0=None, tol=None, restart=None,
               maxiter=None, M=None, callback=None, atol: float = 0.0,
               callback_type=None, rtol: float = 1e-5):
    """Distributed restarted GMRES: the single-chip solver
    (``linalg.gmres``) over the padded sharded system.  Padding rows
    are zero rows with zero right-hand side, so the Krylov space keeps
    them at exactly 0 and residual norms match the unpadded system.
    ``M`` may be a jittable callable on padded sharded vectors.
    Returns ``(x[:rows], iters)``.

    Restart cycles inherit the single-chip sync-free design: Arnoldi +
    progressive Givens QR of the Hessenberg + the solution update run
    as one traced program over the sharded operands (reductions lower
    to ``psum`` over the mesh), with ONE stacked-scalar fetch per cycle
    as the convergence cadence (``transfer.host_sync.gmres_conv``) —
    no per-cycle Hessenberg transfer or host ``lstsq``, which over a
    real tunnel used to cost a full RPC round trip per restart.
    """
    from ..linalg import gmres as _gmres

    from ..obs import comm as _comm

    rows, b_sh, x0_sh, maxiter, cb = _shard_system(
        A, b, x0, maxiter, callback
    )
    if callback_type == "pr_norm":
        cb = callback   # scalar iterates: nothing to truncate
    restart_eff = min(int(restart) if restart else 20,
                      int(b_sh.shape[0]))
    with _tctx.profiler_scope("dist_gmres"), \
            _obs.span("dist_gmres", n=rows, shards=A.num_shards,
                      restart=restart_eff) as sp:
        # Resilience: the cycle loop inside ``_gmres`` owns the
        # ``solver.gmres.conv`` fault/retry site and the checkpoint
        # cadence (the Arnoldi seed x per cycle); a ``DeviceLost``
        # escaping it routes through the recovery ladder, which
        # re-seeds the restarted Arnoldi from the last snapshot on
        # the survivor mesh (guard=False: no second policy wrap).
        def _solve(A_cur, b_cur, x0_cur, miter):
            return _gmres(
                _padded_operator(A_cur), b_cur, x0=x0_cur, tol=tol,
                restart=restart, maxiter=miter,
                M=_padded_precond(M, A_cur), callback=cb, atol=atol,
                callback_type=callback_type, rtol=rtol,
            )

        if _rsettings.resil:
            with _maybe_ckpt_scope("dist.gmres"):
                x, info, A_fin = _solve_with_recovery(
                    "dist.gmres", A, b, b_sh, x0_sh, int(maxiter),
                    _solve, guard=False)
        else:
            x, info = _solve(A, b_sh, x0_sh, maxiter)
            A_fin = A
        # Comm ledger: the driver returns iterations as a host int, so
        # the cycle count is free (approximated as ceil(iters/restart);
        # a run converging at cycle start reports one cycle fewer than
        # it dispatched).  Per-cycle volumes: restart+1 SpMV
        # realizations + the Arnoldi/MGS scalar psums.
        cycles = max(1, -(-int(info) // restart_eff))
        item = jnp.dtype(b_sh.dtype).itemsize
        spmv = spmv_comm_volumes(
            A_fin, A_fin.rows_padded // A_fin.num_shards, item)
        vols = _comm.scale(
            _comm.gmres_cycle_volumes(spmv, restart_eff, item,
                                      A_fin.num_shards),
            cycles,
        )
        n_psum = cycles * (restart_eff * (restart_eff + 1) // 2
                           + restart_eff + 1)
        calls = {k: cycles * (restart_eff + 1) for k in spmv}
        # Additive: a 2-d-block SpMV realization already carries a
        # "psum" call count (its psum_scatter output reduction).
        calls["psum"] = calls.get("psum", 0) + n_psum
        comm_bytes = _comm.record("dist_gmres", vols, calls,
                                  layout=A_fin.layout)
        if sp is not None:
            sp.set(iters=int(info), cycles=cycles,
                   comm_bytes=comm_bytes,
                   comm_calls=sum(calls[k] for k, b in vols.items()
                                  if b > 0))
    return x[:rows], info


def dist_bicgstab(A: DistCSR, b, x0=None, tol=None, maxiter=None,
                  M=None, callback=None, atol: float = 0.0,
                  rtol: float = 1e-5, conv_test_iters: int = 25):
    """Distributed BiCGSTAB over the padded sharded system (see
    ``dist_gmres`` for the padding argument).  Returns
    ``(x[:rows], iters)``."""
    from ..linalg import bicgstab as _bicgstab

    rows, b_sh, x0_sh, maxiter, cb = _shard_system(
        A, b, x0, maxiter, callback
    )
    x, info = _bicgstab(
        _padded_operator(A), b_sh, x0=x0_sh, tol=tol, maxiter=maxiter,
        M=_padded_precond(M, A), callback=cb, atol=atol, rtol=rtol,
        conv_test_iters=conv_test_iters,
    )
    return x[:rows], info


def dist_minres(A: DistCSR, b, x0=None, shift=0.0, tol=None,
                maxiter=None, M=None, callback=None, atol: float = 0.0,
                rtol: float = 1e-5, conv_test_iters: int = 25):
    """Distributed MINRES over the padded sharded system (see
    ``dist_gmres`` for the padding argument — padded rows are zero rows
    with zero rhs, and MINRES tolerates the resulting singular-but-
    consistent system by construction).  For symmetric indefinite
    operators the reference has no equivalent solver at any scale.
    Returns ``(x[:rows], iters)``.

    NOTE: passing ``callback`` routes the solve through host scipy's
    Python iteration loop (one device round trip per iteration) —
    unlike dist_cg/dist_gmres whose callbacks stay native.  Use it for
    diagnostics, not production runs."""
    from ..linalg import minres as _minres

    rows, b_sh, x0_sh, maxiter, cb = _shard_system(
        A, b, x0, maxiter, callback
    )
    x, info = _minres(
        _padded_operator(A), b_sh, x0=x0_sh, shift=shift, tol=tol,
        maxiter=maxiter, M=_padded_precond(M, A), callback=cb,
        atol=atol, rtol=rtol, conv_test_iters=conv_test_iters,
    )
    return x[:rows], info


def dist_eigsh(A: DistCSR, k=6, which="LM", v0=None, ncv=None,
               maxiter=None, tol=0, return_eigenvectors=True,
               sigma=None):
    """Distributed symmetric eigensolver: the single-chip Lanczos
    (``linalg.eigsh``) over the padded sharded operator.

    The start vector is zero on padding rows, and the padded operator's
    padding rows/columns are zero — so the Krylov space stays in the
    orthogonal complement of the padding subspace and NO spurious zero
    eigenvalues appear.  All SpMVs and reductions inside the jitted
    Lanczos scan lower to shard_map collectives.

    ``sigma`` (and ``which='SM'``, served as sigma=0) runs the same
    native shift-invert as single-chip ``eigsh``: the inexact MINRES
    inner solve nests inside the Lanczos scan, so every inner iteration
    is one ppermute/psum round over the mesh — no factorization, which
    is what makes shift-invert possible at distributed scale at all.
    A stagnating probe (sigma at a pencil eigenvalue, singular A at
    SM) raises ``ArpackNoConvergence`` — there is no host fallback for
    a distributed operator.  Returns eigenvalues (and row-truncated
    eigenvectors).  The reference has no eigensolver at any scale."""
    from ..eigen import (
        _eigsh_shift_invert, _lanczos_eigsh, _require_real_sigma,
        _validate_be_k,
    )

    rows = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError("expected square matrix")
    if not (0 < k < rows):
        raise ValueError(f"k={k} must satisfy 0 < k < n={rows}")
    if which not in ("LM", "LA", "SA", "BE", "SM"):
        raise ValueError(
            f"which={which!r}: distributed eigsh supports "
            f"LM/LA/SA/BE/SM")
    _validate_be_k(which, k)
    if which == "SM" and sigma is None:
        sigma, which = 0.0, "LM"    # largest of A^{-1}
    if v0 is None:
        v0 = np.random.default_rng(0).standard_normal(rows)
    v0_sh = shard_vector(jnp.asarray(v0, dtype=A.dtype), A.mesh,
                         A.rows_padded, layout=A.layout)
    # Valid-row mask keeps breakdown restarts out of the padding
    # subspace; max_rank caps the Krylov dimension at the true rows.
    mask = shard_vector(jnp.ones((rows,), dtype=A.dtype), A.mesh,
                        A.rows_padded, layout=A.layout)
    if sigma is None:
        out = _lanczos_eigsh(
            A.matvec_fn(), A.rows_padded, np.dtype(A.dtype), int(k),
            which, v0_sh, ncv, maxiter, tol, return_eigenvectors,
            mask=mask, max_rank=rows)
        if not return_eigenvectors:
            return out
        w, X = out
        return w, X[:rows]

    # Distributed shift-invert: the shared single-chip driver with the
    # valid-subspace mask (the padding block of A - sigma I is
    # -sigma I, singular at sigma=0 — it must not leak into the probe
    # or the Krylov space), the true-rows rank cap, and row truncation
    # applied to every returned/raised eigenvector block.
    _require_real_sigma(sigma)
    return _eigsh_shift_invert(
        A.matvec_fn(), A.rows_padded, np.dtype(A.dtype), int(k),
        float(sigma), which, v0_sh, ncv, maxiter, tol,
        return_eigenvectors, mask=mask, max_rank=rows,
        name="dist_eigsh", trunc_rows=rows)


def dist_diagonal(A: DistCSR) -> jax.Array:
    """diag(A) as a row-block sharded padded vector (square A).

    Distributed analog of the get-diagonal task (reference
    ``src/sparse/array/csr/get_diagonal.cc``); feeds the Jacobi
    smoother in distributed GMG.
    """
    from ._compat import shard_map

    if A.grid is not None:
        raise NotImplementedError(
            "dist_diagonal: 2-d-block layouts are SpMV/SpGEMM-only; "
            "shard with layout='1d-row' for GMG/diagonal consumers"
        )
    rps = A.rows_per_shard

    if A.dia_data is not None:
        # Banded: the main diagonal is one (R, rps) slice of the DIA
        # blocks (0 at holes/padding already).
        offs = A.dia_offsets
        if 0 not in offs:
            return jnp.zeros((A.rows_padded,), dtype=A.dtype)
        d0 = offs.index(0)
        return jnp.reshape(A.dia_data[:, d0, :], (-1,))

    A._require_blocks("dist_diagonal")
    halo = A.halo
    precise = A.gather_globals is not None

    cps = A.cols_per_shard

    def global_cols(cols, shard, ggl=None):
        """Layout columns -> global columns for any realization."""
        if precise:
            base = ggl.reshape(-1)
            rc = base.shape[0]
            own = cols - rc + shard.astype(index_dtype()) * cps
            return jnp.where(
                cols < rc, base[jnp.clip(cols, 0, rc - 1)], own
            )
        if halo >= 0:
            return cols.astype(index_dtype()) + (
                shard.astype(index_dtype()) * rps - halo
            )
        return cols.astype(index_dtype())

    if A.ell:
        def kernel(data, cols, counts, *rest):
            data, cols, counts = data[0], cols[0], counts[0]
            ggl = rest[0][0] if precise else None
            shard = jax.lax.axis_index(ROW_AXIS)
            row_g = shard.astype(index_dtype()) * rps + jnp.arange(
                rps, dtype=index_dtype()
            )
            W = cols.shape[1]
            slot = jnp.arange(W, dtype=counts.dtype)
            valid = slot[None, :] < counts[:, None]
            g = global_cols(cols, shard, ggl)
            hit = jnp.logical_and(valid, g == row_g[:, None])
            return jnp.sum(
                jnp.where(hit, data, jnp.zeros((), data.dtype)), axis=1
            )

        args = (A.data, A.cols, A.counts) + (
            (A.gather_globals,) if precise else ()
        )
    else:
        def kernel(data, cols, row_ids, counts, *rest):
            data, cols, row_ids, counts = (
                data[0], cols[0], row_ids[0], counts[0]
            )
            ggl = rest[0][0] if precise else None
            shard = jax.lax.axis_index(ROW_AXIS)
            slot = jnp.arange(data.shape[0], dtype=jnp.int32)
            valid = slot < counts
            target = (row_ids.astype(index_dtype())
                      + shard.astype(index_dtype()) * rps)
            g = global_cols(cols, shard, ggl)
            hit = jnp.logical_and(valid, g == target)
            return jax.ops.segment_sum(
                jnp.where(hit, data, jnp.zeros((), data.dtype)),
                row_ids, num_segments=rps, indices_are_sorted=True,
            )

        args = (A.data, A.cols, A.row_ids, A.counts) + (
            (A.gather_globals,) if precise else ()
        )
    in_specs = tuple(P(ROW_AXIS, *([None] * (a.ndim - 1))) for a in args)
    return shard_map(
        kernel, mesh=A.mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    )(*args)


def dist_cg(
    A: DistCSR,
    b,
    x0=None,
    tol=None,
    maxiter: Optional[int] = None,
    M=None,
    callback=None,
    atol: float = 0.0,
    rtol: float = 1e-5,
    conv_test_iters: int = 25,
):
    """Distributed (optionally preconditioned) CG: one jitted while_loop
    over sharded state.

    Global reductions (rho, pq, convergence norm) are jnp.vdot on sharded
    vectors — GSPMD lowers them to local dots + ``psum`` over ICI,
    replacing the reference's future-based scalar plumbing
    (``linalg.py:507-533``).  ``M`` is a jittable preconditioner on
    padded sharded vectors (e.g. ``DistGMG.cycle`` — the reference's
    headline GMG-preconditioned configuration, ``examples/gmg.py:104-143``).
    Returns the solution truncated to the unpadded length, plus the
    iteration count.
    """
    from ..linalg import (
        _cg_loop, _cg_loop_resil, _get_atol_rtol, _resil_solver_active,
    )

    _obs.inc("op.dist_cg")
    rows, b_sh, x0_sh, maxiter, cb = _shard_system(
        A, b, x0, maxiter, callback
    )
    if x0_sh is None:
        x0_sh = jnp.zeros_like(b_sh)
    bnrm2 = float(jnp.linalg.norm(b_sh))
    atol, _ = _get_atol_rtol(bnrm2, tol, atol, rtol)
    M_mv = M if M is not None else (lambda r: r)
    from ..obs import comm as _comm
    from ..obs import memory as _mem

    item = jnp.dtype(b_sh.dtype).itemsize
    if callback is None:
        with _tctx.profiler_scope("dist_cg"), \
                _lat.timer("lat.dist_cg.solve." + _lat.shape_bucket(rows)), \
                _obs.span("dist_cg", n=rows, shards=A.num_shards,
                          maxiter=int(maxiter),
                          preconditioned=M is not None) as sp, \
                _mem.watermark("dist_cg", n=rows, shards=A.num_shards):
            # Resilience: the whole loop dispatch is the ``dist.cg``
            # site — an injected (or real) collective failure retries
            # the solve from x0, which re-converges to the identical
            # answer instead of corrupting the Krylov state.  An
            # active deadline scope / health opt-in / checkpoint
            # scope swaps in the chunked driver (one fetch per
            # conv_test_iters cycle — the existing cadence), and a
            # ``DeviceLost`` routes through the recovery ladder
            # (shrink -> reshard -> restore -> resume).  NOTE: after
            # a shrink, ``M`` is applied to survivor-mesh vectors —
            # a mesh-agnostic jittable callable recovers; a
            # mesh-pinned preconditioner will not.
            def _solve(A_cur, b_cur, x0_cur, miter):
                loop = (_cg_loop_resil if _resil_solver_active()
                        else _cg_loop)
                return loop(
                    A_cur.matvec_fn(), M_mv, b_cur, x0_cur, atol,
                    int(miter), int(conv_test_iters),
                )

            if _rsettings.resil:
                with _maybe_ckpt_scope("dist.cg"):
                    x, iters, A_fin = _solve_with_recovery(
                        "dist.cg", A, b, b_sh, x0_sh, int(maxiter),
                        _solve)
            else:
                x, iters = _solve(A, b_sh, x0_sh, maxiter)
                A_fin = A
            if sp is not None:
                # One host sync for honest timing + the true iteration
                # count (tracing mode only; see linalg.cg).  The same
                # count drives the comm ledger: the loop body is traced
                # once, so the per-iteration volumes are multiplied out
                # here rather than at the (trace-time) dispatch.
                it = int(iters)
                vols, calls = cg_comm_volumes(A_fin, item, it)
                sp.set(iters=it,
                       comm_bytes=_comm.record("dist_cg", vols,
                                               calls,
                                               layout=A_fin.layout),
                       comm_calls=sum(
                           calls[k] for k, b in vols.items()
                           if b > 0))
        return x[:rows], iters

    # Callback path: Python-driven loop so user code observes every
    # iterate (mirrors ``linalg.cg``'s callback contract; the truncated
    # host view of x is passed, matching the reference's semantics).
    A_mv = A.matvec_fn()
    x = x0_sh
    r = b_sh - A_mv(x)
    p = jnp.zeros_like(b_sh)
    rho = jnp.ones((), dtype=b_sh.dtype)
    iters = 0
    n_norm = 0
    while iters < maxiter:
        z = M_mv(r)
        rho_old = rho
        rho = jnp.vdot(r, z)
        # Same zero-division guards as _cg_loop: an exactly-converged
        # residual must reach the convergence check, not produce NaNs.
        beta = jnp.where(
            jnp.logical_or(iters == 0, rho_old == 0),
            jnp.zeros_like(rho),
            rho / jnp.where(rho_old == 0, jnp.ones_like(rho_old), rho_old),
        )
        p = z + beta * p
        q = A_mv(p)
        pq = jnp.vdot(p, q)
        alpha = jnp.where(
            pq == 0, jnp.zeros_like(rho),
            rho / jnp.where(pq == 0, jnp.ones_like(pq), pq),
        )
        x = x + alpha * p
        r = r - alpha * q
        iters += 1
        cb(x)
        if iters % conv_test_iters == 0 or iters == maxiter - 1:
            n_norm += 1
            if float(jnp.linalg.norm(r)) < atol:
                break
    # Callback path: every eager A_mv dispatch above self-recorded its
    # realization under comm.dist_spmv.*, so recording SpMV volumes
    # again here would double-count the same bytes.  Only the scalar
    # reductions this driver loop adds are ledgered under dist_cg:
    # rho + pq every iteration, plus the residual norms the check
    # branch actually executed (counted in the loop, not approximated
    # — the ledger's contract is exactness).
    n_psum = 2 * iters + n_norm
    _comm.record(
        "dist_cg",
        {"psum": n_psum * _comm.psum_bytes(1, item, A.num_shards)},
        calls={"psum": n_psum},
        layout=A.layout,
    )
    return x[:rows], iters
