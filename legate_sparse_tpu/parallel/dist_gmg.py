# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed geometric multigrid V-cycle (CG preconditioner).

The distributed realization of the reference's headline application —
GMG-preconditioned CG (reference ``examples/gmg.py:61-143``): the same
weighted-Jacobi smoothing, injection/linear intergrid transfers, and
Galerkin coarse operators ``A_c = R @ A @ P``, but with every level a
row-block ``DistCSR``, the triple product computed by the collective
``dist_spgemm``, and the whole V-cycle a jittable function on padded
sharded vectors — so ``dist_cg(..., M=gmg.cycle)`` runs the entire
preconditioned solve as one XLA while_loop over the mesh.

Intergrid operators are built host-side (they are O(coarse_dim) sparse
and built once — same as the reference's per-level construction,
``gmg.py:201-292``); all per-iteration math is collective.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from .dist_csr import (
    DistCSR, dist_diagonal, dist_spmv, shard_csr, shard_vector,
    spmv_comm_volumes,
)
from .dist_spgemm import dist_spgemm
from .mesh import Mesh


def _injection_csr(fine_dim: int):
    """Injection restriction as host scipy CSR (mirrors
    ``examples/gmg.py`` ``injection_operator``)."""
    import scipy.sparse as sp

    fine_shape = (int(np.sqrt(fine_dim)),) * 2
    coarse_shape = (fine_shape[0] // 2, fine_shape[1] // 2)
    coarse_dim = int(np.prod(coarse_shape))
    ij = np.arange(coarse_dim, dtype=np.int64)
    i = ij // coarse_shape[1]
    j = ij % coarse_shape[1]
    cols = 2 * i * fine_shape[1] + 2 * j
    indptr = np.arange(coarse_dim + 1, dtype=np.int64)
    vals = np.ones(coarse_dim, dtype=np.float64)
    return (
        sp.csr_matrix((vals, cols, indptr), shape=(coarse_dim, fine_dim)),
        coarse_dim,
    )


def _linear_csr(fine_dim: int):
    """Full-weighting 9-point restriction (mirrors ``examples/gmg.py``
    ``linear_operator``)."""
    import scipy.sparse as sp

    fine_shape = (int(np.sqrt(fine_dim)),) * 2
    coarse_shape = (fine_shape[0] // 2, fine_shape[1] // 2)
    coarse_dim = int(np.prod(coarse_shape))
    ij = np.arange(coarse_dim, dtype=np.int64)
    ci = ij // coarse_shape[1]
    cj = ij % coarse_shape[1]
    rows, cols, vals = [], [], []
    for di, dj, w in (
        (-1, -1, 1 / 16), (-1, 0, 2 / 16), (-1, 1, 1 / 16),
        (0, -1, 2 / 16), (0, 0, 4 / 16), (0, 1, 2 / 16),
        (1, -1, 1 / 16), (1, 0, 2 / 16), (1, 1, 1 / 16),
    ):
        fi = 2 * ci + di
        fj = 2 * cj + dj
        ok = (fi >= 0) & (fi < fine_shape[0]) & (fj >= 0) & (
            fj < fine_shape[1]
        )
        rows.append(ij[ok])
        cols.append(fi[ok] * fine_shape[1] + fj[ok])
        vals.append(np.full(int(ok.sum()), w))
    R = sp.csr_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(coarse_dim, fine_dim),
    )
    return R, coarse_dim


_RESTRICTIONS = {"injection": _injection_csr, "linear": _linear_csr}


def _dist_max_eigenvalue(A: DistCSR, d_inv: jax.Array, iters: int = 1):
    """Spectral-radius estimate of A @ D^-1 by power iteration, all
    collective (matches the single-device estimate in ``examples/gmg.py``
    ``max_eigenvalue`` — same seed, same iteration count — so the
    distributed V-cycle reproduces single-device iteration counts)."""
    rng = np.random.default_rng(7)
    x = shard_vector(
        rng.random(A.shape[1]).astype(np.dtype(A.dtype)), A.mesh,
        A.rows_padded,
    )
    mv = lambda v: dist_spmv(A, d_inv * v)
    for _ in range(iters):
        y = mv(x)
        x = y / jnp.linalg.norm(y)
    return float(jnp.vdot(x, mv(x)))


class DistGMG:
    """Distributed GMG hierarchy + jittable V-cycle.

    ``A`` may be a ``DistCSR`` or a host ``csr_array`` (sharded onto
    ``mesh``).  ``cycle`` maps a padded sharded residual to the
    preconditioned correction; pass it as ``M`` to ``dist_cg``.
    """

    def __init__(
        self,
        A,
        levels: int,
        mesh: Optional[Mesh] = None,
        gridop: str = "injection",
        omega: float = 4.0 / 3.0,
        power_iters: int = 1,
    ):
        if not isinstance(A, DistCSR):
            A = shard_csr(A, mesh=mesh)
        self.A = A
        self.levels = levels
        restrict = _RESTRICTIONS[gridop]

        # Per level: (R, A_coarse, P) DistCSRs + (omega, D_inv) params.
        self.operators: List[Tuple[DistCSR, DistCSR, DistCSR]] = []
        self.level_params: List[Tuple[float, jax.Array]] = []

        import legate_sparse_tpu as sparse

        # Level indexing matches the reference example (``gmg.py:141-165``):
        # ``levels`` counts grid levels, the coarsest is ``levels - 1``,
        # so ``levels - 1`` restriction/Galerkin stages are built.
        dim = A.shape[0]
        cur = A
        self._append_params(cur, omega, power_iters)
        for _ in range(levels - 1):
            R_sp, dim = restrict(dim)
            # Grid operators follow the system dtype (an f32 system
            # must not upcast through f64 restriction values — the CG
            # while_loop carry dtype would diverge).
            R_sp = R_sp.astype(np.dtype(cur.dtype))
            P_sp = R_sp.T.tocsr()
            dR = shard_csr(sparse.csr_array(R_sp), mesh=cur.mesh)
            dP = shard_csr(sparse.csr_array(P_sp), mesh=cur.mesh)
            coarse = dist_spgemm(dR, dist_spgemm(cur, dP))
            self.operators.append((dR, coarse, dP))
            self._append_params(coarse, omega, power_iters)
            cur = coarse

        # Comm ledger: the V-cycle's interconnect budget, priced once
        # from the hierarchy's static shard shapes.  A jittable cycle
        # can't self-account per execution (it runs inside the CG
        # while_loop), so the per-cycle total lives here and bench /
        # callers attach it to their spans.
        self.cycle_comm_volumes = self._cycle_comm_volumes()
        self.cycle_comm_bytes = sum(self.cycle_comm_volumes.values())
        _obs.event("dist_gmg.hierarchy", levels=levels,
                   shards=self.A.num_shards,
                   cycle_comm_bytes=self.cycle_comm_bytes)

    def _cycle_comm_volumes(self):
        """Per-collective interconnect bytes of ONE V-cycle: each
        non-coarsest level runs two smoothing SpMVs on its operator
        plus one restriction and one prolongation SpMV; the coarsest
        level is a pointwise Jacobi step with no communication."""
        from ..obs import comm as _comm

        R = self.A.num_shards
        item = np.dtype(self.A.dtype).itemsize
        vols: dict = {}
        levels = [self.A] + [op[1] for op in self.operators]
        for lvl, (dR, coarse_A, dP) in enumerate(self.operators):
            A_l = levels[lvl]
            fine_local = A_l.rows_padded // R
            coarse_local = coarse_A.rows_padded // R
            vols = _comm.merge(
                vols,
                _comm.scale(spmv_comm_volumes(A_l, fine_local, item), 2),
                spmv_comm_volumes(dR, fine_local, item),
                spmv_comm_volumes(dP, coarse_local, item),
            )
        return vols

    def _append_params(self, A: DistCSR, omega: float, power_iters: int):
        diag = dist_diagonal(A)
        # Padded rows have a zero diagonal; guard the reciprocal (the
        # smoother multiplies by residuals that are zero there anyway).
        d_inv = jnp.where(diag != 0, 1.0 / jnp.where(diag == 0, 1.0, diag),
                          0.0)
        rho = _dist_max_eigenvalue(A, d_inv, power_iters)
        self.level_params.append((omega / rho, d_inv))

    # -- V-cycle (jittable) -------------------------------------------------
    def cycle(self, r: jax.Array) -> jax.Array:
        return self._cycle(self.A, r, 0)

    def _cycle(self, A: DistCSR, r, level: int):
        omega, d_inv = self.level_params[level]
        if level == self.levels - 1:
            return omega * r * d_inv
        dR, coarse_A, dP = self.operators[level]
        x = omega * r * d_inv                      # pre-smooth
        fine_r = r - dist_spmv(A, x)
        coarse_r = dist_spmv(dR, fine_r)
        coarse_x = self._cycle(coarse_A, coarse_r, level + 1)
        x = x + dist_spmv(dP, coarse_x)            # correct
        return x + omega * (r - dist_spmv(A, x)) * d_inv   # post-smooth

    def diagnostics(self) -> str:
        """Hierarchy report (reference ``gmg.py:307-324``)."""
        out = ["DistMultilevelSolver", f"Number of Levels: {self.levels}"]
        out.append("  level   unknowns     nonzeros")
        levels = [self.A] + [op[1] for op in self.operators]
        for n, A in enumerate(levels):
            nnz = int(np.sum(np.asarray(A.counts)))
            out.append(f"{n:>6} {A.shape[1]:>11} {nnz:>12}")
        return "\n".join(out)
