# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed SpGEMM: C = A @ B over a row mesh.

TPU-native analog of the reference's flagship multi-node operation — the
GPU single-phase SpGEMM with NCCL nnz-allgather (reference
``src/sparse/array/csr/spgemm_csr_csr_csr.cu:43-62`` global offsets,
driven from ``legate_sparse/csr.py:603-684``):

- Each shard computes its row block of C with the same ESC
  (expand-sort-compress) formulation as the single-device kernel
  (``ops/spgemm.py``) — vectorized over the shard's products, not a
  Gustavson scalar loop.
- The reference's *unbound stores* + NCCL allgather of local nnz become
  XLA's static-shape analog: two tiny collective phases that produce the
  per-shard product count and output nnz, a host sync of their maxima
  (exactly the role of the reference's blocking ``int(nnz)``,
  ``csr.py:714``), and padded (R, cap) output blocks.
- B's rows are realized per shard through a min/max column image of A
  (the reference's image-gather, ``legate_sparse/csr.py:640-666`` +
  ``src/sparse/partition/fast_image_partition.cu:29-55``): a host-side
  window plan maps each shard's A-column range onto B's row blocks, and
  only those blocks ride ring ``ppermute`` rotations — per-shard memory
  O(window · nnz(B)/R), not O(nnz(B)).  When the window covers most of
  the ring (dense/irregular A) the full ``all_gather`` realization is
  used instead (``_B_WINDOW_DENSE_FRAC``).

Phases (each one jitted shard_map over the row mesh):

1. ``T_local``  = per-shard product count        -> host max = T_cap
2. ``nnz_local`` = per-shard distinct (i,j) count -> host max = nnz_cap
3. numeric ESC -> padded-CSR row blocks (R, nnz_cap)

Returns a padded-CSR ``DistCSR`` whose cols are global indices
(all_gather realization; ``shard_csr``-style windows can rebase later).
"""

from __future__ import annotations

import threading
from functools import lru_cache, partial
from typing import NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..obs import latency as _lat
from ..types import index_dtype
from ._compat import shard_map
from jax.sharding import PartitionSpec as P

from .dist_csr import DistCSR
from .mesh import COL_AXIS, ROW_AXIS


class _Layout(NamedTuple):
    """Static layout signature of a DistCSR — everything the ESC
    kernels read about an operand besides its arrays.  Used as the
    lru_cache key for the compiled shard_map phases, so it MUST capture
    every operand attribute the kernel closures consult (adding a new
    attribute read to a kernel without extending this key would leak
    stale compilations)."""

    ell: bool
    rps: int
    halo: int
    cps: int
    has_ggl: bool
    shape: Tuple[int, int]
    rows_padded: int
    num_shards: int
    inner: int          # W for ELL blocks, nnz_max for padded-CSR


def _layout_of(M: DistCSR) -> _Layout:
    return _Layout(
        ell=M.ell, rps=M.rows_per_shard, halo=M.halo,
        cps=M.cols_per_shard, has_ggl=M.gather_globals is not None,
        shape=M.shape, rows_padded=M.rows_padded,
        num_shards=M.num_shards, inner=int(M.cols.shape[-1]),
    )


def _a_local_flat(A: _Layout, data, cols, counts, row_ids, ggl=None):
    """Normalize a shard's A block to flat (a_row, a_col_global, a_val,
    a_valid) arrays of static length L.

    ``data``/``cols``/... are the shard-local blocks (leading R axis
    already consumed by shard_map).  Column indices are rebased back to
    global whatever the layout stores (halo-window-local or precise
    compact positions via ``ggl`` = the shard's gather_globals row).
    """
    rps = A.rps
    shard = jax.lax.axis_index(ROW_AXIS)
    start = shard.astype(index_dtype()) * rps

    if A.ell:
        R_, W = cols.shape  # (rps, W)
        a_row = jnp.broadcast_to(
            jnp.arange(rps, dtype=jnp.int32)[:, None], (rps, W)
        ).reshape(-1)
        slot = jnp.arange(W, dtype=counts.dtype)
        a_valid = (slot[None, :] < counts[:, None]).reshape(-1)
        a_col = cols.reshape(-1).astype(index_dtype())
        a_val = data.reshape(-1)
    else:
        a_row = row_ids
        nnz_max = data.shape[0]
        slot = jnp.arange(nnz_max, dtype=jnp.int32)
        a_valid = slot < counts
        a_col = cols.astype(index_dtype())
        a_val = data

    if A.has_ggl:
        base = ggl.reshape(-1)
        rc = base.shape[0]
        own = a_col - rc + shard.astype(index_dtype()) * A.cps
        a_col = jnp.where(
            a_col < rc, base[jnp.clip(a_col, 0, rc - 1)], own
        )
    elif A.halo >= 0:
        a_col = a_col + (start - A.halo)
    a_col = jnp.clip(a_col, 0, A.shape[1] - 1)
    return a_row, a_col, a_val, a_valid


def _b_global_flat(B: _Layout, data, cols, counts, row_ids, ggl=None):
    """All-gather B's blocks and expose flat per-row random access:
    (b_data_g, b_cols_g, b_start, b_counts) with global column indices.

    The ICI realization of the reference's image-gather of B
    (``csr.py:640-666``); one all_gather per phase, O(nnz(B)/R) words
    per link hop.  Precise-layout blocks are un-rebased per source
    block via the gathered ``gather_globals``.
    """
    R = B.num_shards
    rps = B.rps
    rows_p = B.rows_padded

    data_g = jax.lax.all_gather(data, ROW_AXIS)    # (R, ...) blocks
    cols_g = jax.lax.all_gather(cols, ROW_AXIS)
    counts_g = jax.lax.all_gather(counts, ROW_AXIS)
    if B.has_ggl:
        ggl_g = jax.lax.all_gather(ggl, ROW_AXIS)  # (R, R, C)
        # Un-rebase each source block with its own inverse map; the
        # appended-local region maps back to the block's own columns.
        per_block = cols_g.reshape(R, -1).astype(index_dtype())
        cps_b = B.cps
        s_ids = jnp.arange(R, dtype=index_dtype())

        def unreb(inv, c, s):
            base = inv.reshape(-1)
            rc = base.shape[0]
            own = c - rc + s * cps_b
            return jnp.where(c < rc, base[jnp.clip(c, 0, rc - 1)], own)

        cols_g = jax.vmap(unreb)(ggl_g, per_block, s_ids).reshape(
            cols_g.shape
        )

    if B.ell:
        W = cols.shape[-1]
        b_data_g = data_g.reshape(rows_p, W).reshape(-1)
        b_cols_g = cols_g.reshape(rows_p, W).reshape(-1).astype(index_dtype())
        b_counts = counts_g.reshape(rows_p).astype(jnp.int32)
        b_start = jnp.arange(rows_p, dtype=index_dtype()) * W
    else:
        rid_g = jax.lax.all_gather(row_ids, ROW_AXIS)   # (R, nnz_max)
        nnz_max = data.shape[-1]
        b_data_g = data_g.reshape(-1)
        b_cols_g = cols_g.reshape(-1).astype(index_dtype())
        # Per-row counts from the sorted local row ids: row r of block s
        # occupies [indptr_local[s, r], indptr_local[s, r+1]) clamped to
        # the block's valid prefix (padding replicates the last row id).
        slot = jnp.arange(nnz_max, dtype=jnp.int32)
        valid = slot[None, :] < counts_g[:, None]          # (R, nnz_max)
        ids_2d = jnp.where(valid, rid_g, rps)              # pad -> rps
        one = jnp.ones_like(ids_2d, dtype=jnp.int32)
        percount = jax.vmap(
            lambda ids, on: jax.ops.segment_sum(on, ids, num_segments=rps + 1)
        )(ids_2d, one)[:, :rps]                            # (R, rps)
        b_counts = percount.reshape(rows_p)
        starts_local = jnp.cumsum(percount, axis=1) - percount  # exclusive
        b_start = (
            starts_local.astype(index_dtype())
            + (jnp.arange(R, dtype=index_dtype()) * nnz_max)[:, None]
        ).reshape(rows_p)

    if B.halo >= 0:
        b_cols_g = _unrebase_b(B, b_cols_g, rps)
    b_cols_g = jnp.clip(b_cols_g, 0, B.shape[1] - 1)
    return b_data_g, b_cols_g, b_start, b_counts


def _unrebase_b(B: _Layout, b_cols_g, rps):
    """Undo halo-window rebasing on the gathered flat cols: entry j of
    block s stores local = global - (s*rps - halo)."""
    if B.ell:
        per_block = rps * B.inner
    else:
        per_block = B.inner
    block_of = jnp.arange(b_cols_g.shape[0], dtype=index_dtype()) // per_block
    return b_cols_g + block_of * rps - B.halo


# Window wider than this fraction of the ring -> the ppermute rotation
# chain stops paying for itself; use the one-shot all_gather.
_B_WINDOW_DENSE_FRAC = 0.75

# Legacy introspection globals: how dist_spgemm's last general-path
# call realized B ("window" | "all_gather"), and the plan used.  The
# SUPPORTED inspection mechanism is now the obs subsystem — the
# ``dist_spgemm`` span records ``b_realization``/``b_plan`` attributes
# and the ``dist_spgemm.realization.*`` counters accumulate the choice
# per call (``obs/counters.py``).  These two names stay for existing
# tests/scripts; new code should read the span attrs instead.
LAST_B_REALIZATION: str = ""
LAST_B_PLAN: tuple = ()


@lru_cache(maxsize=128)
def _col_window_fn(mesh, la: _Layout):
    """Per-shard global-column min/max of A (the FAST_IMAGE_RANGE
    analog, ``fast_image_partition.cu:29-55``): one tiny jitted
    shard_map, host-fetched once per (A, B) structure pair.

    The per-shard scalars are ``all_gather``-replicated before leaving
    the shard_map (out_specs ``P(None)``) so the host fetch is legal in
    multi-controller runs — a ``P(ROW_AXIS)``-sharded output would span
    non-addressable devices there and refuse ``np.asarray``.
    """
    _obs.inc("jit_miss.dist_spgemm.col_window_fn")
    in_specs = _esc_specs(la)
    big = la.shape[1]

    def kern(*a_args):
        a_row, a_col, a_val, a_valid = _a_local_flat(la, *_local(a_args))
        mn = jnp.min(jnp.where(a_valid, a_col, big))
        mx = jnp.max(jnp.where(a_valid, a_col, -1))
        return (jax.lax.all_gather(mn, ROW_AXIS),
                jax.lax.all_gather(mx, ROW_AXIS))

    return jax.jit(shard_map(
        kern, mesh=mesh, in_specs=in_specs,
        out_specs=(P(None), P(None)), check_vma=False,
    ))


def _density_bucket(nnz: int, rows: int) -> int:
    """log2 bucket of nnz-per-row — the sparsity term of the
    window-decline key (ADVICE r5 low): two matrices sharing a layout
    but an order of magnitude apart in density get separate decline
    entries, so one wide-window matrix no longer pins every later
    same-layout matrix to the all_gather realization."""
    import math

    if nnz <= 0 or rows <= 0:
        return -1
    per_row = nnz / rows
    return math.floor(math.log2(per_row)) if per_row >= 1 else -1


def _decline_key(A: DistCSR, la: _Layout, lb: _Layout):
    """Cache key for a declined window: layout structure PLUS A's
    nnz-density bucket (the window width is a property of A's column
    sparsity, which the layout alone does not capture) PLUS the full
    mesh+layout fingerprint.  The fingerprint term matters now that
    one matrix shape can be sharded several ways: without it, a 1-D
    verdict (window too wide at R row blocks) would be replayed
    against a 2-d-block layout of the same shape — or against the
    same shapes on a different device set — and wrongly pin it to
    all_gather.  ``nnz_hint`` is set by every builder; an externally
    constructed DistCSR pays one counts fetch, memoized on the
    instance.  NOTE: ``_window_decline`` reads the density bucket at
    ``key[2]`` — keep its position stable."""
    from .dist_csr import mesh_fingerprint

    nnz = A.nnz_hint
    if nnz < 0:
        nnz = A.global_nnz
        A.nnz_hint = nnz
    return (la, lb, _density_bucket(nnz, la.shape[0]),
            mesh_fingerprint(A.mesh, layout=A.layout))


def _b_window_plan(A: DistCSR, la: _Layout, lb: _Layout, a_arrays):
    """Host-side B-realization window plan, or None for all_gather.

    Maps each shard's A-column range onto B's row blocks (block t of B
    lives on shard t).  Returns ``(first_blks, (nblk, d_fwd, d_bwd))``:
    per-shard first window block (an int32 host array — passed to the
    phase kernels as a TRACED operand so sparsity drift between calls
    never recompiles them), plus the static shape knobs: window width
    in blocks and the max forward/backward ring distances the rotation
    chain must cover.  None when B is precise-layout (compact cols
    don't rotate) or the worst-case window is too wide to beat
    all_gather.
    """
    if lb.has_ggl:
        return None
    R = la.num_shards
    if R <= 2:
        return None         # rotation chain degenerates to all_gather
    key = _decline_key(A, la, lb)
    with _STATE_LOCK:
        declined = key in _WINDOW_DECLINED
    if declined:
        # This structure+density pair already proved too wide for a
        # window: skip the min/max image probe (a blocking
        # device->host round trip — ~1 s over the TPU tunnel) on every
        # later call.  The key carries A's nnz-density bucket, so only
        # comparably-dense matrices inherit the decline; a sparser
        # same-layout matrix re-probes (``reset_window_declines()``
        # still clears everything).  Correctness is unaffected.
        _obs.inc("dist_spgemm.window_decline_cached")
        return None
    _obs.inc("transfer.host_sync.spgemm_window_probe")
    from ..obs import comm as _comm

    # Probe cost in the ledger: two 1-element all_gathers (min/max).
    _comm.record("dist_spgemm.window_probe", {
        "all_gather": 2 * _comm.all_gather_bytes(
            1, np.dtype(index_dtype()).itemsize, R),
    }, calls={"all_gather": 2})
    mn, mx = _col_window_fn(A.mesh, la)(*a_arrays)
    mn = np.asarray(mn)
    mx = np.asarray(mx)
    rps_b = lb.rps
    first = np.clip(mn // rps_b, 0, R - 1).astype(np.int64)
    last = np.clip(mx // rps_b, 0, R - 1).astype(np.int64)
    s_ids = np.arange(R)
    empty = mx < 0          # shard with no valid A entries
    first[empty] = s_ids[empty]
    last[empty] = s_ids[empty]
    nblk = int(np.max(last - first) + 1)
    # Floor of 3 so a 2-block window (any band crossing one shard
    # boundary) is accepted on small rings: at R=3 the 0.75 fraction
    # alone would make the window UNREACHABLE (limit 2 declines
    # nblk=2), turning every banded product into an all_gather.
    limit = max(3, int(R * _B_WINDOW_DENSE_FRAC))
    if nblk <= 0 or nblk >= limit:
        _window_decline(key, la, lb)
        return None
    d_fwd = int(np.max(np.maximum(s_ids - first, 0)))
    d_bwd = int(np.max(np.maximum(last - s_ids, 0)))
    if d_fwd + d_bwd >= R:
        _window_decline(key, la, lb)
        return None         # would rotate the whole ring anyway
    return first.astype(np.int32), (nblk, d_fwd, d_bwd)


_WINDOW_DECLINED: set = set()
# Guards the module-level mutable state above (_WINDOW_DECLINED and
# the LAST_B_* introspection globals): the engine's request executor
# makes concurrent dist_spgemm callers a supported configuration, and
# an unguarded size-check-then-clear/add on the set (or a torn
# REALIZATION/PLAN pair) is a real race there.  Device launches still
# serialize (tests/test_obs_concurrency.py: concurrent collective
# launches deadlock the XLA CPU backend); this lock only covers the
# host-side bookkeeping.
_STATE_LOCK = threading.Lock()


def _window_decline(key, la: _Layout, lb: _Layout) -> None:
    with _STATE_LOCK:
        if len(_WINDOW_DECLINED) > 256:  # unbounded-session safety valve
            _WINDOW_DECLINED.clear()
        _WINDOW_DECLINED.add(key)
    _obs.inc("dist_spgemm.window_decline")
    _obs.event("dist_spgemm.window_decline",
               a_shape=la.shape, b_shape=lb.shape,
               shards=la.num_shards, density_bucket=key[2])


def last_b_realization() -> tuple:
    """Consistent snapshot of the legacy introspection pair
    ``(LAST_B_REALIZATION, LAST_B_PLAN)`` — both read under the state
    lock, so a concurrent ``dist_spgemm`` can never tear the pair
    (realization from one call, plan from another).  The SUPPORTED
    mechanism remains the obs span attrs; this accessor exists for the
    scripts that still read the globals."""
    with _STATE_LOCK:
        return LAST_B_REALIZATION, LAST_B_PLAN


def reset_window_declines() -> None:
    """Clear the window-decline cache.  Entries are keyed on layout
    structure PLUS A's nnz-density bucket (``_decline_key``), so a
    wide-window matrix only pins comparably-dense same-layout matrices
    — but a long-lived process retiring whole matrix families can
    still call this to force re-probing of the min/max column image."""
    with _STATE_LOCK:
        _WINDOW_DECLINED.clear()


def _b_window_flat(B: _Layout, plan, first_local, data, cols, counts,
                   row_ids, ggl=None, counts_only: bool = False):
    """Windowed analog of ``_b_global_flat``: realize only the B row
    blocks inside this shard's A-column window via ring ``ppermute``
    rotations (``d_fwd + d_bwd`` rounds), then expose the same flat
    per-row access over the (nblk, ...) buffers.

    ``plan`` carries only the STATIC shape knobs ``(nblk, d_fwd,
    d_bwd)``; ``first_local`` is the shard's first-window-block id as a
    traced (1,)-block operand — keeping the data-dependent part of the
    plan out of the jit key (window drift between calls re-runs, not
    recompiles).

    Returns ``(b_data_g, b_cols_g, b_start, b_counts, row_base)`` —
    identical contract to the global variant except row lookups must
    subtract the traced ``row_base`` (global B row of window slot 0).
    ``counts_only`` rotates just the per-row-count inputs (phase 1
    needs no values/cols) and returns None for the other slots.
    """
    nblk, d_fwd, d_bwd = plan
    R = B.num_shards
    rps = B.rps
    s = jax.lax.axis_index(ROW_AXIS)
    first = first_local.reshape(()).astype(jnp.int32)
    perm_fwd = [(i, (i + 1) % R) for i in range(R)]
    perm_bwd = [(i, (i - 1) % R) for i in range(R)]

    def place(buf, blk, blk_id):
        pos = blk_id.astype(jnp.int32) - first
        ok = (pos >= 0) & (pos < nblk)
        safe = jnp.clip(pos, 0, nblk - 1)
        cur = jax.lax.dynamic_index_in_dim(buf, safe, 0, keepdims=False)
        newv = jnp.where(ok, blk, cur)
        return jax.lax.dynamic_update_index_in_dim(buf, newv, safe, 0)

    def gather_win(*blks):
        bufs = [jnp.zeros((nblk,) + b.shape, b.dtype) for b in blks]
        bufs = [place(buf, b, s) for buf, b in zip(bufs, blks)]
        cur = blks
        for d in range(1, d_fwd + 1):
            cur = tuple(jax.lax.ppermute(c, ROW_AXIS, perm_fwd)
                        for c in cur)
            blk_id = (s - d) % R
            bufs = [place(buf, c, blk_id) for buf, c in zip(bufs, cur)]
        cur = blks
        for d in range(1, d_bwd + 1):
            cur = tuple(jax.lax.ppermute(c, ROW_AXIS, perm_bwd)
                        for c in cur)
            blk_id = (s + d) % R
            bufs = [place(buf, c, blk_id) for buf, c in zip(bufs, cur)]
        return bufs

    # Which global block each window slot holds (for col un-rebasing).
    slot_blk = first.astype(index_dtype()) + jnp.arange(
        nblk, dtype=index_dtype()
    )
    row_base = first.astype(index_dtype()) * rps

    if B.ell:
        W = cols.shape[-1]
        if counts_only:
            (counts_w,) = gather_win(counts)
            b_counts = counts_w.reshape(nblk * rps).astype(jnp.int32)
            return None, None, None, b_counts, row_base
        data_w, cols_w, counts_w = gather_win(data, cols, counts)
        b_data_g = data_w.reshape(-1)
        b_cols_g = cols_w.reshape(nblk, -1).astype(index_dtype())
        if B.halo >= 0:
            # local = global - (t*rps - halo) for source block t.
            b_cols_g = b_cols_g + (slot_blk * rps - B.halo)[:, None]
        b_cols_g = b_cols_g.reshape(-1)
        b_counts = counts_w.reshape(nblk * rps).astype(jnp.int32)
        b_start = jnp.arange(nblk * rps, dtype=index_dtype()) * W
    else:
        nnz_max = B.inner
        if counts_only:
            counts_w, rid_w = gather_win(counts, row_ids)
        else:
            data_w, cols_w, counts_w, rid_w = gather_win(
                data, cols, counts, row_ids
            )
        slot = jnp.arange(nnz_max, dtype=jnp.int32)
        valid = slot[None, :] < counts_w[:, None]          # (nblk, nnz_max)
        ids_2d = jnp.where(valid, rid_w, rps)
        one = jnp.ones_like(ids_2d, dtype=jnp.int32)
        percount = jax.vmap(
            lambda ids, on: jax.ops.segment_sum(on, ids,
                                                num_segments=rps + 1)
        )(ids_2d, one)[:, :rps]                            # (nblk, rps)
        b_counts = percount.reshape(nblk * rps)
        if counts_only:
            return None, None, None, b_counts, row_base
        b_data_g = data_w.reshape(-1)
        b_cols_g = cols_w.reshape(nblk, -1).astype(index_dtype())
        if B.halo >= 0:
            b_cols_g = b_cols_g + (slot_blk * rps - B.halo)[:, None]
        b_cols_g = b_cols_g.reshape(-1)
        starts_local = jnp.cumsum(percount, axis=1) - percount
        b_start = (
            starts_local.astype(index_dtype())
            + (jnp.arange(nblk, dtype=index_dtype()) * nnz_max)[:, None]
        ).reshape(nblk * rps)

    b_cols_g = jnp.clip(b_cols_g, 0, B.shape[1] - 1)
    return b_data_g, b_cols_g, b_start, b_counts, row_base


def _expand_sorted(A: _Layout, a_args, b_args, T_cap: int, n_cols: int,
                   row_base=0):
    """Shared expand + two-key sort producing (c_row, c_col, c_val,
    heads, local_nnz) for one shard — 1-D entry point (flattens the
    shard's A block first); the 2-d path feeds its gathered row-panel
    quad straight into ``_expand_sorted_flat``."""
    return _expand_sorted_flat(
        _a_local_flat(A, *a_args), b_args, T_cap, n_cols, A.rps,
        row_base=row_base,
    )


def _expand_sorted_flat(a_flat, b_args, T_cap: int, n_cols: int,
                        rps: int, row_base=0):
    """Expansion core over a flat (a_row, a_col, a_val, a_valid) quad.
    Invalid product slots carry the sentinel row ``rps`` (sorts after
    every valid row) and value 0.

    ``row_base``: global B row of the realized buffer's first row (0
    for the all_gather realization; the shard's window start — traced —
    for the windowed one).  Every valid A column lies inside the window
    by construction, so the clip only ever moves invalid slots.
    """
    a_row, a_col, a_val, a_valid = a_flat
    b_data_g, b_cols_g, b_start, b_counts = b_args

    b_row = jnp.clip(a_col - row_base, 0, b_counts.shape[0] - 1)
    counts_per_a = jnp.where(a_valid, b_counts[b_row], 0).astype(index_dtype())
    starts = jnp.concatenate(
        [jnp.zeros((1,), index_dtype()), jnp.cumsum(counts_per_a)]
    )
    T_local = starts[-1]

    t = jnp.arange(T_cap, dtype=index_dtype())
    e = jnp.clip(
        jnp.searchsorted(starts, t, side="right") - 1, 0, a_row.shape[0] - 1
    )
    valid_t = t < T_local
    within = t - starts[e]
    k = b_row[e]
    b_pos = jnp.clip(b_start[k] + within, 0, b_data_g.shape[0] - 1)

    c_row = jnp.where(valid_t, a_row[e], rps).astype(jnp.int32)
    c_col = jnp.where(valid_t, b_cols_g[b_pos], n_cols)
    c_val = jnp.where(valid_t, a_val[e] * b_data_g[b_pos],
                      jnp.zeros((), a_val.dtype))
    c_row, c_col, c_val = jax.lax.sort([c_row, c_col, c_val], num_keys=2)

    valid_s = c_row < rps
    if T_cap > 1:
        change = jnp.logical_or(c_row[1:] != c_row[:-1],
                                c_col[1:] != c_col[:-1])
        heads = jnp.concatenate([jnp.ones((1,), bool), change])
    else:
        heads = jnp.ones((T_cap,), bool)
    heads = jnp.logical_and(heads, valid_s)
    local_nnz = jnp.sum(heads.astype(jnp.int32))
    return c_row, c_col, c_val, heads, local_nnz


def _compress_tail(c_row, c_col, c_val, heads, val_mask, local_nnz,
                   nnz_cap: int, rps: int, col_dtype):
    """Shared ESC compression: scatter-add run values into the padded
    (nnz_cap,) output and gather run-head coordinates.  ``val_mask``
    selects the product slots whose values may contribute (invalid
    sentinel slots — and, on the 2-d path, any slot outside the
    device's output block — add 0 wherever their clipped segment id
    lands)."""
    seg = jnp.clip(jnp.cumsum(heads.astype(jnp.int32)) - 1, 0,
                   nnz_cap - 1)
    out_vals = jnp.zeros((nnz_cap,), c_val.dtype).at[seg].add(
        jnp.where(val_mask, c_val, jnp.zeros((), c_val.dtype))
    )
    head_idx = jnp.nonzero(heads, size=nnz_cap, fill_value=0)[0]
    slot = jnp.arange(nnz_cap, dtype=jnp.int32)
    pad = slot >= local_nnz
    out_cols = jnp.where(pad, 0, c_col[head_idx]).astype(col_dtype)
    out_rows = jnp.where(
        pad, max(rps - 1, 0), c_row[head_idx]
    ).astype(jnp.int32)
    out_vals = jnp.where(pad, jnp.zeros((), c_val.dtype), out_vals)
    return out_vals, out_cols, out_rows


def _dist_band_spgemm(A: DistCSR, B: DistCSR):
    """C = A @ B for exactly-banded square operands: nd_a*nd_b shifted
    multiplies on the row-indexed per-shard DIA blocks, with B's rows
    realized by a ``ppermute`` halo exchange — no all_gather, no
    expansion, no sort.  The distributed rendition of
    ``ops.dia_ops.dia_spgemm``.

    Returns a DIA-layout DistCSR (ELL blocks included, same assembly as
    ``dist_diags``), or None when the preconditions don't hold (not
    exact bands, band too wide for halo mode, pattern not provably
    equal to the structural product).
    """
    from ..ops.dia_ops import (
        band_cover, band_product_is_full, band_product_offsets,
    )
    from ..settings import settings

    if (
        A.dia_data is None or B.dia_data is None
        or A.dia_mask is not None or B.dia_mask is not None
        or A.shape[0] != A.shape[1] or B.shape[0] != B.shape[1]
        or A.rows_per_shard != B.rows_per_shard
    ):
        return None
    n = A.shape[0]
    rps = A.rows_per_shard
    offs_a, offs_b = A.dia_offsets, B.dia_offsets
    offs_c = band_product_offsets(offs_a, offs_b)
    nnz_c = band_cover(offs_c, (n, n), n)
    h = max(abs(o) for o in offs_a)          # B-row reach of the product
    halo_c = max(abs(o) for o in offs_c)     # halo of the result matrix
    if (
        h > rps or halo_c > rps
        or len(offs_c) > settings.dia_max_diags
        or len(offs_c) * n > settings.dia_max_expand * max(nnz_c, 1)
        or not band_product_is_full(offs_a, offs_b, offs_c,
                                    A.shape, B.shape)
    ):
        return None

    fn = _band_spgemm_fn(A.mesh, offs_a, offs_b, offs_c, n, rps, h,
                         halo_c)
    data, cols_b, counts, dia_data = fn(A.dia_data, B.dia_data)
    from .dist_csr import attach_dia_prepack

    return attach_dia_prepack(DistCSR(
        data=data, cols=cols_b, counts=counts, row_ids=None,
        shape=(n, n), rows_per_shard=rps, halo=halo_c, ell=True,
        mesh=A.mesh, dia_data=dia_data, dia_offsets=offs_c,
        nnz_hint=nnz_c,
    ))


@lru_cache(maxsize=128)
def _band_spgemm_fn(mesh, offs_a, offs_b, offs_c, n, rps, h, halo_c):
    """Cached shard_map callable for the banded product (fresh closures
    would re-trace/recompile on every call — same reasoning as
    ``dist_csr._dia_spmv_fn``)."""
    _obs.inc("jit_miss.dist_spgemm.band_spgemm_fn")
    nd_c = len(offs_c)
    idx_c = {o: i for i, o in enumerate(offs_c)}
    offs_c_dev = jnp.asarray(offs_c, dtype=index_dtype())

    def kernel(a_blk, b_blk):
        a = a_blk[0]                               # (nd_a, rps)
        b = b_blk[0]                               # (nd_b, rps)
        # Halo-extend B's rows (axis 1) from ring neighbors.  Ring wrap
        # at the global edges multiplies against A's out-of-range zeros
        # (exact-band blocks are 0 there by construction), so wrapped
        # values never reach the result.
        from .dist_csr import _extend_x

        b_ext = _extend_x(b, h, axis=1)
        C = jnp.zeros((nd_c, rps), dtype=jnp.result_type(a.dtype, b.dtype))
        for a_i, oa in enumerate(offs_a):
            for b_i, ob in enumerate(offs_b):
                seg = jax.lax.slice_in_dim(
                    b_ext[b_i], h + oa, h + oa + rps
                )
                C = C.at[idx_c[oa + ob]].add(a[a_i] * seg)
        # ELL assembly: the product band is full, so per-row counts and
        # cols follow from the offsets alone (shared helper with
        # dist_diags — one source of truth for the slot conventions).
        from .dist_build import band_ell_local

        shard = jax.lax.axis_index(ROW_AXIS)
        start = shard.astype(index_dtype()) * rps
        r_l = jnp.arange(rps, dtype=index_dtype())
        r = start + r_l
        ell_data, ell_cols, cnt = band_ell_local(
            C, offs_c_dev, n, rps, halo_c, start, r, r_l
        )
        return ell_data[None], ell_cols[None], cnt[None], C[None]

    out_specs = (P(ROW_AXIS, None, None), P(ROW_AXIS, None, None),
                 P(ROW_AXIS, None), P(ROW_AXIS, None, None))
    return jax.jit(shard_map(
        kernel, mesh=mesh,
        in_specs=(P(ROW_AXIS, None, None), P(ROW_AXIS, None, None)),
        out_specs=out_specs, check_vma=False,
    ))


def _b_realization_volumes(B: DistCSR, lb: _Layout, plan):
    """Predicted interconnect volumes for realizing B across the three
    ESC phases, for BOTH candidate realizations — the evidence behind
    the window-vs-all_gather choice.

    Returns ``(ag_vols, ag_calls, win_vols, win_calls)``: per-
    collective byte dicts + collective-op counts, with the window pair
    None when no accepted plan exists (declined / precise layout /
    R <= 2).  Per-phase arrays mirror ``_esc_t_fn`` (phase 1 rotates
    or gathers only counts/row_ids) and ``_b_global_flat`` /
    ``_b_window_flat`` (phases 2-3 move the full operand set)."""
    from ..obs import comm as _comm

    R = lb.num_shards
    item_d = np.dtype(B.data.dtype).itemsize
    item_c = np.dtype(B.cols.dtype).itemsize
    if lb.ell:
        data_b = lb.rps * lb.inner * item_d
        cols_b = lb.rps * lb.inner * item_c
        cnt_b = lb.rps * 4
        rid_b = 0
    else:
        data_b = lb.inner * item_d
        cols_b = lb.inner * item_c
        cnt_b = 4                       # (R,) per-shard totals
        rid_b = lb.inner * 4
    ggl_b = 0
    if lb.has_ggl:
        g = B.gather_globals
        ggl_b = (int(g.shape[1]) * int(g.shape[2])
                 * np.dtype(g.dtype).itemsize)

    phase1_b = cnt_b + rid_b
    phase1_n = 1 if lb.ell else 2
    phase23_b = data_b + cols_b + cnt_b + rid_b + ggl_b
    phase23_n = (3 if lb.ell else 4) + (1 if lb.has_ggl else 0)

    ag_vols = {"all_gather": _comm.all_gather_bytes(
        phase1_b + 2 * phase23_b, 1, R)}
    ag_calls = {"all_gather": phase1_n + 2 * phase23_n}

    win_vols = win_calls = None
    if plan is not None:
        _, d_fwd, d_bwd = plan
        rounds = d_fwd + d_bwd
        # Window phases never move ggl (precise layouts decline the
        # window) and phase-1 csr rotations carry row_ids too.
        w_phase1_b = cnt_b + rid_b
        w_phase23_b = data_b + cols_b + cnt_b + rid_b
        win_vols = {"ppermute": _comm.ppermute_bytes(
            w_phase1_b + 2 * w_phase23_b, 1, R, rounds=rounds)}
        win_calls = {"ppermute": rounds * (phase1_n + 2 * phase23_n)}
    return ag_vols, ag_calls, win_vols, win_calls


# ------------------------------------------------------------------ 2-D --
# SUMMA-style SpGEMM over 2-d-block operands (docs/DIST.md): device
# (i, j) owns C block (i, j) = sum_k A(i, k) @ B(k, j), so it realizes
# its A ROW panel (all_gather along the mesh COLUMN axis — each A
# element reaches Rc-1 receivers) and its B COLUMN panel (staged along
# the mesh ROW axis — each B element reaches Rr-1 receivers, ledgered
# as the ``bcast`` kind), then runs the SAME local ESC as the 1-D
# kernel.  No product triple ever crosses the interconnect: every
# partial product lands in the block that owns it, which is what makes
# the 2-d layout communication-avoiding for SpGEMM (vs the 1-D path's
# N-1-receiver all_gather of all of B).


def _a_row_panel_flat(cps_a: int, data, cols, row_ids, counts):
    """Gather this device's A row panel along the mesh column axis and
    expose it as one flat (a_row, a_col, a_val, a_valid) quad: rows
    stay BLOCK-local (every block of the row group shares the row
    range), columns rebase to the global [0, cols_padded) domain via
    each source block's column offset."""
    data_g = jax.lax.all_gather(data, COL_AXIS)       # (Rc, capA)
    cols_g = jax.lax.all_gather(cols, COL_AXIS)
    rids_g = jax.lax.all_gather(row_ids, COL_AXIS)
    counts_g = jax.lax.all_gather(counts, COL_AXIS)   # (Rc,)
    cap = data.shape[-1]
    slot = jnp.arange(cap, dtype=jnp.int32)
    a_valid = (slot[None, :] < counts_g[:, None]).reshape(-1)
    off = jnp.arange(cols_g.shape[0], dtype=index_dtype()) * cps_a
    a_col = (cols_g.astype(index_dtype()) + off[:, None]).reshape(-1)
    a_row = rids_g.reshape(-1)
    a_val = data_g.reshape(-1)
    return a_row, a_col, a_val, a_valid


_GRID_SPECS = (P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS, None),
               P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS))


@lru_cache(maxsize=128)
def _esc2d_t_fn(mesh, cps_a: int, rps_b: int):
    """Cached 2-d phase-1 (product count) shard_map: realizes only the
    structural halves of both panels (A cols+counts along mesh cols,
    B row_ids+counts along mesh rows)."""
    _obs.inc("jit_miss.dist_spgemm.esc2d_t_fn")
    in_specs = (P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS),
                P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS))

    def t_kernel(a_cols, a_counts, b_rids, b_counts):
        ac, act = a_cols[0, 0], a_counts[0, 0]
        cols_g = jax.lax.all_gather(ac, COL_AXIS)
        cnts_g = jax.lax.all_gather(act, COL_AXIS)
        slot = jnp.arange(ac.shape[-1], dtype=jnp.int32)
        a_valid = (slot[None, :] < cnts_g[:, None]).reshape(-1)
        off = jnp.arange(cols_g.shape[0], dtype=index_dtype()) * cps_a
        a_col = (cols_g.astype(index_dtype()) + off[:, None]).reshape(-1)

        br, bct = b_rids[0, 0], b_counts[0, 0]
        rid_g = jax.lax.all_gather(br, ROW_AXIS)      # (Rr, capB)
        cnt_g = jax.lax.all_gather(bct, ROW_AXIS)     # (Rr,)
        slotb = jnp.arange(br.shape[-1], dtype=jnp.int32)
        validb = slotb[None, :] < cnt_g[:, None]
        ids_2d = jnp.where(validb, rid_g, rps_b)
        one = jnp.ones_like(ids_2d, dtype=index_dtype())
        percount = jax.vmap(
            lambda ids, on: jax.ops.segment_sum(
                on, ids, num_segments=rps_b + 1
            )
        )(ids_2d, one)[:, :rps_b]
        b_cnt = percount.reshape(-1)                  # (rows_padded(B),)
        b_row = jnp.clip(a_col, 0, b_cnt.shape[0] - 1)
        t_local = jnp.sum(
            jnp.where(a_valid, b_cnt[b_row], 0), dtype=index_dtype()
        )
        return t_local[None, None]

    return jax.jit(shard_map(
        t_kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False,
    ))


@lru_cache(maxsize=128)
def _esc2d_nnz_fn(mesh, lb2: _Layout, cps_a: int, rps_a: int,
                  T_cap: int):
    """Cached 2-d phase-2 (output nnz) shard_map."""
    _obs.inc("jit_miss.dist_spgemm.esc2d_nnz_fn")
    in_specs = _GRID_SPECS + _GRID_SPECS
    n_cols = lb2.shape[1]

    def nnz_kernel(ad, ac, ar, act, bd, bc, br, bct):
        a_flat = _a_row_panel_flat(
            cps_a, ad[0, 0], ac[0, 0], ar[0, 0], act[0, 0]
        )
        b_args = _b_global_flat(lb2, bd[0, 0], bc[0, 0], bct[0, 0],
                                br[0, 0])
        *_, local_nnz = _expand_sorted_flat(
            a_flat, b_args, T_cap, n_cols, rps_a
        )
        return local_nnz[None, None]

    return jax.jit(shard_map(
        nnz_kernel, mesh=mesh, in_specs=in_specs,
        out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False,
    ))


@lru_cache(maxsize=128)
def _esc2d_numeric_fn(mesh, lb2: _Layout, cps_a: int, rps_a: int,
                      T_cap: int, nnz_cap: int):
    """Cached 2-d phase-3 (numeric) shard_map.  Output cols stay
    BLOCK-local: the realized B panel carries block-local columns and
    C block (i, j) inherits B block j's column range exactly."""
    from ..types import coord_dtype_for

    _obs.inc("jit_miss.dist_spgemm.esc2d_numeric_fn")
    in_specs = _GRID_SPECS + _GRID_SPECS
    n_cols = lb2.shape[1]
    col_dtype = coord_dtype_for(n_cols)

    def numeric_kernel(ad, ac, ar, act, bd, bc, br, bct):
        a_flat = _a_row_panel_flat(
            cps_a, ad[0, 0], ac[0, 0], ar[0, 0], act[0, 0]
        )
        b_args = _b_global_flat(lb2, bd[0, 0], bc[0, 0], bct[0, 0],
                                br[0, 0])
        c_row, c_col, c_val, heads, local_nnz = _expand_sorted_flat(
            a_flat, b_args, T_cap, n_cols, rps_a
        )
        out_vals, out_cols, out_rows = _compress_tail(
            c_row, c_col, c_val, heads, c_row < rps_a, local_nnz,
            nnz_cap, rps_a, col_dtype,
        )
        return (out_vals[None, None], out_cols[None, None],
                out_rows[None, None], local_nnz[None, None])

    out_specs = (P(ROW_AXIS, COL_AXIS, None),
                 P(ROW_AXIS, COL_AXIS, None),
                 P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS))
    return jax.jit(shard_map(
        numeric_kernel, mesh=mesh, in_specs=in_specs,
        out_specs=out_specs, check_vma=False,
    ))


def _summa_volumes_2d(A: DistCSR, B: DistCSR, grid):
    """Predicted interconnect volumes of the three 2-d ESC phases from
    the static block shapes: A row panels (``all_gather`` along mesh
    columns — Rr groups of Rc) and B column panels (``bcast`` staging
    along mesh rows — Rc groups of Rr).  Phase 1 moves only the
    structural halves; phases 2-3 move the full operand sets."""
    from ..obs import comm as _comm

    Rr, Rc = grid
    capA = int(A.data.shape[-1])
    capB = int(B.data.shape[-1])
    ia_d = np.dtype(A.data.dtype).itemsize
    ia_c = np.dtype(A.cols.dtype).itemsize
    ib_d = np.dtype(B.data.dtype).itemsize
    ib_c = np.dtype(B.cols.dtype).itemsize
    a1 = Rr * _comm.all_gather_bytes(capA * ia_c + 4, 1, Rc)
    a23 = Rr * _comm.all_gather_bytes(
        capA * (ia_d + ia_c + 4) + 4, 1, Rc)
    b1 = Rc * _comm.all_gather_bytes(capB * 4 + 4, 1, Rr)
    b23 = Rc * _comm.all_gather_bytes(
        capB * (ib_d + ib_c + 4) + 4, 1, Rr)
    vols = {"all_gather": a1 + 2 * a23, "bcast": b1 + 2 * b23}
    calls = {"all_gather": 2 + 2 * 4, "bcast": 2 + 2 * 4}
    vols = {k: v for k, v in vols.items() if v > 0}
    return vols, {k: calls[k] for k in vols}


def _dist_spgemm_2d(A: DistCSR, B: DistCSR) -> DistCSR:
    """C = A @ B for 2-d-block operands on a shared grid; returns a
    2-d-block C on the same grid (rows from A's row blocks, columns
    from B's column blocks — directly consumable by the 2-d SpMV or a
    further SUMMA product)."""
    from ..obs import comm as _comm
    from ..obs import memory as _mem
    from ..types import coord_dtype_for
    from .dist_csr import _device_put_sharded

    mesh = A.mesh
    Rr, Rc = A.grid
    N = Rr * Rc
    rps = A.rows_per_shard
    m, n_cols = A.shape[0], B.shape[1]
    col_dtype = coord_dtype_for(n_cols)
    # The gathered B panel has exactly the ``_b_global_flat`` shape
    # contract over the mesh-row group: Rr source blocks of rps_b rows
    # each, scalar per-block counts, block-local row ids — so the 1-D
    # realization helper is reused verbatim with this synthetic layout.
    lb2 = _Layout(
        ell=False, rps=B.rows_per_shard, halo=-1, cps=0, has_ggl=False,
        shape=B.shape, rows_padded=Rr * B.rows_per_shard,
        num_shards=Rr, inner=int(B.data.shape[-1]),
    )
    _obs.inc("dist_spgemm.realization.2d_panel")
    vols, calls = _summa_volumes_2d(A, B, A.grid)
    comm_bytes = _comm.record("dist_spgemm", vols, calls,
                              layout=A.layout)
    # Evidence: the 1-D counterfactual at the same device count — a
    # perfectly balanced all_gather realization of B over N row shards
    # (inner = ceil(nnz/N)), priced by the same per-phase formula as
    # ``_b_realization_volumes``.
    nnzb = B.nnz_hint
    if nnzb < 0:
        nnzb = B.global_nnz
        B.nnz_hint = nnzb
    inner1 = max(-(-nnzb // N), 1)
    ag1d = _comm.all_gather_bytes(
        (4 + inner1 * 4)
        + 2 * (inner1 * (np.dtype(B.data.dtype).itemsize
                         + np.dtype(B.cols.dtype).itemsize + 4) + 4),
        1, N)
    _obs.event(
        "dist_spgemm.realization", choice="2d-panel", shards=N,
        grid=A.grid, predicted_bytes=comm_bytes,
        predicted_all_gather_bytes=ag1d, predicted_window_bytes=None,
    )
    a_arrays = (A.data, A.cols, A.row_ids, A.counts)
    b_arrays = (B.data, B.cols, B.row_ids, B.counts)
    with _lat.timer("lat.dist_spgemm." + _lat.shape_bucket(m)), \
            _obs.span("dist_spgemm", shards=N, m=m, n=n_cols,
                      b_realization="2d-panel", b_plan=(),
                      comm_bytes=comm_bytes,
                      comm_calls=sum(calls.values())) as sp:
        t_locals = _esc2d_t_fn(mesh, A.cols_per_shard,
                               B.rows_per_shard)(
            A.cols, A.counts, B.row_ids, B.counts)
        _obs.inc("transfer.host_sync.dist_spgemm_T")
        T_cap = int(jnp.max(t_locals))
        val_dtype = jnp.result_type(A.data.dtype, B.data.dtype)
        if T_cap == 0:
            from jax.sharding import NamedSharding

            z3 = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS, None))
            z2 = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))
            return DistCSR(
                data=_device_put_sharded(
                    jnp.zeros((Rr, Rc, 1), val_dtype), z3),
                cols=_device_put_sharded(
                    jnp.zeros((Rr, Rc, 1), col_dtype), z3),
                counts=_device_put_sharded(
                    jnp.zeros((Rr, Rc), jnp.int32), z2),
                row_ids=_device_put_sharded(
                    jnp.full((Rr, Rc, 1), max(rps - 1, 0), jnp.int32),
                    z3),
                shape=(m, n_cols), rows_per_shard=rps, halo=-1,
                ell=False, mesh=mesh,
                cols_per_shard=B.cols_per_shard, nnz_hint=0,
                layout=A.layout, grid=A.grid,
            )

        nnz_locals = _esc2d_nnz_fn(
            mesh, lb2, A.cols_per_shard, rps, T_cap
        )(*a_arrays, *b_arrays)
        _obs.inc("transfer.host_sync.dist_spgemm_nnz")
        nnz_cap = max(int(jnp.max(nnz_locals)), 1)
        nnz_total = int(jnp.sum(nnz_locals)) if _obs.enabled() else -1
        if sp is not None:
            sp.set(T_cap=T_cap, nnz_cap=nnz_cap, nnz=nnz_total)

        item_d = np.dtype(val_dtype).itemsize
        out_mb = N * nnz_cap * (item_d + np.dtype(col_dtype).itemsize
                                + 4) / 2**20
        expand_mb = N * T_cap * (item_d + 2 * np.dtype(
            index_dtype()).itemsize) / 2**20
        with _mem.watermark("dist_spgemm", T_cap=T_cap,
                            nnz_cap=nnz_cap, nnz=nnz_total,
                            out_mb=round(out_mb, 2),
                            expand_mb=round(expand_mb, 2)):
            vals_b, cols_b, rids_b, counts_b = _esc2d_numeric_fn(
                mesh, lb2, A.cols_per_shard, rps, T_cap, nnz_cap
            )(*a_arrays, *b_arrays)

    # cols_padded(C) == cols_padded(B): same global width, same
    # multiple-of-N padding convention — so C inherits B's column
    # blocking and stays a first-class 2-d operand.
    return DistCSR(
        data=vals_b, cols=cols_b, counts=counts_b.astype(jnp.int32),
        row_ids=rids_b, shape=(m, n_cols), rows_per_shard=rps,
        halo=-1, ell=False, mesh=mesh,
        cols_per_shard=B.cols_per_shard, nnz_hint=nnz_total,
        layout=A.layout, grid=A.grid,
    )


# Static (entry point, layout, realization) catalog of this module's
# contract-bearing lowered program families — the SpGEMM counterpart
# of ``dist_csr.DIST_PLAN_SHAPES`` (same consumers: ``tools/verify``
# and the sparselint plan-contract rule; same rule: a new dispatch
# branch grows this tuple and must commit a contract).  The contracted
# program per triple is the phase-1 product-count shard_map — the
# phase whose collective realization choice (window ppermute vs B
# all_gather vs 2-d panel staging) the later phases inherit.
SPGEMM_PLAN_SHAPES = (
    ("dist_spgemm", "1d-row", "all_gather"),
    ("dist_spgemm", "2d-block", "panel"),
)


def dist_spgemm(A: DistCSR, B: DistCSR) -> DistCSR:
    """C = A @ B, both row-block distributed; returns a row-block C.

    Exactly-banded square operands take the gather-free banded fast
    path (``_dist_band_spgemm``: shifted multiplies + ppermute halo —
    no all_gather of B); everything else runs the general collective
    ESC.  Differentially tested against scipy on the 8-device CPU mesh
    (``tests/test_dist_spgemm.py``), including the GMG Galerkin
    triple product R @ A @ P.

    Resilience (``LEGATE_SPARSE_TPU_RESIL``, docs/RESILIENCE.md): the
    whole multiply is the ``dist.spgemm`` site — SpGEMM is a driver of
    eager collective phases with host syncs between them, so a
    transient failure in any phase retries the multiply from its
    immutable inputs (bit-identical on success).
    """
    from ..resilience import guarded_call as _resil_guarded
    from ..settings import settings as _rsettings

    if _rsettings.resil:
        return _resil_guarded("dist.spgemm",
                              lambda: _dist_spgemm_impl(A, B))
    return _dist_spgemm_impl(A, B)


def _dist_spgemm_impl(A: DistCSR, B: DistCSR) -> DistCSR:
    if A.shape[1] != B.shape[0]:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")
    if A.mesh is not B.mesh and A.mesh != B.mesh:
        raise ValueError("operands must share a mesh")
    _obs.inc("op.dist_spgemm")
    from ..obs import comm as _comm

    if A.grid is not None or B.grid is not None:
        if A.grid is None or B.grid is None or A.grid != B.grid:
            raise ValueError(
                f"dist_spgemm: operands must share one 2-d grid "
                f"(got {A.grid} and {B.grid}); reshard with the same "
                f"layout"
            )
        return _dist_spgemm_2d(A, B)

    with _obs.span("dist_spgemm.band_probe"):
        C_band = _dist_band_spgemm(A, B)
    if C_band is not None:
        _obs.inc("dist_spgemm.realization.band")
        # Band realization moves only B's halo-extended DIA rows: one
        # two-sided exchange of (nd_b, h) slices — no all_gather, no
        # expansion.  That byte count IS the evidence for taking the
        # banded path.
        h = max(abs(int(o)) for o in A.dia_offsets)
        nd_b = len(B.dia_offsets)
        band_vols = {"ppermute": _comm.halo_exchange_bytes(
            nd_b * h, np.dtype(B.dtype).itemsize, A.num_shards)}
        band_bytes = _comm.record("dist_spgemm", band_vols,
                                  layout=A.layout)
        _obs.event("dist_spgemm.realization", choice="band",
                   shards=A.num_shards, predicted_bytes=band_bytes)
        return C_band
    A._require_blocks("dist_spgemm")
    B._require_blocks("dist_spgemm")
    if A.rows_padded < A.shape[0] or B.rows_padded < B.shape[0]:
        raise AssertionError("padded row invariant violated")
    # Padded B rows have count 0 everywhere (shard_csr invariant), so
    # they contribute no products even though A cols never index them.

    from ..types import coord_dtype_for

    mesh = A.mesh
    rps = A.rows_per_shard
    m, n_cols = A.shape[0], B.shape[1]
    col_dtype = coord_dtype_for(n_cols)
    la, lb = _layout_of(A), _layout_of(B)

    # Absent layout fields (ELL has no row_ids; only precise layouts
    # carry gather_globals) ride along as (R, 1) zero blocks so every
    # kernel arg shards uniformly on the row axis.
    R = A.num_shards
    placeholder = jnp.zeros((R, 1), dtype=jnp.int32)

    def arrays_of(M):
        return (
            M.data, M.cols,
            M.counts if M.counts is not None else placeholder,
            M.row_ids if M.row_ids is not None else placeholder,
            M.gather_globals if M.gather_globals is not None
            else placeholder,
        )

    a_arrays = arrays_of(A)
    b_arrays = arrays_of(B)

    # B-realization window plan (the reference's min/max column image of
    # A, ``csr.py:640-666``): gather only the B row blocks each shard's
    # A columns reach, via ring ppermute — None falls back to the full
    # all_gather when the window is dense or B is precise-layout.  Only
    # the static shape triple enters the phase-fn cache keys; the
    # per-shard window starts ride as a traced operand.
    global LAST_B_REALIZATION, LAST_B_PLAN
    win = _b_window_plan(A, la, lb, a_arrays)
    if win is not None:
        first_blks, plan = win
        first_dev = (_put_blocks(jnp.asarray(first_blks), mesh),)
        realization = "window"
        b_plan = (tuple(int(f) for f in first_blks), *plan)
    else:
        plan = None
        first_dev = ()
        realization = "all_gather"
        b_plan = ()
    with _STATE_LOCK:
        # Written as a pair under the lock.  Concurrent readers who
        # need the pair to be mutually consistent must read through
        # ``last_b_realization()`` (which takes the same lock); bare
        # reads of either global alone stay safe (single attribute).
        LAST_B_REALIZATION = realization
        LAST_B_PLAN = b_plan
    _obs.inc("dist_spgemm.realization." + realization)
    # Evidence for the realization choice: predicted interconnect
    # bytes of BOTH candidates from the static shard shapes, the
    # chosen one entering the comm ledger.  (The window prediction
    # exists only when a plan was accepted — a declined probe never
    # computed ring distances.)
    ag_vols, ag_calls, win_vols, win_calls = _b_realization_volumes(
        B, lb, plan)
    if win is not None:
        comm_bytes = _comm.record("dist_spgemm", win_vols, win_calls,
                                  layout=A.layout)
        comm_calls = sum(win_calls.values())
    else:
        comm_bytes = _comm.record("dist_spgemm", ag_vols, ag_calls,
                                  layout=A.layout)
        comm_calls = sum(ag_calls.values())
    _obs.event(
        "dist_spgemm.realization", choice=realization,
        shards=R, predicted_bytes=comm_bytes,
        predicted_all_gather_bytes=_comm.total(ag_vols),
        predicted_window_bytes=(_comm.total(win_vols)
                                if win_vols is not None else None),
    )
    with _lat.timer("lat.dist_spgemm." + _lat.shape_bucket(m)), \
            _obs.span("dist_spgemm", shards=R, m=m, n=n_cols,
                      b_realization=realization,
                      b_plan=b_plan, comm_bytes=comm_bytes,
                      comm_calls=comm_calls) as sp:
        return _dist_spgemm_phases(
            A, B, mesh, la, lb, plan, a_arrays, b_arrays, first_dev,
            rps, m, n_cols, col_dtype, R, sp,
        )


def _dist_spgemm_phases(A, B, mesh, la, lb, plan, a_arrays, b_arrays,
                        first_dev, rps, m, n_cols, col_dtype, R, sp):
    """The three collective ESC phases (split out so the realization
    span covers them; ``sp`` is the live span, or None when tracing
    is disabled)."""
    # ---- phase 1: T_local ------------------------------------------------
    t_locals = _esc_t_fn(mesh, la, lb, plan)(
        *a_arrays, *b_arrays, *first_dev
    )
    _obs.inc("transfer.host_sync.dist_spgemm_T")
    T_cap = int(jnp.max(t_locals))

    val_dtype = jnp.result_type(A.data.dtype, B.data.dtype)
    if T_cap == 0:
        return DistCSR(
            data=_put_blocks(jnp.zeros((R, 1), val_dtype), mesh),
            cols=_put_blocks(jnp.zeros((R, 1), col_dtype), mesh),
            counts=_put_blocks(jnp.zeros((R,), jnp.int32), mesh),
            row_ids=_put_blocks(
                jnp.full((R, 1), max(rps - 1, 0), jnp.int32), mesh
            ),
            shape=(m, n_cols), rows_per_shard=rps, halo=-1, ell=False,
            mesh=mesh, nnz_hint=0,
        )

    # ---- phase 2: nnz_local ---------------------------------------------
    nnz_locals = _esc_nnz_fn(mesh, la, lb, T_cap, plan)(
        *a_arrays, *b_arrays, *first_dev
    )
    _obs.inc("transfer.host_sync.dist_spgemm_nnz")
    # Device-side reductions only: fetching the P(ROW_AXIS)-sharded
    # nnz_locals itself (np.asarray) is illegal in multi-controller
    # runs — same pitfall documented at _col_window_fn.  The reduced
    # scalars are replicated and always fetchable.
    nnz_cap = max(int(jnp.max(nnz_locals)), 1)
    # The exact output nnz costs one more blocking scalar fetch —
    # tracing mode only (the default path must not grow a host sync;
    # over the TPU tunnel each one is ~1 s).  Without it the result's
    # nnz_hint stays -1 and the decline key's lazy ``global_nnz``
    # fallback pays once, memoized on the instance.
    nnz_total = int(jnp.sum(nnz_locals)) if _obs.enabled() else -1
    if sp is not None:
        sp.set(T_cap=T_cap, nnz_cap=nnz_cap, nnz=nnz_total)

    # ---- phase 3: numeric ------------------------------------------------
    # Output-nnz blowup becomes a recorded number, not an OOM: the
    # watermark event carries the predicted padded allocation next to
    # the realized RSS delta.
    from ..obs import memory as _mem

    item_d = np.dtype(jnp.result_type(A.data.dtype,
                                      B.data.dtype)).itemsize
    out_mb = R * nnz_cap * (item_d + np.dtype(col_dtype).itemsize
                            + 4) / 2**20
    expand_mb = R * T_cap * (item_d + 2 * np.dtype(
        index_dtype()).itemsize) / 2**20
    with _mem.watermark("dist_spgemm", T_cap=T_cap, nnz_cap=nnz_cap,
                        nnz=nnz_total, out_mb=round(out_mb, 2),
                        expand_mb=round(expand_mb, 2)):
        vals_b, cols_b, rids_b, counts_b = _esc_numeric_fn(
            mesh, la, lb, T_cap, nnz_cap, plan
        )(*a_arrays, *b_arrays, *first_dev)

    return DistCSR(
        data=vals_b, cols=cols_b, counts=counts_b.astype(jnp.int32),
        row_ids=rids_b, shape=(m, n_cols), rows_per_shard=rps,
        halo=-1, ell=False, mesh=mesh, nnz_hint=nnz_total,
    )


def _esc_specs(L: _Layout):
    """in_specs ndims for (data, cols, counts, row_ids, ggl) blocks of a
    layout (placeholders are (R, 1), i.e. 2-D)."""
    data_nd = 3 if L.ell else 2
    counts_nd = 2 if L.ell else 1
    ggl_nd = 3 if L.has_ggl else 2
    return tuple(
        P(ROW_AXIS, *([None] * (k - 1)))
        for k in (data_nd, data_nd, counts_nd, 2, ggl_nd)
    )


def _local(args):
    # Inside shard_map each (R, ...) axis-0-sharded block arrives as a
    # (1, ...) slice — index [0] for the local block (same convention as
    # dist_spmv).
    return tuple(x[0] for x in args)


@lru_cache(maxsize=128)
def _esc_t_fn(mesh, la: _Layout, lb: _Layout, plan=None):
    """Cached phase-1 (product count) shard_map (structure-keyed, see
    ``_Layout``; fresh closures per call would recompile every time).
    ``plan`` is the static window-shape triple or None — the per-shard
    window starts ride as a traced trailing operand, not a cache key."""
    _obs.inc("jit_miss.dist_spgemm.esc_t_fn")
    in_specs = _esc_specs(la) + _esc_specs(lb)
    if plan is not None:
        in_specs = in_specs + (P(ROW_AXIS),)

    def t_kernel(*args):
        if plan is not None:
            a_args, b_args_raw, first = args[:5], args[5:10], args[10]
        else:
            a_args, b_args_raw = args[:5], args[5:]
        a_row, a_col, a_val, a_valid = _a_local_flat(la, *_local(a_args))
        if plan is not None:
            *_, b_counts, row_base = _b_window_flat(
                lb, plan, first[0], *_local(b_args_raw),
                counts_only=True
            )
            b_row = jnp.clip(a_col - row_base, 0,
                             b_counts.shape[0] - 1)
            t_local = jnp.sum(
                jnp.where(a_valid, b_counts[b_row], 0),
                dtype=index_dtype(),
            )
            return t_local[None]
        counts = _local(b_args_raw)[2]
        rid = _local(b_args_raw)[3]
        counts_g = jax.lax.all_gather(counts, ROW_AXIS)
        if lb.ell:
            b_counts = counts_g.reshape(lb.rows_padded).astype(index_dtype())
        else:
            rid_g = jax.lax.all_gather(rid, ROW_AXIS)
            nnz_max = lb.inner
            slot = jnp.arange(nnz_max, dtype=jnp.int32)
            valid = slot[None, :] < counts_g[:, None]
            ids_2d = jnp.where(valid, rid_g, lb.rps)
            one = jnp.ones_like(ids_2d, dtype=index_dtype())
            percount = jax.vmap(
                lambda ids, on: jax.ops.segment_sum(
                    on, ids, num_segments=lb.rps + 1
                )
            )(ids_2d, one)[:, : lb.rps]
            b_counts = percount.reshape(lb.rows_padded)
        t_local = jnp.sum(
            jnp.where(a_valid, b_counts[a_col], 0), dtype=index_dtype()
        )
        return t_local[None]

    return jax.jit(shard_map(
        t_kernel, mesh=mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    ))


@lru_cache(maxsize=128)
def _esc_nnz_fn(mesh, la: _Layout, lb: _Layout, T_cap: int,
                plan=None):
    """Cached phase-2 (output nnz) shard_map."""
    _obs.inc("jit_miss.dist_spgemm.esc_nnz_fn")
    in_specs = _esc_specs(la) + _esc_specs(lb)
    if plan is not None:
        in_specs = in_specs + (P(ROW_AXIS),)
    n_cols = lb.shape[1]

    def nnz_kernel(*args):
        if plan is None:
            a_args, b_args_raw = args[:5], args[5:]
            b_args = _b_global_flat(lb, *_local(b_args_raw))
            row_base = 0
        else:
            a_args, b_args_raw, first = args[:5], args[5:10], args[10]
            *b_args, row_base = _b_window_flat(
                lb, plan, first[0], *_local(b_args_raw)
            )
        *_, local_nnz = _expand_sorted(
            la, _local(a_args), tuple(b_args), T_cap, n_cols,
            row_base=row_base,
        )
        return local_nnz[None]

    return jax.jit(shard_map(
        nnz_kernel, mesh=mesh, in_specs=in_specs, out_specs=P(ROW_AXIS),
        check_vma=False,
    ))


@lru_cache(maxsize=128)
def _esc_numeric_fn(mesh, la: _Layout, lb: _Layout, T_cap: int,
                    nnz_cap: int, plan=None):
    """Cached phase-3 (numeric) shard_map."""
    from ..types import coord_dtype_for

    _obs.inc("jit_miss.dist_spgemm.esc_numeric_fn")
    in_specs = _esc_specs(la) + _esc_specs(lb)
    if plan is not None:
        in_specs = in_specs + (P(ROW_AXIS),)
    n_cols = lb.shape[1]
    col_dtype = coord_dtype_for(n_cols)
    rps = la.rps

    def numeric_kernel(*args):
        if plan is None:
            a_args, b_args_raw = args[:5], args[5:]
            b_args = _b_global_flat(lb, *_local(b_args_raw))
            row_base = 0
        else:
            a_args, b_args_raw, first = args[:5], args[5:10], args[10]
            *b_args, row_base = _b_window_flat(
                lb, plan, first[0], *_local(b_args_raw)
            )
        c_row, c_col, c_val, heads, local_nnz = _expand_sorted(
            la, _local(a_args), tuple(b_args), T_cap, n_cols,
            row_base=row_base,
        )
        out_vals, out_cols, out_rows = _compress_tail(
            c_row, c_col, c_val, heads, c_row < rps, local_nnz,
            nnz_cap, rps, col_dtype,
        )
        return (out_vals[None], out_cols[None], out_rows[None],
                local_nnz[None])

    out_specs = (P(ROW_AXIS, None), P(ROW_AXIS, None), P(ROW_AXIS, None),
                 P(ROW_AXIS))
    return jax.jit(shard_map(
        numeric_kernel, mesh=mesh, in_specs=in_specs,
        out_specs=out_specs, check_vma=False,
    ))


def _put_blocks(arr, mesh):
    from jax.sharding import NamedSharding

    from .dist_csr import _device_put_sharded

    return _device_put_sharded(arr, NamedSharding(mesh, P(ROW_AXIS)))
