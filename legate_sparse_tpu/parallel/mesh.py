# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Mesh construction helpers.

The reference lets Legion pick a launch domain from the machine shape
(reference: ``runtime.py:75-81``, projection functors mapping 1-D grids
onto 2-D stores ``projections.cc:23-64``).  Here the machine model is a
``jax.sharding.Mesh``; sparse row-block distribution wants a 1-D mesh
whose single axis (``"rows"``) spans every chip — ICI-contiguous so the
halo ``ppermute`` in distributed SpMV rides neighbor links.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROW_AXIS = "rows"


def make_row_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or given) devices with axis name ``rows``."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (ROW_AXIS,))


def row_spec() -> PartitionSpec:
    return PartitionSpec(ROW_AXIS)


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(ROW_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
