# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Mesh construction helpers.

The reference lets Legion pick a launch domain from the machine shape
(reference: ``runtime.py:75-81``, projection functors mapping 1-D grids
onto 2-D stores ``projections.cc:23-64``).  Here the machine model is a
``jax.sharding.Mesh``; sparse row-block distribution wants a 1-D mesh
whose single axis (``"rows"``) spans every chip — ICI-contiguous so the
halo ``ppermute`` in distributed SpMV rides neighbor links.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROW_AXIS = "rows"
COL_AXIS = "cols"

# Partition layout strategies for shard_csr (docs/DIST.md).  "1d-row"
# is the historical implicit default: row blocks over the flattened
# mesh, x realized per the all_gather/halo/precise choice.  "1d-col"
# is the transpose assignment (row blocks over the mesh's LAST axis,
# provided for strategy-object completeness — same collective program
# as 1d-row on a 1-D mesh).  "2d-block" block-partitions over a
# (rows, cols) grid: x panels broadcast along mesh rows, partial
# products reduce-scattered along mesh columns.  "auto" routes by
# predicted interconnect bytes (recorded as a ``shard_csr.routing``
# obs event citing both predictions).
LAYOUT_1D_ROW = "1d-row"
LAYOUT_1D_COL = "1d-col"
LAYOUT_2D_BLOCK = "2d-block"
LAYOUT_AUTO = "auto"
LAYOUTS = (LAYOUT_1D_ROW, LAYOUT_1D_COL, LAYOUT_2D_BLOCK, LAYOUT_AUTO)


def resolve_layout(layout: Optional[str] = None) -> str:
    """Resolve a layout request to a concrete strategy name, with
    explicit precedence: argument > ``LEGATE_SPARSE_TPU_DIST_LAYOUT``
    env knob (``settings.dist_layout``) > ``"1d-row"`` default.  The
    returned value may still be ``"auto"`` — shard_csr turns that into
    a concrete layout from predicted bytes at build time."""
    if layout is None:
        from ..settings import settings

        layout = settings.dist_layout or LAYOUT_1D_ROW
    if layout not in LAYOUTS:
        raise ValueError(
            f"unknown dist layout {layout!r}; expected one of {LAYOUTS}"
        )
    return layout


def factor_grid(n: int) -> tuple[int, int]:
    """Near-square factorization of ``n`` (the reference's
    ``factor_int``, ``legate_sparse/utils.py:118-124``): returns
    (r, c) with r * c == n and r <= c, r as large as possible."""
    r = int(n ** 0.5)
    while r > 1 and n % r:
        r -= 1
    return max(r, 1), n // max(r, 1)


def make_grid_mesh(devices: Optional[Sequence | int] = None,
                   shape: Optional[tuple[int, int] | int] = None) -> Mesh:
    """2-D mesh with axes ("rows", "cols") — the analog of the
    reference's 1-D-launch-onto-2-D-grid projection functors
    (``projections.cc:23-64``): the sparse matrix row-shards over
    "rows" while dense SpMM operands column-shard over "cols"
    (independent columns — zero extra communication).  ``shape``
    defaults to the near-square ``factor_grid`` of the device count.

    ``make_grid_mesh(R, C)`` (both ints) is shorthand for an (R, C)
    grid over the first R*C devices — the layout-strategy spelling
    used by the 2-d-block docs and tests.
    """
    if isinstance(devices, int) and isinstance(shape, int):
        devices, shape = devices * shape, (devices, shape)
    if devices is None:
        devices = jax.devices()
    elif isinstance(devices, int):
        avail = jax.devices()
        if len(avail) < devices:
            raise ValueError(
                f"make_grid_mesh({devices}): only {len(avail)} devices "
                f"available"
            )
        devices = avail[:devices]
    devices = list(devices)
    if shape is None:
        shape = factor_grid(len(devices))
    r, c = shape
    if r * c != len(devices):
        raise ValueError(
            f"grid shape {shape} != device count {len(devices)}"
        )
    return Mesh(
        np.asarray(devices).reshape(r, c), (ROW_AXIS, COL_AXIS)
    )


def make_row_mesh(devices: Optional[Sequence | int] = None) -> Mesh:
    """1-D mesh over all (or given) devices with axis name ``rows``.

    ``devices`` may be a device sequence or an int count (the first
    ``devices`` of ``jax.devices()``; errors if fewer are available).

    Multi-host: after ``init_distributed()``, ``jax.devices()`` spans
    every host's chips in process order, so row blocks are contiguous
    per host — halo ``ppermute`` rides ICI within a slice and only the
    two shards at each slice boundary cross DCN.
    """
    if devices is None:
        devices = jax.devices()
    elif isinstance(devices, int):
        avail = jax.devices()
        if len(avail) < devices:
            raise ValueError(
                f"make_row_mesh({devices}): only {len(avail)} devices "
                f"available"
            )
        devices = avail[:devices]
    return Mesh(np.asarray(devices), (ROW_AXIS,))


def survivor_mesh(mesh: Mesh, lost: int | Sequence[int]) -> Mesh:
    """The shrunken mesh after losing device(s) at flat ordinal(s)
    ``lost`` — the recovery ladder's mesh-shrink step
    (docs/RESILIENCE.md).

    Survivors keep the source mesh's flat device order (minus the
    lost ordinals), so row blocks stay contiguous per host after the
    reshard.  A 1-D ``rows`` mesh shrinks to a 1-D ``rows`` mesh; a
    2-D (rows, cols) grid re-factors the survivor count through
    ``factor_grid`` (a lost device rarely leaves the original grid
    shape intact).  Errors rather than returning an empty mesh when
    every device is lost.
    """
    flat = list(np.asarray(mesh.devices).reshape(-1))
    lost_set = {int(lost)} if isinstance(lost, int) else {
        int(i) for i in lost}
    bad = [i for i in lost_set if not 0 <= i < len(flat)]
    if bad:
        raise ValueError(
            f"survivor_mesh: lost ordinal(s) {sorted(bad)} outside "
            f"flat mesh of {len(flat)} devices")
    survivors = [d for i, d in enumerate(flat) if i not in lost_set]
    if not survivors:
        raise ValueError("survivor_mesh: no devices survive")
    if len(mesh.axis_names) == 1:
        return Mesh(np.asarray(survivors), mesh.axis_names)
    r, c = factor_grid(len(survivors))
    return Mesh(np.asarray(survivors).reshape(r, c), mesh.axis_names)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join a multi-host run (the reference's network-backend analog).

    The reference selects GASNet/UCX/MPI at build time
    (``install.py:397-413``) and lets Legion move data over it; here
    the one network bootstrap is ``jax.distributed.initialize`` — on
    TPU pods all arguments are discovered from the environment, on
    other clusters pass them explicitly.  After this, every
    ``jax.Array`` sharded over ``make_row_mesh()`` spans the pod and
    XLA routes collectives over ICI within a slice and DCN across
    slices with no further configuration.

    Safe to call more than once, including after a direct
    ``jax.distributed.initialize`` elsewhere (both are no-ops then).
    """
    if getattr(init_distributed, "_done", False):
        return
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        init_distributed._done = True
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    init_distributed._done = True


def row_spec() -> PartitionSpec:
    return PartitionSpec(ROW_AXIS)


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(ROW_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
