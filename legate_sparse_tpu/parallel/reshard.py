# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Resharding: move distributed operands between meshes and layouts.

ROADMAP item 3's missing primitive, and the middle rung of the
recovery ladder (docs/RESILIENCE.md): after a device loss the solver
needs its operands on the survivor mesh; after a layout decision
changes (autotune, a 2-d-block SpGEMM feeding a 1d-row solve) the
same matrix needs a different partition.  Two entry points:

- :func:`reshard_vector` — THE cached chunk-permute program.  A
  sharded padded vector is, under every layout ``shard_vector``
  produces, one contiguous chunk per device in flat mesh order; a
  placement change over the same device set is therefore exactly one
  ``ppermute`` over the flat mesh whose pairs send chunk ``c`` from
  its source device to the device that owns chunk ``c`` under the
  destination mesh.  One shard_map program per (src, dst) mesh
  fingerprint pair, cached and contracted (``tools/verify``:
  ``dist/reshard/1d-row/chunk-permute/f32``), priced exactly by
  ``obs.comm.reshard_volumes`` — identity pairs move zero bytes, so
  resharding onto the same placement ledgers nothing.

- :func:`reshard` — the matrix path.  Block representations are
  layout-specific (halo-rebased ELL windows vs block-local 2-d
  panels), so a layout or mesh-shape change is a *repartition*, not a
  permute: ``shard_csr`` re-runs on the retained source ``csr_array``
  (``DistCSR._src_csr``) over the destination mesh, with upload bytes
  ledgered by the existing ``transfer.shard_upload*`` counters.  A
  destination whose ``mesh_fingerprint(mesh, layout)`` equals the
  source's returns ``A`` unchanged.

Plan-cache non-aliasing: ``dist_plan_fingerprint`` already folds
``mesh_fingerprint(mesh, layout)`` into every dist-plan identity, so
a resharded matrix can never alias its pre-reshard compiled programs
in the engine's ledger — pinned by test_reshard.py.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .. import obs as _obs
from ..obs import comm as _comm
from ._compat import shard_map
from .mesh import (
    LAYOUT_1D_COL, LAYOUT_1D_ROW, LAYOUT_2D_BLOCK,
    make_grid_mesh, make_row_mesh, resolve_layout,
)

__all__ = ["reshard", "reshard_vector", "chunk_permute_plan"]


def _flat_devices(mesh: Mesh) -> list:
    return list(np.asarray(mesh.devices).reshape(-1))


def _vector_spec(mesh: Mesh) -> P:
    """The dim-0 spec ``shard_vector`` uses: every mesh axis, grouped
    — one contiguous chunk per device in flat (row-major) order."""
    names = tuple(mesh.axis_names)
    return P(names if len(names) > 1 else names[0])


def chunk_permute_plan(src_mesh: Mesh,
                       dst_mesh: Mesh) -> Tuple[Tuple[Tuple[int, int],
                                                      ...], int]:
    """The ppermute pairs of the (src, dst) placement change and how
    many of them actually move a chunk.

    Chunk ``c`` lives on flat device ``src[c]`` and must end on flat
    device ``dst[c]``; device ``dst[c]`` is flat ordinal
    ``src.index(dst[c])`` of the source mesh, so the pair is
    ``(c, src.index(dst[c]))``.  Identity pairs are kept (every
    device must receive or ``ppermute`` zeros its output) but priced
    at zero bytes."""
    src = _flat_devices(src_mesh)
    dst = _flat_devices(dst_mesh)
    if len(src) != len(dst) or set(src) != set(dst):
        raise ValueError(
            "chunk_permute_plan: src and dst meshes must cover the "
            "same device set (a shrink/grow is a repartition — use "
            "reshard / shard_vector from host state)")
    pairs = tuple(
        (c, src.index(dst[c])) for c in range(len(src)))
    moved = sum(1 for s, t in pairs if s != t)
    return pairs, moved


# One compiled chunk-permute program per (src, dst) mesh fingerprint
# pair — the tentpole cache.  jit handles chunk shape/dtype retraces
# within an entry; the fingerprint key (not Mesh object identity)
# means two equal meshes built independently share one program.
_PERMUTE_PROGRAMS: Dict[Tuple[str, str], tuple] = {}


def _chunk_permute_program(src_mesh: Mesh, dst_mesh: Mesh):
    from .dist_csr import mesh_fingerprint

    key = (mesh_fingerprint(src_mesh), mesh_fingerprint(dst_mesh))
    hit = _PERMUTE_PROGRAMS.get(key)
    if hit is not None:
        return hit
    pairs, moved = chunk_permute_plan(src_mesh, dst_mesh)
    axes = (tuple(src_mesh.axis_names)
            if len(src_mesh.axis_names) > 1 else src_mesh.axis_names[0])
    spec = _vector_spec(src_mesh)

    def kernel(chunk):
        return jax.lax.ppermute(chunk, axes, pairs)

    fn = jax.jit(shard_map(
        kernel, mesh=src_mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False,
    ))
    built = (fn, pairs, moved)
    _PERMUTE_PROGRAMS[key] = built
    return built


def reshard_vector(x: jax.Array, mesh: Mesh,
                   layout: str = LAYOUT_1D_ROW) -> jax.Array:
    """Move a sharded padded vector onto ``mesh``'s placement via the
    cached chunk-permute program (same device set; eager only — the
    rewrap below assembles per-device buffers, which has no traced
    equivalent).  The result is the SAME global vector sharded as
    ``shard_vector`` would shard it over ``mesh``/``layout``; chunks
    whose source and destination device coincide never cross the
    interconnect."""
    src_mesh = x.sharding.mesh
    G = int(np.asarray(src_mesh.devices).size)
    L = int(x.shape[0])
    if int(np.asarray(mesh.devices).size) != G:
        from .dist_csr import mesh_fingerprint

        # Name BOTH endpoint fingerprints: the placement controller
        # debugs failed migrations by the same mesh_fingerprint keys
        # its plans and the permute-program cache are ledgered under.
        raise ValueError(
            f"reshard_vector: device count changed ({G} -> "
            f"{int(np.asarray(mesh.devices).size)}; src mesh "
            f"{mesh_fingerprint(src_mesh)} -> dst mesh "
            f"{mesh_fingerprint(mesh)}); a mesh "
            "shrink/grow is a repartition — re-shard from host state "
            "(shard_vector / checkpoint restore)")
    if L % G:
        raise ValueError(
            f"reshard_vector: length {L} not divisible by {G} chunks")
    fn, pairs, moved = _chunk_permute_program(src_mesh, mesh)
    item = jnp.dtype(x.dtype).itemsize
    vols = _comm.reshard_volumes(moved_chunks=moved,
                                 chunk_elems=L // G, itemsize=item,
                                 shards=G)
    comm_bytes = _comm.record("dist_reshard", vols,
                              calls={"ppermute": 1}, layout=layout)
    with _obs.span("dist_reshard", shards=G, moved=moved,
                   comm_bytes=comm_bytes):
        out = fn(x)
        if moved == 0 and src_mesh is mesh:
            return out
        # The program leaves chunk c's bytes ON its destination
        # device; re-wrap those buffers under the destination mesh's
        # sharding without another copy.
        per_dev = {s.device: s.data for s in out.addressable_shards}
        dst_sh = NamedSharding(mesh, _vector_spec(mesh))
        arrays = [per_dev[d] for d in _flat_devices(mesh)]
        return jax.make_array_from_single_device_arrays(
            x.shape, dst_sh, arrays)


def _default_mesh(A, layout: str) -> Mesh:
    """Destination mesh over the source matrix's own devices when the
    caller only names a layout."""
    devs = _flat_devices(A.mesh)
    if layout == LAYOUT_2D_BLOCK:
        return make_grid_mesh(devs)
    if layout == LAYOUT_1D_COL:
        return make_grid_mesh(devs, (1, len(devs)))
    return make_row_mesh(devs)


def reshard(A, mesh: Optional[Mesh] = None,
            layout: Optional[str] = None):
    """Repartition a :class:`~.dist_csr.DistCSR` onto ``mesh`` /
    ``layout`` (each defaulting to the source's).  Returns ``A``
    itself when the destination ``mesh_fingerprint(mesh, layout)``
    already matches — the zero-byte fast path the recovery ladder
    relies on for no-op rungs.

    The repartition runs ``shard_csr`` on the retained source
    ``csr_array`` — correct for ANY (src, dst) pair including mesh
    shrinks, with host->device bytes ledgered by the existing
    ``transfer.shard_upload*`` counters.  Matrices that did not come
    from ``shard_csr`` (no ``_src_csr``) raise a typed error telling
    the caller to reshard from their own source."""
    from .dist_csr import mesh_fingerprint, shard_csr

    # Delta wrappers (delta/dist.py) carry their pending update
    # buffer across the repartition — resharding must never silently
    # drop buffered mutations (pinned by test_delta.py).
    carry = getattr(A, "_delta_reshard_carry", None)
    if carry is not None:
        return carry(mesh, layout)

    lay = A.layout if layout is None else resolve_layout(layout)
    dst_mesh = _default_mesh(A, lay) if mesh is None else mesh
    _obs.inc("op.reshard")
    if (mesh_fingerprint(dst_mesh, lay)
            == mesh_fingerprint(A.mesh, A.layout)):
        _obs.event("reshard.matrix", moved=False, layout=lay,
                   shards=A.num_shards)
        return A
    src = getattr(A, "_src_csr", None)
    if src is None:
        raise ValueError(
            "reshard: this DistCSR carries no retained source matrix "
            "(_src_csr); shard_csr retains one — rebuild via "
            "shard_csr, or repartition your own source explicitly")
    with _obs.span("dist_reshard_matrix", layout=lay,
                   shards=int(np.asarray(dst_mesh.devices).size)):
        B = shard_csr(src, mesh=dst_mesh, layout=lay)
    _obs.event("reshard.matrix", moved=True, layout=lay,
               src_layout=A.layout, shards=B.num_shards)
    return B
