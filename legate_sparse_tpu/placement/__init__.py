# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""legate_sparse_tpu.placement: closed-loop elastic placement.

Connects the three layers prior PRs built — the per-tenant cost
sensors (``obs.attrib`` / ``obs.capacity``), the SLO burn alarm
(``obs.slo``) and the exactly-priced reshard actuator
(``parallel/reshard.py``) — into one control loop (docs/PLACEMENT.md):

- ``submesh``    — pure carving of the flat device order into
                   contiguous per-tenant submeshes, fingerprint-stable
                   so dist plans and permute programs survive epochs.
- ``controller`` — the pure ``propose()`` (sizing + carve + priced
                   amortization) and the epoch-driven
                   ``PlacementController`` (cooldown, thrash
                   detection, optional watchdog).
- ``migrate``    — the placed-tenant registry and live migration:
                   versioned placements atomically swapped behind the
                   gateway, in-flight requests draining on their
                   pinned version.

Inert by default: without ``LEGATE_SPARSE_TPU_PLACEMENT`` the gateway
pays one flag read per armed admission, ``step()`` returns ``None``
after the same single read, no ``placement.*`` counter moves, and
served values are bit-for-bit those of the shared global mesh
(pinned by tests/test_placement.py).
"""

from . import controller, migrate, submesh  # noqa: F401
from .controller import (  # noqa: F401
    PlacementController, PlacementDecision, PlacementSnapshot, propose,
)
from .migrate import (  # noqa: F401
    PlacedHandle, flag_shrink, is_placed_handle, migrate_to, place,
    registry, route,
)

__all__ = [
    "controller", "migrate", "submesh",
    "PlacementController", "PlacementDecision", "PlacementSnapshot",
    "propose",
    "PlacedHandle", "flag_shrink", "is_placed_handle", "migrate_to",
    "place", "registry", "route", "reset",
]


def reset() -> None:
    """Test isolation: drop every placed tenant and shrink flag (the
    controller instances are caller-owned; stop their watchdogs
    yourself)."""
    migrate.reset()
