# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SLO-driven placement controller (elastic placement,
docs/PLACEMENT.md).

The decision half of the loop, split along the same purity seam as
``capacity.recommend``:

- :func:`propose` is a **pure function of its snapshot** — no clock,
  no counter, no settings read inside (pinned by
  tests/test_placement.py the same way ``recommend``'s purity is
  pinned in tests/test_attrib.py).  It sizes via
  ``capacity.recommend``, clamps + carves via ``placement.submesh``,
  prices every move via the ``reshard_volumes`` predictor, and only
  proposes action when the predicted saving amortizes the priced cost
  — unless a tenant's QoS class is burning at page level (the breach
  is already the expensive outcome) or the gateway flagged it for a
  breaker-degraded shrink.
- :class:`PlacementController` owns everything impure: gathering the
  snapshot from the live sensors (attribution demand, SLO burn
  verdicts, the registry's current slices), the monotonic-clock
  cooldown/hysteresis that keeps the loop from flapping, migration
  execution through the registry, thrash detection, and the optional
  watchdog thread (mirroring ``obs/slo.py``).

Amortization model (docs/PLACEMENT.md): priced bytes convert to cost
time at the assumed migration bandwidth (``1 GB/s == 1 byte/ns``, so
``cost_ns = bytes / bw_gbps``); predicted saving is the ideal-scaling
``busy_ns * (1 - eff_src / eff_dst)`` summed over growing tenants; an
efficiency-driven plan executes only when
``saving >= amortize * cost``.

Counters / events / histograms (docs/OBSERVABILITY.md):

- ``placement.steps`` / ``placement.proposals`` /
  ``placement.hold.<reason>`` / ``placement.thrash`` /
  ``placement.watchdog.ticks``
- events ``placement.plan`` / ``placement.hold`` /
  ``placement.thrash``
- histogram ``lat.placement.step``
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

from ..obs import capacity as _capacity
from ..obs import counters as _counters
from ..obs import latency as _latency
from ..obs import slo as _slo
from ..obs import trace as _trace
from ..settings import settings as _rsettings
from . import migrate as _migrate
from . import submesh as _submesh

__all__ = [
    "PlacementSnapshot", "PlacementDecision", "propose",
    "PlacementController",
]

#: Fast-window burn at/above this marks a move SLO-driven (same page
#: threshold as capacity.BURN_PAGE / the SLO evaluator).
BURN_PAGE = _capacity.BURN_PAGE


class PlacementSnapshot(NamedTuple):
    """Everything :func:`propose` is allowed to know — gathered once
    per step by the controller, consumed pure."""

    demand: Dict[str, Dict[str, object]]     # tenant -> busy_ns/qos
    qos_weights: Dict[str, float]
    burns: Dict[Optional[str], float]        # qos -> fast burn
    devices: int
    current: Dict[str, Tuple[int, int]]      # placed tenant -> slice
    payload_bytes: Dict[str, int]            # registered tenants
    shrink: Tuple[str, ...]                  # breaker-flagged tenants


class PlacementDecision(NamedTuple):
    """One proposal: the full target carve, the subset that must
    move, and the amortization verdict."""

    act: bool
    reason: str          # migrate reasons: shrink/burning/amortized;
    #                      hold reasons: steady/no_demand/unamortized/
    #                      cooldown (the last applied by step())
    allocation: Dict[str, int]
    slices: Dict[str, Tuple[int, int]]
    moves: Dict[str, Tuple[int, int]]
    priced_bytes: Dict[str, int]
    total_priced_bytes: int
    predicted_saving_ns: float
    priced_cost_ns: float


def propose(snap: PlacementSnapshot, *, bw_gbps: float = 10.0,
            amortize: float = 1.0) -> PlacementDecision:
    """PURE placement proposal from one sensor snapshot (module
    docstring for the model; no clock/counter/settings reads — pinned
    by test)."""
    rec = _capacity.recommend(snap.demand, snap.qos_weights,
                              snap.burns, snap.devices)
    allocation = _submesh.feasible_allocation(rec, snap.devices)
    # Placed tenants with no demand this window keep their slice: the
    # carve must keep covering them or neighbors would land on their
    # devices.
    for tenant, (_, count) in sorted(snap.current.items()):
        allocation.setdefault(tenant, count)
    # Breaker-degraded shrink: halve the flagged tenant's slice
    # relative to today (floor 1) regardless of what demand says.
    for tenant in snap.shrink:
        cur = snap.current.get(tenant)
        if cur is None:
            continue
        target = max(1, cur[1] // 2)
        allocation[tenant] = min(allocation.get(tenant, target), target)
    if not allocation:
        return PlacementDecision(
            act=False, reason="no_demand", allocation={}, slices={},
            moves={}, priced_bytes={}, total_priced_bytes=0,
            predicted_saving_ns=0.0, priced_cost_ns=0.0)
    overshoot = sum(allocation.values()) - snap.devices
    if overshoot > 0:
        # The keep-your-slice / shrink adjustments can re-overflow a
        # clamped allocation; re-trim with the same deterministic rule.
        allocation = _submesh.feasible_allocation(
            {"tenants": {t: {"devices": n, "share": 0.0}
                         for t, n in allocation.items()}},
            snap.devices)
    slices = _submesh.carve(allocation, snap.devices)
    # Only registered tenants (payload known) can migrate; everything
    # else is advisory sizing with nothing to move.
    moves = {t: sl for t, sl in slices.items()
             if t in snap.payload_bytes and snap.current.get(t) != sl}
    if not moves:
        return PlacementDecision(
            act=False, reason="steady", allocation=allocation,
            slices=slices, moves={}, priced_bytes={},
            total_priced_bytes=0, predicted_saving_ns=0.0,
            priced_cost_ns=0.0)
    priced = {t: _submesh.priced_bytes(
        _submesh.price_migration(snap.payload_bytes[t], sl[1]))
        for t, sl in moves.items()}
    total_bytes = sum(priced.values())
    cost_ns = total_bytes / max(1e-9, float(bw_gbps))
    demanders = max(1, len(snap.demand))
    saving_ns = 0.0
    burning = False
    for t, sl in moves.items():
        d = snap.demand.get(t, {})
        eff_src = _submesh.effective_devices(
            snap.current.get(t), snap.devices, demanders)
        saving_ns += _submesh.predicted_saving_ns(
            int(d.get("busy_ns", 0)), eff_src, float(sl[1]))
        if float(snap.burns.get(d.get("qos"), 0.0)) >= BURN_PAGE:
            burning = True
    if any(t in snap.shrink for t in moves):
        act, reason = True, "shrink"
    elif burning:
        # A page-level burn is already the expensive outcome;
        # amortization gates only efficiency-driven moves.
        act, reason = True, "burning"
    elif saving_ns >= float(amortize) * cost_ns:
        act, reason = True, "amortized"
    else:
        act, reason = False, "unamortized"
    return PlacementDecision(
        act=act, reason=reason, allocation=allocation, slices=slices,
        moves=moves, priced_bytes=priced,
        total_priced_bytes=total_bytes,
        predicted_saving_ns=saving_ns, priced_cost_ns=cost_ns)


class PlacementController:
    """Epoch-driven control loop: explicit :meth:`step` plus an
    optional monotonic-clock watchdog.  One flag read and nothing
    else while ``settings.placement`` is off."""

    def __init__(self, *, devices: Optional[Sequence] = None,
                 cooldown_ms: Optional[float] = None,
                 bw_gbps: Optional[float] = None,
                 amortize: Optional[float] = None):
        if devices is None:
            import jax

            devices = jax.devices()
        self._devices = list(devices)
        self.cooldown_ms = float(
            _rsettings.placement_cooldown_ms if cooldown_ms is None
            else cooldown_ms)
        self.bw_gbps = float(
            _rsettings.placement_bw_gbps if bw_gbps is None
            else bw_gbps)
        self.amortize = float(
            _rsettings.placement_amortize if amortize is None
            else amortize)
        self._lock = threading.Lock()
        self._last_migration_ns: Optional[int] = None
        # tenant -> (migration ts_ns, its class's fast burn then):
        # the thrash detector's memory.
        self._tenant_last: Dict[str, Tuple[int, float]] = {}
        self._watchdog_thread: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()

    # ---------------- sensor gather (impure) ----------------

    def snapshot(self) -> PlacementSnapshot:
        """Join the live sensors into one immutable snapshot: demand
        from the attribution ledger (wall + queue wait, so demand
        moves even with tracing off), burns from the last SLO
        evaluation, slices/payloads/flags from the registry."""
        demand = _capacity.demand_snapshot(include_wait=True)
        from ..obs import attrib as _attrib

        for reserved in (_attrib.UNTAGGED, _attrib.OTHER):
            demand.pop(reserved, None)
        burns: Dict[Optional[str], float] = {}
        for v in _slo.verdicts():
            burns[v.qos] = max(burns.get(v.qos, 0.0), v.fast_burn)
        try:
            from ..engine.gateway import QOS_WEIGHTS as qos_weights
        except Exception:  # pragma: no cover - engine unavailable
            qos_weights = {}
        reg = _migrate.registry()
        return PlacementSnapshot(
            demand=demand, qos_weights=dict(qos_weights), burns=burns,
            devices=len(self._devices), current=reg.slices(),
            payload_bytes=reg.payload_bytes(),
            shrink=reg.shrink_flagged())

    # ---------------- the loop ----------------

    def step(self, now_ns: Optional[int] = None
             ) -> Optional[PlacementDecision]:
        """One control epoch: snapshot -> propose -> (maybe) migrate.
        Cooldown/hysteresis: an actionable plan inside
        ``cooldown_ms`` of the last executed migration is held
        (reason ``cooldown``) — except breaker-driven shrinks, which
        are about containment, not efficiency.  Returns the decision
        (``None`` while placement is off — one flag read)."""
        if not _rsettings.placement:
            return None
        t0 = time.perf_counter_ns()
        _counters.inc("placement.steps")
        snap = self.snapshot()
        decision = propose(snap, bw_gbps=self.bw_gbps,
                           amortize=self.amortize)
        _counters.inc("placement.proposals")
        _trace.event(
            "placement.plan", act=decision.act, reason=decision.reason,
            allocation=json.dumps(decision.allocation, sort_keys=True),
            priced_bytes=decision.total_priced_bytes,
            saving_ns=round(decision.predicted_saving_ns, 1),
            cost_ns=round(decision.priced_cost_ns, 1))
        now = time.monotonic_ns() if now_ns is None else int(now_ns)
        if decision.act:
            with self._lock:
                last = self._last_migration_ns
            cooled = (last is not None and decision.reason != "shrink"
                      and now - last < self.cooldown_ms * 1e6)
            if cooled:
                decision = decision._replace(act=False,
                                             reason="cooldown")
        if decision.act:
            _migrate.registry().apply(decision.moves, self._devices)
            with self._lock:
                self._last_migration_ns = now
                for t in decision.moves:
                    burn = float(snap.burns.get(
                        snap.demand.get(t, {}).get("qos"), 0.0))
                    prev = self._tenant_last.get(t)
                    if (prev is not None
                            and now - prev[0] < self.cooldown_ms * 1e6
                            and burn >= prev[1] > 0.0):
                        # Same tenant re-migrated within its cooldown
                        # window while its class burns no less than at
                        # the previous move: the loop is thrashing,
                        # not converging (doctor: migration-thrash).
                        _counters.inc("placement.thrash")
                        _trace.event("placement.thrash", tenant=t,
                                     burn=round(burn, 3),
                                     prev_burn=round(prev[1], 3))
                    self._tenant_last[t] = (now, burn)
        else:
            _counters.inc(f"placement.hold.{decision.reason}")
            _trace.event("placement.hold", reason=decision.reason)
        _latency.observe("lat.placement.step",
                         (time.perf_counter_ns() - t0) / 1e6)
        return decision

    # ---------------- watchdog (mirrors obs/slo.py) ----------------

    def start_watchdog(self, interval_ms: Optional[float] = None
                       ) -> bool:
        """Start the daemon stepping thread on a monotonic-clock
        cadence (``Event.wait`` never goes backwards with wall-clock
        steps).  Returns True when (already) running; no-op unless
        armed and the interval is positive."""
        if not _rsettings.placement:
            return False
        if interval_ms is None:
            interval_ms = _rsettings.placement_watchdog_ms
        if interval_ms <= 0:
            return False
        with self._lock:
            if (self._watchdog_thread is not None
                    and self._watchdog_thread.is_alive()):
                return True
            self._watchdog_stop.clear()
            interval_s = float(interval_ms) / 1e3

            def _loop():
                while not self._watchdog_stop.wait(interval_s):
                    try:
                        _counters.inc("placement.watchdog.ticks")
                        self.step()
                    except Exception:  # pragma: no cover - never kill
                        pass

            self._watchdog_thread = threading.Thread(
                target=_loop, name="lst-placement-watchdog",
                daemon=True)
            self._watchdog_thread.start()
        return True

    def stop_watchdog(self) -> None:
        t = self._watchdog_thread
        if t is None:
            return
        self._watchdog_stop.set()
        t.join(timeout=5.0)
        self._watchdog_thread = None

    def maybe_start_watchdog(self) -> bool:
        """Arm the watchdog from settings alone."""
        return self.start_watchdog()
