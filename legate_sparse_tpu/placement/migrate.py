# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Live migration behind the gateway (elastic placement,
docs/PLACEMENT.md).

The actuator half of the placement loop: a process-global registry of
**placed tenant matrices**, each an immutable source ``csr_array``
plus a versioned current placement (a :class:`~legate_sparse_tpu.
parallel.dist_csr.DistCSR` on the tenant's submesh, or ``None`` for a
single-device slice / not-yet-carved tenant — those serve through the
plain local kernels).

Routing contract (``engine/gateway.py``): every armed admission for a
registered tenant swaps the submitted matrix for a
:class:`PlacedHandle` **pinning the placement version current at
admission**.  A migration builds the new placement, records its priced
``comm.dist_reshard.*`` volume, then atomically swaps the registry
entry — in-flight requests drain on the old placement through their
pinned handles while new admissions route to the new one.  Nothing is
torn down mid-request and no request observes a half-moved matrix.

Breaker-degraded mode: when the gateway's dispatch breaker is open, a
placed tenant's traffic keeps serving through its own submesh (inline,
off the broken shared path) and the tenant is flagged for a slice
**shrink** — the controller's next step halves its slice instead of
the gateway shedding every deferrable class globally.

Inert by default: nothing here is reachable without
``LEGATE_SPARSE_TPU_PLACEMENT`` (the gateway's routing hook is one
flag read), and no ``placement.*`` counter moves while it is off.

Counters / events / histograms (docs/OBSERVABILITY.md):

- ``placement.placed`` / ``placement.routes`` /
  ``placement.migrations`` / ``placement.migration.bytes`` /
  ``placement.degraded_serve`` / ``placement.shrink.flagged``
- events ``placement.place`` / ``placement.migration``
- histogram ``lat.placement.migration``
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..obs import comm as _comm
from ..obs import counters as _counters
from ..obs import latency as _latency
from ..obs import trace as _trace
from ..obs import attrib as _attrib
from . import submesh as _submesh

__all__ = [
    "PlacedHandle", "PlacementRegistry", "registry", "place", "route",
    "is_placed_handle", "flag_shrink", "migrate_to", "reset",
]


class PlacedHandle:
    """A tenant request's pinned view of its placed matrix: the
    version current at admission.  Quacks enough like ``csr_array``
    for the gateway (shape/nnz/dtype/dot) while deliberately failing
    the engine's ``isinstance`` eligibility gate — placed traffic
    serves inline through its OWN submesh, never through the shared
    engine path it was migrated off of."""

    __slots__ = ("tenant", "version", "_src", "_dist")

    def __init__(self, tenant: str, src, dist, version: int):
        self.tenant = tenant
        self.version = int(version)
        self._src = src
        self._dist = dist

    @property
    def shape(self):
        return self._src.shape

    @property
    def nnz(self):
        return self._src.nnz

    @property
    def dtype(self):
        return self._src.dtype

    def dot(self, x):
        """Serve one SpMV on the pinned placement: the tenant's
        submesh ``dist_spmv`` (comm ledgered + attributed under the
        caller's trace context), or the plain local kernel for a
        single-device / not-yet-carved placement."""
        if self._dist is None:
            return self._src.dot(x)
        import jax.numpy as jnp

        from ..parallel.dist_csr import dist_spmv, shard_vector

        xs = shard_vector(np.asarray(x), self._dist.mesh,
                          self._dist.rows_padded)
        y = dist_spmv(self._dist, xs)
        return jnp.asarray(y)[: self._src.shape[0]]

    def __repr__(self):  # pragma: no cover - debugging aid
        fp = "local" if self._dist is None else "dist"
        return (f"PlacedHandle(tenant={self.tenant!r}, "
                f"v{self.version}, {fp})")


class _Entry:
    __slots__ = ("tenant", "src", "dist", "slice", "version",
                 "payload_bytes")

    def __init__(self, tenant: str, src, payload: int):
        self.tenant = tenant
        self.src = src
        self.dist = None
        self.slice: Optional[Tuple[int, int]] = None
        self.version = 0
        self.payload_bytes = int(payload)


class PlacementRegistry:
    """Process-global placed-tenant ledger (one instance via
    :func:`registry`); all mutation under one lock, handles pin
    immutable (src, dist, version) triples so readers never lock."""

    def __init__(self):
        self._lock = threading.RLock()
        self._items: Dict[str, _Entry] = {}
        self._shrink: set = set()

    # ---------------- registration / routing ----------------

    def place(self, tenant: str, A) -> None:
        """Register ``A`` as tenant's placed matrix (square CSR — the
        served operand and result live on the same row partition).
        Until the controller carves a slice the tenant serves on the
        plain local path; re-placing replaces the source and resets
        the placement."""
        rows, cols = A.shape
        if rows != cols:
            raise ValueError(
                f"placement.place: matrix must be square for submesh "
                f"serving (got {A.shape}); rectangular operators keep "
                f"the shared global mesh")
        tenant = str(tenant)
        with self._lock:
            self._items[tenant] = _Entry(
                tenant, A, _submesh.payload_bytes(A))
            self._shrink.discard(tenant)
        _counters.inc("placement.placed")
        _trace.event("placement.place", tenant=tenant,
                     payload_bytes=_submesh.payload_bytes(A))

    def route(self, A, tenant: str):
        """Admission-time routing: swap a registered tenant's own
        matrix for a handle pinning the current placement version;
        any other (tenant, matrix) pair passes through untouched."""
        e = self._items.get(str(tenant))
        if e is None or e.src is not A:
            return A
        with self._lock:
            handle = PlacedHandle(e.tenant, e.src, e.dist, e.version)
        _counters.inc("placement.routes")
        return handle

    # ---------------- controller-facing snapshot ----------------

    def slices(self) -> Dict[str, Tuple[int, int]]:
        with self._lock:
            return {t: e.slice for t, e in self._items.items()
                    if e.slice is not None}

    def payload_bytes(self) -> Dict[str, int]:
        with self._lock:
            return {t: e.payload_bytes for t, e in self._items.items()}

    def tenants(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._items))

    def version(self, tenant: str) -> Optional[int]:
        e = self._items.get(str(tenant))
        return None if e is None else e.version

    def flag_shrink(self, tenant: str) -> bool:
        """Mark a misbehaving placed tenant for a slice shrink at the
        controller's next step (breaker-degraded mode).  Idempotent:
        the flag (and its counter) moves once until acted on."""
        tenant = str(tenant)
        with self._lock:
            if tenant not in self._items or tenant in self._shrink:
                return False
            self._shrink.add(tenant)
        _counters.inc("placement.shrink.flagged")
        return True

    def shrink_flagged(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._shrink))

    # ---------------- migration ----------------

    def migrate(self, tenant: str, dst: Tuple[int, int],
                devices: Sequence) -> int:
        """Live-migrate one tenant onto slice ``dst = (start, count)``
        of the flat ``devices`` order.  Builds the new placement
        (``reshard()`` when already distributed, ``shard_csr`` for a
        first carve), records the priced ``comm.dist_reshard.*``
        volume attributed to the tenant, then atomically swaps the
        entry — in-flight pinned handles keep the old placement alive
        until they drain.  Returns the recorded bytes."""
        tenant = str(tenant)
        e = self._items.get(tenant)
        if e is None:
            raise KeyError(f"placement.migrate: tenant {tenant!r} is "
                           f"not placed")
        t0 = time.perf_counter_ns()
        start, count = int(dst[0]), int(dst[1])
        mesh = _submesh.build_submesh(devices, start, count)
        if mesh is None:
            new_dist = None
        elif e.dist is not None:
            from ..parallel.reshard import reshard as _reshard

            new_dist = _reshard(e.dist, mesh=mesh)
        else:
            from ..parallel.dist_csr import shard_csr

            new_dist = shard_csr(e.src, mesh=mesh)
        # The migration's interconnect volume is DECLARED through the
        # same reshard_volumes predictor the controller priced with —
        # priced == measured by construction (the physical host->
        # device movement is ledgered separately by the repartition's
        # transfer.shard_upload* counters).
        vols = _submesh.price_migration(e.payload_bytes, count)
        with _attrib.scope(((tenant, None),)):
            moved = _comm.record("dist_reshard", vols,
                                 calls={"ppermute": 1}, layout="1d-row")
        with self._lock:
            e.dist = new_dist
            e.slice = (start, count)
            e.version += 1
            self._shrink.discard(tenant)
            version = e.version
        _counters.inc("placement.migrations")
        _counters.handle("placement.migration.bytes").inc(int(moved))
        _latency.observe("lat.placement.migration",
                         (time.perf_counter_ns() - t0) / 1e6)
        _trace.event("placement.migration", tenant=tenant,
                     start=start, devices=count, bytes=int(moved),
                     version=version)
        return int(moved)

    def apply(self, moves: Dict[str, Tuple[int, int]],
              devices: Sequence) -> int:
        """Execute a decision's moves in sorted tenant order; returns
        the total recorded migration bytes."""
        total = 0
        for tenant in sorted(moves):
            total += self.migrate(tenant, moves[tenant], devices)
        return total

    def reset(self) -> None:
        with self._lock:
            self._items.clear()
            self._shrink.clear()


_REGISTRY = PlacementRegistry()


def registry() -> PlacementRegistry:
    return _REGISTRY


def place(tenant: str, A) -> None:
    _REGISTRY.place(tenant, A)


def route(A, tenant: str):
    return _REGISTRY.route(A, tenant)


def is_placed_handle(A) -> bool:
    return isinstance(A, PlacedHandle)


def flag_shrink(tenant: str) -> bool:
    return _REGISTRY.flag_shrink(tenant)


def migrate_to(tenant: str, count: int,
               devices: Optional[Sequence] = None, *,
               start: int = 0) -> int:
    """Force one tenant onto slice ``(start, count)`` of the flat
    device order — the chaos drill's deterministic mid-storm
    migration trigger (the controller path goes through
    ``PlacementController.step``)."""
    if devices is None:
        import jax

        devices = jax.devices()
    return _REGISTRY.migrate(tenant, (int(start), int(count)), devices)


def reset() -> None:
    """Test isolation: drop every placed tenant and shrink flag."""
    _REGISTRY.reset()
