# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Per-tenant submesh carving (elastic placement, docs/PLACEMENT.md).

Pure functions turning a :func:`~legate_sparse_tpu.obs.capacity.recommend`
advisory sizing into a concrete, deterministic partition of the flat
global device order:

- :func:`feasible_allocation` clamps a (possibly undersized)
  recommendation onto the physical device count;
- :func:`carve` assigns each allocated tenant a **contiguous** slice
  ``(start, count)`` of the flat device list, tenants in sorted-name
  order — same allocation in, same slices out, always;
- :func:`build_submesh` materializes a slice as a 1d-row
  :class:`jax.sharding.Mesh` over exactly those devices.

Invariants (pinned by tests/test_placement.py):

1. **Contiguity / disjointness** — slices never overlap and cover a
   prefix of the flat device order, so neighbor tenants share no
   device (the isolation the controller is buying).
2. **Fingerprint stability** — carving the same allocation over the
   same device list twice builds meshes with equal
   ``mesh_fingerprint``s.  That is what keeps the engine's dist-plan
   ledger and the cached reshard permute programs
   (``parallel/reshard.py`` keys ``(src_fp, dst_fp)``) warm across
   controller epochs: an unchanged tenant re-resolves to the *same*
   plan keys, so "no move" really costs nothing.
3. **Purity** — nothing here reads a clock, a counter, or settings;
   :func:`~legate_sparse_tpu.placement.controller.propose` composes
   these under its own purity contract.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from ..obs import comm as _comm

__all__ = [
    "feasible_allocation", "carve", "build_submesh", "payload_bytes",
    "price_migration", "priced_bytes",
]


def feasible_allocation(recommendation: Dict[str, object],
                        devices: int) -> Dict[str, int]:
    """Clamp a ``capacity.recommend`` result onto ``devices`` physical
    devices.  The advisory layer may legitimately overshoot (every
    burning tenant ceils — that IS its undersized signal); a carve
    cannot.  Deterministic trim rule: one device at a time from the
    largest allocation above 1 (ties by tenant name); if every tenant
    is already at 1 and the mesh still overflows, the
    smallest-share tenants (ties by name, reversed) drop out of the
    allocation entirely and stay on their current placement."""
    devices = max(1, int(devices))
    tenants = recommendation.get("tenants", {}) or {}
    alloc = {t: max(1, int(rec["devices"]))
             for t, rec in sorted(tenants.items())}
    overshoot = sum(alloc.values()) - devices
    while overshoot > 0:
        victims = sorted((t for t, n in alloc.items() if n > 1),
                         key=lambda t: (-alloc[t], t))
        if not victims:
            break
        alloc[victims[0]] -= 1
        overshoot -= 1
    if overshoot > 0:
        drop = sorted(alloc,
                      key=lambda t: (float(tenants[t].get("share", 0.0))
                                     if t in tenants else 0.0, t))
        for t in drop:
            if overshoot <= 0:
                break
            overshoot -= alloc.pop(t)
    return alloc


def carve(allocation: Dict[str, int],
          devices: int) -> Dict[str, Tuple[int, int]]:
    """Assign each tenant a contiguous ``(start, count)`` slice of the
    flat device order, tenants in sorted-name order.  Raises when the
    allocation does not fit — callers clamp with
    :func:`feasible_allocation` first."""
    total = sum(max(1, int(n)) for n in allocation.values())
    if total > max(1, int(devices)):
        raise ValueError(
            f"carve: allocation wants {total} devices, mesh has "
            f"{devices} — clamp with feasible_allocation first")
    slices: Dict[str, Tuple[int, int]] = {}
    start = 0
    for tenant in sorted(allocation):
        count = max(1, int(allocation[tenant]))
        slices[tenant] = (start, count)
        start += count
    return slices


def build_submesh(devices: Sequence, start: int, count: int):
    """Materialize slice ``(start, count)`` of the flat device list as
    a 1d-row mesh (``None`` for a single-device slice — that tenant
    serves through the plain local kernels, no collective in sight).
    Equal slices over equal device lists rebuild meshes with equal
    ``mesh_fingerprint``s (invariant 2)."""
    if count <= 1:
        return None
    from ..parallel.mesh import make_row_mesh

    devs = list(devices)[int(start):int(start) + int(count)]
    if len(devs) != count:
        raise ValueError(
            f"build_submesh: slice ({start}, {count}) falls off the "
            f"{len(list(devices))}-device mesh")
    return make_row_mesh(devs)


def payload_bytes(A) -> int:
    """Bytes a tenant's CSR payload occupies (data + indices +
    indptr) — the mass a migration must move."""
    import numpy as np

    return int(sum(np.asarray(part).nbytes
                   for part in (A.data, A.indices, A.indptr)))


def price_migration(payload: int, dst_devices: int) -> Dict[str, int]:
    """Price moving ``payload`` bytes onto a ``dst_devices``-wide
    submesh, via the same :func:`~legate_sparse_tpu.obs.comm.
    reshard_volumes` predictor ``reshard_vector`` is ledgered by — the
    controller's prediction and the migration's recorded
    ``comm.dist_reshard.*`` bytes come from one function, so priced ==
    measured is an exact contract (ISSUE 19 acceptance band: 1%).

    Model: the payload lands as one chunk per destination device
    (``ceil(payload / G)`` bytes each, byte-granular elements); every
    chunk crosses the interconnect — a migration's src and dst
    placements never coincide, so the permute spans at least two
    devices even for a single-device destination slice."""
    G = max(1, int(dst_devices))
    if int(payload) <= 0:
        return {}
    chunk = -(-int(payload) // G)
    return _comm.reshard_volumes(moved_chunks=G, chunk_elems=chunk,
                                 itemsize=1, shards=max(2, G))


def priced_bytes(vols: Dict[str, int]) -> int:
    """Total predicted bytes of a priced migration (volume dict sum)."""
    return int(sum(int(v) for v in vols.values()))


def fair_share(devices: int, demanders: int) -> float:
    """Effective device share of an *unplaced* tenant: the global mesh
    divided evenly across the demanding tenants (the pre-placement
    baseline the amortization model measures savings against)."""
    return max(1, int(devices)) / max(1, int(demanders))


def effective_devices(current: Optional[Tuple[int, int]],
                      devices: int, demanders: int) -> float:
    """A tenant's effective device count today: its placed slice
    width, or the global-mesh fair share when unplaced."""
    if current is not None:
        return float(max(1, int(current[1])))
    return fair_share(devices, demanders)


def predicted_saving_ns(busy_ns: int, eff_src: float,
                        eff_dst: float) -> float:
    """Busy time a tenant is predicted to shed by moving from
    ``eff_src`` to ``eff_dst`` effective devices — the ideal-scaling
    model ``busy * (1 - src/dst)`` (docs/PLACEMENT.md).  Zero for
    shrinks: giving devices back never *saves* the moved tenant
    anything, it frees capacity for others."""
    if eff_dst <= 0 or eff_dst <= eff_src:
        return 0.0
    return float(busy_ns) * (1.0 - eff_src / eff_dst)
