# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""TPU-native preconditioner factories.

The reference has no preconditioner constructors (its solvers accept a
user-supplied ``M`` only, reference ``legate_sparse/linalg.py``), and
scipy's stock factory (``spilu``) is a sequential triangular
factorization with no sensible accelerator mapping.  The TPU-shaped
alternative is block-Jacobi: extract the dense diagonal blocks with one
masked scatter, invert them as one *batched* ``jnp.linalg.solve`` (MXU
work), and apply as a batched small-GEMM — everything stays on device
and the apply is jit-traceable, so it composes with the jitted
while_loop solvers (cg/minres/...) without host syncs.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["block_jacobi", "jacobi"]


def _diag_blocks(A, bs: int):
    """(nb, bs, bs) dense diagonal blocks of a csr_array via one
    scatter-add of the block-diagonal nnz (duplicate-safe)."""
    n = A.shape[0]
    nb = (n + bs - 1) // bs
    row_ids = A._get_row_ids()
    cols = A._indices
    data = A._data
    keep = (row_ids // bs) == (cols // bs)
    blocks = jnp.zeros((nb, bs, bs), dtype=A.dtype)
    b_idx = row_ids // bs
    r_idx = row_ids % bs
    c_idx = cols % bs
    vals = jnp.where(keep, data, jnp.zeros_like(data))
    # Out-of-block entries scatter with zero value to their (valid)
    # in-block coordinates — a no-op add, so no index clamping needed.
    blocks = blocks.at[b_idx, r_idx, c_idx].add(vals)
    # Padding rows (last partial block) get identity so the batched
    # solve stays nonsingular and padding stays inert.
    pad = nb * bs - n
    if pad:
        eye_tail = jnp.arange(bs) >= bs - pad
        blocks = blocks.at[nb - 1].add(
            jnp.diag(eye_tail.astype(A.dtype)))
    return blocks


def block_jacobi(A, block_size: int = 32):
    """Block-Jacobi preconditioner ``M ~= A^-1`` as a LinearOperator.

    Inverts the ``block_size``-sized dense diagonal blocks of ``A`` in
    one batched solve at construction; each apply is a single batched
    (nb, bs, bs) x (nb, bs) matmul.  Singular blocks raise (like a
    zero pivot in any factorization) — regularize A or choose a
    different block size.  Beyond-reference feature; scipy has no
    block-Jacobi factory.
    """
    from .linalg import LinearOperator

    n, m = A.shape
    if n != m:
        raise ValueError("block_jacobi needs a square matrix")
    bs = int(block_size)
    if bs < 1:
        raise ValueError("block_size must be >= 1")
    if not hasattr(A, "_get_row_ids"):
        from .csr import csr_array

        A = csr_array(A)   # scipy / other-format operand
    elif A.format != "csr":
        A = A.tocsr()
    if bs == 1:
        return jacobi(A)

    nb = (n + bs - 1) // bs
    blocks = _diag_blocks(A, bs)
    eye = jnp.broadcast_to(jnp.eye(bs, dtype=A.dtype), (nb, bs, bs))
    inv_blocks = jnp.linalg.solve(blocks, eye)
    if not bool(jnp.all(jnp.isfinite(inv_blocks))):
        raise ValueError(
            "block_jacobi: a diagonal block is singular "
            f"(block_size={bs}); regularize A or change block_size")
    pad = nb * bs - n

    def _apply(B3, x):
        xp = jnp.concatenate(
            [x, jnp.zeros((pad,), x.dtype)]) if pad else x
        y = jnp.einsum("bij,bj->bi", B3,
                       xp.reshape(nb, bs)).reshape(-1)
        return y[:n] if pad else y

    def matvec(x):
        return _apply(inv_blocks, x)

    def rmatvec(x):
        # Adjoint: conj-transposed blocks (M is block-diagonal, so the
        # adjoint is the per-block conjugate transpose).
        return _apply(jnp.conj(jnp.swapaxes(inv_blocks, 1, 2)), x)

    return LinearOperator((n, n), matvec=matvec, rmatvec=rmatvec,
                          dtype=A.dtype)


def jacobi(A):
    """Diagonal (point-Jacobi) preconditioner ``M = diag(A)^-1``.
    Zero diagonal entries raise, matching a zero pivot."""
    from .linalg import LinearOperator

    n, m = A.shape
    if n != m:
        raise ValueError("jacobi needs a square matrix")
    d = jnp.asarray(A.diagonal())
    if bool(jnp.any(d == 0)):
        raise ValueError("jacobi: zero on the diagonal")
    dinv = 1.0 / d

    def matvec(x):
        return dinv * x        # normal dtype promotion

    def rmatvec(x):
        return jnp.conj(dinv) * x

    return LinearOperator((n, n), matvec=matvec, rmatvec=rmatvec,
                          dtype=np.dtype(d.dtype))
