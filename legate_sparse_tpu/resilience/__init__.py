# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""legate_sparse_tpu.resilience: the request-lifecycle failure layer.

The north star is a service under heavy traffic, and under heavy
traffic partial failure is the steady state: a transient compile
error, a hung collective, a NaN-producing solve.  Before this
subsystem, each of those either raised out of the top-level API or
returned silent garbage.  Now failures are **injectable, bounded, and
observable** (``docs/RESILIENCE.md``):

- ``faults``   — deterministic, seedable fault injection at a closed
                 catalog of named sites (``fault_point("dist.spmv")``)
                 threaded through the engine, ``csr_array.dot``, the
                 distributed collectives, and the solver host-sync
                 points.  ``tools/check_fault_sites.py`` keeps the
                 catalog honest.
- ``policy``   — per-site retry with deterministic exponential
                 backoff, retry budgets, and circuit breakers whose
                 trip flips the existing fallback ladder (engine ->
                 plain jit dispatch -> scipy-coverage fallback).
- ``deadline`` — request deadlines propagated via contextvars; the
                 engine executor sheds expired requests with a typed
                 ``Rejected`` outcome, the solvers check at their
                 existing one-fetch-per-cycle cadence (zero extra
                 host syncs) and raise ``DeadlineExceeded`` with the
                 partial iterate.
- ``health``   — opt-in non-finite/divergence/stagnation detection at
                 the same sync points, surfaced as a structured
                 ``HealthReport`` instead of silent NaN results.
- ``outcomes`` — the typed outcome/error vocabulary shared by all of
                 the above.
- ``chaos``    — composed-fault drill harness: random faults from the
                 closed catalog under live multi-tenant gateway load,
                 with exactly-once / exact-accounting / bitwise-parity
                 invariant checks (``docs/RESILIENCE.md``).
- ``checkpoint`` — restartable solver snapshots at the same
                 one-fetch-per-cycle cadence (host buffers, overhead
                 ledgered in ``resil.ckpt.*``); the recovery ladder in
                 ``dist_cg``/``dist_gmres`` restores the last snapshot
                 after a ``DeviceLost`` and resumes on the shrunken
                 survivor mesh (``parallel/reshard.py``).

Inert by default: with ``LEGATE_SPARSE_TPU_RESIL`` unset every hook is
one flag read, no site adds a host sync, and behavior is bit-for-bit
the pre-subsystem package.  Every retry, breaker transition, shed
request, and injected fault lands in ``resil.*`` obs counters and
events; ``tools/trace_summary.py --resil`` renders the ledger.
"""

from __future__ import annotations

from . import (  # noqa: F401
    chaos, checkpoint, deadline, faults, health, outcomes, policy,
)
from .checkpoint import SolverCheckpoint  # noqa: F401
from .faults import CATALOG, InjectedFault, fault_point, inject  # noqa: F401
from .health import Monitor, SolverHealthError  # noqa: F401
from .outcomes import (  # noqa: F401
    ChecksumError, DeadlineExceeded, DeviceLost, FinalOutcomeError,
    HealthReport, Rejected, ResilienceError,
)
from .policy import CircuitOpenError, breaker, run  # noqa: F401
from ..settings import settings as _settings

__all__ = [
    "chaos", "checkpoint", "deadline", "faults", "health", "outcomes",
    "policy",
    "SolverCheckpoint",
    "CATALOG", "InjectedFault", "fault_point", "inject",
    "Monitor", "SolverHealthError",
    "ChecksumError", "DeadlineExceeded", "DeviceLost",
    "FinalOutcomeError", "HealthReport", "Rejected",
    "ResilienceError",
    "CircuitOpenError", "breaker", "run",
    "active", "guarded_call", "reset",
]


def active() -> bool:
    """The subsystem master switch (``settings.resil``) — the one flag
    every instrumented site reads first."""
    return bool(_settings.resil)


def guarded_call(site: str, fn, fallback=None):
    """The standard site wrap: ``fault_point(site)`` then ``fn()``,
    under ``policy.run``'s retry/breaker ladder — so an injected (or
    real) failure at the site is retried with backoff and accounted
    per site.  Call only when :func:`active` (callers keep their
    zero-overhead fast path explicit)."""
    def attempt():
        faults.fault_point(site)
        return fn()

    return policy.run(site, attempt, fallback=fallback)


def reset() -> None:
    """Disarm all faults, reset breakers, refill retry budgets
    (tests / bench phases)."""
    faults.clear()
    policy.reset()
