# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Chaos drill harness: composed random faults under multi-tenant load.

Single-fault drills (tests/test_resilience.py) prove each mechanism in
isolation; what they cannot prove is *composition* — that a tenant's
injected faults, breaker trips, and deadline storms stay contained
while OTHER tenants' traffic flows through the same gateway and
engine.  :func:`run_drill` drives exactly that scenario and checks the
gateway's isolation contract as hard invariants:

1. **Exactly-once resolution** — every submitted Future resolves
   (never hangs) with a typed outcome: a result array or an
   ``outcomes.Rejected``; an exception surfacing to a caller is a
   violation (the gateway's degradation paths must absorb injected
   faults).
2. **Exact accounting** — per-tenant and global ``gateway.*`` counter
   deltas must balance: ``submitted == served + shed + error`` for
   every tenant, and the global roll-ups agree with the per-tenant
   sums.
3. **Bitwise parity** — every served result equals, bit-for-bit, one
   of the two legitimate clean dispatch paths, computed with all
   faults cleared: the engine's bucketed plan (every batch route —
   packed, grouped, and single-request dispatches are mutually
   bit-identical by the kernel contract) or the plain ``A.dot``
   (the inline/degraded route; the autotuner may pick a
   differently-rounding kernel there).  An injected fault may delay,
   reroute, or shed a request — never corrupt its value.

The fault schedule is drawn from a seeded ``random.Random`` over the
closed site catalog (``faults.CATALOG``) — same seed, same schedule,
every run; no global RNG state is touched.  Faults are cleared between
rounds and the policy registry is reset at the end, so a drill leaves
no armed state behind.

Usage (the shape ``tests/test_gateway.py`` drives)::

    report = chaos.run_drill(
        gw,
        tenants=[
            {"name": "a", "qos": "interactive", "A": A1, "xs": xs1},
            {"name": "b", "qos": "background", "A": A2, "xs": xs2,
             "deadline_ms": 0.0},     # deadline-storm tenant
        ],
        rounds=4, seed=7)
    assert report.ok(), report.violations

Requires ``settings.gateway`` and ``settings.resil`` on (the drill is
about the armed system; with either off there is nothing to compose).
"""

from __future__ import annotations

import random
from concurrent.futures import TimeoutError as _FutTimeoutError
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs as _obs
from ..settings import settings as _settings
from . import deadline as _deadline
from . import faults as _faults
from . import policy as _policy
from .outcomes import Rejected

#: Default fault-site pool: the two gateway sites plus the engine
#: sites a gateway dispatch can reach.
DEFAULT_SITES = ("gateway.admit", "gateway.dispatch",
                 "engine.exec.dispatch", "engine.plan.build")

#: Fault kinds composed by default.  ``nonfinite`` is excluded: the
#: gateway sites carry no value for it to poison (it degrades to a
#: no-op fire), so it adds schedule noise without exercising anything.
DEFAULT_KINDS = ("error", "latency")


@dataclass
class ChaosReport:
    """Outcome ledger of one drill (violations empty == contract
    held)."""

    rounds: int = 0
    submitted: int = 0
    served: int = 0
    shed: int = 0
    errors: int = 0
    faults_armed: int = 0
    faults_fired: int = 0
    per_tenant: Dict[str, Dict[str, int]] = field(default_factory=dict)
    violations: List[str] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.violations


def _arm_random_faults(rng: random.Random, sites: Sequence[str],
                       kinds: Sequence[str],
                       report: ChaosReport) -> None:
    """Arm 1-2 faults for this round, drawn deterministically from
    ``rng`` (sites may repeat across rounds — re-arming replaces)."""
    for _ in range(rng.randint(1, 2)):
        site = rng.choice(list(sites))
        kind = rng.choice(list(kinds))
        _faults.inject(site, kind=kind, count=rng.randint(1, 3),
                       latency_ms=1.0)
        report.faults_armed += 1


def run_drill(gateway, tenants: Sequence[dict], *, rounds: int = 4,
              seed: int = 0,
              sites: Sequence[str] = DEFAULT_SITES,
              kinds: Sequence[str] = DEFAULT_KINDS,
              result_timeout_s: float = 30.0) -> ChaosReport:
    """Run ``rounds`` of composed-fault multi-tenant load through
    ``gateway`` and verify the isolation invariants (module
    docstring).

    Each tenant spec is a dict: ``name``, ``qos``, ``A`` (the
    tenant's matrix), ``xs`` (operand vectors submitted each round),
    and optional ``deadline_ms`` — when set, that tenant's submissions
    run inside ``deadline.scope(deadline_ms)`` (``0.0`` = a deadline
    storm: every one of its requests arrives already expired)."""
    if not (_settings.gateway and _settings.resil):
        raise RuntimeError(
            "chaos.run_drill needs settings.gateway and settings.resil "
            "on — the drill composes faults through the armed system")
    rng = random.Random(seed)
    report = ChaosReport(rounds=rounds)
    c0 = _obs.counters.snapshot("gateway.")
    names = [str(spec["name"]) for spec in tenants]
    try:
        for _round in range(rounds):
            _faults.clear()
            _arm_random_faults(rng, sites, kinds, report)
            inflight: List[Tuple[dict, object, object]] = []
            for spec in tenants:
                dl: Optional[float] = spec.get("deadline_ms")
                for x in spec["xs"]:
                    if dl is not None:
                        with _deadline.scope(dl):
                            fut = gateway.submit(
                                spec["A"], x, tenant=spec["name"],
                                qos=spec.get("qos", "batch"))
                    else:
                        fut = gateway.submit(
                            spec["A"], x, tenant=spec["name"],
                            qos=spec.get("qos", "batch"))
                    report.submitted += 1
                    inflight.append((spec, x, fut))
            gateway.flush()
            report.faults_fired += sum(
                a["fired"] for a in _faults.armed().values())
            # Quiesce injection BEFORE computing parity references:
            # the reference dispatch must be clean.
            _faults.clear()
            for spec, x, fut in inflight:
                try:
                    out = fut.result(timeout=result_timeout_s)
                except (_FutTimeoutError, TimeoutError):
                    report.violations.append(
                        f"hang: tenant {spec['name']} future never "
                        f"resolved")
                    continue
                except BaseException as e:  # noqa: BLE001 - ledger
                    report.errors += 1
                    report.violations.append(
                        f"exception surfaced to tenant "
                        f"{spec['name']}: {e!r}")
                    continue
                if isinstance(out, Rejected):
                    report.shed += 1
                    if out.reason not in (
                            "deadline_shed", "quota", "queue_full",
                            "breaker"):
                        report.violations.append(
                            f"untyped rejection reason {out.reason!r}")
                    continue
                report.served += 1
                out_np = np.asarray(out)
                refs = [np.asarray(spec["A"].dot(x))]
                eng = getattr(gateway, "_engine", None)
                if eng is not None:
                    y_eng = eng.matvec(spec["A"], x)
                    if y_eng is not None:
                        refs.append(np.asarray(y_eng))
                if not any(np.array_equal(out_np, r) for r in refs):
                    report.violations.append(
                        f"bitwise parity violated for tenant "
                        f"{spec['name']}")
    finally:
        _faults.clear()
        _policy.reset()
    # ---- exact accounting over the counter deltas ----
    c1 = _obs.counters.snapshot("gateway.")

    def delta(name: str) -> int:
        return int(c1.get(name, 0)) - int(c0.get(name, 0))

    if delta("gateway.submitted") != report.submitted:
        report.violations.append(
            f"gateway.submitted moved {delta('gateway.submitted')} "
            f"!= {report.submitted} submitted")
    tot_served = tot_shed = tot_err = 0
    for name in names:
        sub = delta(f"gateway.tenant.{name}.submitted")
        srv = delta(f"gateway.tenant.{name}.served")
        shd = delta(f"gateway.tenant.{name}.shed")
        err = delta(f"gateway.tenant.{name}.error")
        report.per_tenant[name] = {
            "submitted": sub, "served": srv, "shed": shd, "error": err}
        tot_served += srv
        tot_shed += shd
        tot_err += err
        if sub != srv + shd + err:
            report.violations.append(
                f"tenant {name} ledger leak: submitted {sub} != "
                f"served {srv} + shed {shd} + error {err}")
    if tot_served != report.served:
        report.violations.append(
            f"served roll-up {tot_served} != observed {report.served}")
    if tot_shed != report.shed:
        report.violations.append(
            f"shed roll-up {tot_shed} != observed {report.shed}")
    reasons = sum(delta(f"gateway.rejected.{r}")
                  for r in ("deadline_shed", "quota", "queue_full",
                            "breaker"))
    if reasons != tot_shed:
        report.violations.append(
            f"per-reason rejections {reasons} != tenant shed sum "
            f"{tot_shed}")
    return report
