# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Chaos drill harness: composed random faults under multi-tenant load.

Single-fault drills (tests/test_resilience.py) prove each mechanism in
isolation; what they cannot prove is *composition* — that a tenant's
injected faults, breaker trips, and deadline storms stay contained
while OTHER tenants' traffic flows through the same gateway and
engine.  :func:`run_drill` drives exactly that scenario and checks the
gateway's isolation contract as hard invariants:

1. **Exactly-once resolution** — every submitted Future resolves
   (never hangs) with a typed outcome: a result array or an
   ``outcomes.Rejected``; an exception surfacing to a caller is a
   violation (the gateway's degradation paths must absorb injected
   faults).
2. **Exact accounting** — per-tenant and global ``gateway.*`` counter
   deltas must balance: ``submitted == served + shed + error`` for
   every tenant, and the global roll-ups agree with the per-tenant
   sums.
3. **Bitwise parity** — every served result equals, bit-for-bit, one
   of the two legitimate clean dispatch paths, computed with all
   faults cleared: the engine's bucketed plan (every batch route —
   packed, grouped, and single-request dispatches are mutually
   bit-identical by the kernel contract) or the plain ``A.dot``
   (the inline/degraded route; the autotuner may pick a
   differently-rounding kernel there).  An injected fault may delay,
   reroute, or shed a request — never corrupt its value.

The fault schedule is drawn from a seeded ``random.Random`` over the
closed site catalog (``faults.CATALOG``) — same seed, same schedule,
every run; no global RNG state is touched.  Faults are cleared between
rounds and the policy registry is reset at the end, so a drill leaves
no armed state behind.

Usage (the shape ``tests/test_gateway.py`` drives)::

    report = chaos.run_drill(
        gw,
        tenants=[
            {"name": "a", "qos": "interactive", "A": A1, "xs": xs1},
            {"name": "b", "qos": "background", "A": A2, "xs": xs2,
             "deadline_ms": 0.0},     # deadline-storm tenant
        ],
        rounds=4, seed=7)
    assert report.ok(), report.violations

Requires ``settings.gateway`` and ``settings.resil`` on (the drill is
about the armed system; with either off there is nothing to compose).
"""

from __future__ import annotations

import random
from concurrent.futures import TimeoutError as _FutTimeoutError
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs as _obs
from ..settings import settings as _settings
from . import deadline as _deadline
from . import faults as _faults
from . import policy as _policy
from .outcomes import Rejected

#: Default fault-site pool: the two gateway sites plus the engine
#: sites a gateway dispatch can reach.
DEFAULT_SITES = ("gateway.admit", "gateway.dispatch",
                 "engine.exec.dispatch", "engine.plan.build")

#: Fault kinds composed by default.  ``nonfinite`` is excluded: the
#: gateway sites carry no value for it to poison (it degrades to a
#: no-op fire), so it adds schedule noise without exercising anything.
DEFAULT_KINDS = ("error", "latency")


@dataclass
class ChaosReport:
    """Outcome ledger of one drill (violations empty == contract
    held)."""

    rounds: int = 0
    submitted: int = 0
    served: int = 0
    shed: int = 0
    errors: int = 0
    faults_armed: int = 0
    faults_fired: int = 0
    recoveries: int = 0
    migrations: int = 0
    mutations: int = 0
    compactions: int = 0
    per_tenant: Dict[str, Dict[str, int]] = field(default_factory=dict)
    violations: List[str] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.violations


def _arm_random_faults(rng: random.Random, sites: Sequence[str],
                       kinds: Sequence[str],
                       report: ChaosReport) -> None:
    """Arm 1-2 faults for this round, drawn deterministically from
    ``rng`` (sites may repeat across rounds — re-arming replaces)."""
    for _ in range(rng.randint(1, 2)):
        site = rng.choice(list(sites))
        kind = rng.choice(list(kinds))
        _faults.inject(site, kind=kind, count=rng.randint(1, 3),
                       latency_ms=1.0)
        report.faults_armed += 1


def _run_device_loss_scenario(rng: random.Random, spec: dict,
                              report: ChaosReport) -> None:
    """One seeded device-loss recovery solve under the in-flight
    gateway load (docs/RESILIENCE.md): arm a ``device_loss`` at the CG
    conv-fetch cadence (the lost ordinal drawn from the drill RNG),
    run a checkpointed ``dist_cg``, and hold the scenario to three
    invariants:

    1. **Exactly-once resolution** — the solve returns one value and
       never raises (the recovery ladder absorbs the loss).
    2. **Exact accounting** — the ``resil.recovery.*`` /
       ``resil.ckpt.restores`` deltas are exactly one recovery's
       worth, and the reshard moved a nonzero byte count.
    3. **Scipy-differential parity** — the recovered solution matches
       ``scipy.sparse.linalg.spsolve`` on the same system within the
       drill tolerance (a recovery may change the iterate path, never
       the answer).

    The spec's matrix must need more than ``2 * conv_test_iters``
    iterations, so a checkpoint lands before the loss fires."""
    import scipy.sparse as _sp
    import scipy.sparse.linalg as _spla

    from ..parallel.dist_csr import dist_cg
    from . import checkpoint as _ckpt

    A = spec["A"]
    b = np.asarray(spec["b"])
    rtol = float(spec.get("rtol", 1e-8))
    cti = int(spec.get("conv_test_iters", 5))
    every = int(spec.get("ckpt_iters", cti))
    device = rng.randrange(int(A.num_shards))
    c0 = _obs.counters.snapshot("resil.")
    _faults.inject("solver.cg.conv", "device_loss",
                   after=int(spec.get("after", 2)), device=device)
    try:
        with _ckpt.scope("chaos.device_loss", every=every):
            x, _iters = dist_cg(A, b, rtol=rtol, conv_test_iters=cti)
    except BaseException as e:  # noqa: BLE001 - ledger
        report.violations.append(
            f"device_loss solve raised instead of recovering: {e!r}")
        return
    report.recoveries += 1
    c1 = _obs.counters.snapshot("resil.")

    def delta(name: str) -> int:
        return int(c1.get(name, 0)) - int(c0.get(name, 0))

    for name, want in (("resil.recovery.attempts", 1),
                       ("resil.recovery.device_loss", 1),
                       ("resil.recovery.mesh_shrink", 1),
                       ("resil.recovery.succeeded", 1),
                       ("resil.ckpt.restores", 1)):
        if delta(name) != want:
            report.violations.append(
                f"device_loss accounting: {name} moved {delta(name)} "
                f"!= {want}")
    if delta("resil.recovery.reshard_bytes") <= 0:
        report.violations.append(
            "device_loss: survivor reshard ledgered zero bytes")
    src = getattr(A, "_src_csr", None)
    if src is None:
        report.violations.append(
            "device_loss: matrix retains no source for the parity "
            "reference (shard via shard_csr)")
        return
    S = _sp.csr_matrix(
        (np.asarray(src.data), np.asarray(src.indices),
         np.asarray(src.indptr)), shape=src.shape)
    ref = _spla.spsolve(S.tocsc(), b)
    if not np.allclose(np.asarray(x), ref, rtol=1e-5,
                       atol=float(spec.get("parity_atol", 1e-6))):
        report.violations.append(
            "device_loss: recovered solution diverged from the scipy "
            "reference")


def _setup_migration_scenario(spec: dict, tenants: Sequence[dict],
                              placed_refs: Dict[str, List],
                              report: ChaosReport) -> dict:
    """Arm the live-migration scenario before the first round: place
    the target tenant's matrix and carve it onto its ``before`` slice
    so the mid-storm migration has a placement to move off of.  The
    pre-migration handle is pinned as a parity reference."""
    from .. import placement as _placement

    name = str(spec["tenant"])
    spec_t = next((t for t in tenants if str(t["name"]) == name), None)
    if spec_t is None:
        raise ValueError(
            f"chaos migration scenario: tenant {name!r} is not in the "
            f"drill tenant list")
    before, after = (int(spec["devices"][0]), int(spec["devices"][1]))
    A = spec_t["A"]
    _placement.place(name, A)
    _placement.migrate_to(name, before)
    report.migrations += 1
    placed_refs[name] = [_placement.route(A, name)]
    return {"tenant": name, "A": A, "after": after,
            "payload": _placement.registry().payload_bytes()[name]}


def _run_migration_scenario(state: dict,
                            placed_refs: Dict[str, List],
                            report: ChaosReport) -> None:
    """Fire one live migration while the round's gateway submissions
    are in flight, and hold it to the placement invariants:

    1. **Exactly-once execution** — exactly one migration's worth of
       ``placement.migration.*`` counter movement.
    2. **Exact pricing** — the recorded ``comm.dist_reshard.*`` bytes
       equal the ``price_migration`` prediction (one predictor on
       both sides — the ISSUE 19 1% acceptance band is exact here).
    3. **Version drain** — requests admitted before the swap drain on
       the old placement; the post-migration handle joins the parity
       reference set, so every served value must still match a clean
       dispatch on whichever placement served it."""
    from .. import placement as _placement
    from ..placement import submesh as _submesh

    c0p = _obs.counters.snapshot("placement.")
    c0r = _obs.counters.snapshot("comm.dist_reshard.")
    moved = _placement.migrate_to(state["tenant"], state["after"])
    report.migrations += 1
    c1p = _obs.counters.snapshot("placement.")
    c1r = _obs.counters.snapshot("comm.dist_reshard.")

    def delta(c0, c1, name: str) -> int:
        return int(c1.get(name, 0)) - int(c0.get(name, 0))

    priced = _submesh.priced_bytes(_submesh.price_migration(
        state["payload"], state["after"]))
    if delta(c0p, c1p, "placement.migrations") != 1:
        report.violations.append(
            f"migration accounting: placement.migrations moved "
            f"{delta(c0p, c1p, 'placement.migrations')} != 1")
    if delta(c0p, c1p, "placement.migration.bytes") != moved:
        report.violations.append(
            f"migration accounting: placement.migration.bytes moved "
            f"{delta(c0p, c1p, 'placement.migration.bytes')} != "
            f"{moved} returned")
    if delta(c0r, c1r, "comm.dist_reshard.ppermute") != 1:
        report.violations.append(
            f"migration accounting: comm.dist_reshard.ppermute moved "
            f"{delta(c0r, c1r, 'comm.dist_reshard.ppermute')} != 1")
    if delta(c0r, c1r, "comm.dist_reshard.ppermute_bytes") != priced:
        report.violations.append(
            f"migration pricing: comm.dist_reshard.ppermute_bytes "
            f"moved {delta(c0r, c1r, 'comm.dist_reshard.ppermute_bytes')}"
            f" != priced {priced}")
    if moved != priced:
        report.violations.append(
            f"migration pricing: recorded {moved} bytes != priced "
            f"{priced}")
    placed_refs[state["tenant"]].append(
        _placement.route(state["A"], state["tenant"]))


def _setup_mutation_scenario(spec: dict, tenants: Sequence[dict],
                             placed_refs: Dict[str, List],
                             report: ChaosReport) -> dict:
    """Arm the serve-while-mutating scenario before the first round:
    wrap the target tenant's matrix in a :class:`~..delta.DeltaCSR`
    so every later submission routes through versioned delta serving,
    and pin the pristine v0 view as the first parity reference."""
    from ..delta import DeltaCSR

    name = str(spec["tenant"])
    spec_t = next((t for t in tenants if str(t["name"]) == name), None)
    if spec_t is None:
        raise ValueError(
            f"chaos mutation scenario: tenant {name!r} is not in the "
            f"drill tenant list")
    A = spec_t["A"]
    D = DeltaCSR(A, capacity=spec.get("capacity"))
    spec_t["A"] = D
    placed_refs[name] = [D.view()]
    return {"tenant": name, "delta": D, "base": A,
            "updates": int(spec.get("updates", 100)),
            "batch": int(spec.get("batch", 10)),
            "seed": int(spec.get("seed", 0))}


def _run_mutation_scenario(state: dict,
                           placed_refs: Dict[str, List],
                           report: ChaosReport) -> None:
    """Stream the seeded update storm into the served matrix and fire
    one background compaction with an atomic version swap, while the
    round's gateway submissions are in flight.  Invariants held:

    1. **Exactly-once resolution** — ``delta.*`` counter movement is
       exactly the independently book-kept applied/overwrite/merge
       counts of the seeded stream (no double-apply, no loss).
    2. **Version drain** — every intermediate view (one per update
       batch) plus the post-compaction view joins the parity
       reference set, so every served value must bitwise-match a
       clean dispatch on whichever version served it.
    3. **Compaction = cold rebuild** — the swapped-in base is
       bitwise the COO rebuild of base-entries + resolved stream."""
    from ..csr import csr_array
    from ..gallery import mutation_stream

    D = state["delta"]
    name = state["tenant"]
    c0 = _obs.counters.snapshot("delta.")
    expected: Dict[Tuple[int, int], float] = {}
    exp_batches = exp_applied = exp_over = 0
    for rows, cols, vals in mutation_stream(
            state["seed"], state["base"], state["updates"],
            batch=state["batch"]):
        batch_seen = set()
        for r, c, v in zip(rows, cols, vals):
            key = (int(r), int(c))
            if key in expected or key in batch_seen:
                exp_over += 1
            else:
                exp_applied += 1
            batch_seen.add(key)
            expected[key] = float(v)
        D.update(rows, cols, vals)
        exp_batches += 1
        report.mutations += 1
        # Each batch publishes a fresh view; a request admitted
        # between batches legitimately drains on it.
        placed_refs[name].append(D.view())
    pending = D.pending
    merged = D.compact()
    report.compactions += 1
    placed_refs[name].append(D.view())
    c1 = _obs.counters.snapshot("delta.")

    def delta(cname: str) -> int:
        return int(c1.get(cname, 0)) - int(c0.get(cname, 0))

    for cname, want in (("delta.updates", exp_batches),
                        ("delta.applied", exp_applied),
                        ("delta.overwrites", exp_over),
                        ("delta.compactions", 1),
                        ("delta.swap.versions", 1),
                        ("delta.compaction.merged", merged)):
        if delta(cname) != want:
            report.violations.append(
                f"mutation accounting: {cname} moved {delta(cname)} "
                f"!= {want}")
    if merged != pending:
        report.violations.append(
            f"mutation accounting: compaction merged {merged} != "
            f"{pending} pending")
    if D.pending != 0:
        report.violations.append(
            f"mutation: {D.pending} updates survived compaction")
    # Criterion (c): the swapped-in base == a cold COO rebuild of the
    # mutated matrix, bitwise (independent bookkeeping on both sides).
    base = state["base"]
    brows, bcols, bdata = (np.asarray(a) for a in base._coo_parts())
    cold_entries = {(int(r), int(c)): float(v)
                    for r, c, v in zip(brows, bcols, bdata)}
    for key, v in expected.items():
        if v == 0.0:
            cold_entries.pop(key, None)
        else:
            cold_entries[key] = v
    keys = sorted(cold_entries)
    cold = csr_array(
        (np.asarray([cold_entries[k] for k in keys],
                    dtype=base.dtype),
         (np.asarray([k[0] for k in keys], dtype=np.int64),
          np.asarray([k[1] for k in keys], dtype=np.int64))),
        shape=base.shape, dtype=base.dtype)
    nb = D.view().base
    same = (nb.nnz == cold.nnz
            and np.array_equal(np.asarray(nb.data),
                               np.asarray(cold.data))
            and np.array_equal(np.asarray(nb.indices),
                               np.asarray(cold.indices))
            and np.array_equal(np.asarray(nb.indptr),
                               np.asarray(cold.indptr)))
    if not same:
        report.violations.append(
            "mutation: compacted base != cold rebuild of the mutated "
            "matrix (bitwise)")


def run_drill(gateway, tenants: Sequence[dict], *, rounds: int = 4,
              seed: int = 0,
              sites: Sequence[str] = DEFAULT_SITES,
              kinds: Sequence[str] = DEFAULT_KINDS,
              result_timeout_s: float = 30.0,
              device_loss: Optional[dict] = None,
              migration: Optional[dict] = None,
              mutation: Optional[dict] = None) -> ChaosReport:
    """Run ``rounds`` of composed-fault multi-tenant load through
    ``gateway`` and verify the isolation invariants (module
    docstring).

    Each tenant spec is a dict: ``name``, ``qos``, ``A`` (the
    tenant's matrix), ``xs`` (operand vectors submitted each round),
    and optional ``deadline_ms`` — when set, that tenant's submissions
    run inside ``deadline.scope(deadline_ms)`` (``0.0`` = a deadline
    storm: every one of its requests arrives already expired).

    ``device_loss`` opts a recovery scenario into every round: while
    the round's gateway submissions are in flight, a seeded
    ``device_loss`` drill solve runs through the full recovery ladder
    and is held to exactly-once / exact-accounting / scipy-parity
    invariants (:func:`_run_device_loss_scenario`).  The spec dict:
    ``A`` (a ``shard_csr`` matrix), ``b``, and optional ``rtol`` /
    ``conv_test_iters`` / ``ckpt_iters`` / ``after`` /
    ``parity_atol``.

    ``migration`` opts a live-migration scenario into the drill
    (requires ``settings.placement``): the spec dict names a drill
    ``tenant`` (a square-matrix one) and its ``devices = (before,
    after)`` slice widths.  The tenant is placed on its ``before``
    slice up front; at the midpoint round, while that round's
    submissions are in flight, it live-migrates to ``after`` — held
    to exactly-once / exact-pricing invariants
    (:func:`_run_migration_scenario`), with both placement versions'
    handles joining the tenant's bitwise-parity reference set (early
    requests legitimately drain on the pre-migration placement).

    ``mutation`` opts the serve-while-mutating scenario into the
    drill (requires ``settings.delta``, docs/MUTATION.md): the spec
    dict names a drill ``tenant`` plus optional ``updates`` (default
    100), ``batch``, ``seed`` and ``capacity``.  The tenant's matrix
    is wrapped in a ``DeltaCSR`` up front; at the midpoint round,
    while that round's submissions are in flight, the seeded update
    storm streams in and a background compaction fires with an
    atomic version swap — held to exactly-once / exact
    ``delta.*``-accounting / cold-rebuild-bitwise invariants
    (:func:`_run_mutation_scenario`), with every version's view
    joining the parity reference set."""
    if not (_settings.gateway and _settings.resil):
        raise RuntimeError(
            "chaos.run_drill needs settings.gateway and settings.resil "
            "on — the drill composes faults through the armed system")
    if migration is not None and not _settings.placement:
        raise RuntimeError(
            "chaos.run_drill migration scenario needs "
            "settings.placement on — there is no live placement to "
            "migrate otherwise")
    if mutation is not None and not _settings.delta:
        raise RuntimeError(
            "chaos.run_drill mutation scenario needs settings.delta "
            "on — there is no delta layer to mutate otherwise")
    rng = random.Random(seed)
    report = ChaosReport(rounds=rounds)
    placed_refs: Dict[str, List] = {}
    mig_state: Optional[dict] = None
    if migration is not None:
        mig_state = _setup_migration_scenario(migration, tenants,
                                              placed_refs, report)
    mut_state: Optional[dict] = None
    if mutation is not None:
        mut_state = _setup_mutation_scenario(mutation, tenants,
                                             placed_refs, report)
    c0 = _obs.counters.snapshot("gateway.")
    names = [str(spec["name"]) for spec in tenants]
    try:
        for _round in range(rounds):
            _faults.clear()
            _arm_random_faults(rng, sites, kinds, report)
            inflight: List[Tuple[dict, object, object]] = []
            for spec in tenants:
                dl: Optional[float] = spec.get("deadline_ms")
                for x in spec["xs"]:
                    if dl is not None:
                        with _deadline.scope(dl):
                            fut = gateway.submit(
                                spec["A"], x, tenant=spec["name"],
                                qos=spec.get("qos", "batch"))
                    else:
                        fut = gateway.submit(
                            spec["A"], x, tenant=spec["name"],
                            qos=spec.get("qos", "batch"))
                    report.submitted += 1
                    inflight.append((spec, x, fut))
            if device_loss is not None:
                # The recovery solve runs while this round's gateway
                # submissions are still queued — live load.
                _run_device_loss_scenario(rng, device_loss, report)
            if mig_state is not None and _round == rounds // 2:
                # Fire the live migration mid-storm, while this
                # round's submissions are still in flight: admitted
                # requests hold handles pinned at admission, so they
                # drain on the old placement.
                _run_migration_scenario(mig_state, placed_refs,
                                        report)
            if mut_state is not None and _round == rounds // 2:
                # Fire the update storm + compaction mid-storm: the
                # round's admitted requests hold views pinned at
                # admission and drain on the pre-mutation version.
                _run_mutation_scenario(mut_state, placed_refs,
                                       report)
            gateway.flush()
            report.faults_fired += sum(
                a["fired"] for a in _faults.armed().values())
            # Quiesce injection BEFORE computing parity references:
            # the reference dispatch must be clean.
            _faults.clear()
            for spec, x, fut in inflight:
                try:
                    out = fut.result(timeout=result_timeout_s)
                except (_FutTimeoutError, TimeoutError):
                    report.violations.append(
                        f"hang: tenant {spec['name']} future never "
                        f"resolved")
                    continue
                except BaseException as e:  # noqa: BLE001 - ledger
                    report.errors += 1
                    report.violations.append(
                        f"exception surfaced to tenant "
                        f"{spec['name']}: {e!r}")
                    continue
                if isinstance(out, Rejected):
                    report.shed += 1
                    if out.reason not in (
                            "deadline_shed", "quota", "queue_full",
                            "breaker"):
                        report.violations.append(
                            f"untyped rejection reason {out.reason!r}")
                    continue
                report.served += 1
                out_np = np.asarray(out)
                refs = [np.asarray(spec["A"].dot(x))]
                # A placed tenant's requests legitimately served on
                # either placement version bracketing the mid-storm
                # migration; both pinned handles are clean dispatch
                # paths (faults are cleared above).
                for h in placed_refs.get(str(spec["name"]), ()):
                    refs.append(np.asarray(h.dot(x)))
                eng = getattr(gateway, "_engine", None)
                if eng is not None:
                    y_eng = eng.matvec(spec["A"], x)
                    if y_eng is not None:
                        refs.append(np.asarray(y_eng))
                if not any(np.array_equal(out_np, r) for r in refs):
                    report.violations.append(
                        f"bitwise parity violated for tenant "
                        f"{spec['name']}")
    finally:
        _faults.clear()
        _policy.reset()
    # ---- exact accounting over the counter deltas ----
    c1 = _obs.counters.snapshot("gateway.")

    def delta(name: str) -> int:
        return int(c1.get(name, 0)) - int(c0.get(name, 0))

    if delta("gateway.submitted") != report.submitted:
        report.violations.append(
            f"gateway.submitted moved {delta('gateway.submitted')} "
            f"!= {report.submitted} submitted")
    tot_served = tot_shed = tot_err = 0
    for name in names:
        sub = delta(f"gateway.tenant.{name}.submitted")
        srv = delta(f"gateway.tenant.{name}.served")
        shd = delta(f"gateway.tenant.{name}.shed")
        err = delta(f"gateway.tenant.{name}.error")
        report.per_tenant[name] = {
            "submitted": sub, "served": srv, "shed": shd, "error": err}
        tot_served += srv
        tot_shed += shd
        tot_err += err
        if sub != srv + shd + err:
            report.violations.append(
                f"tenant {name} ledger leak: submitted {sub} != "
                f"served {srv} + shed {shd} + error {err}")
    if tot_served != report.served:
        report.violations.append(
            f"served roll-up {tot_served} != observed {report.served}")
    if tot_shed != report.shed:
        report.violations.append(
            f"shed roll-up {tot_shed} != observed {report.shed}")
    reasons = sum(delta(f"gateway.rejected.{r}")
                  for r in ("deadline_shed", "quota", "queue_full",
                            "breaker"))
    if reasons != tot_shed:
        report.violations.append(
            f"per-reason rejections {reasons} != tenant shed sum "
            f"{tot_shed}")
    return report
