# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Solver checkpoint/restore: restartable snapshots at the fetch
cadence.

A Krylov solve is a long straight-line computation whose only durable
output is its final iterate — lose a device mid-run and every
completed iteration is gone.  This module makes distributed solves
restartable without adding a single host sync: the chunked resilience
drivers (``linalg._cg_loop_resil``, the ``gmres`` cycle loop) already
fetch convergence state once per cycle, and a checkpoint scope rides
exactly that cadence::

    with checkpoint.scope("dist.cg", every=50):
        x, iters = dist_cg(A, b)        # snapshot every >= 50 iters

Every ``every`` iterations the driver hands the scope its restartable
state — ``(x, r, p)`` for CG, the Arnoldi seed ``x`` for GMRES — and
the scope copies it into HOST numpy buffers.  Host buffers are the
point: a snapshot sharded over the mesh dies with the mesh, while a
host copy survives any device loss by construction.  The copy cost is
ledgered (``resil.ckpt.bytes`` / ``resil.ckpt.ms``) so the overhead
of a cadence is a measured quantity, not a guess.

After a :class:`~.outcomes.DeviceLost`, the recovery ladder in
``dist_cg`` / ``dist_gmres`` calls :meth:`SolverCheckpoint.restore`,
re-shards the snapshot over the survivor mesh, and resumes — CG
restarted from a checkpointed ``x`` re-derives ``r`` and ``p`` from
scratch (a plain restart), which preserves convergence to tolerance;
it does not replay the exact iterate sequence.

Like ``deadline``, scopes are ``contextvars``-propagated and inert
without ``LEGATE_SPARSE_TPU_RESIL``: the instrumented drivers read
the flag before consulting the scope, and ``scope()`` with the
default cadence of 0 (``settings.resil_ckpt_iters``) never snapshots.

Counters::

    resil.ckpt.saves      snapshots taken
    resil.ckpt.bytes      host bytes copied across all saves
    resil.ckpt.ms         accumulated device->host copy milliseconds
    resil.ckpt.restores   snapshots handed back to a recovery ladder
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Any, Iterator, Optional, Sequence, Tuple

from .. import obs as _obs
from ..settings import settings as _settings


class SolverCheckpoint:
    """Host-buffered snapshots of one solve's restartable state.

    ``every`` is the snapshot cadence in *iterations* (not cycles):
    the driver calls :meth:`maybe_save` at each convergence fetch and
    a snapshot is taken whenever at least ``every`` iterations have
    elapsed since the last one (the first eligible fetch always
    saves).  ``every <= 0`` disables snapshotting; the scope then only
    serves as a marker that routes solvers through their chunked
    drivers."""

    def __init__(self, site: str, every: int):
        self.site = site
        self.every = int(every)
        self.iterations = -1          # iteration count of last save
        self.arrays: Optional[Tuple[Any, ...]] = None
        self.saves = 0
        self.restores = 0
        self.nbytes = 0               # bytes of the LAST snapshot

    def maybe_save(self, iterations: int, arrays: Sequence[Any]) -> bool:
        """Snapshot ``arrays`` if the cadence says so; True if saved."""
        if self.every <= 0:
            return False
        if (self.arrays is not None
                and int(iterations) - self.iterations < self.every):
            return False
        self.save(iterations, arrays)
        return True

    def save(self, iterations: int, arrays: Sequence[Any]) -> None:
        """Unconditionally snapshot ``arrays`` into host buffers."""
        import numpy as np

        t0 = time.monotonic_ns()
        snap = tuple(np.asarray(a) for a in arrays)
        ms = (time.monotonic_ns() - t0) / 1e6
        self.arrays = snap
        self.iterations = int(iterations)
        self.saves += 1
        self.nbytes = sum(int(a.nbytes) for a in snap)
        _obs.inc("resil.ckpt.saves")
        _obs.inc("resil.ckpt.bytes", self.nbytes)
        _obs.inc("resil.ckpt.ms", ms)
        _obs.event("resil.ckpt", site=self.site,
                   iterations=self.iterations, nbytes=self.nbytes)

    def restore(self) -> Optional[Tuple[int, Tuple[Any, ...]]]:
        """Hand back ``(iterations, arrays)`` of the last snapshot, or
        None when nothing was ever saved (the ladder then restarts the
        solve from its original ``x0`` at iteration 0)."""
        if self.arrays is None:
            return None
        self.restores += 1
        _obs.inc("resil.ckpt.restores")
        _obs.event("resil.ckpt.restore", site=self.site,
                   iterations=self.iterations)
        return self.iterations, self.arrays

    def rebase(self, iterations: int = 0) -> None:
        """Re-key the held snapshot to a new iteration origin.  The
        recovery ladder calls this after consuming a restore: the
        resumed solve counts its iterations from 0 again, so the same
        snapshot now represents iteration 0 of the resumed lineage
        (its credit has already been banked by the ladder)."""
        self.iterations = int(iterations)


_var: contextvars.ContextVar[Optional[SolverCheckpoint]] = (
    contextvars.ContextVar("legate_sparse_tpu_resil_ckpt", default=None))


@contextlib.contextmanager
def scope(site: str = "solver",
          every: Optional[int] = None) -> Iterator[SolverCheckpoint]:
    """Bind a checkpoint scope for the enclosed solve.  ``every``
    defaults to ``settings.resil_ckpt_iters`` (0 = no snapshots).
    Unlike deadlines, scopes do not compose: the innermost scope owns
    the solve it encloses (an outer scope's snapshots would mix two
    solves' state)."""
    ck = SolverCheckpoint(
        site, _settings.resil_ckpt_iters if every is None else every)
    token = _var.set(ck)
    try:
        yield ck
    finally:
        _var.reset(token)


def current() -> Optional[SolverCheckpoint]:
    """The innermost active checkpoint scope, or None."""
    return _var.get()


def active() -> bool:
    """True iff a checkpoint scope is bound (callers gate on
    ``settings.resil`` before consulting this, as with deadlines)."""
    return _var.get() is not None
