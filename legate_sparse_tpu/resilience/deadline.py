# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Deadline propagation: request budgets that ride the call stack.

A serving request that can no longer meet its deadline is *negative*
work: it occupies queue slots and device time that on-time requests
need.  This module carries the deadline down the stack as a
``contextvars`` scope so the layers below can shed:

    with deadline.scope(250.0):          # 250 ms budget
        fut = engine.submit(A, x)        # queue wait counts against it
        x, iters = linalg.cg(A, b)       # checked each conv cycle

- The **executor** captures ``deadline.current()`` at submit time (the
  submitting thread's scope — the worker thread dispatching later
  still sheds against the *request's* deadline, not its own) and sheds
  expired requests with a typed :class:`..outcomes.Rejected` Future
  result instead of dispatching them.
- The **solvers** check ``deadline.expired()`` at their existing
  one-fetch-per-cycle convergence cadence (PR 2's design), so deadline
  enforcement adds ZERO extra host syncs; an expired mid-flight solve
  raises :class:`..outcomes.DeadlineExceeded` carrying the partial
  iterate.

Nested scopes compose by *sooner wins*: an inner ``scope(1000)``
under an outer 50 ms budget still expires at the outer deadline.
Scopes are inert without ``LEGATE_SPARSE_TPU_RESIL`` — the instrumented
sites read the flag before consulting the contextvar.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from dataclasses import dataclass
from typing import Iterator, Optional

from .. import obs as _obs
from .outcomes import DeadlineExceeded
from .outcomes import Rejected  # noqa: F401  (re-export convenience)


@dataclass(frozen=True)
class Deadline:
    """An absolute expiry on the monotonic clock plus the budget it
    was created with (for reporting).

    Expiry arithmetic is integer ``time.monotonic_ns()`` — never wall
    clock (NTP steps would expire or resurrect budgets), and never
    float seconds (whose 2^53 mantissa silently coarsens long-uptime
    monotonic readings below the sub-ms budgets used here).  The
    clock source is read through the ``time`` module attribute at
    every call so tests can freeze/step it with ``monkeypatch``."""

    t_end_ns: int           # time.monotonic_ns() expiry
    total_ms: float

    def remaining_ms(self) -> float:
        return (self.t_end_ns - time.monotonic_ns()) / 1e6

    def expired(self) -> bool:
        return time.monotonic_ns() >= self.t_end_ns


_var: contextvars.ContextVar[Optional[Deadline]] = contextvars.ContextVar(
    "legate_sparse_tpu_resil_deadline", default=None)


@contextlib.contextmanager
def scope(ms: float) -> Iterator[Deadline]:
    """Bind a deadline ``ms`` milliseconds from now for the enclosed
    code (sooner-wins under nesting)."""
    d = Deadline(time.monotonic_ns() + int(float(ms) * 1e6), float(ms))
    cur = _var.get()
    if cur is not None and cur.t_end_ns < d.t_end_ns:
        d = cur
    token = _var.set(d)
    try:
        yield d
    finally:
        _var.reset(token)


def current() -> Optional[Deadline]:
    """The innermost active deadline, or None."""
    return _var.get()


def remaining_ms() -> Optional[float]:
    """Milliseconds left on the active deadline (None without one)."""
    d = _var.get()
    return None if d is None else d.remaining_ms()


def expired() -> bool:
    """True iff a deadline is active AND has passed."""
    d = _var.get()
    return d is not None and d.expired()


def raise_if_expired(site: str, iterations: int = 0,
                     residual: Optional[float] = None,
                     partial=None) -> None:
    """The shared solver-side enforcement point: when the active
    deadline has passed, account it (``resil.deadline.solver`` +
    per-site counter, ``resil.deadline`` event) and raise
    :class:`DeadlineExceeded` carrying the solve's progress.  Checked
    BEFORE each cycle dispatch, so an expired budget buys no further
    device work.  No-op without an active, expired deadline."""
    d = _var.get()
    if d is None or not d.expired():
        return
    _obs.inc("resil.deadline.solver")
    _obs.inc(f"resil.deadline.{site}")
    _obs.event("resil.deadline", site=site, iterations=iterations,
               residual=residual)
    raise DeadlineExceeded(site, iterations=iterations,
                           residual=residual, partial=partial)
