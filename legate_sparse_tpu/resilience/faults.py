# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Deterministic, seedable fault injection at named sites.

Distributed sparse solves live where partial failure is the steady
state (PAPERS.md: the GPGPU-cluster SpMV line treats per-node variance
as a design input), but a failure mode that can only be reproduced by
waiting for it cannot be tested.  This registry makes failures
*injectable*: every resilience-instrumented dispatch point calls

    fault_point("dist.spmv")            # error / latency sites
    stats = fault_point("solver.cg.conv", stats)   # value sites

which is a single flag read while the subsystem is off
(``LEGATE_SPARSE_TPU_RESIL`` unset) and consults the armed-fault table
when it is on.  Tests and the bench resilience phase arm faults with
:func:`inject`; drills are deterministic — "fail calls 1..count, then
succeed" — so retry/breaker accounting can be asserted *exactly*, and
optionally probabilistic with a seeded LCG (no global RNG state, no
run-to-run wobble).

Site names form a closed catalog (:data:`CATALOG`).  A ``fault_point``
call with an unknown name raises while the subsystem is armed, and
``tools/check_fault_sites.py`` statically cross-checks the package's
call-site literals against the catalog and ``docs/RESILIENCE.md`` so
injection coverage cannot rot silently.

Kinds
-----
- ``error``     raise :class:`InjectedFault` (retry/breaker drills)
- ``latency``   ``time.sleep(latency_ms)`` before proceeding (deadline
                and shedding drills — queue wait counts against the
                deadline)
- ``nonfinite`` poison the value flowing through a value-carrying site
                (last element set to NaN: the residual slot of the
                solver convergence fetches) — the health-detection
                drill; sites without a value treat it as a no-op fire.
- ``device_loss`` raise :class:`~.outcomes.DeviceLost` carrying the
                armed device ordinal — the recovery-ladder drill
                (``inject(site, "device_loss", device=N)``): the
                solver observes the loss at its conv-fetch, shrinks
                the mesh to the survivors, reshards, and resumes from
                the last checkpoint.

Trace safety: injection is suppressed inside an ambient jax trace
(``resil.fault.trace_skipped``) — a fault fired at trace time would be
baked into the compiled program and replayed forever, which is neither
deterministic-count nor recoverable.  Every instrumented site executes
its Python dispatch eagerly somewhere; drills target those calls.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from .. import obs as _obs
from ..settings import settings as _settings
from .outcomes import DeviceLost, ResilienceError

#: The closed site catalog: every ``fault_point`` in the package names
#: one of these.  Keep in sync with docs/RESILIENCE.md (enforced by
#: tools/check_fault_sites.py in tier-1).
CATALOG: Dict[str, str] = {
    "engine.plan.build":
        "engine/plan_cache.py: AOT plan compile (XLA lower+compile)",
    "engine.exec.queue":
        "engine/executor.py: request admission into the micro-batch "
        "queue",
    "engine.exec.dispatch":
        "engine/core.py: bucketed plan dispatch (matvec/matmat)",
    "csr.dot":
        "csr.py: csr_array.dot SpMV/SpMM/SpGEMM dispatch",
    "dist.spmv":
        "parallel/dist_csr.py: distributed SpMV collective dispatch",
    "dist.spmv.abft":
        "parallel/dist_csr.py: ABFT y-checksum verification of an "
        "eager distributed SpMV (value site carrying y — arm "
        "nonfinite to drill a corrupted collective)",
    "dist.cg":
        "parallel/dist_csr.py: dist_cg solve dispatch (collective "
        "loop)",
    "dist.spgemm":
        "parallel/dist_spgemm.py: distributed SpGEMM phases",
    "solver.cg.conv":
        "linalg.py: CG chunked convergence fetch (one per "
        "conv_test_iters cycle)",
    "solver.gmres.conv":
        "linalg.py: GMRES per-restart-cycle convergence fetch",
    "gateway.admit":
        "engine/gateway.py: multi-tenant admission (quota / token "
        "bucket / deadline triage)",
    "gateway.dispatch":
        "engine/gateway.py: WFQ batch dispatch (stacked multi-matrix "
        "or per-matrix plan execution)",
    "delta.compact":
        "delta/core.py: background compaction merge (side-buffer -> "
        "fresh base CSR) before the atomic version swap",
}

#: Fault kinds a site can be armed with.
KINDS = ("error", "latency", "nonfinite", "device_loss")


class InjectedFault(ResilienceError):
    """The exception an ``error``-kind armed site raises."""

    def __init__(self, site: str, ordinal: int):
        self.site = site
        self.ordinal = ordinal
        super().__init__(f"injected fault #{ordinal} at {site}")


@dataclass
class _Arm:
    site: str
    kind: str
    count: int
    after: int
    latency_ms: float
    p: float
    seed: int
    calls: int = 0
    fired: int = 0
    meta: dict = field(default_factory=dict)


_lock = threading.Lock()
_arms: Dict[str, _Arm] = {}


def inject(site: str, kind: str = "error", count: int = 1,
           after: int = 0, latency_ms: float = 5.0, p: float = 1.0,
           seed: int = 0, device: int = 0) -> None:
    """Arm ``site`` to fire ``kind`` on its next ``count`` eligible
    calls (skipping the first ``after``).  ``p < 1`` makes each
    eligible call fire with probability ``p`` drawn from a
    deterministic per-call LCG over ``seed`` — same seed, same
    schedule, every run.  ``device`` names the flat mesh ordinal a
    ``device_loss`` fire reports as lost (ignored by other kinds)."""
    if site not in CATALOG:
        raise ValueError(
            f"unknown fault site {site!r}; catalog: {sorted(CATALOG)}")
    if kind not in KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; one of {KINDS}")
    with _lock:
        _arms[site] = _Arm(site=site, kind=kind, count=int(count),
                           after=int(after),
                           latency_ms=float(latency_ms), p=float(p),
                           seed=int(seed),
                           meta={"device": int(device)})


def clear(site: Optional[str] = None) -> None:
    """Disarm one site, or every site."""
    with _lock:
        if site is None:
            _arms.clear()
        else:
            _arms.pop(site, None)


def armed(site: Optional[str] = None):
    """Snapshot of the armed table (one site, or all): ``{site:
    {kind, count, fired, calls}}``."""
    with _lock:
        items = ([_arms[site]] if site is not None and site in _arms
                 else (list(_arms.values()) if site is None else []))
        return {a.site: {"kind": a.kind, "count": a.count,
                         "fired": a.fired, "calls": a.calls}
                for a in items}


def fired(site: str) -> int:
    """How many times ``site``'s armed fault has fired."""
    with _lock:
        a = _arms.get(site)
        return a.fired if a is not None else 0


def _trace_clean() -> bool:
    """True when no jax trace is ambient (mirrors
    ``csr_array._can_build_cache``); unknown state counts as traced —
    never inject where the effect could be staged into a program."""
    try:
        from jax._src.core import trace_state_clean
    except ImportError:  # pragma: no cover - jax internals moved
        return False
    try:
        return trace_state_clean()
    except Exception:  # pragma: no cover
        return False


def _lcg01(seed: int, n: int) -> float:
    """Deterministic per-call uniform in [0, 1): one 64-bit LCG step
    over (seed, call ordinal) — no global RNG state touched."""
    x = (seed * 6364136223846793005 + n * 1442695040888963407
         + 1442695040888963407) & 0xFFFFFFFFFFFFFFFF
    x = (x * 6364136223846793005 + 1) & 0xFFFFFFFFFFFFFFFF
    return (x >> 11) / float(1 << 53)


def _poison(value: Any) -> Any:
    """Return ``value`` with its LAST element set to NaN — the
    residual slot of the stacked solver convergence fetches (the
    leading slots carry iteration counters the drivers must keep
    reading)."""
    import jax.numpy as jnp
    import numpy as np

    try:
        arr = jnp.asarray(value)
    except (TypeError, ValueError):
        # Not array-like (e.g. the csr_array an SpGEMM dispatch flows
        # through csr.dot): nonfinite degrades to a no-op fire rather
        # than surfacing a bogus TypeError the retry ladder would then
        # misread as a site failure.
        return value
    if not (jnp.issubdtype(arr.dtype, jnp.floating)
            or jnp.issubdtype(arr.dtype, jnp.complexfloating)):
        return value
    if arr.ndim == 0:
        return jnp.asarray(np.nan, dtype=arr.dtype)
    flat = arr.reshape(-1)
    flat = flat.at[flat.shape[0] - 1].set(np.nan)
    return flat.reshape(arr.shape)


def fault_point(site: str, value: Any = None) -> Any:
    """The per-site injection hook (see module docstring).

    Returns ``value`` unchanged on the overwhelmingly common path; an
    armed ``error`` fault raises :class:`InjectedFault`, ``latency``
    sleeps, ``nonfinite`` returns a poisoned copy of ``value``."""
    if not _settings.resil:
        return value
    if site not in CATALOG:
        raise ValueError(
            f"fault_point({site!r}): site not in catalog "
            f"(tools/check_fault_sites.py should have caught this)")
    # Unlocked emptiness/get probes are GIL-atomic dict reads: the
    # zero-arm common case must not take a lock per fault_point, and
    # the hit path re-reads under the lock below before acting.
    if not _arms:  # lint: disable=lock-discipline — lock-free zero-arm fast path
        return value
    arm = _arms.get(site)  # lint: disable=lock-discipline — re-read under lock below
    if arm is None:
        return value
    if not _trace_clean():
        _obs.inc("resil.fault.trace_skipped")
        return value
    with _lock:
        # Re-read under the lock (clear() may have raced the fast path).
        arm = _arms.get(site)
        if arm is None:
            return value
        arm.calls += 1
        fire = (arm.calls > arm.after and arm.fired < arm.count
                and (arm.p >= 1.0
                     or _lcg01(arm.seed, arm.calls) < arm.p))
        if fire:
            arm.fired += 1
            ordinal = arm.fired
            kind = arm.kind
            latency_ms = arm.latency_ms
            device = int(arm.meta.get("device", 0))
    if not fire:
        return value
    _obs.inc("resil.fault.injected")
    _obs.inc(f"resil.fault.{site}.injected")
    _obs.event("resil.fault", site=site, kind=kind, ordinal=ordinal)
    if kind == "error":
        raise InjectedFault(site, ordinal)
    if kind == "device_loss":
        raise DeviceLost(site, ordinal, device)
    if kind == "latency":
        if latency_ms > 0:
            time.sleep(latency_ms / 1e3)
        return value
    # nonfinite
    if value is None:
        return None
    return _poison(value)
