# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Opt-in solver health detection at the existing host-sync points.

A NaN-producing solve today returns silent garbage: the while_loop
runs to ``maxiter`` (NaN compares false against the tolerance) and the
caller gets a vector of NaNs with a plausible iteration count.  This
module turns that into a *structured outcome* — site, cause,
iterations completed, partial residual — raised from the same per-
cycle scalar fetch the convergence decision already pays for, so
detection adds zero extra host syncs.

Opt-in twice over: requires both ``LEGATE_SPARSE_TPU_RESIL`` (the
subsystem master) and ``LEGATE_SPARSE_TPU_RESIL_HEALTH`` — residual
monitoring changes solver *failure* semantics (raises instead of
returning), which a caller must ask for.

Causes
------
- ``non_finite``   the fetched residual (or cycle-start norm) is NaN
                   or Inf — the classic silent-garbage precursor.
- ``divergence``   residual grew past ``resil_divergence_mult`` x the
                   initial residual (breakdown surfaced as a number,
                   not an eventual overflow).
- ``stagnation``   no relative improvement of the best residual for
                   ``resil_stagnation_cycles`` consecutive
                   observations (0 disables — default).

Each detection increments ``resil.health.<cause>`` and
``resil.health.<site>.<cause>`` and raises
:class:`SolverHealthError` carrying a :class:`..outcomes.HealthReport`
plus the partial iterate.
"""

from __future__ import annotations

import math
from typing import Any, Optional

from .. import obs as _obs
from ..settings import settings as _settings
from .outcomes import FinalOutcomeError, HealthReport

# Relative improvement of the best-so-far residual that resets the
# stagnation clock.  Fixed (not a knob): stagnation detection asks "is
# the solver still moving at all", not "is it fast".
STAGNATION_RTOL = 1e-3


class SolverHealthError(FinalOutcomeError):
    """An unhealthy solve, surfaced instead of silent NaNs.

    ``report`` is the structured verdict; ``partial`` the last iterate
    (device array, no extra transfer paid)."""

    def __init__(self, report: HealthReport, partial: Any = None):
        self.report = report
        self.partial = partial
        super().__init__(
            f"solver health: {report.cause} at {report.site} after "
            f"{report.iterations} iterations"
            + (f" (residual {report.residual:.3e})"
               if isinstance(report.residual, float)
               and math.isfinite(report.residual) else
               f" (residual {report.residual})"
               if report.residual is not None else ""))


def active() -> bool:
    """Health detection on? (master switch AND the health opt-in)."""
    return bool(_settings.resil and _settings.resil_health)


def _raise(site: str, cause: str, iterations: int,
           residual: Optional[float], partial: Any,
           detail: str = "") -> None:
    _obs.inc(f"resil.health.{cause}")
    _obs.inc(f"resil.health.{site}.{cause}")
    _obs.event("resil.health", site=site, cause=cause,
               iterations=iterations, residual=residual)
    raise SolverHealthError(
        HealthReport(site=site, cause=cause, iterations=int(iterations),
                     residual=residual, detail=detail),
        partial=partial)


class Monitor:
    """Per-solve residual monitor fed at each host-sync point.

    Construct once per solve; ``observe(residual, iterations,
    partial)`` at every convergence fetch.  No-op (two attribute
    reads) when health detection is off."""

    def __init__(self, site: str):
        self.site = site
        self._initial: Optional[float] = None
        self._best = math.inf
        self._since_best = 0

    def observe(self, residual: float, iterations: int,
                partial: Any = None) -> None:
        if not active():
            return
        r = float(residual)
        if not math.isfinite(r):
            _raise(self.site, "non_finite", iterations, r, partial)
        if self._initial is None:
            self._initial = r
        mult = float(_settings.resil_divergence_mult)
        if mult > 0 and r > mult * max(self._initial, 1e-300):
            _raise(self.site, "divergence", iterations, r, partial,
                   detail=f"initial={self._initial:.3e}")
        cycles = int(_settings.resil_stagnation_cycles)
        if cycles > 0:
            if r < self._best * (1.0 - STAGNATION_RTOL):
                self._best = r
                self._since_best = 0
            else:
                self._since_best += 1
                if self._since_best >= cycles:
                    _raise(self.site, "stagnation", iterations, r,
                           partial,
                           detail=f"best={self._best:.3e} for "
                                  f"{self._since_best} cycles")
