# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Typed outcomes of the resilience layer.

A failure the layer could not absorb never surfaces as a silent NaN
result, a dropped request, or a hang — it surfaces as one of these
types, each carrying enough structure (site, iterations completed,
partial residual/result) for the caller to decide between degrading,
re-queueing, and reporting.

- :class:`Rejected` — a request shed *before* dispatch (expired
  deadline at the executor's admission or flush point).  It is a
  **value**, not an exception: the executor resolves the request's
  Future with it, because for serving traffic "not done, and here is
  why" is a normal response, not a crash.
- :class:`DeadlineExceeded` — a solve cut off *mid-flight* at one of
  its host-sync points.  Raised, because the caller asked for a
  converged solution and is not getting one; the exception carries the
  partial iterate so a caller with laxer requirements can still use
  it.
- :class:`ResilienceError` — base class of every exception this layer
  raises (``policy.CircuitOpenError`` and
  ``health.SolverHealthError`` included), so one ``except`` clause
  covers the whole contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


class ResilienceError(RuntimeError):
    """Base class of every exception the resilience layer raises."""


class FinalOutcomeError(ResilienceError):
    """A resilience *verdict* (deadline expired, health failure, open
    breaker) as opposed to a retryable fault: ``policy.run`` re-raises
    these immediately — retrying a deadline expiry would re-run a
    whole solve past its deadline, and a verdict is not a site
    failure, so it never feeds the breaker either."""


#: Closed vocabulary of shed/reject causes.  ``deadline_shed`` — the
#: request's deadline expired (at admission, flush, or a deadline
#: storm eviction); ``quota`` — the tenant's token bucket ran dry;
#: ``queue_full`` — a per-tenant queue quota or the global pending
#: bound was hit (including backpressure eviction of a queued
#: victim); ``breaker`` — shed during a breaker-open degraded window.
REJECT_REASONS = ("deadline_shed", "quota", "queue_full", "breaker")


@dataclass(frozen=True)
class Rejected:
    """A request shed before dispatch (typed outcome, not an error).

    ``site`` is the shedding point (``engine.exec.queue`` for
    admission, ``engine.exec.dispatch`` for a flush-time shed,
    ``gateway.admit`` / ``gateway.dispatch`` for the multi-tenant
    gateway), ``reason`` one of :data:`REJECT_REASONS`,
    ``waited_ms`` how long the request sat in the queue before the
    shed decision, ``deadline_ms`` the budget it arrived with, and
    ``tenant`` the owning tenant when shed by the gateway.

    Backward compatible: the pre-typed spelling ``reason="deadline"``
    (PR 5..8 executor sheds) normalizes to ``deadline_shed``; any
    string outside the vocabulary fails loudly at construction."""

    site: str
    reason: str = "deadline_shed"
    waited_ms: float = 0.0
    deadline_ms: Optional[float] = None
    tenant: Optional[str] = None

    def __post_init__(self) -> None:
        if self.reason == "deadline":        # legacy spelling
            object.__setattr__(self, "reason", "deadline_shed")
        if self.reason not in REJECT_REASONS:
            raise ValueError(
                f"Rejected.reason={self.reason!r}: expected one of "
                f"{REJECT_REASONS}")


class DeadlineExceeded(FinalOutcomeError):
    """A solve ran out of deadline at a host-sync point.

    ``iterations`` is the count completed when the deadline check
    fired, ``residual`` the last observed residual norm (None when the
    site had not fetched one yet), ``partial`` the best iterate so far
    (a device array — no extra transfer was paid to raise this)."""

    def __init__(self, site: str, iterations: int = 0,
                 residual: Optional[float] = None,
                 partial: Any = None):
        self.site = site
        self.iterations = int(iterations)
        self.residual = residual
        self.partial = partial
        super().__init__(
            f"deadline exceeded at {site} after {iterations} "
            f"iterations"
            + (f" (residual {residual:.3e})"
               if isinstance(residual, float) else ""))


class DeviceLost(FinalOutcomeError):
    """A mesh device vanished mid-solve (detected at a host-sync
    point — the conv-fetch cadence is the only place a distributed
    solve touches the host, so it is also where loss is observed).

    A final outcome, not a retryable fault: retrying the same dispatch
    on the same (now smaller) device set would fail identically, and
    feeding the breaker would poison the site for the *recovered*
    mesh.  ``policy.run`` re-raises immediately; the recovery ladder
    in ``dist_cg`` / ``dist_gmres`` catches it, shrinks the mesh to
    the survivor grid, reshards, restores the last checkpoint, and
    resumes (docs/RESILIENCE.md, "Recovery ladder")."""

    def __init__(self, site: str, ordinal: int = 0,
                 device: int = 0):
        self.site = site
        self.ordinal = int(ordinal)
        self.device = int(device)
        super().__init__(
            f"device {device} lost at {site} (ordinal {ordinal})")


class ChecksumError(ResilienceError):
    """An ABFT checksum mismatch: the y-checksum of a distributed SpMV
    disagreed with the column-checksum prediction, i.e. a collective
    (or the kernel feeding it) corrupted data in flight.  Retryable —
    ``policy.run`` at the ``dist.spmv`` site re-dispatches the SpMV,
    which recomputes from the (intact) operands — unlike the final
    verdicts above."""

    def __init__(self, site: str, observed: float, expected: float):
        self.site = site
        self.observed = float(observed)
        self.expected = float(expected)
        super().__init__(
            f"ABFT checksum mismatch at {site}: observed "
            f"{observed!r}, expected {expected!r}")


@dataclass(frozen=True)
class HealthReport:
    """Structured description of an unhealthy solve (see
    ``health.SolverHealthError``): which sync point saw it, why
    (``non_finite`` / ``stagnation`` / ``divergence``), how far the
    solve got, and the residual that triggered the verdict."""

    site: str
    cause: str
    iterations: int
    residual: Optional[float] = None
    detail: str = ""
    extra: dict = field(default_factory=dict)
