# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Per-site retry ladders, retry budgets, and circuit breakers.

One function is the whole integration surface::

    y = policy.run("engine.exec.dispatch", attempt, fallback=plain)

``run`` executes ``attempt`` under the site's policy:

- **retry with deterministic exponential backoff** — up to
  ``settings.resil_retries`` re-executions, sleeping
  ``backoff_ms * mult**attempt`` (clamped at ``backoff_max_ms``)
  between them.  The schedule is deterministic (no jitter): drills
  assert exact counter accounting, and a single-tenant accelerator
  queue gains nothing from decorrelation.
- **retry budgets** — a per-site, per-process budget
  (``settings.resil_retry_budget``) bounds total retry amplification:
  a persistently failing hot loop degrades to fail-fast instead of
  multiplying its own load by ``1 + retries``.
- **circuit breaker** — ``closed -> open`` after K *consecutive*
  failures (``settings.resil_breaker_k``), ``open -> half_open`` after
  ``resil_breaker_cooldown_ms``, where exactly one probe call is let
  through: success closes the breaker, failure re-opens it.  While
  open, ``run`` short-circuits to ``fallback`` — for the engine
  dispatch site that *flips the existing ladder* (engine -> plain jit
  dispatch -> scipy-coverage fallback) instead of hammering a broken
  rung — or raises :class:`CircuitOpenError` when the site has no
  cheaper rung (fail fast IS the load-shedding behavior there).

Counters (always exact — drills assert equality, not >=):
``resil.retry.attempts`` / ``resil.retry.<site>`` /
``resil.retry.backoff_ms`` / ``resil.retry.exhausted`` /
``resil.retry.budget_exhausted``; ``resil.breaker.trips`` /
``resil.breaker.<site>.trips`` / ``.short_circuit`` / ``.half_open`` /
``.close``; ``resil.fallback`` / ``resil.fallback.<site>``.

With ``settings.resil`` off, ``run`` is ``fn()`` behind one flag read.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type

from .. import obs as _obs
from ..settings import settings as _settings
from .outcomes import FinalOutcomeError


class CircuitOpenError(FinalOutcomeError):
    """Raised by ``run`` when the site's breaker is open and no
    fallback rung exists — the typed fast-fail."""

    def __init__(self, site: str):
        self.site = site
        super().__init__(f"circuit breaker open for {site}")


class CircuitBreaker:
    """Closed / open / half-open breaker over consecutive failures.

    ``allow()`` answers "may this call proceed?" and performs the
    open -> half-open transition (electing exactly one probe);
    ``record_success`` / ``record_failure`` feed outcomes back."""

    def __init__(self, site: str, k: int, cooldown_s: float):
        self.site = site
        self.k = max(int(k), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        # Cooldown arithmetic on integer monotonic_ns (clock-step
        # safe; the source is read via the ``time`` module attribute
        # at call time so tests can freeze it).
        self.cooldown_ns = int(self.cooldown_s * 1e9)
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at_ns = 0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == "closed":
                return True
            now_ns = time.monotonic_ns()
            if self._state == "open":
                if now_ns - self._opened_at_ns < self.cooldown_ns:
                    return False
                self._state = "half_open"
                self._probing = True
                _obs.inc("resil.breaker.half_open")
                _obs.event("resil.breaker", site=self.site,
                           to="half_open")
                return True          # this caller is the probe
            # half_open: one probe in flight at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            # Any non-closed -> closed transition is a close in the
            # ledger (a concurrent trip can land between this call's
            # attempt and its feedback, so the open state is reachable
            # here too — the counter contract is exact either way).
            if self._state != "closed":
                _obs.inc("resil.breaker.close")
                _obs.event("resil.breaker", site=self.site, to="closed")
            self._state = "closed"
            self._failures = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half_open":
                self._trip_locked(reopen=True)
                return
            if self._state == "open":
                return
            self._failures += 1
            if self._failures >= self.k:
                self._trip_locked(reopen=False)

    def _trip_locked(self, reopen: bool) -> None:
        self._state = "open"
        self._opened_at_ns = time.monotonic_ns()
        self._failures = 0
        self._probing = False
        _obs.inc("resil.breaker.trips")
        _obs.inc(f"resil.breaker.{self.site}.trips")
        _obs.event("resil.breaker", site=self.site, to="open",
                   reopen=reopen)

    def release_probe(self) -> None:
        """Give back a half-open probe slot without a verdict.

        The probe call may end in a resilience *verdict*
        (``FinalOutcomeError``: deadline expiry, inner open breaker)
        that says nothing about this site's health — neither success
        nor failure.  Without this release the slot would stay taken
        and the breaker would wedge in half-open forever (no
        time-based exit from that state)."""
        with self._lock:
            if self._state == "half_open":
                self._probing = False

    def reset(self) -> None:
        with self._lock:
            self._state = "closed"
            self._failures = 0
            self._probing = False


_registry_lock = threading.Lock()
_breakers: Dict[str, CircuitBreaker] = {}
_budgets: Dict[str, int] = {}


def breaker(site: str) -> CircuitBreaker:
    """The site's breaker (created from the live settings knobs on
    first use)."""
    # Unlocked .get is a GIL-atomic dict read on the hot path; a miss
    # falls through to the locked double-checked create below.
    br = _breakers.get(site)  # lint: disable=lock-discipline — double-checked fast path
    if br is not None:
        return br
    with _registry_lock:
        br = _breakers.get(site)
        if br is None:
            br = _breakers[site] = CircuitBreaker(
                site, _settings.resil_breaker_k,
                _settings.resil_breaker_cooldown_ms / 1e3)
        return br


def _take_budget(site: str) -> bool:
    """Consume one unit of the site's retry budget; False when dry."""
    with _registry_lock:
        left = _budgets.get(site)
        if left is None:
            left = max(int(_settings.resil_retry_budget), 0)
        if left <= 0:
            _budgets[site] = 0
            return False
        _budgets[site] = left - 1
        return True


def reset() -> None:
    """Drop every breaker and refill every budget (tests / bench
    phases; live traffic never needs this)."""
    with _registry_lock:
        _breakers.clear()
        _budgets.clear()


def run(site: str, fn: Callable, fallback: Optional[Callable] = None,
        retryable: Tuple[Type[BaseException], ...] = (Exception,)):
    """Execute ``fn`` under ``site``'s retry/breaker policy (module
    docstring).  ``fallback`` is invoked (once, unretried) when the
    breaker is open or retries are exhausted; without one the last
    error (or :class:`CircuitOpenError`) propagates."""
    if not _settings.resil:
        return fn()
    br = breaker(site)
    if not br.allow():
        _obs.inc("resil.breaker.short_circuit")
        _obs.inc(f"resil.breaker.{site}.short_circuit")
        if fallback is not None:
            _obs.inc("resil.fallback")
            _obs.inc(f"resil.fallback.{site}")
            return fallback()
        raise CircuitOpenError(site)
    retries = max(int(_settings.resil_retries), 0)
    attempt = 0
    while True:
        try:
            out = fn()
        except FinalOutcomeError:
            # A verdict from a nested resilience layer (deadline
            # expiry, health failure, open inner breaker) is not a
            # site failure: no retry, no breaker feedback, no
            # fallback masking — it IS the answer.  If this call held
            # the half-open probe slot, give it back (a verdict is
            # not a probe outcome).
            br.release_probe()
            raise
        except retryable:
            br.record_failure()
            # Re-consult the breaker BEFORE another attempt: this
            # call's own failures may just have tripped it, and a
            # tripped site must not keep getting hammered from inside
            # the retry loop (allow() may instead elect this attempt
            # as the half-open probe, whose success/failure feedback
            # the normal paths handle).
            if attempt < retries and br.allow():
                if not _take_budget(site):
                    _obs.inc("resil.retry.budget_exhausted")
                else:
                    delay_ms = min(
                        _settings.resil_backoff_ms
                        * (_settings.resil_backoff_mult ** attempt),
                        _settings.resil_backoff_max_ms)
                    _obs.inc("resil.retry.attempts")
                    _obs.inc(f"resil.retry.{site}")
                    _obs.inc("resil.retry.backoff_ms", delay_ms)
                    if delay_ms > 0:
                        time.sleep(delay_ms / 1e3)
                    attempt += 1
                    continue
            _obs.inc("resil.retry.exhausted")
            if fallback is not None:
                _obs.inc("resil.fallback")
                _obs.inc(f"resil.fallback.{site}")
                return fallback()
            raise
        except BaseException:
            # Non-Exception escapes (KeyboardInterrupt, SystemExit)
            # bypass the retryable clause entirely — release a held
            # probe slot so the breaker cannot wedge in half-open.
            br.release_probe()
            raise
        else:
            br.record_success()
            return out
