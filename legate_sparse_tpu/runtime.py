# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Runtime singleton: device discovery and global configuration.

The reference's runtime shim (reference: ``legate_sparse/runtime.py:54-107``)
wraps the Legion machine model — store creation, task factories, processor
counts.  On TPU none of that exists: XLA owns compilation and placement, and
``jax.sharding`` owns distribution.  What remains useful is a single place
that answers "how many devices do I have", "what mesh should ops default
to", and dtype-policy questions — that is this module.
"""

from __future__ import annotations

import numpy as np

from .settings import settings

import jax


class Runtime:
    """Process-wide singleton (analog of reference ``runtime.py:54``)."""

    def __init__(self) -> None:
        if settings.x64:
            # scipy-parity: default dtype is float64 (emulated on TPU;
            # benchmarks opt into float32/bfloat16 explicitly).
            jax.config.update("jax_enable_x64", True)
        if settings.check_bounds:
            # Debug mode (reference --check-bounds analog): first NaN
            # from any kernel raises with a traceback; index invariants
            # are validated at construction (csr.py).
            jax.config.update("jax_debug_nans", True)
        self._default_mesh = None

    @property
    def num_devices(self) -> int:
        return len(jax.devices())

    @property
    def num_procs(self) -> int:
        return self.num_devices

    @property
    def num_gpus(self) -> int:  # parity shim; TPUs are the accelerator here
        return sum(1 for d in jax.devices() if d.platform != "cpu")

    @property
    def default_mesh(self):
        """1-D mesh over all addressable devices, axis name ``rows``.

        Lazily built; the TPU analog of Legion picking a launch domain
        from the machine (reference ``runtime.py:75-81``).
        """
        if self._default_mesh is None:
            from .parallel.mesh import make_row_mesh

            self._default_mesh = make_row_mesh()
        return self._default_mesh

    def set_default_mesh(self, mesh) -> None:
        self._default_mesh = mesh

    # Value dtype used when constructors receive python lists / no dtype.
    @property
    def default_float(self) -> np.dtype:
        return np.dtype(np.float64) if settings.x64 else np.dtype(np.float32)


runtime = Runtime()
