# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Environment-driven settings.

Parity with the reference's settings layer (reference:
``legate_sparse/settings.py:22-48``), re-expressed without Legate's
``PrioritizedSetting`` machinery: each setting reads an environment
variable once at import, and can be overridden programmatically.

Settings
--------
``precise_images`` (``LEGATE_SPARSE_PRECISE_IMAGES``)
    Reference semantics: use precise Legion image partitions instead of
    min/max bounding-box approximations (reference ``settings.py:23-33``).
    Accepted for parity.  CURRENT STATUS: informational only — the
    distributed SpMV always uses the min/max column-window (halo) or
    all_gather realization; a precise per-index gather path is planned.

``fast_spgemm`` (``LEGATE_SPARSE_FAST_SPGEMM``)
    Reference semantics: pick cuSPARSE SpGEMM ALG1 (fast, memory hungry)
    over ALG3 (reference ``settings.py:35-45``).  Accepted for parity.
    CURRENT STATUS: informational only — the ESC SpGEMM always performs
    one full sort; a chunked low-memory mode is planned
    (``spgemm_chunk_products`` reserves its chunk size).

``x64`` (``LEGATE_SPARSE_TPU_X64``)
    Enable float64 (scipy-parity default: on).  Set to ``0`` for
    TPU-native float32/bfloat16-only operation.
"""

import os


def _env_bool(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() not in ("0", "false", "no", "off", "")


class Settings:
    def __init__(self) -> None:
        self.precise_images: bool = _env_bool("LEGATE_SPARSE_PRECISE_IMAGES", False)
        self.fast_spgemm: bool = _env_bool("LEGATE_SPARSE_FAST_SPGEMM", False)
        self.x64: bool = _env_bool("LEGATE_SPARSE_TPU_X64", True)
        # SpMV fast path: pack CSR into ELL (rows, max-row-nnz) when the
        # padded size stays within this multiple of the true nnz.  TPU
        # gathers over a rectangular layout run at HBM roofline; scatter-
        # based segment sums do not.  Set to 0 to disable ELL packing.
        self.ell_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_ELL_EXPAND", "4.0")
        )
        # Capacity multiplier for spgemm chunked mode (rows per chunk heuristic).
        self.spgemm_chunk_products: int = int(
            os.environ.get("LEGATE_SPARSE_SPGEMM_CHUNK", 1 << 24)
        )


settings = Settings()
