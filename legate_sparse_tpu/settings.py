# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Environment-driven settings.

Parity with the reference's settings layer (reference:
``legate_sparse/settings.py:22-48``), re-expressed without Legate's
``PrioritizedSetting`` machinery: each setting reads an environment
variable once at import, and can be overridden programmatically.

Settings
--------
``precise_images`` (``LEGATE_SPARSE_PRECISE_IMAGES``)
    Reference semantics: use precise Legion image partitions instead of
    min/max bounding-box approximations (reference ``settings.py:23-33``).
    Here: ``shard_csr`` builds a per-shard exact gather plan (the unique
    x entries each shard reads, exchanged via ``all_to_all``) instead of
    the min/max column-window/halo realization — communication and
    gather working set shrink from O(window) to O(unique columns).
    Per-matrix override: ``shard_csr(..., precise=True/False)``.

``fast_spgemm`` (``LEGATE_SPARSE_FAST_SPGEMM``)
    Reference semantics: pick cuSPARSE SpGEMM ALG1 (fast, memory hungry)
    over ALG3 (reference ``settings.py:35-45``).  Here: ``True`` forces
    the single-shot (T,)-sized ESC expansion; ``False`` (default) caps
    the expansion at ``spgemm_chunk_products`` products per chunk
    (``LEGATE_SPARSE_SPGEMM_CHUNK``), bounding peak memory at
    O(chunk + nnz_C) for product-heavy multiplies.

``x64`` (``LEGATE_SPARSE_TPU_X64``)
    ``1``/``0`` force float64 support on/off; unset (or ``auto``)
    resolves by platform *without initializing any jax backend*:
    CPU-hosted processes (``JAX_PLATFORMS`` names cpu first, e.g. the
    test suite / multichip dryrun) get scipy-parity float64;
    TPU-hosted processes (``JAX_PLATFORMS`` names tpu/axon first, or a
    TPU runtime is importable) get float32/int32 — on TPU float64 is
    emulated (~10x slower) and 64-bit types are rejected by Mosaic
    (Pallas) kernels outright.  Other accelerator names resolve to
    float64 (the split is TPU-specific; CUDA f64 is native, which is
    also why the reference needs no such policy).

``obs`` (``LEGATE_SPARSE_TPU_OBS``)
    Observability: op-level span tracing (``legate_sparse_tpu.obs``).
    Off by default — the span API is a no-op context manager and the
    hot paths pay only a module-global check.  Exposed here as a
    property delegating to ``obs.trace`` so ``settings.obs = True``
    and the env var are equivalent switches.
    ``LEGATE_SPARSE_TPU_OBS_FILE`` names the default trace artifact
    (``bench.py`` derives its ``BENCH_*.trace.json`` from it).

``check_bounds`` (``LEGATE_SPARSE_TPU_CHECK_BOUNDS``)
    Debug mode, the analog of the reference's ``--check-bounds``
    build flag (reference ``install.py:375-381`` wiring
    ``Legion_BOUNDS_CHECKS``): validates index invariants (indices
    within [0, cols), indptr monotone and consistent) at array
    construction, and turns on ``jax_debug_nans`` so the first NaN
    produced by any kernel raises with a traceback.

``engine`` (``LEGATE_SPARSE_TPU_ENGINE``)
    Execution engine (``legate_sparse_tpu.engine``): shape-bucketed
    plan cache + micro-batching request executor.  Off by default —
    with it on, eligible matvec/solve hot paths run through cached
    executables whose shapes are padded to policy buckets, so nearby
    ``n``/``nnz`` hit one compiled program instead of retracing.
    Knobs (all env-overridable, see ``docs/ENGINE.md``):

    - ``engine_bucket_ladder`` (``LEGATE_SPARSE_TPU_ENGINE_BUCKETS``):
      comma-separated ascending sizes; empty = power-of-two buckets.
    - ``engine_min_bucket`` (``..._ENGINE_MIN_BUCKET``): floor bucket,
      bounds tiny-matrix plan proliferation.
    - ``engine_plan_cache_size`` (``..._ENGINE_PLANS``): LRU capacity.
    - ``engine_max_batch`` / ``engine_queue_depth`` /
      ``engine_batch_timeout_ms`` (``..._ENGINE_BATCH`` / ``..._QUEUE``
      / ``..._BATCH_TIMEOUT_MS``): executor micro-batching limits and
      backpressure bound.
    - ``engine_persist_dir`` (``..._ENGINE_PERSIST``): when set, plans
      additionally back onto JAX's persistent compilation cache there
      (process-global: it captures every XLA compile, not only engine
      plans — scope caveat in ``docs/ENGINE.md``).

``resil`` (``LEGATE_SPARSE_TPU_RESIL``)
    Resilience subsystem (``legate_sparse_tpu.resilience``,
    ``docs/RESILIENCE.md``): fault injection, retry/backoff ladders,
    circuit breakers, deadline propagation and load shedding for the
    engine and distributed ops.  Off by default — every instrumented
    site is then one flag read with zero behavior change.  Knobs (all
    env-overridable, prefix ``LEGATE_SPARSE_TPU_RESIL_``):

    - ``resil_retries`` (``_RETRIES``, 2): re-executions per failed
      site call.
    - ``resil_backoff_ms`` / ``resil_backoff_mult`` /
      ``resil_backoff_max_ms`` (``_BACKOFF_MS``/``_BACKOFF_MULT``/
      ``_BACKOFF_MAX_MS``): deterministic exponential backoff
      schedule between retries.
    - ``resil_retry_budget`` (``_RETRY_BUDGET``, 64): per-site
      per-process cap on total retries (amplification bound).
    - ``resil_breaker_k`` / ``resil_breaker_cooldown_ms``
      (``_BREAKER_K``/``_BREAKER_COOLDOWN_MS``): consecutive failures
      that trip a site's circuit breaker, and the open->half-open
      cooldown.
    - ``resil_health`` (``_HEALTH``): opt-in solver health detection
      (non-finite / divergence / stagnation raised as structured
      outcomes); ``resil_stagnation_cycles`` (``_STAGNATION_CYCLES``,
      0 = off) and ``resil_divergence_mult`` (``_DIVERGENCE_MULT``)
      tune it.
    - ``resil_ckpt_iters`` (``_CKPT_ITERS``, 0 = off): default
      solver checkpoint cadence — snapshot the solve state every k
      convergence fetches (``resilience.checkpoint``); the recovery
      ladder restores the last snapshot after a device loss.
    - ``resil_abft`` (``_ABFT``): opt-in ABFT-checksummed eager
      distributed SpMV (column-checksum verification of y; mismatch
      raises a retryable ``ChecksumError``).

``gateway`` (``LEGATE_SPARSE_TPU_GATEWAY``)
    Multi-tenant admission gateway (``legate_sparse_tpu.engine.gateway``,
    ``docs/ENGINE.md``): per-tenant QoS classes, token-bucket rate
    limits, queue quotas, weighted-fair-queueing batch formation and
    deadline-aware dispatch in front of the execution engine.  Off by
    default — no existing call path routes through the gateway, and
    ``Gateway.submit`` degrades to a transparent inline dispatch, so
    behavior and counters stay bit-for-bit those of the engine alone.
    Knobs (all env-overridable, prefix ``LEGATE_SPARSE_TPU_GATEWAY_``):

    - ``gateway_max_batch`` (``_BATCH``, 8): requests packed per
      stacked dispatch.
    - ``gateway_queue_depth`` (``_QUEUE``, 128): global pending bound —
      beyond it admission evicts by least-slack/lowest-class.
    - ``gateway_tenant_quota`` (``_TENANT_QUOTA``, 32): per-tenant
      queued-request cap (reason ``queue_full`` beyond it).
    - ``gateway_rate`` / ``gateway_burst`` (``_RATE``/``_BURST``):
      per-tenant token-bucket refill (requests/s, 0 = unlimited) and
      capacity (reason ``quota`` when empty).
    - ``gateway_slack_ms`` (``_SLACK_MS``, 5.0): deadline slack below
      which a request is dispatched immediately, never held for a
      fuller batch.
    - ``gateway_timeout_ms`` (``_TIMEOUT_MS``, 2.0): background drain
      cadence; ``<= 0`` = deterministic flush-only mode (tests).

``obs_slo`` (``LEGATE_SPARSE_TPU_OBS_SLO``)
    Declarative SLO burn-rate evaluation (``legate_sparse_tpu.obs.slo``,
    ``docs/OBSERVABILITY.md``): per-(op, QoS) latency objectives with
    error budgets, evaluated as multi-window burn rates over rebased
    snapshots of the always-on ``lat.*`` histograms.  Off by default —
    ``slo.evaluate()`` is then a single flag read returning ``[]``,
    and no ``slo.*`` counter ever moves (inertness pinned by test).
    ``obs_slo_watchdog_ms`` (``LEGATE_SPARSE_TPU_OBS_SLO_WATCHDOG_MS``,
    0 = off) arms a daemon watchdog thread evaluating on a
    monotonic-clock cadence.

``obs_attrib`` (``LEGATE_SPARSE_TPU_OBS_ATTRIB``)
    Per-tenant resource attribution + capacity advisor
    (``legate_sparse_tpu.obs.attrib`` / ``.capacity``,
    ``docs/OBSERVABILITY.md``): charges dispatch wall time, ``comm.*``
    bytes, queue wait, and memory-watermark growth to the
    ``(tenant, qos)`` identity minted at ``Gateway.submit``, with a
    deterministic split rule for packed multi-tenant batches so
    per-tenant sums conserve exactly against the untagged totals.
    Off by default — every hook is then one flag read, no
    ``attrib.*``/``util.*``/``capacity.*`` counter ever moves, and
    results are bit-for-bit identical (inertness pinned by test).
    ``obs_tenant_cap`` (``LEGATE_SPARSE_TPU_OBS_TENANT_CAP``, 64)
    bounds distinct tenant labels; overflow folds into ``__other__``.

``placement`` (``LEGATE_SPARSE_TPU_PLACEMENT``)
    Closed-loop elastic placement (``legate_sparse_tpu.placement``,
    ``docs/PLACEMENT.md``): carves the global device grid into
    contiguous per-tenant submeshes sized from QoS weight and observed
    demand (``capacity.recommend``), with an SLO-burn-driven
    controller that prices every migration via ``reshard_volumes``
    and live-migrates tenant matrices behind the gateway.  Off by
    default — the gateway pays one flag read per armed admission, no
    ``placement.*`` counter ever moves, and results are bit-for-bit
    those of the shared global mesh (inertness pinned by test).
    Knobs (all env-overridable, prefix ``LEGATE_SPARSE_TPU_PLACEMENT_``):

    - ``placement_cooldown_ms`` (``_COOLDOWN_MS``, 1000.0): minimum
      wall time between executed migrations (anti-flap hysteresis;
      breaker-driven shrinks override it).
    - ``placement_watchdog_ms`` (``_WATCHDOG_MS``, 0 = off): arms a
      daemon controller thread stepping on a monotonic-clock cadence
      (mirrors the SLO watchdog).
    - ``placement_amortize`` (``_AMORTIZE``, 1.0): predicted savings
      must reach this multiple of the priced migration cost before an
      efficiency-driven move executes.
    - ``placement_bw_gbps`` (``_BW_GBPS``, 10.0): assumed migration
      bandwidth converting priced bytes into amortization cost time.

``delta`` (``LEGATE_SPARSE_TPU_DELTA``)
    Streaming matrix mutation under live traffic
    (``legate_sparse_tpu.delta``, ``docs/MUTATION.md``): a
    ``DeltaCSR`` wrapper serving an immutable base ``csr_array`` plus
    a bounded COO side-buffer of entry updates as ``base @ x +
    delta @ x``, with background compaction merging the buffer into a
    fresh base and atomically swapping versions behind the gateway.
    Off by default — the gateway pays one flag read per armed
    admission, no ``delta.*`` counter ever moves, and results are
    bit-for-bit those of the immutable path (inertness pinned by
    test).  Knobs (prefix ``LEGATE_SPARSE_TPU_DELTA_``):

    - ``delta_capacity`` (``_CAPACITY``, 1024): distinct (row, col)
      update slots before ``update()`` raises ``DeltaCapacityError``.
    - ``delta_watermark`` (``_WATERMARK``, 0.75): pending/capacity
      fraction that flags the matrix for background compaction.
    - ``delta_worker_ms`` (``_WORKER_MS``, 0 = off): arms a daemon
      compaction worker stepping on a monotonic-clock cadence.

``autotune`` (``LEGATE_SPARSE_TPU_AUTOTUNE``)
    Sparsity-fingerprint autotuner (``legate_sparse_tpu.autotune``,
    ``docs/AUTOTUNER.md``): measured kernel selection for the
    gather-class SpMV/SpMM paths, keyed on a structure fingerprint.
    Off by default — every dispatch site then pays one attribute read
    and nothing else.  Knobs (all env-overridable):

    - ``autotune_store_path`` (``..._AUTOTUNE_STORE``): optional JSON
      file verdicts persist to / warm-start from (epoch- and
      platform-invalidated on load).
    - ``autotune_store_size`` (``..._AUTOTUNE_VERDICTS``, 256): verdict
      LRU capacity.
    - ``autotune_trials`` (``..._AUTOTUNE_TRIALS``, 5) and
      ``autotune_warmup`` (``..._AUTOTUNE_WARMUP``, 1): median-of-k
      measurement budget per candidate.

``graph`` knobs (``legate_sparse_tpu.graph``)
    - ``graph_max_iters`` (``LEGATE_SPARSE_TPU_GRAPH_MAX_ITERS``,
      0 = n+1): sweep cap for BFS / connected-components semiring
      traversal loops.
    - ``graph_conv_iters`` (``LEGATE_SPARSE_TPU_GRAPH_CONV_ITERS``,
      5): PageRank device iterations per host convergence fetch
      (one-fetch-per-cycle cadence; see ``docs/GRAPH.md``).

Settings epoch
--------------
``settings.epoch`` is a monotone counter bumped by every post-import
VALUE CHANGE of a lowering-relevant setting.  Compiled-plan caches
(``engine.plan_cache``) key on it, so flipping a setting that could
change lowering (kernel budgets, variants) naturally invalidates
cached executables instead of serving stale programs.  ``obs`` and
``engine`` are exempt (they gate tracing/routing, never lowering), so
turning observability on to watch a warmed server does not void the
``warmup()`` guarantee.
"""

import os


def _parse_ladder(spec: str) -> tuple:
    """Parse a user bucket ladder ("1024,4096,65536") into an ascending
    int tuple; empty spec = () = power-of-two policy.  A malformed
    ladder must fail loudly at import, not silently bucket wrong."""
    spec = spec.strip()
    if not spec:
        return ()
    try:
        rungs = tuple(sorted({int(tok) for tok in spec.split(",")
                              if tok.strip()}))
    except ValueError:
        raise ValueError(
            f"LEGATE_SPARSE_TPU_ENGINE_BUCKETS={spec!r}: expected "
            f"comma-separated integers"
        ) from None
    if rungs and rungs[0] <= 0:
        raise ValueError(
            f"LEGATE_SPARSE_TPU_ENGINE_BUCKETS={spec!r}: rungs must "
            f"be positive"
        )
    return rungs


def _env_bool(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() not in ("0", "false", "no", "off", "")


def _looks_tpu_hosted() -> bool:
    """Heuristic TPU detection with NO jax backend init (initializing an
    unavailable tunnel can hang — the round-1 failure mode)."""
    if os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get(
        "TPU_WORKER_HOSTNAMES"
    ):
        return True
    try:
        import importlib.util

        return importlib.util.find_spec("libtpu") is not None
    except Exception:
        return False


def _resolve_x64() -> bool:
    val = os.environ.get("LEGATE_SPARSE_TPU_X64")
    if val is not None and val.lower() != "auto":
        return val.lower() not in ("0", "false", "no", "off", "")
    # Platform signal: a programmatic pin (jax.config, e.g. pin_cpu with
    # override_env=False under a TPU-set JAX_PLATFORMS env) outranks the
    # env var.  Reading jax.config does NOT initialize a backend.
    import sys

    plats = ""
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            plats = jax_mod.config.jax_platforms or ""
        except Exception:
            plats = ""
    if not plats:
        plats = os.environ.get("JAX_PLATFORMS", "")
    first = plats.split(",")[0].strip().lower()
    if first == "cpu":
        return True
    if first in ("tpu", "axon"):
        return False
    return not _looks_tpu_hosted()


class Settings:
    def __init__(self) -> None:
        self.precise_images: bool = _env_bool("LEGATE_SPARSE_PRECISE_IMAGES", False)
        self.fast_spgemm: bool = _env_bool("LEGATE_SPARSE_FAST_SPGEMM", False)
        # Default partition layout for shard_csr when no explicit
        # ``layout=`` argument is given: "1d-row" (historical default),
        # "1d-col", "2d-block", or "auto" (route by predicted bytes).
        # NOT epoch-exempt — the layout changes what dist plans lower
        # to.  See docs/DIST.md.
        self.dist_layout: str = os.environ.get(
            "LEGATE_SPARSE_TPU_DIST_LAYOUT", "1d-row"
        )
        self.x64: bool = _resolve_x64()
        self.check_bounds: bool = _env_bool(
            "LEGATE_SPARSE_TPU_CHECK_BOUNDS", False
        )
        # SpMV fast path: pack CSR into ELL (rows, max-row-nnz) when the
        # padded size stays within this multiple of the true nnz.  TPU
        # gathers over a rectangular layout run at HBM roofline; scatter-
        # based segment sums do not.  Set to 0 to disable ELL packing.
        self.ell_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_ELL_EXPAND", "4.0")
        )
        # Capacity multiplier for spgemm chunked mode (rows per chunk heuristic).
        self.spgemm_chunk_products: int = int(
            os.environ.get("LEGATE_SPARSE_SPGEMM_CHUNK", 1 << 24)
        )
        # SpMV fastest path: exactly-banded CSR matrices run gather-free
        # shifted-add (DIA) kernels when num_diags*cols stays within this
        # multiple of nnz.  Set to 0 to disable band detection.
        self.dia_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_DIA_EXPAND", "2.0")
        )
        self.dia_max_diags: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_DIA_MAX_DIAGS", "128")
        )
        # Irregular SpMV path: densify present 128x128 blocks and stream
        # them through the MXU (ops/bsr.py), skipping absent blocks,
        # when the densified size stays within this multiple of nnz.
        # 128.0 ~= the break-even vs the XLA gather path on v5e (useful
        # bandwidth law in ops/bsr.py docstring).  0 disables BSR.
        self.bsr_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_BSR_EXPAND", "128.0")
        )
        # Build the BSR structure on any platform (kernel runs in
        # interpret mode off-TPU) — differential-testing hook.
        self.bsr_force: bool = _env_bool("LEGATE_SPARSE_TPU_BSR_FORCE",
                                         False)
        # XLA banded-SpMV lowering: "fused" (padded single-pass form,
        # the TPU-friendly layout), "nopad" (interior/edge split that
        # skips the x-pad materialization — measured ~20-25% faster on
        # the CPU lane, where every avoided copy is bandwidth), or
        # "auto" (nopad on cpu backends, fused elsewhere).  Only the
        # XLA path is affected; the Pallas kernel stays the TPU fast
        # path.  A typo must fail loudly, not silently benchmark the
        # wrong kernel.
        self.dia_xla_variant: str = os.environ.get(
            "LEGATE_SPARSE_TPU_DIA_XLA", "auto"
        )
        if self.dia_xla_variant not in ("fused", "nopad", "auto"):
            raise ValueError(
                f"LEGATE_SPARSE_TPU_DIA_XLA="
                f"{self.dia_xla_variant!r}: expected one of "
                f"'fused', 'nopad', 'auto'"
            )
        # ---- execution engine (legate_sparse_tpu.engine) ----
        self.engine: bool = _env_bool("LEGATE_SPARSE_TPU_ENGINE", False)
        self.engine_bucket_ladder: tuple = _parse_ladder(
            os.environ.get("LEGATE_SPARSE_TPU_ENGINE_BUCKETS", "")
        )
        self.engine_min_bucket: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_ENGINE_MIN_BUCKET", "64")
        )
        self.engine_plan_cache_size: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_ENGINE_PLANS", "128")
        )
        self.engine_max_batch: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_ENGINE_BATCH", "8")
        )
        self.engine_queue_depth: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_ENGINE_QUEUE", "64")
        )
        self.engine_batch_timeout_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_ENGINE_BATCH_TIMEOUT_MS",
                           "2.0")
        )
        self.engine_persist_dir: str = os.environ.get(
            "LEGATE_SPARSE_TPU_ENGINE_PERSIST", ""
        )
        # ---- resilience (legate_sparse_tpu.resilience) ----
        self.resil: bool = _env_bool("LEGATE_SPARSE_TPU_RESIL", False)
        self.resil_retries: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_RETRIES", "2")
        )
        self.resil_backoff_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_BACKOFF_MS", "1.0")
        )
        self.resil_backoff_mult: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_BACKOFF_MULT",
                           "2.0")
        )
        self.resil_backoff_max_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_BACKOFF_MAX_MS",
                           "50.0")
        )
        self.resil_retry_budget: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_RETRY_BUDGET",
                           "64")
        )
        self.resil_breaker_k: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_BREAKER_K", "3")
        )
        self.resil_breaker_cooldown_ms: float = float(
            os.environ.get(
                "LEGATE_SPARSE_TPU_RESIL_BREAKER_COOLDOWN_MS", "100.0")
        )
        self.resil_health: bool = _env_bool(
            "LEGATE_SPARSE_TPU_RESIL_HEALTH", False
        )
        self.resil_stagnation_cycles: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_STAGNATION_CYCLES",
                           "0")
        )
        self.resil_divergence_mult: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_DIVERGENCE_MULT",
                           "1e8")
        )
        self.resil_ckpt_iters: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_RESIL_CKPT_ITERS", "0")
        )
        self.resil_abft: bool = _env_bool(
            "LEGATE_SPARSE_TPU_RESIL_ABFT", False
        )
        # ---- multi-tenant gateway (legate_sparse_tpu.engine.gateway) ----
        self.gateway: bool = _env_bool("LEGATE_SPARSE_TPU_GATEWAY",
                                       False)
        self.gateway_max_batch: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_BATCH", "8")
        )
        self.gateway_queue_depth: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_QUEUE", "128")
        )
        self.gateway_tenant_quota: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_TENANT_QUOTA",
                           "32")
        )
        self.gateway_rate: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_RATE", "0.0")
        )
        self.gateway_burst: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_BURST", "16.0")
        )
        self.gateway_slack_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_SLACK_MS", "5.0")
        )
        self.gateway_timeout_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_GATEWAY_TIMEOUT_MS",
                           "2.0")
        )
        # ---- SLO burn-rate evaluation (legate_sparse_tpu.obs.slo) ----
        self.obs_slo: bool = _env_bool("LEGATE_SPARSE_TPU_OBS_SLO",
                                       False)
        self.obs_slo_watchdog_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_OBS_SLO_WATCHDOG_MS",
                           "0")
        )
        # ---- per-tenant attribution (legate_sparse_tpu.obs.attrib) ----
        self.obs_attrib: bool = _env_bool(
            "LEGATE_SPARSE_TPU_OBS_ATTRIB", False)
        # Distinct tenant labels before counters fold into __other__
        # (bounded OpenMetrics label cardinality).
        self.obs_tenant_cap: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_OBS_TENANT_CAP", "64")
        )
        # ---- graph analytics (legate_sparse_tpu.graph) ----
        # Sweep cap for the semiring traversal loops (BFS/CC label
        # propagation); 0 = derive from the vertex count (n+1, the
        # structural bound).  SSSP keeps its own n-sweep cap — that
        # one is the negative-cycle detector, not a budget.
        self.graph_max_iters: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_GRAPH_MAX_ITERS", "0")
        )
        # PageRank convergence-fetch cadence: device iterations per
        # host residual fetch (the solvers' one-fetch-per-cycle
        # pattern; also quantizes iteration counts for the bench
        # golden).
        self.graph_conv_iters: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_GRAPH_CONV_ITERS", "5")
        )
        # ---- elastic placement (legate_sparse_tpu.placement) ----
        self.placement: bool = _env_bool("LEGATE_SPARSE_TPU_PLACEMENT",
                                         False)
        self.placement_cooldown_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_PLACEMENT_COOLDOWN_MS",
                           "1000.0")
        )
        self.placement_watchdog_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_PLACEMENT_WATCHDOG_MS",
                           "0")
        )
        self.placement_amortize: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_PLACEMENT_AMORTIZE",
                           "1.0")
        )
        self.placement_bw_gbps: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_PLACEMENT_BW_GBPS",
                           "10.0")
        )
        # ---- streaming mutation / delta layer (legate_sparse_tpu.delta) ----
        self.delta: bool = _env_bool("LEGATE_SPARSE_TPU_DELTA", False)
        # Side-buffer bound: distinct (row, col) update slots a
        # DeltaCSR may hold before update() raises DeltaCapacityError
        # (compact first).  Device buffers pad to pow2 buckets up to
        # this bound so streaming mutation never retraces.
        self.delta_capacity: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_DELTA_CAPACITY", "1024")
        )
        # Compaction watermark as a fraction of capacity: crossing it
        # flags the matrix for background compaction (and bumps
        # delta.watermark.exceeded — the doctor's compaction-lagging
        # evidence).
        self.delta_watermark: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_DELTA_WATERMARK", "0.75")
        )
        # Background compaction worker cadence (ms); 0 = no worker
        # thread — compaction runs only via compact() / the watermark
        # check at update time when a worker is armed.
        self.delta_worker_ms: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_DELTA_WORKER_MS", "0")
        )
        # ---- autotuner (legate_sparse_tpu.autotune) ----
        self.autotune: bool = _env_bool("LEGATE_SPARSE_TPU_AUTOTUNE",
                                        False)
        self.autotune_store_path: str = os.environ.get(
            "LEGATE_SPARSE_TPU_AUTOTUNE_STORE", ""
        )
        self.autotune_store_size: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_AUTOTUNE_VERDICTS",
                           "256")
        )
        self.autotune_trials: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_AUTOTUNE_TRIALS", "5")
        )
        self.autotune_warmup: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_AUTOTUNE_WARMUP", "1")
        )
        # Settings epoch: compiled-plan cache keys include it, so any
        # later settings mutation (see __setattr__) invalidates plans.
        self._epoch: int = 0
        self._init_done: bool = True

    # Settings that cannot change what a plan lowers to: mutating them
    # must NOT void warmup() guarantees (flipping ``obs`` on to watch
    # steady state would otherwise trigger the very compile storm one
    # is trying to measure; ``engine`` only gates routing; the
    # executor/cache knobs shape queueing and capacity, never the
    # compiled program — the bucket policy knobs are NOT exempt, they
    # legitimately change plan keys).
    _EPOCH_EXEMPT = frozenset({
        "obs", "engine", "engine_max_batch", "engine_queue_depth",
        "engine_batch_timeout_ms", "engine_plan_cache_size",
        "engine_persist_dir", "_epoch", "_init_done",
        # Resilience knobs shape retries/breakers/deadlines — the
        # request lifecycle around a dispatch, never what a plan
        # lowers to; flipping them (tests and the bench drill do, per
        # phase) must not void warmup() guarantees.
        "resil", "resil_retries", "resil_backoff_ms",
        "resil_backoff_mult", "resil_backoff_max_ms",
        "resil_retry_budget", "resil_breaker_k",
        "resil_breaker_cooldown_ms", "resil_health",
        "resil_stagnation_cycles", "resil_divergence_mult",
        "resil_ckpt_iters", "resil_abft",
        # Gateway knobs shape admission, fairness and queueing in
        # front of the engine — pure request-lifecycle policy, never
        # what a plan lowers to (the stacked multi-matrix plan is
        # keyed on its own bucketed batch size, not on these knobs).
        "gateway", "gateway_max_batch", "gateway_queue_depth",
        "gateway_tenant_quota", "gateway_rate", "gateway_burst",
        "gateway_slack_ms", "gateway_timeout_ms",
        # SLO evaluation only *reads* the always-on latency
        # histograms — pure telemetry, like ``obs``.
        "obs_slo", "obs_slo_watchdog_ms",
        # The attribution ledger only *tags* costs the obs stack
        # already measures — pure telemetry; the tenant-label cap
        # shapes counter naming, never any plan.
        "obs_attrib", "obs_tenant_cap",
        # Graph loop caps/cadence shape the HOST iteration loop around
        # semiring dist_spmv dispatches, never what any plan lowers to.
        "graph_max_iters", "graph_conv_iters",
        # Placement knobs shape which submesh serves a tenant and how
        # often the controller migrates — request-lifecycle policy in
        # front of the engine, never what any plan lowers to (the
        # per-submesh dist plans are keyed on their own
        # mesh_fingerprint; tests and the bench placement phase flip
        # these per phase).
        "placement", "placement_cooldown_ms", "placement_watchdog_ms",
        "placement_amortize", "placement_bw_gbps",
        # Delta knobs shape the mutation side-buffer's bound and
        # compaction cadence — request-lifecycle policy around the
        # serving path, never what any plan lowers to (a compaction
        # swaps in a FRESH base matrix whose packs/fingerprints are
        # new objects, so plan/autotune caches invalidate structurally
        # without an epoch bump; tests and the bench mutation phase
        # flip these per phase).
        "delta", "delta_capacity", "delta_watermark",
        "delta_worker_ms",
        # Autotune knobs pick *which already-compiled kernel* serves a
        # dispatch (routing) or shape the measurement budget — never
        # what any kernel lowers to.  Verdict keys carry the epoch
        # separately, so lowering-relevant mutations still invalidate
        # verdicts without these bumping the epoch themselves.
        "autotune", "autotune_store_path", "autotune_store_size",
        "autotune_trials", "autotune_warmup",
    })

    def __setattr__(self, name: str, value) -> None:
        # A post-init VALUE CHANGE of a lowering-relevant setting
        # bumps the epoch (a changed budget/variant can change what a
        # plan would lower to); no-op rewrites and exempt flags don't.
        d = self.__dict__
        if (d.get("_init_done") and name not in self._EPOCH_EXEMPT
                and (name not in d or d[name] != value)):
            d["_epoch"] = d.get("_epoch", 0) + 1
        super().__setattr__(name, value)

    @property
    def epoch(self) -> int:
        """Monotone settings-mutation counter (plan-cache key term)."""
        return self._epoch

    @property
    def obs(self) -> bool:
        """Span tracing on/off — delegates to ``obs.trace`` (single
        source of truth; the env var was read there at import)."""
        from .obs import trace

        return trace.enabled()

    @obs.setter
    def obs(self, value: bool) -> None:
        from .obs import trace

        if value:
            trace.enable()
        else:
            trace.disable()


settings = Settings()
