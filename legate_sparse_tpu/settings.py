# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Environment-driven settings.

Parity with the reference's settings layer (reference:
``legate_sparse/settings.py:22-48``), re-expressed without Legate's
``PrioritizedSetting`` machinery: each setting reads an environment
variable once at import, and can be overridden programmatically.

Settings
--------
``precise_images`` (``LEGATE_SPARSE_PRECISE_IMAGES``)
    Reference semantics: use precise Legion image partitions instead of
    min/max bounding-box approximations (reference ``settings.py:23-33``).
    Here: ``shard_csr`` builds a per-shard exact gather plan (the unique
    x entries each shard reads, exchanged via ``all_to_all``) instead of
    the min/max column-window/halo realization — communication and
    gather working set shrink from O(window) to O(unique columns).
    Per-matrix override: ``shard_csr(..., precise=True/False)``.

``fast_spgemm`` (``LEGATE_SPARSE_FAST_SPGEMM``)
    Reference semantics: pick cuSPARSE SpGEMM ALG1 (fast, memory hungry)
    over ALG3 (reference ``settings.py:35-45``).  Here: ``True`` forces
    the single-shot (T,)-sized ESC expansion; ``False`` (default) caps
    the expansion at ``spgemm_chunk_products`` products per chunk
    (``LEGATE_SPARSE_SPGEMM_CHUNK``), bounding peak memory at
    O(chunk + nnz_C) for product-heavy multiplies.

``x64`` (``LEGATE_SPARSE_TPU_X64``)
    ``1``/``0`` force float64 support on/off; unset (or ``auto``)
    resolves by platform *without initializing any jax backend*:
    CPU-hosted processes (``JAX_PLATFORMS`` names cpu first, e.g. the
    test suite / multichip dryrun) get scipy-parity float64;
    TPU-hosted processes (``JAX_PLATFORMS`` names tpu/axon first, or a
    TPU runtime is importable) get float32/int32 — on TPU float64 is
    emulated (~10x slower) and 64-bit types are rejected by Mosaic
    (Pallas) kernels outright.  Other accelerator names resolve to
    float64 (the split is TPU-specific; CUDA f64 is native, which is
    also why the reference needs no such policy).

``obs`` (``LEGATE_SPARSE_TPU_OBS``)
    Observability: op-level span tracing (``legate_sparse_tpu.obs``).
    Off by default — the span API is a no-op context manager and the
    hot paths pay only a module-global check.  Exposed here as a
    property delegating to ``obs.trace`` so ``settings.obs = True``
    and the env var are equivalent switches.
    ``LEGATE_SPARSE_TPU_OBS_FILE`` names the default trace artifact
    (``bench.py`` derives its ``BENCH_*.trace.json`` from it).

``check_bounds`` (``LEGATE_SPARSE_TPU_CHECK_BOUNDS``)
    Debug mode, the analog of the reference's ``--check-bounds``
    build flag (reference ``install.py:375-381`` wiring
    ``Legion_BOUNDS_CHECKS``): validates index invariants (indices
    within [0, cols), indptr monotone and consistent) at array
    construction, and turns on ``jax_debug_nans`` so the first NaN
    produced by any kernel raises with a traceback.
"""

import os


def _env_bool(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() not in ("0", "false", "no", "off", "")


def _looks_tpu_hosted() -> bool:
    """Heuristic TPU detection with NO jax backend init (initializing an
    unavailable tunnel can hang — the round-1 failure mode)."""
    if os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get(
        "TPU_WORKER_HOSTNAMES"
    ):
        return True
    try:
        import importlib.util

        return importlib.util.find_spec("libtpu") is not None
    except Exception:
        return False


def _resolve_x64() -> bool:
    val = os.environ.get("LEGATE_SPARSE_TPU_X64")
    if val is not None and val.lower() != "auto":
        return val.lower() not in ("0", "false", "no", "off", "")
    # Platform signal: a programmatic pin (jax.config, e.g. pin_cpu with
    # override_env=False under a TPU-set JAX_PLATFORMS env) outranks the
    # env var.  Reading jax.config does NOT initialize a backend.
    import sys

    plats = ""
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            plats = jax_mod.config.jax_platforms or ""
        except Exception:
            plats = ""
    if not plats:
        plats = os.environ.get("JAX_PLATFORMS", "")
    first = plats.split(",")[0].strip().lower()
    if first == "cpu":
        return True
    if first in ("tpu", "axon"):
        return False
    return not _looks_tpu_hosted()


class Settings:
    def __init__(self) -> None:
        self.precise_images: bool = _env_bool("LEGATE_SPARSE_PRECISE_IMAGES", False)
        self.fast_spgemm: bool = _env_bool("LEGATE_SPARSE_FAST_SPGEMM", False)
        self.x64: bool = _resolve_x64()
        self.check_bounds: bool = _env_bool(
            "LEGATE_SPARSE_TPU_CHECK_BOUNDS", False
        )
        # SpMV fast path: pack CSR into ELL (rows, max-row-nnz) when the
        # padded size stays within this multiple of the true nnz.  TPU
        # gathers over a rectangular layout run at HBM roofline; scatter-
        # based segment sums do not.  Set to 0 to disable ELL packing.
        self.ell_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_ELL_EXPAND", "4.0")
        )
        # Capacity multiplier for spgemm chunked mode (rows per chunk heuristic).
        self.spgemm_chunk_products: int = int(
            os.environ.get("LEGATE_SPARSE_SPGEMM_CHUNK", 1 << 24)
        )
        # SpMV fastest path: exactly-banded CSR matrices run gather-free
        # shifted-add (DIA) kernels when num_diags*cols stays within this
        # multiple of nnz.  Set to 0 to disable band detection.
        self.dia_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_DIA_EXPAND", "2.0")
        )
        self.dia_max_diags: int = int(
            os.environ.get("LEGATE_SPARSE_TPU_DIA_MAX_DIAGS", "128")
        )
        # Irregular SpMV path: densify present 128x128 blocks and stream
        # them through the MXU (ops/bsr.py), skipping absent blocks,
        # when the densified size stays within this multiple of nnz.
        # 128.0 ~= the break-even vs the XLA gather path on v5e (useful
        # bandwidth law in ops/bsr.py docstring).  0 disables BSR.
        self.bsr_max_expand: float = float(
            os.environ.get("LEGATE_SPARSE_TPU_BSR_EXPAND", "128.0")
        )
        # Build the BSR structure on any platform (kernel runs in
        # interpret mode off-TPU) — differential-testing hook.
        self.bsr_force: bool = _env_bool("LEGATE_SPARSE_TPU_BSR_FORCE",
                                         False)
        # XLA banded-SpMV lowering: "fused" (padded single-pass form,
        # the TPU-friendly layout), "nopad" (interior/edge split that
        # skips the x-pad materialization — measured ~20-25% faster on
        # the CPU lane, where every avoided copy is bandwidth), or
        # "auto" (nopad on cpu backends, fused elsewhere).  Only the
        # XLA path is affected; the Pallas kernel stays the TPU fast
        # path.  A typo must fail loudly, not silently benchmark the
        # wrong kernel.
        self.dia_xla_variant: str = os.environ.get(
            "LEGATE_SPARSE_TPU_DIA_XLA", "auto"
        )
        if self.dia_xla_variant not in ("fused", "nopad", "auto"):
            raise ValueError(
                f"LEGATE_SPARSE_TPU_DIA_XLA="
                f"{self.dia_xla_variant!r}: expected one of "
                f"'fused', 'nopad', 'auto'"
            )

    @property
    def obs(self) -> bool:
        """Span tracing on/off — delegates to ``obs.trace`` (single
        source of truth; the env var was read there at import)."""
        from .obs import trace

        return trace.enabled()

    @obs.setter
    def obs(self, value: bool) -> None:
        from .obs import trace

        if value:
            trace.enable()
        else:
            trace.disable()


settings = Settings()
