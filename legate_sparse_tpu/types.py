# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Canonical dtypes for legate_sparse_tpu.

Parity with the reference's canonical types (reference:
``legate_sparse/types.py:20-25`` defines ``coord_ty=int64``,
``nnz_ty=uint64``).  TPU-first deviation: XLA strongly prefers 32-bit
integer indices (vector lanes, gather throughput), so the *default*
coordinate type here is int32, transparently promoted to int64 whenever a
matrix dimension or nnz count exceeds ``int32`` range.  ``nnz_ty`` is int64
(JAX has weak uint64 support and nnz counts never need the extra bit).
"""

import numpy as np

# Default (TPU-friendly) coordinate type; promoted to int64 for huge axes.
coord_ty = np.dtype(np.int32)
# Wide coordinate type used when shapes exceed int32 range.
wide_coord_ty = np.dtype(np.int64)
# Type used for nnz counts / indptr.
nnz_ty = np.dtype(np.int64)

float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
uint64 = np.dtype(np.uint64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)

# Value dtypes accepted by the compute kernels (reference:
# ``legate_sparse/utils.py:28-33`` SUPPORTED_DATATYPES) — plus
# bfloat16, a TPU-native extension: the VPU operates on bf16 natively
# and SpMV is bandwidth-bound, so halving value bytes nearly halves
# solve time for tolerance-insensitive workloads.
import jax.numpy as _jnp

SUPPORTED_DATATYPES = (
    np.dtype(_jnp.bfloat16),
    np.dtype(np.float32),
    np.dtype(np.float64),
    np.dtype(np.complex64),
    np.dtype(np.complex128),
)


def coord_dtype_for(extent: int) -> np.dtype:
    """Pick int32 unless ``extent`` (a dimension or nnz) needs int64."""
    return coord_ty if extent <= np.iinfo(np.int32).max else wide_coord_ty
