# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Canonical dtypes for legate_sparse_tpu.

Parity with the reference's canonical types (reference:
``legate_sparse/types.py:20-25`` defines ``coord_ty=int64``,
``nnz_ty=uint64``).  TPU-first deviation: XLA strongly prefers 32-bit
integer indices (vector lanes, gather throughput), so the *default*
coordinate type here is int32, transparently promoted to int64 whenever a
matrix dimension or nnz count exceeds ``int32`` range.  ``nnz_ty`` is int64
(JAX has weak uint64 support and nnz counts never need the extra bit).
"""

import numpy as np

# Default (TPU-friendly) coordinate type; promoted to int64 for huge axes.
coord_ty = np.dtype(np.int32)
# Wide coordinate type used when shapes exceed int32 range.
wide_coord_ty = np.dtype(np.int64)
# Type used for nnz counts / indptr.
nnz_ty = np.dtype(np.int64)

float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
uint64 = np.dtype(np.uint64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)

# Value dtypes accepted by the compute kernels (reference:
# ``legate_sparse/utils.py:28-33`` SUPPORTED_DATATYPES) — plus
# bfloat16, a TPU-native extension: the VPU operates on bf16 natively
# and SpMV is bandwidth-bound, so halving value bytes nearly halves
# solve time for tolerance-insensitive workloads.
import jax.numpy as _jnp

SUPPORTED_DATATYPES = (
    np.dtype(_jnp.bfloat16),
    np.dtype(np.float32),
    np.dtype(np.float64),
    np.dtype(np.complex64),
    np.dtype(np.complex128),
)


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def index_dtype() -> np.dtype:
    """Platform-aware wide-index dtype: int64 when 64-bit integers
    exist in this process, else int32.

    Under the no-x64 TPU policy (``settings.py`` resolves x64 off on
    TPU processes) a request for int64 is *silently truncated* to int32
    by jax with a UserWarning — the r3 on-chip capture showed exactly
    that from ``csr.py``'s indptr builds.  Routing every device-side
    index/nnz/counter dtype request through here means a no-x64
    process never asks for a width it cannot have (reference parity:
    ``src/sparse/util/dispatch.h:56-77`` index-type dispatch).  The
    documented consequence: a no-x64 process supports dims and nnz up
    to 2^31-1 (per shard in the distributed case);
    ``coord_dtype_for`` raises loudly past that instead of letting
    int32 wrap."""
    return nnz_ty if _x64_enabled() else int32


# indptr/nnz requests read the same platform policy.
nnz_dtype = index_dtype


def check_nnz(nnz: int) -> None:
    """Loud-failure guard for nnz at the host constructor boundary:
    under no-x64, indptr is int32, so >2^31-1 nonzeros would wrap
    negative SILENTLY (an explicit cast carries no warning).  Device-
    computed nnz (conversions, SpGEMM) past 2^31 in a no-x64 process
    is likewise unsupported — this guard covers the entry points where
    external data arrives with a concrete count."""
    if nnz > np.iinfo(np.int32).max and not _x64_enabled():
        raise OverflowError(
            f"nnz={nnz} needs int64 indptr, but this process has x64 "
            f"disabled (TPU policy); enable x64 (JAX_ENABLE_X64=1 / "
            f"LEGATE_SPARSE_TPU_X64=1) or build on a CPU process"
        )


def coord_dtype_for(extent: int) -> np.dtype:
    """Pick int32 unless ``extent`` (a dimension or nnz) needs int64.

    Raises ``OverflowError`` when the extent needs int64 but the
    process has x64 disabled (no-x64 TPU policy): a silent int32
    truncation would corrupt coordinates; callers must enable x64 (or
    run the build on a CPU process) for >2^31-extent matrices."""
    if extent <= np.iinfo(np.int32).max:
        return coord_ty
    if not _x64_enabled():
        raise OverflowError(
            f"matrix extent {extent} needs int64 coordinates, but this "
            f"process has x64 disabled (TPU policy); enable x64 "
            f"(JAX_ENABLE_X64=1 / LEGATE_SPARSE_TPU_X64=1) or build on "
            f"a CPU process"
        )
    return wide_coord_ty
