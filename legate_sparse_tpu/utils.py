# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Array utilities: dtype coercion, conversion helpers.

Parity with the reference store/array utilities (reference:
``legate_sparse/utils.py``).  The store<->cuPyNumeric plumbing
(``utils.py:48-65``) has no TPU analog — jax.Arrays are used directly —
but the dtype-coercion rules (``utils.py:90-114``) and grid factorization
(``utils.py:118-124``) are kept semantically identical.
"""

from __future__ import annotations

import math
from typing import Any, Tuple

import numpy as np

import jax.numpy as jnp

from .types import SUPPORTED_DATATYPES


def is_sparse_matrix(o: Any) -> bool:
    from .base import CompressedBase
    from .coo import coo_array
    from .csc import csc_array

    return isinstance(o, (CompressedBase, csc_array, coo_array))


def find_common_type(*args) -> np.dtype:
    """numpy result_type over sparse matrices / arrays / scalars.

    Mirrors reference ``utils.py:90-103``: size-1 arrays participate as
    scalar types so that e.g. float32 matrix * python float stays float32.
    """
    array_types = []
    scalar_types = []
    for array in args:
        if is_sparse_matrix(array):
            array_types.append(np.dtype(array.dtype))
        elif np.isscalar(array):
            scalar_types.append(np.result_type(array))
        elif getattr(array, "size", None) == 1:
            scalar_types.append(np.dtype(array.dtype))
        else:
            array_types.append(np.dtype(array.dtype))
    return np.result_type(*array_types, *scalar_types)


def cast_to_common_type(*args) -> Tuple[Any, ...]:
    """Cast all arguments to their common dtype (reference ``utils.py:106-114``)."""
    common = find_common_type(*args)
    out = []
    for arg in args:
        if is_sparse_matrix(arg):
            out.append(arg.astype(common, copy=False))
        else:
            out.append(jnp.asarray(arg, dtype=common))
    return tuple(out)


def require_supported_dtype(dtype: np.dtype) -> None:
    if np.dtype(dtype) not in SUPPORTED_DATATYPES:
        raise NotImplementedError(
            f"Operation not supported for dtype {np.dtype(dtype)}; "
            f"supported: {[str(d) for d in SUPPORTED_DATATYPES]}"
        )


def factor_int(n: int) -> Tuple[int, int]:
    """Decompose n into a near-square grid (reference ``utils.py:118-124``)."""
    val = math.ceil(math.sqrt(n))
    val2 = int(n / val)
    while val2 * val != float(n):
        val -= 1
        val2 = int(n / val)
    return val, val2


def fill_out(result, out, check_shape: bool = True):
    """Uniform functional ``out=`` contract.

    JAX arrays are immutable, so true aliasing writes are impossible; for
    parity with the reference's ``out=`` semantics (``csr.py:457-476``)
    numpy outputs are filled in place and returned, jax outputs get the
    result cast to their dtype.  Shared by csr/dia methods and linalg.
    """
    if out is None:
        return result
    if check_shape and tuple(out.shape) != tuple(result.shape):
        raise ValueError(f"out shape {out.shape} != result {result.shape}")
    if isinstance(out, np.ndarray):
        np.copyto(out, np.asarray(result, dtype=out.dtype))
        return out
    return result.astype(out.dtype)


def asarray_1d(x, dtype=None):
    arr = jnp.asarray(x, dtype=dtype)
    if arr.ndim == 2 and arr.shape[1] == 1:
        arr = arr.reshape(-1)
    if arr.ndim != 1:
        raise ValueError(f"expected 1-D array, got shape {arr.shape}")
    return arr
