# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""ctypes bridge to the native C++ helper library.

The analog of the reference's CFFI boundary (reference:
``legate_sparse/config.py:49-113`` dlopens ``liblegate_sparse.so``),
reduced to the pieces that genuinely belong in native code on a TPU
stack: host-side IO parsing and the structure-static CSR->BSR pack
(``src/bsr_pack.cc``).  The library is optional — every entry
point has a numpy fallback and callers degrade gracefully.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_LIB = None
_LIB_TRIED = False


def _try_build(src_dir: str) -> None:
    """Opt-in `make -C src` (LEGATE_SPARSE_TPU_BUILD_NATIVE=1): building
    at import time surprises sandboxed/read-only deployments, so by
    default a missing library just means numpy fallbacks.  Failures are
    logged in one line and ignored."""
    import subprocess
    import sys

    if os.environ.get("LEGATE_SPARSE_TPU_BUILD_NATIVE", "0") != "1":
        return
    try:
        r = subprocess.run(
            ["make", "-C", src_dir],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            timeout=120,
            check=False,
        )
        if r.returncode != 0:
            sys.stderr.write(
                "legate_sparse_tpu: native helper build failed "
                f"(rc={r.returncode}); using numpy fallbacks\n"
            )
    except Exception as e:
        sys.stderr.write(
            f"legate_sparse_tpu: native helper build failed ({e!r}); "
            "using numpy fallbacks\n"
        )


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.abspath(__file__))
    src_dir = os.path.join(here, "..", "src")
    candidates = [
        os.path.join(src_dir, "build", "liblegate_sparse_tpu.so"),
        os.path.join(here, "liblegate_sparse_tpu.so"),
    ]
    if not any(os.path.exists(p) for p in candidates) and os.path.isdir(
        src_dir
    ):
        _try_build(src_dir)
    for path in candidates:
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
                _bind(lib)
                _LIB = lib
                break
            except (OSError, AttributeError):
                # Unloadable, or a stale build missing newer symbols:
                # degrade to the numpy fallbacks.
                continue
    return _LIB


def _bind(lib: ctypes.CDLL) -> None:
    lib.lst_mtx_read.restype = ctypes.c_int
    lib.lst_mtx_read.argtypes = [
        ctypes.c_char_p,                     # path
        ctypes.POINTER(ctypes.c_int64),      # out m
        ctypes.POINTER(ctypes.c_int64),      # out n
        ctypes.POINTER(ctypes.c_int64),      # out nnz (post symmetry)
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),   # rows
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),   # cols
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # vals
    ]
    lib.lst_free.restype = None
    lib.lst_free.argtypes = [ctypes.c_void_p]
    lib.lst_bsr_count.restype = ctypes.c_int
    lib.lst_bsr_count.argtypes = [
        ctypes.c_int64, ctypes.c_int64,                   # rows, cols
        ctypes.POINTER(ctypes.c_int64),                   # indptr
        ctypes.POINTER(ctypes.c_int64),                   # indices
        ctypes.c_double, ctypes.c_int64,                  # budget, cap
        ctypes.POINTER(ctypes.c_int64),                   # out nb
        ctypes.POINTER(ctypes.c_int64),                   # out nbr
        ctypes.POINTER(ctypes.c_int64),                   # out nbc
    ]
    lib.lst_bsr_fill.restype = ctypes.c_int
    lib.lst_bsr_fill.argtypes = [
        ctypes.c_int64, ctypes.c_int64,                   # rows, cols
        ctypes.POINTER(ctypes.c_int64),                   # indptr
        ctypes.POINTER(ctypes.c_int64),                   # indices
        ctypes.POINTER(ctypes.c_float),                   # data
        ctypes.POINTER(ctypes.c_float),                   # blocks (out)
        ctypes.POINTER(ctypes.c_int32),                   # brow (out)
        ctypes.POINTER(ctypes.c_int32),                   # bcol (out)
    ]
    lib.lst_coo_to_csr.restype = ctypes.c_int
    lib.lst_coo_to_csr.argtypes = [
        ctypes.c_int64,                      # nnz
        ctypes.c_int64,                      # rows
        ctypes.POINTER(ctypes.c_int64),      # row
        ctypes.POINTER(ctypes.c_int64),      # col
        ctypes.POINTER(ctypes.c_double),     # val
        ctypes.POINTER(ctypes.c_int64),      # out indptr
        ctypes.POINTER(ctypes.c_int64),      # out cols
        ctypes.POINTER(ctypes.c_double),     # out vals
    ]


def native_available() -> bool:
    return _load() is not None


def native_mtx_read(path: str) -> Optional[Tuple[int, int, np.ndarray, np.ndarray, np.ndarray]]:
    """Fast C++ matrix-market parse; None if the library is unavailable.

    Native counterpart of the reference's single-task parser
    (``src/sparse/io/mtx_to_coo.cc:31-143``).
    """
    lib = _load()
    if lib is None:
        return None
    m = ctypes.c_int64()
    n = ctypes.c_int64()
    nnz = ctypes.c_int64()
    rows_p = ctypes.POINTER(ctypes.c_int64)()
    cols_p = ctypes.POINTER(ctypes.c_int64)()
    vals_p = ctypes.POINTER(ctypes.c_double)()
    rc = lib.lst_mtx_read(
        path.encode(), ctypes.byref(m), ctypes.byref(n), ctypes.byref(nnz),
        ctypes.byref(rows_p), ctypes.byref(cols_p), ctypes.byref(vals_p),
    )
    if rc != 0:
        return None
    count = nnz.value
    try:
        rows = np.ctypeslib.as_array(rows_p, shape=(count,)).copy()
        cols = np.ctypeslib.as_array(cols_p, shape=(count,)).copy()
        vals = np.ctypeslib.as_array(vals_p, shape=(count,)).copy()
    finally:
        lib.lst_free(rows_p)
        lib.lst_free(cols_p)
        lib.lst_free(vals_p)
    return m.value, n.value, rows, cols, vals


def native_coo_to_csr(
    row: np.ndarray, col: np.ndarray, val: np.ndarray, rows_n: int
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Stable host-side COO->CSR (counting sort by row; intra-row order
    and duplicates preserved — same contract as the device argsort path,
    reference ``csr.py:183-219``).  None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    nnz = int(row.shape[0])
    row = np.ascontiguousarray(row, dtype=np.int64)
    col = np.ascontiguousarray(col, dtype=np.int64)
    val = np.ascontiguousarray(val, dtype=np.float64)
    indptr = np.empty(rows_n + 1, dtype=np.int64)
    out_cols = np.empty(nnz, dtype=np.int64)
    out_vals = np.empty(nnz, dtype=np.float64)
    as_p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))
    rc = lib.lst_coo_to_csr(
        nnz, int(rows_n),
        as_p(row, ctypes.c_int64), as_p(col, ctypes.c_int64),
        as_p(val, ctypes.c_double),
        as_p(indptr, ctypes.c_int64), as_p(out_cols, ctypes.c_int64),
        as_p(out_vals, ctypes.c_double),
    )
    if rc != 0:
        return None
    return out_vals, out_cols, indptr


def native_bsr_pack(
    indptr: np.ndarray, indices: np.ndarray, data: np.ndarray,
    rows: int, cols: int, max_expand: float, max_blocks: int,
):
    """Fast C++ CSR -> transposed-BSR densification (``ops/bsr.py``'s
    host pack); exploits CSR row order so no global sort runs.

    Returns ``(blkT, brow, bcol, nbr, nbc)``, ``"over_budget"`` when the
    densification exceeds the budget (callers must NOT fall back to
    numpy — same answer, slower), or None when the library is
    unavailable / input unsupported (callers use the numpy pack).
    """
    lib = _load()
    if lib is None:
        return None
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    nb = ctypes.c_int64()
    nbr = ctypes.c_int64()
    nbc = ctypes.c_int64()
    as_p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))  # noqa: E731
    rc = lib.lst_bsr_count(
        ctypes.c_int64(rows), ctypes.c_int64(cols),
        as_p(indptr, ctypes.c_int64), as_p(indices, ctypes.c_int64),
        ctypes.c_double(max_expand), ctypes.c_int64(max_blocks),
        ctypes.byref(nb), ctypes.byref(nbr), ctypes.byref(nbc),
    )
    if rc == 1:
        return "over_budget"
    if rc != 0:
        return None
    # Python owns the output buffers: no result copy.  (Data is
    # converted only now — the reject path above never reads it.)
    data = np.ascontiguousarray(data, dtype=np.float32)
    n_blocks = nb.value
    blkT = np.zeros((n_blocks, 128, 128), dtype=np.float32)
    brow = np.zeros((n_blocks,), dtype=np.int32)
    bcol = np.zeros((n_blocks,), dtype=np.int32)
    rc = lib.lst_bsr_fill(
        ctypes.c_int64(rows), ctypes.c_int64(cols),
        as_p(indptr, ctypes.c_int64), as_p(indices, ctypes.c_int64),
        as_p(data, ctypes.c_float), as_p(blkT, ctypes.c_float),
        as_p(brow, ctypes.c_int32), as_p(bcol, ctypes.c_int32),
    )
    if rc != 0:
        return None
    return blkT, brow, bcol, int(nbr.value), int(nbc.value)
