// Copyright 2026.
// SPDX-License-Identifier: Apache-2.0
//
// Native CSR -> transposed-BSR densification: the host side of the
// block-sparse irregular SpMV path (legate_sparse_tpu/ops/bsr.py).
// Exposed over the same plain C ABI as mtx_reader.cc and consumed via
// ctypes (legate_sparse_tpu/utils_native.py); numpy fallbacks exist
// for every entry point.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <vector>

// ---------------------------------------------------------------------------
// CSR -> transposed-BSR densification (the host side of the block-sparse
// irregular SpMV path, ops/bsr.py).  Exploits CSR ordering: block-rows
// arrive sorted, so present blocks are discovered with one bitmap pass
// per block-row and no global sort.  Emits blocks in (brow, bcol) order
// with blkT[b][c][r] transposed storage and one zero block for every
// empty block-row (the kernel's "output fully written" invariant).
// Returns 0 = ok, 1 = over budget / too many blocks (caller falls
// back), 2 = bad input.

extern "C" int lst_bsr_count(int64_t rows, int64_t cols,
                             const int64_t* indptr, const int64_t* indices,
                             double max_expand, int64_t max_blocks,
                             int64_t* out_nb, int64_t* out_nbr,
                             int64_t* out_nbc) {
  if (rows <= 0 || cols <= 0) return 2;
  const int64_t B = 128;
  const int64_t nbr = (rows + B - 1) / B;
  const int64_t nbc = (cols + B - 1) / B;
  const int64_t nnz = indptr[rows];
  if (nnz <= 0) return 2;

  // Count present blocks (bitmap per block-row); O(nnz), no sort.
  std::vector<uint8_t> seen(static_cast<size_t>(nbc), 0);
  std::vector<int64_t> touched;  // bcols hit in the current block-row
  int64_t nb = 0;
  for (int64_t br = 0; br < nbr; ++br) {
    const int64_t r0 = br * B;
    const int64_t r1 = std::min(r0 + B, rows);
    int64_t found = 0;
    for (int64_t i = indptr[r0]; i < indptr[r1]; ++i) {
      const int64_t ci = indices[i];
      if (ci < 0 || ci >= cols) return 2;
      const int64_t bc = ci / B;
      if (!seen[static_cast<size_t>(bc)]) {
        seen[static_cast<size_t>(bc)] = 1;
        touched.push_back(bc);
        ++found;
      }
    }
    for (int64_t bc : touched) seen[static_cast<size_t>(bc)] = 0;
    touched.clear();
    nb += (found == 0) ? 1 : found;  // empty block-row -> one zero block
  }
  if (nb > max_blocks) return 1;
  const double dens = static_cast<double>(nb) * B * B;
  if (dens > max_expand * static_cast<double>(nnz)) return 1;
  *out_nb = nb;
  *out_nbr = nbr;
  *out_nbc = nbc;
  return 0;
}

// Fill caller-allocated (zeroed) buffers: blocks nb*B*B f32,
// brow/bcol nb i32.  Caller sizes them from lst_bsr_count.
extern "C" int lst_bsr_fill(int64_t rows, int64_t cols,
                            const int64_t* indptr, const int64_t* indices,
                            const float* data, float* blocks,
                            int32_t* brow, int32_t* bcol) {
  if (rows <= 0 || cols <= 0) return 2;
  const int64_t B = 128;
  const int64_t nbr = (rows + B - 1) / B;
  const int64_t nbc = (cols + B - 1) / B;
  std::vector<int64_t> touched;

  // Per block-row, map bcol -> block id, then scatter values into
  // transposed slots blkT[b][c % B][r % B] (duplicates add).
  std::vector<int64_t> slot_of(static_cast<size_t>(nbc), -1);
  int64_t next_b = 0;
  for (int64_t br = 0; br < nbr; ++br) {
    const int64_t r0 = br * B;
    const int64_t r1 = std::min(r0 + B, rows);
    for (int64_t i = indptr[r0]; i < indptr[r1]; ++i) {
      const int64_t bc = indices[i] / B;
      if (slot_of[static_cast<size_t>(bc)] < 0) {
        slot_of[static_cast<size_t>(bc)] = 1;  // mark; ids after sort
        touched.push_back(bc);
      }
    }
    if (touched.empty()) {
      brow[next_b] = static_cast<int32_t>(br);
      bcol[next_b] = 0;  // zero block keeps the row written
      ++next_b;
      continue;
    }
    std::sort(touched.begin(), touched.end());
    for (int64_t bc : touched) {
      slot_of[static_cast<size_t>(bc)] = next_b;
      brow[next_b] = static_cast<int32_t>(br);
      bcol[next_b] = static_cast<int32_t>(bc);
      ++next_b;
    }
    for (int64_t r = r0; r < r1; ++r) {
      for (int64_t i = indptr[r]; i < indptr[r + 1]; ++i) {
        const int64_t c = indices[i];
        const int64_t b = slot_of[static_cast<size_t>(c / B)];
        blocks[(static_cast<size_t>(b) * B + (c % B)) * B + (r % B)] +=
            data[i];
      }
    }
    for (int64_t bc : touched) slot_of[static_cast<size_t>(bc)] = -1;
    touched.clear();
  }
  return 0;
}
