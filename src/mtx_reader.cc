// Copyright 2026.
// SPDX-License-Identifier: Apache-2.0
//
// Native host-side helpers for legate_sparse_tpu, exposed over a plain
// C ABI consumed via ctypes (legate_sparse_tpu/utils_native.py).
//
// This is the TPU framework's counterpart of the reference's C++ leaf
// tasks that are genuinely host work rather than accelerator compute:
// the matrix-market parser (reference: src/sparse/io/mtx_to_coo.cc) and
// a stable COO->CSR conversion (reference reaches this through a device
// argsort, csr.py:183-219).  Errors return nonzero codes — callers fall
// back to the numpy implementations.

#include <algorithm>
#include <cctype>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Read one logical line (handles very long lines) into buf; returns
// false at EOF.
bool read_line(FILE* f, std::string& buf) {
  buf.clear();
  char chunk[1 << 16];
  while (std::fgets(chunk, sizeof(chunk), f)) {
    buf += chunk;
    if (!buf.empty() && buf.back() == '\n') {
      buf.pop_back();
      if (!buf.empty() && buf.back() == '\r') buf.pop_back();
      return true;
    }
  }
  return !buf.empty();
}

std::string lower(std::string s) {
  for (char& c : s) c = static_cast<char>(std::tolower(c));
  return s;
}

enum Field { FIELD_REAL, FIELD_INTEGER, FIELD_PATTERN };
enum Symmetry { SYM_GENERAL, SYM_SYMMETRIC, SYM_SKEW };

}  // namespace

extern "C" {

void lst_free(void* p) { std::free(p); }

// Parse a MatrixMarket coordinate file.  On success (return 0) the
// caller owns *rows/*cols/*vals (malloc'd; release with lst_free) and
// *nnz is the entry count after symmetry expansion.
int lst_mtx_read(const char* path, int64_t* out_m, int64_t* out_n,
                 int64_t* out_nnz, int64_t** out_rows, int64_t** out_cols,
                 double** out_vals) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;

  std::string line;
  if (!read_line(f, line)) {
    std::fclose(f);
    return 2;
  }
  // Header: %%MatrixMarket matrix coordinate <field> <symmetry>
  char obj[64] = {0}, fmt[64] = {0}, field_s[64] = {0}, sym_s[64] = {0};
  if (std::sscanf(line.c_str(), "%%%%MatrixMarket %63s %63s %63s %63s",
                  obj, fmt, field_s, sym_s) != 4) {
    std::fclose(f);
    return 2;
  }
  if (lower(obj) != "matrix" || lower(fmt) != "coordinate") {
    std::fclose(f);
    return 3;
  }
  Field field;
  std::string fs = lower(field_s);
  if (fs == "real" || fs == "double") {
    field = FIELD_REAL;
  } else if (fs == "integer") {
    field = FIELD_INTEGER;
  } else if (fs == "pattern") {
    field = FIELD_PATTERN;
  } else {
    std::fclose(f);
    return 3;  // complex unsupported here; numpy fallback handles errors
  }
  Symmetry sym;
  std::string ss = lower(sym_s);
  if (ss == "general") {
    sym = SYM_GENERAL;
  } else if (ss == "symmetric") {
    sym = SYM_SYMMETRIC;
  } else if (ss == "skew-symmetric") {
    sym = SYM_SKEW;
  } else {
    std::fclose(f);
    return 3;
  }

  // Skip comment lines, find the dimensions line.
  do {
    if (!read_line(f, line)) {
      std::fclose(f);
      return 2;
    }
  } while (!line.empty() && line[0] == '%');

  int64_t m = 0, n = 0, declared = 0;
  // %ld targets `long`, which is 32-bit on LLP64 platforms; SCNd64 is
  // the portable int64_t conversion.
  if (std::sscanf(line.c_str(),
                  "%" SCNd64 " %" SCNd64 " %" SCNd64,
                  &m, &n, &declared) != 3 ||
      m < 0 || n < 0 || declared < 0) {
    std::fclose(f);
    return 2;
  }

  size_t cap = static_cast<size_t>(declared) *
               (sym == SYM_GENERAL ? 1 : 2);
  if (cap == 0) cap = 1;
  auto* rows = static_cast<int64_t*>(std::malloc(cap * sizeof(int64_t)));
  auto* cols = static_cast<int64_t*>(std::malloc(cap * sizeof(int64_t)));
  auto* vals = static_cast<double*>(std::malloc(cap * sizeof(double)));
  if (!rows || !cols || !vals) {
    std::free(rows);
    std::free(cols);
    std::free(vals);
    std::fclose(f);
    return 4;
  }

  size_t idx = 0;
  int64_t seen = 0;
  while (seen < declared && read_line(f, line)) {
    if (line.empty()) continue;
    char* p = const_cast<char*>(line.c_str());
    char* end = nullptr;
    int64_t r = std::strtoll(p, &end, 10);
    if (end == p) continue;  // blank/garbage line
    p = end;
    int64_t c = std::strtoll(p, &end, 10);
    if (end == p) { idx = 0; break; }
    p = end;
    double v;
    if (field == FIELD_PATTERN) {
      v = 1.0;
    } else if (field == FIELD_INTEGER) {
      v = static_cast<double>(std::strtoll(p, &end, 10));
    } else {
      v = std::strtod(p, &end);
    }
    --r;  // 1-based -> 0-based
    --c;
    if (r < 0 || r >= m || c < 0 || c >= n) { idx = 0; break; }
    rows[idx] = r;
    cols[idx] = c;
    vals[idx] = v;
    ++idx;
    ++seen;
    if (sym != SYM_GENERAL && r != c) {
      rows[idx] = c;
      cols[idx] = r;
      vals[idx] = (sym == SYM_SKEW) ? -v : v;
      ++idx;
    }
  }
  std::fclose(f);
  if (seen != declared || idx == 0) {
    // Truncated file or malformed entry: refuse (fallback re-parses).
    if (!(declared == 0 && idx == 0)) {
      std::free(rows);
      std::free(cols);
      std::free(vals);
      return 5;
    }
  }

  *out_m = m;
  *out_n = n;
  *out_nnz = static_cast<int64_t>(idx);
  *out_rows = rows;
  *out_cols = cols;
  *out_vals = vals;
  return 0;
}

// Stable COO->CSR: counting sort by row (intra-row input order kept,
// duplicates preserved — the same contract as the device path).
// Caller provides out_indptr (rows_n + 1), out_cols / out_vals (nnz).
int lst_coo_to_csr(int64_t nnz, int64_t rows_n, const int64_t* row,
                   const int64_t* col, const double* val,
                   int64_t* out_indptr, int64_t* out_cols,
                   double* out_vals) {
  if (nnz < 0 || rows_n < 0) return 1;
  std::vector<int64_t> count(static_cast<size_t>(rows_n) + 1, 0);
  for (int64_t i = 0; i < nnz; ++i) {
    if (row[i] < 0 || row[i] >= rows_n) return 2;
    ++count[static_cast<size_t>(row[i]) + 1];
  }
  for (int64_t r = 0; r < rows_n; ++r) count[r + 1] += count[r];
  std::memcpy(out_indptr, count.data(),
              (static_cast<size_t>(rows_n) + 1) * sizeof(int64_t));
  std::vector<int64_t> cursor(count.begin(), count.end() - 1);
  for (int64_t i = 0; i < nnz; ++i) {
    int64_t& pos = cursor[static_cast<size_t>(row[i])];
    out_cols[pos] = col[i];
    out_vals[pos] = val[i];
    ++pos;
  }
  return 0;
}

}  // extern "C"

