#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""One-command multi-shape test driver (the ``legate.tester`` analog).

The reference's ``test.py`` runs its suite across resource shapes
(CPU/GPU counts) in one invocation (reference ``test.py:24-32``); here
the resource axis is the virtual device-mesh shape: the full suite runs
once per requested device count, plus optional slow and real-chip
lanes.  Each lane is a fresh subprocess (jax's device count is frozen
at backend init, so shapes cannot share a process).

Usage:
    python test.py                  # 8-device + 1-device lanes
    python test.py --devices 8 4 1  # explicit shapes
    python test.py --slow           # also the -m slow lane (8 devices)
    python test.py --tpu            # also the real-chip -m tpu lane
    python test.py --multiproc      # ONLY the 2-rank jax.distributed
                                    # lane (the multi-rank analog)
    python test.py -- -k spmv       # extra args forwarded to pytest

Exit code: non-zero if any lane fails.  This box has one CPU core, so
lanes run strictly sequentially (concurrent pytest multiplies wall
time).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))


def run_lane(name: str, env_extra: dict, args: list[str],
             path: str = "tests/") -> bool:
    env = dict(os.environ)
    env.update(env_extra)
    t0 = time.time()
    print(f"=== lane: {name} ===", flush=True)
    r = subprocess.run(
        [sys.executable, "-m", "pytest", path, "-q", *args],
        cwd=ROOT, env=env,
    )
    dt = time.time() - t0
    status = "ok" if r.returncode == 0 else f"FAILED (rc={r.returncode})"
    print(f"=== lane {name}: {status} in {dt:.0f}s ===", flush=True)
    return r.returncode == 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, nargs="+", default=[8, 1],
                    help="virtual device counts to run the suite at")
    ap.add_argument("--slow", action="store_true",
                    help="also run the -m slow lane (heavy shapes)")
    ap.add_argument("--tpu", action="store_true",
                    help="also run the real-chip -m tpu lane")
    ap.add_argument("--multiproc", action="store_true",
                    help="run ONLY the multi-process distributed lane "
                         "(2 ranks x 4 devices via jax.distributed; "
                         "also part of the default lanes)")
    ap.add_argument("rest", nargs="*",
                    help="extra pytest args (after --)")
    args = ap.parse_args()

    ok = True
    if args.multiproc:
        ok = run_lane("multiproc (2 ranks x 4 devices)", {},
                      ["-m", "slow or not slow", *args.rest],
                      path="tests/test_multiprocess.py")
        return 0 if ok else 1
    for n in args.devices:
        ok &= run_lane(
            f"{n}-device",
            {"LEGATE_SPARSE_TPU_TEST_DEVICES": str(n)},
            args.rest,
        )
    if args.slow:
        ok &= run_lane(
            "slow (8-device)",
            {"LEGATE_SPARSE_TPU_TEST_DEVICES": "8"},
            ["-m", "slow", *args.rest],
        )
    if args.tpu:
        ok &= run_lane(
            "tpu (real chip)",
            {"LEGATE_SPARSE_TPU_TEST_PLATFORM": "tpu"},
            ["-m", "tpu", *args.rest],
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
