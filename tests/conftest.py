# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The TPU analog of the reference's ``legate.tester`` resource shapes
(reference ``test.py:24-32``): the same pytest files exercise 1-device
and 8-device behavior, with multi-device tests using the host-platform
device-count trick instead of a pod (SURVEY §4).
"""

import os
import sys

# Must run before the jax backend initializes (see _platform.pin_cpu).
# LEGATE_SPARSE_TPU_TEST_DEVICES re-runs the suite at a different
# resource shape (the legate.tester analog): 1 = single device, 8 =
# default mesh.  LEGATE_SPARSE_TPU_TEST_PLATFORM=tpu skips the pin so
# @pytest.mark.tpu smoke tests can run on a real chip.
TEST_DEVICES = int(os.environ.get("LEGATE_SPARSE_TPU_TEST_DEVICES", "8"))

# Persistent XLA compile cache: jit-compile time dominates suite wall
# time on this 1-core box, and the compiled kernels are identical
# across runs.  Must precede the first jaxlib load so the AOT-loader's
# machine-feature log spam is suppressed (the recorded prefer-no-* XLA
# tuning pseudo-features differ textually from the host report; same
# machine).  LEGATE_SPARSE_TPU_TEST_CACHE=0 disables.
_USE_CACHE = os.environ.get("LEGATE_SPARSE_TPU_TEST_CACHE", "1") != "0"
_TEST_PLATFORM = os.environ.get("LEGATE_SPARSE_TPU_TEST_PLATFORM", "cpu")
if _USE_CACHE and _TEST_PLATFORM == "cpu":
    # CPU lane only: the real-chip lane must keep ERROR-level XLA/TPU
    # runtime diagnostics visible (the tunnel's crash modes are only
    # explained there).
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

if _USE_CACHE and _TEST_PLATFORM == "cpu":
    # Export the cache to SUBPROCESS lanes too (bench smoke, pallas
    # crash-regression, dist-int64, obs v4, resilience scripts all
    # spawn `sys.executable` with `dict(os.environ)`): each child is
    # a fresh jax process that would otherwise recompile its big
    # shard_map/solver executables from scratch on every suite run.
    # Env-var config must precede the child's jax import, which it
    # does by construction; same >= 1 s persistence floor as below.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", ".jax_cache"),
    )
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")

# jit-compile time, not execution, dominates tier-1 wall time (the
# matrices are tiny), and the suite's ~1100 tests compile thousands of
# executables.  Backend optimization level 0 skips the expensive LLVM
# mid-end for a measured ~15% whole-suite win with identical test
# verdicts (tolerances are unaffected: XLA stays semantics-preserving,
# only fusion/scheduling effort drops).  CPU lane only — real-chip
# runs must measure what production compiles.
# LEGATE_SPARSE_TPU_TEST_FAST_COMPILE=0 restores default optimization.
if (_TEST_PLATFORM == "cpu"
        and os.environ.get("LEGATE_SPARSE_TPU_TEST_FAST_COMPILE",
                           "1") != "0"
        and "xla_backend_optimization_level"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_backend_optimization_level=0").strip()

if os.environ.get("LEGATE_SPARSE_TPU_TEST_PLATFORM", "cpu") == "cpu":
    from legate_sparse_tpu._platform import pin_cpu

    pin_cpu(TEST_DEVICES, override_env=False)

import jax  # noqa: E402

if _USE_CACHE:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
    )
    # Only compiles >= 1 s are persisted.  XLA:CPU executable
    # (de)serialization segfaulted the suite three times (2026-07-31,
    # stacks in git history: put/get_executable_and_time under
    # compress_coo / spgemm_csr_csr_csr_impl) and the crash is
    # suite-context-dependent — not reproducible in isolation, so not
    # reportable upstream with a repro.  The sub-second executables it
    # struck are cheap to recompile; the multi-device shard_map and
    # solver compiles that dominate suite wall time (10-60 s each)
    # stay cached, which preserves nearly all of the warm-run win.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The full suite compiles thousands of XLA:CPU executables; each holds
# several JIT code mmaps, and one pytest process crosses the kernel's
# default vm.max_map_count (65530) at ~450 tests — the next mmap
# failure SEGFAULTS inside backend_compile_and_load (observed at
# 59k maps, 2026-07-31).  Two defenses: an opt-in raise of the limit
# (it is a HOST-GLOBAL sysctl that outlives the suite, so it never
# fires silently: set LEGATE_SPARSE_TPU_TEST_RAISE_MAP_COUNT=1 to
# allow it), and — always on — an adaptive cache flush that drops
# executables before the ceiling.  clear_caches() recompiles later
# reuses — the persistent compile cache absorbs the big ones.
if os.environ.get("LEGATE_SPARSE_TPU_TEST_RAISE_MAP_COUNT") == "1":
    try:
        with open("/proc/sys/vm/max_map_count", "r+") as _f:
            if int(_f.read()) < 262144:
                _f.seek(0)
                _f.write("262144")
                sys.stderr.write(
                    "conftest: raised host-global vm.max_map_count to "
                    "262144 (LEGATE_SPARSE_TPU_TEST_RAISE_MAP_COUNT=1)\n"
                )
    except OSError:
        pass

# Each clear_caches() costs ~12 s of teardown plus the recompiles of
# every executable still in use downstream; at 45000 the full suite
# flushes twice.  52000 keeps >13k maps of slack below the 65530
# ceiling (a test adds at most a few hundred maps, and the sampled
# check overshoots by at most ~5 tests' worth) while typically saving
# one flush per run.
_MAPS_SOFT_LIMIT = 52000
# Reading /proc/self/maps costs ~30 ms once the process holds 45k
# maps; over a ~1100-test run the every-test read alone burns ~15 s
# of the tier-1 budget.  Sampling every 5th teardown keeps the guard
# safe while shedding 80% of the proc reads.
_MAPS_CHECK_EVERY = 5
_maps_check_tick = 0


def _map_count() -> int:
    try:
        with open("/proc/self/maps", "rb") as f:
            return f.read().count(b"\n")
    except OSError:
        return 0


@pytest.fixture(autouse=True)
def _vma_guard():
    yield
    global _maps_check_tick
    _maps_check_tick += 1
    if _maps_check_tick % _MAPS_CHECK_EVERY:
        return
    if _map_count() > _MAPS_SOFT_LIMIT:
        import jax as _jax

        _jax.clear_caches()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
