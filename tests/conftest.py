# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The TPU analog of the reference's ``legate.tester`` resource shapes
(reference ``test.py:24-32``): the same pytest files exercise 1-device
and 8-device behavior, with multi-device tests using the host-platform
device-count trick instead of a pod (SURVEY §4).
"""

import os

# Must be set before the jax backend initializes.  The environment's
# sitecustomize may force-register an accelerator platform and override
# JAX_PLATFORMS, so pin the config directly after import as well.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
