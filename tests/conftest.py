# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The TPU analog of the reference's ``legate.tester`` resource shapes
(reference ``test.py:24-32``): the same pytest files exercise 1-device
and 8-device behavior, with multi-device tests using the host-platform
device-count trick instead of a pod (SURVEY §4).
"""

import os

# Must run before the jax backend initializes (see _platform.pin_cpu).
# LEGATE_SPARSE_TPU_TEST_DEVICES re-runs the suite at a different
# resource shape (the legate.tester analog): 1 = single device, 8 =
# default mesh.  LEGATE_SPARSE_TPU_TEST_PLATFORM=tpu skips the pin so
# @pytest.mark.tpu smoke tests can run on a real chip.
TEST_DEVICES = int(os.environ.get("LEGATE_SPARSE_TPU_TEST_DEVICES", "8"))

# Persistent XLA compile cache: jit-compile time dominates suite wall
# time on this 1-core box, and the compiled kernels are identical
# across runs.  Must precede the first jaxlib load so the AOT-loader's
# machine-feature log spam is suppressed (the recorded prefer-no-* XLA
# tuning pseudo-features differ textually from the host report; same
# machine).  LEGATE_SPARSE_TPU_TEST_CACHE=0 disables.
_USE_CACHE = os.environ.get("LEGATE_SPARSE_TPU_TEST_CACHE", "1") != "0"
_TEST_PLATFORM = os.environ.get("LEGATE_SPARSE_TPU_TEST_PLATFORM", "cpu")
if _USE_CACHE and _TEST_PLATFORM == "cpu":
    # CPU lane only: the real-chip lane must keep ERROR-level XLA/TPU
    # runtime diagnostics visible (the tunnel's crash modes are only
    # explained there).
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

if os.environ.get("LEGATE_SPARSE_TPU_TEST_PLATFORM", "cpu") == "cpu":
    from legate_sparse_tpu._platform import pin_cpu

    pin_cpu(TEST_DEVICES, override_env=False)

import jax  # noqa: E402

if _USE_CACHE:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
