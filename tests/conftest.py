# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The TPU analog of the reference's ``legate.tester`` resource shapes
(reference ``test.py:24-32``): the same pytest files exercise 1-device
and 8-device behavior, with multi-device tests using the host-platform
device-count trick instead of a pod (SURVEY §4).
"""

import os

# Must run before the jax backend initializes (see _platform.pin_cpu).
# LEGATE_SPARSE_TPU_TEST_DEVICES re-runs the suite at a different
# resource shape (the legate.tester analog): 1 = single device, 8 =
# default mesh.  LEGATE_SPARSE_TPU_TEST_PLATFORM=tpu skips the pin so
# @pytest.mark.tpu smoke tests can run on a real chip.
TEST_DEVICES = int(os.environ.get("LEGATE_SPARSE_TPU_TEST_DEVICES", "8"))

if os.environ.get("LEGATE_SPARSE_TPU_TEST_PLATFORM", "cpu") == "cpu":
    from legate_sparse_tpu._platform import pin_cpu

    pin_cpu(TEST_DEVICES, override_env=False)

import jax  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
