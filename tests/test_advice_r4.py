# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Regressions for the round-4 advisor findings (ADVICE.md r4).

1. ``bench_timing.loop_ms_per_iter``: sub-resolution low point
   (t_lo == 0) must not ZeroDivisionError when ``k_hi`` is None, and a
   noise-dominated break-out must raise instead of returning a
   fantasy per-iter estimate.
2. ``csr_array`` COO ``(data, (row, col))`` constructor must route
   through ``check_nnz`` like every other host constructor boundary.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import legate_sparse_tpu as sparse
from legate_sparse_tpu import bench_timing


def test_loop_timing_zero_t_lo_no_zerodivision(monkeypatch):
    # Freeze the clock: every measurement reads 0 elapsed, so
    # per_iter_est == 0 — the exact sub-resolution case that divided
    # by zero when k_hi=None (ADVICE r4 #1a).
    monkeypatch.setattr(bench_timing.time, "perf_counter", lambda: 1.0)
    import jax.numpy as jnp

    x0 = jnp.ones((8,), dtype=jnp.float32)
    try:
        bench_timing.loop_ms_per_iter(
            lambda v: v * 1.0, x0, k_lo=2, k_hi=None, k_cap=8,
            deadline_s=5.0,
        )
    except RuntimeError:
        pass  # "unresolvable timing" is the acceptable loud outcome
    # ZeroDivisionError escaping is the regression.


def test_loop_timing_noise_dominated_break_raises(monkeypatch):
    # t_hi marginally above t_lo but below the noise floor at the
    # k_cap break: must raise, not return the noise slope (#1b).
    # Clock intervals grow quadratically-slowly, so the later (t_hi)
    # measurement is strictly above the earlier (t_lo) one but far
    # below the 2*fixed noise floor — the old code returned that noise
    # slope as data; the new code must refuse.
    state = {"i": 0}

    def fake_clock():
        state["i"] += 1
        i = state["i"]
        return i * 1e-6 + i * i * 1e-9

    monkeypatch.setattr(bench_timing.time, "perf_counter", fake_clock)
    import jax.numpy as jnp

    x0 = jnp.ones((8,), dtype=jnp.float32)
    with pytest.raises(RuntimeError, match="unresolvable"):
        bench_timing.loop_ms_per_iter(
            lambda v: v * 1.0, x0, k_lo=2, k_hi=4, k_cap=4,
        )


def test_coo_ctor_routes_through_check_nnz(monkeypatch):
    from legate_sparse_tpu import csr as csr_mod

    seen = []
    real = csr_mod.check_nnz

    def spy(nnz):
        seen.append(int(nnz))
        return real(nnz)

    monkeypatch.setattr(csr_mod, "check_nnz", spy)
    row = np.array([0, 1, 2, 2])
    col = np.array([1, 0, 2, 1])
    data = np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)
    A = sparse.csr_array((data, (row, col)), shape=(3, 3))
    assert A.nnz == 4
    assert 4 in seen, "COO constructor path skipped check_nnz"
