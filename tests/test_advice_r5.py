# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Regression tests for the ADVICE r5 findings fixed in this round.

- medium (eigen.py): the generalized-eigsh SM remap must not leak its
  internal sigma=0.0/'LM' into the ArpackNoConvergence host fallback —
  for a singular A that made scipy splu(A - 0*M) raise "Factor is
  exactly singular" where direct SM mode succeeds.
- low (dist_spgemm.py): the window-decline cache is keyed on layout
  structure only and permanently pinned later same-layout matrices to
  all_gather; ``reset_window_declines()`` un-pins, and decline events
  now flow through the obs counters.
"""

import numpy as np
import pytest
import scipy.linalg
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg
from legate_sparse_tpu.obs import counters


def _spd_mass(n, seed=5):
    rng = np.random.RandomState(seed)
    Q = scipy.linalg.qr(rng.standard_normal((n, n)))[0]
    return (Q * (1.0 + rng.rand(n))) @ Q.T


def test_eigsh_generalized_sm_singular_falls_back_to_host():
    """Singular A = diag(0..n-1) with SPD M, which='SM': the native
    shift-invert at sigma=0 cannot converge (A is exactly singular),
    and the host fallback must receive the CALLER's sigma=None /
    which='SM' — not the remapped 0.0/'LM' that makes scipy factor the
    singular matrix and raise."""
    n = 12
    k = 3
    A_d = np.diag(np.arange(n, dtype=np.float64))
    M_d = _spd_mass(n)
    A = sparse.csr_array(sp.csr_matrix(A_d))
    M = sparse.csr_array(sp.csr_matrix(M_d))

    w, v = linalg.eigsh(A, k=k, M=M, which="SM")

    w_ref = scipy.linalg.eigh(A_d, M_d, eigvals_only=True)
    ref_sm = np.sort(w_ref[np.argsort(np.abs(w_ref))[:k]])
    np.testing.assert_allclose(np.sort(w), ref_sm, rtol=1e-6, atol=1e-8)
    # Residuals in the original pencil: A v = lambda M v.
    for i in range(k):
        r = A_d @ v[:, i] - w[i] * (M_d @ v[:, i])
        assert np.linalg.norm(r) < 1e-6 * max(1.0, abs(w[i]))


def test_eigsh_generalized_sm_regular_still_native():
    """A nonsingular pencil keeps taking the native generalized
    shift-invert route (no behavior change for the healthy case)."""
    n = 16
    k = 3
    A_d = np.diag(np.arange(1.0, n + 1.0))
    M_d = _spd_mass(n, seed=7)
    A = sparse.csr_array(sp.csr_matrix(A_d))
    M = sparse.csr_array(sp.csr_matrix(M_d))
    w, v = linalg.eigsh(A, k=k, M=M, which="SM")
    w_ref = scipy.linalg.eigh(A_d, M_d, eigvals_only=True)
    ref_sm = np.sort(w_ref[np.argsort(np.abs(w_ref))[:k]])
    np.testing.assert_allclose(np.sort(w), ref_sm, rtol=1e-5, atol=1e-7)


needs_window = pytest.mark.skipif(
    len(jax.devices()) < 3, reason="window plan needs R > 2"
)


@needs_window
def test_window_decline_reset_hook_unpins_layout():
    """A dense-column matrix declines the window plan and caches the
    decline; without the reset hook every later same-layout product
    skips the probe forever.  After ``reset_window_declines()`` the
    next call re-probes (observable through the obs counters)."""
    import importlib

    from legate_sparse_tpu.parallel import (dist_spgemm, make_row_mesh,
                                            shard_csr)

    # The package re-exports the FUNCTION under the module's name, so
    # attribute imports hand back the callable; go through importlib.
    mod = importlib.import_module(
        "legate_sparse_tpu.parallel.dist_spgemm")

    mesh = make_row_mesh(jax.devices())
    rng = np.random.RandomState(3)
    n = 64
    A_sp = sp.random(n, n, density=0.3, random_state=rng, format="csr",
                     dtype=np.float64)
    A_sp.sum_duplicates()
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh)
    dB = shard_csr(sparse.csr_array(A_sp), mesh=mesh)

    mod.reset_window_declines()          # pristine cache for this test
    declines0 = counters.get("dist_spgemm.window_decline")
    _ = dist_spgemm(dA, dB)
    assert mod.LAST_B_REALIZATION == "all_gather"
    assert counters.get("dist_spgemm.window_decline") > declines0
    assert len(mod._WINDOW_DECLINED) > 0

    # Second product: the decline cache short-circuits the probe.
    cached0 = counters.get("dist_spgemm.window_decline_cached")
    probes0 = counters.get("transfer.host_sync.spgemm_window_probe")
    _ = dist_spgemm(dA, dB)
    assert counters.get("dist_spgemm.window_decline_cached") == cached0 + 1
    assert counters.get("transfer.host_sync.spgemm_window_probe") == probes0

    # Reset: the same layout re-probes instead of staying pinned.
    mod.reset_window_declines()
    assert len(mod._WINDOW_DECLINED) == 0
    _ = dist_spgemm(dA, dB)
    assert (counters.get("transfer.host_sync.spgemm_window_probe")
            == probes0 + 1)


@needs_window
def test_dist_spgemm_span_records_realization():
    """The obs span is the supported inspection mechanism for the
    collective-realization choice (replacing the write-only
    LAST_B_REALIZATION globals): its attrs must carry the decision and
    agree with the legacy global."""
    from legate_sparse_tpu import obs
    from legate_sparse_tpu.obs import trace
    import importlib

    from legate_sparse_tpu.parallel import (dist_spgemm, make_row_mesh,
                                            shard_csr)

    mod = importlib.import_module(
        "legate_sparse_tpu.parallel.dist_spgemm")

    mesh = make_row_mesh(jax.devices())
    n = 128
    d0 = np.where(np.arange(n) % 3 == 0, 0.0, 2.0)
    A = sparse.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                     format="csr")
    dAm = shard_csr(A, mesh=mesh)
    assert dAm.dia_mask is not None      # general ESC path, not banded

    was = trace.enabled()
    trace.reset()
    trace.enable()
    try:
        _ = dist_spgemm(dAm, dAm)
        spans = [r for r in obs.records()
                 if r["name"] == "dist_spgemm"]
        assert len(spans) == 1
        at = spans[0]["attrs"]
        assert at["b_realization"] == mod.LAST_B_REALIZATION
        if at["b_realization"] == "window":
            assert tuple(at["b_plan"]) == tuple(mod.LAST_B_PLAN)
        assert at["T_cap"] > 0 and at["nnz_cap"] > 0
    finally:
        trace.reset()
        if was:
            trace.enable()
        else:
            trace.disable()
