# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Round-6 satellite fixes.

- Explicit-sigma ``eigsh``/``eigs`` get the same
  ``ArpackNoConvergence`` -> host-fallback ladder the SM routes
  already had (ADVICE r5 low): a sigma near an eigenvalue stagnates
  the inexact iterative inverse where scipy's exact ``splu``
  factorization succeeds — the user should get scipy's answer, not a
  raise.
- ``lobpcg``'s Lanczos-backed routes seed with the FULL orthogonalized
  X block (one combined start vector), not just ``X[:, 0]`` (ADVICE
  r5 low).
- The accelerator-probe verdict is TTL-cached in a state file shared
  with the tunnel watcher, so a second down-tunnel CLI run skips the
  2 x 90 s subprocess ladder.
"""

import json
import os
import time

import numpy as np
import pytest

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


# ---------------------------------------------- explicit-sigma ladders --
def _laplacian_1d(n=64, dtype=np.float64):
    """Tridiagonal 1-D Laplacian: eigenvalues 2 - 2 cos(k pi / (n+1)).
    A sigma 1e-9 above the smallest one makes (A - sigma I) condition
    ~1e9 — the inexact inner Krylov solve stagnates at its probe —
    while scipy's exact ``splu`` factorization handles it exactly."""
    main = np.full(n, 2.0, dtype=dtype)
    off = np.full(n - 1, -1.0, dtype=dtype)
    A = sparse.diags([main, off, off], [0, 1, -1], shape=(n, n),
                     format="csr", dtype=dtype)
    lam = np.sort(2.0 - 2.0 * np.cos(
        np.arange(1, n + 1) * np.pi / (n + 1)))
    return A, lam


def test_eigsh_explicit_sigma_near_eigenvalue_falls_back():
    from legate_sparse_tpu.obs import counters

    A, lam = _laplacian_1d()
    sigma = lam[0] + 1e-9
    before = counters.get("scipy_fallback.linalg.eigsh")
    w = linalg.eigsh(A, k=3, sigma=sigma, which="LM",
                     return_eigenvectors=False)
    # Nearest to sigma: the three smallest (ascending, scipy order).
    np.testing.assert_allclose(np.sort(np.asarray(w)), lam[:3],
                               atol=1e-8)
    assert counters.get("scipy_fallback.linalg.eigsh") == before + 1


def test_eigs_explicit_sigma_near_eigenvalue_falls_back():
    from legate_sparse_tpu.obs import counters

    A, lam = _laplacian_1d()
    sigma = lam[0] + 1e-9
    before = counters.get("scipy_fallback.linalg.eigs")
    w = linalg.eigs(A, k=3, sigma=sigma, which="LM",
                    return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(np.real(np.asarray(w))),
                               lam[:3], atol=1e-7)
    assert np.iscomplexobj(np.asarray(w))   # scipy contract preserved
    assert counters.get("scipy_fallback.linalg.eigs") == before + 1


def test_eigsh_explicit_sigma_clean_stays_native():
    """A well-separated sigma must keep the native device route (the
    ladder is a fallback, not a rewrite)."""
    from legate_sparse_tpu.obs import counters

    A = sparse.diags([np.arange(1.0, 25.0)], [0], shape=(24, 24),
                     format="csr", dtype=np.float64)
    before = counters.get("scipy_fallback.linalg.eigsh")
    w, X = linalg.eigsh(A, k=2, sigma=2.5, which="LM")
    np.testing.assert_allclose(np.asarray(w), [2.0, 3.0], atol=1e-6)
    assert counters.get("scipy_fallback.linalg.eigsh") == before


# ------------------------------------------------- lobpcg block seed --
@pytest.mark.slow
def test_lobpcg_generalized_block_seed_survives_bad_first_column():
    """X[:, 0] an exact eigenvector of the WRONG end of the spectrum:
    the old single-column seed handed Lanczos an immediate breakdown
    start; the block seed must still find the largest pairs."""
    n = 60
    d = np.arange(1.0, n + 1.0)
    A = sparse.diags([d], [0], shape=(n, n), format="csr",
                     dtype=np.float64)
    B = sparse.diags([np.ones(n)], [0], shape=(n, n), format="csr",
                     dtype=np.float64)
    rng = np.random.default_rng(9)
    X = np.zeros((n, 2))
    X[0, 0] = 1.0                      # eigenvector of the SMALLEST
    X[:, 1] = rng.standard_normal(n)
    w, V = linalg.lobpcg(A, X, B=B, largest=True, tol=1e-9)
    np.testing.assert_allclose(np.asarray(w), [n, n - 1], atol=1e-6)
    for i, lam in enumerate(np.asarray(w)):
        v = np.asarray(V)[:, i]
        resid = np.linalg.norm(d * v - lam * v)
        assert resid < 1e-5 * max(abs(lam), 1.0)


def test_lobpcg_complex_block_seed():
    """Complex-Hermitian route through the native Lanczos: same block
    seeding."""
    n = 40
    d = np.arange(1.0, n + 1.0)
    A_d = np.diag(d).astype(np.complex64)
    A = sparse.csr_array(A_d)
    rng = np.random.default_rng(21)
    X = np.zeros((n, 2), dtype=np.complex64)
    X[0, 0] = 1.0
    X[:, 1] = (rng.standard_normal(n)
               + 1j * rng.standard_normal(n)).astype(np.complex64)
    w, V = linalg.lobpcg(A, X, largest=True, tol=1e-5)
    np.testing.assert_allclose(np.asarray(w), [n, n - 1], atol=1e-3)


# ------------------------------------------------- probe verdict cache --
@pytest.fixture
def probe_state(tmp_path, monkeypatch):
    path = tmp_path / "lst_probe.json"
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PROBE_STATE", str(path))
    monkeypatch.delenv("LEGATE_SPARSE_TPU_PROBE_FORCE", raising=False)
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PROBE_TTL", "600")
    return path


def test_probe_cache_roundtrip(probe_state):
    from legate_sparse_tpu import _platform as P

    assert P.read_cached_probe() is None
    P.write_probe_state(False)
    assert P.read_cached_probe() is False
    P.write_probe_state(True)
    assert P.read_cached_probe() is True


def test_probe_cache_ttl_and_force(probe_state, monkeypatch):
    from legate_sparse_tpu import _platform as P

    P.write_probe_state(True)
    st = json.loads(probe_state.read_text())
    st["ts"] = time.time() - 10_000          # expired
    probe_state.write_text(json.dumps(st))
    assert P.read_cached_probe() is None

    P.write_probe_state(True)
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PROBE_FORCE", "1")
    assert P.read_cached_probe() is None     # capture scripts bypass
    monkeypatch.delenv("LEGATE_SPARSE_TPU_PROBE_FORCE")
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PROBE_TTL", "0")
    assert P.read_cached_probe() is None     # caching disabled


def test_probe_cache_tunnel_transition_invalidates(probe_state):
    from legate_sparse_tpu import _platform as P

    P.write_probe_state(False)
    st = json.loads(probe_state.read_text())
    # Simulate the live-tunnel marker flipping since the verdict.
    st["tunnel_marker"] = not os.path.exists(P._ALIVE_MARKER)
    probe_state.write_text(json.dumps(st))
    assert P.read_cached_probe() is None


def test_probe_cache_corrupt_file_ignored(probe_state):
    from legate_sparse_tpu import _platform as P

    probe_state.write_text("{not json")
    assert P.read_cached_probe() is None
    probe_state.write_text('["wrong", "shape"]')
    assert P.read_cached_probe() is None


# --------------------------------------------- roofline itemization --
def test_cpu_roofline_items_are_measured_and_named():
    """The sub-0.7 itemization path (bench contract: a bare ratio is
    not evidence) must keep producing its named, measured terms — it
    only fires on sub-roofline boxes, so the bench JSON alone cannot
    guard it."""
    import jax.numpy as jnp

    import bench

    n = 1 << 14
    A = bench._banded_config(sparse, n, 11)
    x = jnp.full((n,), 1.0, dtype=jnp.float32)
    _ = A @ x      # warm structure caches
    items = bench._cpu_roofline_items(sparse, A, x, dt_ms=1.0,
                                      bw_ms=0.5, compute_ms=0.1)
    for key in ("measured_ms", "bound_bw_ms", "bound_compute_ms",
                "shifted_add_ms", "mask_ms", "pad_alloc_ms",
                "segment_sum_n", "segment_sum_ms",
                "shifted_add_seg_ms"):
        assert key in items, key
    assert items["segment_sum_ms"] > 0
    assert items["shifted_add_ms"] > 0
