# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Round-3 scipy-surface additions: find/bmat/block_array/kronsum,
maximum/minimum/argmax/argmin/trace/count_nonzero/reshape/resize,
shape-only constructor, todok/tolil host conversions.

Differential model: scipy (a user switching from scipy.sparse must
find these working)."""

import warnings

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as lst


@pytest.fixture
def pair():
    A = lst.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(8, 8),
                  format="csr")
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(8, 8)).tocsr()
    return A, As


def test_find(pair):
    A, As = pair
    r, c, v = lst.find(A)
    rs, cs, vs = sp.find(As)
    assert (np.sort(r * 8 + c) == np.sort(rs * 8 + cs)).all()
    np.testing.assert_allclose(np.sort(v), np.sort(vs))


def test_bmat_and_block_array(pair):
    A, As = pair
    np.testing.assert_allclose(
        lst.bmat([[A, None], [None, A]]).toarray(),
        sp.bmat([[As, None], [None, As]]).toarray(),
    )
    np.testing.assert_allclose(
        lst.block_array([[A, A]]).toarray(),
        sp.block_array([[As, As]]).toarray(),
    )
    with pytest.raises(ValueError):
        lst.bmat([[None, None]])


def test_kronsum(pair):
    A, As = pair
    np.testing.assert_allclose(
        lst.kronsum(A, A).toarray(), sp.kronsum(As, As).toarray()
    )


def test_kronsum_asymmetric_operands():
    """A != B catches the operand-order convention."""
    A = np.array([[1.0, 2.0], [0.0, 3.0]])
    B = np.array([[5.0, 0.0, 1.0], [0.0, 6.0, 0.0], [2.0, 0.0, 7.0]])
    got = lst.kronsum(lst.csr_array(A), lst.csr_array(B)).toarray()
    want = sp.kronsum(sp.csr_array(A), sp.csr_array(B)).toarray()
    np.testing.assert_allclose(got, want)


def test_bmat_integer_dtype_preserved():
    Ai = sp.identity(3, dtype=np.int64, format="csr")
    got = lst.bmat([[lst.csr_array(Ai), None], [None, lst.csr_array(Ai)]])
    want = sp.bmat([[Ai, None], [None, Ai]])
    assert got.dtype == want.dtype
    np.testing.assert_allclose(got.toarray(), want.toarray())


def test_count_nonzero_duplicates_cancel():
    A = lst.csr_array(
        (np.array([1.0, -1.0, 2.0]),
         (np.array([0, 0, 1]), np.array([0, 0, 1]))),
        shape=(2, 2),
    )
    assert A.count_nonzero() == 1


def test_reshape_1d_rejected(pair):
    A, _ = pair
    with pytest.raises(ValueError):
        A.reshape(64)


def test_trace_count_nonzero(pair):
    A, As = pair
    assert float(A.trace()) == As.trace()
    assert float(A.trace(1)) == As.trace(1)
    assert A.count_nonzero() == As.count_nonzero()
    for axis in (0, 1):
        # Dense-derived reference: the axis kwarg only landed in scipy
        # 1.13+ sparray (the installed spmatrix rejects it).
        np.testing.assert_array_equal(
            np.asarray(A.count_nonzero(axis=axis)).ravel(),
            (As.toarray() != 0).sum(axis=axis).ravel(),
        )


@pytest.mark.parametrize("op", ["maximum", "minimum"])
def test_minmax_sparse_and_scalar(pair, op):
    A, As = pair
    other = sp.random(8, 8, density=0.3, format="csr", random_state=4)
    got = getattr(A, op)(lst.csr_array(other))
    want = getattr(As, op)(other)
    np.testing.assert_allclose(got.toarray(), want.toarray())
    np.testing.assert_allclose(
        getattr(A, op)(0).toarray(), getattr(As, op)(0).toarray()
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        s = -1.0 if op == "maximum" else 1.0
        np.testing.assert_allclose(
            getattr(A, op)(s).toarray(), getattr(As, op)(s).toarray()
        )


def test_argmax_argmin(pair):
    A, As = pair
    assert A.argmax() == As.argmax()
    assert A.argmin() == As.argmin()
    np.testing.assert_array_equal(
        np.asarray(A.argmax(axis=1)).ravel(),
        np.asarray(As.argmax(axis=1)).ravel(),
    )


def test_reshape_resize(pair):
    A, As = pair
    np.testing.assert_allclose(
        A.reshape(4, 16).toarray(), As.toarray().reshape(4, 16)
    )
    B = lst.csr_array(A)
    B.resize((5, 5))
    Bs = As.copy()
    Bs.resize((5, 5))
    np.testing.assert_allclose(B.toarray(), Bs.toarray())
    B2 = lst.csr_array(A)
    B2.resize((12, 12))
    Bs2 = As.copy()
    Bs2.resize((12, 12))
    np.testing.assert_allclose(B2.toarray(), Bs2.toarray())


def test_dok_lil_host_conversions(pair):
    A, As = pair
    np.testing.assert_allclose(np.asarray(A.todok().toarray()),
                               As.toarray())
    np.testing.assert_allclose(np.asarray(A.tolil().toarray()),
                               As.toarray())


@pytest.mark.parametrize("fmt", ["dia", "csc", "coo"])
def test_csr_delegation_on_other_formats(fmt):
    """csc/coo/dia carry the same method surface via CSR delegation."""
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(8, 8))
    A = lst.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(8, 8),
                  format="csr").asformat(fmt)
    assert float(A.trace()) == As.diagonal().sum()
    assert A.count_nonzero() == sp.csr_matrix(As).count_nonzero()
    np.testing.assert_allclose(
        np.asarray(A.maximum(0).toarray()),
        np.maximum(As.toarray(), 0),
    )
    np.testing.assert_allclose(
        np.asarray(A.multiply(2.0).toarray()), As.toarray() * 2.0
    )


def test_shape_only_constructor():
    Z = lst.csr_array((3, 4))
    assert Z.shape == (3, 4) and Z.nnz == 0
    np.testing.assert_allclose(Z.toarray(), np.zeros((3, 4)))
    Zi = lst.csr_array((2, 2), dtype=np.float32)
    assert Zi.dtype == np.float32


def test_minmax_scalar_duplicates_and_axis_validation():
    A = lst.csr_array(
        (np.array([1.0, -10.0]), (np.array([0, 0]), np.array([0, 0]))),
        shape=(1, 1),
    )
    got = A.maximum(-5.0)
    np.testing.assert_allclose(np.asarray(got.toarray()), [[-5.0]])
    with pytest.raises(ValueError):
        A.count_nonzero(axis=2)


def test_mul_semantics_array_vs_matrix():
    """sparray ``*`` is element-wise; spmatrix ``*`` is matmul."""
    rng = np.random.default_rng(0)
    As = sp.random(8, 8, density=0.4, format="csr", random_state=rng)
    Bs = sp.random(8, 8, density=0.4, format="csr", random_state=rng)
    A, B = lst.csr_array(As), lst.csr_array(Bs)
    np.testing.assert_allclose(
        np.asarray((A * B).toarray()),
        (sp.csr_array(As) * sp.csr_array(Bs)).toarray(),
    )
    Am, Bm = lst.csr_matrix(As), lst.csr_matrix(Bs)
    np.testing.assert_allclose(
        np.asarray((Am * Bm).toarray()),
        (sp.csr_matrix(As) * sp.csr_matrix(Bs)).toarray(), atol=1e-12,
    )
    # csc and coo follow the same split.
    np.testing.assert_allclose(
        np.asarray((A.tocsc() * B.tocsc()).toarray()),
        (sp.csr_array(As) * sp.csr_array(Bs)).toarray(),
    )
    Cm = lst.csc_matrix(A.tocsc())
    Dm = lst.csc_matrix(B.tocsc())
    np.testing.assert_allclose(
        np.asarray((Cm * Dm).toarray()), (As @ Bs).toarray(), atol=1e-12,
    )
    O = A.asformat("coo")
    np.testing.assert_allclose(
        np.asarray((O * B.asformat("coo")).toarray()),
        (sp.csr_array(As) * sp.csr_array(Bs)).toarray(),
    )


def test_mul_class_preservation_and_rmul():
    rng = np.random.default_rng(0)
    As = sp.random(8, 8, density=0.4, format="csr", random_state=rng)
    M = lst.csr_matrix(As)
    assert type(M * 2).__name__ == "csr_matrix"   # stays matmul-flavored
    np.testing.assert_allclose(
        np.asarray((M * np.array(3.0)).toarray()), (As * 3).toarray()
    )
    C = lst.csr_array(As).tocsc()
    np.testing.assert_allclose(                    # numpy defers to us
        np.asarray((np.ones(8) * C).toarray()),
        np.asarray((C * np.ones(8)).toarray()),
    )
    assert (C * C).format == "csc"                 # format-preserving
    O = lst.csr_array(As).asformat("coo")
    assert (O * O).format == "coo"


def test_truediv_dense_and_sparse():
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(4, 4)).tocsr()
    A = lst.csr_array(As)
    np.testing.assert_allclose(
        np.asarray((A / np.full(4, 2.0)).toarray()),
        (sp.csr_array(As) / np.full(4, 2.0)).toarray(),
    )
    np.testing.assert_allclose(
        np.asarray(A / A), sp.csr_array(As) / sp.csr_array(As),
        equal_nan=True,
    )


def test_truediv_shape_check_and_broadcast():
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(4, 4)).tocsr()
    A = lst.csr_array(As)
    with pytest.raises(ValueError):
        A / lst.csr_array(sp.eye(3).tocsr())
    np.testing.assert_allclose(
        np.asarray((A / np.full((4, 1), 2.0)).toarray()),
        (sp.csr_array(As) / np.full((4, 1), 2.0)).toarray(),
    )


def test_comparisons_pow_abs_nonzero():
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(4, 4)).tocsr()
    Bs = As.copy()
    Bs[0, 0] = 5.0
    A, B = lst.csr_array(As), lst.csr_array(Bs)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for ours, theirs in [
            (A == B, sp.csr_array(As) == sp.csr_array(Bs)),
            (A != B, sp.csr_array(As) != sp.csr_array(Bs)),
            (A < B, sp.csr_array(As) < sp.csr_array(Bs)),
            (A >= B, sp.csr_array(As) >= sp.csr_array(Bs)),
            (A == 1.0, sp.csr_array(As) == 1.0),
            (A > 0, sp.csr_array(As) > 0),
        ]:
            np.testing.assert_array_equal(
                np.asarray(ours.toarray()), theirs.toarray()
            )
            assert ours.dtype == np.bool_
    np.testing.assert_allclose(
        np.asarray((A ** 2).toarray()), (sp.csr_array(As) ** 2).toarray()
    )
    np.testing.assert_allclose(
        np.asarray(abs(A).toarray()), abs(sp.csr_array(As)).toarray()
    )
    r, c = A.nonzero()
    rs, cs = As.nonzero()
    assert (np.sort(r * 4 + c) == np.sort(rs * 4 + cs)).all()
    M = lst.csr_matrix(As)
    np.testing.assert_allclose(
        np.asarray(M.getrow(1).toarray()),
        sp.csr_matrix(As).getrow(1).toarray(),
    )
    np.testing.assert_allclose(
        np.asarray(M.getcol(2).toarray()),
        sp.csr_matrix(As).getcol(2).toarray(),
    )
    np.testing.assert_allclose(
        np.asarray(M.getH().toarray()), sp.csr_matrix(As).getH().toarray()
    )


def test_matrix_power_and_class_flavor():
    As = sp.csr_matrix(np.array([[1.0, 2.0], [0.0, 3.0]]))
    M = lst.csr_matrix(As)
    np.testing.assert_allclose(
        np.asarray((M ** 2).toarray()), (As ** 2).toarray()
    )
    for obj in (M ** 2, M.getH(), M.getrow(0), M.getcol(1), M.T,
                M.copy(), M * 2):
        assert type(obj).__name__ == "csr_matrix", type(obj)
    # sparray ** stays element-wise.
    A = lst.csr_array(As)
    np.testing.assert_allclose(
        np.asarray((A ** 2).toarray()), (sp.csr_array(As) ** 2).toarray()
    )


def test_comparison_warning_parity():
    As = sp.csr_matrix(np.array([[1.0, 2.0], [0.0, 3.0]]))
    A = lst.csr_array(As)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        A == 1.0   # noqa: B015 - sparse result, no warning
        assert not rec
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        A < 1.0    # noqa: B015 - implicit zeros compare True
        assert rec


def test_sparse_union_comparison_no_densify():
    """Sparse-result comparisons work at scales where densifying would
    allocate tens of GB."""
    n = 200_000
    rng = np.random.default_rng(0)
    r = rng.integers(0, n, 500)
    c = rng.integers(0, n, 500)
    A = lst.csr_array(
        (np.ones(500), (r, c)), shape=(n, n)
    )
    res = A != A
    assert res.nnz == 0
    res2 = A > A * 0.5
    assert res2.nnz == A._canonicalized().nnz


def test_tocoo_returns_coo_array():
    As = sp.csr_matrix(np.array([[1.0, 2.0], [0.0, 3.0]]))
    co = lst.csr_array(As).tocoo()
    assert type(co).__name__ == "coo_array"
    np.testing.assert_allclose(np.asarray(co.toarray()), As.toarray())
    # csc/dia get tocoo via delegation too.
    assert type(lst.csr_array(As).tocsc().tocoo()).__name__ == "coo_array"


def test_matrix_power_other_formats():
    As = sp.csr_matrix(np.array([[1.0, 2.0], [0.0, 3.0]]))
    for name, to in [("csc", "tocsc"), ("coo", "tocoo"), ("dia", "todia")]:
        M = getattr(lst, f"{name}_matrix")(
            getattr(lst.csr_array(As), to)()
        )
        got = M ** 2
        np.testing.assert_allclose(
            np.asarray(got.toarray()), (As @ As).toarray()
        )
        assert type(got).__name__ == f"{name}_matrix"


def test_dia_csc_arithmetic_surface():
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(5, 5))
    D = lst.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(5, 5))
    np.testing.assert_allclose(
        np.asarray((D * 2.0).toarray()), (As * 2).toarray()
    )
    assert (D * 2.0).format == "dia"
    for got, want in [(2.0 * D, As * 2), (-D, -As), (D / 2, As / 2),
                      (D + D, As + As), (D - D, As - As)]:
        np.testing.assert_allclose(
            np.asarray(got.toarray()), want.toarray()
        )
    C = D.tocsr().tocsc()
    np.testing.assert_allclose(
        np.asarray((C + C).toarray()), (As + As).toarray()
    )


def test_dia_matrix_spmatrix_semantics():
    As_d = sp.dia_matrix(
        sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(5, 5))
    )
    D = lst.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(5, 5))
    M = lst.dia_matrix(D)
    np.testing.assert_allclose(
        np.asarray((M * M).toarray()), (As_d * As_d).toarray()
    )
    assert type(M * 2.0).__name__ == "dia_matrix"
    assert type(-M).__name__ == "dia_matrix"
    x = np.arange(5.0)
    np.testing.assert_allclose(np.asarray(x * M), x * As_d)
    assert type(M + M).__name__ == "csr_matrix"
    assert (-D.astype(np.int32)).dtype == np.int32
    np.testing.assert_allclose(
        np.asarray(sum([D, D]).toarray()), (As_d * 2).toarray()
    )
    with pytest.raises(NotImplementedError):
        np.ones((5, 5)) @ D
