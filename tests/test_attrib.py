# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Per-tenant attribution ledger + capacity sensor (obs/attrib.py,
obs/capacity.py — docs/OBSERVABILITY.md "Per-tenant attribution").

The load-bearing contracts, each pinned here:

- **exact conservation**: attributed integer costs (comm bytes, wall
  ns, waits) sum over tenants to the untagged totals EXACTLY — for
  single-tenant dispatches, packed multi-tenant batches (the declared
  remainder apportioning rule), and under the composed-fault chaos
  drill;
- **every outcome attributes its wait**: shed requests show queue
  wait but zero dispatch/comm cost;
- **bounded label cardinality**: tenant names are sanitized to a
  dot-free OpenMetrics-safe charset (fuzzed with quotes / newlines /
  unicode) and fold into ``__other__`` past the cap;
- **inert-by-default**: without ``LEGATE_SPARSE_TPU_OBS_ATTRIB`` no
  ``attrib.*`` / ``util.*`` / ``capacity.*`` counter ever moves and
  results are bit-for-bit identical;
- **capacity report**: the pure ``recommend()`` join of demand, QoS
  weight and SLO burn is deterministic, and ``capacity_report`` emits
  the advisory ``capacity.recommendation`` event;
- **doctor**: the ``noisy-neighbor`` rule fires on a hog + page-level
  burn and stays quiet otherwise.
"""

import time

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import legate_sparse_tpu as lst
from legate_sparse_tpu import graph, obs, resilience
from legate_sparse_tpu.engine import Engine, Gateway
from legate_sparse_tpu.obs import (
    attrib, capacity, context, counters, export, latency, report,
    slo, trace,
)
from legate_sparse_tpu.parallel import make_row_mesh, shard_csr
from legate_sparse_tpu.parallel.dist_csr import dist_spmv, shard_vector
from legate_sparse_tpu.resilience import chaos
from legate_sparse_tpu.settings import settings

from utils_test.tools import load_tool as _tool

R = len(jax.devices())
needs_mesh = pytest.mark.skipif(R < 2, reason="needs a multi-device mesh")

_ENG = Engine()


@pytest.fixture(autouse=True)
def _obs_isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    context.reset_ids()
    yield
    obs.reset_all()
    context.reset_ids()
    if was:
        trace.enable()
    else:
        trace.disable()


@pytest.fixture
def attrib_on():
    saved = (settings.obs_attrib, settings.obs_tenant_cap)
    settings.obs_attrib = True
    yield settings
    settings.obs_attrib, settings.obs_tenant_cap = saved


@pytest.fixture
def gw_on():
    saved = settings.gateway
    settings.gateway = True
    yield settings
    settings.gateway = saved


_RESIL_KNOBS = (
    "resil", "resil_retries", "resil_backoff_ms", "resil_breaker_k",
    "resil_breaker_cooldown_ms",
)


@pytest.fixture
def armed(gw_on):
    """Gateway + resilience armed (the chaos-drill configuration)."""
    saved = {k: getattr(settings, k) for k in _RESIL_KNOBS}
    settings.resil = True
    settings.resil_backoff_ms = 0.0
    resilience.reset()
    yield settings
    for k, v in saved.items():
        setattr(settings, k, v)
    resilience.reset()


def _random_csr(n=400, density=0.03, seed=0):
    S = sp.random(n, n, density=density, format="csr",
                  random_state=np.random.default_rng(seed),
                  dtype=np.float32)
    return lst.csr_array(S)


def _banded(n, dtype=np.float32):
    return lst.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)], [-1, 0, 1],
        shape=(n, n), format="csr", dtype=dtype,
    )


def _x(n, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(n).astype(np.float32))


def _gateway(**kw):
    base = dict(max_batch=64, queue_depth=128, tenant_quota=64,
                rate=0.0, burst=16.0, slack_ms=1.0, timeout_ms=0.0)
    base.update(kw)
    return Gateway(_ENG, **base)


def _tenant_sum(kind):
    """Sum of ``attrib.tenant.<t>.<kind>`` over every tenant."""
    return sum(v for k, v in counters.snapshot("attrib.tenant.").items()
               if k.endswith("." + kind))


# ------------------------------------------------- apportioning rule --
def test_apportion_conserves_and_orders_remainder():
    members = [("b", "x"), ("a", "x"), ("a", "x")]
    shares = attrib.apportion(10, members)
    assert sum(shares) == 10
    # Remainder units go one at a time in ascending (tenant, qos,
    # position) order: the two "a" members lead "b".
    assert shares == [3, 4, 3]
    assert attrib.apportion(9, members) == [3, 3, 3]
    assert attrib.apportion(2, members) == [0, 1, 1]
    assert attrib.apportion(0, members) == [0, 0, 0]
    assert attrib.apportion(7, [("t", "q")]) == [7]


# ------------------------------------------------- label sanitation --
def test_tenant_label_fuzz_sanitizes_hostile_names(attrib_on):
    hostile = ['evil"quote', "line\nbreak", "tab\there",
               "dots.in.name", "semi;colon", 'back\\slash',
               "uniécode-\U0001f680", "x" * 200]
    for raw in hostile:
        label = attrib.tenant_label(raw)
        assert label, raw
        assert len(label) <= 64, raw
        assert set(label) <= attrib._SAFE, (raw, label)
        assert "." not in label and '"' not in label and \
            "\n" not in label, (raw, label)
    # Fully-mangled names keep a stable stand-in, never a reserved
    # name collision; empties fall to the untagged sink.
    assert attrib.tenant_label("\U0001f680\U0001f680") == "t2"
    assert attrib.tenant_label("") == attrib.UNTAGGED
    assert attrib.tenant_label(None) == attrib.UNTAGGED
    assert attrib.tenant_label(attrib.UNTAGGED) == attrib.UNTAGGED
    assert attrib.tenant_label(attrib.OTHER) == attrib.OTHER


def test_tenant_label_fuzz_openmetrics_roundtrip(attrib_on):
    """Counters named with sanitized hostile tenants must survive the
    OpenMetrics render -> parse round trip exactly."""
    for raw in ('quo"te', "new\nline", "unié-\U0001f680",
                "ok-tenant_1"):
        with attrib.scope([(raw, "interactive")]):
            attrib.on_comm("fuzz_op", 37, 1)
    snap = counters.snapshot("attrib.")
    assert snap, "no attributed counters recorded"
    text = export.snapshot_openmetrics()
    parsed_counters, _hists = export.parse_openmetrics(text)
    for name, val in snap.items():
        assert parsed_counters.get(name) == val, name


def test_tenant_cap_folds_overflow_into_other(attrib_on):
    settings.obs_tenant_cap = 2
    assert attrib.tenant_label("alpha") == "alpha"
    assert attrib.tenant_label("beta") == "beta"
    # Third distinct label folds; already-seen labels stay stable.
    assert attrib.tenant_label("gamma") == attrib.OTHER
    assert attrib.tenant_label("delta") == attrib.OTHER
    assert attrib.tenant_label("alpha") == "alpha"
    assert counters.get("attrib.fold.other") == 2
    # Folded tenants still attribute (into the shared bucket).
    with attrib.scope([("gamma", "batch")]):
        attrib.on_comm("cap_op", 11, 1)
    assert counters.get(
        f"attrib.tenant.{attrib.OTHER}.comm_bytes") == 11


# --------------------------------------------- conservation: bytes --
@needs_mesh
def test_dist_spmv_bytes_conserve_exactly(attrib_on):
    """The tier-1 conservation pin: per-tenant attributed comm bytes
    sum EXACTLY to the untagged ``comm.total_bytes`` — one
    single-tenant dispatch plus one packed 3-member dispatch whose
    byte total does not divide evenly (remainder apportioning)."""
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    assert dA.halo == 1
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    per_call = 2 * R * dA.halo * 4      # two-sided halo exchange, f32

    with context.use(context.mint(tenant="alice", qos="interactive")):
        _ = np.asarray(dist_spmv(dA, x))
    with attrib.scope([("alice", "interactive"), ("bob", "batch"),
                       ("carol", "background")]):
        _ = np.asarray(dist_spmv(dA, x))

    base, rem = divmod(per_call, 3)
    assert rem == 1, "fixture must exercise the remainder path"
    # alice sorts first among the members, so she takes the remainder
    # unit — on top of her whole single-tenant dispatch.
    assert counters.get("attrib.tenant.alice.comm_bytes") == \
        per_call + base + 1
    assert counters.get("attrib.tenant.bob.comm_bytes") == base
    assert counters.get("attrib.tenant.carol.comm_bytes") == base
    assert _tenant_sum("comm_bytes") == \
        counters.get("attrib.total.comm_bytes") == \
        counters.get("comm.total_bytes") == 2 * per_call
    # Collective-call conservation: 1 call per dispatch; the packed
    # dispatch's single call lands on the first-sorted member.
    assert counters.get("attrib.tenant.alice.comm_calls") == 2
    assert _tenant_sum("comm_calls") == \
        counters.get("comm.total_calls") == 2


# ----------------------------------------- conservation: wall time --
def test_packed_gateway_wall_ns_conserves_to_span_sum(gw_on,
                                                      attrib_on):
    """A packed multi-tenant gateway batch: attributed wall ns per
    tenant sums exactly to the dispatch spans' summed durations, and
    both tenants in the pack carry nonzero cost."""
    obs.enable()
    A1, A2 = _random_csr(seed=3), _random_csr(seed=4)
    gw = _gateway(max_batch=4)
    try:
        futs = [gw.submit(A1, _x(400, seed=1), tenant="alice",
                          qos="interactive"),
                gw.submit(A2, _x(400, seed=2), tenant="alice",
                          qos="interactive"),
                gw.submit(A1, _x(400, seed=3), tenant="bob",
                          qos="batch"),
                gw.submit(A2, _x(400, seed=4), tenant="bob",
                          qos="batch")]
        gw.flush()
        for f in futs:
            _ = np.asarray(f.result(timeout=60))
    finally:
        gw.shutdown()
    span_sum = sum(r["dur_ns"] for r in obs.records()
                   if r.get("type") == "span"
                   and r["name"] in attrib.DISPATCH_SPANS)
    assert span_sum > 0
    assert _tenant_sum("wall_ns") == \
        counters.get("attrib.total.wall_ns") == span_sum
    for tenant in ("alice", "bob"):
        assert counters.get(f"attrib.tenant.{tenant}.wall_ns") > 0
        assert counters.get(f"attrib.tenant.{tenant}.wait_ns") > 0
    assert _tenant_sum("dispatches") == 4
    # Per-(tenant, qos, op) wall breakdown conserves too.
    op_sum = sum(counters.snapshot("attrib.op.").values())
    assert op_sum == span_sum
    # The dispatch fed the utilization window.
    assert counters.get("util.busy_ns") == span_sum
    assert counters.get("util.dispatches") >= 1


# --------------------------------------- chaos-drill conservation --
@needs_mesh
def test_chaos_drill_conserves_attribution(armed, attrib_on):
    """Satellite: the multi-tenant chaos drill with faults armed — a
    deadline-storm tenant shed every round — plus a distributed
    dispatch in the same window.  Per-tenant attributed bytes and
    wall-ns sum EXACTLY to the untagged ledgers, and shed requests
    attribute wait but zero dispatch/comm cost."""
    obs.enable()
    A_good, A_storm = _random_csr(seed=3), _random_csr(seed=4)
    gw = _gateway(max_batch=8)
    try:
        rep = chaos.run_drill(
            gw,
            tenants=[
                {"name": "good", "qos": "interactive", "A": A_good,
                 "xs": [_x(400, seed=s) for s in range(3)]},
                {"name": "storm", "qos": "background", "A": A_storm,
                 "xs": [_x(400, seed=s) for s in range(10, 13)],
                 "deadline_ms": 0.0},
            ],
            rounds=3, seed=7)
    finally:
        gw.shutdown()
    assert rep.ok(), rep.violations
    assert rep.per_tenant["storm"]["shed"] == 9

    # Real interconnect bytes inside the same attributed window.
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    with context.use(context.mint(tenant="good", qos="interactive")):
        _ = np.asarray(dist_spmv(dA, x))

    # Bytes: exact conservation against the untagged comm ledger.
    assert counters.get("comm.total_bytes") > 0
    assert _tenant_sum("comm_bytes") == \
        counters.get("attrib.total.comm_bytes") == \
        counters.get("comm.total_bytes")
    # Wall: exact conservation against the dispatch span durations.
    span_sum = sum(r["dur_ns"] for r in obs.records()
                   if r.get("type") == "span"
                   and r["name"] in attrib.DISPATCH_SPANS)
    assert span_sum > 0
    assert _tenant_sum("wall_ns") == \
        counters.get("attrib.total.wall_ns") == span_sum
    # The storm tenant was shed at admit every time: wait attributed,
    # zero dispatch cost, zero bytes.
    assert counters.get("attrib.tenant.storm.wait_ns") > 0
    assert counters.get("attrib.tenant.storm.wall_ns") == 0
    assert counters.get("attrib.tenant.storm.dispatches") == 0
    assert counters.get("attrib.tenant.storm.comm_bytes") == 0
    assert counters.get("attrib.tenant.good.wall_ns") > 0


# ------------------------------------------------ inert by default --
@needs_mesh
def test_attrib_inert_without_flag(gw_on):
    """Acceptance: with the flag off (default) the whole subsystem is
    bit-for-bit + counter inert — tenant-tagged traffic moves no
    ``attrib.*`` / ``util.*`` / ``capacity.*`` counter, and enabling
    it changes no numerics."""
    assert settings.obs_attrib is False
    obs.enable()
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    A = _random_csr()
    xg = _x(400)

    def _run():
        with context.use(context.mint(tenant="alice",
                                      qos="interactive")):
            y_d = np.asarray(dist_spmv(dA, x))
        gw = _gateway()
        try:
            fut = gw.submit(A, xg, tenant="alice", qos="interactive")
            gw.flush()
            y_g = np.asarray(fut.result(timeout=60))
        finally:
            gw.shutdown()
        return y_d, y_g

    with attrib.scope([("alice", "interactive")]):  # no-op while off
        assert attrib.current_members() == \
            ((attrib.UNTAGGED, "none"),)
    y_d_off, y_g_off = _run()
    for prefix in ("attrib.", "util.", "capacity."):
        assert counters.snapshot(prefix) == {}, prefix
    assert capacity.capacity_report() is None
    assert counters.snapshot("capacity.") == {}

    saved = settings.obs_attrib
    try:
        settings.obs_attrib = True
        y_d_on, y_g_on = _run()
    finally:
        settings.obs_attrib = saved
    assert np.array_equal(y_d_off, y_d_on)
    assert np.array_equal(y_g_off, y_g_on)
    assert counters.snapshot("attrib.") != {}


# -------------------------------------------------- capacity layer --
def test_recommend_is_pure_and_deterministic():
    demand = {"a": {"busy_ns": 6_000_000_000, "qos": "interactive"},
              "b": {"busy_ns": 3_000_000_000, "qos": "batch"},
              "c": {"busy_ns": 1_000_000_000, "qos": "background"}}
    weights = {"interactive": 8.0, "batch": 4.0, "background": 1.0}
    rec = capacity.recommend(demand, weights, {}, devices=8)
    assert rec["devices"] == 8
    assert rec["tenants"]["a"]["devices"] == 6
    assert rec["tenants"]["b"]["devices"] == 1
    assert rec["tenants"]["c"]["devices"] == 1   # min 1 per demander
    assert rec["allocated"] == 8
    assert rec["undersized"] is False
    # A page-level burn on the interactive class rounds its tenant UP;
    # with no non-burning allocation above 1 to trim, the overshoot
    # stands — the undersized signal.
    rec2 = capacity.recommend(
        demand, weights, {"interactive": capacity.BURN_PAGE}, 8)
    assert rec2["tenants"]["a"]["burning"] is True
    assert rec2["tenants"]["a"]["devices"] == 7
    assert rec2["allocated"] == 9
    assert rec2["undersized"] is True
    assert capacity.recommend({}, weights, {}, 8)["allocated"] == 0


def test_utilization_window_evicts_by_timestamp(attrib_on):
    capacity.note_busy(5_000_000, (("alice", "interactive"),))
    capacity.note_busy(3_000_000, (("bob", "batch"),))
    now = time.monotonic_ns()
    util = capacity.utilization(60_000.0, now_ns=now)
    assert util["busy_ns"] == 8_000_000
    assert util["per_tenant"] == {"alice": 5_000_000,
                                  "bob": 3_000_000}
    assert 0.0 < util["busy_frac"] <= 1.0
    assert counters.get("util.busy_ns") == 8_000_000
    assert counters.get("util.dispatches") == 2
    # A window whose horizon is in the future evicts every sample.
    empty = capacity.utilization(1.0, now_ns=now + 10 ** 12)
    assert empty["busy_ns"] == 0 and empty["per_tenant"] == {}


def test_capacity_report_emits_recommendation_event(attrib_on):
    obs.enable()
    with attrib.scope([("alice", "interactive")]):
        attrib.on_span_close("gateway.batch", 5_000_000, True)
    rec = capacity.capacity_report(devices=8)
    assert rec is not None
    assert rec["devices"] == 8
    assert rec["tenants"]["alice"]["qos"] == "interactive"
    assert rec["tenants"]["alice"]["devices"] == 8
    assert rec["undersized"] is False
    assert counters.get("capacity.reports") == 1
    evs = [r for r in obs.records()
           if r["name"] == "capacity.recommendation"]
    assert len(evs) == 1
    at = evs[0]["attrs"]
    assert at["devices"] == 8 and at["allocated"] == 8
    assert "alice" in at["tenants"]


# ------------------------------------------------------- surfaces --
def test_render_tenants_table_conservation_line():
    assert "no attrib.tenant.* counters" in \
        report.render_tenants_table({})
    ctrs = {"attrib.tenant.alice.comm_bytes": 86,
            "attrib.tenant.alice.wall_ns": 2_000_000,
            "attrib.tenant.bob.comm_bytes": 42,
            "attrib.total.comm_bytes": 128,
            "comm.total_bytes": 128,
            "util.busy_ns": 2_000_000,
            "util.dispatches": 1}
    out = report.render_tenants_table(ctrs)
    assert "alice" in out and "bob" in out
    assert "conservation: 128 attributed bytes" in out
    assert "exact" in out and "VIOLATED" not in out
    assert "utilization:" in out
    bad = dict(ctrs)
    bad["attrib.total.comm_bytes"] = 999
    assert "VIOLATED" in report.render_tenants_table(bad)


def test_trace_summary_tenants_flag(tmp_path, attrib_on, capsys):
    obs.enable()
    with attrib.scope([("alice", "interactive"), ("bob", "batch")]):
        attrib.on_comm("unit_op", 9, 1)
        with obs.span("engine.batch"):   # real dispatch span
            pass
    path = str(tmp_path / "t.trace.json")
    obs.write_chrome_trace(path)
    rc = _tool("trace_summary").main([path, "--tenants"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "tenant attribution:" in out
    assert "alice" in out and "bob" in out
    assert "conservation:" in out and "exact" in out


def test_doctor_noisy_neighbor_rule():
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    ev.counters = {"attrib.tenant.hog.wall_ns": 9e9,
                   "attrib.tenant.meek.wall_ns": 1e9,
                   "slo.breach.gateway.interactive": 2}
    codes = [f["code"] for f in doctor.diagnose(ev)]
    assert "noisy-neighbor" in codes
    finding = next(f for f in doctor.diagnose(ev)
                   if f["code"] == "noisy-neighbor")
    assert "hog" in finding["message"]
    assert "0.90" == finding["value"]
    # No page-level burn -> no finding (a hog alone is not a problem).
    ev.counters.pop("slo.breach.gateway.interactive")
    assert "noisy-neighbor" not in [
        f["code"] for f in doctor.diagnose(ev)]
    # Balanced tenants under a burn -> no finding (share not > 50%).
    ev.counters = {"attrib.tenant.a.wall_ns": 5e9,
                   "attrib.tenant.b.wall_ns": 5e9,
                   "slo.breach.gateway.interactive": 1}
    assert "noisy-neighbor" not in [
        f["code"] for f in doctor.diagnose(ev)]
    # The untagged sink never counts as a tenant pair.
    ev.counters = {"attrib.tenant.hog.wall_ns": 9e9,
                   "attrib.tenant.__untagged__.wall_ns": 1e9,
                   "slo.breach.gateway.interactive": 1}
    assert "noisy-neighbor" not in [
        f["code"] for f in doctor.diagnose(ev)]


# --------------------------------------- graph latency histograms --
def test_graph_algorithms_record_latency_histograms():
    """Satellite: the PR 16 graph algorithms feed always-on
    ``lat.graph.<alg>`` histograms (tracing off — histograms are
    always-on like every other lat.* family)."""
    S = sp.random(64, 64, density=0.06, format="csr",
                  random_state=np.random.default_rng(0))
    S.data[:] = 1.0
    graph.bfs(S, 0)
    graph.sssp(S, 0)
    graph.connected_components(S)
    graph.pagerank(S, tol=0.0, max_iters=3)
    for alg in ("bfs", "sssp", "cc", "pagerank"):
        hist = latency.get(f"lat.graph.{alg}")
        assert hist is not None and hist.count >= 1, alg


def test_graph_slos_registered_by_default():
    by_name = {s.name: s for s in slo.registered()}
    for alg, objective in (("bfs", 1000.0), ("sssp", 2000.0),
                           ("cc", 2000.0), ("pagerank", 5000.0)):
        s = by_name[f"graph.{alg}"]
        assert s.hist_prefix == f"lat.graph.{alg}"
        assert s.objective_ms == objective
        assert s.qos is None and s.target == 0.95


# -------------------------------------------------- trace context --
def test_trace_context_carries_tenant_and_qos():
    c = context.mint(rid=1, tenant="alice", qos="interactive")
    assert c.tenant == "alice" and c.qos == "interactive"
    assert "alice" in repr(c)
    with context.use(c):
        # A nested mint joins the outer admission identity: costs
        # charge to the outermost tenant, not an inner re-mint.
        assert context.mint(rid=2, tenant="bob", qos="batch") is c
        assert attrib.current_members() == (("alice", "interactive"),)
    assert context.mint(rid=3).tenant is None
