# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sparsity-fingerprint autotuner (docs/AUTOTUNER.md): fingerprints,
sliced-ELL kernels, verdict store, routing, engine defer.

The three load-bearing contracts:

- **inert off**: with ``settings.autotune`` False (the default) a
  dispatch records zero ``autotune.*`` counter movement, zero extra
  kernel compiles (``trace.*``), and bit-for-bit the same result;
- **parity on**: a routed dispatch runs the verdict's kernel exactly
  as a direct dispatch of that kernel would — a ``csr-rowids``
  verdict is bitwise-identical to the plain chain, a ``sliced-ell``
  verdict bitwise-identical to calling the kernel directly — fuzzed
  on f32/f64/c64;
- **silent declines**: tracer contexts, dtype promotion, store
  misses, and stale verdicts all fall through to today's heuristics,
  never error.
"""

import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import legate_sparse_tpu as lst
from legate_sparse_tpu import autotune, gallery, obs
from legate_sparse_tpu.autotune import (
    CANDIDATES, Fingerprint, VerdictKey, VerdictStore,
    compute_fingerprint, key_for, platform_fingerprint,
)
from legate_sparse_tpu.ops import spmv as spmv_ops
from legate_sparse_tpu.settings import settings

from utils_test.tools import load_tool as _tool


@pytest.fixture
def at_settings():
    """Snapshot/restore the autotune switches and a fresh process
    store around each test (verdicts must not leak across tests)."""
    saved = (settings.autotune, settings.autotune_store_size,
             settings.autotune_trials, settings.autotune_warmup,
             settings.engine)
    autotune.reset()
    yield settings
    (settings.autotune, settings.autotune_store_size,
     settings.autotune_trials, settings.autotune_warmup,
     settings.engine) = saved
    autotune.reset()


# One canonical structure per (n, w, seed): tier-1 runs single-core,
# and every distinct (bin shapes, dtype) pair is a fresh XLA compile —
# sharing the structure keeps this module to a handful of compiles.
_PL_CACHE = {}


def _powerlaw(n=512, nnz_per_row=4, seed=3, dtype=np.float32):
    key = (n, nnz_per_row, seed, np.dtype(dtype).name)
    if key not in _PL_CACHE:
        A = gallery.powerlaw(n, nnz_per_row=nnz_per_row, rng=seed,
                             dtype=dtype)
        A.sum_duplicates()
        _PL_CACHE[key] = A.toscipy().tocsr()
    return lst.csr_array(_PL_CACHE[key])


def _uniform(n=512, density=0.02, seed=0, dtype=np.float32):
    A_sp = sp.random(n, n, density=density, format="csr",
                     random_state=np.random.default_rng(seed),
                     dtype=np.float64).astype(dtype)
    return lst.csr_array(A_sp)


# ---------------------------------------------------------------- #
# fingerprints
# ---------------------------------------------------------------- #

def test_fingerprint_deterministic_across_builds():
    mk = lambda: gallery.powerlaw(512, nnz_per_row=4, rng=7,
                                  dtype=np.float32)
    A1, A2 = mk(), mk()
    A1.sum_duplicates(); A2.sum_duplicates()
    f1, f2 = compute_fingerprint(A1), compute_fingerprint(A2)
    assert f1 == f2
    assert f1.klass == f2.klass


def test_fingerprint_cached_and_shared_with_data():
    A = _powerlaw()
    fp = A._get_fingerprint()
    assert fp is A._get_fingerprint()        # cached
    B = A * 2.0                               # _with_data shares it
    assert B._get_fingerprint() is fp


def test_fingerprint_class_invariant_under_row_permutation():
    """Row permutation preserves the row-length histogram, and for
    scattered-column matrices the spread/block terms are whole-array
    means over the same multiset — the class must not move."""
    A = _powerlaw()
    A_sp = A.toscipy().tocsr()
    perm = np.random.default_rng(1).permutation(A.shape[0])
    B = lst.csr_array(A_sp[perm].tocsr())
    fa, fb = compute_fingerprint(A), compute_fingerprint(B)
    assert fa.row_cv == pytest.approx(fb.row_cv, rel=1e-9)
    assert fa.klass == fb.klass


def test_fingerprint_classes_separate_structures():
    # banded: tridiagonal
    n = 512
    A_band = lst.csr_array(sp.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)],
        [-1, 0, 1], format="csr", dtype=np.float32))
    assert compute_fingerprint(A_band).klass.startswith("banded/")
    # uniform random columns, fixed row length
    assert compute_fingerprint(_uniform()).klass.startswith(
        ("uniform/", "skewed/"))
    # heavy-tailed rows
    assert compute_fingerprint(_powerlaw()).klass.startswith(
        ("powerlaw/", "skewed/"))


def test_fingerprint_empty_matrix():
    A = lst.csr_array(sp.csr_array((8, 8), dtype=np.float32))
    fp = compute_fingerprint(A)
    assert fp.klass == "empty/w1"
    assert A._get_sliced_ell() is None


def test_fingerprint_declines_inside_trace(at_settings):
    A = _powerlaw()

    captured = []

    @jax.jit
    def f(x):
        captured.append(A._get_fingerprint())
        return x

    f(jnp.zeros((4,), jnp.float32))
    assert captured == [None]
    assert A._fingerprint is None            # nothing cached under trace


# ---------------------------------------------------------------- #
# sliced-ELL kernel
# ---------------------------------------------------------------- #

@pytest.mark.parametrize("dtype", [np.float32, np.float64,
                                   np.complex64])
def test_sliced_ell_matches_csr(dtype):
    A = _powerlaw(dtype=dtype)
    bins = A._get_sliced_ell()
    assert bins is not None
    rng = np.random.default_rng(2)
    x = rng.standard_normal(A.shape[1]).astype(dtype)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        x = (x + 1j * rng.standard_normal(A.shape[1])).astype(dtype)
    y_ref = A.toscipy() @ x
    y = spmv_ops.sliced_ell_spmv(bins, jnp.asarray(x), A.shape[0])
    rtol = 1e-5 if np.dtype(dtype).itemsize <= 8 else 1e-12
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=rtol,
                               atol=rtol)


def test_sliced_ell_nonfinite_x_propagates():
    """Masked (not clamped-gather-then-multiply-by-zero) products:
    a NaN/inf in x must reach exactly the rows that store a column
    touching it, IEEE-style, like the CSR path."""
    A = _powerlaw()
    x = np.ones(A.shape[1], np.float32)
    x[17] = np.nan
    x[23] = np.inf
    y_csr = np.asarray(A @ jnp.asarray(x))
    bins = A._get_sliced_ell()
    y_sl = np.asarray(spmv_ops.sliced_ell_spmv(bins, jnp.asarray(x),
                                               A.shape[0]))
    np.testing.assert_array_equal(np.isnan(y_csr), np.isnan(y_sl))
    np.testing.assert_array_equal(np.isinf(y_csr), np.isinf(y_sl))


@pytest.mark.slow
def test_sliced_ell_padding_bound():
    """pow2 row bins bound padded slots below 2x nnz for any skew —
    the property that lets sliced-ELL skip flat ELL's budget knob."""
    for A in (_powerlaw(), _powerlaw(n=600, nnz_per_row=3, seed=0)):
        bins = A._get_sliced_ell()
        padded = sum(int(b[0].size) for b in bins)
        assert padded < 2 * A.nnz, (padded, A.nnz)


def test_sliced_ell_cache_invalidation():
    A = _powerlaw()
    assert A._get_sliced_ell() is not None
    assert A._get_fingerprint() is not None
    A._data = A._data.at[0].set(0)            # explicit zero to drop
    A.eliminate_zeros()
    assert A._sliced_ell is None and A._fingerprint is None
    assert A._get_sliced_ell() is not None    # rebuilds
    A._invalidate_caches(structure_changed=True)
    assert A._sliced_ell is None and A._fingerprint is None


# ---------------------------------------------------------------- #
# verdict store
# ---------------------------------------------------------------- #

def _key(i, epoch=None):
    return VerdictKey(op="spmv", dtype="float32", fp_class="uniform/w8",
                      rows_b=1024 * (i + 1), nnz_b=8192, k_b=1,
                      platform=platform_fingerprint(),
                      epoch=settings.epoch if epoch is None else epoch)


def test_store_lru_eviction(at_settings):
    store = VerdictStore(capacity=2)
    for i in range(3):
        store.record(_key(i), "csr-rowids")
    assert len(store) == 2
    assert store.lookup(_key(0)) is None      # oldest evicted
    assert store.lookup(_key(2)) is not None


def test_store_persistence_roundtrip(at_settings, tmp_path):
    path = str(tmp_path / "verdicts.json")
    store = VerdictStore(capacity=8, path=path)
    store.record(_key(0), "sliced-ell",
                 timings_ms={"sliced-ell": 0.5, "csr-rowids": 2.0},
                 trials=5)
    assert os.path.exists(path)
    store2 = VerdictStore(capacity=8, path=path)
    v = store2.lookup(_key(0))
    assert v is not None and v.label == "sliced-ell"
    assert v.timings_ms["csr-rowids"] == 2.0 and v.trials == 5


def test_store_load_drops_foreign_platform_and_epoch(at_settings,
                                                     tmp_path):
    path = str(tmp_path / "verdicts.json")
    VerdictStore(capacity=8, path=path).record(_key(0), "ell")
    doc = json.loads(open(path).read())
    doc["verdicts"][0]["platform"] = "tpu:fake_v9:8"
    doc["verdicts"].append(dict(doc["verdicts"][0],
                                platform=platform_fingerprint(),
                                epoch=settings.epoch + 999))
    with open(path, "w") as f:
        json.dump(doc, f)
    assert len(VerdictStore(capacity=8, path=path)) == 0


def test_key_for_buckets_and_epoch(at_settings):
    A = _uniform()
    k1 = key_for(A, "spmv")
    assert k1 is not None
    assert k1.rows_b >= 512 and k1.nnz_b >= A.nnz
    assert k1.epoch == settings.epoch
    assert k1.key_id.startswith("spmv/float32/")
    # a lowering-relevant settings mutation re-keys (old verdicts
    # stop matching without eviction)
    saved = settings.ell_max_expand
    try:
        settings.ell_max_expand = saved + 1.0
        assert key_for(A, "spmv").epoch == k1.epoch + 1
    finally:
        settings.ell_max_expand = saved


# ---------------------------------------------------------------- #
# routing: inert off, parity on, silent declines
# ---------------------------------------------------------------- #

def test_autotune_off_is_inert(at_settings):
    at_settings.autotune = False
    A = _powerlaw()
    x = jnp.ones((A.shape[1],), jnp.float32)
    _ = np.asarray(A @ x)                     # warm every compile
    c0 = obs.counters.snapshot("autotune.")
    t0 = obs.counters.snapshot("trace.")
    y = np.asarray(A @ x)
    assert obs.counters.snapshot("autotune.") == c0
    assert obs.counters.snapshot("trace.") == t0
    at_settings.autotune = True               # miss path: same result
    y_miss = np.asarray(A @ x)
    np.testing.assert_array_equal(y, y_miss)
    assert obs.counters.get("autotune.route.hits",
                            0) == c0.get("autotune.route.hits", 0)


@pytest.mark.parametrize("dtype", [np.float32, np.float64,
                                   np.complex64])
def test_routed_csr_rowids_bitwise_equals_plain(at_settings, dtype):
    A = _powerlaw(dtype=dtype)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal(A.shape[1])
        .astype(dtype))
    y_plain = np.asarray(A @ x)
    at_settings.autotune = True
    autotune.get_store().record(key_for(A, "spmv"), "csr-rowids")
    h0 = obs.counters.get("autotune.route.hits", 0)
    y_routed = np.asarray(A @ x)
    assert obs.counters.get("autotune.route.hits") == h0 + 1
    np.testing.assert_array_equal(y_routed, y_plain)


def test_routed_sliced_ell_bitwise_equals_direct_kernel(at_settings):
    A = _powerlaw()
    x = jnp.asarray(np.random.default_rng(1)
                    .standard_normal(A.shape[1]).astype(np.float32))
    y_plain = np.asarray(A @ x)
    at_settings.autotune = True
    autotune.get_store().record(key_for(A, "spmv"), "sliced-ell")
    y_routed = np.asarray(A @ x)
    y_direct = np.asarray(spmv_ops.sliced_ell_spmv(
        A._get_sliced_ell(), x, A.shape[0]))
    np.testing.assert_array_equal(y_routed, y_direct)
    np.testing.assert_allclose(y_routed, y_plain, rtol=1e-5,
                               atol=1e-5)
    assert obs.counters.get("autotune.route.sliced-ell", 0) >= 1


def test_route_declines_in_tracer_context(at_settings):
    at_settings.autotune = True
    A = _powerlaw()
    autotune.get_store().record(key_for(A, "spmv"), "sliced-ell")
    h0 = obs.counters.get("autotune.route.hits", 0)

    y = np.asarray(jax.jit(lambda v: A @ v)(
        jnp.ones((A.shape[1],), jnp.float32)))
    assert y.shape == (A.shape[0],)
    assert obs.counters.get("autotune.route.hits", 0) == h0


def test_route_declines_on_dtype_promotion(at_settings):
    at_settings.autotune = True
    A = _powerlaw()
    autotune.get_store().record(key_for(A, "spmv"), "sliced-ell")
    x64 = jnp.ones((A.shape[1],), jnp.float64)
    assert autotune.route_matvec(A, x64) is None
    y = np.asarray(A @ x64)                   # promoted heuristic path
    assert y.dtype == np.float64


def test_route_declines_on_stale_verdict(at_settings):
    """A verdict naming a kernel this matrix can't run is skipped,
    never errored (warm-started stores cross matrices)."""
    at_settings.autotune = True
    A = _powerlaw()
    A._sliced_ell = False                     # pack "not viable"
    autotune.get_store().record(key_for(A, "spmv"), "sliced-ell")
    d0 = obs.counters.get("autotune.route.decline", 0)
    y = np.asarray(A @ jnp.ones((A.shape[1],), jnp.float32))
    assert y.shape == (A.shape[0],)
    assert obs.counters.get("autotune.route.decline") == d0 + 1


def test_route_spmm(at_settings):
    at_settings.autotune = True
    A = _uniform()
    X = jnp.asarray(np.random.default_rng(3)
                    .standard_normal((512, 4)).astype(np.float32))
    Y_plain = np.asarray(A @ X)
    autotune.get_store().record(key_for(A, "spmm", k=4), "csr-rowids")
    Y_routed = np.asarray(A @ X)
    # Parity contract: routed == a direct dispatch of the verdict's
    # kernel (bitwise); the plain chain may serve this matrix via a
    # different kernel (flat ELL here), so only allclose vs plain.
    Y_direct = np.asarray(CANDIDATES["csr-rowids"].run(A, X, "spmm"))
    np.testing.assert_array_equal(Y_routed, Y_direct)
    np.testing.assert_allclose(Y_routed, Y_plain, rtol=1e-5,
                               atol=1e-5)


# ---------------------------------------------------------------- #
# engine interplay
# ---------------------------------------------------------------- #

def test_engine_defers_to_non_csr_verdict(at_settings):
    at_settings.autotune = True
    at_settings.engine = True
    A = _powerlaw()
    x = jnp.ones((A.shape[1],), jnp.float32)
    autotune.get_store().record(key_for(A, "spmv"), "sliced-ell")
    d0 = obs.counters.get("autotune.engine.defer", 0)
    h0 = obs.counters.get("autotune.route.hits", 0)
    y = np.asarray(A @ x)
    assert obs.counters.get("autotune.engine.defer") == d0 + 1
    assert obs.counters.get("autotune.route.hits") == h0 + 1
    y_direct = np.asarray(spmv_ops.sliced_ell_spmv(
        A._get_sliced_ell(), x, A.shape[0]))
    np.testing.assert_array_equal(y, y_direct)


def test_engine_keeps_csr_rowids_verdict(at_settings):
    """A csr-rowids verdict must NOT kick the matrix off the engine:
    bucketed plans serve the same kernel family."""
    at_settings.autotune = True
    at_settings.engine = True
    A = _uniform()
    autotune.get_store().record(key_for(A, "spmv"), "csr-rowids")
    d0 = obs.counters.get("autotune.engine.defer", 0)
    e0 = obs.counters.get("engine.plan.misses", 0) + \
        obs.counters.get("engine.plan.hits", 0)
    _ = np.asarray(A @ jnp.ones((A.shape[1],), jnp.float32))
    assert obs.counters.get("autotune.engine.defer", 0) == d0
    assert (obs.counters.get("engine.plan.misses", 0)
            + obs.counters.get("engine.plan.hits", 0)) > e0


# ---------------------------------------------------------------- #
# harness / tune
# ---------------------------------------------------------------- #

def test_measure_candidates_times_eligible(at_settings):
    A = _powerlaw()
    timings = autotune.measure_candidates(A, warmup=0, trials=1)
    assert "csr-rowids" in timings and "sliced-ell" in timings
    assert all(ms > 0 for ms in timings.values())
    for label in timings:
        assert label in CANDIDATES


def test_tune_records_winner_and_routes(at_settings):
    at_settings.autotune = True
    A = _powerlaw()
    x = jnp.ones((A.shape[1],), jnp.float32)
    verdict = autotune.tune(A, x, warmup=0, trials=1)
    assert verdict is not None
    assert verdict.label in verdict.timings_ms
    assert autotune.get_store().lookup(key_for(A, "spmv")) is verdict
    h0 = obs.counters.get("autotune.route.hits", 0)
    _ = np.asarray(A @ x)
    assert obs.counters.get("autotune.route.hits") == h0 + 1


# ---------------------------------------------------------------- #
# gallery generators
# ---------------------------------------------------------------- #

def test_gallery_powerlaw_deterministic_and_skewed():
    A = gallery.powerlaw(2048, nnz_per_row=4, rng=7)
    B = gallery.powerlaw(2048, nnz_per_row=4, rng=7)
    assert A.shape == (2048, 2048)
    assert np.array_equal(np.asarray(A.indices), np.asarray(B.indices))
    assert np.array_equal(np.asarray(A.indptr), np.asarray(B.indptr))
    counts = np.diff(np.asarray(A.indptr))
    assert counts.max() >= 8 * counts.mean()  # heavy tail present


def test_gallery_rmat_deterministic_and_valid():
    G = gallery.rmat(10, nnz_per_row=4, rng=13)
    G2 = gallery.rmat(10, nnz_per_row=4, rng=13)
    assert G.shape == (1024, 1024)
    assert np.array_equal(np.asarray(G.indices), np.asarray(G2.indices))
    idx = np.asarray(G.indices)
    assert idx.min() >= 0 and idx.max() < 1024
    with pytest.raises(ValueError):
        gallery.rmat(4, a=0.6, b=0.3, c=0.2)  # probs sum > 1


def test_gallery_directed_flag_symmetrizes():
    # directed=False stores both orientations of every sampled edge
    # with the same value -> structurally and numerically symmetric.
    for A in (gallery.rmat(7, nnz_per_row=4, rng=3, directed=False),
              gallery.powerlaw(256, nnz_per_row=4, rng=3,
                               directed=False)):
        D = np.asarray(A.todense())
        # allclose, not equal: duplicate sampled edges sum in a
        # different order on the two orientations (reassociation).
        np.testing.assert_allclose(D, D.T, rtol=1e-12)
    # directed=True (the default) keeps the historical structure.
    A1 = gallery.powerlaw(256, nnz_per_row=4, rng=9)
    A2 = gallery.powerlaw(256, nnz_per_row=4, rng=9, directed=True)
    assert np.array_equal(np.asarray(A1.indices),
                          np.asarray(A2.indices))
    with pytest.raises(ValueError):
        gallery.powerlaw(8, 6, directed=False)  # rectangular


# ---------------------------------------------------------------- #
# static gate
# ---------------------------------------------------------------- #

def test_kernel_registry_gate_passes(capsys):
    rc = _tool("check_kernel_registry").main([])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "check_kernel_registry: OK" in out.out


def test_kernel_registry_gate_lists(capsys):
    rc = _tool("check_kernel_registry").main(["--list"])
    out = capsys.readouterr().out
    assert rc == 0
    for label in CANDIDATES:
        assert label in out
