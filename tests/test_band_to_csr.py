# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""band_to_csr three-segment extraction vs scipy dia->csr.

The interior-slice fast path (r5 perf work: static slices + reshape for
rows where every offset is in range, ragged gathers only for the edge
rows) must agree with scipy's own DIA->CSR conversion on every shape
class: square, tall, wide, band wider than the matrix (no interior),
one-sided bands, single diagonal, and tiny matrices.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from legate_sparse_tpu.ops import dia_ops as dio


def _check(offsets, shape, seed=0):
    rows, cols = shape
    offsets = tuple(sorted(offsets))
    rng = np.random.default_rng(seed)
    width = cols
    dia_data = rng.uniform(0.5, 2.0, (len(offsets), width)).astype(
        np.float32)
    nnz = dio.band_cover(offsets, shape, cols)
    vals, col, indptr = dio.band_to_csr(
        jnp.asarray(dia_data), offsets, shape, nnz)
    got = sp.csr_matrix(
        (np.asarray(vals), np.asarray(col), np.asarray(indptr)),
        shape=shape)
    want = sp.dia_matrix((dia_data, offsets), shape=shape).tocsr()
    # band_to_csr keeps explicit zeros; the random values are nonzero,
    # so the structures must agree exactly.
    assert got.nnz == nnz
    np.testing.assert_array_equal(got.indptr, want.indptr)
    np.testing.assert_array_equal(got.indices, want.indices)
    np.testing.assert_allclose(got.toarray(), want.toarray(), rtol=1e-6)


@pytest.mark.parametrize("offsets,shape", [
    ((-2, -1, 0, 1, 2), (64, 64)),        # square, symmetric band
    ((-1, 0, 1), (100, 40)),              # tall
    ((-1, 0, 1), (40, 100)),              # wide
    ((-70, 0, 70), (64, 64)),             # band wider than matrix
    ((1, 2, 3), (32, 32)),                # strictly upper
    ((-3, -2, -1), (32, 32)),             # strictly lower
    ((0,), (17, 17)),                     # single main diagonal
    ((-1, 1), (2, 2)),                    # tiny, no main diagonal
    ((0, 5), (6, 6)),                     # offset reaching the corner
    ((-2, 0, 1), (3, 9)),                 # interior spans whole width
])
def test_band_to_csr_matches_scipy(offsets, shape):
    _check(offsets, shape)


def test_band_to_csr_interior_only():
    # Wide matrix where EVERY row is interior (no edge segments).
    _check((0, 1, 2), (8, 64))


def test_band_to_csr_keeps_explicit_zeros():
    offsets = (-1, 0, 1)
    shape = (16, 16)
    dia_data = np.zeros((3, 16), np.float32)   # all-zero band
    nnz = dio.band_cover(offsets, shape, 16)
    vals, col, indptr = dio.band_to_csr(
        jnp.asarray(dia_data), offsets, shape, nnz)
    assert int(np.asarray(indptr)[-1]) == nnz  # zeros kept explicitly
    assert np.asarray(vals).shape[0] == nnz
