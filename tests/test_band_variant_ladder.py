# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""bench.py band-variant survival ladder: selection logic.

The ladder is the round's fault-containment machine (r3: the Pallas
kernel faulted the TPU worker only in the looped composition); these
tests pin its decision table with a mocked canary so the on-chip
behavior is the only untested part.
"""

import importlib
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture()
def bench_mod(monkeypatch, tmp_path):
    import bench

    importlib.reload(bench)
    # Keep variant persistence inside the sandbox.
    monkeypatch.chdir(tmp_path)
    for var in ("LEGATE_SPARSE_TPU_PALLAS_ROLL",
                "LEGATE_SPARSE_TPU_PALLAS_INPUTS",
                "LEGATE_SPARSE_TPU_PALLAS_DIA"):
        monkeypatch.delenv(var, raising=False)
    # _select_band_variant writes the chosen variant straight into
    # os.environ (its job); monkeypatch does not track those writes,
    # so snapshot and restore the whole environment — a leaked
    # PALLAS_DIA=0 would silently disable the band path for every
    # later test in the session.
    snapshot = dict(os.environ)
    yield bench
    os.environ.clear()
    os.environ.update(snapshot)


def _mock(bench, monkeypatch, verdicts, alive=True):
    calls = []

    def fake_canary(log2n, timeout_s=480, env_extra=None):
        name = {(): "pallas",
                (("LEGATE_SPARSE_TPU_PALLAS_INPUTS", "distinct"),):
                    "pallas-shift3",
                (("LEGATE_SPARSE_TPU_PALLAS_ROLL", "xla"),):
                    "pallas-jroll"}[
            tuple(sorted((env_extra or {}).items()))]
        calls.append(name)
        return verdicts.get(name, "crash")

    monkeypatch.setattr(bench, "_pallas_canary", fake_canary)
    monkeypatch.setattr(bench, "_probe_accelerator", lambda: alive)
    return calls


def test_first_rung_survives(bench_mod, monkeypatch):
    calls = _mock(bench_mod, monkeypatch, {"pallas": "ok"})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas:ok"] and alive
    assert calls == ["pallas"]
    assert "LEGATE_SPARSE_TPU_PALLAS_DIA" not in os.environ
    # Survivor persisted for the later capture phases.
    env = open("evidence/band_variant.env").read()
    assert "pallas" in env


def test_falls_through_to_shift3(bench_mod, monkeypatch):
    calls = _mock(bench_mod, monkeypatch,
                  {"pallas": "crash", "pallas-shift3": "ok"})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas:crash", "pallas-shift3:ok"] and alive
    assert os.environ.get("LEGATE_SPARSE_TPU_PALLAS_INPUTS") == "distinct"
    assert "distinct" in open("evidence/band_variant.env").read()


def test_all_rungs_fail_lands_on_xla(bench_mod, monkeypatch):
    _mock(bench_mod, monkeypatch, {})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert [a.split(":")[0] for a in attempts] == [
        "pallas", "pallas-shift3", "pallas-jroll"]
    assert alive
    assert os.environ.get("LEGATE_SPARSE_TPU_PALLAS_DIA") == "0"
    assert "PALLAS_DIA=0" in open("evidence/band_variant.env").read()


def test_dead_worker_stops_ladder(bench_mod, monkeypatch):
    calls = _mock(bench_mod, monkeypatch, {"pallas": "crash"},
                  alive=False)
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas:crash"] and not alive
    assert calls == ["pallas"]      # no rung probed on a dead worker
    assert os.environ.get("LEGATE_SPARSE_TPU_PALLAS_DIA") == "0"


def test_operator_roll_pin_restricts_ladder(bench_mod, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_ROLL", "xla")
    calls = _mock(bench_mod, monkeypatch, {"pallas-jroll": "ok"})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas-jroll:ok"] and alive
    assert calls == ["pallas-jroll"]
    # The pin itself is never overridden.
    assert os.environ["LEGATE_SPARSE_TPU_PALLAS_ROLL"] == "xla"


def test_operator_tpu_pin_probes_only_mosaic_rung(bench_mod, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_ROLL", "tpu")
    calls = _mock(bench_mod, monkeypatch, {"pallas": "crash"})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert calls == ["pallas"]      # no jroll rung under a tpu pin
    assert os.environ["LEGATE_SPARSE_TPU_PALLAS_ROLL"] == "tpu"
    assert os.environ.get("LEGATE_SPARSE_TPU_PALLAS_DIA") == "0"


def test_inputs_pin_starts_ladder_at_shift3(bench_mod, monkeypatch):
    # With INPUTS pinned in the environment the canary subprocess would
    # probe the de-aliased variant anyway; the ladder must start there
    # and label it honestly (ADVICE r4).
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_INPUTS", "distinct")
    calls = _mock(bench_mod, monkeypatch, {"pallas-shift3": "ok"})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas-shift3:ok"] and alive
    assert calls == ["pallas-shift3"]
    assert "distinct" in open("evidence/band_variant.env").read()


def test_roll_and_inputs_pins_label_shift3(bench_mod, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_ROLL", "tpu")
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_INPUTS", "distinct")
    calls = _mock(bench_mod, monkeypatch, {"pallas-shift3": "ok"})
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas-shift3:ok"] and alive
    assert calls == ["pallas-shift3"]


def test_trace_error_skips_recovery_probe(bench_mod, monkeypatch):
    # A Python-level canary bug is not a worker fault: the ladder keeps
    # going without the recovery probe (which would otherwise pin CPU).
    probes = []

    def fake_probe():
        probes.append(1)
        return True

    calls = _mock(bench_mod, monkeypatch,
                  {"pallas": "trace-error", "pallas-shift3": "ok"})
    monkeypatch.setattr(bench_mod, "_probe_accelerator", fake_probe)
    attempts, alive = bench_mod._select_band_variant(24, 480)
    assert attempts == ["pallas:trace-error", "pallas-shift3:ok"]
    assert alive and calls == ["pallas", "pallas-shift3"]
    assert probes == []             # no recovery probe for a trace error


def test_canary_wrapper_distinguishes_trace_error(bench_mod):
    # End-to-end through the real subprocess wrapper: a Python-level
    # raise inside the canary code yields "trace-error", not "crash".
    real_code = bench_mod._CANARY_CODE
    try:
        bench_mod._CANARY_CODE = "import sys\nraise ValueError('boom')\n"
        verdict = bench_mod._pallas_canary(4, timeout_s=120)
        assert verdict == "trace-error"
        bench_mod._CANARY_CODE = "print('canary-ok')\n"
        assert bench_mod._pallas_canary(4, timeout_s=120) == "ok"
        bench_mod._CANARY_CODE = "import sys\nsys.exit(1)\n"
        assert bench_mod._pallas_canary(4, timeout_s=120) == "crash"
        # jax 0.9's device-fault class must be scored as a crash, not a
        # trace error (code-review r5: the classifier must match
        # JaxRuntimeError, not just the legacy XlaRuntimeError name).
        bench_mod._CANARY_CODE = (
            "from jax.errors import JaxRuntimeError\n"
            "raise JaxRuntimeError('TPU worker process crashed')\n")
        assert bench_mod._pallas_canary(4, timeout_s=120) == "crash"
    finally:
        bench_mod._CANARY_CODE = real_code
