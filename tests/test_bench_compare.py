# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Bench-trajectory regression gate (obs/regress.py +
tools/bench_compare.py): noise bands from the recorded stream spread,
nonzero exit on synthetic regressions, a clean pass on the real
archived round pair, and the trajectory table."""

import json
import os

import pytest

from legate_sparse_tpu.obs import regress
from utils_test.tools import load_tool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool():
    return load_tool("bench_compare")


def _base(**over):
    d = {
        "metric": "csr_spmv_bandwidth",
        "platform": "cpu",
        "schema_version": 7,
        "stream_samples": [50.0, 52.0, 51.0],
        "stream_gbs": 51.0,
        "spmv_ms": 2.0,
        "cg_ms_per_iter": 0.1,
        "pde_roofline_ratio": 0.8,
        "dist_spmv_comm_bytes": 320,
        "bench_wall_s": 100.0,
    }
    d.update(over)
    return d


# ---------------------------------------------------------------- loads --
def test_load_bench_shapes(tmp_path):
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(_base()))
    assert regress.load_bench(str(raw))["spmv_ms"] == 2.0

    wrapper = tmp_path / "wrap.json"
    wrapper.write_text(json.dumps({"n": 6, "rc": 0,
                                   "parsed": _base(spmv_ms=3.0)}))
    assert regress.load_bench(str(wrapper))["spmv_ms"] == 3.0

    log = tmp_path / "log.txt"
    log.write_text("noise line\n" + json.dumps(_base(spmv_ms=4.0))
                   + "\n")
    assert regress.load_bench(str(log))["spmv_ms"] == 4.0

    empty = tmp_path / "empty.json"
    empty.write_text("no json here")
    with pytest.raises(ValueError):
        regress.load_bench(str(empty))


# ---------------------------------------------------------- noise bands --
def test_stream_spread_and_band():
    tight = _base()
    assert regress.stream_spread(tight) == pytest.approx(2 / 51)
    legacy = {"stream_gbs": 66.34, "stream2_gbs": 28.91}
    # Pre-r6 artifacts: the two-sample pair, even-median averaged.
    assert regress.stream_spread(legacy) == pytest.approx(
        (66.34 - 28.91) / ((66.34 + 28.91) / 2))
    assert regress.stream_spread({"spmv_ms": 1}) is None
    # Band is the worst spread of the pair, floored.
    assert regress.noise_band(tight, tight, floor=0.25) == 0.25
    wild = _base(stream_samples=[30.0, 60.0, 45.0])
    assert regress.noise_band(tight, wild, floor=0.1) == pytest.approx(
        30 / 45)


# -------------------------------------------------------------- compare --
def test_in_band_wobble_passes_and_out_of_band_fails():
    old = _base()
    ok = regress.compare(old, _base(spmv_ms=2.5))     # 1.25x < 1.75x
    assert not regress.regressions(ok)
    bad = regress.compare(old, _base(spmv_ms=20.0))   # 10x
    (r,) = regress.regressions(bad)
    assert r["field"] == "spmv_ms" and r["status"] == "regressed"


def test_roofline_ratio_direction_is_inverted():
    old = _base()
    bad = regress.compare(old, _base(pde_roofline_ratio=0.2))  # 4x worse
    assert any(f["field"] == "pde_roofline_ratio"
               and f["status"] == "regressed"
               for f in bad)
    ok = regress.compare(old, _base(pde_roofline_ratio=0.95))
    assert not regress.regressions(ok)


def test_comm_bytes_are_gated_strictly():
    old = _base()
    # +50% comm bytes is a code change, not machine noise: fails even
    # though the timing band would forgive it.
    bad = regress.compare(old, _base(dist_spmv_comm_bytes=480))
    (r,) = regress.regressions(bad)
    assert r["field"] == "dist_spmv_comm_bytes"
    # Fewer bytes is an improvement.
    ok = regress.compare(old, _base(dist_spmv_comm_bytes=160))
    assert not regress.regressions(ok)
    assert any(f["status"] == "improved" for f in ok)


def test_comm_gate_skipped_across_platform_or_mesh_transitions():
    """A CPU-fallback round vs a live multi-chip round runs a
    different collective program: comm fields must be reported
    incomparable, not regressed, in either direction."""
    old = _base(dist_shards=1, dist_spmv_comm_bytes=0)
    new = _base(platform="tpu", dist_shards=8,
                dist_spmv_comm_bytes=81920)
    findings = regress.compare(old, new)
    assert not regress.regressions(findings)
    (f,) = [x for x in findings if x["field"] == "dist_spmv_comm_bytes"]
    assert f["status"] == "incomparable"
    # Same mesh+platform: the strict gate applies again.
    same = regress.compare(_base(dist_shards=8),
                           _base(dist_shards=8,
                                 dist_spmv_comm_bytes=480))
    assert regress.regressions(same)


def test_missing_gated_field_breaks_superset_contract():
    old = _base()
    new = _base()
    del new["cg_ms_per_iter"]
    bad = regress.compare(old, new)
    (r,) = regress.regressions(bad)
    assert r["field"] == "cg_ms_per_iter" and r["status"] == "missing"
    ok = regress.compare(old, new, allow_missing=True)
    assert not regress.regressions(ok)


def test_fields_filter_restricts_the_gate():
    old = _base()
    new = _base(spmv_ms=50.0)               # would regress unfiltered
    findings = regress.compare(old, new,
                               fields=["*_comm_bytes",
                                       "schema_version"])
    assert not regress.regressions(findings)
    names = {f["field"] for f in findings}
    assert "spmv_ms" not in names
    assert "schema_version" in names        # exact-match gated
    bad = regress.compare(old, _base(schema_version=8),
                          fields=["schema_version"])
    assert regress.regressions(bad)


# ------------------------------------------------------- real artifacts --
def test_real_archived_pair_passes_with_noise_band():
    old = regress.load_bench(os.path.join(REPO, "BENCH_r04.json"))
    new = regress.load_bench(os.path.join(REPO, "BENCH_r05.json"))
    findings = regress.compare(old, new)
    assert not regress.regressions(findings), regress.render_findings(
        findings)


def test_real_artifact_synthetically_regressed_fails():
    old = regress.load_bench(os.path.join(REPO, "BENCH_r05.json"))
    new = dict(old)
    new["spmv_ms"] = old["spmv_ms"] * 10
    assert regress.regressions(regress.compare(old, new))


# ----------------------------------------------------------------- tool --
def test_cli_pair_and_exit_codes(tmp_path, capsys):
    mod = _tool()
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_base()))
    b.write_text(json.dumps(_base(spmv_ms=2.1)))
    assert mod.main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "clean" in out and "spmv_ms" in out

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_base(spmv_ms=40.0)))
    assert mod.main([str(a), str(bad)]) == 1
    assert mod.main([str(a), str(tmp_path / "nope.json")]) == 2
    assert mod.main([]) == 2


def test_cli_trajectory_renders_and_gates(tmp_path, capsys):
    mod = _tool()
    for i, ms in enumerate([4.0, 3.0, 2.5], start=1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps(_base(spmv_ms=ms)))
    assert mod.main(["--trajectory", "--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "r01" in out and "r03" in out and "spmv_ms" in out
    # Newest round regresses -> trajectory gate fails.
    (tmp_path / "BENCH_r04.json").write_text(
        json.dumps(_base(spmv_ms=30.0)))
    assert mod.main(["--trajectory", "--dir", str(tmp_path)]) == 1


def test_repo_trajectory_gate_is_clean(capsys):
    """The committed BENCH_r0*.json trajectory must gate clean — this
    is the standing CI guard the tentpole exists for."""
    mod = _tool()
    rc = mod.main(["--trajectory", "--dir", REPO])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
