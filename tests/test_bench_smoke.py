# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Tier-1 bench smoke lane: ``bench.py --smoke`` under
``LEGATE_SPARSE_TPU_OBS=1`` must produce a non-empty trace artifact
with nonzero ``comm.*`` counters from the dist phase, a schema-
versioned JSON line whose deterministic fields match the committed
golden through ``tools/bench_compare.py``, and the gate must fire on a
synthetically regressed copy.  This is the CI teeth of the obs v2
tentpole: the wiring can no longer silently no-op between capture
rounds."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "evidence", "BENCH_golden_smoke.json")

# Deterministic fields only: timings vary per machine, but the static
# comm predictions, the mesh width, the schema — the engine phase's
# plan-cache hit/miss counts (a fixed call sequence against a fresh
# engine) — the resilience drill's exact fault/retry/shed/trip
# accounting, the saturation sweep's totals (fixed request plan;
# every request batches exactly once; one deterministic shed drill),
# the autotune phase's verdict count (one pinned verdict against a
# fresh store), the gateway fairness sweep's admission/packing/
# rejection totals (fixed submission sequence, flush-only dispatch),
# and the mutation phase's exact delta accounting (a seeded
# ``gallery.mutation_stream`` against a fixed matrix) do not.
GOLDEN_FIELDS = ("*_comm_bytes,dist_shards,dist2d_cg_iters,"
                 "schema_version,"
                 "spmv_bytes_per_nnz,spmv_bytes_per_nnz_bf16,"
                 "engine_plan_hits,engine_plan_misses,"
                 "engine_batch_requests,"
                 "resil_retries,resil_shed,resil_breaker_trips,"
                 "resil_faults_injected,"
                 "resil_ckpt_saves,resil_recoveries,resil_restored,"
                 "resil_reshard_bytes,"
                 "saturation_requests,saturation_shed,"
                 "saturation_batched_requests,autotune_verdicts,"
                 "gateway_requests,gateway_dispatches,gateway_packed,"
                 "gateway_rejected_queue_full,"
                 "gateway_interactive_served,gateway_interactive_shed,"
                 "gateway_batch_served,gateway_background_served,"
                 "gateway_background_shed,"
                 "graph_n,graph_nnz,graph_bfs_iters,graph_sssp_iters,"
                 "graph_cc_iters,graph_pagerank_iters,"
                 "attrib_requests,attrib_packed,attrib_tenants,"
                 "attrib_conserved,"
                 "placement_migrations,placement_routes,"
                 "placement_reshard_bytes,"
                 "placement_noisy_served,placement_quiet_served,"
                 "mutation_updates,mutation_applied,mutation_merged,"
                 "mutation_compactions,mutation_version_swaps,"
                 "mutation_served,mutation_routes")


from utils_test.tools import load_tool as _tool


@pytest.fixture(scope="module")
def smoke_run(tmp_path_factory):
    """One shared ``bench.py --smoke`` subprocess for every assertion
    below (the run costs ~10 s; the checks are free)."""
    tmp = tmp_path_factory.mktemp("bench_smoke")
    trace_path = tmp / "smoke.trace.json"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "LEGATE_SPARSE_TPU_OBS": "1",
        "LEGATE_SPARSE_TPU_OBS_FILE": str(trace_path),
    })
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=480,
    )
    assert r.returncode == 0, (r.stdout or "") + (r.stderr or "")[-2000:]
    line = next(ln for ln in reversed(r.stdout.strip().splitlines())
                if ln.startswith("{"))
    return json.loads(line), trace_path, tmp


def test_smoke_emits_versioned_result_with_dist_comm(smoke_run):
    result, _, _ = smoke_run
    assert result["schema_version"] >= 7
    assert result["smoke"] is True
    assert result["platform"] == "cpu"
    assert result["dist_shards"] == 8
    assert result["dist_spmv_comm_bytes"] > 0
    assert result["dist_cg_comm_bytes"] > result["dist_spmv_comm_bytes"]
    assert result["comm_total_bytes"] > 0
    assert result["mem_peak_rss_mb"] > 0
    assert result["trace_spans"] > 0


def test_smoke_trace_artifact_has_comm_counters_and_mem_events(
        smoke_run):
    result, trace_path, _ = smoke_run
    assert os.path.exists(trace_path)
    assert os.path.getsize(trace_path) > 0
    doc = json.loads(trace_path.read_text())
    assert doc["traceEvents"], "empty trace artifact"
    ctrs = doc["otherData"]["counters"]
    comm = {k: v for k, v in ctrs.items() if k.startswith("comm.")}
    assert comm, "no comm.* counters in the trace"
    assert any(k.startswith("comm.dist_") and k.endswith("_bytes")
               and v > 0 for k, v in comm.items()), comm
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "bench.dist" in names
    assert any(n.startswith("mem.") for n in names), sorted(names)


def test_smoke_matches_committed_golden(smoke_run, capsys):
    result, _, tmp = smoke_run
    assert os.path.exists(GOLDEN), "golden smoke artifact not committed"
    new = tmp / "smoke.json"
    new.write_text(json.dumps(result))
    rc = _tool("bench_compare").main(
        [GOLDEN, str(new), "--fields", GOLDEN_FIELDS])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_gate_fires_on_synthetic_comm_regression(smoke_run, capsys):
    result, _, tmp = smoke_run
    bad = dict(result)
    bad["dist_spmv_comm_bytes"] = result["dist_spmv_comm_bytes"] * 2
    bad_path = tmp / "regressed.json"
    bad_path.write_text(json.dumps(bad))
    rc = _tool("bench_compare").main(
        [GOLDEN, str(bad_path), "--fields", GOLDEN_FIELDS])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "dist_spmv_comm_bytes" in out.out + out.err


def test_trace_summary_comm_table_renders(smoke_run, capsys):
    _, trace_path, _ = smoke_run
    rc = _tool("trace_summary").main([str(trace_path), "--comm"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "comm ledger:" in out
    assert "dist_spmv" in out and "ppermute" in out


def test_smoke_dist2d_phase_numbers(smoke_run):
    """ISSUE 10 acceptance: on the 8-virtual-device mesh the recorded
    2-D SpMV and windowed-SpGEMM bytes beat the recorded 1-D bytes for
    a non-banded matrix at equal device count, the auto router chose
    2d-block, and the fixed-iteration CG volume is deterministic."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 13
    assert result["dist2d_layout"] == "2d-block"
    assert result["dist2d_grid"] == "2x4"
    assert 0 < result["dist2d_spmv_comm_bytes"] < \
        result["dist2d_spmv_1d_comm_bytes"]
    assert 0 < result["dist2d_spgemm_comm_bytes"] < \
        result["dist2d_spgemm_1d_comm_bytes"]
    assert result["dist2d_cg_iters"] == 8
    assert result["dist2d_cg_comm_bytes"] > \
        result["dist2d_spmv_comm_bytes"]


def test_smoke_trace_has_dist2d_evidence(smoke_run):
    """The trace artifact carries the routing decision (citing both
    predictions), the 2-d SpGEMM realization event, and the by-layout
    comm aggregates."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "bench.dist2d" in names
    routing = [ev for ev in doc["traceEvents"]
               if ev["name"] == "shard_csr.routing"]
    assert routing, sorted(names)
    at = routing[-1].get("args") or {}
    assert at.get("layout") == "2d-block"
    assert 0 < at["predicted_2d_bytes"] < at["predicted_1d_bytes"]
    ctrs = doc["otherData"]["counters"]
    assert ctrs.get("comm.layout.2d-block.dist_spmv_bytes", 0) > 0
    assert ctrs.get("comm.layout.2d-block.dist_spgemm_bytes", 0) > 0
    assert ctrs.get("comm.layout.1d-row.dist_spmv_bytes", 0) > 0


def test_smoke_engine_phase_numbers(smoke_run):
    """ISSUE 4 acceptance: cold/warm/batched engine numbers recorded,
    warm >= 2x faster than cold on the CPU lane (cold carries the plan
    compile; warm is the cached-executable hit path), and the
    deterministic plan-cache ledger for the fixed phase sequence:
    1 spmv miss (cold) + 1 spmm miss (stacked batch), 6 hits (1 pack
    warm + 5 timed)."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 8
    assert result["engine_cold_ms"] > 0
    assert result["engine_warm_ms"] > 0
    assert result["engine_cold_ms"] >= 2 * result["engine_warm_ms"], (
        result["engine_cold_ms"], result["engine_warm_ms"])
    assert result["engine_batched_ms_per_req"] > 0
    assert result["engine_batch_requests"] == 8
    assert result["engine_plan_misses"] == 2
    assert result["engine_plan_hits"] == 6


def test_smoke_resil_phase_numbers(smoke_run):
    """ISSUE 5 acceptance: the smoke lane runs the deterministic
    resilience drill — exactly 2 retries (fail-twice-then-recover on
    csr.dot), 1 breaker trip (K=3 consecutive failures), 1 shed
    request (expired-deadline submit), 5 injected faults (2 + 3) —
    and records the recovered-vs-clean latency pair."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 9
    assert result["resil_retries"] == 2
    assert result["resil_breaker_trips"] == 1
    assert result["resil_shed"] == 1
    assert result["resil_faults_injected"] == 5
    assert result["resil_clean_ms"] > 0
    assert result["resil_recovered_ms"] > 0


def test_smoke_trace_has_resil_ledger(smoke_run, capsys):
    """The trace artifact carries the resil.* counters and
    ``trace_summary --resil`` renders the per-site ledger."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    assert ctrs.get("resil.retry.csr.dot", 0) == 2
    assert ctrs.get("resil.breaker.csr.dot.trips", 0) == 1
    # Process total: 1 from the resil drill + 1 from the saturation
    # phase's deadline-shed drill (each phase's own delta stays 1).
    assert ctrs.get("resil.shed", 0) == 2
    rc = _tool("trace_summary").main([str(trace_path), "--resil"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "resilience ledger:" in out
    assert "csr.dot" in out
    assert "shedding: 2 requests shed" in out


def test_smoke_recovery_phase_numbers(smoke_run):
    """ISSUE 15 acceptance: the smoke lane runs the seeded device-loss
    recovery drill mid-``dist_cg`` — with conv fetches and checkpoints
    every 10 iterations and the loss firing at the third fetch (it=30),
    the ladder shrinks the mesh to the 7 survivors, reshards, restores
    the it=20 snapshot and resumes: 4 checkpoint saves (two pre-loss +
    two post-restore), exactly 1 recovery restoring 20 iterations, and
    the deterministic survivor-repartition byte count — all
    golden-pinned.  Timings are informational."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 16
    assert result["resil_ckpt_saves"] == 4
    assert result["resil_recoveries"] == 1
    assert result["resil_restored"] == 20
    assert result["resil_reshard_bytes"] > 0
    assert result["recovery_clean_ms"] > 0
    assert result["recovery_recovered_ms"] > 0


def test_smoke_trace_has_recovery_ledger(smoke_run, capsys):
    """The trace artifact carries the resil.ckpt.* / resil.recovery.*
    counters from the recovery drill and ``trace_summary --resil``
    renders the checkpoint and recovery summary rows."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    # Process-cumulative: the phase's compile run and clean timing run
    # each snapshot 4 times (fetches at 10/20/30/40) before the
    # faulted run adds its 4 (two pre-loss + two post-restore) — the
    # JSON field pins the faulted-run delta, the trace the total.
    assert ctrs.get("resil.ckpt.saves", 0) == 12
    assert ctrs.get("resil.ckpt.restores", 0) == 1
    assert ctrs.get("resil.recovery.attempts", 0) == 1
    assert ctrs.get("resil.recovery.mesh_shrink", 0) == 1
    assert ctrs.get("resil.recovery.restored_iters", 0) == 20
    assert ctrs.get("resil.recovery.reshard_bytes", 0) > 0
    rc = _tool("trace_summary").main([str(trace_path), "--resil"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "checkpoints: 12 saved" in out
    assert "recoveries: 1 device losses" in out
    assert "20 iterations restored" in out


def test_smoke_graph_phase_numbers(smoke_run):
    """ISSUE 16 acceptance: the smoke lane runs the four semiring
    algorithms on one seeded R-MAT matrix (scale 9, 4 edges/row,
    rng 1234) over the 8-device mesh.  Structure is deterministic, so
    the sweep counts are exact: BFS drains its frontier in 3 or-and
    sweeps, Bellman-Ford reaches its fixed point in 6 min-plus
    relaxations, min-label CC converges in 3 sweeps over the
    symmetrized structure, and PageRank with ``tol=0`` runs exactly
    its 20-iteration budget.  Per-algorithm comm bytes ride the
    ``*_comm_bytes`` golden band; the timing stays informational."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 17
    assert result["graph_n"] == 512
    assert result["graph_nnz"] == 2048
    assert result["graph_bfs_iters"] == 3
    assert result["graph_sssp_iters"] == 6
    assert result["graph_cc_iters"] == 3
    assert result["graph_pagerank_iters"] == 20
    for alg in ("bfs", "sssp", "cc", "pagerank"):
        assert result[f"graph_{alg}_comm_bytes"] > 0, alg
    # Bool frontiers move 1-byte blocks; float distances 4-byte — the
    # or-and sweep must be the cheapest per-iteration mover.
    assert (result["graph_bfs_comm_bytes"] / result["graph_bfs_iters"]
            < result["graph_sssp_comm_bytes"]
            / result["graph_sssp_iters"])
    assert result["graph_ms"] > 0


def test_smoke_trace_has_graph_ledger(smoke_run, capsys):
    """The trace artifact carries the graph.* counters (per-algorithm
    runs/iters plus the per-semiring dist dispatch rows) and
    ``trace_summary --graph`` renders the ledger."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    for alg in ("bfs", "sssp", "cc", "pagerank"):
        assert ctrs.get(f"graph.{alg}.runs", 0) >= 1, alg
        assert ctrs.get(f"graph.{alg}.iters", 0) >= 1, alg
    assert ctrs.get("graph.dist_spmv.or-and", 0) >= 1
    assert ctrs.get("graph.dist_spmv.min-plus", 0) >= 1
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "bench.graph" in names
    assert "graph.pagerank" in names
    rc = _tool("trace_summary").main([str(trace_path), "--graph"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "graph ledger:" in out
    assert "graph.bfs" in out


def test_smoke_saturation_phase_numbers(smoke_run):
    """ISSUE 6 acceptance: the smoke lane records the saturation sweep
    — per load level p50/p99 latency, shed count, mean batch occupancy
    — and the deterministic totals the golden pins: 60 requests
    ((1+2+4+8) clients x 4 closed-loop requests each), every one
    batched exactly once, plus the 1 deadline-shed drill request."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 10
    levels = result["saturation"]
    assert [lv["clients"] for lv in levels] == [1, 2, 4, 8]
    for lv in levels:
        assert lv["requests"] == lv["clients"] * 4
        assert lv["p50_ms"] > 0
        assert lv["p99_ms"] >= lv["p50_ms"]
        assert lv["throughput_rps"] > 0
        assert lv["mean_batch_occupancy"] >= 1.0
        assert lv["shed"] == 0
    assert result["saturation_requests"] == 60
    assert result["saturation_batched_requests"] == 60
    assert result["saturation_shed"] == 1
    assert result["saturation_p99_ms"] >= result["saturation_p50_ms"]


def test_smoke_autotune_phase_numbers(smoke_run):
    """ISSUE 8 acceptance (smoke lane): the autotune phase pins one
    sliced-ELL verdict against a fresh store, proves it actually
    routes an eager dispatch, and records the kernel race — the
    verdict count is golden-pinned; the timings are informational."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 11
    assert result["autotune_verdicts"] == 1
    assert result["irregular_spmv_path"] == "sliced-ell"
    assert result["irregular_spmv_ms"] > 0
    assert result["irregular_csr_ms"] > 0
    assert result["irregular_spmv_speedup"] > 0
    assert result["irregular_spmv_nnz"] > 0


def test_smoke_trace_has_autotune_ledger(smoke_run, capsys):
    """The trace artifact carries the autotune.* counters and
    ``trace_summary --autotune`` renders the routing/verdict table."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    assert ctrs.get("autotune.verdict.records", 0) == 1
    assert ctrs.get("autotune.route.hits", 0) >= 1
    assert ctrs.get("autotune.route.sliced-ell", 0) >= 1
    rc = _tool("trace_summary").main([str(trace_path), "--autotune"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "autotune ledger:" in out
    assert "autotune.route.hits" in out


def test_smoke_gateway_phase_numbers(smoke_run):
    """Gateway fairness sweep acceptance: the 3-tenant sweep's totals
    are deterministic given the fixed submission sequence.  Stage A
    (max_batch=4): 48 requests in 12 batches, the interactive tenant's
    two alternating same-bucket matrices land in 2 packed multi-matrix
    dispatches (+1 mixed-tenant pack in stage B's single wide batch =
    3 packed).  Stage B (flood, tenant_quota=8): the background tenant
    offers 32 and rejects exactly 24 ``queue_full`` — while the
    interactive tenant serves everything it submitted (16 across both
    stages, 0 shed): the isolation headline."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 12
    assert result["gateway_requests"] == 96
    assert result["gateway_dispatches"] == 13
    assert result["gateway_packed"] == 3
    assert result["gateway_rejected_queue_full"] == 24
    assert result["gateway_interactive_served"] == 16
    assert result["gateway_interactive_shed"] == 0
    assert result["gateway_batch_served"] == 16
    assert result["gateway_background_served"] == 40
    assert result["gateway_background_shed"] == 24


def test_smoke_trace_has_gateway_ledger(smoke_run, capsys):
    """The trace artifact carries the gateway.* counters with exact
    per-tenant accounting, and ``trace_summary --gateway`` renders the
    per-tenant ledger."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    # Process-cumulative: 96 from the fairness sweep + 16 from the
    # attribution phase's 2-tenant load (8 interactive + 8 batch) +
    # 30 from the placement phase (24 noisy + 6 quiet across its two
    # serving rounds) + 24 from the mutation phase's "mut" tenant
    # (20 live-storm serves + 4 post-swap serves).
    assert ctrs.get("gateway.submitted", 0) == 166
    assert ctrs.get("gateway.rejected.queue_full", 0) == 24
    # Per-tenant ledgers balance: submitted == served + shed.
    for tenant, served, shed in (("interactive", 24, 0),
                                 ("batch", 24, 0),
                                 ("background", 40, 24)):
        assert ctrs.get(f"gateway.tenant.{tenant}.submitted", 0) == (
            served + shed), tenant
        assert ctrs.get(f"gateway.tenant.{tenant}.served", 0) == served
        assert ctrs.get(f"gateway.tenant.{tenant}.shed", 0) == shed
    hists = doc["otherData"].get("histograms") or {}
    assert any(k.startswith("lat.gateway.wait.") and v["count"] > 0
               for k, v in hists.items()), sorted(hists)
    rc = _tool("trace_summary").main([str(trace_path), "--gateway"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "gateway ledger:" in out
    assert "interactive" in out and "background" in out
    assert "24 queue_full" in out


def test_smoke_attrib_phase_numbers(smoke_run):
    """ISSUE 18 acceptance (smoke lane): the attribution phase arms
    the per-tenant ledger over a deterministic 2-tenant gateway load
    (16 requests; the interactive tenant's alternating matrices land
    in 2 packed dispatches) plus two dist SpMV dispatches — one
    single-tenant, one under a packed 3-member scope — and the
    conservation verdict is exact: the per-tenant attributed byte sum
    equals the untagged ``comm.total_bytes`` delta, remainder
    apportioning included."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 18
    assert result["attrib_requests"] == 16
    assert result["attrib_packed"] == 2
    assert result["attrib_tenants"] == 3
    assert result["attrib_conserved"] == 1
    assert result["attrib_comm_bytes"] > 0
    assert result["attrib_tenant_comm_bytes"] == \
        result["attrib_comm_bytes"]
    assert result["attrib_ms"] > 0


def test_smoke_trace_has_attrib_ledger(smoke_run, capsys):
    """The trace artifact carries the attrib.*/util.* counters from
    the attribution phase — per-tenant comm bytes and (with tracing
    on) wall-time attribution from the dispatch spans — and
    ``trace_summary --tenants`` renders the ledger with its
    conservation line."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    for t in ("interactive", "batch", "background"):
        assert ctrs.get(f"attrib.tenant.{t}.comm_bytes", 0) > 0, t
    total = sum(v for k, v in ctrs.items()
                if k.startswith("attrib.tenant.")
                and k.endswith(".comm_bytes"))
    assert total == ctrs.get("attrib.total.comm_bytes", 0)
    # Tracing was on, so the gateway.batch dispatch spans attributed
    # wall time and fed the utilization estimator.
    assert ctrs.get("attrib.tenant.interactive.wall_ns", 0) > 0
    assert ctrs.get("util.busy_ns", 0) > 0
    assert ctrs.get("util.dispatches", 0) >= 4
    rc = _tool("trace_summary").main([str(trace_path), "--tenants"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "tenant attribution:" in out
    assert "interactive" in out
    assert "conservation:" in out and "exact" in out


def test_smoke_placement_phase_numbers(smoke_run):
    """ISSUE 19 acceptance (smoke lane): the placement phase serves
    two placed tenants through the gateway's routing (16+4 pre-carve,
    8+2 on the new carve — every armed admission routed, plus the two
    warm-up routes: 32), and the burning-tenant plan migrates both
    tenants exactly once (noisy onto a 7-device submesh, quiet onto
    its 1-device slice) with the declared reshard bytes golden-pinned
    as an exact field."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 19
    assert result["placement_migrations"] == 2
    assert result["placement_routes"] == 32
    assert result["placement_reshard_bytes"] > 0
    assert result["placement_noisy_served"] == 24
    assert result["placement_quiet_served"] == 6
    assert result["placement_ms"] > 0


def test_smoke_trace_has_placement_ledger(smoke_run, capsys):
    """The trace artifact carries the placement.* counters with the
    declared-volume invariant (placement.migration.bytes equals the
    phase's recorded field) and ``trace_summary --placement`` renders
    the ledger."""
    result, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    assert ctrs.get("placement.placed", 0) == 2
    assert ctrs.get("placement.migrations", 0) == 2
    assert ctrs.get("placement.migration.bytes", 0) == \
        result["placement_reshard_bytes"]
    assert ctrs.get("placement.routes", 0) == 32
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "bench.placement" in names
    assert "placement.migration" in names
    rc = _tool("trace_summary").main([str(trace_path), "--placement"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "placement ledger:" in out
    assert "migrations: 2 applied" in out


def test_smoke_mutation_phase_numbers(smoke_run):
    """ISSUE 20 acceptance (smoke lane): the mutation phase wraps a
    fixed engine matrix in a ``DeltaCSR`` and serves it through the
    gateway's delta routing while a seeded ``gallery.mutation_stream``
    storm (100 updates, batch=10, seed 23) lands in the side-buffer.
    Every count is exact: 11 update batches (1 warm-up + 10 stream),
    101 distinct slots applied (the warm-up entry + 100 stream slots),
    21 delta-term serves (1 warm direct + 20 live gateway — the 4
    post-swap serves ride the fresh base with an empty buffer and
    bump nothing), 24 routed admissions, and exactly 1 compaction
    merging all 101 into the version-2 base (1 atomic swap)."""
    result, _, _ = smoke_run
    assert result["schema_version"] >= 20
    assert result["mutation_updates"] == 11
    assert result["mutation_applied"] == 101
    assert result["mutation_merged"] == 101
    assert result["mutation_compactions"] == 1
    assert result["mutation_version_swaps"] == 1
    assert result["mutation_served"] == 21
    assert result["mutation_routes"] == 24
    assert result["mutation_compaction_ms"] > 0
    assert result["mutation_ms"] > 0


def test_smoke_trace_has_delta_ledger(smoke_run, capsys):
    """The trace artifact carries the delta.* counters matching the
    phase's JSON fields, the mutation tenant's balanced gateway
    ledger, the delta latency histograms, and ``trace_summary
    --delta`` renders the mutation ledger."""
    result, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    assert ctrs.get("delta.updates", 0) == 11
    assert ctrs.get("delta.applied", 0) == 101
    assert ctrs.get("delta.compaction.merged", 0) == 101
    assert ctrs.get("delta.compactions", 0) == 1
    assert ctrs.get("delta.swap.versions", 0) == 1
    assert ctrs.get("delta.served", 0) == 21
    assert ctrs.get("delta.routes", 0) == 24
    assert ctrs.get("delta.compaction.bytes", 0) > 0
    assert ctrs.get("gateway.tenant.mut.submitted", 0) == 24
    assert ctrs.get("gateway.tenant.mut.served", 0) == 24
    hists = doc["otherData"].get("histograms") or {}
    assert hists.get("lat.delta.update", {}).get("count", 0) == 11
    assert hists.get("lat.delta.compaction", {}).get("count", 0) == 1
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "bench.mutation" in names
    assert "delta.compaction" in names
    rc = _tool("trace_summary").main([str(trace_path), "--delta"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "delta ledger:" in out
    assert "compaction" in out


def test_smoke_trace_has_latency_histograms(smoke_run, capsys):
    """The trace artifact embeds the lat.* histogram ledger (request
    lifecycle + per-op dispatch latencies) and ``trace_summary
    --latency`` renders it."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    hists = doc["otherData"].get("histograms") or {}
    assert any(k.startswith("lat.engine.request.") and v["count"] > 0
               for k, v in hists.items()), sorted(hists)
    assert any(k.startswith("lat.engine.wait.") for k in hists)
    assert any(k.startswith("lat.dist_spmv.") and v["count"] > 0
               for k, v in hists.items()), sorted(hists)
    occ = hists.get("lat.engine.batch_occupancy")
    assert occ is not None and occ["count"] > 0
    rc = _tool("trace_summary").main([str(trace_path), "--latency"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "latency histograms:" in out
    assert "lat.engine.request." in out


def test_smoke_trace_has_engine_plans(smoke_run, capsys):
    """The trace artifact carries the engine.plan.* counters and
    ``trace_summary --plans`` renders the per-plan table from them."""
    _, trace_path, _ = smoke_run
    doc = json.loads(trace_path.read_text())
    ctrs = doc["otherData"]["counters"]
    assert ctrs.get("engine.plan.misses", 0) >= 2
    assert any(k.startswith("engine.plan.spmv/") for k in ctrs), [
        k for k in ctrs if k.startswith("engine.")]
    rc = _tool("trace_summary").main([str(trace_path), "--plans"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "engine plans:" in out
    assert "plan cache:" in out and "spmv/float32" in out
