# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""BiCGSTAB solver (beyond-reference: the reference ships cg/gmres
only) — differential vs scipy on non-symmetric systems."""

import numpy as np
import scipy.sparse as scsp

import legate_sparse_tpu as sparse
from legate_sparse_tpu.linalg import LinearOperator, bicgstab


def _nonsym(n, seed=1):
    S = scsp.random(n, n, density=0.02, format="csr", random_state=seed)
    return S + scsp.diags([np.full(n, 10.0)], [0], format="csr")


def test_bicgstab_converges_nonsymmetric():
    n = 400
    S = _nonsym(n)
    A = sparse.csr_array(S)
    b = np.random.default_rng(0).normal(size=n)
    x, iters = bicgstab(A, b, rtol=1e-10, maxiter=2000)
    res = np.linalg.norm(b - S @ np.asarray(x)) / np.linalg.norm(b)
    assert res < 1e-8
    assert int(iters) < 200


def test_bicgstab_matches_scipy_solution():
    n = 200
    S = _nonsym(n, seed=3)
    A = sparse.csr_array(S)
    b = np.random.default_rng(2).normal(size=n)
    x, _ = bicgstab(A, b, rtol=1e-12, maxiter=2000)
    import scipy.sparse.linalg as sla

    x_ref, info = sla.bicgstab(S, b, rtol=1e-12, maxiter=2000)
    assert info == 0
    np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-6, atol=1e-9)


def test_bicgstab_preconditioned():
    n = 400
    S = _nonsym(n)
    A = sparse.csr_array(S)
    b = np.random.default_rng(4).normal(size=n)
    d_inv = 1.0 / S.diagonal()
    M = LinearOperator((n, n), matvec=lambda v: d_inv * v)
    x, iters = bicgstab(A, b, rtol=1e-10, maxiter=2000, M=M)
    res = np.linalg.norm(b - S @ np.asarray(x)) / np.linalg.norm(b)
    assert res < 1e-8


def test_bicgstab_callback():
    """Callback path runs the same carried-state algorithm as the
    while_loop path (same iterate sequence, same solution)."""
    n = 100
    S = _nonsym(n, seed=5)
    A = sparse.csr_array(S)
    b = np.ones(n)
    iterates = []
    x, iters = bicgstab(
        A, b, rtol=1e-8, maxiter=500, callback=lambda xk: iterates.append(1)
    )
    assert len(iterates) == int(iters)
    res = np.linalg.norm(b - S @ np.asarray(x)) / np.linalg.norm(b)
    assert res < 1e-6
    x_plain, iters_plain = bicgstab(
        A, b, rtol=1e-8, maxiter=500, conv_test_iters=1
    )
    assert int(iters) == int(iters_plain)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_plain),
                               rtol=1e-10, atol=1e-12)


def test_bicgstab_exact_start():
    """x0 already the solution: zero-residual guards must not NaN."""
    n = 50
    S = scsp.diags([np.full(n, 2.0)], [0], format="csr")
    A = sparse.csr_array(S)
    b = np.ones(n)
    x0 = b / 2.0
    x, iters = bicgstab(A, b, x0=x0, rtol=1e-12, maxiter=100)
    np.testing.assert_allclose(np.asarray(x), x0, atol=1e-12)
    assert np.all(np.isfinite(np.asarray(x)))
