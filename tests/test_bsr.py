# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Block-sparse (BSR) irregular-path SpMV: pack + kernels.

Differential model: scipy (reference ``tests/test_csr.py`` style).
The Pallas kernel runs in interpret mode on the CPU mesh; the real
Mosaic lowering is exercised by the ``-m tpu`` lane below.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from legate_sparse_tpu.ops.bsr import (
    B, BsrStructure, bsr_pack, bsr_spmv_xla,
)


def _random_csr(rows, cols, density, seed=0):
    rng = np.random.default_rng(seed)
    return sp.random(
        rows, cols, density=density, format="csr",
        random_state=rng, dtype=np.float32,
    )


@pytest.mark.parametrize(
    "rows,cols,density",
    [(256, 256, 0.03), (300, 700, 0.02), (1000, 130, 0.05)],
)
def test_bsr_matches_scipy(rows, cols, density):
    A = _random_csr(rows, cols, density)
    pack = bsr_pack(A.data, A.indices, A.indptr, A.shape, max_expand=1e9)
    assert pack is not None
    st = BsrStructure(*pack, rows, cols)
    x = np.random.default_rng(1).standard_normal(cols).astype(np.float32)
    y = np.asarray(st.matvec(x, interpret=True))
    np.testing.assert_allclose(y, A @ x, rtol=1e-5, atol=1e-5)


def test_bsr_xla_reference_matches():
    rows = cols = 384
    A = _random_csr(rows, cols, 0.04, seed=3)
    blkT, brow, bcol, nbr, nbc = bsr_pack(
        A.data, A.indices, A.indptr, A.shape, max_expand=1e9
    )
    x = np.random.default_rng(2).standard_normal(cols).astype(np.float32)
    xf = np.zeros(nbc * B, np.float32)
    xf[:cols] = x
    y = np.asarray(
        bsr_spmv_xla(jnp.asarray(blkT), jnp.asarray(brow),
                     jnp.asarray(bcol), jnp.asarray(xf.reshape(nbc, B)),
                     nbr, nbc)
    ).ravel()[:rows]
    np.testing.assert_allclose(y, A @ x, rtol=1e-5, atol=1e-5)


def test_bsr_empty_block_rows_and_duplicates():
    # Block-rows 0 and 2 have no nonzeros; one entry is a duplicate.
    r = np.array([130, 135, 400, 500, 500])
    c = np.array([0, 300, 10, 499, 499])
    v = np.array([1.0, 2.0, 3.0, 4.0, 2.5], dtype=np.float32)
    A = sp.coo_matrix((v, (r, c)), shape=(512, 512)).tocsr()
    pack = bsr_pack(A.data, A.indices, A.indptr, A.shape, max_expand=1e9)
    st = BsrStructure(*pack, 512, 512)
    x = np.random.default_rng(4).standard_normal(512).astype(np.float32)
    y = np.asarray(st.matvec(x, interpret=True))
    np.testing.assert_allclose(y, A @ x, rtol=1e-5, atol=1e-6)


def test_bsr_budget_rejects_hyper_sparse():
    n, nnz = 100000, 5000
    rng = np.random.default_rng(5)
    A = sp.coo_matrix(
        (rng.standard_normal(nnz).astype(np.float32),
         (rng.integers(0, n, nnz), rng.integers(0, n, nnz))),
        shape=(n, n),
    ).tocsr()
    assert bsr_pack(A.data, A.indices, A.indptr, A.shape,
                    max_expand=32) is None


def test_bsr_1x1():
    A = sp.csr_matrix(np.array([[3.0]], dtype=np.float32))
    pack = bsr_pack(A.data, A.indices, A.indptr, (1, 1), max_expand=1e9)
    st = BsrStructure(*pack, 1, 1)
    y = np.asarray(st.matvec(np.array([2.0], np.float32), interpret=True))
    np.testing.assert_allclose(y, [6.0])


def test_csr_dispatch_uses_bsr(monkeypatch):
    """csr_array @ x routes through BSR under the force flag (CPU) and
    produces scipy-identical results for a non-banded matrix."""
    import legate_sparse_tpu as lst
    from legate_sparse_tpu.settings import settings

    monkeypatch.setattr(settings, "bsr_force", True)
    A = _random_csr(256, 256, 0.05, seed=7)
    M = lst.csr_array(A)
    bsr = M._get_bsr()
    assert bsr is not None and bsr.nblocks >= 1
    x = np.random.default_rng(8).standard_normal(256).astype(np.float32)
    y = np.asarray(M @ x)
    np.testing.assert_allclose(y, A @ x, rtol=1e-5, atol=1e-5)


def test_csr_dispatch_bsr_bf16(monkeypatch):
    """bf16 matrices keep their dtype through the BSR route (bf16
    blocks, f32 accumulation)."""
    import legate_sparse_tpu as lst
    from legate_sparse_tpu.settings import settings

    monkeypatch.setattr(settings, "bsr_force", True)
    A = _random_csr(256, 256, 0.05, seed=13)
    M = lst.csr_array(A).astype(jnp.bfloat16)
    assert M._get_bsr() is not None
    x = np.random.default_rng(14).standard_normal(256).astype(np.float32)
    y = M @ jnp.asarray(x, jnp.bfloat16)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, dtype=np.float32), A @ x, rtol=0.05, atol=0.05
    )


def test_csr_dispatch_prefers_dia_over_bsr(monkeypatch):
    """A banded matrix keeps the DIA route; BSR is not built for it."""
    import legate_sparse_tpu as lst
    from legate_sparse_tpu.settings import settings

    monkeypatch.setattr(settings, "bsr_force", True)
    M = lst.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(512, 512),
                  format="csr", dtype=np.float32)
    assert M._get_dia() is not None
    x = np.random.default_rng(9).standard_normal(512).astype(np.float32)
    y = np.asarray(M @ x)
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(512, 512)).tocsr()
    np.testing.assert_allclose(y, As @ x, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("k", [1, 5, 16])
def test_bsr_spmm_matches_scipy(k):
    A = _random_csr(256, 200, 0.04, seed=21)
    pack = bsr_pack(A.data, A.indices, A.indptr, A.shape, max_expand=1e9)
    st = BsrStructure(*pack, 256, 200)
    X = np.random.default_rng(22).standard_normal((200, k)).astype(
        np.float32
    )
    Y = np.asarray(st.matmat(X, interpret=True))
    np.testing.assert_allclose(Y, A @ X, rtol=1e-4, atol=1e-4)


def test_csr_dispatch_bsr_spmm(monkeypatch):
    import legate_sparse_tpu as lst
    from legate_sparse_tpu.settings import settings

    monkeypatch.setattr(settings, "bsr_force", True)
    A = _random_csr(256, 256, 0.05, seed=23)
    M = lst.csr_array(A)
    assert M._get_bsr() is not None
    X = np.random.default_rng(24).standard_normal((256, 6)).astype(
        np.float32
    )
    Y = np.asarray(M @ X)
    np.testing.assert_allclose(Y, A @ X, rtol=1e-4, atol=1e-4)


def test_native_pack_matches_numpy():
    """When the C++ helper is built, its single-pass pack must be
    bit-identical to the numpy pack (budget decisions included)."""
    from legate_sparse_tpu import utils_native as un
    from legate_sparse_tpu.ops.bsr import MAX_BLOCKS

    if not un.native_available():
        pytest.skip("native helper not built")
    A = _random_csr(700, 500, 0.03, seed=31)
    nat = un.native_bsr_pack(A.indptr, A.indices, A.data, 700, 500,
                             1e9, MAX_BLOCKS)
    real_load = un._load
    un._load = lambda: None   # force the numpy path
    try:
        ref = bsr_pack(A.data, A.indices, A.indptr, A.shape,
                       max_expand=1e9)
    finally:
        un._load = real_load
    np.testing.assert_array_equal(nat[0], ref[0])
    np.testing.assert_array_equal(nat[1], ref[1])
    np.testing.assert_array_equal(nat[2], ref[2])
    assert nat[3:] == ref[3:]
    # Budget decisions agree too.
    assert un.native_bsr_pack(A.indptr, A.indices, A.data, 700, 500,
                              1.0, MAX_BLOCKS) == "over_budget"
    un._load = lambda: None
    try:
        assert bsr_pack(A.data, A.indices, A.indptr, A.shape,
                        max_expand=1.0) is None
    finally:
        un._load = real_load


@pytest.mark.tpu
def test_bsr_on_chip():
    """Real-chip Mosaic lowering + correctness of the merged kernel."""
    import jax

    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU")
    A = _random_csr(1024, 1024, 0.02, seed=11)
    pack = bsr_pack(A.data, A.indices, A.indptr, A.shape, max_expand=1e9)
    st = BsrStructure(*pack, 1024, 1024)
    x = np.random.default_rng(12).standard_normal(1024).astype(np.float32)
    y = np.asarray(st.matvec(x, interpret=False))
    np.testing.assert_allclose(y, A @ x, rtol=1e-4, atol=1e-4)


@pytest.mark.tpu
def test_bsr_spmm_on_chip():
    """Mosaic lowering of the BSR SpMM kernel on a real chip."""
    import jax

    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU")
    A = _random_csr(1024, 1024, 0.02, seed=41)
    pack = bsr_pack(A.data, A.indices, A.indptr, A.shape, max_expand=1e9)
    st = BsrStructure(*pack, 1024, 1024)
    X = np.random.default_rng(42).standard_normal((1024, 8)).astype(
        np.float32
    )
    Y = np.asarray(st.matmat(X, interpret=False))
    np.testing.assert_allclose(Y, A @ X, rtol=1e-3, atol=1e-3)
