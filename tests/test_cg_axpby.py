# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Fused axpby kernel tests (mirrors reference ``test_cg_axpby.py``:
all four isalpha x negate combinations vs closed form)."""

import numpy as np
import pytest

from legate_sparse_tpu.linalg import cg_axpby


@pytest.mark.parametrize("isalpha", [True, False])
@pytest.mark.parametrize("negate", [True, False])
def test_cg_axpby(isalpha, negate):
    rng = np.random.default_rng(3)
    n = 57
    y = rng.standard_normal(n)
    x = rng.standard_normal(n)
    a, b = 3.7, 1.3
    coef = -(a / b) if negate else (a / b)
    expected = coef * x + y if isalpha else x + coef * y
    y_arg = y.copy()
    result = cg_axpby(y_arg, x, a, b, isalpha=isalpha, negate=negate)
    np.testing.assert_allclose(result, expected, atol=1e-14)
    # numpy outputs are mutated in place (reference contract).
    np.testing.assert_allclose(y_arg, expected, atol=1e-14)
