# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""CG solver tests (mirrors reference ``test_cg_solve.py``)."""

import numpy as np
import pytest

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg
from utils_test.gen import spd_system


def test_cg_solve():
    N = 1000
    A_dense, x = spd_system(N, 0.1, 471014)
    assert np.all(np.linalg.eigvals(A_dense) > 0)
    A = sparse.csr_array(A_dense)
    y = A @ x
    x_pred, iters = linalg.cg(A, y, tol=1e-8)
    np.testing.assert_allclose(
        np.asarray(A @ x_pred), np.asarray(y), rtol=1e-8, atol=0.0
    )
    assert iters > 0


def test_cg_solve_with_callback():
    N = 300
    A_dense, x = spd_system(N, 0.1, 471014)
    A = sparse.csr_array(A_dense)
    y = A @ x
    residuals = []

    def callback(xk):
        residuals.append(y - A @ xk)

    x_pred, iters = linalg.cg(A, y, tol=1e-8, callback=callback)
    np.testing.assert_allclose(
        np.asarray(A @ x_pred), np.asarray(y), rtol=1e-8, atol=0.0
    )
    assert len(residuals) == iters


def test_cg_solve_linear_operator():
    N = 300
    A_dense, x = spd_system(N, 0.1, 7)
    A = sparse.csr_array(A_dense)
    y = A @ x
    op = linalg.LinearOperator(A.shape, matvec=lambda v: A @ v,
                               dtype=A.dtype)
    x_pred, _ = linalg.cg(op, y, tol=1e-8)
    np.testing.assert_allclose(
        np.asarray(A @ x_pred), np.asarray(y), rtol=1e-8, atol=0.0
    )


def test_cg_solve_preconditioned():
    N = 300
    A_dense, x = spd_system(N, 0.1, 99)
    A = sparse.csr_array(A_dense)
    y = A @ x
    dinv = 1.0 / np.asarray(A.diagonal())
    M = linalg.LinearOperator(
        A.shape, matvec=lambda v: dinv * v, dtype=A.dtype
    )
    x_pred, iters_pre = linalg.cg(A, y, tol=1e-10, M=M)
    np.testing.assert_allclose(
        np.asarray(A @ x_pred), np.asarray(y), rtol=1e-8, atol=1e-8
    )


def test_cg_x0():
    N = 200
    A_dense, x = spd_system(N, 0.2, 31)
    A = sparse.csr_array(A_dense)
    y = A @ x
    x_pred, iters = linalg.cg(A, y, x0=np.asarray(x), tol=1e-8,
                              conv_test_iters=1)
    # Starting at the exact solution must converge immediately.
    assert iters <= 2
