# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Comm ledger (obs/comm.py): the distributed layer's collective byte
accounting must MATCH the static shard-shape prediction — asserted
here by recomputing the model from first principles (mesh size, halo
width, block sizes) and comparing against the recorded counters and
span attrs.  Also covers the sparsity-aware window-decline key
(ADVICE r5 low, finished this round)."""

import importlib

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs
from legate_sparse_tpu.obs import comm, counters, trace
from legate_sparse_tpu.parallel import (
    DistGMG, dist_cg, dist_spgemm, make_row_mesh, shard_csr,
)
from legate_sparse_tpu.parallel.dist_csr import (
    cg_comm_volumes, dist_spmv, shard_vector, spmv_comm_volumes,
)

_spg = importlib.import_module("legate_sparse_tpu.parallel.dist_spgemm")

R = len(jax.devices())
needs_mesh = pytest.mark.skipif(R < 2, reason="needs a multi-device mesh")
needs_window = pytest.mark.skipif(R < 4,
                                  reason="window + density buckets "
                                         "need R >= 4")


@pytest.fixture(autouse=True)
def _obs_isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was:
        trace.enable()
    else:
        trace.disable()


def _banded(n, dtype=np.float32):
    return sparse.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)], [-1, 0, 1],
        shape=(n, n), format="csr", dtype=dtype,
    )


# ----------------------------------------------------------- the model --
def test_model_single_shard_moves_nothing():
    for fn in (comm.all_gather_bytes, comm.psum_bytes,
               comm.all_to_all_bytes):
        assert fn(100, 4, 1) == 0
    assert comm.halo_exchange_bytes(5, 4, 1) == 0
    assert comm.ppermute_bytes(10, 4, 1, rounds=3) == 0


def test_model_formulas():
    assert comm.all_gather_bytes(10, 4, 8) == 8 * 7 * 10 * 4
    assert comm.halo_exchange_bytes(5, 4, 8) == 2 * 8 * 5 * 4
    assert comm.halo_exchange_bytes(0, 4, 8) == 0
    assert comm.psum_bytes(1, 4, 8) == 2 * 7 * 4
    assert comm.all_to_all_bytes(3, 4, 8) == 8 * 7 * 3 * 4
    assert comm.ppermute_bytes(10, 4, 8, rounds=3) == 3 * 8 * 10 * 4


def test_merge_scale_total():
    a = {"psum": 10, "ppermute": 5}
    b = {"psum": 1}
    assert comm.merge(a, b) == {"psum": 11, "ppermute": 5}
    assert comm.scale(a, 3) == {"psum": 30, "ppermute": 15}
    assert comm.total(a) == 15


def test_record_drops_zero_entries_and_accumulates():
    counters.reset("comm.")
    got = comm.record("unit_op", {"psum": 0, "all_gather": 128},
                      calls={"all_gather": 4})
    assert got == 128
    assert counters.get("comm.unit_op.all_gather") == 4
    assert counters.get("comm.unit_op.all_gather_bytes") == 128
    assert counters.get("comm.unit_op.psum") == 0
    assert counters.get("comm.total_bytes") == 128
    assert counters.get("comm.total_calls") == 4


# ----------------------------------------- counters match shard shapes --
@needs_mesh
def test_halo_spmv_counters_match_static_prediction():
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    assert dA.halo == 1       # tridiagonal band
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    counters.reset("comm.")
    _ = dist_spmv(dA, x)
    _ = dist_spmv(dA, x)
    per_call = 2 * R * dA.halo * 4      # two-sided exchange, f32
    assert counters.get("comm.dist_spmv.ppermute") == 2
    assert counters.get("comm.dist_spmv.ppermute_bytes") == 2 * per_call
    assert counters.get("comm.total_bytes") == 2 * per_call


@needs_mesh
def test_all_gather_spmv_counters_match_static_prediction():
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh, force_all_gather=True)
    assert dA.halo == -1 and dA.gather_idx is None
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    counters.reset("comm.")
    _ = dist_spmv(dA, x)
    per_call = R * (R - 1) * (dA.rows_padded // R) * 4
    assert counters.get("comm.dist_spmv.all_gather") == 1
    assert counters.get("comm.dist_spmv.all_gather_bytes") == per_call


@needs_mesh
def test_precise_spmv_counters_match_static_prediction():
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh, precise=True)
    assert dA.gather_idx is not None
    C = int(dA.gather_idx.shape[-1])
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    counters.reset("comm.")
    _ = dist_spmv(dA, x)
    per_call = R * (R - 1) * C * 4
    assert counters.get("comm.dist_spmv.all_to_all") == 1
    assert counters.get("comm.dist_spmv.all_to_all_bytes") == per_call


@needs_mesh
def test_spmv_span_carries_comm_attrs():
    trace.enable()
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    _ = dist_spmv(dA, x)
    (span,) = [r for r in obs.records() if r["name"] == "dist_spmv"]
    assert span["attrs"]["comm_bytes"] == 2 * R * dA.halo * 4
    assert span["attrs"]["comm_calls"] == 1


@needs_mesh
def test_dist_cg_comm_matches_iteration_model():
    trace.enable()
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    counters.reset("comm.")
    maxiter = 7
    _, iters = dist_cg(dA, np.ones(n, np.float32), rtol=0.0,
                       maxiter=maxiter, conv_test_iters=5)
    it = int(iters)
    assert it == maxiter        # rtol=0/atol=0 never converges early
    vols, _calls = cg_comm_volumes(dA, 4, it)
    (span,) = [r for r in obs.records() if r["name"] == "dist_cg"]
    assert span["attrs"]["comm_bytes"] == sum(vols.values())
    # Independent recomputation against the fused _cg_loop program:
    # iters+1 halo exchanges (initial residual + one per iteration)
    # and 3 scalar psums per iteration (rho, pq, and the
    # unconditional rnorm2 vdot).
    expect_pp = (it + 1) * 2 * R * dA.halo * 4
    expect_ps = 3 * it * 2 * (R - 1) * 4
    assert counters.get("comm.dist_cg.ppermute_bytes") == expect_pp
    assert counters.get("comm.dist_cg.psum_bytes") == expect_ps


@needs_mesh
def test_dist_cg_callback_path_does_not_double_count_spmv():
    """The eager callback loop's A_mv calls self-record under
    comm.dist_spmv.*; dist_cg must ledger only the scalar reductions
    the driver adds — re-recording the SpMV volumes would double the
    reported interconnect bytes vs the fused path."""
    mesh = make_row_mesh()
    n = 32 * R
    dA = shard_csr(_banded(n), mesh=mesh)
    counters.reset("comm.")
    seen = []
    _ = dist_cg(dA, np.ones(n, np.float32), rtol=0.0, maxiter=3,
                callback=seen.append)
    assert len(seen) == 3
    # 4 eager dispatches: the initial residual + one per iteration.
    assert counters.get("comm.dist_spmv.ppermute") == 4
    # No SpMV bytes under dist_cg — psums only.
    assert counters.get("comm.dist_cg.ppermute") == 0
    assert counters.get("comm.dist_cg.ppermute_bytes") == 0
    assert counters.get("comm.dist_cg.psum") == 2 * 3 + 3 // 25 + 1


@needs_mesh
def test_dist_spgemm_realization_event_carries_predictions():
    trace.enable()
    mesh = make_row_mesh()
    n = 16 * R
    rng = np.random.RandomState(0)
    A_sp = sp.random(n, n, density=0.4, random_state=rng,
                     format="csr", dtype=np.float64)
    A_sp.sum_duplicates()
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh,
                   force_all_gather=True)
    counters.reset("comm.")
    _ = dist_spgemm(dA, dA)
    evs = [r for r in obs.records()
           if r["name"] == "dist_spgemm.realization"]
    assert len(evs) == 1
    at = evs[0]["attrs"]
    assert at["choice"] == "all_gather"
    assert at["predicted_bytes"] == at["predicted_all_gather_bytes"] > 0
    # The chosen realization is what entered the ledger.
    assert (counters.get("comm.dist_spgemm.all_gather_bytes")
            == at["predicted_bytes"])
    (span,) = [r for r in obs.records() if r["name"] == "dist_spgemm"]
    assert span["attrs"]["comm_bytes"] == at["predicted_bytes"]


@needs_window
def test_windowed_realization_predicts_fewer_bytes_than_all_gather():
    """The window-vs-all_gather choice is now evidence-backed: for a
    narrow-window band on the general ESC path the recorded window
    prediction must undercut the all_gather counterfactual."""
    trace.enable()
    mesh = make_row_mesh()
    n = 16 * R
    d0 = np.where(np.arange(n) % 3 == 0, 0.0, 2.0)
    A = sparse.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                     format="csr")
    dA = shard_csr(A, mesh=mesh)
    assert dA.dia_mask is not None     # holey band -> general ESC
    _spg.reset_window_declines()
    counters.reset("comm.")
    _ = dist_spgemm(dA, dA)
    assert _spg.LAST_B_REALIZATION == "window"
    evs = [r for r in obs.records()
           if r["name"] == "dist_spgemm.realization"]
    at = evs[-1]["attrs"]
    assert at["choice"] == "window"
    assert 0 < at["predicted_window_bytes"] == at["predicted_bytes"]
    assert at["predicted_window_bytes"] < at["predicted_all_gather_bytes"]
    assert (counters.get("comm.dist_spgemm.ppermute_bytes")
            == at["predicted_bytes"])
    # The probe's own two scalar all_gathers are ledgered too.
    assert counters.get(
        "comm.dist_spgemm.window_probe.all_gather") == 2


@pytest.mark.slow
@pytest.mark.skipif(R < 8, reason="needs the 8-device mesh")
def test_gmg_hierarchy_prices_its_cycle():
    # Same operator/mesh construction as test_grid_mesh's
    # test_full_dist_stack_on_grid_mesh, so the expensive
    # hierarchy-build compiles are shared once per suite run.
    from legate_sparse_tpu.parallel import make_grid_mesh

    trace.enable()
    mesh = make_grid_mesh(jax.devices()[:8])
    n = 256
    A = sparse.diags([-1.0, 4.0, -1.0], [-16, 0, 16], shape=(n, n),
                     format="csr", dtype=np.float64)
    gmg = DistGMG(shard_csr(A, mesh=mesh), levels=2)
    assert gmg.cycle_comm_bytes == sum(gmg.cycle_comm_volumes.values())
    assert gmg.cycle_comm_bytes > 0
    evs = [r for r in obs.records()
           if r["name"] == "dist_gmg.hierarchy"]
    assert evs and evs[0]["attrs"]["cycle_comm_bytes"] == \
        gmg.cycle_comm_bytes


@needs_mesh
def test_model_matches_lowered_collectives():
    """Anti-circularity check: the ledger's collective KINDS,
    multiplicities AND bytes must match the program XLA actually
    lowers, not just the model that produced the counters.  Goes
    through planverify's schedule checker (tools/verify) — the same
    parser and byte convention the contract gate enforces — instead of
    ad-hoc substring counting."""
    from tools.verify.catalog import Built
    from tools.verify.rules import lowered_volumes, schedule_of

    mesh = make_row_mesh()
    n = 32 * R

    def built_of(dA):
        x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
        hlo = jax.jit(lambda v: dist_spmv(dA, v)).lower(x).as_text()
        return Built(hlo=hlo, jaxpr=None, predicted=None)

    def model_of(dA):
        vols = spmv_comm_volumes(dA, dA.rows_padded // dA.num_shards, 4)
        return {k: v for k, v in vols.items() if v > 0}

    dA = shard_csr(_banded(n), mesh=mesh)
    built = built_of(dA)
    # Two-sided halo exchange: exactly the two ppermutes the model
    # prices as one exchange of 2*R*halo*itemsize bytes; no gather.
    assert [e["kind"] for e in schedule_of(built)] == \
        ["collective_permute", "collective_permute"]
    assert lowered_volumes(built) == model_of(dA)

    dA = shard_csr(_banded(n), mesh=mesh, force_all_gather=True)
    built = built_of(dA)
    kinds = [e["kind"] for e in schedule_of(built)]
    assert kinds and set(kinds) == {"all_gather"}
    assert lowered_volumes(built) == model_of(dA)


# ------------------------------------- sparsity-aware window declines --
@needs_window
def test_window_decline_keyed_on_density_bucket():
    """ADVICE r5 low, finished: one wide-window matrix must not pin a
    later SAME-LAYOUT but much sparser matrix to all_gather.  Two
    matrices engineered to share an identical ``_Layout`` (same ELL
    width, shards, shape, halo) but sit in different nnz-density
    buckets: the dense one declines; the sparse one still probes and
    wins the window."""
    mesh = make_row_mesh()
    n = 8 * R
    rps = 8

    # Wide: every row has R entries striped across every shard.
    rows1, cols1 = [], []
    for i in range(n):
        for k in range(R):
            rows1.append(i)
            cols1.append((i + k * rps) % n)
    A1 = sp.csr_matrix(
        (np.ones(len(rows1)), (rows1, cols1)), shape=(n, n))

    # Narrow: near-diagonal pairs, one row widened to R entries inside
    # its own shard so the ELL width (and so the layout) matches A1.
    rows2, cols2 = [0] * R, list(range(R))
    for i in range(1, n - 1):
        rows2 += [i, i]
        cols2 += [i, i + 1]
    rows2.append(n - 1)
    cols2.append(n - 1)
    A2 = sp.csr_matrix(
        (np.ones(len(rows2)), (rows2, cols2)), shape=(n, n))

    dA1 = shard_csr(sparse.csr_array(A1), mesh=mesh,
                    force_all_gather=True)
    dA2 = shard_csr(sparse.csr_array(A2), mesh=mesh,
                    force_all_gather=True)
    la1 = _spg._layout_of(dA1)
    la2 = _spg._layout_of(dA2)
    assert la1 == la2, "test precondition: identical layouts"
    b1 = _spg._density_bucket(dA1.nnz_hint, n)
    b2 = _spg._density_bucket(dA2.nnz_hint, n)
    assert b1 != b2, "test precondition: distinct density buckets"

    _spg.reset_window_declines()
    _ = dist_spgemm(dA1, dA1)
    assert _spg.LAST_B_REALIZATION == "all_gather"
    assert len(_spg._WINDOW_DECLINED) > 0

    # Same layout, sparser bucket: the probe must run (and accept).
    probes0 = counters.get("transfer.host_sync.spgemm_window_probe")
    _ = dist_spgemm(dA2, dA2)
    assert (counters.get("transfer.host_sync.spgemm_window_probe")
            == probes0 + 1)
    assert _spg.LAST_B_REALIZATION == "window"

    # Identical density still short-circuits on the cached decline.
    cached0 = counters.get("dist_spgemm.window_decline_cached")
    _ = dist_spgemm(dA1, dA1)
    assert (counters.get("dist_spgemm.window_decline_cached")
            == cached0 + 1)
    _spg.reset_window_declines()


def test_density_bucket_edges():
    assert _spg._density_bucket(0, 100) == -1
    assert _spg._density_bucket(50, 100) == -1       # < 1 per row
    assert _spg._density_bucket(100, 100) == 0
    assert _spg._density_bucket(800, 100) == 3
    assert _spg._density_bucket(100, 0) == -1


# --------------------------------------------- 2-d-block exactness --
needs_grid = pytest.mark.skipif(R < 8, reason="needs the 8-device mesh")


def _random_sym(n, density=0.08, dtype=np.float64, seed=3):
    rng = np.random.default_rng(seed)
    A_sp = sp.random(n, n, density=density, random_state=rng,
                     format="csr", dtype=np.float64)
    A_sp = (A_sp + A_sp.T + 10.0 * sp.eye(n)).tocsr().astype(dtype)
    return sparse.csr_array(A_sp)


@needs_grid
def test_2d_spmv_counters_match_static_prediction():
    from legate_sparse_tpu.parallel import make_grid_mesh

    mesh = make_grid_mesh(2, 4)
    n = 96
    A = _random_sym(n)
    dA = shard_csr(A, mesh=mesh, layout="2d-block")
    assert dA.grid == (2, 4) and dA.layout == "2d-block"
    x = shard_vector(np.ones(n, np.float64), mesh, dA.rows_padded,
                     layout=dA.layout)
    vols = spmv_comm_volumes(dA, dA.rows_padded // dA.num_shards, 8)
    assert set(vols) == {"ppermute", "all_gather", "psum"}
    counters.reset("comm.")
    _ = dist_spmv(dA, x)
    for kind, nbytes in vols.items():
        assert counters.get(f"comm.dist_spmv.{kind}") == 1, kind
        assert counters.get(
            f"comm.dist_spmv.{kind}_bytes") == nbytes, kind
    assert counters.get(
        "comm.layout.2d-block.dist_spmv_bytes") == sum(vols.values())
    # And the 2-D program moves fewer predicted bytes than the 1-D
    # all_gather the same matrix forces at equal device count.
    dA1 = shard_csr(A, mesh=make_row_mesh(), force_all_gather=True)
    vols1 = spmv_comm_volumes(dA1, dA1.rows_padded // 8, 8)
    assert sum(vols.values()) < sum(vols1.values())


@needs_grid
def test_2d_model_matches_lowered_collectives():
    """Anti-circularity for the 2-d-block program, through
    planverify's schedule checker: the lowered HLO carries exactly the
    collectives the ledger prices — one input fixup permute, one
    x-panel all-gather, one reduce-scatter — and their byte volumes
    (ledger convention) match the static model exactly, here at f64."""
    from legate_sparse_tpu.parallel import make_grid_mesh
    from tools.verify.catalog import Built
    from tools.verify.rules import lowered_volumes, schedule_of

    mesh = make_grid_mesh(2, 4)
    n = 96
    dA = shard_csr(_random_sym(n), mesh=mesh, layout="2d-block")
    x = shard_vector(np.ones(n, np.float64), mesh, dA.rows_padded,
                     layout=dA.layout)
    hlo = jax.jit(lambda v: dist_spmv(dA, v)).lower(x).as_text()
    built = Built(hlo=hlo, jaxpr=None, predicted=None)
    assert [e["kind"] for e in schedule_of(built)] == [
        "collective_permute", "all_gather", "reduce_scatter"]
    vols = spmv_comm_volumes(dA, dA.rows_padded // 8, 8)
    assert lowered_volumes(built) == {
        k: v for k, v in vols.items() if v > 0}


@needs_grid
def test_2d_cg_comm_matches_iteration_model():
    from legate_sparse_tpu.parallel import make_grid_mesh

    trace.enable()
    mesh = make_grid_mesh(2, 4)
    n = 96
    dA = shard_csr(_random_sym(n), mesh=mesh, layout="2d-block")
    counters.reset("comm.")
    maxiter = 7
    _, iters = dist_cg(dA, np.ones(n, np.float64), rtol=0.0,
                       maxiter=maxiter, conv_test_iters=5)
    it = int(iters)
    assert it == maxiter
    vols, calls = cg_comm_volumes(dA, 8, it)
    # The SpMV's own psum_scatter merges ADDITIVELY with the solver's
    # 3 scalar psums per iteration — the 2-D regression this guards:
    # an overwrite would drop one or the other from the ledger.
    assert calls["psum"] == (it + 1) + 3 * it
    (span,) = [r for r in obs.records() if r["name"] == "dist_cg"]
    assert span["attrs"]["comm_bytes"] == sum(vols.values())
    spmv_vols = spmv_comm_volumes(dA, dA.rows_padded // 8, 8)
    expect_psum = ((it + 1) * spmv_vols["psum"]
                   + 3 * it * 2 * (8 - 1) * 8)
    assert counters.get("comm.dist_cg.psum_bytes") == expect_psum
    assert counters.get("comm.dist_cg.ppermute_bytes") == (
        (it + 1) * spmv_vols["ppermute"])


@needs_grid
def test_2d_spgemm_counters_match_summa_prediction():
    from legate_sparse_tpu.parallel import make_grid_mesh

    trace.enable()
    mesh = make_grid_mesh(2, 4)
    n = 96
    A = _random_sym(n)
    dA = shard_csr(A, mesh=mesh, layout="2d-block")
    vols, calls = _spg._summa_volumes_2d(dA, dA, dA.grid)
    counters.reset("comm.")
    C = dist_spgemm(dA, dA)
    assert C.grid == (2, 4) and C.layout == "2d-block"
    for kind, nbytes in vols.items():
        assert counters.get(
            f"comm.dist_spgemm.{kind}_bytes") == nbytes, kind
        assert counters.get(
            f"comm.dist_spgemm.{kind}") == calls[kind], kind
    assert counters.get(
        "comm.layout.2d-block.dist_spgemm_bytes") == sum(vols.values())
    evs = [r for r in obs.records()
           if r["name"] == "dist_spgemm.realization"]
    at = evs[-1]["attrs"]
    assert at["choice"] == "2d-panel"
    assert at["predicted_bytes"] == sum(vols.values())
    # Evidence of the win: the SUMMA panels undercut the recorded 1-D
    # all_gather realization of the same product.
    counters.reset("comm.")
    dA1 = shard_csr(A, mesh=make_row_mesh(), force_all_gather=True)
    _ = dist_spgemm(dA1, dA1)
    bytes_1d = sum(
        v for k, v in counters.snapshot().items()
        if k.startswith("comm.dist_spgemm.") and k.endswith("_bytes"))
    assert at["predicted_all_gather_bytes"] > 0
    assert sum(vols.values()) < bytes_1d


@pytest.mark.slow
@needs_mesh
def test_builders_set_nnz_hint():
    from legate_sparse_tpu.parallel import dist_diags

    mesh = make_row_mesh()
    n = 16 * R
    A = _banded(n)
    dA = shard_csr(A, mesh=mesh)
    assert dA.nnz_hint == A.nnz
    dD = dist_diags([4.0, -1.0, -1.0], [0, 1, -1], shape=(n, n),
                    mesh=mesh, dtype=np.float32)
    assert dD.nnz_hint == 3 * n - 2
    C = dist_spgemm(dA, dA)
    assert C.nnz_hint == C.global_nnz > 0
