# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Systematic complex (c64/c128) coverage (VERDICT r3 #7).

The reference supports complex across its native task families
(reference ``legate_sparse/utils.py:28-33`` SUPPORTED_DATATYPES,
``src/sparse/util/dispatch.h:26-77`` value-type dispatch).  This file
parameterizes the core differential surface — SpMV/SpMM, SpGEMM,
transpose/conjugate, and every native solver — over both complex
dtypes on the CPU lane, plus the mixed real-rhs-on-complex-operator
promotion scipy performs implicitly (which once built mixed-dtype
while_loop carries here).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg

CDTYPES = [np.complex64, np.complex128]


def _tol(dtype):
    return 1e-4 if np.dtype(dtype) == np.complex64 else 1e-10


def _rand_complex(n, m, density, rng, dtype):
    M = (sp.random(n, m, density=density, random_state=rng)
         + 1j * sp.random(n, m, density=density, random_state=rng))
    return sp.csr_array(M).astype(dtype)


@pytest.mark.parametrize("dtype", CDTYPES)
def test_complex_spmv_spmm(dtype):
    rng = np.random.default_rng(1)
    S = _rand_complex(70, 50, 0.1, rng, dtype)
    A = sparse.csr_array(S)
    assert np.dtype(A.dtype) == np.dtype(dtype)
    x = (rng.normal(size=50) + 1j * rng.normal(size=50)).astype(dtype)
    np.testing.assert_allclose(np.asarray(A @ x), S @ x,
                               rtol=_tol(dtype), atol=_tol(dtype))
    X = (rng.normal(size=(50, 6))
         + 1j * rng.normal(size=(50, 6))).astype(dtype)
    np.testing.assert_allclose(np.asarray(A @ X), S @ X,
                               rtol=_tol(dtype), atol=_tol(dtype))
    # rmatvec drives the conjugate-transpose path solvers rely on.
    y = (rng.normal(size=70) + 1j * rng.normal(size=70)).astype(dtype)
    np.testing.assert_allclose(
        np.asarray(A.T.conj() @ y), S.conj().T @ y,
        rtol=_tol(dtype), atol=_tol(dtype))


@pytest.mark.parametrize("dtype", [
    pytest.param(np.complex64, marks=pytest.mark.slow),
    np.complex128,
])
def test_complex_spgemm_and_arithmetic(dtype):
    rng = np.random.default_rng(2)
    S1 = _rand_complex(40, 40, 0.15, rng, dtype)
    S2 = _rand_complex(40, 40, 0.15, rng, dtype)
    A1, A2 = sparse.csr_array(S1), sparse.csr_array(S2)
    C = A1 @ A2
    assert np.dtype(C.dtype) == np.dtype(dtype)
    np.testing.assert_allclose(C.todense(), (S1 @ S2).toarray(),
                               rtol=_tol(dtype), atol=_tol(dtype))
    np.testing.assert_allclose((A1 + A2).todense(),
                               (S1 + S2).toarray(),
                               rtol=_tol(dtype), atol=_tol(dtype))
    np.testing.assert_allclose((A1.multiply(A2)).todense(),
                               (S1.multiply(S2)).toarray(),
                               rtol=_tol(dtype), atol=_tol(dtype))


@pytest.mark.parametrize("dtype", CDTYPES)
@pytest.mark.parametrize("solver", ["cg", "minres"])
def test_complex_hermitian_solvers(dtype, solver):
    # Hermitian positive-definite system: CG/MINRES territory.
    rng = np.random.default_rng(3)
    S = _rand_complex(48, 48, 0.15, rng, np.complex128)
    H = sp.csr_array(S + S.conj().T + 12 * sp.eye(48)).astype(dtype)
    A = sparse.csr_array(H)
    b = (rng.normal(size=48) + 1j * rng.normal(size=48)).astype(dtype)
    tol = 1e-5 if np.dtype(dtype) == np.complex64 else 1e-10
    x, _ = getattr(linalg, solver)(A, b, rtol=tol)
    resid = np.linalg.norm(H @ np.asarray(x) - b) / np.linalg.norm(b)
    assert resid <= 50 * tol, f"{solver} {dtype}: rel resid {resid}"


@pytest.mark.parametrize("dtype", CDTYPES)
@pytest.mark.parametrize("solver", ["gmres", "bicgstab"])
def test_complex_nonsymmetric_solvers(dtype, solver):
    rng = np.random.default_rng(4)
    S = sp.csr_array(
        _rand_complex(48, 48, 0.15, rng, np.complex128)
        + 10 * sp.eye(48)).astype(dtype)
    A = sparse.csr_array(S)
    b = (rng.normal(size=48) + 1j * rng.normal(size=48)).astype(dtype)
    tol = 1e-5 if np.dtype(dtype) == np.complex64 else 1e-10
    x, _ = getattr(linalg, solver)(A, b, rtol=tol)
    resid = np.linalg.norm(S @ np.asarray(x) - b) / np.linalg.norm(b)
    assert resid <= 100 * tol, f"{solver} {dtype}: rel resid {resid}"


@pytest.mark.parametrize("dtype", CDTYPES)
@pytest.mark.parametrize("solver", ["lsqr", "lsmr"])
def test_complex_least_squares(dtype, solver):
    rng = np.random.default_rng(5)
    S = _rand_complex(60, 35, 0.2, rng, dtype)
    A = sparse.csr_array(S)
    b = (rng.normal(size=60) + 1j * rng.normal(size=60)).astype(dtype)
    out = getattr(linalg, solver)(A, b, atol=1e-10, btol=1e-10)
    x = np.asarray(out[0])
    # Compare against scipy's solution of the same problem.
    ref = sp.linalg.lsqr(S, b, atol=1e-10, btol=1e-10)[0]
    np.testing.assert_allclose(
        np.linalg.norm(S @ x - b), np.linalg.norm(S @ ref - b),
        rtol=1e-3 if np.dtype(dtype) == np.complex64 else 1e-6,
        atol=1e-5)


@pytest.mark.parametrize("dtype", CDTYPES)
def test_complex_eigs(dtype):
    rng = np.random.default_rng(6)
    S = _rand_complex(60, 60, 0.15, rng, dtype)
    A = sparse.csr_array(S)
    w, V = linalg.eigs(A, k=3, which="LM")
    resid = np.linalg.norm(S @ V - V * w[None, :], axis=0)
    tol = 1e-3 if np.dtype(dtype) == np.complex64 else 1e-8
    assert np.all(resid <= tol * np.abs(w).max()), resid


def test_complex_eigsh_hermitian():
    rng = np.random.default_rng(7)
    S = _rand_complex(60, 60, 0.15, rng, np.complex128)
    H = sp.csr_array(S + S.conj().T)
    w, V = linalg.eigsh(sparse.csr_array(H), k=3, which="LA")
    assert np.all(np.abs(w.imag) < 1e-12)  # hermitian: real spectrum
    resid = np.linalg.norm(H @ V - V * w.real[None, :], axis=0)
    assert np.all(resid <= 1e-7 * max(1.0, np.abs(w).max())), resid


@pytest.mark.parametrize(
    "solver", ["cg", "gmres", "bicgstab", "minres", "lsqr", "lsmr"])
def test_real_rhs_on_complex_operator_promotes(solver):
    # scipy promotes implicitly; mixed dtypes must neither crash the
    # jitted while_loop carries nor silently cast complex to real.
    rng = np.random.default_rng(8)
    S = _rand_complex(40, 40, 0.2, rng, np.complex128)
    H_s = sp.csr_array(S + S.conj().T + 10 * sp.eye(40))
    A = sparse.csr_array(H_s)
    b = rng.normal(size=40)          # REAL rhs
    out = getattr(linalg, solver)(A, b)
    x = np.asarray(out[0])
    assert np.iscomplexobj(x)
    resid = np.linalg.norm(H_s @ x - b) / np.linalg.norm(b)
    assert resid <= 1e-5, f"{solver}: rel resid {resid}"


@pytest.mark.parametrize("dtype", CDTYPES)
def test_complex_norm_trace_diagonal(dtype):
    rng = np.random.default_rng(9)
    S = _rand_complex(30, 30, 0.3, rng, dtype)
    A = sparse.csr_array(S)
    np.testing.assert_allclose(linalg.norm(A), sp.linalg.norm(S),
                               rtol=_tol(dtype))
    np.testing.assert_allclose(np.asarray(A.trace()), S.trace(),
                               rtol=_tol(dtype), atol=_tol(dtype))
    np.testing.assert_allclose(np.asarray(A.diagonal()), S.diagonal(),
                               rtol=_tol(dtype), atol=_tol(dtype))


def test_differentiable_solve_real_rhs_on_complex_operator():
    # differentiable_solve shares the cg/minres loops; the same
    # promotion must apply (it was missed by the first fix pass).
    from legate_sparse_tpu.krylov_extra import differentiable_solve

    rng = np.random.default_rng(10)
    S = _rand_complex(24, 24, 0.3, rng, np.complex128)
    H_s = sp.csr_array(S + S.conj().T + 8 * sp.eye(24))
    A = sparse.csr_array(H_s)
    b = rng.normal(size=24)
    for method in ("cg", "minres"):
        x = np.asarray(differentiable_solve(A, b, method=method))
        assert np.iscomplexobj(x)
        resid = np.linalg.norm(H_s @ x - b) / np.linalg.norm(b)
        assert resid <= 1e-6, f"{method}: rel resid {resid}"


def test_complex_svds_and_lobpcg():
    # svds runs natively on complex (Gram-operator Lanczos); lobpcg
    # delegates complex Hermitian operators to host scipy (jax's
    # lobpcg_standard builds mixed-dtype carries there).
    rng = np.random.default_rng(11)
    S = _rand_complex(50, 30, 0.3, rng, np.complex128)
    U, s, Vt = linalg.svds(sparse.csr_array(S), k=3)
    ref = np.linalg.svd(S.toarray(), compute_uv=False)
    np.testing.assert_allclose(sorted(s), sorted(ref[:3]), rtol=1e-6)

    H = sp.csr_array(S @ S.conj().T + 5 * sp.eye(50))
    X0 = (rng.normal(size=(50, 3))
          + 1j * rng.normal(size=(50, 3)))
    w, V = linalg.lobpcg(sparse.csr_array(H), X0, maxiter=300)
    ref_w = np.linalg.eigvalsh(H.toarray())[-3:]
    np.testing.assert_allclose(sorted(np.real(w)), sorted(ref_w),
                               rtol=1e-4)


def test_complex_expm_multiply_and_preconditioners():
    # expm_multiply native over complex (incl. mixed real-v), and
    # jacobi/block_jacobi-preconditioned CG on complex Hermitian.
    import scipy.sparse.linalg as ssl

    from legate_sparse_tpu.precond import block_jacobi, jacobi

    rng = np.random.default_rng(12)
    S = _rand_complex(40, 40, 0.2, rng, np.complex128)
    A = sparse.csr_array(S)
    v = rng.normal(size=40) + 1j * rng.normal(size=40)
    np.testing.assert_allclose(
        np.asarray(linalg.expm_multiply(A, v)),
        ssl.expm_multiply(S, v), rtol=1e-8, atol=1e-8)
    np.testing.assert_allclose(
        np.asarray(linalg.expm_multiply(A, v.real)),
        ssl.expm_multiply(S, v.real), rtol=1e-8, atol=1e-8)

    H_s = sp.csr_array(S + S.conj().T + 10 * sp.eye(40))
    H = sparse.csr_array(H_s)
    b = rng.normal(size=40) + 1j * rng.normal(size=40)
    for M in (jacobi(H), block_jacobi(H, block_size=8)):
        x, _ = linalg.cg(H, b, M=M, rtol=1e-10)
        assert np.linalg.norm(H_s @ np.asarray(x) - b) <= 1e-7


def test_complex_distributed_paths():
    # Row-block distribution over complex operands: spmv, CG, SpGEMM
    # on the 8-device mesh (reference supports complex across its
    # distributed task families).
    import jax

    from legate_sparse_tpu.parallel.dist_csr import (
        dist_cg, dist_spmv, shard_csr, shard_vector,
    )
    from legate_sparse_tpu.parallel.dist_spgemm import dist_spgemm
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_row_mesh(devs[:8])
    rng = np.random.default_rng(13)
    n = 96
    S = _rand_complex(n, n, 0.15, rng, np.complex128)
    A = sparse.csr_array(S)
    dA = shard_csr(A, mesh=mesh)
    x = rng.normal(size=n) + 1j * rng.normal(size=n)
    xs = shard_vector(x, mesh, dA.rows_padded)
    np.testing.assert_allclose(
        np.asarray(dist_spmv(dA, xs))[:n], S @ x,
        rtol=1e-10, atol=1e-12)

    H_s = sp.csr_array(S + S.conj().T + 10 * sp.eye(n))
    dH = shard_csr(sparse.csr_array(H_s), mesh=mesh)
    b = rng.normal(size=n) + 1j * rng.normal(size=n)
    sol, _ = dist_cg(dH, b, rtol=1e-10)
    assert np.linalg.norm(
        H_s @ np.asarray(sol).reshape(-1)[:n] - b) <= 1e-7

    C = dist_spgemm(dA, dA).to_csr().toscipy()
    np.testing.assert_allclose(C.toarray(), (S @ S).toarray(),
                               rtol=1e-10, atol=1e-12)
