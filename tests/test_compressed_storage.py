# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Compressed storage (``csr_array.compress`` / ``astype_storage``):
bf16 values + int16 column indices with f32-grade ``.dot`` semantics.

The load-bearing contracts this file pins:

- **representation**: ``compress()`` narrows values to bf16 and
  indices to int16 (when the column extent fits), shares structure,
  keeps ``.dtype`` honest, and ``astype_storage`` widens back
  losslessly (bf16 -> f32 is exact);
- **accuracy, scipy-differential**: every routed precision variant —
  the gather-class ``*_f32acc`` kernels and the DIA shifted-add
  promotion — lands within f32-accumulation distance of float64
  scipy over the *rounded* values (the bf16 rounding is the declared
  loss; the accumulation must not add to it);
- **routed == direct**: an autotune ``*-bf16`` verdict dispatches the
  f32-accumulation kernel bit-for-bit identically to calling it
  directly, and only ``*-bf16`` labels may serve the declared
  bf16/f16 x f32 -> f32 widening;
- **verdict-key separation**: bf16-storage and compressed-index
  verdicts can never replay against f32/int32 storage of the same
  logical matrix;
- **DIA hole-mask trade**: compressed storage drops the hole mask
  (documented IEEE trade — a non-finite operand entry at a band hole
  propagates where canonical f32 storage masks it), f32 storage keeps
  it;
- **npz round-trip**: a compressed matrix checkpoints at its true
  byte size and loads back bit-exact (ISSUE satellite);
- **dist parity**: a sharded compressed matrix against an f32 vector
  honors the same promotion contract as the local ``.dot`` on every
  layout — 1d-row, 1d-col, 2d-block (ISSUE satellite);
- **refine=**: cg/gmres mixed-precision iterative refinement meets
  the unrefined full-precision tolerance, one host fetch per cycle.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import legate_sparse_tpu as lst
from legate_sparse_tpu import autotune, linalg, obs
from legate_sparse_tpu.autotune import key_for
from legate_sparse_tpu.io import load_npz, save_npz
from legate_sparse_tpu.obs import counters, trace
from legate_sparse_tpu.ops import spmv as spmv_ops
from legate_sparse_tpu.parallel import (
    dist_spmv, make_grid_mesh, make_row_mesh, shard_csr,
)
from legate_sparse_tpu.parallel.dist_csr import shard_vector
from legate_sparse_tpu.settings import settings

R = len(jax.devices())
needs_grid = pytest.mark.skipif(R < 8, reason="needs the 8-device mesh")


@pytest.fixture(autouse=True)
def _isolation():
    """Fresh obs state and a clean autotune store around every test;
    autotune off unless the test flips it."""
    saved = settings.autotune
    obs.reset_all()
    trace.disable()
    autotune.reset()
    yield
    settings.autotune = saved
    autotune.reset()
    obs.reset_all()


def _random_csr(n, m=None, density=0.08, seed=0, spd=False):
    m = n if m is None else m
    rng = np.random.default_rng(seed)
    A_sp = sp.random(n, m, density=density, random_state=rng,
                     format="csr", dtype=np.float64)
    if spd:
        A_sp = (A_sp + A_sp.T + 10.0 * sp.eye(n)).tocsr()
    return A_sp.astype(np.float32)


def _holey_tridiag(n=64, hole=10):
    """Tridiagonal with the (hole, hole) main-diagonal slot absent
    from the structure — a holey band (``_get_dia`` builds a mask)."""
    rows, cols, vals = [], [], []
    for i in range(n):
        for j in (i - 1, i, i + 1):
            if 0 <= j < n and not (i == j == hole):
                rows.append(i)
                cols.append(j)
                vals.append(1.0 + 0.01 * i + 0.5 * (i == j))
    A_sp = sp.coo_matrix(
        (np.asarray(vals, np.float32), (rows, cols)),
        shape=(n, n)).tocsr()
    return lst.csr_array(A_sp)


def _scipy_ref(C, x):
    """float64 scipy product over C's *stored* (rounded) values — the
    accuracy referee: the bf16 rounding is the only loss allowed."""
    ref = sp.csr_matrix(
        (np.asarray(C.data).astype(np.float64),
         np.asarray(C.indices).astype(np.int64),
         np.asarray(C.indptr).astype(np.int64)),
        shape=C.shape)
    return ref @ np.asarray(x).astype(np.float64)


# ------------------------------------------------- representation --
def test_compress_defaults_bf16_int16():
    A = lst.csr_array(_random_csr(256, seed=1))
    C = A.compress()
    assert str(C.dtype) == "bfloat16"
    assert np.dtype(C.indices.dtype) == np.int16
    assert C.shape == A.shape and C.nnz == A.nnz
    # The original is untouched (compress returns a new view).
    assert np.dtype(A.dtype) == np.float32
    assert np.dtype(A.indices.dtype) == np.int32
    # Values are exactly the bf16 rounding, indices identical.
    want = np.asarray(jnp.asarray(A.data).astype(jnp.bfloat16))
    assert np.array_equal(np.asarray(C.data).view(np.uint16),
                          want.view(np.uint16))
    np.testing.assert_array_equal(np.asarray(C.indices),
                                  np.asarray(A.indices))
    np.testing.assert_array_equal(np.asarray(C.indptr),
                                  np.asarray(A.indptr))


def test_compress_auto_keeps_int32_when_columns_overflow_int16():
    n_cols = (1 << 15) + 8            # 32776 > int16 max
    A = lst.csr_array(_random_csr(8, n_cols, density=0.01, seed=2))
    C = A.compress()
    assert str(C.dtype) == "bfloat16"
    assert np.dtype(C.indices.dtype) == np.int32


def test_compress_rejects_bad_storage_dtypes():
    A = lst.csr_array(_random_csr(64))
    with pytest.raises(ValueError, match="overflows"):
        lst.csr_array(_random_csr(8, (1 << 15) + 8, density=0.01)
                      ).compress(indices="int16")
    with pytest.raises(ValueError, match="signed integer"):
        A.compress(indices="float32")
    with pytest.raises(NotImplementedError, match="not supported"):
        A.compress(values="float16")


def test_astype_storage_widens_back_exactly():
    A = lst.csr_array(_random_csr(128, seed=3))
    C = A.compress()
    W = C.astype_storage(values="float32", indices="int32")
    assert np.dtype(W.dtype) == np.float32
    assert np.dtype(W.indices.dtype) == np.int32
    # bf16 -> f32 is exact: widening restores the rounded values
    # bit-for-bit as f32.
    want = np.asarray(jnp.asarray(C.data).astype(jnp.float32))
    assert np.array_equal(np.asarray(W.data), want)
    # Keep-by-default: no arguments is a representation no-op.
    K = C.astype_storage()
    assert str(K.dtype) == "bfloat16"
    assert np.dtype(K.indices.dtype) == np.int16


# ------------------------------------- accuracy, scipy-differential --
@pytest.mark.parametrize("structure", ["uniform", "powerlaw", "banded"])
def test_lowp_spmv_scipy_differential(structure):
    if structure == "banded":
        A_sp = sp.diags(
            [np.linspace(0.5, 1.5, 255), np.linspace(2.0, 3.0, 256),
             np.linspace(-1.0, 1.0, 255)],
            [-1, 0, 1]).tocsr().astype(np.float32)
        A = lst.csr_array(A_sp)
    elif structure == "powerlaw":
        from legate_sparse_tpu import gallery
        A = gallery.powerlaw(256, nnz_per_row=4, rng=5,
                             dtype=np.float32)
        A.sum_duplicates()
    else:
        A = lst.csr_array(_random_csr(256, density=0.05, seed=4))
    C = A.compress()
    x = jnp.asarray(np.linspace(-1.0, 1.0, 256), jnp.float32)
    y = C @ x
    # Promotion contract: bf16 storage x f32 operand -> f32 out.
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y), _scipy_ref(C, x),
                               rtol=1e-4, atol=1e-5)


def test_lowp_spmm_scipy_differential():
    A = lst.csr_array(_random_csr(192, density=0.06, seed=6))
    C = A.compress()
    X = jnp.asarray(
        np.linspace(-1.0, 1.0, 192 * 3).reshape(192, 3), jnp.float32)
    Y = C @ X
    assert Y.dtype == jnp.float32 and Y.shape == (192, 3)
    ref = np.stack([_scipy_ref(C, X[:, j]) for j in range(3)], axis=1)
    np.testing.assert_allclose(np.asarray(Y), ref,
                               rtol=1e-4, atol=1e-5)


def test_same_dtype_bf16_spmv_stays_bf16():
    A = lst.csr_array(_random_csr(128, density=0.08, seed=7))
    C = A.compress()
    x = jnp.asarray(np.linspace(0.1, 1.0, 128), jnp.bfloat16)
    y = C @ x
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y).astype(np.float64),
        _scipy_ref(C, np.asarray(x).astype(np.float32)),
        rtol=0.05, atol=0.05)


# ------------------------------------------------ DIA mask trade --
def test_compressed_dia_drops_mask_f32_keeps_it():
    A = _holey_tridiag()
    dia_f32 = A._get_dia()
    assert dia_f32 is not None and dia_f32[2] is not None
    C = A.compress()
    dia_c = C._get_dia()
    assert dia_c is not None and dia_c[2] is None
    # f32 values + compressed indices alone keep the mask: the trade
    # is declared by the *value* narrowing only.
    N = A.astype_storage(indices="int16")
    dia_n = N._get_dia()
    assert dia_n is not None and dia_n[2] is not None


def test_compressed_dia_nonfinite_hole_trade():
    hole = 10
    A = _holey_tridiag(hole=hole)
    n = A.shape[0]
    x = np.linspace(0.5, 1.5, n).astype(np.float32)
    x[hole] = np.inf
    xj = jnp.asarray(x)
    # Canonical f32 storage: the mask guards the hole — row `hole`
    # (whose only structural entries are off-diagonal) stays finite.
    y_f32 = np.asarray(A @ xj)
    assert np.isfinite(y_f32[hole])
    # Compressed storage: the zero-filled hole multiplies inf -> NaN.
    # This is the documented opt-in IEEE trade.
    y_c = np.asarray(A.compress() @ xj)
    assert np.isnan(y_c[hole])


def test_compressed_dia_finite_parity():
    A = _holey_tridiag()
    C = A.compress()
    x = jnp.asarray(np.linspace(-2.0, 2.0, A.shape[0]), jnp.float32)
    y = C @ x
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y), _scipy_ref(C, x),
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------ autotune routing --
def test_verdict_key_separates_storage():
    A = lst.csr_array(_random_csr(256, seed=8))
    C = A.compress()
    kf = key_for(A, "spmv")
    kc = key_for(C, "spmv")
    assert kf is not None and kc is not None
    assert kf.dtype == "float32" and kf.storage == ""
    assert kc.dtype == "bfloat16" and kc.storage == "i16"
    assert "/si16@" in kc.key_id and "/si16@" not in kf.key_id
    assert kf != kc
    store = autotune.get_store()
    store.record(kc, "csr-rowids-bf16", timings_ms={}, trials=1)
    assert store.lookup(kc) is not None
    # A bf16-storage verdict never replays against f32 storage.
    assert store.lookup(kf) is None


@pytest.mark.parametrize("label,structure", [
    ("csr-rowids-bf16", "uniform"),
    ("ell-bf16", "uniform"),
    pytest.param("sliced-ell-bf16", "powerlaw",
                 marks=pytest.mark.slow),
])
def test_routed_bf16_verdict_is_bitwise_direct(label, structure):
    if structure == "powerlaw":
        from legate_sparse_tpu import gallery
        A = gallery.powerlaw(256, nnz_per_row=4, rng=9,
                             dtype=np.float32)
        A.sum_duplicates()
    else:
        A = lst.csr_array(_random_csr(256, density=0.05, seed=9))
    C = A.compress()
    x = jnp.asarray(np.linspace(-1.0, 1.0, 256), jnp.float32)
    key = key_for(C, "spmv")
    settings.autotune = True
    autotune.get_store().record(key, label, timings_ms={}, trials=1)
    hits0 = counters.get("autotune.route.hits")
    y = C @ x
    assert counters.get("autotune.route.hits") == hits0 + 1
    assert counters.get("autotune.route." + label) >= 1
    if label == "csr-rowids-bf16":
        y_direct = spmv_ops.csr_spmv_rowids_f32acc(
            C.data, C.indices, C._get_row_ids(), x, C.shape[0])
    elif label == "ell-bf16":
        ell = C._get_ell()
        assert ell is not None
        y_direct = spmv_ops.ell_spmv_f32acc(ell[0], ell[1], ell[2], x)
    else:
        bins = C._get_sliced_ell()
        assert bins is not None
        y_direct = spmv_ops.sliced_ell_spmv_f32acc(bins, x, C.shape[0])
    # Routed == direct: same jitted entry point, bit-for-bit.
    assert y.dtype == y_direct.dtype == jnp.float32
    assert np.array_equal(np.asarray(y), np.asarray(y_direct))


def test_widening_declines_non_bf16_verdicts():
    A = lst.csr_array(_random_csr(256, density=0.05, seed=10))
    C = A.compress()
    x = jnp.asarray(np.linspace(-1.0, 1.0, 256), jnp.float32)
    settings.autotune = True
    # A plain-family verdict must not serve the widening: its output
    # dtype under promotion is not pinned by construction.
    autotune.get_store().record(
        key_for(C, "spmv"), "csr-rowids", timings_ms={}, trials=1)
    declines0 = counters.get("autotune.route.decline")
    hits0 = counters.get("autotune.route.hits")
    y = C @ x
    assert counters.get("autotune.route.decline") > declines0
    assert counters.get("autotune.route.hits") == hits0
    # The heuristic lowp chain still serves correctly.
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y), _scipy_ref(C, x),
                               rtol=1e-4, atol=1e-5)


def test_routed_spmm_bf16_bitwise_direct():
    A = lst.csr_array(_random_csr(192, density=0.06, seed=11))
    C = A.compress()
    X = jnp.asarray(
        np.linspace(-1.0, 1.0, 192 * 4).reshape(192, 4), jnp.float32)
    settings.autotune = True
    autotune.get_store().record(
        key_for(C, "spmm", k=4), "csr-rowids-bf16",
        timings_ms={}, trials=1)
    Y = C @ X
    Y_direct = spmv_ops.csr_spmm_rowids_f32acc(
        C.data, C.indices, C._get_row_ids(), X, C.shape[0])
    assert Y.dtype == Y_direct.dtype == jnp.float32
    assert np.array_equal(np.asarray(Y), np.asarray(Y_direct))


# ------------------------------------------------- npz round-trip --
def test_npz_roundtrip_bf16_int16_bit_exact(tmp_path):
    A = lst.csr_array(_random_csr(200, seed=12))
    C = A.compress()
    path = str(tmp_path / "compressed.npz")
    save_npz(path, C)
    L = load_npz(path)
    # Storage dtypes survive the container.
    assert str(L.dtype) == "bfloat16"
    assert np.dtype(L.indices.dtype) == np.int16
    assert L.shape == C.shape and L.nnz == C.nnz
    # Bit-exact values: compare the raw 16-bit patterns.
    assert np.array_equal(np.asarray(L.data).view(np.uint16),
                          np.asarray(C.data).view(np.uint16))
    np.testing.assert_array_equal(np.asarray(L.indices),
                                  np.asarray(C.indices))
    np.testing.assert_array_equal(np.asarray(L.indptr),
                                  np.asarray(C.indptr))
    # The loaded matrix dispatches the same lowp kernels bit-for-bit.
    x = jnp.asarray(np.linspace(-1.0, 1.0, 200), jnp.float32)
    assert np.array_equal(np.asarray(L @ x), np.asarray(C @ x))


# ------------------------------------------------------ dist parity --
@needs_grid
@pytest.mark.parametrize("layout", ["1d-row", "1d-col", "2d-block"])
def test_dist_lowp_parity_matches_local_dot(layout):
    n = 96
    A = lst.csr_array(_random_csr(n, density=0.08, seed=13))
    C = A.compress()
    x = jnp.asarray(np.linspace(-1.0, 1.0, n), jnp.float32)
    y_local = np.asarray(C @ x)
    mesh = (make_grid_mesh(2, 4) if layout == "2d-block"
            else make_row_mesh())
    dC = shard_csr(C, mesh=mesh, layout=layout)
    xs = shard_vector(x, dC.mesh, dC.rows_padded, layout=dC.layout)
    y = dist_spmv(dC, xs)
    # Same promotion contract as the local dot: f32 out.
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y)[:n], y_local,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y)[:n], _scipy_ref(C, x),
                               rtol=1e-4, atol=1e-5)


@needs_grid
def test_dist_2d_block_carries_int16_cols():
    A = lst.csr_array(_random_csr(96, density=0.08, seed=14))
    dC = shard_csr(A.compress(), mesh=make_grid_mesh(2, 4),
                   layout="2d-block")
    # Block-local columns live in [0, cps): int16 end-to-end.
    assert np.dtype(dC.cols.dtype) == np.int16
    assert str(np.dtype(dC.data.dtype)) == "bfloat16"


# -------------------------------------------------------- refine= --
def test_cg_refine_auto_meets_f32_tolerance():
    n = 120
    A = lst.csr_array(_random_csr(n, density=0.05, seed=15, spd=True))
    b = jnp.asarray(np.linspace(0.5, 1.5, n), jnp.float32)
    rtol = 1e-6
    atol = rtol * float(jnp.linalg.norm(b))
    x, iters = linalg.cg(A, b, rtol=rtol, atol=0.0, refine="auto")
    resid = float(jnp.linalg.norm(b - A @ x))
    assert resid <= atol * 1.05
    assert iters > 0
    # One stacked host fetch per refinement cycle, counted.
    assert counters.get("transfer.host_sync.cg_refine") >= 1


def test_cg_refine_f64_system_uses_f32_inner():
    n = 120
    A_sp = _random_csr(n, density=0.05, seed=16, spd=True).astype(
        np.float64)
    A = lst.csr_array(A_sp)
    b = jnp.asarray(np.linspace(0.5, 1.5, n), jnp.float64)
    rtol = 1e-10
    x, _ = linalg.cg(A, b, rtol=rtol, atol=0.0, refine="auto")
    resid = float(jnp.linalg.norm(b - A @ x))
    assert resid <= rtol * float(jnp.linalg.norm(b)) * 1.05
    # The inner rung for f64 is f32 storage, one precision down.
    inner = linalg._refine_inner_operator(A)
    assert np.dtype(inner.dtype) == np.float32


def test_gmres_refine_auto_meets_tolerance():
    n = 80
    rng = np.random.default_rng(17)
    A_sp = sp.random(n, n, density=0.08, random_state=rng,
                     format="csr", dtype=np.float64)
    A_sp = (A_sp + 12.0 * sp.eye(n)).tocsr().astype(np.float32)
    A = lst.csr_array(A_sp)
    b = jnp.asarray(np.linspace(0.5, 1.5, n), jnp.float32)
    rtol = 1e-6
    x, _ = linalg.gmres(A, b, rtol=rtol, atol=0.0, refine="auto")
    resid = float(jnp.linalg.norm(b - A @ x))
    assert resid <= rtol * float(jnp.linalg.norm(b)) * 1.05
    assert counters.get("transfer.host_sync.gmres_refine") >= 1


def test_refine_rejects_bad_compositions():
    n = 32
    A = lst.csr_array(_random_csr(n, density=0.2, seed=18, spd=True))
    b = np.ones(n, np.float32)
    with pytest.raises(ValueError, match="composes with neither"):
        linalg.cg(A, b, refine="auto", M=sp.eye(n).tocsr())
    with pytest.raises(ValueError, match="composes with neither"):
        linalg.gmres(A, b, refine="auto", callback=lambda x: None)
    with pytest.raises(ValueError, match="positive cycle count"):
        linalg.cg(A, b, refine=0)
    # Already-low-precision storage has no rung below it.
    with pytest.raises(ValueError, match="float32/float64"):
        linalg.cg(A.compress(), b, refine="auto")
    # Dense operands have no compressed inner representation.
    with pytest.raises(ValueError, match="sparse-matrix operand"):
        linalg.cg(np.eye(n, dtype=np.float32), b, refine="auto")
