# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""coo_array differential tests vs scipy."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.fixture
def pair(rng):
    A_sp = scsp.random(30, 40, density=0.15, random_state=0,
                       format="coo", dtype=np.float64)
    return sparse.coo_array(A_sp), A_sp


def test_roundtrips(pair):
    A, A_sp = pair
    assert A.shape == A_sp.shape and A.nnz == A_sp.nnz
    np.testing.assert_allclose(A.toarray(), A_sp.toarray())
    np.testing.assert_allclose(A.toscipy().toarray(), A_sp.toarray())
    np.testing.assert_allclose(A.tocsr().toscipy().toarray(),
                               A_sp.tocsr().toarray())
    np.testing.assert_allclose(A.tocsc().toarray(), A_sp.toarray())


def test_from_ijv_and_duplicates():
    A = sparse.coo_array(
        (np.array([1.0, 2.0, 3.0]),
         (np.array([0, 0, 1]), np.array([2, 2, 0]))),
        shape=(3, 4),
    )
    assert A.nnz == 3
    A.sum_duplicates()
    assert A.nnz == 2
    dense = np.zeros((3, 4))
    dense[0, 2] = 3.0
    dense[1, 0] = 3.0
    np.testing.assert_allclose(A.toarray(), dense)


def test_matvec_and_transpose(pair, rng):
    A, A_sp = pair
    x = rng.standard_normal(40)
    np.testing.assert_allclose(np.asarray(A @ x), A_sp @ x, rtol=1e-10)
    np.testing.assert_allclose(A.T.toarray(), A_sp.T.toarray())
    np.testing.assert_allclose((2.0 * A).toarray(), 2 * A_sp.toarray())


def test_predicates_and_asformat(pair):
    A, _ = pair
    assert sparse.issparse(A)
    assert sparse.isspmatrix_coo(A)
    assert A.asformat("csr").format == "csr"
    assert A.tocsr().asformat("coo").format == "coo"
    from legate_sparse_tpu import linalg

    op = linalg.make_linear_operator(A) if hasattr(
        linalg, "make_linear_operator") else None


def test_solver_accepts_coo(rng):
    from legate_sparse_tpu import linalg

    n = 60
    A_sp = (scsp.random(n, n, density=0.2, random_state=1)
            + scsp.eye(n) * n).tocoo()
    A_sp = ((A_sp + A_sp.T) / 2).tocoo()
    A = sparse.coo_array(A_sp)
    b = rng.standard_normal(n)
    x, it = linalg.cg(A, b, rtol=1e-8, maxiter=400)
    np.testing.assert_allclose(np.asarray(A @ np.asarray(x)), b,
                               rtol=1e-5, atol=1e-6)


def test_coo_from_other_formats(pair):
    A, A_sp = pair
    C1 = sparse.coo_array(sparse.csc_array(A_sp.tocsc()))
    np.testing.assert_allclose(C1.toarray(), A_sp.toarray())
    C2 = sparse.coo_array(A.tocsr().todia()) if hasattr(
        A.tocsr(), "todia") else None
    assert A.ndim == 2
