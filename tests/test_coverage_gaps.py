# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Coverage for round-1 verdict gaps: coord-dtype promotion wiring,
empty-matrix SpGEMM-through-solver, distributed IEEE masking, and the
blown-halo -> precise-image fallback."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu import linalg
from legate_sparse_tpu.types import coord_dtype_for, coord_ty, wide_coord_ty
from legate_sparse_tpu.parallel import make_row_mesh, shard_csr, dist_spmv
from legate_sparse_tpu.parallel.dist_csr import shard_vector

needs_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


def test_coord_dtype_for_boundaries():
    imax = np.iinfo(np.int32).max
    assert coord_dtype_for(0) == coord_ty
    assert coord_dtype_for(imax) == coord_ty
    assert coord_dtype_for(imax + 1) == wide_coord_ty


def test_coord_dtype_wiring_through_constructors():
    """Constructors must pick the index dtype from the matrix extent
    (the int32-local / int64-global split of SURVEY hard part #5); the
    >2^31 branch can't be exercised at test scale, so the wiring is
    unit-tested at the dtype-selection seam."""
    A = sparse.csr_array(
        (np.ones(2), (np.array([0, 1]), np.array([0, 1]))), shape=(4, 4)
    )
    assert A.indices.dtype == coord_ty

    # Simulate the huge-extent decision the ctor applies.
    big = int(np.iinfo(np.int32).max) + 10
    assert coord_dtype_for(big) == np.int64


def test_empty_spgemm_through_solver():
    """C = A @ B with nnz(C) = 0, then solve against C + I — the
    empty-product path must produce a structurally valid csr_array."""
    n = 16
    A = sparse.csr_array(sp.csr_matrix((n, n)))
    B = sparse.csr_array(sp.csr_matrix((n, n)))
    C = A @ B
    assert C.nnz == 0
    assert np.asarray(C.indptr).shape == (n + 1,)
    eye = sparse.csr_array(sp.eye(n, format="csr"))
    S = C + eye
    x, iters = linalg.cg(S, np.ones(n), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(x), np.ones(n), rtol=1e-10)


@needs_multi
def test_distributed_nonfinite_x_masking():
    """Padded slots must contribute an exact zero even when x carries
    non-finite values (0*inf must not inject NaN) — in BOTH distributed
    layouts (the single-chip invariant tested in
    test_review_regressions)."""
    n = 40
    mesh = make_row_mesh()
    # ELL layout (banded).
    A = sparse.diags([1.0, 2.0, 1.0], [-1, 0, 1], shape=(n, n),
                     format="csr", dtype=np.float64)
    dA = shard_csr(A, mesh=mesh)
    assert dA.ell
    # Padded-CSR layout (skewed rows defeat the budget).
    B_l = sp.diags([np.ones(n)], [0]).tolil()
    B_l[0, :] = 1.0
    B_sp = B_l.tocsr()
    dB = shard_csr(sparse.csr_array(B_sp), mesh=mesh,
                   force_all_gather=True)
    assert not dB.ell

    x = np.ones(n)
    x[-1] = np.inf     # the inf entry is genuinely referenced...
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    # ...so rows touching it are inf, every other row stays finite.
    assert np.all(np.isinf(y[-2:]))
    assert np.all(np.isfinite(y[:-2]))

    yb = np.asarray(dist_spmv(dB, xs))[:n]
    assert np.isinf(yb[0]) and np.isinf(yb[-1])
    assert np.all(np.isfinite(yb[1:-1]))


@needs_multi
def test_blown_halo_falls_back_to_precise_not_all_gather():
    """One long-range row must not force a full x realization for every
    shard (VERDICT r1 item 8): shard_csr auto-upgrades to the precise
    all_to_all plan when the max-window is blown."""
    n = 256
    A = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(n, n)).tolil()
    A[1, n - 1] = 5.0
    A_sp = A.tocsr()
    mesh = make_row_mesh()
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh)
    R = len(mesh.devices)
    assert dA.gather_idx is not None, "expected precise fallback"
    C = dA.gather_idx.shape[-1]
    assert R * C + dA.cols_per_shard < dA.rows_padded
    x = np.linspace(0, 1, n)
    xs = shard_vector(x, mesh, dA.rows_padded)
    np.testing.assert_allclose(
        np.asarray(dist_spmv(dA, xs))[:n], A_sp @ x, rtol=1e-12,
        atol=1e-12,
    )


def test_init_distributed_idempotent(monkeypatch):
    from legate_sparse_tpu.parallel import mesh as mesh_mod

    calls = []
    monkeypatch.setattr(
        "jax.distributed.initialize", lambda **kw: calls.append(kw)
    )
    monkeypatch.setattr(mesh_mod.init_distributed, "_done", False,
                        raising=False)
    mesh_mod.init_distributed(coordinator_address="host:1234",
                              num_processes=2, process_id=0)
    mesh_mod.init_distributed()  # second call is a no-op
    assert len(calls) == 1
    assert calls[0]["coordinator_address"] == "host:1234"


def test_sparse_norm_matches_scipy(rng):
    import scipy.sparse as scsp
    import scipy.sparse.linalg as ssl

    import legate_sparse_tpu as sparse
    from legate_sparse_tpu import linalg

    A_sp = scsp.random(20, 15, density=0.3, random_state=0, format="csr")
    A_sp.data -= 0.5
    A = sparse.csr_array(A_sp)
    for order in (None, "fro", 1, -1, np.inf, -np.inf):
        np.testing.assert_allclose(
            linalg.norm(A, ord=order), ssl.norm(A_sp, ord=order),
            rtol=1e-12,
        )
    for axis in (0, 1):
        for order in (None, 1, np.inf):
            np.testing.assert_allclose(
                np.asarray(linalg.norm(A, ord=order, axis=axis)),
                ssl.norm(A_sp, ord=order, axis=axis),
                rtol=1e-6,
            )
    with pytest.raises(ValueError):
        linalg.norm(A, ord=0)
    with pytest.raises(TypeError):
        linalg.norm(np.ones((3, 3)))


def test_sparse_norm_spectral_and_zero_size():
    import scipy.sparse as scsp
    import scipy.sparse.linalg as ssl

    import legate_sparse_tpu as sparse
    from legate_sparse_tpu import linalg

    A_sp = scsp.random(12, 12, density=0.4, random_state=2, format="csr")
    A = sparse.csr_array(A_sp)
    np.testing.assert_allclose(linalg.norm(A, ord=2),
                               ssl.norm(A_sp, ord=2), rtol=1e-9)
    empty = sparse.csr_array(
        (np.zeros(0), np.zeros(0, np.int32), np.zeros(6, np.int64)),
        shape=(5, 0),
    )
    with pytest.raises(ValueError):
        linalg.norm(empty)
