# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""csc_array differential tests vs scipy."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.fixture
def pair(rng):
    A_sp = scsp.random(40, 30, density=0.2, random_state=0,
                       format="csc", dtype=np.float64)
    return sparse.csc_array(A_sp), A_sp


def test_from_scipy_roundtrip(pair):
    A, A_sp = pair
    assert A.shape == A_sp.shape
    assert A.nnz == A_sp.nnz
    np.testing.assert_allclose(A.toarray(), A_sp.toarray())
    np.testing.assert_allclose(A.toscipy().toarray(), A_sp.toarray())


def test_from_triple():
    A_sp = scsp.random(20, 25, density=0.3, random_state=1,
                       format="csc", dtype=np.float64)
    A = sparse.csc_array((A_sp.data, A_sp.indices, A_sp.indptr),
                         shape=A_sp.shape)
    np.testing.assert_allclose(A.toarray(), A_sp.toarray())


def test_matvec_and_matmat(pair, rng):
    A, A_sp = pair
    x = rng.standard_normal(30)
    np.testing.assert_allclose(np.asarray(A @ x), A_sp @ x, rtol=1e-10)
    X = rng.standard_normal((30, 4))
    np.testing.assert_allclose(np.asarray(A @ X), A_sp @ X, rtol=1e-10)


def test_spgemm_mixed_formats(pair, rng):
    A, A_sp = pair
    B_sp = scsp.random(30, 20, density=0.2, random_state=2,
                       format="csr", dtype=np.float64)
    B = sparse.csr_array(B_sp)
    C = A @ B                      # csc @ csr
    np.testing.assert_allclose(C.toscipy().toarray(),
                               (A_sp @ B_sp).toarray(), rtol=1e-10)
    D = B.T @ A.T                  # csr @ csc-transpose interop
    np.testing.assert_allclose(D.toscipy().toarray(),
                               (B_sp.T @ A_sp.T).toarray(), rtol=1e-10)


def test_transpose_and_diagonal(pair):
    A, A_sp = pair
    np.testing.assert_allclose(A.T.toscipy().toarray(),
                               A_sp.T.toarray())
    for k in (-2, 0, 3):
        np.testing.assert_allclose(np.asarray(A.diagonal(k)),
                                   A_sp.diagonal(k))


def test_sum_axes(pair):
    A, A_sp = pair
    np.testing.assert_allclose(float(A.sum()), A_sp.sum())
    np.testing.assert_allclose(np.asarray(A.sum(axis=0)).ravel(),
                               np.asarray(A_sp.sum(axis=0)).ravel())
    np.testing.assert_allclose(np.asarray(A.sum(axis=1)).ravel(),
                               np.asarray(A_sp.sum(axis=1)).ravel())


def test_format_conversions(pair):
    A, A_sp = pair
    assert sparse.issparse(A)
    assert sparse.isspmatrix_csc(A)
    R = A.tocsr()
    assert sparse.isspmatrix_csr(R)
    np.testing.assert_allclose(R.toscipy().toarray(), A_sp.toarray())
    A2 = R.tocsc()
    assert sparse.isspmatrix_csc(A2)
    np.testing.assert_allclose(A2.toarray(), A_sp.toarray())
    assert R.asformat("csc").shape == A.shape


def test_scalar_ops(pair):
    A, A_sp = pair
    np.testing.assert_allclose((2.0 * A).toarray(), 2.0 * A_sp.toarray())
    np.testing.assert_allclose((-A).toarray(), -A_sp.toarray())
    np.testing.assert_allclose(A.astype(np.float32).toarray(),
                               A_sp.toarray().astype(np.float32),
                               rtol=1e-6)


def test_cg_accepts_csc(pair, rng):
    # Shared is_sparse_matrix must classify csc as sparse, else linalg
    # wraps it as a dense operator and crashes.
    import scipy.sparse as sp
    from legate_sparse_tpu import linalg

    n = 80
    A_sp = (sp.random(n, n, density=0.2, random_state=3)
            + sp.eye(n) * n).tocsc()
    A_sp = (A_sp + A_sp.T) / 2
    A = sparse.csc_array(A_sp)
    b = rng.standard_normal(n)
    x, it = linalg.cg(A, b, rtol=1e-8, maxiter=500)
    np.testing.assert_allclose(
        np.asarray(A @ np.asarray(x)), b, rtol=1e-5, atol=1e-6
    )


def test_spgemm_scipy_operand(pair):
    A, A_sp = pair
    B_sp = scsp.random(30, 10, density=0.3, random_state=5)
    C = A.tocsr() @ B_sp.tocsc()   # scipy csc operand
    np.testing.assert_allclose(C.toscipy().toarray(),
                               (A_sp @ B_sp).toarray(), rtol=1e-10)


def test_transpose_mutation_does_not_alias(pair):
    A, A_sp = pair
    B = A.T
    before = A.nnz
    B.data = B.data.at[:].set(0.0) if hasattr(B.data, "at") else B.data
    B.eliminate_zeros()
    assert A.nnz == before  # A unchanged by mutating its transpose


def test_ctor_dtype_applies_to_csr_input(pair):
    A, _ = pair
    C = sparse.csc_array(A.tocsr(), dtype=np.float32)
    assert C.dtype == np.float32


def test_elementwise_mul_vector(pair):
    A, A_sp = pair
    got = A * np.ones(A.shape[1])
    want = scsp.csc_array(A_sp) * np.ones(A.shape[1])
    want = want.toarray() if hasattr(want, "toarray") else want
    np.testing.assert_allclose(np.asarray(got.toarray()), want)


def test_tocsr_cached_and_isolated(pair):
    A, A_sp = pair
    R1 = A.tocsr()
    R2 = A.tocsr()
    assert R1 is not R2
    R1.sum_duplicates()
    np.testing.assert_allclose(R2.toscipy().toarray(), A_sp.toarray())
