# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""csgraph facade: native device algorithms + adapted fallbacks.

The reference has no graph surface (SURVEY §2); scipy.sparse.csgraph
is part of the drop-in story, so the namespace must take package
arrays.  Differential tests vs host scipy.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as scsg

import legate_sparse_tpu as sparse


def _graph(n=200, density=0.01, seed=0, sym=True):
    rng = np.random.default_rng(seed)
    E = sp.random(n, n, density=density, format="csr", random_state=rng)
    if sym:
        E = ((E + E.T) > 0).astype(np.float64)
    else:
        E = (E > 0).astype(np.float64)
    return E.tocsr(), sparse.csr_array(E.tocsr())


def test_connected_components_undirected():
    E, A = _graph()
    k, labels = sparse.csgraph.connected_components(A, directed=False)
    k_ref, l_ref = scsg.connected_components(E, directed=False)
    assert k == k_ref
    np.testing.assert_array_equal(labels, l_ref)


def test_connected_components_weak_and_strong():
    E, A = _graph(density=0.008, sym=False)
    for connection in ("weak", "strong"):
        k, labels = sparse.csgraph.connected_components(
            A, directed=True, connection=connection)
        k_ref, l_ref = scsg.connected_components(
            E, directed=True, connection=connection)
        assert k == k_ref
        np.testing.assert_array_equal(labels, l_ref)


def test_connected_components_count_only_and_isolated():
    # Two explicit components + an isolated node.
    rows = np.array([0, 1, 3, 4])
    cols = np.array([1, 0, 4, 3])
    A = sparse.csr_array((np.ones(4), (rows, cols)), shape=(6, 6))
    k = sparse.csgraph.connected_components(A, directed=False,
                                            return_labels=False)
    assert k == 4   # {0,1}, {3,4}, {2}, {5}


@pytest.mark.parametrize("kw", [
    {}, {"normed": True}, {"use_out_degree": True},
    {"symmetrized": True}, {"dtype": np.float32},
])
def test_laplacian_matches_scipy(kw):
    # Asymmetric graph: row sums != column sums, so a swapped degree
    # axis (in- vs out-degree) cannot slip through.
    E, A = _graph(seed=1, density=0.02, sym=False)
    got = sparse.csgraph.laplacian(A, return_diag=True, **kw)
    ref = scsg.laplacian(E, return_diag=True, **kw)
    np.testing.assert_allclose(got[0].toarray(), ref[0].toarray(),
                               atol=1e-6)
    np.testing.assert_allclose(got[1], ref[1], atol=1e-6)


def test_laplacian_self_loops():
    # Degrees exclude self-loops; diagonal is overwritten (scipy
    # ``_laplacian_sparse`` semantics).
    E, _ = _graph(n=60, seed=2)
    S = (E + 3.0 * sp.eye(60)).tocsr()
    A = sparse.csr_array(S)
    for kw in ({}, {"normed": True}):
        got = sparse.csgraph.laplacian(A, return_diag=True, **kw)
        ref = scsg.laplacian(S, return_diag=True, **kw)
        np.testing.assert_allclose(got[0].toarray(), ref[0].toarray(),
                                   atol=1e-12)
        np.testing.assert_allclose(got[1], ref[1])


def test_fallbacks_take_package_arrays():
    # scipy's csgraph Cython is int32-indexed; the boundary narrows
    # our int64 indices (raw scipy rejects int64 outright).
    E, A = _graph(seed=3)
    np.testing.assert_allclose(
        sparse.csgraph.minimum_spanning_tree(A).toarray(),
        scsg.minimum_spanning_tree(E).toarray())
    np.testing.assert_allclose(
        sparse.csgraph.dijkstra(A, indices=[0, 5]),
        scsg.dijkstra(E, indices=[0, 5]))
    np.testing.assert_allclose(
        sparse.csgraph.shortest_path(A, method="D", unweighted=True),
        scsg.shortest_path(E, method="D", unweighted=True))
