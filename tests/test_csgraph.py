# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""csgraph facade: native device algorithms + adapted fallbacks.

The reference has no graph surface (SURVEY §2); scipy.sparse.csgraph
is part of the drop-in story, so the namespace must take package
arrays.  Differential tests vs host scipy.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as scsg

import legate_sparse_tpu as sparse


def _graph(n=200, density=0.01, seed=0, sym=True):
    rng = np.random.default_rng(seed)
    E = sp.random(n, n, density=density, format="csr", random_state=rng)
    if sym:
        E = ((E + E.T) > 0).astype(np.float64)
    else:
        E = (E > 0).astype(np.float64)
    return E.tocsr(), sparse.csr_array(E.tocsr())


def test_connected_components_undirected():
    E, A = _graph()
    k, labels = sparse.csgraph.connected_components(A, directed=False)
    k_ref, l_ref = scsg.connected_components(E, directed=False)
    assert k == k_ref
    np.testing.assert_array_equal(labels, l_ref)


def test_connected_components_weak_and_strong():
    E, A = _graph(density=0.008, sym=False)
    for connection in ("weak", "strong"):
        k, labels = sparse.csgraph.connected_components(
            A, directed=True, connection=connection)
        k_ref, l_ref = scsg.connected_components(
            E, directed=True, connection=connection)
        assert k == k_ref
        np.testing.assert_array_equal(labels, l_ref)


def test_connected_components_count_only_and_isolated():
    # Two explicit components + an isolated node.
    rows = np.array([0, 1, 3, 4])
    cols = np.array([1, 0, 4, 3])
    A = sparse.csr_array((np.ones(4), (rows, cols)), shape=(6, 6))
    k = sparse.csgraph.connected_components(A, directed=False,
                                            return_labels=False)
    assert k == 4   # {0,1}, {3,4}, {2}, {5}


@pytest.mark.parametrize("kw", [
    {}, {"normed": True}, {"use_out_degree": True},
    {"symmetrized": True}, {"dtype": np.float32},
])
def test_laplacian_matches_scipy(kw):
    # Asymmetric graph: row sums != column sums, so a swapped degree
    # axis (in- vs out-degree) cannot slip through.
    E, A = _graph(seed=1, density=0.02, sym=False)
    got = sparse.csgraph.laplacian(A, return_diag=True, **kw)
    ref = scsg.laplacian(E, return_diag=True, **kw)
    np.testing.assert_allclose(got[0].toarray(), ref[0].toarray(),
                               atol=1e-6)
    np.testing.assert_allclose(got[1], ref[1], atol=1e-6)


def test_laplacian_self_loops():
    # Degrees exclude self-loops; diagonal is overwritten (scipy
    # ``_laplacian_sparse`` semantics).
    E, _ = _graph(n=60, seed=2)
    S = (E + 3.0 * sp.eye(60)).tocsr()
    A = sparse.csr_array(S)
    for kw in ({}, {"normed": True}):
        got = sparse.csgraph.laplacian(A, return_diag=True, **kw)
        ref = scsg.laplacian(S, return_diag=True, **kw)
        np.testing.assert_allclose(got[0].toarray(), ref[0].toarray(),
                                   atol=1e-12)
        np.testing.assert_allclose(got[1], ref[1])


def test_fallbacks_take_package_arrays():
    # Distinct weights so the MST is unique — tied weights make
    # scipy's own tree argsort-order-dependent.  (Also exercises the
    # int64->int32 narrowing on the scipy side.)
    E, A = _weighted(n=60, density=0.1, seed=3)
    Es = ((E + E.T) / 2).tocsr()
    np.testing.assert_allclose(
        sparse.csgraph.minimum_spanning_tree(
            sparse.csr_array(Es)).toarray(),
        scsg.minimum_spanning_tree(Es).toarray())


def _weighted(n=80, density=0.06, seed=4, negative=False):
    rng = np.random.default_rng(seed)
    E = sp.random(n, n, density=density, format="csr", random_state=rng)
    w = rng.uniform(0.5, 3.0, size=E.nnz)
    if negative:
        # a few negative edges but no negative cycles (only edges
        # u -> v with u < v go negative: a DAG subset can't cycle)
        r, c = E.tocoo().row, E.tocoo().col
        w = np.where((r < c) & (rng.random(E.nnz) < 0.2), -w * 0.1, w)
    E = sp.csr_array((w, E.indices, E.indptr), shape=(n, n))
    return E, sparse.csr_array(E)


@pytest.mark.parametrize("method", ["auto", "D", "BF", "J", "FW"])
@pytest.mark.parametrize("directed", [True, False])
def test_shortest_path_matches_scipy(method, directed):
    E, A = _weighted()
    got = sparse.csgraph.shortest_path(A, method=method,
                                       directed=directed)
    ref = scsg.shortest_path(E, method=method, directed=directed)
    np.testing.assert_allclose(got, ref, rtol=1e-10)


def test_shortest_path_unweighted_and_indices():
    E, A = _weighted(seed=5)
    np.testing.assert_allclose(
        sparse.csgraph.shortest_path(A, unweighted=True),
        scsg.shortest_path(E, method="D", unweighted=True))
    np.testing.assert_allclose(
        sparse.csgraph.bellman_ford(A, indices=[3, 7]),
        scsg.bellman_ford(E, indices=[3, 7]))
    # scalar index → 1-D result, scipy shape semantics
    got = sparse.csgraph.dijkstra(A, indices=2)
    ref = scsg.dijkstra(E, indices=2)
    assert got.shape == ref.shape == (E.shape[0],)
    np.testing.assert_allclose(got, ref)


def test_negative_weights_and_cycle():
    E, A = _weighted(seed=6, negative=True)
    for fn, sfn in [(sparse.csgraph.bellman_ford, scsg.bellman_ford),
                    (sparse.csgraph.johnson, scsg.johnson),
                    (sparse.csgraph.floyd_warshall,
                     scsg.floyd_warshall)]:
        np.testing.assert_allclose(fn(A), sfn(E), rtol=1e-10,
                                   atol=1e-12)
    # explicit negative cycle raises scipy's exception class
    C = sparse.csr_array((np.array([1.0, -3.0]),
                          (np.array([0, 1]), np.array([1, 0]))),
                         shape=(2, 2))
    with pytest.raises(scsg.NegativeCycleError):
        sparse.csgraph.bellman_ford(C)
    with pytest.raises(scsg.NegativeCycleError):
        sparse.csgraph.floyd_warshall(C)


def _check_predecessors(dist, pred, E, directed):
    """Predecessor matrices are implementation-specific under ties;
    check consistency instead of equality: every reachable non-source
    node's predecessor edge must exist and be tight."""
    G = E.toarray()
    if not directed:
        both = np.where(G != 0, G, np.inf)
        both = np.minimum(both, both.T)
    else:
        both = np.where(G != 0, G, np.inf)
    # stored zeros are edges; rebuild edge weights from sparse struct
    coo = E.tocoo()
    W = np.full_like(G, np.inf, dtype=float)
    W[coo.row, coo.col] = coo.data
    if not directed:
        W = np.minimum(W, W.T)
    n = G.shape[0]
    for i in range(dist.shape[0]):
        for j in range(n):
            p = pred[i, j]
            if p == -9999:
                continue
            assert np.isfinite(W[p, j])
            np.testing.assert_allclose(dist[i, p] + W[p, j],
                                       dist[i, j], rtol=1e-10)


@pytest.mark.parametrize("directed", [True, False])
def test_predecessors_consistent(directed):
    E, A = _weighted(n=40, density=0.1, seed=7)
    dist, pred = sparse.csgraph.shortest_path(
        A, return_predecessors=True, directed=directed)
    ref_d = scsg.shortest_path(E, directed=directed)
    np.testing.assert_allclose(dist, ref_d, rtol=1e-10)
    _check_predecessors(dist, pred, E, directed)
    dist, pred = sparse.csgraph.floyd_warshall(
        A, return_predecessors=True, directed=directed)
    np.testing.assert_allclose(dist, ref_d, rtol=1e-10)
    _check_predecessors(dist, pred, E, directed)


def test_dijkstra_limit_and_min_only():
    E, A = _weighted(n=60, density=0.08, seed=8)
    np.testing.assert_allclose(
        sparse.csgraph.dijkstra(A, limit=2.5),
        scsg.dijkstra(E, limit=2.5))
    d_got = sparse.csgraph.dijkstra(A, indices=[0, 9], min_only=True)
    d_ref = scsg.dijkstra(E, indices=[0, 9], min_only=True)
    np.testing.assert_allclose(d_got, d_ref)
    got = sparse.csgraph.dijkstra(A, indices=[0, 9], min_only=True,
                                  return_predecessors=True)
    ref = scsg.dijkstra(E, indices=[0, 9], min_only=True,
                        return_predecessors=True)
    np.testing.assert_allclose(got[0], ref[0])
    np.testing.assert_array_equal(got[2], ref[2])


def test_unreachable_predecessors_and_bad_indices():
    # edge 1->2 only; from source 0 everything is unreachable, and the
    # inf+w==inf tightness trap must not invent pred[2]=1
    A = sparse.csr_array((np.array([1.0]), (np.array([1]),
                                            np.array([2]))), shape=(3, 3))
    dist, pred = sparse.csgraph.bellman_ford(A, indices=[0],
                                             return_predecessors=True)
    np.testing.assert_array_equal(pred, [[-9999, -9999, -9999]])
    assert np.isinf(dist[0, 1]) and np.isinf(dist[0, 2])
    # scipy index semantics: negative wraps, out-of-range raises
    d = sparse.csgraph.dijkstra(A, indices=-2)
    np.testing.assert_allclose(d, [np.inf, 0.0, 1.0])
    with pytest.raises(ValueError):
        sparse.csgraph.dijkstra(A, indices=[3])


def test_shortest_path_stored_zero_edges():
    # stored zeros ARE edges (verified scipy semantics)
    B = sp.csr_array((np.array([1.0, 0.0, 2.0]), np.array([1, 2, 2]),
                      np.array([0, 2, 3, 3])), shape=(3, 3))
    A = sparse.csr_array(B)
    np.testing.assert_allclose(sparse.csgraph.shortest_path(A),
                               scsg.shortest_path(B))
    np.testing.assert_allclose(
        sparse.csgraph.floyd_warshall(A), scsg.floyd_warshall(B))


@pytest.mark.slow
def test_minimum_spanning_tree_native():
    # Symmetric distinct weights: MST unique, exact scipy equality.
    rng = np.random.default_rng(12)
    for trial in range(6):
        n = int(rng.integers(5, 60))
        Eu = sp.triu(sp.random(n, n, density=0.2, random_state=rng),
                     k=1).tocoo()
        w = rng.permutation(len(Eu.data)) + 1.0
        S = sp.csr_array((np.concatenate([w, w]),
                          (np.concatenate([Eu.row, Eu.col]),
                           np.concatenate([Eu.col, Eu.row]))),
                         shape=(n, n))
        got = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(S))
        ref = scsg.minimum_spanning_tree(S)
        np.testing.assert_allclose(np.asarray(got.todense()),
                                   ref.toarray())
    # Asymmetric stored direction is preserved; disconnected forest.
    B = sp.csr_array(np.array([[0, 0, 0], [4.0, 0, 0], [0, 1.0, 0]]))
    got = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(B))
    np.testing.assert_allclose(np.asarray(got.todense()),
                               scsg.minimum_spanning_tree(B).toarray())
    C = sp.csr_array(np.array([[0, 1.0, 0, 0]] + [[0] * 4] * 3))
    got = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(C))
    np.testing.assert_allclose(np.asarray(got.todense()),
                               scsg.minimum_spanning_tree(C).toarray())
    # Equal-weight ties: tree may differ from Kruskal's, but it must be
    # a spanning forest of the same total weight and component count.
    T = sp.csr_array(np.array(
        [[0, 1.0, 1.0, 0], [1.0, 0, 1.0, 0], [1.0, 1.0, 0, 1.0],
         [0, 0, 1.0, 0]]))
    got = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(T))
    ref = scsg.minimum_spanning_tree(T)
    assert got.nnz == ref.nnz
    np.testing.assert_allclose(np.asarray(got.sum()), ref.sum())
    k_got = sparse.csgraph.connected_components(
        got, directed=False, return_labels=False)
    k_ref = scsg.connected_components(T, directed=False,
                                      return_labels=False)
    assert k_got == k_ref
    # scipy-wart parity: float64 output always; a chosen zero-weight
    # edge vanishes from the stored structure (scipy drops explicit
    # zeros in its CSR construction).
    Zd = np.array([[0, 0, 2.0], [0, 0, 3.0], [0, 0, 0]])
    Z = sp.csr_array(Zd)
    Z[0, 1] = 0.0   # explicit stored zero edge, cheapest 0-1 link
    Z[1, 0] = 0.0
    got = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(Z))
    ref = scsg.minimum_spanning_tree(Z)
    assert got.dtype == ref.dtype == np.float64
    assert got.nnz == ref.nnz
    np.testing.assert_allclose(np.asarray(got.todense()), ref.toarray())
    Zi = sp.csr_array(np.array([[0, 3, 2], [0, 0, 1], [0, 0, 0]],
                               dtype=np.int64))
    assert sparse.csgraph.minimum_spanning_tree(
        sparse.csr_array(Zi)).dtype == np.float64


def _kruskal_lex(S):
    """Reference Kruskal under the strict (weight, row, col) total
    order over stored entries, treating the graph as undirected — the
    pinned minimum_spanning_tree tie-breaking policy, independently
    implemented."""
    coo = S.tocoo()
    order = np.lexsort((coo.col, coo.row, coo.data))
    parent = np.arange(S.shape[0])

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    out = np.zeros(S.shape, dtype=np.float64)
    for k in order:
        u, v = int(coo.row[k]), int(coo.col[k])
        ru, rv = find(u), find(v)
        if ru != rv:
            parent[ru] = rv
            out[u, v] = coo.data[k]
    return out


def test_minimum_spanning_tree_tie_breaking_deterministic():
    # Tie-heavy graphs (weights drawn from {1, 2, 3} only): the pinned
    # lowest-(weight, row, col) policy must reproduce the reference
    # lexicographic Kruskal at EXACT stored positions, every trial —
    # not merely match the (unique) tree weight.
    # 3 fuzz trials in the default lane: each distinct n compiles a
    # fresh MST program, and the property is shape-independent — the
    # 8-trial sweep predates the tier-1 wall-time budget.
    rng = np.random.default_rng(7)
    for trial in range(3):
        n = int(rng.integers(6, 40))
        Eu = sp.triu(sp.random(n, n, density=0.25, random_state=rng),
                     k=1).tocoo()
        w = rng.integers(1, 4, size=len(Eu.data)).astype(np.float64)
        S = sp.csr_array((np.concatenate([w, w]),
                          (np.concatenate([Eu.row, Eu.col]),
                           np.concatenate([Eu.col, Eu.row]))),
                         shape=(n, n))
        got = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(S))
        np.testing.assert_array_equal(np.asarray(got.todense()),
                                      _kruskal_lex(S))
        # Tree weight still agrees with scipy (unique even where its
        # tie-broken edge choices differ from ours).
        np.testing.assert_allclose(np.asarray(got.sum()),
                                   scsg.minimum_spanning_tree(S).sum())
    # Asymmetric tie-heavy input: same policy over stored positions.
    D = sp.random(30, 30, density=0.15, random_state=rng).tocsr()
    D.data[:] = rng.integers(1, 3, size=D.nnz).astype(np.float64)
    D.setdiag(0)
    D.eliminate_zeros()
    gotd = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(D))
    np.testing.assert_array_equal(np.asarray(gotd.todense()),
                                  _kruskal_lex(D))
    # Determinism: a repeated run is bit-identical.
    got2 = sparse.csgraph.minimum_spanning_tree(sparse.csr_array(S))
    np.testing.assert_array_equal(np.asarray(got.todense()),
                                  np.asarray(got2.todense()))
