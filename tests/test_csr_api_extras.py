# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""scipy-API surface extras on csr_array: todia/asformat/getnnz/
eliminate_zeros/sort_indices/power — differential vs scipy."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.fixture
def S():
    S = scsp.random(60, 50, density=0.08, format="csr", random_state=5)
    S.data[::7] = 0.0  # explicit zeros for eliminate_zeros
    return S


def test_todia_roundtrip(S):
    A = sparse.csr_array(S)
    D = A.todia()
    assert D.data.shape[0] == S.todia().data.shape[0]
    np.testing.assert_allclose(
        np.asarray(D.tocsr().todense()), S.toarray(), atol=1e-12
    )


def test_todia_banded_small():
    A = sparse.diags([[1.0, 2.0], [3.0, 4.0, 5.0]], [-1, 0],
                     shape=(3, 3), format="csr")
    D = A.todia()
    np.testing.assert_array_equal(np.asarray(D.offsets), [-1, 0])
    np.testing.assert_allclose(
        np.asarray(D.tocsr().todense()),
        scsp.diags([[1.0, 2.0], [3.0, 4.0, 5.0]], [-1, 0]).toarray(),
    )


def test_asformat(S):
    A = sparse.csr_array(S)
    assert A.asformat("csr") is A
    assert A.asformat(None) is A
    from legate_sparse_tpu.dia import dia_array

    assert isinstance(A.asformat("dia"), dia_array)
    with pytest.raises(ValueError):
        A.asformat("lil")


def test_getnnz(S):
    A = sparse.csr_array(S)
    assert A.getnnz() == S.nnz
    np.testing.assert_array_equal(np.asarray(A.getnnz(axis=1)),
                                  S.getnnz(axis=1))
    np.testing.assert_array_equal(np.asarray(A.getnnz(axis=0)),
                                  S.getnnz(axis=0))


def test_eliminate_zeros(S):
    A = sparse.csr_array(S)
    S2 = S.copy()
    S2.eliminate_zeros()
    A.eliminate_zeros()
    assert A.nnz == S2.nnz
    np.testing.assert_allclose(np.asarray(A.todense()), S2.toarray(),
                               atol=1e-12)
    # idempotent
    A.eliminate_zeros()
    assert A.nnz == S2.nnz


def test_eliminate_zeros_invalidates_caches():
    A = sparse.diags([[1.0, 0.0, 2.0]], [0], shape=(3, 3), format="csr")
    x = np.array([1.0, 1.0, 1.0])
    y0 = np.asarray(A @ x)
    A.eliminate_zeros()
    np.testing.assert_allclose(np.asarray(A @ x), y0, atol=1e-12)
    assert A.nnz == 2


def test_sort_indices():
    data = np.array([1.0, 2.0, 3.0])
    indices = np.array([3, 1, 2])
    indptr = np.array([0, 2, 3])
    A = sparse.csr_array((data, indices, indptr), shape=(2, 4))
    Su = scsp.csr_array((data, indices, indptr), shape=(2, 4))
    A.sort_indices()
    Su.sort_indices()
    np.testing.assert_array_equal(np.asarray(A.indices), Su.indices)
    np.testing.assert_allclose(np.asarray(A.data), Su.data)


def test_power(S):
    A = sparse.csr_array(S)
    np.testing.assert_allclose(
        np.asarray(A.power(3).todense()), S.power(3).toarray(), atol=1e-12
    )


def test_power_coalesces_duplicates():
    """scipy's power sums duplicates before raising; ours must too."""
    r = np.array([0, 0])
    c = np.array([0, 0])
    v = np.array([1.0, 2.0])
    A = sparse.csr_array((v, (r, c)), shape=(1, 1))
    Sd = scsp.coo_array((v, (r, c)), shape=(1, 1)).tocsr()
    np.testing.assert_allclose(
        np.asarray(A.power(2).todense()), Sd.power(2).toarray()
    )  # (1+2)^2 = 9, not 1^2 + 2^2


def test_todia_empty():
    A = sparse.csr_array(
        (np.zeros(0), np.zeros(0, np.int64), np.zeros(2, np.int64)),
        shape=(1, 3),
    )
    D = A.todia()
    assert D.data.shape[0] == 0  # scipy: no stored diagonals
    SD = scsp.csr_array((np.zeros(0), np.zeros(0, np.int64),
                         np.zeros(2, np.int64)), shape=(1, 3)).todia()
    assert SD.data.shape[0] == 0


def test_sort_indices_stable_with_duplicates():
    data = np.array([1.0, 2.0, 3.0])
    indices = np.array([2, 2, 0])
    indptr = np.array([0, 3, 3])
    A = sparse.csr_array((data, indices, indptr), shape=(2, 3))
    Su = scsp.csr_array((data.copy(), indices.copy(), indptr.copy()),
                        shape=(2, 3))
    A.sort_indices()
    Su.sort_indices()
    np.testing.assert_array_equal(np.asarray(A.indices), Su.indices)
    np.testing.assert_allclose(np.asarray(A.data), Su.data)
    assert A.has_sorted_indices
    # second call is a no-op (flag cached despite duplicates)
    A.sort_indices()
    np.testing.assert_allclose(np.asarray(A.data), Su.data)


class TestBfloat16:
    """bfloat16 value support — TPU-native extension beyond the
    reference's f32/f64/c64/c128 gate (halves SpMV HBM traffic)."""

    def _banded_bf16(self, n=64):
        import jax.numpy as jnp

        offs = [-1, 0, 1]
        diags = [
            np.random.default_rng(i).normal(size=n - abs(o)).astype(
                np.float32
            )
            for i, o in enumerate(offs)
        ]
        A = sparse.diags(diags, offs, shape=(n, n), format="csr",
                         dtype=jnp.bfloat16)
        S = scsp.diags(diags, offs, shape=(n, n), format="csr")
        return A, S

    def test_spmv(self):
        import jax.numpy as jnp

        A, S = self._banded_bf16()
        n = A.shape[0]
        assert str(A.dtype) == "bfloat16"
        y = np.asarray(A @ jnp.ones(n, dtype=jnp.bfloat16),
                       dtype=np.float32)
        ref = S @ np.ones(n)
        denom = max(np.abs(ref).max(), 1e-6)
        assert np.abs(y - ref).max() / denom < 0.05

    def test_spgemm(self):
        A, S = self._banded_bf16()
        C = np.asarray((A @ A).todense(), dtype=np.float32)
        ref = (S @ S).toarray()
        assert np.abs(C - ref).max() / max(np.abs(ref).max(), 1.0) < 0.05

    def test_mixed_promotes(self):
        import jax.numpy as jnp
        import ml_dtypes

        A, S = self._banded_bf16()
        n = A.shape[0]
        x32 = jnp.ones(n, dtype=jnp.float32)
        y = A @ x32
        assert y.dtype == jnp.float32
        # Fair reference: the matrix was *stored* in bf16, so compare
        # against the bf16-rounded values computed in f32.
        S_rounded = S.copy()
        S_rounded.data = (
            S.data.astype(ml_dtypes.bfloat16).astype(np.float32)
        )
        np.testing.assert_allclose(
            np.asarray(y), S_rounded @ np.ones(n), rtol=1e-5, atol=1e-6
        )

    def test_cg_runs_finite(self):
        import jax.numpy as jnp

        from legate_sparse_tpu import linalg

        n = 64
        P = sparse.diags([4.0, -1.0, -1.0], [0, 1, -1], shape=(n, n),
                         format="csr", dtype=jnp.bfloat16)
        b = jnp.ones(n, dtype=jnp.bfloat16)
        x, iters = linalg.cg(P, b, rtol=1e-2, maxiter=100)
        assert bool(jnp.all(jnp.isfinite(x)))
