# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""csr_array constructor differential tests vs scipy (mirrors reference
``test_csr_from_dense.py``, ``test_csr_from_coo.py``, ``test_csr_from_csr.py``,
``test_csr_to_dense.py``)."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse
from utils_test.gen import random_csr, simple_system_gen


@pytest.mark.parametrize("N", [5, 29])
@pytest.mark.parametrize("M", [7, 17])
def test_from_dense(N, M):
    a_dense, A, _ = simple_system_gen(N, M, sparse.csr_array)
    s = scsp.csr_array(a_dense)
    assert A.nnz == s.nnz
    np.testing.assert_array_equal(np.asarray(A.indptr), s.indptr)
    np.testing.assert_array_equal(np.asarray(A.indices), s.indices)
    np.testing.assert_allclose(np.asarray(A.data), s.data)


@pytest.mark.parametrize("N", [4, 25])
def test_to_dense_roundtrip(N):
    a_dense, A, _ = simple_system_gen(N, N + 3, sparse.csr_array)
    np.testing.assert_allclose(np.asarray(A.todense()), a_dense)


def test_from_coo_unsorted():
    # Unsorted COO triplets must produce scipy-identical CSR (stable
    # within-row order, duplicates preserved).
    rng = np.random.default_rng(42)
    N, M, nnz = 13, 11, 40
    rows = rng.integers(0, N, nnz)
    cols = rng.integers(0, M, nnz)
    vals = rng.standard_normal(nnz)
    A = sparse.csr_array((vals, (rows, cols)), shape=(N, M))
    s = scsp.coo_matrix((vals, (rows, cols)), shape=(N, M)).tocsr()
    s.sum_duplicates()
    np.testing.assert_allclose(
        np.asarray(A.todense()), s.todense(), atol=1e-14
    )


def test_from_scipy():
    s = random_csr(20, 30, 0.3, 7)
    A = sparse.csr_array(s)
    assert A.shape == (20, 30)
    assert A.nnz == s.nnz
    np.testing.assert_allclose(np.asarray(A.todense()), s.todense())


def test_from_data_indices_indptr():
    s = random_csr(15, 9, 0.4, 3)
    A = sparse.csr_array(
        (s.data, s.indices, s.indptr), shape=s.shape
    )
    np.testing.assert_allclose(np.asarray(A.todense()), s.todense())


def test_copy_and_dtype():
    s = random_csr(10, 10, 0.5, 1)
    A = sparse.csr_array(s)
    B = sparse.csr_array(A, copy=True)
    C = A.astype(np.float32)
    assert B.nnz == A.nnz
    assert C.dtype == np.float32
    assert A.dtype == np.float64


def test_repr_and_str():
    A = sparse.csr_array(np.eye(3))
    assert "3x3" in repr(A)
    assert "(0, 0)" in str(A)
