# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Transpose / diagonal / sum / arithmetic tests (mirrors reference
``test_csr_transpose.py``, ``test_diagonal.py``)."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse
from utils_test.gen import random_csr, simple_system_gen


@pytest.mark.parametrize("N,M", [(5, 5), (12, 7), (7, 12)])
def test_transpose(N, M):
    s = random_csr(N, M, 0.4, 11)
    A = sparse.csr_array(s)
    At = A.T
    assert At.shape == (M, N)
    np.testing.assert_allclose(np.asarray(At.todense()), s.T.todense())
    # transpose must produce scipy-identical structure
    st = s.T.tocsr()
    st.sort_indices()
    np.testing.assert_array_equal(np.asarray(At.indptr), st.indptr)


@pytest.mark.parametrize("N", [5, 20])
def test_diagonal(N):
    s = random_csr(N, N, 0.5, 2)
    A = sparse.csr_array(s)
    np.testing.assert_allclose(np.asarray(A.diagonal()), s.diagonal())


@pytest.mark.parametrize("k", [-2, -1, 1, 3])
def test_diagonal_k(k):
    s = random_csr(9, 9, 0.6, 4)
    A = sparse.csr_array(s)
    np.testing.assert_allclose(np.asarray(A.diagonal(k)), s.diagonal(k))


@pytest.mark.parametrize("axis", [None, 0, 1])
def test_sum(axis):
    s = random_csr(8, 13, 0.4, 6)
    A = sparse.csr_array(s)
    expected = np.asarray(s.todense()).sum(axis=axis)
    np.testing.assert_allclose(np.asarray(A.sum(axis=axis)), expected,
                               atol=1e-13)


def test_scalar_mul_div_neg():
    a_dense, A, _ = simple_system_gen(6, 8, sparse.csr_array)
    np.testing.assert_allclose(
        np.asarray((2.5 * A).todense()), 2.5 * a_dense
    )
    np.testing.assert_allclose(
        np.asarray((A / 2.0).todense()), a_dense / 2.0
    )
    np.testing.assert_allclose(np.asarray((-A).todense()), -a_dense)


def test_add_sub():
    sa = random_csr(10, 9, 0.3, 1)
    sb = random_csr(10, 9, 0.3, 2)
    A = sparse.csr_array(sa)
    B = sparse.csr_array(sb)
    np.testing.assert_allclose(
        np.asarray((A + B).todense()), (sa + sb).todense(), atol=1e-14
    )
    np.testing.assert_allclose(
        np.asarray((A - B).todense()), (sa - sb).todense(), atol=1e-14
    )


def test_multiply_dense_and_vector():
    a_dense, A, x = simple_system_gen(7, 9, sparse.csr_array)
    other = np.random.default_rng(3).random((7, 9))
    np.testing.assert_allclose(
        np.asarray(A.multiply(other).todense()), a_dense * other, atol=1e-14
    )
    np.testing.assert_allclose(
        np.asarray(A.multiply(x).todense()), a_dense * x[None, :], atol=1e-14
    )


def test_conj_complex():
    s = random_csr(6, 6, 0.5, 9).astype(np.complex128)
    s.data = s.data + 1j * np.arange(s.nnz)
    A = sparse.csr_array(s)
    np.testing.assert_allclose(
        np.asarray(A.conj().todense()), np.conj(np.asarray(s.todense()))
    )


def test_mean():
    s = random_csr(6, 4, 0.5, 9)
    A = sparse.csr_array(s)
    np.testing.assert_allclose(
        np.asarray(A.mean(axis=1)), np.asarray(s.todense()).mean(axis=1),
        atol=1e-14,
    )
