# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Streaming mutation under live traffic (ISSUE 20, docs/MUTATION.md).

The delta layer's load-bearing contracts, each pinned here:

- **off == inert**: with ``LEGATE_SPARSE_TPU_DELTA`` unset the
  constructors raise, gateway serving is bit-for-bit the plain path,
  and no ``delta.*`` counter ever moves;
- **buffer semantics**: absolute overwrite-wins updates, 0.0 deletes,
  typed ``DeltaCapacityError`` before any mutation on overflow;
- **two-term serving**: ``base @ x + delta @ x`` numerically matches
  the mutated matrix; an empty buffer is bitwise the base dispatch;
- **versioned swap**: a view pinned at admission keeps serving its
  version across updates and a compaction (drain semantics);
- **compaction == cold rebuild bitwise** (acceptance criterion c):
  the merged base's CSR arrays equal a fresh COO construction of the
  mutated matrix exactly;
- **resilience**: compaction checkpoints the buffer under an active
  scope, retries injected ``delta.compact`` faults exactly-once;
- **distributed**: owner-shard routed updates with exact
  ``comm.delta.*`` pricing, dist serve parity, compaction-by-
  repartition, typed layout/type errors;
- **reshard carry** (the ride-along bugfix): ``reshard()`` of a
  wrapper with pending updates carries the buffer — never drops it;
- **the closed-loop acceptance drill**: ``chaos.run_drill`` with a
  ``mutation`` scenario — >= 100 seeded updates under live
  multi-tenant gateway load, a mid-storm compaction + atomic version
  swap, exactly-once accounting and bitwise parity throughout;
- **time-evolving graphs**: mutate-compact-rerun equals the cold
  rebuild for BFS (bitwise levels) and PageRank (tolerance).
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu import gallery, obs, resilience
from legate_sparse_tpu.csr import csr_array
from legate_sparse_tpu.delta import (
    DeltaCapacityError, DeltaCSR, DistDeltaCSR, is_delta, route,
)
from legate_sparse_tpu.delta import core as delta_core
from legate_sparse_tpu.engine import Engine, Gateway
from legate_sparse_tpu.graph import bfs, pagerank
from legate_sparse_tpu.obs import counters, report as obs_report, trace
from legate_sparse_tpu.parallel import (
    dist_spmv, make_row_mesh, reshard, shard_csr,
)
from legate_sparse_tpu.parallel.dist_csr import shard_vector
from legate_sparse_tpu.resilience import chaos, checkpoint as rckpt
from legate_sparse_tpu.resilience import faults as rfaults
from legate_sparse_tpu.settings import settings

from utils_test.tools import load_tool as _tool

R = len(jax.devices())
needs_mesh = pytest.mark.skipif(R < 2, reason="needs >= 2 devices")

_ENG = Engine()

_DELTA_KNOBS = ("delta", "delta_capacity", "delta_watermark",
                "delta_worker_ms")


@pytest.fixture(autouse=True)
def _isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was:
        trace.enable()
    else:
        trace.disable()


@pytest.fixture
def delta_on():
    saved = {k: getattr(settings, k) for k in _DELTA_KNOBS}
    settings.delta = True
    yield settings
    for k, v in saved.items():
        setattr(settings, k, v)


@pytest.fixture
def gw_on():
    saved = settings.gateway
    settings.gateway = True
    yield settings
    settings.gateway = saved


@pytest.fixture
def resil_on():
    saved = (settings.resil, settings.resil_backoff_ms)
    settings.resil = True
    settings.resil_backoff_ms = 0.0
    resilience.reset()
    yield settings
    (settings.resil, settings.resil_backoff_ms) = saved
    resilience.reset()


def _tridiag(n, dtype=np.float64):
    return sparse.diags(
        [np.full(n, 4.0, dtype), np.full(n - 1, -1.0, dtype),
         np.full(n - 1, -1.0, dtype)],
        [0, 1, -1], format="csr", dtype=dtype)


def _x(n, seed=1):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(n)


def _gateway(**kw):
    base = dict(max_batch=8, queue_depth=128, tenant_quota=64,
                rate=0.0, burst=64.0, slack_ms=1.0, timeout_ms=0.0)
    base.update(kw)
    return Gateway(_ENG, **base)


def _cold_rebuild(A, targets):
    """Fresh csr_array of ``A`` with ``targets`` applied (0.0
    deletes) — the independent reference every compaction must equal
    bitwise."""
    rows, cols, data = (np.asarray(p) for p in A._coo_parts())
    merged = {(int(r), int(c)): v
              for r, c, v in zip(rows, cols, data)}
    for (r, c), v in targets.items():
        if v == 0.0:
            merged.pop((r, c), None)
        else:
            merged[(r, c)] = v
    keys = sorted(merged)
    return csr_array(
        (np.asarray([merged[k] for k in keys], dtype=A.dtype),
         (np.asarray([k[0] for k in keys], dtype=np.int64),
          np.asarray([k[1] for k in keys], dtype=np.int64))),
        shape=A.shape, dtype=A.dtype)


# ---------------------------------------------------------------------------
# inertness: flag off
# ---------------------------------------------------------------------------
def test_constructors_require_flag():
    assert not settings.delta, "suite must run with delta off"
    with pytest.raises(RuntimeError, match="LEGATE_SPARSE_TPU_DELTA"):
        DeltaCSR(_tridiag(16))
    with pytest.raises(RuntimeError, match="LEGATE_SPARSE_TPU_DELTA"):
        DistDeltaCSR(None)


def test_flag_off_serving_is_bitwise_and_counter_inert(gw_on):
    """The whole armed-gateway serving path with delta off: identical
    bits to the direct dispatch, zero delta.* counter movement."""
    A = _tridiag(64)
    x = _x(64)
    y_direct = np.asarray(A.dot(jnp.asarray(x)))
    c0 = counters.snapshot("")
    gw = _gateway()
    try:
        y_gw = np.asarray(
            gw.submit(A, x, tenant="t", qos="interactive")
            .result(timeout=30))
    finally:
        gw.shutdown()
    c1 = counters.snapshot("")
    np.testing.assert_array_equal(y_gw, y_direct)
    moved = {k for k in c1 if c1[k] != c0.get(k, 0)}
    assert not any(k.startswith("delta.") for k in moved), moved
    assert route(A) is A, "route must pass plain matrices through"


# ---------------------------------------------------------------------------
# buffer semantics
# ---------------------------------------------------------------------------
def test_update_overwrite_wins_and_delete(delta_on):
    A = _tridiag(32)
    D = DeltaCSR(A, capacity=16)
    assert is_delta(D) and not is_delta(A)
    D.update([0, 0], [1, 1], [5.0, 7.0])      # within-batch repeat
    assert D.entries() == {(0, 1): 7.0}
    D.set_entries([0], [1], [9.0])            # cross-batch overwrite
    assert D.entries() == {(0, 1): 9.0}
    D.update([3], [3], [0.0])                 # pending delete
    assert D.entries()[(3, 3)] == 0.0
    assert D.pending == 2
    c = counters.snapshot("delta.")
    assert c.get("delta.updates") == 3
    assert c.get("delta.applied") == 2
    # Overwrites count every rewrite of an occupied slot — the
    # within-batch repeat AND the cross-batch one.
    assert c.get("delta.overwrites") == 2


def test_update_validation(delta_on):
    D = DeltaCSR(_tridiag(8))
    with pytest.raises(ValueError, match="shapes disagree"):
        D.update([0, 1], [0], [1.0])
    with pytest.raises(IndexError, match="out of range"):
        D.update([8], [0], [1.0])
    with pytest.raises(IndexError, match="out of range"):
        D.update([0], [-1], [1.0])


def test_capacity_typed_error_mutates_nothing(delta_on):
    D = DeltaCSR(_tridiag(32), capacity=2)
    D.update([0], [0], [1.0])
    with pytest.raises(DeltaCapacityError) as ei:
        D.update([1, 2], [1, 2], [1.0, 2.0])
    assert ei.value.pending == 3
    assert ei.value.capacity == 2
    assert D.entries() == {(0, 0): 1.0}, "failed batch must not land"
    assert D.pending == 1


# ---------------------------------------------------------------------------
# two-term serving
# ---------------------------------------------------------------------------
def test_empty_buffer_serves_base_bitwise(delta_on):
    A = _tridiag(96)
    x = jnp.asarray(_x(96))
    D = DeltaCSR(A)
    c0 = counters.snapshot("delta.")
    np.testing.assert_array_equal(np.asarray(D.dot(x)),
                                  np.asarray(A.dot(x)))
    assert counters.snapshot("delta.") == c0, \
        "empty-buffer serve must not move delta counters"


def test_two_term_serve_matches_mutated_matrix(delta_on):
    A = _tridiag(64)
    x = jnp.asarray(_x(64))
    D = DeltaCSR(A)
    targets = {(0, 0): 9.5, (5, 6): -2.25, (63, 62): 0.5,
               (10, 40): 3.0}                 # insert outside pattern
    for (r, c), v in targets.items():
        D.update([r], [c], [v])
    ref = _cold_rebuild(A, targets)
    np.testing.assert_allclose(np.asarray(D.dot(x)),
                               np.asarray(ref.dot(x)),
                               rtol=1e-12, atol=1e-12)
    assert counters.snapshot("delta.").get("delta.served") == 1


def test_pow2_bucket_policy():
    assert delta_core._pow2_bucket(0) == 1
    assert delta_core._pow2_bucket(1) == 1
    assert delta_core._pow2_bucket(2) == 2
    assert delta_core._pow2_bucket(3) == 4
    assert delta_core._pow2_bucket(1024) == 1024


def test_buffer_growth_never_retraces_within_bucket(delta_on):
    """Updates within one pow2 bucket reuse the compiled serving
    kernel: the trace counter moves only at bucket crossings."""
    A = _tridiag(64)
    x = jnp.asarray(_x(64))
    D = DeltaCSR(A, capacity=16)
    D.update([0, 1, 2], [0, 1, 2], [1.0, 2.0, 3.0])  # bucket 4
    D.dot(x)
    c0 = counters.snapshot("trace.")
    D.update([3], [3], [4.0])                  # 4 pending: bucket 4
    D.dot(x)
    c1 = counters.snapshot("trace.")
    assert c1.get("trace.coo_spmv_segment", 0) == \
        c0.get("trace.coo_spmv_segment", 0)
    D.update([4], [4], [5.0])                  # 5 pending: bucket 8
    D.dot(x)
    c2 = counters.snapshot("trace.")
    assert c2.get("trace.coo_spmv_segment", 0) == \
        c1.get("trace.coo_spmv_segment", 0) + 1, \
        "a bucket crossing recompiles once"


# ---------------------------------------------------------------------------
# compaction + versioned swap
# ---------------------------------------------------------------------------
def test_compact_is_bitwise_cold_rebuild(delta_on):
    A = _tridiag(48)
    D = DeltaCSR(A)
    targets = {(0, 1): 11.0, (7, 7): 0.0, (20, 3): 1.75}
    for (r, c), v in targets.items():
        D.update([r], [c], [v])
    assert D.compact() == 3
    ref = _cold_rebuild(A, targets)
    np.testing.assert_array_equal(np.asarray(D.base.data),
                                  np.asarray(ref.data))
    np.testing.assert_array_equal(np.asarray(D.base.indices),
                                  np.asarray(ref.indices))
    np.testing.assert_array_equal(np.asarray(D.base.indptr),
                                  np.asarray(ref.indptr))
    assert D.base.nnz == A.nnz, "one insert + one delete cancel"
    assert D.pending == 0 and D.version == 1
    x = jnp.asarray(_x(48))
    np.testing.assert_array_equal(np.asarray(D.dot(x)),
                                  np.asarray(ref.dot(x)))
    c = counters.snapshot("delta.")
    assert c.get("delta.compactions") == 1
    assert c.get("delta.compaction.merged") == 3
    assert c.get("delta.swap.versions") == 1
    assert c.get("delta.compaction.bytes", 0) > 0
    assert D.compact() == 0, "empty buffer: no-op"
    assert counters.snapshot("delta.").get("delta.compactions") == 1


def test_pinned_view_drains_its_version_across_swap(delta_on):
    """A view pinned at admission serves its version while updates and
    a compaction swap newer ones underneath — the drain contract."""
    A = _tridiag(40)
    x = jnp.asarray(_x(40))
    D = DeltaCSR(A)
    v0 = D.view()
    y0 = np.asarray(v0.dot(x))
    D.update([0], [0], [123.0])
    v1 = D.view()
    assert v1 is not v0 and v1.pending == 1
    D.compact()
    v2 = D.view()
    assert v2.version == 1 and v2.pending == 0
    # The pinned v0 still serves the pristine base, bitwise.
    np.testing.assert_array_equal(np.asarray(v0.dot(x)), y0)
    np.testing.assert_array_equal(np.asarray(A.dot(x)), y0)
    # ...and the post-swap wrapper serves the merged matrix.
    ref = _cold_rebuild(A, {(0, 0): 123.0})
    np.testing.assert_array_equal(np.asarray(D.dot(x)),
                                  np.asarray(ref.dot(x)))


def test_watermark_worker_compacts_in_background(delta_on):
    settings.delta_watermark = 0.5
    settings.delta_worker_ms = 5.0
    D = DeltaCSR(_tridiag(32), capacity=8)
    try:
        D.update([0, 1, 2, 3], [0, 1, 2, 3],
                 [1.0, 2.0, 3.0, 4.0])       # 4/8 hits the watermark
        deadline = time.monotonic() + 10.0
        while D.pending and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        D.stop_worker()
    assert D.pending == 0 and D.version == 1
    c = counters.snapshot("delta.")
    assert c.get("delta.watermark.exceeded", 0) >= 1
    assert c.get("delta.compactions") == 1


def test_maybe_compact_below_watermark_is_noop(delta_on):
    D = DeltaCSR(_tridiag(32), capacity=100)
    D.update([0], [0], [1.0])
    assert D.maybe_compact() == 0
    assert D.pending == 1


# ---------------------------------------------------------------------------
# resilience: checkpoint + fault injection at delta.compact
# ---------------------------------------------------------------------------
def test_compact_snapshots_buffer_under_checkpoint_scope(
        delta_on, resil_on):
    D = DeltaCSR(_tridiag(32))
    D.update([3, 5], [2, 5], [1.5, 0.0])
    with rckpt.scope("delta.compact", every=1) as ck:
        assert D.compact() == 2
    assert ck.saves == 1
    assert ck.iterations == 0, "keyed by the pre-swap version"
    rows, cols, vals = ck.arrays
    np.testing.assert_array_equal(rows, [3, 5])
    np.testing.assert_array_equal(cols, [2, 5])
    np.testing.assert_array_equal(vals, [1.5, 0.0])


def test_compact_retries_injected_fault_exactly_once(
        delta_on, resil_on):
    """An injected error at the delta.compact site is retried by the
    site policy; the swap lands exactly once and the merged base is
    still the bitwise cold rebuild."""
    A = _tridiag(32)
    D = DeltaCSR(A)
    D.update([0], [2], [42.0])
    rfaults.inject("delta.compact", kind="error", count=1)
    try:
        assert D.compact() == 1
    finally:
        rfaults.clear()
    c = counters.snapshot("")
    assert c.get("resil.retry.delta.compact") == 1
    assert c.get("delta.compactions") == 1
    assert c.get("delta.swap.versions") == 1
    assert D.version == 1 and D.pending == 0
    ref = _cold_rebuild(A, {(0, 2): 42.0})
    np.testing.assert_array_equal(np.asarray(D.base.data),
                                  np.asarray(ref.data))


def test_compact_exhausted_retries_keep_buffer_intact(
        delta_on, resil_on):
    """A compaction that fails beyond the retry budget propagates and
    leaves the buffer and version untouched — no half-applied swap."""
    D = DeltaCSR(_tridiag(32))
    D.update([1], [1], [9.0])
    rfaults.inject("delta.compact", kind="error", count=99)
    try:
        with pytest.raises(Exception):
            D.compact()
    finally:
        rfaults.clear()
    assert D.pending == 1 and D.version == 0
    assert D.entries() == {(1, 1): 9.0}
    assert counters.snapshot("delta.").get("delta.compactions",
                                           0) == 0


# ---------------------------------------------------------------------------
# gateway routing
# ---------------------------------------------------------------------------
def test_gateway_routes_delta_and_serves_two_terms(delta_on, gw_on):
    A = _tridiag(64)
    x = _x(64)
    D = DeltaCSR(A)
    D.update([0], [0], [7.5])
    gw = _gateway()
    try:
        y = np.asarray(
            gw.submit(D, x, tenant="mut", qos="interactive")
            .result(timeout=30))
    finally:
        gw.shutdown()
    ref = _cold_rebuild(A, {(0, 0): 7.5})
    np.testing.assert_allclose(
        y, np.asarray(ref.dot(jnp.asarray(x))),
        rtol=1e-12, atol=1e-12)
    c = counters.snapshot("delta.")
    assert c.get("delta.routes") == 1
    assert c.get("delta.served") == 1


# ---------------------------------------------------------------------------
# gallery.mutation_stream (satellite 1)
# ---------------------------------------------------------------------------
def test_mutation_stream_deterministic_and_mixed():
    A = _tridiag(128)
    def collect(seed):
        return list(gallery.mutation_stream(seed, A, 60, batch=7))
    s1, s2 = collect(5), collect(5)
    assert len(s1) == 9                       # ceil(60 / 7)
    assert sum(r.size for r, _c, _v in s1) == 60
    assert s1[-1][0].size == 4, "final batch is short"
    for (r1, c1, v1), (r2, c2, v2) in zip(s1, s2):
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(c1, c2)
        np.testing.assert_array_equal(v1, v2)
    other = collect(6)
    assert any(not np.array_equal(a[0], b[0])
               for a, b in zip(s1, other)), "seed must matter"
    pattern = set(zip(*(np.asarray(p).tolist()
                        for p in A._coo_parts()[:2])))
    flat = [(int(r), int(c), float(v))
            for rows, cols, vals in s1
            for r, c, v in zip(rows, cols, vals)]
    assert any(v == 0.0 for _r, _c, v in flat), "deletes present"
    assert any((r, c) not in pattern for r, c, _v in flat), \
        "inserts present"
    assert any(v != 0.0 and (r, c) in pattern
               for r, c, v in flat), "overwrites present"


def test_mutation_stream_empty_matrix_raises():
    empty = csr_array(np.zeros((4, 4)))
    with pytest.raises(ValueError, match="no stored entries"):
        next(gallery.mutation_stream(0, empty, 10))


# ---------------------------------------------------------------------------
# distributed
# ---------------------------------------------------------------------------
@needs_mesh
def test_dist_delta_typed_errors(delta_on):
    with pytest.raises(TypeError, match="wraps a DistCSR"):
        DistDeltaCSR(_tridiag(16))
    mesh = make_row_mesh(2)
    dA = shard_csr(_tridiag(64, np.float32), mesh=mesh,
                   layout="1d-col")
    with pytest.raises(ValueError, match="1d-row"):
        DistDeltaCSR(dA)


@needs_mesh
def test_dist_delta_serve_update_pricing_and_compact(delta_on):
    mesh = make_row_mesh(2)
    A = _tridiag(64, np.float32)
    dA = shard_csr(A, mesh=mesh, layout="1d-row")
    D = DistDeltaCSR(dA)
    x = _x(64, seed=3).astype(np.float32)
    xv = shard_vector(x, mesh, dA.rows_padded, layout="1d-row")
    y_base = np.asarray(dist_spmv(dA, xv))[:64]
    np.testing.assert_array_equal(
        np.asarray(D.dot(xv))[:64], y_base), \
        "empty buffer == base dispatch"
    c0 = counters.snapshot("comm.delta.")
    targets = {(0, 0): 2.5, (33, 32): -1.0, (10, 20): 4.0}
    D.update([0, 33, 10], [0, 32, 20], [2.5, -1.0, 4.0])
    c1 = counters.snapshot("comm.delta.")
    rec = 2 * 4 + np.dtype(np.float32).itemsize
    assert c1.get("comm.delta.scatter_bytes", 0) \
        - c0.get("comm.delta.scatter_bytes", 0) == 3 * rec
    ref = _cold_rebuild(A, targets)
    y_ref = np.asarray(ref.dot(jnp.asarray(x)))
    np.testing.assert_allclose(np.asarray(D.dot(xv))[:64], y_ref,
                               rtol=1e-5, atol=1e-5)
    c2 = counters.snapshot("comm.delta.")
    assert c2.get("comm.delta.all_gather_bytes", 0) > 0
    assert D.compact() == 3
    assert D.version == 1 and D.pending == 0
    # Compacted == cold shard_csr of the merged source, served equal.
    cold = shard_csr(ref, mesh=mesh, layout="1d-row")
    np.testing.assert_array_equal(
        np.asarray(dist_spmv(D.base, xv))[:64],
        np.asarray(dist_spmv(cold, xv))[:64])


# ---------------------------------------------------------------------------
# reshard carry: the ride-along bugfix regression pin
# ---------------------------------------------------------------------------
@needs_mesh
def test_reshard_carries_pending_delta_buffer(delta_on):
    """Repartitioning a wrapper with a non-empty buffer must carry
    the pending updates (never silently drop them) and keep serving
    the mutated values on the new mesh."""
    mesh2 = make_row_mesh(2)
    A = _tridiag(64, np.float32)
    D = DistDeltaCSR(shard_csr(A, mesh=mesh2, layout="1d-row"))
    targets = {(5, 5): 9.0, (40, 39): 0.5}
    D.update([5, 40], [5, 39], [9.0, 0.5])
    # Identity repartition: zero-byte fast path returns the wrapper.
    assert reshard(D, mesh=mesh2, layout="1d-row") is D
    mesh1 = make_row_mesh(1)
    D1 = reshard(D, mesh=mesh1, layout="1d-row")
    assert isinstance(D1, DistDeltaCSR)
    assert D1.pending == 2, "buffer must survive the repartition"
    assert D1.entries() == targets
    assert D1.version == D.version
    assert D1.num_shards == 1
    x = _x(64, seed=9).astype(np.float32)
    ref = _cold_rebuild(A, targets)
    y_ref = np.asarray(ref.dot(jnp.asarray(x)))
    xv1 = shard_vector(x, mesh1, D1.rows_padded, layout="1d-row")
    np.testing.assert_allclose(np.asarray(D1.dot(xv1))[:64], y_ref,
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# chaos: the closed-loop acceptance drill
# ---------------------------------------------------------------------------
def test_chaos_mutation_scenario_requires_delta(gw_on, resil_on):
    with pytest.raises(RuntimeError, match="settings.delta"):
        chaos.run_drill(None, tenants=[],
                        mutation={"tenant": "t"})


def test_chaos_drill_mutation_mid_storm(delta_on, gw_on, resil_on):
    """ISSUE 20 acceptance: >= 100 seeded updates stream into a
    served tenant under live multi-tenant gateway load with composed
    faults, one background compaction fires mid-round with an atomic
    version swap — exactly-once resolution with exact ``delta.*``
    accounting, bitwise serving parity on whichever version served,
    and post-compaction == cold-rebuild bitwise (all asserted inside
    the scenario; violations land in the report)."""
    A_mut = _tridiag(128)
    A_storm = _tridiag(96)
    gw = _gateway()
    c0 = counters.snapshot("")
    try:
        report = chaos.run_drill(
            gw,
            tenants=[
                {"name": "mut", "qos": "interactive",
                 "A": A_mut, "xs": [_x(128, seed=s)
                                    for s in range(3)]},
                {"name": "storm", "qos": "background",
                 "A": A_storm, "xs": [_x(96, seed=s)
                                      for s in range(10, 13)],
                 "deadline_ms": 0.0},
            ],
            rounds=4, seed=3,
            mutation={"tenant": "mut", "updates": 100, "seed": 11})
    finally:
        gw.shutdown()
    c1 = counters.snapshot("")

    def moved(name):
        return int(c1.get(name, 0)) - int(c0.get(name, 0))

    assert report.ok(), report.violations
    assert report.mutations == 10, "100 updates in batches of 10"
    assert report.compactions == 1
    assert moved("delta.compactions") == 1
    assert moved("delta.swap.versions") == 1
    assert moved("delta.updates") == 10
    assert not rfaults.armed()


# ---------------------------------------------------------------------------
# time-evolving graphs (satellite 3)
# ---------------------------------------------------------------------------
def test_evolving_graph_bfs_bitwise_after_compaction(delta_on):
    """Mutate edges through the delta layer, compact, re-run BFS: the
    int32 level array is bitwise the cold rebuild's."""
    G = gallery.rmat(6, nnz_per_row=4, rng=77)   # 64 vertices
    D = DeltaCSR(G)
    # Edge arrivals + one removal, streamed through the buffer.
    targets = {(0, 63): 1.0, (63, 1): 1.0}
    first = tuple(int(v) for v in
                  np.asarray(G._coo_parts()[0])[:1]), tuple(
                      int(v) for v in np.asarray(G._coo_parts()[1])[:1])
    targets[(first[0][0], first[1][0])] = 0.0    # remove one edge
    for (r, c), v in targets.items():
        D.update([r], [c], [v])
    D.compact()
    ref = _cold_rebuild(G, targets)
    lv_delta = np.asarray(bfs(D.base, source=0))
    lv_cold = np.asarray(bfs(ref, source=0))
    np.testing.assert_array_equal(lv_delta, lv_cold)
    assert int(lv_delta[63]) == 1, "the inserted 0->63 edge serves"


def test_evolving_graph_pagerank_matches_cold_rebuild(delta_on):
    G = gallery.rmat(6, nnz_per_row=4, rng=78)
    D = DeltaCSR(G)
    updates = list(gallery.mutation_stream(13, G, 30, batch=10))
    for rows, cols, vals in updates:
        D.update(rows, cols, vals)
    D.compact()
    targets = {}
    for rows, cols, vals in updates:
        for r, c, v in zip(rows, cols, vals):
            targets[(int(r), int(c))] = float(v)
    ref = _cold_rebuild(G, targets)
    r_delta = np.asarray(pagerank(D.base, alpha=0.85, tol=1e-10,
                                  max_iters=60))
    r_cold = np.asarray(pagerank(ref, alpha=0.85, tol=1e-10,
                                 max_iters=60))
    np.testing.assert_allclose(r_delta, r_cold, rtol=1e-7, atol=1e-9)


# ---------------------------------------------------------------------------
# ledger rendering + doctor (satellite 2)
# ---------------------------------------------------------------------------
def test_render_delta_table():
    assert "delta off" in obs_report.render_delta_table({})
    text = obs_report.render_delta_table({
        "delta.updates": 11, "delta.applied": 101,
        "delta.overwrites": 2, "delta.compactions": 1,
        "delta.compaction.merged": 101,
        "delta.compaction.bytes": 4096, "delta.swap.versions": 1,
        "delta.served": 21, "delta.routes": 24,
        "comm.delta.scatter_bytes": 48,
    })
    assert "11 update batches" in text
    assert "101 entries merged" in text
    assert "21 two-term serves" in text
    assert "48" in text


def test_doctor_compaction_lagging_and_delta_disabled_rules():
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    # Watermark pressure while an SLO burns: warn.
    ev.counters = {"delta.watermark.exceeded": 3,
                   "slo.breach.gateway.interactive": 2}
    finding = next(f for f in doctor.diagnose(ev)
                   if f["code"] == "compaction-lagging")
    assert finding["severity"] == "warn"
    assert finding["value"] == "3"
    assert "WORKER_MS" in finding["hint"]
    # Watermark pressure alone (no burn): quiet.
    ev.counters = {"delta.watermark.exceeded": 3}
    codes = [f["code"] for f in doctor.diagnose(ev)]
    assert "compaction-lagging" not in codes
    # Repeated same-bucket COO rebuilds with delta off: info points
    # at the subsystem that amortizes them...
    ev.counters = {"build.csr.coo.64x64": 5, "build.csr.coo.8x8": 1}
    finding = next(f for f in doctor.diagnose(ev)
                   if f["code"] == "delta-disabled-but-rebuilding")
    assert finding["severity"] == "info"
    assert "64x64" in finding["message"]
    assert finding["value"] == "5"
    # ...and stays quiet once the delta layer is demonstrably live.
    ev.counters["delta.updates"] = 1
    codes = [f["code"] for f in doctor.diagnose(ev)]
    assert "delta-disabled-but-rebuilding" not in codes
    # Below the rebuild floor: quiet.
    ev.counters = {"build.csr.coo.64x64": 2}
    codes = [f["code"] for f in doctor.diagnose(ev)]
    assert "delta-disabled-but-rebuilding" not in codes


def test_coo_constructor_bumps_shape_bucket_counter():
    A = _tridiag(48)                           # diags -> COO path?
    c0 = counters.snapshot("build.csr.coo.")
    rows, cols, data = (np.asarray(p) for p in A._coo_parts())
    csr_array((data, (rows, cols)), shape=A.shape, dtype=A.dtype)
    c1 = counters.snapshot("build.csr.coo.")
    assert c1.get("build.csr.coo.64x64", 0) \
        == c0.get("build.csr.coo.64x64", 0) + 1
