# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Banded (DIA) SpMV fast-path: detection, exactness guard, kernels.

On TPU, HBM gathers run far below roofline while shifted-add streams hit
it; ``csr_array`` detects exactly-banded structure and routes matvec
through gather-free DIA kernels (``ops/dia_ops.py``).  The reference
always converts DIA→CSR and pays the gather (``dia.py:152-190``) — this
path is a TPU-first improvement, so these tests pin both the speedup
preconditions (when it must activate) and the safety preconditions
(when it must NOT).
"""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


def _banded(n, offsets, seed=0, dtype=np.float64):
    diags = [
        np.random.default_rng(seed + i).normal(size=n - abs(o)).astype(dtype)
        for i, o in enumerate(offsets)
    ]
    A = sparse.diags(diags, offsets, shape=(n, n), format="csr", dtype=dtype)
    S = scsp.diags(diags, offsets, shape=(n, n), format="csr", dtype=dtype)
    return A, S


def test_dia_detected_on_banded():
    A, S = _banded(64, [-2, 0, 1])
    x = np.random.default_rng(1).normal(size=64)
    np.testing.assert_allclose(np.asarray(A @ x), S @ x, rtol=1e-10)
    assert A._dia not in (None, False)
    assert A._dia_offsets == (-2, 0, 1)


def test_dia_not_used_on_irregular():
    S = scsp.random(128, 128, density=0.05, format="csr", random_state=3)
    A = sparse.csr_array(S)
    x = np.random.default_rng(2).normal(size=128)
    np.testing.assert_allclose(np.asarray(A @ x), S @ x, rtol=1e-10)
    assert A._dia is False


def test_dia_band_hole_masked_path():
    """A banded matrix with a *hole* (in-bounds band slot with no stored
    entry) takes the masked DIA path: the hole never multiplies x, so
    IEEE semantics against non-finite x match CSR exactly."""
    # rows 0,2 populated on diagonal 0; row 1 empty -> hole at (1,1).
    S = scsp.csr_array(
        (np.array([1.0, 2.0]), np.array([0, 2]), np.array([0, 1, 1, 2])),
        shape=(3, 3),
    )
    A = sparse.csr_array(S)
    y = np.asarray(A @ np.array([1.0, np.inf, np.inf]))
    dia = A._get_dia()
    assert dia is not None and dia[2] is not None  # masked mode
    assert y[1] == 0.0  # empty row stays clean even with inf in x
    np.testing.assert_allclose(y[[0, 2]], [1.0, np.inf])


def test_dia_masked_path_pde_operator():
    """The pde.py-style Poisson operator (diags().tocsr() drops the
    explicit boundary zeros -> holey band) runs the masked DIA path and
    matches scipy."""
    N = 12
    n = N * N
    main = np.full(n, 4.0)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0
    offn = np.full(n - N, -1.0)
    A = sparse.diags(
        [main, off1, off1, offn, offn], [0, 1, -1, N, -N],
        shape=(n, n), format="csr",
    )
    S = scsp.diags(
        [main, off1, off1, offn, offn], [0, 1, -1, N, -N],
        shape=(n, n), format="csr",
    )
    x = np.random.default_rng(11).normal(size=n)
    np.testing.assert_allclose(np.asarray(A @ x), S @ x, rtol=1e-10)
    dia = A._get_dia()
    assert dia is not None and dia[2] is not None


def test_dia_nonfinite_x_explicit_entries():
    """Explicit band entries propagate inf/nan exactly like scipy."""
    A, S = _banded(8, [0])
    x = np.array([1.0, np.inf, np.nan, 2.0, 3.0, -np.inf, 0.0, 1.0])
    y = np.asarray(A @ x)
    ref = S @ x
    np.testing.assert_array_equal(np.isnan(y), np.isnan(ref))
    np.testing.assert_allclose(
        y[~np.isnan(y)], ref[~np.isnan(ref)], rtol=1e-12
    )


def test_dia_spmm_matches_scipy():
    A, S = _banded(96, [-3, -1, 0, 1, 3])
    X = np.random.default_rng(5).normal(size=(96, 7))
    np.testing.assert_allclose(np.asarray(A @ X), S @ X, rtol=1e-9)
    assert A._dia not in (None, False)


def test_dia_cache_invalidation_on_data_set():
    A, S = _banded(32, [0, 1])
    x = np.random.default_rng(6).normal(size=32)
    y1 = np.asarray(A @ x)
    np.testing.assert_allclose(y1, S @ x, rtol=1e-10)
    A.data = np.asarray(A.data) * 2.0
    y2 = np.asarray(A @ x)
    np.testing.assert_allclose(y2, 2.0 * y1, rtol=1e-10)


def test_dia_disabled_by_setting(monkeypatch):
    from legate_sparse_tpu.settings import settings

    monkeypatch.setattr(settings, "dia_max_expand", 0.0)
    A, S = _banded(32, [0, 1])
    x = np.random.default_rng(7).normal(size=32)
    np.testing.assert_allclose(np.asarray(A @ x), S @ x, rtol=1e-10)
    assert A._dia is False


@pytest.mark.slow
def test_dist_dia_masked_holey_band():
    """Distributed masked DIA path: a holey band (diags().tocsr()
    dropped zeros) through shard_csr carries dia_mask blocks, and
    dist_spmv matches scipy including inf-at-hole semantics."""
    import jax

    from legate_sparse_tpu.parallel import shard_csr, dist_spmv
    from legate_sparse_tpu.parallel.dist_csr import shard_vector
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs >= 4 virtual devices")
    mesh = make_row_mesh(devs[:4])
    N = 8
    n = N * N
    main = np.full(n, 4.0)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0  # holes after tocsr
    offn = np.full(n - N, -1.0)
    A = sparse.diags(
        [main, off1, off1, offn, offn], [0, 1, -1, N, -N],
        shape=(n, n), format="csr",
    )
    dA = shard_csr(A, mesh=mesh)
    assert dA.dia_data is not None and dA.dia_mask is not None
    x = np.random.default_rng(13).normal(size=n)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    S = A.toscipy()
    np.testing.assert_allclose(y, S @ x, rtol=1e-10)
    # inf placed at a hole column: rows whose band hole points there
    # must stay clean (CSR never touches a hole).
    xi = np.zeros(n)
    xi[N - 1] = np.inf  # column N-1 is a hole for row N (off1 zero)
    xsi = shard_vector(xi, mesh, dA.rows_padded)
    yi = np.asarray(dist_spmv(dA, xsi))[:n]
    ref = S @ xi
    np.testing.assert_array_equal(np.isnan(yi), np.isnan(ref))
    np.testing.assert_array_equal(np.isinf(yi), np.isinf(ref))


@pytest.mark.slow
def test_dist_dia_only_matrix():
    """materialize_ell=False: solver-path consumers work off the DIA
    blocks alone; block consumers raise with guidance."""
    import jax

    from legate_sparse_tpu.parallel.dist_build import dist_poisson2d
    from legate_sparse_tpu.parallel.dist_csr import (
        dist_cg, dist_diagonal, dist_spmv, shard_vector,
    )
    from legate_sparse_tpu.parallel.dist_spgemm import dist_spgemm
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs >= 4 virtual devices")
    mesh = make_row_mesh(devs[:4])
    N = 8
    n = N * N
    dA = dist_poisson2d(N, mesh=mesh, materialize_ell=False)
    assert dA.data is None and dA.dia_data is not None
    S = dist_poisson2d(N, mesh=mesh).to_csr().toscipy()
    # to_csr reconstructs from DIA blocks alone.
    np.testing.assert_allclose(
        dA.to_csr().todense(), S.toarray(), atol=1e-12
    )
    x = np.random.default_rng(17).normal(size=n)
    xs = shard_vector(x, mesh, dA.rows_padded)
    np.testing.assert_allclose(
        np.asarray(dist_spmv(dA, xs))[:n], S @ x, rtol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(dist_diagonal(dA))[:n], S.diagonal(), rtol=1e-12
    )
    b = np.ones(n)
    sol, _ = dist_cg(dA, b, rtol=1e-10)
    assert np.linalg.norm(b - S @ np.asarray(sol)) <= 1e-8
    # Banded products work even DIA-only (no blocks needed).
    C = dist_spgemm(dA, dA)
    np.testing.assert_allclose(
        C.to_csr().todense(), (S @ S).toarray(), atol=1e-10
    )
    # A product whose band blows the halo budget needs the general
    # (block-consuming) path -> must raise with guidance on DIA-only.
    from legate_sparse_tpu.parallel.dist_build import dist_diags

    wide = dist_diags(
        [1.0, 1.0], [0, 12], shape=(n, n), mesh=mesh,
        materialize_ell=False,
    )
    with pytest.raises(ValueError, match="materialize_ell"):
        dist_spgemm(wide, wide)  # product offset 24 > rps=16


def test_dia_rectangular_not_crashing():
    """Rectangular banded matrices: detection must either activate with
    correct results or fall back — differential check either way."""
    offsets = [0, 1]
    diags = [np.ones(5), np.ones(5)]
    A = sparse.diags(diags, offsets, shape=(5, 6), format="csr")
    S = scsp.diags(diags, offsets, shape=(5, 6), format="csr")
    x = np.random.default_rng(8).normal(size=6)
    np.testing.assert_allclose(np.asarray(A @ x), S @ x, rtol=1e-10)


def test_banded_spgemm_fast_path():
    """Exact-band @ exact-band runs the Minkowski-band kernel with
    scipy nnz parity and warms the product's own DIA cache."""
    n = 96
    offsA = [-2, 0, 1]
    offsB = [-1, 0, 3]
    dA = [np.random.default_rng(i).normal(size=n - abs(o))
          for i, o in enumerate(offsA)]
    dB = [np.random.default_rng(9 + i).normal(size=n - abs(o))
          for i, o in enumerate(offsB)]
    A = sparse.diags(dA, offsA, shape=(n, n), format="csr")
    B = sparse.diags(dB, offsB, shape=(n, n), format="csr")
    SA = scsp.diags(dA, offsA, shape=(n, n), format="csr")
    SB = scsp.diags(dB, offsB, shape=(n, n), format="csr")
    C = A @ B
    SC = SA @ SB
    np.testing.assert_allclose(
        np.asarray(C.todense()), SC.toarray(), rtol=1e-9, atol=1e-12
    )
    assert C.nnz == SC.nnz
    assert C._dia not in (None, False)  # product cache pre-warmed
    x = np.random.default_rng(3).normal(size=n)
    np.testing.assert_allclose(np.asarray(C @ x), SC @ x, rtol=1e-8)


def test_banded_spgemm_unreachable_slot_falls_back():
    """A={-1} @ B={+1}: slot (0,0) is in-bounds but structurally
    unreachable; the product must keep scipy's pattern (ESC path)."""
    n = 32
    A = sparse.diags([np.ones(n - 1)], [-1], shape=(n, n), format="csr")
    B = sparse.diags([np.ones(n - 1)], [1], shape=(n, n), format="csr")
    SC = (scsp.diags([np.ones(n - 1)], [-1], format="csr", shape=(n, n))
          @ scsp.diags([np.ones(n - 1)], [1], format="csr", shape=(n, n)))
    C = A @ B
    assert C.nnz == SC.nnz
    np.testing.assert_allclose(np.asarray(C.todense()), SC.toarray(),
                               atol=1e-12)


@pytest.mark.slow
def test_banded_spgemm_rectangular():
    A = sparse.diags([np.ones(50), np.ones(50)], [0, 1],
                     shape=(50, 60), format="csr")
    B = sparse.diags([np.ones(55), np.ones(55)], [0, -5],
                     shape=(60, 55), format="csr")
    SA = scsp.diags([np.ones(50), np.ones(50)], [0, 1],
                    shape=(50, 60), format="csr")
    SB = scsp.diags([np.ones(55), np.ones(55)], [0, -5],
                    shape=(60, 55), format="csr")
    C = A @ B
    SC = SA @ SB
    assert C.nnz == SC.nnz
    np.testing.assert_allclose(np.asarray(C.todense()), SC.toarray(),
                               atol=1e-12)


@pytest.mark.slow
def test_transpose_wide_band_storage_matches_dense():
    # Stored band wider than the matrix: scipy 1.17's dia transpose is
    # internally inconsistent here (S.T.toarray() != S.toarray().T —
    # entries shift along the diagonal), so the oracle is the DENSE
    # transpose, which this package matches.
    import scipy.sparse as sp

    data = np.arange(1.0, 11.0).reshape(1, 10)
    for offs, shape in [([2], (5, 9)), ([5], (5, 9)), ([2], (9, 5))]:
        S = sp.dia_array((data, offs), shape=shape)
        D = sparse.dia_array((data, offs), shape=shape)
        np.testing.assert_array_equal(np.asarray(D.todense()),
                                      S.toarray())
        np.testing.assert_array_equal(np.asarray(D.T.todense()),
                                      S.toarray().T)
        np.testing.assert_array_equal(
            np.asarray(D.tocsr().T.todense()), S.toarray().T)


@pytest.mark.parametrize("shape", [(60, 60), (80, 50), (50, 80)])
@pytest.mark.parametrize("masked", [False, True])
def test_dia_spmv_fused_matches_unfused(shape, masked):
    # The fused pad+slice formulation (one XLA pass) must agree with
    # the at[].add reference formulation (to roundoff — XLA fusion may
    # reassociate) on exact and holey bands, square and rectangular.
    import jax.numpy as jnp

    from legate_sparse_tpu.ops import dia_ops

    rows, cols = shape
    offsets = (-7, -2, 0, 1, 5)
    rng = np.random.default_rng(42)
    width = cols
    data = np.zeros((len(offsets), width), np.float64)
    mask = np.zeros((len(offsets), width), bool)
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(cols, rows + off)
        data[d, j_lo:j_hi] = rng.normal(size=max(0, j_hi - j_lo))
        if masked:
            keep = rng.random(max(0, j_hi - j_lo)) < 0.7
            data[d, j_lo:j_hi] *= keep
            mask[d, j_lo:j_hi] = keep
        else:
            mask[d, j_lo:j_hi] = True
    x = rng.normal(size=cols)
    dj, mj, xj = jnp.asarray(data), jnp.asarray(mask), jnp.asarray(x)
    m_arg = mj if masked else None
    ref = (dia_ops.dia_spmv_masked(dj, mj, xj, offsets, shape) if masked
           else dia_ops.dia_spmv(dj, xj, offsets, shape))
    dpad, mpad = dia_ops.pad_dia(dj, offsets, shape, mask=m_arg,
                                 with_mask=masked)
    got = dia_ops.dia_spmv_fused(dpad, mpad, xj, offsets, shape)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-13, atol=1e-13)


def test_dia_spmv_fused_ieee_nonfinite_x_at_hole():
    # A non-finite x entry at a band HOLE (or out-of-matrix slot) must
    # not leak NaN into y through the fused form's zero pads.
    import jax.numpy as jnp

    from legate_sparse_tpu.ops import dia_ops

    n = 16
    offsets = (-1, 0, 1)
    data = np.ones((3, n))
    mask = np.ones((3, n), bool)
    mask[2, 5] = False          # hole at A[4, 5]
    data[2, 5] = 0.0
    x = np.ones(n)
    x[5] = np.inf               # referenced by rows 4(hole),5,6
    dpad, mpad = dia_ops.pad_dia(jnp.asarray(data), offsets, (n, n),
                                 mask=jnp.asarray(mask), with_mask=True)
    y = np.asarray(dia_ops.dia_spmv_fused(dpad, mpad, jnp.asarray(x),
                                          offsets, (n, n)))
    assert not np.isnan(y).any()
    assert np.isinf(y[5]) and np.isinf(y[6]) and np.isfinite(y[3])
