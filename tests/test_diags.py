# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""diags / dia_array tests (mirrors reference ``test_diags.py`` and the
dia_array transpose/tocsr paths)."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.mark.parametrize(
    "diagonals,offsets,shape",
    [
        ([[1, 2, 3, 4]], [0], None),
        ([[1, 2, 3], [4, 5, 6, 7], [8, 9, 10]], [-1, 0, 1], None),
        ([[1, 2, 3, 4, 5]], [2], (7, 7)),
        ([[1] * 6, [2] * 6], [0, -1], (7, 6)),
    ],
)
def test_diags_matches_scipy(diagonals, offsets, shape):
    ours = sparse.diags(diagonals, offsets, shape=shape, format="csr",
                        dtype=np.float64)
    theirs = scsp.diags(diagonals, offsets, shape=shape, format="csr",
                        dtype=np.float64)
    np.testing.assert_allclose(np.asarray(ours.todense()), theirs.todense())


def test_diags_scalar_broadcast():
    ours = sparse.diags([1, -2, 1], [-1, 0, 1], shape=(8, 8), format="csr")
    theirs = scsp.diags([1, -2, 1], [-1, 0, 1], shape=(8, 8), format="csr")
    np.testing.assert_allclose(np.asarray(ours.todense()), theirs.todense())


def test_diags_dia_format():
    ours = sparse.diags([[1, 2, 3, 4], [4, 5, 6]], [0, 1], shape=(4, 4))
    assert ours.format == "dia"
    theirs = scsp.diags([[1, 2, 3, 4], [4, 5, 6]], [0, 1], shape=(4, 4))
    np.testing.assert_allclose(np.asarray(ours.todense()), theirs.todense())


def test_dia_transpose():
    d = sparse.diags([[1, 2, 3, 4], [5, 6, 7, 8]], [0, -1], shape=(5, 4))
    s = scsp.diags([[1, 2, 3, 4], [5, 6, 7, 8]], [0, -1], shape=(5, 4))
    np.testing.assert_allclose(
        np.asarray(d.T.todense()), s.T.todense()
    )


def test_dia_nnz():
    d = sparse.diags([1, 1], [0, 2], shape=(6, 6))
    s = scsp.diags([np.ones(6), np.ones(4)], [0, 2], shape=(6, 6))
    assert d.nnz == s.nnz


def test_dia_tocsr_explicit_zero_drop():
    # tocsr drops explicit zeros from the stored diagonals (in-band zeros).
    d = sparse.dia_array(
        (np.array([[1.0, 0.0, 3.0]]), np.array([0])), shape=(3, 3)
    )
    c = d.tocsr()
    assert c.nnz == 2


def test_eye_identity():
    np.testing.assert_allclose(
        np.asarray(sparse.identity(5, format="csr").todense()), np.eye(5)
    )
    np.testing.assert_allclose(
        np.asarray(sparse.eye(4, 6, k=1, format="csr").todense()),
        np.eye(4, 6, k=1),
    )


def test_diags_integer_input_casts_to_float():
    # scipy.sparse.diags casts integer diagonals to float64; keeping
    # int64 made A @ x raise (integer dtypes are gated out of the
    # kernels, same as the reference).  Platform float policy applies.
    ours = sparse.diags([1, -2, 1], [-1, 0, 1], shape=(5, 5), format="csr")
    assert np.issubdtype(ours.dtype, np.floating)
    y = np.asarray(ours @ np.ones(5, dtype=ours.dtype))
    np.testing.assert_allclose(y, [-1.0, 0.0, 0.0, 0.0, -1.0])
