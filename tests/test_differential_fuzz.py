# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Randomized differential battery vs scipy: many ops, pooled shapes
(so jit compiles amortize), seeded for reproducibility.  Slow lane —
the unit files cover each op; this net catches cross-op regressions."""

import warnings

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as lst

pytestmark = pytest.mark.slow

SHAPES = [(12, 12), (8, 15)]


def _chk(fails, trial, name, got, want, tol=1e-9):
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    w = np.asarray(want.toarray() if hasattr(want, "toarray") else want)
    if g.shape != w.shape or not np.allclose(g, w, atol=tol,
                                             equal_nan=True):
        fails.append((trial, name))


def test_differential_battery():
    rng = np.random.default_rng(99)
    fails = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for trial in range(6):
            m, n = SHAPES[trial % 2]
            d = float(rng.uniform(0.05, 0.5))
            As = sp.random(m, n, density=d, format="csr",
                           random_state=rng)
            Bs = sp.random(m, n, density=d, format="csr",
                           random_state=rng)
            A, B = lst.csr_array(As), lst.csr_array(Bs)
            _chk(fails, trial, "add", A + B, As + Bs)
            _chk(fails, trial, "sub", A - B, As - Bs)
            _chk(fails, trial, "mul_elem", A * B,
                 sp.csr_array(As) * sp.csr_array(Bs))
            _chk(fails, trial, "maximum", A.maximum(B), As.maximum(Bs))
            _chk(fails, trial, "minimum", A.minimum(B), As.minimum(Bs))
            _chk(fails, trial, "multiply", A.multiply(B),
                 As.multiply(Bs))
            _chk(fails, trial, "ne", A != B,
                 sp.csr_array(As) != sp.csr_array(Bs))
            _chk(fails, trial, "sum0", A.sum(axis=0),
                 np.asarray(As.sum(axis=0)).ravel())
            _chk(fails, trial, "sum1", A.sum(axis=1),
                 np.asarray(As.sum(axis=1)).ravel())
            _chk(fails, trial, "max1", A.max(axis=1),
                 As.max(axis=1).toarray().ravel())
            _chk(fails, trial, "T", A.T, As.T)
            _chk(fails, trial, "tocsc", A.tocsc(), As.tocsc())
            _chk(fails, trial, "tril", lst.tril(A, k=1),
                 sp.tril(As, k=1))
            if m == n:
                _chk(fails, trial, "diag", A.diagonal(), As.diagonal())
                _chk(fails, trial, "spgemm",
                     A @ lst.csr_array(Bs.T.tocsr()), As @ Bs.T.tocsr())
            x = rng.standard_normal(n)
            _chk(fails, trial, "spmv", A @ x, As @ x)
            X = rng.standard_normal((n, 3))
            _chk(fails, trial, "spmm", A @ X, As @ X)
    assert not fails, fails


def test_degenerate_shapes():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fails = []
        Es = sp.csr_array((3, 4))
        E = lst.csr_array((3, 4))
        _chk(fails, 0, "empty+", E + E, Es + Es)
        _chk(fails, 0, "emptyT", E.T, Es.T)
        _chk(fails, 0, "empty spmv", E @ np.ones(4), Es @ np.ones(4))
        Rs = sp.random(1, 9, density=0.5, format="csr", random_state=1)
        R = lst.csr_array(Rs)
        _chk(fails, 0, "row spmv", R @ np.ones(9), Rs @ np.ones(9))
        _chk(fails, 0, "rowT", R.T, Rs.T)
        Cs = sp.random(9, 1, density=0.5, format="csr", random_state=2)
        C = lst.csr_array(Cs)
        _chk(fails, 0, "col spmv", C @ np.ones(1), Cs @ np.ones(1))
        _chk(fails, 0, "col sum0", C.sum(axis=0),
             np.asarray(Cs.sum(axis=0)).ravel())
        assert not fails, fails


def test_solver_eigensolver_battery():
    """Randomized cross-check of the round-3 linalg surface: minres,
    lsqr, lsmr, eigsh, svds, expm_multiply, block_jacobi-preconditioned
    cg, and csgraph — one pooled loop, seeded."""
    import scipy.sparse.csgraph as scsg
    import scipy.sparse.linalg as ssl

    import legate_sparse_tpu.linalg as linalg

    rng = np.random.default_rng(7)
    fails = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for trial in range(3):
            n = int(rng.integers(40, 90))
            # SPD + a symmetric indefinite variant.
            R = sp.random(n, n, density=0.15, format="csr",
                          random_state=rng)
            S = (R + R.T).tocsr()
            spd = (S @ S.T + n * sp.eye(n)).tocsr()
            b = rng.standard_normal(n)

            x, _ = linalg.minres(lst.csr_array(S), b, rtol=1e-10,
                                 maxiter=6000)
            _chk(fails, trial, "minres",
                 np.linalg.norm(S @ np.asarray(x) - b)
                 / np.linalg.norm(b), 0.0, tol=1e-6)

            M = linalg.block_jacobi(lst.csr_array(spd), block_size=16)
            xp, _ = linalg.cg(lst.csr_array(spd), b, M=M, rtol=1e-10,
                              maxiter=4000, conv_test_iters=5)
            _chk(fails, trial, "pcg",
                 np.linalg.norm(spd @ np.asarray(xp) - b)
                 / np.linalg.norm(b), 0.0, tol=1e-6)

            w = linalg.eigsh(lst.csr_array(spd), k=3, which="LA",
                             return_eigenvectors=False)
            w_ref = ssl.eigsh(spd, k=3, which="LA",
                              return_eigenvectors=False)
            _chk(fails, trial, "eigsh", np.sort(w), np.sort(w_ref),
                 tol=1e-6)

            m2 = int(rng.integers(50, 90))
            T = sp.random(m2, n, density=0.2, format="csr",
                          random_state=rng) + sp.vstack(
                [sp.eye(n), sp.csr_matrix((m2 - n, n))]
            ) if m2 >= n else sp.random(m2, n, density=0.2,
                                        format="csr", random_state=rng)
            T = T.tocsr()
            bt = rng.standard_normal(m2)
            for name, fn in (("lsqr", linalg.lsqr),
                             ("lsmr", linalg.lsmr)):
                ref_fn = getattr(ssl, name)
                o = fn(lst.csr_array(T), bt, atol=1e-12, btol=1e-12)
                r = ref_fn(T, bt, atol=1e-12, btol=1e-12)
                _chk(fails, trial, name + "_resid",
                     np.linalg.norm(T @ o[0] - bt),
                     np.linalg.norm(T @ r[0] - bt), tol=1e-5)

            s = linalg.svds(lst.csr_array(T), k=3,
                            return_singular_vectors=False)
            s_ref = ssl.svds(T, k=3, return_singular_vectors=False)
            _chk(fails, trial, "svds", np.sort(s), np.sort(s_ref),
                 tol=1e-6)

            L = (S - sp.diags([S.diagonal()], [0])).tocsr() * 0.1
            _chk(fails, trial, "expm",
                 linalg.expm_multiply(lst.csr_array(L), b),
                 ssl.expm_multiply(L, b), tol=1e-8)

            G = ((abs(R) + abs(R.T)) > 0.5).astype(np.float64).tocsr()
            kcc, lab = lst.csgraph.connected_components(
                lst.csr_array(G), directed=False)
            kcc_r, lab_r = scsg.connected_components(G, directed=False)
            _chk(fails, trial, "cc_k", kcc, kcc_r)
            _chk(fails, trial, "cc_labels", lab, lab_r)
            _chk(fails, trial, "laplacian",
                 lst.csgraph.laplacian(lst.csr_array(G),
                                       normed=True).toarray(),
                 scsg.laplacian(G, normed=True).toarray(), tol=1e-10)
    assert not fails, fails


@pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
def test_differential_battery_complex(dtype):
    # The same cross-op battery over complex operands (reference
    # supports complex across its task families; utils.py:28-33).
    rng = np.random.default_rng(7)
    tol = 1e-4 if np.dtype(dtype) == np.complex64 else 1e-9
    fails = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for trial in range(3):
            m, n = SHAPES[trial % 2]
            d = float(rng.uniform(0.05, 0.3))

            def rnd():
                M = (sp.random(m, n, density=d, random_state=rng)
                     + 1j * sp.random(m, n, density=d,
                                      random_state=rng))
                return sp.csr_array(M).astype(dtype)

            As, Bs = rnd(), rnd()
            A, B = lst.csr_array(As), lst.csr_array(Bs)
            _chk(fails, trial, "add", A + B, As + Bs, tol=tol)
            _chk(fails, trial, "sub", A - B, As - Bs, tol=tol)
            _chk(fails, trial, "multiply", A.multiply(B),
                 As.multiply(Bs), tol=tol)
            _chk(fails, trial, "conjT", A.conj().T,
                 As.conj().T.tocsr(), tol=tol)
            _chk(fails, trial, "sum1", A.sum(axis=1),
                 np.asarray(As.sum(axis=1)).ravel(), tol=tol)
            _chk(fails, trial, "tocsc", A.tocsc(), As.tocsc(), tol=tol)
            if m == n:
                _chk(fails, trial, "spgemm", A @ B, As @ Bs, tol=tol)
                _chk(fails, trial, "diag", A.diagonal(), As.diagonal(),
                     tol=tol)
            x = (rng.standard_normal(n)
                 + 1j * rng.standard_normal(n)).astype(dtype)
            _chk(fails, trial, "spmv", A @ x, As @ x, tol=tol)
            X = (rng.standard_normal((n, 3))
                 + 1j * rng.standard_normal((n, 3))).astype(dtype)
            _chk(fails, trial, "spmm", A @ X, As @ X, tol=tol)
    assert not fails, fails
