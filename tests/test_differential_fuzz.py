# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Randomized differential battery vs scipy: many ops, pooled shapes
(so jit compiles amortize), seeded for reproducibility.  Slow lane —
the unit files cover each op; this net catches cross-op regressions."""

import warnings

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as lst

pytestmark = pytest.mark.slow

SHAPES = [(12, 12), (8, 15)]


def _chk(fails, trial, name, got, want, tol=1e-9):
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    w = np.asarray(want.toarray() if hasattr(want, "toarray") else want)
    if g.shape != w.shape or not np.allclose(g, w, atol=tol,
                                             equal_nan=True):
        fails.append((trial, name))


def test_differential_battery():
    rng = np.random.default_rng(99)
    fails = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for trial in range(6):
            m, n = SHAPES[trial % 2]
            d = float(rng.uniform(0.05, 0.5))
            As = sp.random(m, n, density=d, format="csr",
                           random_state=rng)
            Bs = sp.random(m, n, density=d, format="csr",
                           random_state=rng)
            A, B = lst.csr_array(As), lst.csr_array(Bs)
            _chk(fails, trial, "add", A + B, As + Bs)
            _chk(fails, trial, "sub", A - B, As - Bs)
            _chk(fails, trial, "mul_elem", A * B,
                 sp.csr_array(As) * sp.csr_array(Bs))
            _chk(fails, trial, "maximum", A.maximum(B), As.maximum(Bs))
            _chk(fails, trial, "minimum", A.minimum(B), As.minimum(Bs))
            _chk(fails, trial, "multiply", A.multiply(B),
                 As.multiply(Bs))
            _chk(fails, trial, "ne", A != B,
                 sp.csr_array(As) != sp.csr_array(Bs))
            _chk(fails, trial, "sum0", A.sum(axis=0),
                 np.asarray(As.sum(axis=0)).ravel())
            _chk(fails, trial, "sum1", A.sum(axis=1),
                 np.asarray(As.sum(axis=1)).ravel())
            _chk(fails, trial, "max1", A.max(axis=1),
                 As.max(axis=1).toarray().ravel())
            _chk(fails, trial, "T", A.T, As.T)
            _chk(fails, trial, "tocsc", A.tocsc(), As.tocsc())
            _chk(fails, trial, "tril", lst.tril(A, k=1),
                 sp.tril(As, k=1))
            if m == n:
                _chk(fails, trial, "diag", A.diagonal(), As.diagonal())
                _chk(fails, trial, "spgemm",
                     A @ lst.csr_array(Bs.T.tocsr()), As @ Bs.T.tocsr())
            x = rng.standard_normal(n)
            _chk(fails, trial, "spmv", A @ x, As @ x)
            X = rng.standard_normal((n, 3))
            _chk(fails, trial, "spmm", A @ X, As @ X)
    assert not fails, fails


def test_degenerate_shapes():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fails = []
        Es = sp.csr_array((3, 4))
        E = lst.csr_array((3, 4))
        _chk(fails, 0, "empty+", E + E, Es + Es)
        _chk(fails, 0, "emptyT", E.T, Es.T)
        _chk(fails, 0, "empty spmv", E @ np.ones(4), Es @ np.ones(4))
        Rs = sp.random(1, 9, density=0.5, format="csr", random_state=1)
        R = lst.csr_array(Rs)
        _chk(fails, 0, "row spmv", R @ np.ones(9), Rs @ np.ones(9))
        _chk(fails, 0, "rowT", R.T, Rs.T)
        Cs = sp.random(9, 1, density=0.5, format="csr", random_state=2)
        C = lst.csr_array(Cs)
        _chk(fails, 0, "col spmv", C @ np.ones(1), Cs @ np.ones(1))
        _chk(fails, 0, "col sum0", C.sum(axis=0),
             np.asarray(Cs.sum(axis=0)).ravel())
        assert not fails, fails
