# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed irregular SpMV through the per-shard BSR Pallas kernel
(LEGATE_SPARSE_TPU_PALLAS_DIST=interpret on the CPU mesh)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import dist_spmv, make_row_mesh, shard_csr
from legate_sparse_tpu.parallel.dist_csr import shard_vector


@pytest.fixture
def mesh():
    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_row_mesh(devs[:8])


def _irregular(n=512, density=0.05, seed=0):
    rng = np.random.default_rng(seed)
    A = sp.random(n, n, density=density, format="csr", random_state=rng,
                  dtype=np.float32)
    return A


def test_dist_bsr_prepack_and_matches(mesh, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    A_sp = _irregular()
    n = A_sp.shape[0]
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh,
                   force_all_gather=True)
    # Lazy: the pack is built on first SpMV, not at shard time (other
    # consumers never pay the densification).
    assert dA.bsr_blocks is None and not dA.bsr_tried
    x = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    assert dA.bsr_blocks is not None and dA.bsr_grid is not None, (
        "irregular all_gather matrix should build the BSR prepack"
    )
    np.testing.assert_allclose(y, A_sp @ x, rtol=1e-4, atol=1e-4)


def test_dist_bsr_off_matches_xla(mesh, monkeypatch):
    """Route parity: BSR on vs off produce the same result."""
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    A_sp = _irregular(seed=2)
    n = A_sp.shape[0]
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh,
                   force_all_gather=True)
    x = np.random.default_rng(3).standard_normal(n).astype(np.float32)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y_bsr = np.asarray(dist_spmv(dA, xs))[:n]
    assert dA.bsr_blocks is not None, "BSR route was not active"
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "0")
    y_xla = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y_bsr, y_xla, rtol=1e-5, atol=1e-5)


@pytest.mark.tpu
def test_dist_bsr_kernel_on_chip(monkeypatch):
    """The per-shard BSR route lowers on a real chip inside shard_map
    (1-device mesh)."""
    import jax

    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU")
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "1")
    A_sp = _irregular(n=1024, density=0.02, seed=5)
    n = A_sp.shape[0]
    dA = shard_csr(sparse.csr_array(A_sp), mesh=make_row_mesh(
        jax.devices()[:1]), force_all_gather=True)
    x = np.random.default_rng(6).standard_normal(n).astype(np.float32)
    xs = shard_vector(x, dA.mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    assert dA.bsr_blocks is not None
    np.testing.assert_allclose(y, A_sp @ x, rtol=1e-3, atol=1e-3)
