# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sharded construction tests: matrices built per shard, never as a
host CSR (VERDICT r1 item 5 — the reference's known single-process
construction bottleneck, ``legate_sparse/csr.py:134-145``, must be a
win here, not a tie)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import make_row_mesh, shard_csr, dist_spmv
from legate_sparse_tpu.parallel.dist_build import dist_diags, dist_poisson2d
from legate_sparse_tpu.parallel.dist_csr import dist_cg, shard_vector

needs_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


@needs_multi
@pytest.mark.parametrize("n,offsets", [
    pytest.param(64, [0], marks=pytest.mark.slow),
    (64, [-1, 0, 1]),
    pytest.param(61, [-7, -1, 0, 1, 7],  # non-divisible rows
                 marks=pytest.mark.slow),
    pytest.param(40, [-33, 0, 33],  # reach > rps -> all_gather layout
                 marks=pytest.mark.slow),
])
def test_dist_diags_scalar_bands(n, offsets):
    bands = [float(i + 2) for i in range(len(offsets))]
    dA = dist_diags(bands, offsets, shape=(n, n), dtype=np.float64)
    A_ref = sparse.diags(
        [np.full(n - abs(k), v) for v, k in zip(bands, offsets)],
        offsets, shape=(n, n), format="csr", dtype=np.float64,
    )
    np.testing.assert_allclose(
        dA.to_csr().toscipy().toarray(), A_ref.toscipy().toarray()
    )


@needs_multi
@pytest.mark.slow
def test_dist_diags_array_and_callable_bands():
    n = 50
    rng = np.random.default_rng(1)
    d0 = rng.standard_normal(n)
    dm2 = rng.standard_normal(n - 2)
    dA = dist_diags(
        [d0, dm2, lambda i: jnp.sin(i.astype(jnp.float64))],
        [0, -2, 3],
        shape=(n, n), dtype=np.float64,
    )
    d3 = np.sin(np.arange(n - 3, dtype=np.float64))
    A_ref = sparse.diags([d0, dm2, d3], [0, -2, 3], shape=(n, n),
                         format="csr", dtype=np.float64)
    np.testing.assert_allclose(
        dA.to_csr().toscipy().toarray(), A_ref.toscipy().toarray(),
        atol=1e-14,
    )


@pytest.mark.slow
@needs_multi
def test_dist_poisson2d_matches_host_and_solves():
    N = 24
    n = N * N
    dA = dist_poisson2d(N)
    main = np.full(n, 4.0)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0
    offN = np.full(n - N, -1.0)
    A_ref = sparse.diags([main, off1, off1, offN, offN],
                         [0, 1, -1, N, -N], shape=(n, n), format="csr",
                         dtype=np.float64)
    np.testing.assert_allclose(
        dA.to_csr().toscipy().toarray(), A_ref.toscipy().toarray()
    )
    b = np.ones(n)
    x, iters = dist_cg(dA, b, rtol=1e-8, maxiter=2000)
    res = np.linalg.norm(A_ref.toscipy() @ np.asarray(x) - b)
    assert res <= 1e-8 * np.linalg.norm(b) * 10


@needs_multi
@pytest.mark.slow
def test_dist_diags_spmv_matches_sharded_host_build():
    """dist_diags output behaves identically to shard_csr of the same
    matrix under dist_spmv (same layout invariants)."""
    n = 96
    A = sparse.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(n, n),
                     format="csr", dtype=np.float64)
    mesh = make_row_mesh()
    dA_host = shard_csr(A, mesh=mesh)
    dA_dev = dist_diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(n, n),
                        mesh=mesh, dtype=np.float64)
    assert dA_dev.ell and dA_dev.halo == dA_host.halo
    x = np.linspace(-1, 1, n)
    xs = shard_vector(x, mesh, dA_dev.rows_padded)
    y_dev = np.asarray(dist_spmv(dA_dev, xs))[:n]
    y_host = np.asarray(dist_spmv(dA_host, xs))[:n]
    np.testing.assert_allclose(y_dev, y_host, rtol=1e-14)


@needs_multi
@pytest.mark.slow
def test_scale_1e7_row_build_and_solve():
    """VERDICT done-criterion: construct + run CG on a 1e7-row 5-pt
    Laplacian on the 8-device mesh without a host copy of the CSR."""
    N = 3163                      # N^2 ≈ 1.0003e7 rows
    n = N * N
    dA = dist_poisson2d(N, dtype=np.float32)
    assert dA.shape == (n, n)

    # Construction correctness at scale without any host matrix:
    # (A @ 1)[r] = 4 - #neighbors -> 0 interior, 1 edges, 2 corners.
    ones = shard_vector(jnp.ones((n,), jnp.float32), dA.mesh,
                        dA.rows_padded)
    y = np.asarray(dist_spmv(dA, ones))[:n].reshape(N, N)
    expected = np.zeros((N, N), dtype=np.float32)
    expected[0, :] += 1.0
    expected[-1, :] += 1.0
    expected[:, 0] += 1.0
    expected[:, -1] += 1.0
    np.testing.assert_array_equal(y, expected)

    # CG executes at this scale (residual 2-norm overshoots early on
    # Poisson w/ b=1 — that's textbook CG, so only sanity is asserted).
    b = jnp.ones((n,), dtype=jnp.float32)
    x, iters = dist_cg(dA, b, maxiter=30, rtol=0.0, atol=1e-30)
    x = np.asarray(x)
    assert np.all(np.isfinite(x)) and int(iters) == 30
