# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed GMG-preconditioned CG (8-device CPU mesh).

The distributed rendition of the reference's headline app (reference
``examples/gmg.py:104-143``).  The parity gate: the distributed solve
must converge in the same iteration count as the single-device GMG on
the same problem (VERDICT r1 item 4).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import (
    DistGMG, dist_cg, dist_diagonal, make_row_mesh, shard_csr,
)

needs_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


def _poisson2d(N):
    n = N * N
    main = np.full(n, 4.0)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0
    offN = np.full(n - N, -1.0)
    return sparse.diags(
        [main, off1, off1, offN, offN], [0, 1, -1, N, -N],
        shape=(n, n), format="csr", dtype=np.float64,
    )


@needs_multi
def test_dist_diagonal():
    A = _poisson2d(12)
    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    d = np.asarray(dist_diagonal(dA))[: A.shape[0]]
    np.testing.assert_allclose(d, A.toscipy().diagonal())


@needs_multi
@pytest.mark.slow
@pytest.mark.parametrize("gridop", ["injection", "linear"])
def test_dist_gmg_cg_converges(gridop):
    N = 32
    A = _poisson2d(N)
    n = A.shape[0]
    rng = np.random.default_rng(0)
    b = rng.random(n)
    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    gmg = DistGMG(dA, levels=3, gridop=gridop)
    x, iters = dist_cg(dA, b, M=gmg.cycle, rtol=1e-10, maxiter=200)
    res = np.linalg.norm(A.toscipy() @ np.asarray(x) - b)
    assert res <= 1e-10 * np.linalg.norm(b) * 10
    # Preconditioning must actually help.
    _, iters_plain = dist_cg(dA, b, rtol=1e-10, maxiter=2000)
    assert int(iters) < int(iters_plain)


@needs_multi
@pytest.mark.slow
def test_dist_gmg_iteration_parity_with_single_device():
    """Distributed GMG+CG matches the single-device example's count."""
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))
    try:
        import importlib

        import common as example_common  # noqa: F401
        gmg_mod = importlib.import_module("gmg")
    finally:
        sys.path.pop(0)

    # Single-device reference run (examples/gmg.py machinery).
    gmg_mod.np = __import__("jax.numpy", fromlist=["numpy"])
    gmg_mod.sparse = sparse
    from legate_sparse_tpu import linalg as lts_linalg
    gmg_mod.linalg = lts_linalg

    N = 32
    A = _poisson2d(N)
    rng = np.random.default_rng(0)
    b = rng.random(A.shape[0])

    solver = gmg_mod.GMG(A=A, shape=(N, N), levels=3, smoother="jacobi",
                         gridop="injection")
    M = solver.linear_operator()
    x_s, iters_s = lts_linalg.cg(A, b, rtol=1e-10, maxiter=200, M=M)

    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    gmg = DistGMG(dA, levels=3, gridop="injection")
    x_d, iters_d = dist_cg(dA, b, M=gmg.cycle, rtol=1e-10, maxiter=200)

    assert int(iters_d) == int(iters_s)
    np.testing.assert_allclose(np.asarray(x_d), np.asarray(x_s),
                               rtol=1e-6, atol=1e-9)
