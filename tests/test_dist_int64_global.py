# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""int32-local / int64-global index split (VERDICT r4 #4, SURVEY §7
hard part 5).

The reference runs ``coord_ty = int64`` everywhere
(``legate_sparse/types.py:20-25``); the TPU policy is the split: device
structures are shard-LOCAL int32, global bookkeeping (row offsets,
total nnz) is host-side int64/Python ints.  The capability these tests
pin: a NO-x64 process builds and SpMVs a distributed matrix whose
GLOBAL nnz exceeds 2^31 while every shard stays within int32 —
``coord_dtype_for``'s OverflowError is the single-device boundary only.

The >2^31 run is slow-lane (a ~10 GB DIA-only build on this box); the
default lane proves the same pathway end-to-end at small n.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Template: runs in a SUBPROCESS with x64 hard-disabled (the TPU
# process policy), builds a banded DistCSR shard-locally (no host CSR
# ever exists), SpMVs, and verifies sampled rows exactly against
# host-side references computed with Python ints.
_SNIPPET = r"""
import sys
import numpy as np
from legate_sparse_tpu._platform import pin_cpu
pin_cpu(8)
import jax
jax.config.update("jax_enable_x64", False)   # the TPU-process policy
import jax.numpy as jnp
import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import make_row_mesh
from legate_sparse_tpu.parallel.dist_build import dist_diags
from legate_sparse_tpu.parallel.dist_csr import dist_spmv, shard_vector
from legate_sparse_tpu import types

log2n = int(sys.argv[1])
n = 1 << log2n
offsets = [0, 1, -1, 2, -2, 3, -3, 4, -4]


def val(k):
    if k == 0:
        return 2.0                       # scalar diagonal
    # Callable diagonal: traced on device per shard; the SAME formula
    # re-evaluated on host (numpy int64) for the expected values.
    return lambda i: ((i % 97).astype(jnp.float32) * 0.01 + 0.5 + k * 0.05)


def val_host(k, i):
    if k == 0:
        return np.float32(2.0)
    return np.float32((i % 97) * 0.01 + 0.5 + k * 0.05)


mesh = make_row_mesh(jax.devices())
A = dist_diags([val(k) for k in offsets], offsets, shape=(n, n),
               mesh=mesh, dtype=np.float32, materialize_ell=False)

# --- the int64-global bookkeeping -----------------------------------
gn = A.global_nnz
expected_nnz = sum(n - abs(k) for k in offsets)
assert gn == expected_nnz, (gn, expected_nnz)
starts = A.shard_row_starts
assert starts.dtype == np.int64
assert int(starts[-1]) == (A.num_shards - 1) * A.rows_per_shard

# --- every DEVICE array must be int32-or-narrower / float -----------
for name in ("data", "cols", "counts", "row_ids", "dia_data",
             "dia_mask", "pdia_data", "pdia_mask"):
    arr = getattr(A, name)
    if arr is None:
        continue
    assert np.dtype(arr.dtype).itemsize <= 4, (name, arr.dtype)

# --- SpMV with exact sampled verification ---------------------------
rng = np.random.default_rng(12)
x = ((np.arange(n, dtype=np.int64) * 2654435761) % (1 << 20)
     ).astype(np.float32) / np.float32(1 << 20)
xs = shard_vector(x, mesh, A.rows_padded)
y = np.asarray(dist_spmv(A, xs))[:n]

rps = A.rows_per_shard
samples = sorted(set(
    [0, 1, 4, n // 2, n - 1, n - 5, rps - 1, rps, rps + 1,
     3 * rps - 1, 3 * rps]
    + [int(v) for v in rng.integers(0, n, size=8)]))
for g in samples:
    exp = np.float32(0.0)
    for k in offsets:
        c = g + k
        if 0 <= c < n:
            exp += val_host(k, np.int64(g + min(k, 0))) * x[c]
    got = y[g]
    assert abs(float(got) - float(exp)) <= 1e-4 * max(1.0, abs(float(exp))), (
        g, float(got), float(exp))

assert np.dtype(types.index_dtype()) == np.dtype(np.int32)
print(f"INT64-GLOBAL-OK nnz={gn}")
"""


def _run(log2n: int, timeout_s: int) -> str:
    env = dict(os.environ)
    env.pop("LEGATE_SPARSE_TPU_X64", None)
    env.pop("JAX_ENABLE_X64", None)
    r = subprocess.run([sys.executable, "-c", _SNIPPET, str(log2n)],
                       capture_output=True, text=True, timeout=timeout_s,
                       env=env)
    assert r.returncode == 0, (
        f"rc={r.returncode}\nstdout: {r.stdout[-800:]}\n"
        f"stderr: {r.stderr[-2500:]}"
    )
    assert "INT64-GLOBAL-OK" in r.stdout
    return r.stdout


def test_no_x64_dist_pathway_small():
    out = _run(12, timeout_s=420)          # n=4096: fast sanity
    assert "nnz=" in out


@pytest.mark.slow
def test_no_x64_global_nnz_past_2_31():
    """The VERDICT done-criterion: global nnz > 2^31 in a no-x64
    process, int32 everywhere on device, exact sampled results."""
    out = _run(28, timeout_s=1500)         # n=2^28, 9 diagonals
    nnz = int(out.split("nnz=")[1].split()[0])
    assert nnz > (1 << 31), nnz
