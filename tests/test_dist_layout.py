# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Layout strategies (docs/DIST.md): shard_csr's first-class
``layout`` argument — 1d-row / 1d-col / 2d-block / auto — with the
explicit argument > env > default precedence, the byte-predicting
auto router and its ``shard_csr.routing`` evidence event, the
fingerprint separation the engine's dist-plan ledger relies on, and
scipy-differential parity of the 2-d-block SpMV/SpGEMM programs
against both the 1-D path and the local kernels."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs
from legate_sparse_tpu.obs import trace
from legate_sparse_tpu.parallel import (
    LAYOUTS,
    dist_cg,
    dist_plan_fingerprint,
    dist_spgemm,
    dist_spmm,
    dist_spmv,
    make_grid_mesh,
    make_row_mesh,
    mesh_fingerprint,
    resolve_layout,
    shard_csr,
)
from legate_sparse_tpu.parallel.dist_csr import dist_diagonal, shard_vector
from legate_sparse_tpu.settings import settings

R = len(jax.devices())
needs_grid = pytest.mark.skipif(R < 8, reason="needs the 8-device mesh")


@pytest.fixture(autouse=True)
def _obs_isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was:
        trace.enable()
    else:
        trace.disable()


def _random_csr(n, m=None, density=0.08, dtype=np.float64, seed=0,
                spd=False):
    m = n if m is None else m
    rng = np.random.default_rng(seed)
    A_sp = sp.random(n, m, density=density, random_state=rng,
                     format="csr", dtype=np.float64)
    if spd:
        A_sp = A_sp + A_sp.T + 10.0 * sp.eye(n)
    return A_sp.tocsr().astype(dtype)


# ------------------------------------------------------- resolution --
def test_resolve_layout_precedence(monkeypatch):
    assert resolve_layout(None) == "1d-row"          # default
    monkeypatch.setattr(settings, "dist_layout", "2d-block")
    assert resolve_layout(None) == "2d-block"        # env knob
    assert resolve_layout("1d-row") == "1d-row"      # argument wins
    for lay in LAYOUTS:
        assert resolve_layout(lay) == lay
    with pytest.raises(ValueError, match="unknown dist layout"):
        resolve_layout("3d-torus")


def test_env_knob_reaches_shard_csr(monkeypatch):
    if R < 8:
        pytest.skip("needs the 8-device mesh")
    monkeypatch.setattr(settings, "dist_layout", "2d-block")
    dA = shard_csr(sparse.csr_array(_random_csr(32)),
                   mesh=make_grid_mesh(2, 4))
    assert dA.layout == "2d-block" and dA.grid == (2, 4)
    dB = shard_csr(sparse.csr_array(_random_csr(32)),
                   mesh=make_row_mesh(), layout="1d-row")
    assert dB.layout == "1d-row" and dB.grid is None


@needs_grid
def test_make_grid_mesh_two_int_shorthand():
    mesh = make_grid_mesh(2, 4)
    assert dict(mesh.shape) == {"rows": 2, "cols": 4}
    mesh2 = make_grid_mesh(4, 2)
    assert dict(mesh2.shape) == {"rows": 4, "cols": 2}


# ----------------------------------------------------- fingerprints --
@needs_grid
def test_fingerprints_distinguish_layouts():
    A = sparse.csr_array(_random_csr(64))
    mesh_g = make_grid_mesh(2, 4)
    d2 = shard_csr(A, mesh=mesh_g, layout="2d-block")
    d1 = shard_csr(A, mesh=make_row_mesh(), layout="1d-row")
    assert mesh_fingerprint(d1.mesh, layout=d1.layout) != \
        mesh_fingerprint(d2.mesh, layout=d2.layout)
    # Same device set, different strategy: the layout term alone must
    # split the fingerprint (the dist-plan ledger aliasing hazard).
    assert mesh_fingerprint(mesh_g, layout="1d-row") != \
        mesh_fingerprint(mesh_g, layout="2d-block")
    f2 = dist_plan_fingerprint(d2)
    assert f2.endswith(":g2x4"), f2
    assert dist_plan_fingerprint(d1).endswith(":g-")


@needs_grid
def test_window_decline_keyed_on_layout():
    """Satellite: a 1-D window decline must not replay against a 2-D
    layout of the same matrix shape — the decline key carries the
    mesh+layout fingerprint."""
    import importlib

    _spg = importlib.import_module(
        "legate_sparse_tpu.parallel.dist_spgemm")
    A = sparse.csr_array(_random_csr(64))
    d1 = shard_csr(A, mesh=make_row_mesh(), layout="1d-row")
    d2 = shard_csr(A, mesh=make_grid_mesh(2, 4), layout="2d-block")
    k1 = _spg._decline_key(d1, _spg._layout_of(d1), _spg._layout_of(d1))
    k2 = _spg._decline_key(d2, _spg._layout_of(d2), _spg._layout_of(d2))
    assert k1 != k2
    # The mesh+layout fingerprint term splits the key even when the
    # density bucket agrees (same matrix either way).
    assert k1[2] == k2[2]
    assert k1[-1] != k2[-1]


# ------------------------------------------------------ auto router --
@needs_grid
def test_auto_routing_event_cites_both_predictions():
    trace.enable()
    A = sparse.csr_array(_random_csr(96))       # non-banded
    dA = shard_csr(A, mesh=make_grid_mesh(2, 4), layout="auto")
    assert dA.layout == "2d-block"              # random -> 2-D wins
    evs = [r for r in obs.records() if r["name"] == "shard_csr.routing"]
    at = evs[-1]["attrs"]
    assert at["layout"] == "2d-block"
    assert at["grid"] == (2, 4) and at["shards"] == 8
    assert 0 < at["predicted_2d_bytes"] < at["predicted_1d_bytes"]


@needs_grid
def test_auto_routing_keeps_banded_on_1d():
    """A tridiagonal band halo-exchanges a 1-element boundary in 1-D —
    far below the 2-D program's panel traffic — so auto must keep it
    on 1d-row."""
    trace.enable()
    n = 96
    A = sparse.diags([1.0, 4.0, 1.0], [-1, 0, 1], shape=(n, n),
                     format="csr")
    dA = shard_csr(A, mesh=make_grid_mesh(2, 4), layout="auto")
    assert dA.layout == "1d-row" and dA.grid is None
    evs = [r for r in obs.records() if r["name"] == "shard_csr.routing"]
    at = evs[-1]["attrs"]
    assert at["layout"] == "1d-row"
    assert at["predicted_1d_bytes"] <= at["predicted_2d_bytes"]


# ------------------------------------ satellite: precise precedence --
@pytest.mark.skipif(R < 2, reason="needs a multi-device mesh")
def test_force_all_gather_wins_over_env_precise(monkeypatch):
    """Regression (satellite): with ``LEGATE_SPARSE_PRECISE_IMAGES``
    set at call time, an explicit ``force_all_gather=True`` argument
    used to be silently ignored — argument > env."""
    monkeypatch.setattr(settings, "precise_images", True)
    A = sparse.diags([1.0, 2.0], [-1, 0], shape=(32, 32), format="csr")
    dA = shard_csr(A, mesh=make_row_mesh(), force_all_gather=True)
    assert dA.gather_idx is None       # not the precise realization
    assert dA.halo == -1               # the all_gather realization
    # Env alone (no conflicting argument) still selects precise.
    dP = shard_csr(A, mesh=make_row_mesh())
    assert dP.gather_idx is not None


def test_explicit_precise_conflicts_with_force_all_gather():
    A = sparse.diags([1.0, 2.0], [-1, 0], shape=(32, 32), format="csr")
    with pytest.raises(ValueError, match="conflicts"):
        shard_csr(A, mesh=make_row_mesh(), precise=True,
                  force_all_gather=True)


@needs_grid
def test_precise_rejected_on_2d_layouts():
    A = sparse.csr_array(_random_csr(32))
    with pytest.raises(ValueError, match="1d-row realization"):
        shard_csr(A, mesh=make_grid_mesh(2, 4), layout="2d-block",
                  precise=True)


# ------------------------------------------------ parity (scipy diff) --
@needs_grid
@pytest.mark.parametrize("dtype,tol", [(np.float32, 1e-5),
                                       (np.float64, 1e-12)])
def test_spmv_parity_2d_vs_1d_vs_local(dtype, tol):
    n = 96
    A_sp = _random_csr(n, density=0.08, dtype=dtype, seed=1)
    A = sparse.csr_array(A_sp)
    x = np.linspace(-1.0, 1.0, n).astype(dtype)
    y_local = np.asarray(A @ x)
    y_ref = A_sp @ x

    d2 = shard_csr(A, mesh=make_grid_mesh(2, 4), layout="2d-block")
    x2 = shard_vector(x, d2.mesh, d2.rows_padded, layout=d2.layout)
    y_2d = np.asarray(dist_spmv(d2, x2))[:n]

    d1 = shard_csr(A, mesh=make_row_mesh())
    x1 = shard_vector(x, d1.mesh, d1.rows_padded)
    y_1d = np.asarray(dist_spmv(d1, x1))[:n]

    np.testing.assert_allclose(y_2d, y_ref, rtol=tol, atol=tol)
    np.testing.assert_allclose(y_1d, y_ref, rtol=tol, atol=tol)
    np.testing.assert_allclose(y_local, y_ref, rtol=tol, atol=tol)


@needs_grid
@pytest.mark.slow
@pytest.mark.parametrize("dtype,tol", [
    (np.float32, 1e-5),
    (np.float64, 1e-12),
])
def test_spgemm_parity_2d_vs_1d_vs_local(dtype, tol):
    A_sp = _random_csr(64, 80, density=0.1, dtype=dtype, seed=2)
    B_sp = _random_csr(80, 72, density=0.12, dtype=dtype, seed=3)
    ref = (A_sp @ B_sp).toarray()
    A, B = sparse.csr_array(A_sp), sparse.csr_array(B_sp)
    local = (A @ B).todense()

    mesh_g = make_grid_mesh(2, 4)
    C2 = dist_spgemm(shard_csr(A, mesh=mesh_g, layout="2d-block"),
                     shard_csr(B, mesh=mesh_g, layout="2d-block"))
    mesh_r = make_row_mesh()
    C1 = dist_spgemm(shard_csr(A, mesh=mesh_r),
                     shard_csr(B, mesh=mesh_r))

    np.testing.assert_allclose(np.asarray(C2.to_csr().todense()), ref,
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(C1.to_csr().todense()), ref,
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(local), ref,
                               rtol=tol, atol=tol)
    # The 2-D product is a first-class 2-D operand: chain it.
    sq = _random_csr(64, density=0.1, dtype=np.float64, seed=4)
    dsq = shard_csr(sparse.csr_array(sq), mesh=mesh_g,
                    layout="2d-block")
    D = dist_spgemm(dist_spgemm(dsq, dsq), dsq)
    np.testing.assert_allclose(
        np.asarray(D.to_csr().todense()), (sq @ sq @ sq).toarray(),
        rtol=1e-10, atol=1e-10)


@needs_grid
def test_cg_parity_2d_vs_1d():
    n = 96
    A_sp = _random_csr(n, density=0.08, seed=5, spd=True)
    A = sparse.csr_array(A_sp)
    b = np.linspace(0.5, 1.5, n)
    x2, it2 = dist_cg(shard_csr(A, mesh=make_grid_mesh(2, 4),
                                layout="2d-block"),
                      b, rtol=0.0, maxiter=8)
    x1, it1 = dist_cg(shard_csr(A, mesh=make_row_mesh()),
                      b, rtol=0.0, maxiter=8)
    assert int(it2) == int(it1) == 8
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x1),
                               rtol=1e-10, atol=1e-10)


@needs_grid
def test_1d_col_layout_spmv_parity():
    n = 96
    A_sp = _random_csr(n, density=0.08, seed=6)
    dA = shard_csr(sparse.csr_array(A_sp), mesh=make_row_mesh(),
                   layout="1d-col")
    assert dA.grid == (1, 8)
    x = np.linspace(-1.0, 1.0, n)
    xs = shard_vector(x, dA.mesh, dA.rows_padded, layout=dA.layout)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y, A_sp @ x, rtol=1e-12, atol=1e-12)


# ------------------------------------------------------- guard rails --
@needs_grid
def test_2d_rejects_unsupported_consumers():
    A = sparse.csr_array(_random_csr(64))
    mesh_g = make_grid_mesh(2, 4)
    d2 = shard_csr(A, mesh=mesh_g, layout="2d-block")
    with pytest.raises(NotImplementedError, match="2-d-block"):
        dist_spmm(d2, np.ones((64, 4)))
    with pytest.raises(NotImplementedError, match="2-d-block"):
        dist_diagonal(d2)
    d1 = shard_csr(A, mesh=make_row_mesh())
    with pytest.raises(ValueError):
        dist_spgemm(d2, d1)


@needs_grid
def test_round_trip_and_shard_vector_2d():
    n, m = 56, 72                       # padded on both axes
    A_sp = _random_csr(n, m, density=0.1, seed=7)
    d2 = shard_csr(sparse.csr_array(A_sp), mesh=make_grid_mesh(2, 4),
                   layout="2d-block")
    np.testing.assert_allclose(
        np.asarray(d2.to_csr().todense()), A_sp.toarray())
    x = np.arange(n, dtype=np.float64)
    xs = shard_vector(x, d2.mesh, d2.rows_padded, layout=d2.layout)
    np.testing.assert_allclose(np.asarray(xs)[:n], x)
