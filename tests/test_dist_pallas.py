# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed banded SpMV through the per-shard Mosaic kernel
(LEGATE_SPARSE_TPU_PALLAS_DIST=interpret on the CPU mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import shard_csr, dist_spmv
from legate_sparse_tpu.parallel.dist_csr import shard_vector
from legate_sparse_tpu.parallel.mesh import make_row_mesh


@pytest.fixture
def mesh():
    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_row_mesh(devs[:8])


def _poisson(n_grid, dtype=np.float32):
    n = n_grid * n_grid
    return sparse.diags(
        [-1.0, -1.0, 4.0, -1.0, -1.0],
        [-n_grid, -1, 0, 1, n_grid],
        shape=(n, n), format="csr", dtype=dtype,
    )


@pytest.mark.slow
def test_dist_dia_spmv_pallas_matches(mesh, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    assert dA.dia_data is not None and dA.halo >= 0, "need banded halo mode"
    x = np.linspace(-1.0, 1.0, n).astype(np.float32)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    y_ref = A.toscipy() @ x
    np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-5)


def test_dist_prepack_built_and_routes_match(mesh, monkeypatch):
    """shard_csr pre-blocks the Mosaic layout once (pdia_*); the Pallas
    route over it matches the XLA shifted-add branch exactly."""
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    assert dA.pdia_tile > 0 and dA.pdia_data is not None
    assert dA.pdia_mask is not None
    assert dA.pdia_data.shape[1] == len(dA.dia_offsets)
    x = np.linspace(-2.0, 2.0, n).astype(np.float32)
    xs = shard_vector(x, mesh, dA.rows_padded)
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "0")
    y_xla = np.asarray(dist_spmv(dA, xs))[:n]
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    y_pl = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y_pl, y_xla, rtol=1e-6, atol=1e-6)


def test_dist_prepack_on_builders(mesh, monkeypatch):
    """dist_diags (the memory-lean path) and the banded dist_spgemm
    product also carry the prepack — not just shard_csr."""
    from legate_sparse_tpu.parallel import dist_poisson2d
    from legate_sparse_tpu.parallel.dist_spgemm import dist_spgemm

    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    dA = dist_poisson2d(16, mesh=mesh, dtype=np.float32,
                        materialize_ell=False)
    assert dA.pdia_tile > 0, "dist_diags lost the prepack"
    n = dA.shape[0]
    x = np.linspace(-1.0, 1.0, n).astype(np.float32)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    import scipy.sparse as sp

    # True 2-D Poisson: (i, i+1) coupling is zero across grid-row
    # boundaries (unlike the plain 5-diagonal band in _poisson).
    N = 16
    main = np.full(n, 4.0)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0
    offn = np.full(n - N, -1.0)
    Aref = sp.diags([main, off1, off1, offn, offn],
                    [0, 1, -1, N, -N]).tocsr()
    np.testing.assert_allclose(y, Aref @ x, rtol=1e-5, atol=1e-5)

    dB = shard_csr(_poisson(16), mesh=mesh)
    C = dist_spgemm(dB, dB)
    if C.dia_data is not None:
        assert C.pdia_tile > 0, "banded dist_spgemm product lost prepack"


@pytest.mark.tpu
def test_dist_prepack_kernel_on_chip(monkeypatch):
    """The pre-blocked Mosaic dist kernel lowers and runs on a real
    chip inside shard_map (1-device mesh; ring halo wraps to self and
    must stay masked)."""
    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU")
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "1")
    A = _poisson(32)
    n = A.shape[0]
    dA = shard_csr(A, mesh=make_row_mesh(jax.devices()[:1]))
    assert dA.pdia_tile > 0
    x = np.linspace(-1.0, 1.0, n).astype(np.float32)
    xs = shard_vector(x, dA.mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y, A.toscipy() @ x, rtol=1e-4, atol=1e-4)


def test_dist_dia_spmv_pallas_ieee_nonfinite(mesh, monkeypatch):
    # inf in a halo region another shard's rows never reference must
    # not leak NaN through the ring-wrapped exchange.
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    assert dA.dia_data is not None and dA.halo >= 0
    x = np.ones(n, np.float32)
    x[0] = np.inf  # wraps to the LAST shard's halo via the ring
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    y_ref = A.toscipy() @ x
    # Rows referencing column 0 see inf; the last rows (whose ring halo
    # holds the wrapped inf) must NOT.
    np.testing.assert_array_equal(np.isinf(y), np.isinf(y_ref))
    assert np.isfinite(y[-1])
