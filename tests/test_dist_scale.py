# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Slow-lane distributed tests at non-trivial shapes (VERDICT r3 weak #6).

The default-lane distributed tests use tiny shapes (N=64-129) — enough
to prove wiring, not enough to engage padding budgets, the chunked
dist-SpGEMM expansion, or a precise gather plan whose per-shard windows
actually differ.  Each test here runs one path at a shape where those
mechanisms do real work, differentially against scipy on the 8-device
CPU mesh.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse

pytestmark = pytest.mark.slow


def _mesh():
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_row_mesh(devs[:8])


def _banded(n, W=11, seed=0):
    rng = np.random.default_rng(seed)
    half = W // 2
    offs = list(range(-half, half + 1))
    diags = [rng.normal(size=n - abs(o)) for o in offs]
    A = sparse.diags(diags, offs, shape=(n, n), format="csr")
    S = sp.diags(diags, offs, shape=(n, n), format="csr")
    return A, sp.csr_array(S)


def test_dist_spmv_halo_path_200k_rows():
    # 25k rows per shard; the band reach (5) stays inside one neighbor
    # shard, so this must take the fixed-width ppermute halo path.
    from legate_sparse_tpu.parallel.dist_csr import (
        dist_spmv, shard_csr, shard_vector,
    )

    mesh = _mesh()
    n = 200_000
    A, S = _banded(n)
    dA = shard_csr(A, mesh=mesh)
    assert dA.halo >= 0, "expected the ppermute halo-exchange path"
    x = np.random.default_rng(1).normal(size=n)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y, S @ x, rtol=1e-9, atol=1e-9)


def test_dist_spmv_precise_gather_plan_wide_windows():
    # Long-range coupling (random far columns) defeats the halo
    # detector; with precise=True each shard's exact all_to_all gather
    # plan must still reproduce scipy at a shape where shard column
    # windows genuinely differ.
    from legate_sparse_tpu.parallel.dist_csr import (
        dist_spmv, shard_csr, shard_vector,
    )

    mesh = _mesh()
    n = 40_000
    rng = np.random.default_rng(2)
    nnz_per_row = 8
    rows = np.repeat(np.arange(n), nnz_per_row)
    # Mix of local and far columns: window extents differ per shard.
    local = (rows + rng.integers(-40, 40, size=rows.size)) % n
    far = rng.integers(0, n, size=rows.size)
    cols = np.where(rng.random(rows.size) < 0.8, local, far)
    vals = rng.normal(size=rows.size)
    S = sp.csr_array((vals, (rows, cols)), shape=(n, n))
    S.sum_duplicates()
    A = sparse.csr_array(S)
    dA = shard_csr(A, mesh=mesh, precise=True)
    assert dA.halo < 0 and dA.gather_globals is not None, (
        "expected the precise all_to_all gather plan")
    x = rng.normal(size=n)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y, S @ x, rtol=1e-9, atol=1e-9)


def test_dist_spgemm_chunked_expansion_50k():
    # Product count large enough that the chunked ESC expansion
    # actually iterates (cap below the total products).
    from legate_sparse_tpu.parallel.dist_csr import shard_csr
    from legate_sparse_tpu.parallel.dist_spgemm import dist_spgemm
    from legate_sparse_tpu.settings import settings

    mesh = _mesh()
    n = 50_000
    rng = np.random.default_rng(3)
    S = sp.csr_array(sp.random(n, n, density=2e-4, random_state=rng,
                               data_rvs=lambda k: rng.normal(size=k)))
    # Break banded detection so the general ESC runs.
    S[0, n - 1] = 1.0
    S[n - 1, 0] = 1.0
    S = sp.csr_array(S)
    A = sparse.csr_array(S)
    old = settings.fast_spgemm
    try:
        settings.fast_spgemm = False     # chunked mode
        dA = shard_csr(A, mesh=mesh)
        C = dist_spgemm(dA, dA).to_csr()
    finally:
        settings.fast_spgemm = old
    ref = sp.csr_array(S @ S)
    got = C.toscipy()
    diff = (got - ref)
    denom = max(1.0, float(abs(ref).max()))
    assert abs(diff).max() <= 1e-9 * denom


def test_dist_cg_poisson_256():
    # 65k-row Poisson solve to tolerance across 8 shards.
    from legate_sparse_tpu.parallel.dist_build import dist_poisson2d
    from legate_sparse_tpu.parallel.dist_csr import dist_cg

    mesh = _mesh()
    N = 256
    n = N * N
    dA = dist_poisson2d(N, mesh=mesh)
    b = np.ones(n)
    sol, iters = dist_cg(dA, b, rtol=1e-8)
    S = dA.to_csr().toscipy()
    x = np.asarray(sol).reshape(-1)[:n]
    rnorm = np.linalg.norm(b - S @ x)
    assert rnorm <= 1e-5, f"||r||={rnorm} after {int(iters)} iters"
