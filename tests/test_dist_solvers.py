# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed GMRES / BiCGSTAB (8-device CPU mesh).

The reference runs its solvers transparently on distributed arrays
(Legion); here the single-chip solver loops run over padded sharded
vectors with ``dist_spmv`` as the matvec — reductions lower to psum.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import (
    dist_bicgstab, dist_gmres, make_row_mesh, shard_csr,
)

needs_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


def _nonsym(n):
    """Banded, diagonally dominant, NON-symmetric (upwind convection)."""
    return sparse.diags(
        [-1.0, 4.0, -0.3, -1.0], [-1, 0, 1, 16],
        shape=(n, n), format="csr", dtype=np.float64,
    )


def _ref(n):
    return sp.diags([-1.0, 4.0, -0.3, -1.0], [-1, 0, 1, 16],
                    shape=(n, n)).tocsr()


@needs_multi
@pytest.mark.slow
def test_dist_gmres_converges():
    n = 300  # deliberately not a multiple of the shard count
    A = _nonsym(n)
    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    rng = np.random.default_rng(0)
    b = rng.random(n)
    x, iters = dist_gmres(dA, b, rtol=1e-10, maxiter=600)
    res = np.linalg.norm(_ref(n) @ np.asarray(x) - b)
    assert res <= 1e-8 * np.linalg.norm(b)
    assert x.shape == (n,)


@needs_multi
def test_dist_bicgstab_converges():
    n = 300
    A = _nonsym(n)
    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    rng = np.random.default_rng(1)
    b = rng.random(n)
    x, iters = dist_bicgstab(dA, b, rtol=1e-10, maxiter=2000)
    res = np.linalg.norm(_ref(n) @ np.asarray(x) - b)
    assert res <= 1e-7 * np.linalg.norm(b)


@needs_multi
def test_dist_gmres_callback_sees_unpadded():
    n = 300
    dA = shard_csr(_nonsym(n), mesh=make_row_mesh())
    b = np.ones(n)
    seen = []
    dist_gmres(dA, b, rtol=1e-8, maxiter=100,
               callback=lambda xk: seen.append(np.asarray(xk).shape))
    assert seen and all(s == (n,) for s in seen)


@needs_multi
@pytest.mark.slow
def test_dist_minres_symmetric_indefinite():
    # Symmetric but INDEFINITE banded operator: cg is inapplicable,
    # minres converges; padded rows stay exactly zero.
    n = 300
    rng = np.random.default_rng(2)
    d = rng.standard_normal(n) * 3
    A_sp = sp.diags([np.full(n - 1, 1.0), d, np.full(n - 1, 1.0)],
                    [-1, 0, 1], format="csr")
    A = sparse.csr_array(A_sp)
    from legate_sparse_tpu.parallel import dist_minres

    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    b = rng.standard_normal(n)
    x, iters = dist_minres(dA, b, rtol=1e-10, maxiter=3000)
    res = np.linalg.norm(A_sp @ np.asarray(x) - b)
    assert res <= 1e-7 * np.linalg.norm(b)
    assert x.shape == (n,)

    # Shifted solve: (A - 0.5 I) x = b.
    x2, _ = dist_minres(dA, b, shift=0.5, rtol=1e-10, maxiter=3000)
    res2 = np.linalg.norm((A_sp - 0.5 * sp.eye(n)) @ np.asarray(x2) - b)
    assert res2 <= 1e-7 * np.linalg.norm(b)


@needs_multi
@pytest.mark.parametrize(
    "which", [pytest.param("LA", marks=pytest.mark.slow), "SA"])
def test_dist_eigsh_matches_scipy(which):
    # Padding rows (300 not divisible by 8) must contribute no
    # spurious eigenvalues, even when slow SA convergence escalates
    # the Krylov dimension to the rank cap and triggers restarts.
    n = 300
    main = np.full(n, 4.0)
    off = np.full(n - 1, -1.0)
    A_sp = sp.diags([off, main, off], [-1, 0, 1], format="csr")
    from legate_sparse_tpu.parallel import dist_eigsh

    dA = shard_csr(sparse.csr_array(A_sp), mesh=make_row_mesh())
    w, V = dist_eigsh(dA, k=4, which=which)
    import scipy.sparse.linalg as ssl

    w_ref = ssl.eigsh(A_sp, k=4, which=which, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)
    assert V.shape == (n, 4)
    resid = np.linalg.norm(A_sp @ V - V * w[None, :], axis=0)
    assert np.all(resid < 1e-6)


@needs_multi
def test_dist_eigsh_shift_invert():
    # Distributed shift-invert: the MINRES inner solve nests in the
    # Lanczos scan over the mesh; padding block of (A - sigma I) must
    # not leak (n chosen non-divisible by 8).
    n = 300
    main = np.full(n, 4.0)
    off = np.full(n - 1, -1.0)
    A_sp = sp.diags([off, main, off], [-1, 0, 1], format="csr")
    from legate_sparse_tpu.parallel import dist_eigsh
    import scipy.sparse.linalg as ssl

    dA = shard_csr(sparse.csr_array(A_sp), mesh=make_row_mesh())
    sigma = 3.37          # interior, not an eigenvalue
    w, V = dist_eigsh(dA, k=3, sigma=sigma)
    w_ref = ssl.eigsh(A_sp, k=3, sigma=sigma, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    assert V.shape == (n, 3)
    resid = np.linalg.norm(A_sp @ V - V * np.asarray(w)[None, :],
                           axis=0)
    assert np.all(resid < 1e-5)


@pytest.mark.slow
@needs_multi
def test_dist_eigsh_sm_and_be():
    n = 264
    main = np.full(n, 4.0)
    off = np.full(n - 1, -1.0)
    A_sp = sp.diags([off, main, off], [-1, 0, 1], format="csr")
    from legate_sparse_tpu.parallel import dist_eigsh
    import scipy.sparse.linalg as ssl

    dA = shard_csr(sparse.csr_array(A_sp), mesh=make_row_mesh())
    w_sm = dist_eigsh(dA, k=2, which="SM", return_eigenvectors=False)
    w_ref = ssl.eigsh(A_sp, k=2, sigma=0.0, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w_sm), np.sort(w_ref),
                               rtol=1e-7)
    w_be, _ = dist_eigsh(dA, k=4, which="BE")
    w_be_ref = ssl.eigsh(A_sp, k=4, which="BE",
                         return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w_be), np.sort(w_be_ref),
                               rtol=1e-8)
    # SM with an EXPLICIT sigma: farthest-from-sigma (transformed-SM
    # semantics), not closest — code-review regression.  The dense
    # spectrum referees (scipy's own ARPACK fails to converge on this
    # request — smallest |nu| is the hardest Krylov target).
    w_far = dist_eigsh(dA, k=2, sigma=3.37, which="SM",
                       return_eigenvectors=False)
    full = np.linalg.eigvalsh(A_sp.toarray())
    w_far_ref = full[np.argsort(np.abs(1.0 / (full - 3.37)))[:2]]
    np.testing.assert_allclose(np.sort(w_far), np.sort(w_far_ref),
                               rtol=1e-6)
