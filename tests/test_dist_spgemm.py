# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed SpGEMM differential tests (8-device CPU mesh).

The distributed analog of the reference's GPU single-phase SpGEMM test
coverage (reference ``tests/integration/test_spgemm.py:25-34``), plus
the GMG Galerkin triple product R @ A @ P the op exists to serve
(reference ``examples/gmg.py:90-102``).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import (
    dist_spgemm, dist_spmv, make_row_mesh, shard_csr,
)

needs_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


def _mesh(n=None):
    devs = jax.devices()
    return make_row_mesh(devs if n is None else devs[:n])


def _random_csr(rng, m, n, density=0.08, dtype=np.float64):
    M = sp.random(m, n, density=density, random_state=rng,
                  format="csr", dtype=dtype)
    M.sum_duplicates()
    return M


def _check(dC, C_ref, rtol=1e-10):
    C = dC.to_csr().toscipy()
    assert C.shape == C_ref.shape
    np.testing.assert_allclose(C.toarray(), C_ref.toarray(), rtol=rtol,
                               atol=1e-12)


@needs_multi
@pytest.mark.slow
@pytest.mark.parametrize("shape", [(64, 64, 64), (96, 40, 56), (17, 33, 9)])
def test_dist_spgemm_random(shape):
    rng = np.random.RandomState(7)
    m, k, n = shape
    A_sp = _random_csr(rng, m, k)
    B_sp = _random_csr(rng, k, n)
    mesh = _mesh()
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh)
    dB = shard_csr(sparse.csr_array(B_sp), mesh=mesh)
    _check(dist_spgemm(dA, dB), (A_sp @ B_sp).tocsr())


@needs_multi
def test_dist_spgemm_banded_ell_layout():
    # Banded operands stay under the ELL budget -> exercises the ELL
    # (and halo-rebased) layout path on both sides.
    n = 128
    A = sparse.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n),
                     format="csr", dtype=np.float64)
    mesh = _mesh()
    dA = shard_csr(A, mesh=mesh)
    assert dA.ell, "banded operand should take the ELL layout"
    C_ref = (A.toscipy() @ A.toscipy()).tocsr()
    _check(dist_spgemm(dA, dA), C_ref)


@needs_multi
def test_dist_spgemm_empty_product():
    mesh = _mesh()
    m, k, n = 24, 16, 24
    A_sp = sp.csr_matrix((m, k), dtype=np.float64)
    B_sp = sp.csr_matrix((k, n), dtype=np.float64)
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh)
    dB = shard_csr(sparse.csr_array(B_sp), mesh=mesh)
    dC = dist_spgemm(dA, dB)
    assert dC.to_csr().nnz == 0
    assert dC.shape == (m, n)


@needs_multi
@pytest.mark.slow
def test_dist_spgemm_mixed_layouts():
    # ELL A times padded-CSR B (skewed row lengths defeat the budget).
    rng = np.random.RandomState(3)
    n = 96
    A = sparse.diags([1.0, 3.0, 1.0], [-1, 0, 1], shape=(n, n),
                     format="csr", dtype=np.float64)
    B_sp = _random_csr(rng, n, n, density=0.02)
    # One heavy row blows the ELL padding budget.
    heavy = sp.lil_matrix((n, n), dtype=np.float64)
    heavy[0, :] = 1.0
    B_sp = (B_sp + heavy.tocsr()).tocsr()
    mesh = _mesh()
    dA = shard_csr(A, mesh=mesh)
    dB = shard_csr(sparse.csr_array(B_sp), mesh=mesh)
    assert dA.ell and not dB.ell
    _check(dist_spgemm(dA, dB), (A.toscipy() @ B_sp).tocsr())


@needs_multi
@pytest.mark.slow
def test_dist_galerkin_triple_product():
    """A_c = R @ A @ P — the GMG coarse-operator construction."""
    nf, nc = 64, 32
    A = sparse.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(nf, nf),
                     format="csr", dtype=np.float64)
    # Linear interpolation P (nf x nc) and restriction R = P^T / 2.
    rows, cols, vals = [], [], []
    for i in range(nf):
        c = i // 2
        if c < nc:
            rows.append(i); cols.append(c); vals.append(0.5 + 0.5 * (i % 2))
    P_sp = sp.csr_matrix((vals, (rows, cols)), shape=(nf, nc))
    R_sp = (P_sp.T / 2.0).tocsr()
    mesh = _mesh()
    dA = shard_csr(A, mesh=mesh)
    dP = shard_csr(sparse.csr_array(P_sp), mesh=mesh)
    dR = shard_csr(sparse.csr_array(R_sp), mesh=mesh)
    dAP = dist_spgemm(dA, dP)
    dAc = dist_spgemm(dR, dAP)
    Ac_ref = (R_sp @ (A.toscipy() @ P_sp)).tocsr()
    _check(dAc, Ac_ref)


@needs_multi
@pytest.mark.slow
def test_dist_spgemm_result_feeds_spmv():
    """The padded-CSR product must be directly usable by dist_spmv."""
    n = 80
    A = sparse.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n),
                     format="csr", dtype=np.float64)
    mesh = _mesh()
    dA = shard_csr(A, mesh=mesh)
    dC = dist_spgemm(dA, dA)
    x = np.linspace(0.0, 1.0, n)
    from legate_sparse_tpu.parallel.dist_csr import shard_vector
    xs = shard_vector(x, mesh, dC.rows_padded)
    y = np.asarray(dist_spmv(dC, xs))[:n]
    y_ref = (A.toscipy() @ A.toscipy()) @ x
    np.testing.assert_allclose(y, y_ref, rtol=1e-10, atol=1e-12)


@pytest.mark.slow
@needs_multi
def test_dist_band_spgemm_fast_path():
    """Exactly-banded square operands take the ppermute-halo banded
    product (no all_gather of B): scipy parity + chainability."""
    from legate_sparse_tpu.parallel.dist_csr import shard_vector

    mesh = _mesh()
    n = 256
    offsA = [-1, 0, 1]
    offsB = [-2, 0, 2]
    dA = [np.random.default_rng(i).normal(size=n - abs(o))
          for i, o in enumerate(offsA)]
    dB = [np.random.default_rng(7 + i).normal(size=n - abs(o))
          for i, o in enumerate(offsB)]
    A = sparse.diags(dA, offsA, shape=(n, n), format="csr")
    B = sparse.diags(dB, offsB, shape=(n, n), format="csr")
    SA = sp.diags(dA, offsA, shape=(n, n), format="csr")
    SB = sp.diags(dB, offsB, shape=(n, n), format="csr")
    dAm = shard_csr(A, mesh=mesh)
    dBm = shard_csr(B, mesh=mesh)
    C = dist_spgemm(dAm, dBm)
    assert C.dia_data is not None  # banded path produced a DIA result
    SC = SA @ SB
    np.testing.assert_allclose(
        C.to_csr().todense(), SC.toarray(), rtol=1e-9, atol=1e-12
    )
    assert C.to_csr().nnz == SC.nnz
    x = np.random.default_rng(3).normal(size=n)
    xs = shard_vector(x, mesh, C.rows_padded)
    np.testing.assert_allclose(
        np.asarray(dist_spmv(C, xs))[:n], SC @ x, rtol=1e-8
    )
    # Chained product stays on the banded path.
    C2 = dist_spgemm(C, C)
    assert C2.dia_data is not None
    np.testing.assert_allclose(
        C2.to_csr().todense(), (SC @ SC).toarray(), rtol=1e-8, atol=1e-10
    )


@needs_multi
@pytest.mark.slow
def test_dist_band_spgemm_holey_falls_back():
    """Holey-band operands (masked DIA) must take the general ESC path
    and still match scipy."""
    mesh = _mesh()
    n = 64
    d0 = np.where(np.arange(n) % 4 == 0, 0.0, 2.0)
    A = sparse.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                     format="csr")
    SA = sp.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                  format="csr")
    dAm = shard_csr(A, mesh=mesh)
    assert dAm.dia_mask is not None
    C = dist_spgemm(dAm, dAm)
    SC = SA @ SA
    np.testing.assert_allclose(
        C.to_csr().todense(), SC.toarray(), rtol=1e-9, atol=1e-12
    )


# ---- windowed B realization (VERDICT r4 #3: the reference's min/max
# column image of A, legate_sparse/csr.py:640-666) ----------------------

def _spgemm_mod():
    # The package re-exports the dist_spgemm FUNCTION under the same
    # name, shadowing the submodule attribute — resolve via importlib.
    import importlib
    return importlib.import_module(
        "legate_sparse_tpu.parallel.dist_spgemm")


@pytest.mark.slow
@needs_multi
def test_windowed_b_banded_general_path():
    """A holey band drives the general ESC with a narrow A-column
    window: the B realization must be the ppermute window, not the full
    all_gather, and match scipy exactly."""
    mod = _spgemm_mod()
    mesh = _mesh()
    R = int(mesh.shape["rows"])
    if R < 3:
        pytest.skip("window plan needs R > 2")
    n = 128
    d0 = np.where(np.arange(n) % 3 == 0, 0.0, 2.0)
    A = sparse.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                     format="csr")
    SA = sp.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                  format="csr")
    dAm = shard_csr(A, mesh=mesh)
    assert dAm.dia_mask is not None      # general path, not banded fast
    C = mod.dist_spgemm(dAm, dAm)
    assert mod.LAST_B_REALIZATION == "window"
    first, nblk, d_fwd, d_bwd = mod.LAST_B_PLAN
    assert nblk <= max(2, R // 2), (nblk, R)
    assert d_fwd + d_bwd < R
    np.testing.assert_allclose(
        C.to_csr().todense(), (SA @ SA).toarray(), rtol=1e-9, atol=1e-12
    )


@needs_multi
def test_windowed_b_rectangular_galerkin():
    """Rectangular operands (halo=-1, global-column layout): the
    Galerkin A @ P product still takes the windowed realization for the
    banded A and matches scipy."""
    mod = _spgemm_mod()
    mesh = _mesh()
    R = int(mesh.shape["rows"])
    if R < 3:
        pytest.skip("window plan needs R > 2")
    nf, nc = 96, 48
    A = sparse.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(nf, nf),
                     format="csr", dtype=np.float64)
    rows, cols, vals = [], [], []
    for i in range(nf):
        c = i // 2
        if c < nc:
            rows.append(i); cols.append(c); vals.append(1.0)
    P_sp = sp.csr_matrix((vals, (rows, cols)), shape=(nf, nc))
    dP = shard_csr(sparse.csr_array(P_sp), mesh=mesh)
    # A @ P: A is square banded but P is rectangular, so the product
    # runs the general ESC; A's narrow window must realize only a few
    # of P's row blocks.
    dA = shard_csr(sparse.csr_array(A.toscipy()), mesh=mesh,
                   force_all_gather=True)
    C = mod.dist_spgemm(dA, dP)
    assert mod.LAST_B_REALIZATION == "window"
    _check(C, (A.toscipy() @ P_sp).tocsr())


@needs_multi
def test_dense_a_column_window_falls_back_to_all_gather():
    """A matrix whose rows span the full column range defeats the
    window (nblk ~ R): the plan must decline and the all_gather
    realization still produce exact results."""
    mod = _spgemm_mod()
    mesh = _mesh()
    rng = np.random.RandomState(11)
    n = 64
    A_sp = _random_csr(rng, n, n, density=0.3)
    B_sp = _random_csr(rng, n, n, density=0.1)
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh)
    dB = shard_csr(sparse.csr_array(B_sp), mesh=mesh)
    C = mod.dist_spgemm(dA, dB)
    assert mod.LAST_B_REALIZATION == "all_gather"
    _check(C, (A_sp @ B_sp).tocsr())


@needs_multi
def test_window_drift_does_not_recompile(monkeypatch):
    """Per-shard window starts are a traced operand, not a jit key:
    sparsity drift between calls (same static window shape) must reuse
    the compiled phase programs (code-review r5 finding)."""
    mod = _spgemm_mod()
    mesh = _mesh()
    R = int(mesh.shape["rows"])
    if R < 3:
        pytest.skip("window plan needs R > 2")
    n = 128
    d0 = np.where(np.arange(n) % 3 == 0, 0.0, 2.0)
    A = sparse.diags([d0, np.ones(n - 1)], [0, 1], shape=(n, n),
                     format="csr")
    dAm = shard_csr(A, mesh=mesh)
    real_plan = mod._b_window_plan
    shift = {"v": 0}

    def drifting(Aa, la, lb, arrays):
        out = real_plan(Aa, la, lb, arrays)
        if out is None:
            return None
        first, (nblk, d_fwd, d_bwd) = out
        # Pad the static window by one block so BOTH drifted variants
        # still cover every needed block (results stay exact, so the
        # data-dependent T_cap/nnz_cap keys stay identical); only the
        # per-shard starts differ between the two calls.
        static = (nblk + 1, d_fwd + 1, d_bwd)
        if shift["v"] == 0:
            return np.maximum(first - 1, 0).astype(np.int32), static
        return first.astype(np.int32), static

    monkeypatch.setattr(mod, "_b_window_plan", drifting)
    C1 = mod.dist_spgemm(dAm, dAm)
    assert mod.LAST_B_REALIZATION == "window"
    before = (mod._esc_t_fn.cache_info().misses,
              mod._esc_nnz_fn.cache_info().misses,
              mod._esc_numeric_fn.cache_info().misses)
    shift["v"] = 1
    C2 = mod.dist_spgemm(dAm, dAm)
    after = (mod._esc_t_fn.cache_info().misses,
             mod._esc_nnz_fn.cache_info().misses,
             mod._esc_numeric_fn.cache_info().misses)
    assert after == before, (
        f"window drift recompiled phase fns: {before} -> {after}")
    # Both drifted windows cover every needed block: results exact.
    ref = (A.toscipy() @ A.toscipy()).toarray()
    np.testing.assert_allclose(C1.to_csr().toarray(), ref, rtol=1e-12)
    np.testing.assert_allclose(C2.to_csr().toarray(), ref, rtol=1e-12)


@needs_multi
@pytest.mark.slow
def test_windowed_b_fraction_much_less_than_one_at_scale():
    """Slow-lane scaling assertion (VERDICT r4 #3 'done' criterion):
    for a banded A at a scale where each shard holds many rows, the
    gathered fraction of B is ≪ 1."""
    mod = _spgemm_mod()
    mesh = _mesh()
    R = int(mesh.shape["rows"])
    if R < 4:
        pytest.skip("fraction assertion needs R >= 4")
    n = 1024
    d0 = np.where(np.arange(n) % 5 == 0, 0.0, 4.0)
    A = sparse.diags([d0, np.ones(n - 1), np.ones(n - 2)], [0, 1, 2],
                     shape=(n, n), format="csr")
    SA = sp.diags([d0, np.ones(n - 1), np.ones(n - 2)], [0, 1, 2],
                  shape=(n, n), format="csr")
    dAm = shard_csr(A, mesh=mesh)
    assert dAm.dia_mask is not None
    C = mod.dist_spgemm(dAm, dAm)
    assert mod.LAST_B_REALIZATION == "window"
    first, nblk, d_fwd, d_bwd = mod.LAST_B_PLAN
    gathered_fraction = nblk / R
    assert gathered_fraction <= 0.5, (nblk, R)
    # Traffic bound: the rotation chain moves d_fwd + d_bwd blocks per
    # shard vs R - 1 for all_gather.
    assert (d_fwd + d_bwd) / (R - 1) <= 0.5
    np.testing.assert_allclose(
        C.to_csr().todense(), (SA @ SA).toarray(), rtol=1e-9, atol=1e-12
    )
