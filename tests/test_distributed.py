# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distribution tests on the virtual 8-device CPU mesh (the analog of
the reference's multi-rank legate.tester runs, SURVEY §4)."""

import numpy as np
import pytest

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import (
    DistCSR, dist_cg, dist_spmv, make_row_mesh, shard_csr,
)
from legate_sparse_tpu.parallel.dist_csr import shard_vector
from utils_test.gen import banded_matrix, random_csr


requires_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


@requires_multi
@pytest.mark.parametrize("N", [64, 129])
@pytest.mark.parametrize("nnz_per_row", [3, 11])
def test_dist_spmv_banded_halo(N, nnz_per_row):
    s = banded_matrix(N, nnz_per_row)
    A = sparse.csr_array(s)
    D = shard_csr(A)
    assert D.halo >= 0, "banded matrix should take the halo-exchange path"
    x = np.random.default_rng(0).standard_normal(N)
    x_sh = shard_vector(x, D.mesh, D.rows_padded)
    y = dist_spmv(D, x_sh)
    np.testing.assert_allclose(np.asarray(y)[:N], s @ x, atol=1e-12)


@requires_multi
def test_dist_spmv_random_allgather():
    N = 100
    s = random_csr(N, N, 0.2, 3)
    A = sparse.csr_array(s)
    D = shard_csr(A, force_all_gather=True)
    assert D.halo == -1
    x = np.random.default_rng(1).standard_normal(N)
    x_sh = shard_vector(x, D.mesh, D.rows_padded)
    y = dist_spmv(D, x_sh)
    np.testing.assert_allclose(np.asarray(y)[:N], s @ x, atol=1e-12)


@requires_multi
def test_dist_spmv_rectangular():
    N, M = 48, 80
    s = random_csr(N, M, 0.3, 7)
    A = sparse.csr_array(s)
    D = shard_csr(A)
    assert D.halo == -1  # rectangular -> all_gather path
    x = np.random.default_rng(2).standard_normal(M)
    # x for rectangular case: padded to shard count * ceil — here x is
    # gathered fully, shard layout just needs divisibility.
    x_sh = shard_vector(
        x, D.mesh, int(np.ceil(M / D.num_shards)) * D.num_shards
    )
    y = dist_spmv(D, x_sh)
    np.testing.assert_allclose(np.asarray(y)[:N], s @ x, atol=1e-12)


@requires_multi
def test_dist_cg_poisson():
    # 1-D Poisson (tridiagonal SPD) solved across 8 shards.
    import scipy.sparse as scsp

    N = 256
    s = scsp.diags([-1.0, 4.0, -1.0], [-1, 0, 1], shape=(N, N)).tocsr()
    A = sparse.csr_array(s)
    D = shard_csr(A)
    rng = np.random.default_rng(4)
    x_true = rng.standard_normal(N)
    b = s @ x_true
    x, iters = dist_cg(D, b, tol=1e-10, maxiter=2000)
    np.testing.assert_allclose(np.asarray(x), x_true, atol=1e-6)
    assert int(iters) > 0


@requires_multi
def test_dist_matches_single_device():
    N = 90
    s = banded_matrix(N, 5)
    A = sparse.csr_array(s)
    D = shard_csr(A)
    x = np.random.default_rng(6).standard_normal(N)
    y_single = A @ x
    x_sh = shard_vector(x, D.mesh, D.rows_padded)
    y_dist = dist_spmv(D, x_sh)
    np.testing.assert_allclose(
        np.asarray(y_dist)[:N], np.asarray(y_single), atol=1e-12
    )
