# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-native eigensolvers (eigen.py) vs scipy.sparse.linalg.

The reference has no eigensolver surface (its linalg is cg/gmres only,
reference ``legate_sparse/linalg.py``); these are differential tests in
the same style as the solver tests — small SPD / rectangular systems
checked against host scipy.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as ssl

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


def _lap1d(n, dtype=np.float64):
    main = np.full(n, 4.0)
    off = np.full(n - 1, -1.0)
    A_sp = sp.diags([off, main, off], [-1, 0, 1], format="csr").astype(dtype)
    return A_sp, sparse.csr_array(A_sp)


@pytest.mark.parametrize("which", ["LA", "SA", "LM"])
def test_eigsh_native_matches_scipy(which):
    A_sp, A = _lap1d(120)
    w, v = linalg.eigsh(A, k=4, which=which)
    w_ref = ssl.eigsh(A_sp, k=4, which=which,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)
    resid = np.linalg.norm(A_sp @ v - v * w[None, :], axis=0)
    assert np.all(resid < 1e-6)


@pytest.mark.slow
def test_eigsh_f32_and_linear_operator():
    A_sp, A = _lap1d(90, np.float32)
    w, _ = linalg.eigsh(A, k=3, which="LA")
    w_ref = ssl.eigsh(A_sp.astype(np.float64), k=3, which="LA",
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-4)

    op = linalg.LinearOperator(A.shape, matvec=lambda x: A @ x,
                               dtype=np.float32)
    w2 = linalg.eigsh(op, k=3, which="LA", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w2), np.sort(w_ref), rtol=1e-4)


def test_eigsh_complex_hermitian():
    n = 80
    A_sp, _ = _lap1d(n)
    H = (A_sp.astype(np.complex128)
         + 1j * sp.diags([np.full(n - 1, 0.5)], [1])
         - 1j * sp.diags([np.full(n - 1, 0.5)], [-1])).tocsr()
    w, _ = linalg.eigsh(sparse.csr_array(H), k=3, which="LA")
    w_ref = ssl.eigsh(H, k=3, which="LA", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)


def test_eigsh_shift_invert_native_matches_scipy():
    # sigma now runs NATIVELY (inexact MINRES inner solve); the scipy
    # comparison is unchanged from when this path was a host fallback.
    A_sp, A = _lap1d(60)
    w, _ = linalg.eigsh(A, k=2, sigma=1.0)
    w_ref = ssl.eigsh(A_sp, k=2, sigma=1.0, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)


def _no_fallback(monkeypatch):
    """Fail the test if any eigen path touches the host-scipy boundary."""
    from legate_sparse_tpu import eigen as eig_mod

    def boom(name):
        raise AssertionError(f"_host_fallback({name!r}) used on a "
                             "native path")

    monkeypatch.setattr(eig_mod, "_host_fallback", boom)


@pytest.mark.parametrize("dtype,rtol", [
    (np.float32, 2e-3), (np.float64, 1e-8),
])
def test_eigsh_sigma_native_dtypes_no_fallback(monkeypatch, dtype, rtol):
    _no_fallback(monkeypatch)
    A_sp, A = _lap1d(80, dtype)
    # Interior shift (A - sigma I indefinite), NOT an exact eigenvalue:
    # 3.0 is one for n=80 (4 - 2cos(27*pi/81) exactly).
    sigma = 3.3
    w, v = linalg.eigsh(A, k=3, sigma=sigma)
    w_ref = ssl.eigsh(A_sp.astype(np.float64), k=3, sigma=sigma,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=rtol)
    # Residuals judged in the ORIGINAL spectrum.
    resid = np.linalg.norm(
        A_sp.astype(np.float64) @ v - v * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < (1e-5 if dtype == np.float64 else 2e-2))


def test_eigsh_sigma_complex_hermitian_no_fallback(monkeypatch):
    _no_fallback(monkeypatch)
    n = 64
    A_sp, _ = _lap1d(n)
    H = (A_sp.astype(np.complex128)
         + 1j * sp.diags([np.full(n - 1, 0.5)], [1])
         - 1j * sp.diags([np.full(n - 1, 0.5)], [-1])).tocsr()
    sigma = 2.5
    w, v = linalg.eigsh(sparse.csr_array(H), k=3, sigma=sigma)
    w_ref = ssl.eigsh(H, k=3, sigma=sigma, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    resid = np.linalg.norm(H @ v - v * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def test_eigsh_sigma_complex64(monkeypatch):
    _no_fallback(monkeypatch)
    n = 48
    A_sp, _ = _lap1d(n)
    H = (A_sp.astype(np.complex64)
         + 1j * sp.diags([np.full(n - 1, 0.5)], [1]).astype(np.complex64)
         - 1j * sp.diags([np.full(n - 1, 0.5)], [-1]).astype(np.complex64)
         ).tocsr()
    w, _ = linalg.eigsh(sparse.csr_array(H), k=2, sigma=2.0)
    w_ref = ssl.eigsh(H.astype(np.complex128), k=2, sigma=2.0,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=5e-3)


def test_eigs_sigma_native_real_no_fallback(monkeypatch):
    _no_fallback(monkeypatch)
    n = 60
    rng = np.random.default_rng(5)
    # Nonsymmetric, diagonally dominant, WELL-SEPARATED spectrum (the
    # varied diagonal): an inexact inner solve needs sigma at a sane
    # distance from the nearest eigenvalue, unlike ARPACK's exact splu.
    A_sp = (sp.diags([np.linspace(1.0, 12.0, n),
                      0.3 * rng.uniform(-1, 1, n - 1),
                      0.3 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    sigma = 5.03
    w, v = linalg.eigs(sparse.csr_array(A_sp), k=3, sigma=sigma)
    w_ref = ssl.eigs(A_sp, k=3, sigma=sigma, return_eigenvectors=False)
    key = np.argsort(np.real(w))
    key_ref = np.argsort(np.real(w_ref))
    np.testing.assert_allclose(np.asarray(w)[key], w_ref[key_ref],
                               rtol=1e-6, atol=1e-8)
    resid = np.linalg.norm(
        A_sp @ v - v * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def test_eigs_sigma_complex_shift(monkeypatch):
    _no_fallback(monkeypatch)
    n = 50
    rng = np.random.default_rng(9)
    A_sp = (sp.diags([np.linspace(1.0, 10.0, n),
                      0.3 * rng.uniform(-1, 1, n - 1),
                      0.3 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    sigma = 4.55 + 0.3j   # complex shift on a REAL operator
    w, _ = linalg.eigs(sparse.csr_array(A_sp), k=2, sigma=sigma)
    # Reference: the dense spectrum's 2 closest eigenvalues to sigma.
    # (scipy's ARPACK path for a complex sigma on a REAL matrix
    # reconstructs lambda from Re[(A-sigma I)^-1] via an ambiguous
    # quadratic and can return junk — the dense eig is the honest
    # referee here.)
    full = np.linalg.eigvals(A_sp.toarray())
    w_ref = full[np.argsort(np.abs(full - sigma))[:2]]
    # Conjugate pairs tie on the real part: order by (real, imag).
    w = np.asarray(w)
    key = np.lexsort((np.imag(w), np.real(w)))
    key_ref = np.lexsort((np.imag(w_ref), np.real(w_ref)))
    np.testing.assert_allclose(w[key], w_ref[key_ref],
                               rtol=1e-6, atol=1e-8)


def test_lobpcg_complex_hermitian_native(monkeypatch):
    _no_fallback(monkeypatch)
    n = 72
    A_sp, _ = _lap1d(n)
    H = (A_sp.astype(np.complex128)
         + 1j * sp.diags([np.full(n - 1, 0.4)], [1])
         - 1j * sp.diags([np.full(n - 1, 0.4)], [-1])).tocsr()
    X = np.random.default_rng(2).standard_normal((n, 3))
    w, U = linalg.lobpcg(sparse.csr_array(H), X, largest=False)
    w_ref = ssl.eigsh(H, k=3, which="SA", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    resid = np.linalg.norm(H @ U - U * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def test_eigsh_sm_native_no_fallback(monkeypatch):
    # which='SM' without sigma: native shift-invert at 0 (largest of
    # A^{-1}) — no host boundary for a well-conditioned operator.
    _no_fallback(monkeypatch)
    A_sp, A = _lap1d(80)                  # spectrum in (2, 6)
    w, v = linalg.eigsh(A, k=3, which="SM")
    w_ref = ssl.eigsh(A_sp, k=3, sigma=0.0, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)
    resid = np.linalg.norm(A_sp @ v - v * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-6)


def test_eigs_sm_native_no_fallback(monkeypatch):
    _no_fallback(monkeypatch)
    n = 50
    rng = np.random.default_rng(8)
    A_sp = (sp.diags([np.linspace(1.0, 9.0, n),
                      0.2 * rng.uniform(-1, 1, n - 1),
                      0.2 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    w, _ = linalg.eigs(sparse.csr_array(A_sp), k=2, which="SM")
    full = np.linalg.eigvals(A_sp.toarray())
    w_ref = full[np.argsort(np.abs(full))[:2]]
    np.testing.assert_allclose(np.sort(np.real(w)),
                               np.sort(np.real(w_ref)), rtol=1e-6)


def test_eigsh_sm_with_explicit_sigma_native(monkeypatch):
    # scipy semantics: under shift-invert, SM refers to the TRANSFORMED
    # spectrum — smallest |nu| = eigenvalues FARTHEST from sigma.
    _no_fallback(monkeypatch)
    A_sp, A = _lap1d(80)
    sigma = 3.3
    w = linalg.eigsh(A, k=2, sigma=sigma, which="SM",
                     return_eigenvectors=False)
    # Dense referee: scipy's own ARPACK fails to converge on this
    # request (smallest |nu| is the hardest Krylov target; the native
    # escalation reaches the exact full-space answer instead).
    full = np.linalg.eigvalsh(A_sp.toarray())
    w_ref = full[np.argsort(np.abs(1.0 / (full - sigma)))[:2]]
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)


def test_eigsh_sm_singular_falls_back_to_host(monkeypatch):
    # Singular A: the probe solve detects the stagnating inexact
    # inverse (a pseudo-inverse apply would silently DROP the null
    # eigenvalue while passing every residual test) and SM serves
    # through host ARPACK's direct mode.  scipy parity is matching
    # scipy's OWN answer — its direct SM mode also returns [1, 2] on
    # this matrix, not [0, 1].
    from legate_sparse_tpu import eigen as eig_mod

    used = []
    real = eig_mod._host_fallback

    def spy(name):
        used.append(name)
        return real(name)

    monkeypatch.setattr(eig_mod, "_host_fallback", spy)
    n = 24
    d = np.arange(n, dtype=np.float64)    # eigenvalue 0 present
    A_sp = sp.diags([d], [0]).tocsr()
    A = sparse.csr_array(A_sp)
    w = linalg.eigsh(A, k=2, which="SM", return_eigenvectors=False)
    assert used == ["eigsh"], "singular SM must take the host boundary"
    w_ref = ssl.eigsh(A_sp, k=2, which="SM", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), atol=1e-8)


def test_eigs_generalized_native_no_fallback(monkeypatch):
    # Non-symmetric pencil A x = lambda M x, SPD M: Arnoldi on M^{-1}A
    # with an inner CG — no transform needed, eigenvalues are the
    # pencil's directly.
    _no_fallback(monkeypatch)
    n = 60
    rng = np.random.default_rng(3)
    A_sp = (sp.diags([np.linspace(1.0, 9.0, n),
                      0.3 * rng.uniform(-1, 1, n - 1),
                      0.3 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    M_sp = _mass_matrix(n)
    w, v = linalg.eigs(sparse.csr_array(A_sp), k=3,
                       M=sparse.csr_array(M_sp), which="LM")
    w_ref = ssl.eigs(A_sp, k=3, M=M_sp, which="LM",
                     return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(np.real(w)),
                               np.sort(np.real(w_ref)), rtol=1e-6)
    resid = np.linalg.norm(
        A_sp @ v - (M_sp @ v) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def test_eigs_generalized_shift_invert(monkeypatch):
    _no_fallback(monkeypatch)
    n = 56
    rng = np.random.default_rng(4)
    A_sp = (sp.diags([np.linspace(1.0, 10.0, n),
                      0.25 * rng.uniform(-1, 1, n - 1),
                      0.25 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    M_sp = _mass_matrix(n)
    sigma = 5.02
    w, v = linalg.eigs(sparse.csr_array(A_sp), k=2,
                       M=sparse.csr_array(M_sp), sigma=sigma)
    w_ref = ssl.eigs(A_sp, k=2, M=M_sp, sigma=sigma,
                     return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(np.real(w)),
                               np.sort(np.real(w_ref)), rtol=1e-6,
                               atol=1e-8)
    resid = np.linalg.norm(
        A_sp @ v - (M_sp @ v) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def test_eigs_generalized_returns_complex_dtype(monkeypatch):
    # scipy contract: eigs eigenvalues are complex even when the
    # Hessenberg spectrum happens to be all-real (code-review r5).
    _no_fallback(monkeypatch)
    n = 40
    rng = np.random.default_rng(1)
    A_sp = (sp.diags([np.linspace(1.0, 8.0, n),
                      0.2 * rng.uniform(-1, 1, n - 1),
                      0.2 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    M_sp = _mass_matrix(n)
    w = linalg.eigs(sparse.csr_array(A_sp), k=2,
                    M=sparse.csr_array(M_sp),
                    return_eigenvectors=False)
    assert np.iscomplexobj(np.asarray(w))


def test_eigs_sm_sigma_near_eigenvalue_falls_back():
    # sigma pathologically close to an eigenvalue: the probe stagnates
    # and SM must serve through host ARPACK instead of raising
    # (code-review r5 repro).
    n = 40
    A_sp = sp.diags([np.arange(1.0, n + 1.0)], [0]).tocsr()
    w = linalg.eigs(sparse.csr_array(A_sp), k=2, sigma=3.0 + 1e-13,
                    which="SM", return_eigenvectors=False)
    full = np.arange(1.0, n + 1.0)
    w_ref = full[np.argsort(np.abs(1.0 / (full - 3.0)))[:2]]
    np.testing.assert_allclose(np.sort(np.real(w)), np.sort(w_ref),
                               rtol=1e-6)


def test_lobpcg_complex_nonconvergence_returns_not_raises():
    # scipy's lobpcg contract: non-convergence returns the current
    # approximation with a warning, never raises (code-review r5).
    n = 72
    A_sp, _ = _lap1d(n)
    H = (A_sp.astype(np.complex128)
         + 1j * sp.diags([np.full(n - 1, 0.4)], [1])
         - 1j * sp.diags([np.full(n - 1, 0.4)], [-1])).tocsr()
    X = np.random.default_rng(4).standard_normal((n, 3))
    with pytest.warns(UserWarning, match="did not converge"):
        w, U = linalg.lobpcg(sparse.csr_array(H), X, maxiter=1,
                             tol=1e-30, largest=False)
    assert w.shape == (3,) and U.shape == (n, 3)
    assert np.all(np.isfinite(w))


def test_eigsh_complex_sigma_raises_like_scipy():
    # scipy: float(sigma) raises TypeError for a complex shift; the
    # native path must not silently truncate to the real part.
    _, A = _lap1d(30)
    with pytest.raises(TypeError):
        linalg.eigsh(A, k=2, sigma=1.0 + 0.5j)
    with pytest.raises(TypeError):
        # Even a zero imaginary part: float(complex) raises in scipy.
        linalg.eigsh(A, k=2, sigma=1.0 + 0j)


def test_eigsh_sigma_generalized_native(monkeypatch):
    # sigma AND M together: native mode-3 (M-inner Lanczos on
    # (A - sigma M)^{-1} M with an inexact MINRES inner solve).
    _no_fallback(monkeypatch)
    A_sp, A = _lap1d(40)
    M_sp = sp.eye(40).tocsr() * 2.0
    w, _ = linalg.eigsh(A, k=2, sigma=1.0, M=sparse.csr_array(M_sp))
    w_ref = ssl.eigsh(A_sp, k=2, sigma=1.0, M=M_sp,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)


def test_eigsh_sigma_generalized_mass_matrix(monkeypatch):
    _no_fallback(monkeypatch)
    n = 80
    A_sp, A = _lap1d(n)
    M_sp = _mass_matrix(n)
    sigma = 3.1                  # interior shift of the pencil
    w, v = linalg.eigsh(A, k=3, sigma=sigma, M=sparse.csr_array(M_sp))
    w_ref = ssl.eigsh(A_sp, k=3, sigma=sigma, M=M_sp,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    resid = np.linalg.norm(
        A_sp @ v - (M_sp @ v) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def _mass_matrix(n, dtype=np.float64):
    # SPD tridiagonal mass matrix (FEM-style), strictly diagonally
    # dominant so the inner CG converges fast.
    return sp.diags([np.full(n - 1, 1.0), np.full(n, 4.0),
                     np.full(n - 1, 1.0)], [-1, 0, 1],
                    format="csr").astype(dtype) / 6.0


@pytest.mark.parametrize("which", ["LA", "SA", "LM"])
def test_eigsh_generalized_native_matches_scipy(monkeypatch, which):
    _no_fallback(monkeypatch)
    n = 80
    A_sp, A = _lap1d(n)
    M_sp = _mass_matrix(n)
    w, v = linalg.eigsh(A, k=3, M=sparse.csr_array(M_sp), which=which)
    w_ref = ssl.eigsh(A_sp, k=3, M=M_sp, which=which,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    # Pencil residuals + M-orthonormality of the returned vectors.
    resid = np.linalg.norm(
        A_sp @ v - (M_sp @ v) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)
    gram = v.T @ (M_sp @ v)
    np.testing.assert_allclose(gram, np.eye(3), atol=1e-7)


@pytest.mark.slow
def test_eigsh_generalized_complex_hermitian(monkeypatch):
    _no_fallback(monkeypatch)
    n = 64
    A_sp, _ = _lap1d(n)
    H = (A_sp.astype(np.complex128)
         + 1j * sp.diags([np.full(n - 1, 0.3)], [1])
         - 1j * sp.diags([np.full(n - 1, 0.3)], [-1])).tocsr()
    M_sp = _mass_matrix(n)
    w, v = linalg.eigsh(sparse.csr_array(H), k=2,
                        M=sparse.csr_array(M_sp), which="LA")
    w_ref = ssl.eigsh(H, k=2, M=M_sp, which="LA",
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    resid = np.linalg.norm(
        H @ v - (M_sp @ v) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


@pytest.mark.parametrize(
    "largest", [pytest.param(True, marks=pytest.mark.slow), False])
def test_lobpcg_generalized_native(monkeypatch, largest):
    _no_fallback(monkeypatch)
    n = 72
    A_sp, A = _lap1d(n)
    B_sp = _mass_matrix(n)
    X = np.random.default_rng(6).standard_normal((n, 3))
    w, U = linalg.lobpcg(A, X, B=sparse.csr_array(B_sp),
                         largest=largest)
    which = "LA" if largest else "SA"
    w_ref = ssl.eigsh(A_sp, k=3, M=B_sp, which=which,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-6)
    resid = np.linalg.norm(
        A_sp @ U - (B_sp @ U) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


@pytest.mark.parametrize("k", [2, 3])
def test_eigsh_be_native(monkeypatch, k):
    # which='BE' (both ends): k/2 from each end, extra from the top.
    _no_fallback(monkeypatch)
    A_sp, A = _lap1d(90)
    w = linalg.eigsh(A, k=k, which="BE", return_eigenvectors=False)
    w_ref = ssl.eigsh(A_sp, k=k, which="BE", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)


def test_eigsh_be_k1_raises_like_scipy():
    from scipy.sparse.linalg import ArpackError

    _, A = _lap1d(30)
    with pytest.raises(ArpackError):
        linalg.eigsh(A, k=1, which="BE")


def test_eigsh_be_generalized(monkeypatch):
    _no_fallback(monkeypatch)
    n = 72
    A_sp, A = _lap1d(n)
    M_sp = _mass_matrix(n)
    w = linalg.eigsh(A, k=3, M=sparse.csr_array(M_sp), which="BE",
                     return_eigenvectors=False)
    w_ref = ssl.eigsh(A_sp, k=3, M=M_sp, which="BE",
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)


def test_eigsh_generalized_sm_routes_through_shift_invert(monkeypatch):
    # M + which='SM' without sigma: served as generalized shift-invert
    # at 0 (direct smallest-magnitude on a pencil would be the hardest
    # Krylov target) — native, matching scipy.
    _no_fallback(monkeypatch)
    n = 64
    A_sp, A = _lap1d(n)
    M_sp = _mass_matrix(n)
    w = linalg.eigsh(A, k=2, M=sparse.csr_array(M_sp), which="SM",
                     return_eigenvectors=False)
    w_ref = ssl.eigsh(A_sp, k=2, M=M_sp, sigma=0.0,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)


@pytest.mark.slow
def test_eigsh_generalized_small_norm_pencil_precise(monkeypatch):
    # Code-review repro: a 1e-6-scaled operator must NOT lose digits to
    # an absolute inner tolerance (the rhs of the M-solve has norm
    # ~||A||; the fix normalizes it so the tolerance is relative).
    _no_fallback(monkeypatch)
    import scipy.linalg as sl

    n = 200
    A_sp, _ = _lap1d(n)
    A_small = (A_sp * 1e-6).tocsr()
    M_sp = _mass_matrix(n)
    w, _ = linalg.eigsh(sparse.csr_array(A_small), k=3,
                        M=sparse.csr_array(M_sp), which="SA")
    w_dense = sl.eigh(A_small.toarray(), M_sp.toarray(),
                      eigvals_only=True)[:3]
    np.testing.assert_allclose(np.sort(w), w_dense, rtol=1e-7)


@pytest.mark.parametrize("mode", ["buckling", "cayley"])
def test_eigsh_buckling_cayley_native(monkeypatch, mode):
    # ARPACK modes 4/5: B-inner Lanczos on the mode's operator with
    # the per-mode back-transform; scipy (host splu) referees.
    _no_fallback(monkeypatch)
    n = 72
    A_sp, A = _lap1d(n)            # SPD, as buckling requires
    M_sp = _mass_matrix(n)
    sigma = 1.5
    w, v = linalg.eigsh(A, k=3, M=sparse.csr_array(M_sp), sigma=sigma,
                        mode=mode)
    w_ref = ssl.eigsh(A_sp, k=3, M=M_sp, sigma=sigma, mode=mode,
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-7)
    resid = np.linalg.norm(
        A_sp @ v - (M_sp @ v) * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-5)


def test_eigsh_buckling_zero_sigma_raises():
    _, A = _lap1d(30)
    M = sparse.csr_array(_mass_matrix(30))
    with pytest.raises(ValueError, match="nonzero sigma"):
        linalg.eigsh(A, k=2, M=M, sigma=0.0, mode="buckling")


def test_eigsh_generalized_bad_m_falls_back(monkeypatch):
    # A stagnating M-solve (the native route's honesty probe) must fall
    # back to the host boundary, not return silently wrong pairs.
    from scipy.sparse.linalg import ArpackNoConvergence

    from legate_sparse_tpu import eigen as eig_mod

    used = []
    real = eig_mod._host_fallback

    def spy(name):
        used.append(name)
        return real(name)

    def boom(*a, **kw):
        raise ArpackNoConvergence("probe tripped", np.empty(0),
                                  np.empty((40, 0)))

    monkeypatch.setattr(eig_mod, "_host_fallback", spy)
    monkeypatch.setattr(eig_mod, "_eigsh_generalized", boom)
    A_sp, A = _lap1d(40)
    M_sp = _mass_matrix(40)
    w = linalg.eigsh(A, k=2, M=sparse.csr_array(M_sp),
                     return_eigenvectors=False)
    assert used == ["eigsh"]
    w_ref = ssl.eigsh(A_sp, k=2, M=M_sp, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)


@pytest.mark.parametrize("largest", [True, False])
def test_lobpcg_native(largest):
    A_sp, A = _lap1d(100)
    X = np.random.default_rng(0).standard_normal((100, 3))
    # The top of this spectrum is clustered (cos^2 spacing): the
    # largest triple needs more block iterations than the smallest.
    w, U = linalg.lobpcg(A, X, maxiter=300 if largest else 100,
                         largest=largest)
    which = "LA" if largest else "SA"
    w_ref = ssl.eigsh(A_sp, k=3, which=which, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-5)
    assert U.shape == (100, 3)
    resid = np.linalg.norm(A_sp @ U - U * w[None, :], axis=0)
    assert np.all(resid < 1e-4)


def test_svds_native_rectangular():
    rng = np.random.default_rng(1)
    B_sp = sp.random(80, 50, density=0.2, format="csr", random_state=rng)
    B = sparse.csr_array(B_sp)
    U, s, Vh = linalg.svds(B, k=5)
    s_ref = ssl.svds(B_sp, k=5, return_singular_vectors=False)
    np.testing.assert_allclose(np.sort(s), np.sort(s_ref), rtol=1e-6)
    # Triplet consistency and orthonormality.
    np.testing.assert_allclose(
        np.linalg.norm(B_sp @ Vh.T - U * s[None, :], axis=0), 0, atol=1e-6)
    np.testing.assert_allclose(U.T @ U, np.eye(5), atol=1e-8)
    np.testing.assert_allclose(Vh @ Vh.T, np.eye(5), atol=1e-8)


@pytest.mark.slow
def test_svds_values_only_and_sm():
    rng = np.random.default_rng(2)
    B_sp = sp.random(40, 30, density=0.3, format="csr", random_state=rng)
    B = sparse.csr_array(B_sp)
    s = linalg.svds(B, k=3, return_singular_vectors=False)
    s_ref = ssl.svds(B_sp, k=3, return_singular_vectors=False)
    np.testing.assert_allclose(np.sort(s), np.sort(s_ref), rtol=1e-6)
    # SM: now native (shift-invert at 0 on the Gram operator) — the
    # random 40x30 matrix is full-rank, so no fallback engages; a
    # rank-deficient one would route to host via the probe.
    s_sm = linalg.svds(B, k=2, which="SM", return_singular_vectors=False)
    s_sm_ref = ssl.svds(B_sp, k=2, which="SM",
                        return_singular_vectors=False)
    np.testing.assert_allclose(np.sort(s_sm), np.sort(s_sm_ref), rtol=1e-6)


def test_svds_sm_native_no_fallback_with_vectors(monkeypatch):
    _no_fallback(monkeypatch)
    rng = np.random.default_rng(7)
    # Well-conditioned rectangular operator: dense QR-based construction
    # keeps kappa modest so the Gram inverse is iterative-friendly.
    B_dense = (rng.standard_normal((36, 24))
               + 3.0 * np.eye(36, 24)).astype(np.float64)
    B = sparse.csr_array(B_dense)
    U, s, Vt = linalg.svds(B, k=2, which="SM")
    s_ref = np.linalg.svd(B_dense, compute_uv=False)
    np.testing.assert_allclose(np.sort(s), np.sort(s_ref)[:2],
                               rtol=1e-7)
    # Triplet consistency: B v = s u.
    for i in range(2):
        np.testing.assert_allclose(
            B_dense @ Vt[i], s[i] * U[:, i], atol=1e-6)


def test_eigsh_invariant_subspace_breakdown():
    # Krylov space is invariant at dim 1: breakdown must restart with a
    # fresh direction, not pad T with fabricated zero eigenvalues.
    A = sparse.eye(50, format="csr") * 2.0
    w, _ = linalg.eigsh(A, k=3, which="LA")
    np.testing.assert_allclose(w, 2.0, rtol=1e-10)


def test_lobpcg_small_n_falls_back():
    # jax's lobpcg_standard needs 5k < n; smaller problems must serve
    # through host scipy instead of raising.
    A_sp = sp.diags([np.arange(1.0, 17.0)], [0], format="csr")
    X = np.random.default_rng(0).standard_normal((16, 4))
    w, _ = linalg.lobpcg(sparse.csr_array(A_sp), X, maxiter=200)
    np.testing.assert_allclose(np.sort(w), [13, 14, 15, 16], atol=1e-3)


def test_svds_rank_deficient():
    # Gram operator has rank 5 << n: breakdown path must not fabricate
    # spurious singular values above the true ones.
    B = np.zeros((30, 20))
    B[:5, :5] = np.diag([5.0, 4.0, 3.0, 2.0, 1.0])
    s = linalg.svds(sparse.csr_array(B), k=3,
                    return_singular_vectors=False)
    np.testing.assert_allclose(np.sort(s), [3, 4, 5], atol=1e-5)


# ---- non-symmetric Arnoldi (eigs) ----

@pytest.mark.slow
def test_eigs_nonsymmetric_vs_analytic():
    # Asymmetric tridiagonal: analytic spectrum 4 + 2*sqrt(bc)*cos(.).
    # Non-normal with exponentially ill-conditioned eigenvectors, so
    # ~1e-3 accuracy is the honest attainable bar — ARPACK lands in the
    # same range (measured 1.9e-3 where this Arnoldi gives 1.2e-3).
    n = 150
    A_sp = sp.diags([np.full(n - 1, -1.2), np.full(n, 4.0),
                     np.full(n - 1, -0.7)], [-1, 0, 1], format="csr")
    A = sparse.csr_array(A_sp)
    true = 4 + 2 * np.sqrt(1.2 * 0.7) * np.cos(
        np.arange(1, n + 1) * np.pi / (n + 1))
    for which, want in [("LM", np.sort(np.abs(true))[-4:]),
                        ("LR", np.sort(true)[-4:]),
                        ("SR", np.sort(true)[:4])]:
        w = linalg.eigs(A, k=4, which=which,
                        return_eigenvectors=False)
        key = np.abs if which == "LM" else np.real
        assert np.max(np.abs(np.sort(key(w)) - want)) < 2e-2


@pytest.mark.slow
def test_eigs_random_matches_scipy_with_residuals():
    rng = np.random.default_rng(0)
    n = 150
    R_sp = (sp.random(n, n, density=0.1, format="csr",
                      random_state=rng) + 3 * sp.eye(n)).tocsr()
    w, X = linalg.eigs(sparse.csr_array(R_sp), k=3, which="LM")
    resid = np.linalg.norm(R_sp @ X - X * w[None, :], axis=0)
    assert np.all(resid < 1e-6)
    w_ref = ssl.eigs(R_sp, k=3, which="LM", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(np.abs(w)),
                               np.sort(np.abs(w_ref)), rtol=1e-6)
    # SM routes through host scipy (shift-invert, like scipy itself).
    wsm = linalg.eigs(sparse.csr_array(R_sp), k=2, which="SM",
                      return_eigenvectors=False)
    wsm_ref = ssl.eigs(R_sp, k=2, which="SM",
                       return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(np.abs(wsm)),
                               np.sort(np.abs(wsm_ref)), rtol=1e-8)


@pytest.mark.slow
def test_eigs_complex_pairs_and_complex_operator():
    rng = np.random.default_rng(1)
    n = 120
    # Rotation-like: purely imaginary pairs shifted by 0.1.
    C_sp = (sp.diags([np.full(n - 1, 1.0), np.full(n - 1, -1.0)],
                     [1, -1], format="csr") + 0.1 * sp.eye(n)).tocsr()
    wc, Xc = linalg.eigs(sparse.csr_array(C_sp), k=4, which="LI")
    resid = np.linalg.norm(C_sp @ Xc - Xc * wc[None, :], axis=0)
    assert np.all(resid < 1e-6)
    assert np.all(np.imag(wc) > 1.9)
    H_sp = (sp.random(n, n, density=0.1, format="csr",
                      random_state=rng) + 3 * sp.eye(n)
            + 1j * sp.random(n, n, density=0.05,
                             random_state=rng)).tocsr()
    wh, Xh = linalg.eigs(sparse.csr_array(H_sp), k=3, which="LM")
    resid_h = np.linalg.norm(H_sp @ Xh - Xh * wh[None, :], axis=0)
    assert np.all(resid_h < 1e-6)


def test_no_convergence_raises_like_scipy():
    # A Krylov subspace too small to converge with escalation capped at
    # one try must raise scipy's exception class, not silently return
    # unconverged Ritz pairs (scipy _lanczos/_arnoldi parity).
    from scipy.sparse.linalg import ArpackNoConvergence

    rng = np.random.default_rng(3)
    n = 400
    A_sp = sp.csr_array(
        sp.random(n, n, density=0.05, random_state=rng) + 5 * sp.eye(n))
    with pytest.raises(ArpackNoConvergence) as ei:
        linalg.eigs(sparse.csr_array(A_sp), k=4, ncv=6, maxiter=1,
                    tol=1e-14)
    assert ei.value.eigenvalues.ndim == 1     # converged subset carried
    S_sp = sp.csr_array((A_sp + A_sp.T) / 2)
    with pytest.raises(ArpackNoConvergence):
        linalg.eigsh(sparse.csr_array(S_sp), k=4, ncv=6, maxiter=1,
                     tol=1e-14)


def test_no_convergence_final_try_doubling_still_raises():
    # Advisor r3 (eigen.py:471): the escalation loop doubled m at the
    # end of the last failed try, so the post-loop checks judged a
    # subspace size that never ran — when cap/2 <= m_last < cap the
    # unconverged pairs were returned silently.  ncv=24 on n=40 with
    # maxiter=1 lands exactly in that window (m doubles to 48 >= 40
    # after the sole failed try).
    from scipy.sparse.linalg import ArpackNoConvergence

    rng = np.random.default_rng(7)
    n = 40
    A_sp = sp.csr_array(rng.standard_normal((n, n)))
    with pytest.raises(ArpackNoConvergence):
        linalg.eigs(sparse.csr_array(A_sp), k=4, ncv=24, maxiter=1,
                    tol=1e-30)
    S_sp = sp.csr_array((A_sp + A_sp.T) / 2)
    with pytest.raises(ArpackNoConvergence):
        linalg.eigsh(sparse.csr_array(S_sp), k=4, ncv=24, maxiter=1,
                     tol=1e-30)
