# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Differential fuzz battery over the native eigensolver surface.

Random SPD/Hermitian operators, random mass matrices, random interior
shifts — every draw checked against dense LAPACK ground truth (the
referee scipy/ARPACK itself sometimes fails: SM-with-sigma, complex
shifts on real operators).  Seeds are fixed, so failures reproduce.
Complements the targeted tests in test_eigen.py the way
test_differential_fuzz.py complements the op tests (SURVEY §4).
"""

import numpy as np
import pytest
import scipy.linalg as sl
import scipy.sparse as sp

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


def _rand_spd(n, rng, dtype=np.float64):
    """Random SPD tridiagonal-ish operator with a spread spectrum."""
    main = rng.uniform(2.0, 10.0, n)
    off = rng.uniform(-0.8, 0.8, n - 1)
    return sp.diags([off, main, off], [-1, 0, 1], format="csr").astype(
        dtype)


def _rand_mass(n, rng):
    main = rng.uniform(3.0, 5.0, n)
    off = rng.uniform(0.2, 0.9, n - 1)
    return sp.diags([off, main, off], [-1, 0, 1], format="csr") / 6.0


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_eigsh_sigma(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(40, 90))
    A_sp = _rand_spd(n, rng)
    full = sl.eigh(A_sp.toarray(), eigvals_only=True)
    # Interior shift at a safe distance from the nearest eigenvalue.
    mid = 0.5 * (full[n // 3] + full[n // 3 + 1])
    w, v = linalg.eigsh(sparse.csr_array(A_sp), k=3, sigma=float(mid))
    w_ref = full[np.argsort(np.abs(full - mid))[:3]]
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-8)
    resid = np.linalg.norm(A_sp @ v - v * np.asarray(w)[None, :], axis=0)
    assert np.all(resid < 1e-6)


@pytest.mark.parametrize(
    "seed", [3, pytest.param(4, marks=pytest.mark.slow)])
def test_fuzz_eigsh_generalized_modes(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(40, 80))
    A_sp = _rand_spd(n, rng)
    M_sp = _rand_mass(n, rng)
    full = sl.eigh(A_sp.toarray(), M_sp.toarray(), eigvals_only=True)
    A = sparse.csr_array(A_sp)
    M = sparse.csr_array(M_sp)
    # mode 2 (no sigma), LA and SA
    for which, ref in (("SA", full[:2]), ("LA", full[-2:])):
        w = linalg.eigsh(A, k=2, M=M, which=which,
                         return_eigenvectors=False)
        np.testing.assert_allclose(np.sort(w), np.sort(ref), rtol=1e-8)
    # mode 3 at a random interior shift
    j = int(rng.integers(5, n - 5))
    mid = 0.5 * (full[j] + full[j + 1])
    w3 = linalg.eigsh(A, k=2, M=M, sigma=float(mid),
                      return_eigenvectors=False)
    ref3 = full[np.argsort(np.abs(full - mid))[:2]]
    np.testing.assert_allclose(np.sort(w3), np.sort(ref3), rtol=1e-8)


@pytest.mark.parametrize("seed", [5, 6])
def test_fuzz_eigsh_hermitian_sigma(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(40, 70))
    A_sp = _rand_spd(n, rng)
    off = rng.uniform(0.1, 0.5, n - 1)
    H = (A_sp.astype(np.complex128)
         + 1j * sp.diags([off], [1]) - 1j * sp.diags([off], [-1])
         ).tocsr()
    full = sl.eigh(H.toarray(), eigvals_only=True)
    j = int(rng.integers(5, n - 5))
    mid = 0.5 * (full[j] + full[j + 1])
    w = linalg.eigsh(sparse.csr_array(H), k=2, sigma=float(mid),
                     return_eigenvectors=False)
    ref = full[np.argsort(np.abs(full - mid))[:2]]
    np.testing.assert_allclose(np.sort(w), np.sort(ref), rtol=1e-8)


@pytest.mark.parametrize(
    "seed", [7, pytest.param(8, marks=pytest.mark.slow)])
def test_fuzz_eigs_generalized(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(40, 70))
    # Diagonally dominant nonsymmetric operator.
    A_sp = (sp.diags([np.linspace(1.0, 11.0, n),
                      0.3 * rng.uniform(-1, 1, n - 1),
                      0.3 * rng.uniform(-1, 1, n - 1)], [0, 1, -1])
            .tocsr())
    M_sp = _rand_mass(n, rng)
    pencil = sl.eig(A_sp.toarray(), M_sp.toarray(), right=False)
    w = linalg.eigs(sparse.csr_array(A_sp), k=3,
                    M=sparse.csr_array(M_sp), which="LM",
                    return_eigenvectors=False)
    ref = pencil[np.argsort(np.abs(pencil))[-3:]]
    np.testing.assert_allclose(
        np.sort(np.real(w)), np.sort(np.real(ref)), rtol=1e-6)
    sigma = float(np.real(np.median(np.real(pencil)))) + 0.013
    w_si = linalg.eigs(sparse.csr_array(A_sp), k=2,
                       M=sparse.csr_array(M_sp), sigma=sigma,
                       return_eigenvectors=False)
    ref_si = pencil[np.argsort(np.abs(pencil - sigma))[:2]]
    np.testing.assert_allclose(
        np.sort(np.real(w_si)), np.sort(np.real(ref_si)), rtol=1e-6)


@pytest.mark.parametrize(
    "seed", [pytest.param(9, marks=pytest.mark.slow), 10])
def test_fuzz_svds_sm(seed):
    rng = np.random.default_rng(seed)
    m = int(rng.integers(40, 60))
    n = int(rng.integers(24, m))          # tall: native SM route
    B_dense = (rng.standard_normal((m, n))
               + 2.5 * np.eye(m, n)).astype(np.float64)
    s_all = np.linalg.svd(B_dense, compute_uv=False)
    s = linalg.svds(sparse.csr_array(B_dense), k=2, which="SM",
                    return_singular_vectors=False)
    np.testing.assert_allclose(np.sort(s), np.sort(s_all)[:2],
                               rtol=1e-7)
