# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Execution engine (docs/ENGINE.md): bucketing, plan cache,
executor, routing.

The two load-bearing contracts:

- **bit-for-bit**: a bucketed (padded, masked-tail) dispatch must
  equal the unpadded ``csr_spmv_rowids``/``csr_spmm_rowids`` kernels
  exactly — fuzzed here on f32/f64/c64 including bucket boundaries
  and non-finite operands (the ISSUE 4 differential-fuzz satellite);
- **zero retraces on a plan hit**: a second same-bucket different-``n``
  workload must record no kernel compile (the ``trace.*`` counters
  ARE the compile count — obs counter contract) and no plan miss.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import legate_sparse_tpu as lst
import legate_sparse_tpu.linalg as linalg
from legate_sparse_tpu import obs
from legate_sparse_tpu.engine import (
    Engine, RequestExecutor, bucket, k_bucket, next_pow2,
)
from legate_sparse_tpu.ops import spmv as spmv_ops
from legate_sparse_tpu.settings import settings


@pytest.fixture
def eng_settings():
    """Snapshot/restore every setting the tests flip."""
    saved = (settings.engine, settings.ell_max_expand,
             settings.dia_max_expand, settings.engine_bucket_ladder,
             settings.engine_min_bucket)
    yield settings
    (settings.engine, settings.ell_max_expand,
     settings.dia_max_expand, settings.engine_bucket_ladder,
     settings.engine_min_bucket) = saved


def _random_csr(n, density=0.02, dtype=np.float32, seed=0):
    """Random CSR + the same structure as a scipy reference.  Random
    columns defeat band detection, so the matrix is engine-eligible."""
    rng = np.random.default_rng(seed)
    A_sp = sp.random(n, n, density=density, format="csr",
                     random_state=rng, dtype=np.float64)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        A_sp = (A_sp + 1j * sp.random(
            n, n, density=density, format="csr",
            random_state=np.random.default_rng(seed + 1),
            dtype=np.float64)).tocsr()
    A_sp = A_sp.astype(dtype)
    return lst.csr_array(A_sp), A_sp


def _x(n, dtype, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(n)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        x = x + 1j * rng.standard_normal(n)
    return jnp.asarray(x.astype(dtype))


def _ref_spmv(A, x):
    return spmv_ops.csr_spmv_rowids(
        A.data, A.indices, A._get_row_ids(), x, A.shape[0])


def _bitident(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)


# ---------------------------------------------------------------- buckets


def test_bucket_policy():
    assert next_pow2(1) == 1 and next_pow2(5) == 8
    assert bucket(1000, ladder=(), minimum=64) == 1024
    assert bucket(1024, ladder=(), minimum=64) == 1024   # exact
    assert bucket(3, ladder=(), minimum=64) == 64        # floor
    # Ladder: smallest holding rung; above the top -> pow2.
    assert bucket(900, ladder=(1000, 5000), minimum=1) == 1000
    assert bucket(1000, ladder=(1000, 5000), minimum=1) == 1000
    assert bucket(4000, ladder=(1000, 5000), minimum=1) == 5000
    assert bucket(6000, ladder=(1000, 5000), minimum=1) == 8192
    assert k_bucket(3) == 4 and k_bucket(1) == 1


def test_ladder_setting_applies(eng_settings):
    settings.engine_bucket_ladder = (500, 2000)
    settings.engine_min_bucket = 1
    assert bucket(400) == 500
    assert bucket(1999) == 2000


# ---------------------------------------------------- bucketed correctness


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex64])
def test_bucketed_spmv_bitident_fuzz(dtype):
    """Differential fuzz (ISSUE 4 satellite): bucketed SpMV == unpadded
    kernel bit-for-bit, across sizes including the bucket boundary
    (n == rows_b: row padding zero, nnz tail still masked)."""
    eng = Engine()
    for n, seed in [(100, 0), (256, 1), (300, 2), (511, 3)]:
        A, _ = _random_csr(n, dtype=dtype, seed=seed)
        x = _x(n, dtype, seed=seed + 10)
        y = eng.matvec(A, x)
        assert y is not None and y.shape == (n,)
        assert _bitident(y, _ref_spmv(A, x)), (dtype, n)


def test_bucketed_spmv_boundary_exact_nnz():
    """Both shape terms exactly at their buckets (n = 256 = rows_b,
    nnz = 4096 = nnz_b): zero padding anywhere — the masked kernel
    must still match bit-for-bit."""
    n, per_row = 256, 16            # nnz = 4096, a power of two
    rng = np.random.default_rng(5)
    indptr = np.arange(n + 1, dtype=np.int64) * per_row
    indices = rng.integers(0, n, size=n * per_row).astype(np.int32)
    row_ids = np.repeat(np.arange(n), per_row)
    order = np.lexsort((indices, row_ids))
    data = rng.standard_normal(n * per_row).astype(np.float32)
    A = lst.csr_array((data, indices[order], indptr), shape=(n, n))
    assert A.nnz == 4096
    x = _x(n, np.float32)
    y = Engine().matvec(A, x)
    assert y is not None
    assert _bitident(y, _ref_spmv(A, x))


def test_bucketed_spmv_nonfinite_x_masked_tail():
    """Padded slots must contribute an EXACT zero even against inf/nan
    x entries (masked product, not 0*x)."""
    n = 200
    A, _ = _random_csr(n, seed=4)
    x = np.array(np.asarray(_x(n, np.float32)))
    x[7] = np.inf
    x[11] = np.nan
    x = jnp.asarray(x)
    y = Engine().matvec(A, x)
    assert y is not None
    assert _bitident(y, _ref_spmv(A, x))


@pytest.mark.parametrize("dtype", [np.float32, np.complex64])
def test_bucketed_spmm_bitident(dtype):
    n, k = 220, 3            # k buckets to 4: one padded column
    A, _ = _random_csr(n, dtype=dtype, seed=6)
    rng = np.random.default_rng(6)
    X = rng.standard_normal((n, k))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        X = X + 1j * rng.standard_normal((n, k))
    X = jnp.asarray(X.astype(dtype))
    Y = Engine().matmat(A, X)
    assert Y is not None and Y.shape == (n, k)
    Y_ref = spmv_ops.csr_spmm_rowids(
        A.data, A.indices, A._get_row_ids(), X, n)
    assert _bitident(Y, Y_ref)


def test_bucketed_solve_bitident(eng_settings):
    """cg routed through the engine's traceable matvec must produce
    bit-for-bit the iterates of the plain csr-rowids path: the closure
    slices back to n before every reduction."""
    settings.ell_max_expand = 0.0   # force the csr-rowids base path
    settings.dia_max_expand = 0.0
    n = 300
    A_sp = sp.random(n, n, density=0.02, format="csr",
                     random_state=np.random.default_rng(8),
                     dtype=np.float32)
    A_spd = (A_sp + A_sp.T + sp.eye(n, dtype=np.float32) * 10).tocsr()
    b = np.ones(n, np.float32)
    settings.engine = True
    x_eng, it_eng = linalg.cg(lst.csr_array(A_spd), b, maxiter=40)
    settings.engine = False
    x_ref, it_ref = linalg.cg(lst.csr_array(A_spd), b, maxiter=40)
    assert int(it_eng) == int(it_ref)
    assert _bitident(x_eng, x_ref)


# -------------------------------------------------------------- plan cache


def test_plan_hit_zero_retrace():
    """ISSUE 4 acceptance: the second call of a same-bucket
    different-n workload records NO kernel compile (trace.* counters
    unchanged) and no plan miss."""
    eng = Engine()
    A1, _ = _random_csr(1000, seed=11)
    A2, _ = _random_csr(1010, seed=12)
    x1, x2 = _x(1000, np.float32), _x(1010, np.float32)
    y1 = eng.matvec(A1, x1)
    assert y1 is not None
    trace0 = obs.counters.snapshot("trace.")
    miss0 = obs.counters.get("engine.plan.misses")
    hit0 = obs.counters.get("engine.plan.hits")
    y2 = eng.matvec(A2, x2)
    assert y2 is not None
    trace1 = obs.counters.snapshot("trace.")
    assert trace1 == trace0, "plan hit must not retrace any kernel"
    assert obs.counters.get("engine.plan.misses") == miss0
    assert obs.counters.get("engine.plan.hits") == hit0 + 1
    assert _bitident(y2, _ref_spmv(A2, x2))


def test_warmup_prevents_cold_miss():
    eng = Engine()
    A, _ = _random_csr(700, seed=13)
    ids = eng.warmup([{"op": "spmv", "dtype": "float32",
                       "rows": 700, "nnz": A.nnz}])
    assert len(ids) == 1
    miss0 = obs.counters.get("engine.plan.misses")
    y = eng.matvec(A, _x(700, np.float32))
    assert y is not None
    assert obs.counters.get("engine.plan.misses") == miss0


def test_settings_epoch_invalidates(eng_settings):
    eng = Engine()
    A, _ = _random_csr(90, seed=14)
    x = _x(90, np.float32)
    assert eng.matvec(A, x) is not None
    miss0 = obs.counters.get("engine.plan.misses")
    ep0 = settings.epoch
    # No-op rewrites and non-lowering flags must NOT invalidate...
    settings.ell_max_expand = settings.ell_max_expand
    settings.obs = settings.obs
    assert settings.epoch == ep0
    assert eng.matvec(A, x) is not None
    assert obs.counters.get("engine.plan.misses") == miss0
    # ...a real value change of a lowering-relevant setting must.
    settings.ell_max_expand = settings.ell_max_expand + 1.0
    assert settings.epoch == ep0 + 1
    assert eng.matvec(A, x) is not None
    assert obs.counters.get("engine.plan.misses") == miss0 + 1


def test_plan_lru_eviction():
    eng = Engine(plan_capacity=1)
    A1, _ = _random_csr(80, seed=15)
    A2, _ = _random_csr(600, seed=16)   # different bucket
    ev0 = obs.counters.get("engine.plan.evictions")
    assert eng.matvec(A1, _x(80, np.float32)) is not None
    assert eng.matvec(A2, _x(600, np.float32)) is not None
    assert obs.counters.get("engine.plan.evictions") == ev0 + 1


def test_pack_invalidation_on_data_mutation():
    eng = Engine()
    A, A_sp = _random_csr(150, seed=17)
    x = _x(150, np.float32)
    y1 = eng.matvec(A, x)
    A.data = jnp.asarray(A.data) * 2.0      # setter invalidates caches
    y2 = eng.matvec(A, x)
    assert _bitident(y2, _ref_spmv(A, x))
    assert np.allclose(np.asarray(y2), 2 * np.asarray(y1),
                       rtol=1e-6, atol=1e-6)


def test_engine_declines_banded_and_tracers():
    eng = Engine()
    n = 256
    A_band = lst.csr_array(sp.diags(
        [np.ones(n - 1), np.full(n, 2.0), np.ones(n - 1)],
        [-1, 0, 1], format="csr", dtype=np.float32))
    assert A_band._get_dia() is not None
    assert eng.matvec(A_band, _x(n, np.float32)) is None
    A, _ = _random_csr(100, seed=18)

    # Inside an ambient trace the eager route declines (falls back).
    @jax.jit
    def traced(x):
        return eng.matvec(A, x)

    assert traced(_x(100, np.float32)) is None


def test_matvec_shape_validation():
    eng = Engine()
    A, _ = _random_csr(64, seed=19)
    with pytest.raises(ValueError):
        eng.matvec(A, _x(65, np.float32))
    with pytest.raises(ValueError):
        eng.matmat(A, jnp.ones((63, 2), jnp.float32))


# ---------------------------------------------------------------- executor


def test_executor_batched_bitident_and_counters():
    eng = Engine()
    A, _ = _random_csr(400, seed=20)
    ex = RequestExecutor(eng, max_batch=4, queue_depth=32, timeout_ms=0)
    xs = [_x(400, np.float32, seed=30 + i) for i in range(6)]
    b0 = obs.counters.get("engine.exec.batches")
    futs = [ex.submit(A, x) for x in xs]
    ex.flush()                      # 4 dispatched at max_batch, +2 here
    for f, x in zip(futs, xs):
        assert _bitident(f.result(timeout=30), _ref_spmv(A, x))
    assert obs.counters.get("engine.exec.batches") == b0 + 2
    ex.shutdown()


def test_executor_timeout_worker():
    eng = Engine()
    A, _ = _random_csr(120, seed=21)
    ex = RequestExecutor(eng, max_batch=64, queue_depth=128,
                         timeout_ms=5)
    futs = [ex.submit(A, _x(120, np.float32, seed=40 + i))
            for i in range(3)]
    for f in futs:                  # worker must flush on timeout
        assert f.result(timeout=30).shape == (120,)
    ex.shutdown()


def test_executor_backpressure_inline_dispatch():
    eng = Engine()
    A, _ = _random_csr(130, seed=22)
    ex = RequestExecutor(eng, max_batch=64, queue_depth=2, timeout_ms=0)
    bp0 = obs.counters.get("engine.exec.backpressure")
    futs = [ex.submit(A, _x(130, np.float32, seed=50 + i))
            for i in range(4)]
    assert obs.counters.get("engine.exec.backpressure") >= bp0 + 1
    ex.flush()
    for f in futs:
        assert f.result(timeout=30).shape == (130,)
    ex.shutdown()


def test_executor_backpressure_age_bound_beats_largest_group():
    """Backpressure fairness regression: the eviction pick must not
    starve a small old group behind an endless series of fuller ones.
    Any group older than 2x the batch timeout wins the pick — with
    ``timeout_ms=0`` (flush-only) the bound is 0, so the OLDEST group
    always wins deterministically: here the lone request for A (group
    of 1, submitted first) must dispatch ahead of the fuller group for
    B when the 5th submit trips the queue bound."""
    eng = Engine()
    A, _ = _random_csr(140, seed=23)
    B, _ = _random_csr(140, seed=24)
    ex = RequestExecutor(eng, max_batch=64, queue_depth=4, timeout_ms=0)
    aged0 = obs.counters.get("engine.exec.backpressure_aged")
    xa = _x(140, np.float32, seed=60)
    fut_a = ex.submit(A, xa)                      # oldest, group of 1
    futs_b = [ex.submit(B, _x(140, np.float32, seed=61 + i))
              for i in range(3)]                  # larger group
    trigger = ex.submit(B, _x(140, np.float32, seed=70))
    # The 5th submit hit the queue bound: pre-fix the LARGEST group
    # (B) would have been dispatched inline and A left to starve; the
    # age bound dispatches the oldest group instead.
    assert fut_a.done(), "aged group was not the eviction pick"
    assert not any(f.done() for f in futs_b)
    assert obs.counters.get("engine.exec.backpressure_aged") == aged0 + 1
    assert _bitident(fut_a.result(timeout=30), _ref_spmv(A, xa))
    ex.flush()
    for f in futs_b + [trigger]:
        assert f.result(timeout=30).shape == (140,)
    ex.shutdown()


def test_solver_route_not_stale_after_mutation(eng_settings):
    """An operator wrapped BEFORE an in-place matrix mutation must not
    solve the old matrix: the construction-time engine closure
    captured padded copies, so the freshness check has to fall back to
    the live dispatch."""
    settings.ell_max_expand = 0.0
    settings.dia_max_expand = 0.0
    n = 220
    A_sp = sp.random(n, n, density=0.02, format="csr",
                     random_state=np.random.default_rng(30),
                     dtype=np.float32)
    A_spd = (A_sp + A_sp.T + sp.eye(n, dtype=np.float32) * 9).tocsr()
    b = np.ones(n, np.float32)
    settings.engine = True
    A_lst = lst.csr_array(A_spd)
    op = linalg.make_linear_operator(A_lst)   # engine closure built NOW
    A_lst.data = jnp.asarray(A_lst.data) * 1.5     # in-place mutation
    x_eng, it_eng = linalg.cg(op, b, maxiter=60)
    settings.engine = False
    A_ref = lst.csr_array(A_spd)
    A_ref.data = jnp.asarray(A_ref.data) * 1.5
    x_ref, it_ref = linalg.cg(A_ref, b, maxiter=60)
    assert int(it_eng) == int(it_ref)
    assert _bitident(x_eng, x_ref)


def test_promoted_rhs_solve_not_downcast(eng_settings):
    """f64 rhs over an f32 matrix: _promote_rhs runs the solve in f64,
    and the engine's solver route must NOT downcast the iterates back
    to f32 — the promoted solve takes the normal dispatch and matches
    the engine-off result bit-for-bit."""
    n = 200
    A_sp = sp.random(n, n, density=0.02, format="csr",
                     random_state=np.random.default_rng(29),
                     dtype=np.float32)
    A_spd = (A_sp + A_sp.T + sp.eye(n, dtype=np.float32) * 8).tocsr()
    b = np.ones(n, np.float64)
    settings.engine = True
    x_eng, it_eng = linalg.cg(lst.csr_array(A_spd), b, maxiter=60)
    settings.engine = False
    x_ref, it_ref = linalg.cg(lst.csr_array(A_spd), b, maxiter=60)
    assert x_eng.dtype == np.float64
    assert int(it_eng) == int(it_ref)
    assert _bitident(x_eng, x_ref)


def test_executor_rejects_bad_shape_and_shutdown_submits():
    """A wrong-length request raises at submit() — it must not poison
    the futures batched with it — and a submit after shutdown raises
    instead of enqueueing into a drained queue."""
    eng = Engine()
    A, _ = _random_csr(110, seed=27)
    ex = RequestExecutor(eng, max_batch=4, queue_depth=8, timeout_ms=0)
    good = ex.submit(A, _x(110, np.float32))
    with pytest.raises(ValueError):
        ex.submit(A, _x(111, np.float32))
    with pytest.raises(ValueError):
        ex.submit(A, [1.0] * 111)       # array-less operands too
    ex.flush()
    assert good.result(timeout=30).shape == (110,)
    ex.shutdown()
    with pytest.raises(RuntimeError):
        ex.submit(A, _x(110, np.float32))


def test_route_falls_back_on_engine_error(eng_settings, monkeypatch):
    """'settings.engine = True is always safe': a plan build/dispatch
    failure inside routing must fall back to the normal dispatch, not
    surface through A @ x."""
    from legate_sparse_tpu.engine import core as engine_core

    settings.engine = True
    A, _ = _random_csr(140, seed=28)
    x = _x(140, np.float32)

    def boom(self, A, x, _checked=False):
        raise RuntimeError("synthetic plan build failure")

    monkeypatch.setattr(engine_core.Engine, "matvec", boom)
    e0 = obs.counters.get("engine.route.error")
    y = A @ x
    assert obs.counters.get("engine.route.error") == e0 + 1
    # The fallback runs the NORMAL dispatch (which may pick ELL —
    # different reduction order than the csr-rowids referee).
    settings.engine = False
    assert _bitident(y, A @ x)


def test_solver_falls_back_on_engine_error(eng_settings, monkeypatch):
    """The constructor route has the same safety contract: a plan
    build failure (e.g. PlanBuildError off the negative cache) while
    building the solver's traceable matvec must fall back to the
    normal dispatch, not raise out of cg/gmres construction."""
    from legate_sparse_tpu.engine import core as engine_core
    from legate_sparse_tpu.engine.plan_cache import PlanBuildError

    settings.engine = True
    A_sp = sp.diags([np.full(120, 4.0), np.ones(119), np.ones(119)],
                    [0, -1, 1], format="csr", dtype=np.float64)
    rng = np.random.default_rng(3)
    A_sp = A_sp + sp.random(120, 120, density=0.02, format="csr",
                            random_state=rng, dtype=np.float64)
    A_sp = (A_sp + A_sp.T).tocsr()
    A = lst.csr_array(A_sp)
    b = _x(120, np.float64)

    def boom(self, A):
        raise PlanBuildError("synthetic cached failure")

    monkeypatch.setattr(engine_core.Engine, "traceable_matvec", boom)
    e0 = obs.counters.get("engine.route.error")
    x, _iters = linalg.cg(A, b, rtol=1e-8, maxiter=300)
    assert obs.counters.get("engine.route.error") == e0 + 1
    assert np.allclose(np.asarray(A_sp @ np.asarray(x)),
                       np.asarray(b), atol=1e-6)


def test_executor_ineligible_inline():
    """A banded (DIA-path) matrix submits fine — served inline through
    the normal dispatch, same Future contract."""
    eng = Engine()
    n = 256
    A_band = lst.csr_array(sp.diags(
        [np.ones(n - 1), np.full(n, 2.0), np.ones(n - 1)],
        [-1, 0, 1], format="csr", dtype=np.float32))
    ex = RequestExecutor(eng, max_batch=4, queue_depth=8, timeout_ms=0)
    in0 = obs.counters.get("engine.exec.inline")
    x = _x(n, np.float32)
    f = ex.submit(A_band, x)
    assert obs.counters.get("engine.exec.inline") == in0 + 1
    assert _bitident(f.result(timeout=30), A_band @ x)
    ex.shutdown()


def test_executor_thread_safety():
    """Concurrent submitters against one executor: every future
    resolves to the right answer (host-side queue concurrency; device
    launches serialize in the dispatching thread)."""
    import threading

    eng = Engine()
    A, _ = _random_csr(200, seed=23)
    ex = RequestExecutor(eng, max_batch=8, queue_depth=64,
                         timeout_ms=50)
    xs = [_x(200, np.float32, seed=60 + i) for i in range(16)]
    refs = [_ref_spmv(A, x) for x in xs]
    futs = [None] * len(xs)

    def submit(lo, hi):
        for i in range(lo, hi):
            futs[i] = ex.submit(A, xs[i])

    threads = [threading.Thread(target=submit, args=(i * 4, i * 4 + 4))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ex.flush()
    for f, ref in zip(futs, refs):
        assert _bitident(f.result(timeout=30), ref)
    ex.shutdown()


# ---------------------------------------------------------------- routing


def test_dot_routes_through_engine(eng_settings):
    settings.engine = True
    A, _ = _random_csr(350, seed=24)
    x = _x(350, np.float32)
    obs.enable()
    try:
        obs.reset()
        y = A @ x
        spans = [r for r in obs.records()
                 if r.get("type") == "span" and r["name"] == "spmv"]
        assert spans and spans[-1]["attrs"]["path"] == "engine"
        Y = A @ jnp.stack([np.asarray(x)] * 2, axis=1)
        spans = [r for r in obs.records()
                 if r.get("type") == "span" and r["name"] == "spmm"]
        assert spans and spans[-1]["attrs"]["path"] == "engine"
    finally:
        obs.disable()
        obs.reset()
    assert _bitident(y, _ref_spmv(A, x))
    assert Y.shape == (350, 2)


def test_engine_off_is_inert(eng_settings):
    """settings.engine = False: dispatch never touches the engine."""
    settings.engine = False
    A, _ = _random_csr(360, seed=25)
    m0 = obs.counters.get("engine.plan.misses")
    h0 = obs.counters.get("engine.plan.hits")
    _ = A @ _x(360, np.float32)
    assert obs.counters.get("engine.plan.misses") == m0
    assert obs.counters.get("engine.plan.hits") == h0


# ------------------------------------------------------------- distributed


def test_mesh_fingerprint_stable_and_dist_plan_reuse():
    from legate_sparse_tpu.parallel import (
        make_row_mesh, mesh_fingerprint, shard_csr,
    )
    from legate_sparse_tpu.parallel.dist_csr import shard_vector

    mesh1 = make_row_mesh()
    mesh2 = make_row_mesh()
    assert mesh_fingerprint(mesh1) == mesh_fingerprint(mesh2)

    n = 1 << 10
    eng = Engine()

    def banded(seed):
        rng = np.random.default_rng(seed)
        return lst.csr_array(sp.diags(
            [rng.standard_normal(n - 1).astype(np.float32),
             np.full(n, 4.0, np.float32),
             rng.standard_normal(n - 1).astype(np.float32)],
            [-1, 0, 1], format="csr", dtype=np.float32))

    A1, A2 = banded(1), banded(2)
    dA1 = shard_csr(A1, mesh=mesh1)
    dA2 = shard_csr(A2, mesh=mesh2)
    x = shard_vector(np.ones(n, np.float32), mesh1, dA1.rows_padded)
    m0 = obs.counters.get("engine.plan.misses")
    h0 = obs.counters.get("engine.plan.hits")
    y1 = eng.dist_matvec(dA1, x)
    assert obs.counters.get("engine.plan.misses") == m0 + 1
    y2 = eng.dist_matvec(dA2, x)
    # Same layout + same physical mesh -> ONE plan: the second matrix
    # is a hit, proving the compiled distributed program is shared.
    assert obs.counters.get("engine.plan.misses") == m0 + 1
    assert obs.counters.get("engine.plan.hits") == h0 + 1
    ref1 = np.asarray(A1 @ np.ones(n, np.float32))
    assert np.allclose(np.asarray(y1)[:n], ref1, rtol=1e-5, atol=1e-5)
    assert y2.shape == y1.shape


def test_dist_spmv_feeds_plan_ledger_when_routed(eng_settings):
    """The PRODUCTION dist path (solvers/bench call dist_spmv
    directly) records into the process engine's plan ledger when
    routing is enabled — the reuse evidence doesn't require calling
    dist_matvec by hand."""
    from legate_sparse_tpu.engine import get_engine, reset_engine
    from legate_sparse_tpu.parallel import make_row_mesh, shard_csr
    from legate_sparse_tpu.parallel.dist_csr import (
        dist_spmv, shard_vector,
    )

    n = 1 << 9
    A = lst.csr_array(sp.diags(
        [np.ones(n - 1, np.float32), np.full(n, 4.0, np.float32),
         np.ones(n - 1, np.float32)],
        [-1, 0, 1], format="csr", dtype=np.float32))
    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    settings.engine = True
    reset_engine()
    try:
        _ = dist_spmv(dA, x)
        _ = dist_spmv(dA, x)
        stats = get_engine().stats()["plans"]
        dist_plans = {k: v for k, v in stats.items()
                      if k.startswith("dist_spmv/")}
        assert dist_plans, stats
        assert sum(p["execs"] for p in dist_plans.values()) == 2
    finally:
        reset_engine()


def test_failed_plan_build_negative_cache(eng_settings, monkeypatch):
    """A reproducible plan-build failure is cached: the second routed
    dispatch fails FAST (no repeat compile attempt) and still falls
    back to the normal dispatch."""
    from legate_sparse_tpu.engine import core as engine_core
    from legate_sparse_tpu.engine import plan_cache as pc

    calls = {"n": 0}

    def bad_builder(key):
        calls["n"] += 1
        raise RuntimeError("synthetic XLA failure")

    monkeypatch.setitem(pc.BUILDERS, "spmv", bad_builder)
    monkeypatch.setitem(pc.BUILDERS, "spmm", bad_builder)
    settings.engine = True
    engine_core.reset_engine()
    try:
        A, _ = _random_csr(160, seed=31)
        x = _x(160, np.float32)
        y1 = A @ x          # build fails -> fallback
        y2 = A @ x          # cached failure -> fast fallback
        assert calls["n"] == 1, "failed build must not re-run"
        # The executor honors the same contract: a batch whose plan
        # cannot build resolves every future via the normal dispatch.
        from legate_sparse_tpu.engine import RequestExecutor

        ex = RequestExecutor(engine_core.get_engine(), max_batch=2,
                             queue_depth=8, timeout_ms=0)
        f1, f2 = ex.submit(A, x), ex.submit(A, x)
        ex.shutdown()
        settings.engine = False
        assert _bitident(y1, A @ x) and _bitident(y2, A @ x)
        assert _bitident(f1.result(timeout=30), A @ x)
        assert _bitident(f2.result(timeout=30), A @ x)
    finally:
        engine_core.reset_engine()


# ------------------------------------------------------------------ report


def test_plans_table_renders():
    from legate_sparse_tpu.obs import report

    eng = Engine()
    A, _ = _random_csr(70, seed=26)
    _ = eng.matvec(A, _x(70, np.float32))
    table = report.render_plans_table(obs.counters.snapshot())
    assert "spmv/float32" in table
    assert "plan cache:" in table
