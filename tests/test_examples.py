# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Differential tests for the examples layer (reference's examples are
its de-facto acceptance suite; SURVEY §2.4)."""

import os
import sys

import numpy as np
import pytest
import scipy.sparse as scsp

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")
sys.path.insert(0, EXAMPLES)


@pytest.fixture(scope="module")
def tpu_backend(request):
    argv = sys.argv
    sys.argv = ["test", "--package", "tpu"]
    import common

    try:
        yield common.parse_common_args()
    finally:
        sys.argv = argv


def test_banded_matrix_matches_scipy(tpu_backend):
    import common

    A = common.banded_matrix(64, 5)
    ref = scsp.diags([1.0] * 5, [-2, -1, 0, 1, 2], shape=(64, 64)).tocsr()
    np.testing.assert_allclose(A.todense(), ref.toarray())


def test_banded_matrix_from_diags(tpu_backend):
    import common

    A = common.banded_matrix(32, 3, from_diags=True)
    ref = scsp.diags([1.0] * 3, [-1, 0, 1], shape=(32, 32)).tocsr()
    np.testing.assert_allclose(A.todense(), ref.toarray())


def test_poisson2D_structure(tpu_backend):
    import common

    A = common.poisson2D(8)
    # SPD penta-diagonal: 4 on the diagonal, -1 couplings, row sums >= 0.
    d = np.asarray(A.diagonal())
    np.testing.assert_allclose(d, 4.0)
    dense = np.asarray(A.todense())
    np.testing.assert_allclose(dense, dense.T)
    # 5 bands of 64 minus off-matrix truncation (8 per +/-N band, 1 per
    # +/-1 band) minus the 7 explicit zeros per +/-1 band at row-block
    # boundaries (dropped in DIA->CSR conversion).
    assert A.nnz == 5 * 64 - 2 * 8 - 2 * (1 + 7)


def test_stencil_grid_matches_poisson(tpu_backend):
    import common

    # The 5-point stencil through stencil_grid must equal poisson2D.
    S = np.array([[0, -1, 0], [-1, 4, -1], [0, -1, 0]], dtype=np.float64)
    A = common.stencil_grid(S, (6, 6))
    B = common.poisson2D(6)
    np.testing.assert_allclose(
        np.asarray(A.todense()), np.asarray(B.todense())
    )


def test_diffusion2D_spd(tpu_backend):
    import common

    A = common.diffusion2D(8, epsilon=0.1, theta=np.pi / 4)
    dense = np.asarray(A.todense())
    np.testing.assert_allclose(dense, dense.T, atol=1e-12)
    w = np.linalg.eigvalsh(dense)
    assert w.min() > 0


@pytest.mark.slow
def test_gmg_converges(tpu_backend):
    import gmg
    import common

    gmg.np = common.np
    gmg.sparse = common.sparse
    gmg.linalg = common.linalg
    gmg.use_tpu = True

    A = common.poisson2D(16)
    solver = gmg.GMG(A=A, shape=(16, 16), levels=2, smoother="jacobi",
                     gridop="linear")
    M = solver.linear_operator()
    rng = np.random.default_rng(3)
    b = rng.random(16 * 16)
    from legate_sparse_tpu.linalg import cg

    x, iters = cg(A, b, rtol=1e-10, maxiter=200, M=M)
    res = np.linalg.norm(b - np.asarray(A @ x)) / np.linalg.norm(b)
    assert res < 1e-9
    # Preconditioning must beat plain CG on iteration count.
    _, iters_plain = cg(A, b, rtol=1e-10, maxiter=500)
    assert int(iters) < int(iters_plain)


def test_gmg_galerkin_operators(tpu_backend):
    import gmg
    import common

    gmg.np = common.np
    gmg.sparse = common.sparse
    gmg.linalg = common.linalg

    A = common.poisson2D(8)
    R, dim = gmg.linear_operator(8 * 8)
    assert dim == 16
    P = R.T
    Ac = R @ A @ P
    ref = (
        R.toscipy() @ A.toscipy() @ P.toscipy()
    )
    np.testing.assert_allclose(
        np.asarray(Ac.todense()), ref.toarray(), atol=1e-12
    )


def test_pde_operator_matches_scipy(tpu_backend):
    import pde
    import common

    pde.np = common.np
    pde.sparse = common.sparse

    nx = ny = 10
    A = pde.d2_mat_dirichlet_2d(nx, ny, 0.1, 0.1)
    n = nx - 2
    # scipy reference construction of the same operator.
    a = g = 1.0 / 0.1**2
    c = -2 * a - 2 * g
    I = scsp.eye(n)
    T = scsp.diags([a, c / 2, a], [-1, 0, 1], shape=(n, n))
    ref = scsp.kron(I, T) + scsp.kron(
        scsp.diags([g, c / 2, g], [-1, 0, 1], shape=(n, n)), I
    )
    np.testing.assert_allclose(
        np.asarray(A.todense()), ref.toarray(), atol=1e-9
    )


@pytest.mark.slow
def test_pde_distributed_operator_and_solve(tpu_backend):
    """pde.py --distributed path: the shard-locally built operator
    (dist_diags, no host CSR) equals the host build, and the collective
    CG converges to the same solution."""
    import pde
    import common

    pde.np = common.np
    pde.sparse = common.sparse

    import jax
    import jax.numpy as jnp

    from legate_sparse_tpu.parallel.dist_build import dist_diags
    from legate_sparse_tpu.parallel.dist_csr import dist_cg
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    nx = ny = 12
    dx = dy = 0.1
    A_host = pde.d2_mat_dirichlet_2d(nx, ny, dx, dy)
    a = 1.0 / dx**2
    g = 1.0 / dy**2
    c = -2.0 * a - 2.0 * g
    m = nx - 2
    n = m * (ny - 2)

    def off1(i):
        return jnp.where((i + 1) % m == 0, 0.0, a)

    mesh = make_row_mesh(jax.devices("cpu")[:4])
    dA = dist_diags([c, off1, off1, g, g], [0, 1, -1, m, -m],
                    shape=(n, n), mesh=mesh, dtype=np.float64)
    np.testing.assert_allclose(
        dA.to_csr().todense(), np.asarray(A_host.todense()), atol=1e-12
    )
    b = np.ones(n)
    x, iters = dist_cg(dA, b, rtol=1e-10)
    res = np.linalg.norm(b - A_host.toscipy() @ np.asarray(x))
    assert res <= 1e-8 * np.linalg.norm(b)


@pytest.mark.slow
def test_spectral_example_pipeline(tpu_backend):
    """spectral.py pipeline: clustered graph -> components ->
    normalized Laplacian -> smallest eigenpairs, vs host scipy."""
    import spectral

    import scipy.sparse.csgraph as scsg
    import scipy.sparse.linalg as ssl

    import legate_sparse_tpu as lst
    import legate_sparse_tpu.linalg as llinalg

    rng = np.random.default_rng(0)
    host_A = spectral.clustered_graph(400, 4, p_in=0.05, p_out=0.002,
                                      rng=rng)
    A = lst.csr_array(host_A)
    k, _ = lst.csgraph.connected_components(A, directed=False)
    k_ref, _ = scsg.connected_components(host_A, directed=False)
    assert k == k_ref
    L = lst.csgraph.laplacian(A, normed=True)
    w, _ = llinalg.eigsh(L, k=5, which="SA")
    w_ref = ssl.eigsh(scsg.laplacian(host_A, normed=True).tocsc(),
                      k=5, which="SA", return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), atol=1e-8)
