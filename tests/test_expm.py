# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-native expm_multiply vs scipy (expm.py).

The reference has no matrix-function surface; differential tests in
the house style (small systems vs host scipy).
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as ssl

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


def _rand(n, seed=0, density=0.1):
    rng = np.random.default_rng(seed)
    A_sp = (sp.random(n, n, density=density, format="csr",
                      random_state=rng) - 0.5 * sp.eye(n)).tocsr()
    return A_sp, sparse.csr_array(A_sp), rng


def test_expm_multiply_vector_and_block():
    A_sp, A, rng = _rand(80)
    b = rng.standard_normal(80)
    got = linalg.expm_multiply(A, b)
    ref = ssl.expm_multiply(A_sp, b)
    np.testing.assert_allclose(got, ref, rtol=1e-11, atol=1e-13)
    B = rng.standard_normal((80, 5))
    np.testing.assert_allclose(linalg.expm_multiply(A, B),
                               ssl.expm_multiply(A_sp, B),
                               rtol=1e-11, atol=1e-13)


def test_expm_multiply_linspace_sweep():
    A_sp, A, rng = _rand(60, seed=1)
    b = rng.standard_normal(60)
    got = linalg.expm_multiply(A, b, start=0.0, stop=2.0, num=7)
    ref = ssl.expm_multiply(A_sp, b, start=0.0, stop=2.0, num=7)
    assert got.shape == ref.shape == (7, 60)
    np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)


def test_expm_multiply_complex():
    A_sp, _, rng = _rand(50, seed=2)
    C_sp = (A_sp + 1j * sp.random(50, 50, density=0.05,
                                  random_state=rng)).tocsr()
    b = rng.standard_normal(50).astype(np.complex128)
    np.testing.assert_allclose(
        linalg.expm_multiply(sparse.csr_array(C_sp), b),
        ssl.expm_multiply(C_sp, b), rtol=1e-10, atol=1e-12)


def test_expm_multiply_scaled_identity_and_stiff():
    # A = mu I flows through the general path exactly.
    got = linalg.expm_multiply(sp.eye(10).tocsr() * 2.0, np.ones(10))
    np.testing.assert_allclose(got, np.e ** 2 * np.ones(10), rtol=1e-12)
    # Stiff diagonal: many scaling steps, no overflow of intermediate
    # Taylor terms thanks to the trace shift.
    S_sp = sp.diags([np.linspace(-30, -1, 64)], [0], format="csr")
    np.testing.assert_allclose(
        linalg.expm_multiply(sparse.csr_array(S_sp), np.ones(64)),
        ssl.expm_multiply(S_sp, np.ones(64)), rtol=1e-10, atol=1e-15)


def test_expm_multiply_linear_operator_falls_back():
    # rmatvec is required by scipy's own 1-norm estimator — operators
    # without it cannot run expm_multiply in scipy either.
    A_sp, A, rng = _rand(40, seed=3)
    AT = sparse.csr_array(A_sp.T.tocsr())
    b = rng.standard_normal(40)
    op = linalg.LinearOperator(A.shape, matvec=lambda x: A @ x,
                               rmatvec=lambda x: AT @ x,
                               dtype=np.float64)
    got = linalg.expm_multiply(op, b)
    ref = ssl.expm_multiply(A_sp, b)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-9,
                               atol=1e-12)
