# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""kron / tril / triu / save_npz / load_npz — native implementations
(the reference reaches these only via its scipy-fallback facade clone),
differential vs scipy."""

import io

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.fixture
def S():
    return scsp.random(12, 9, density=0.3, format="csr", random_state=1)


def test_kron_matches_scipy(S):
    S2 = scsp.random(5, 7, density=0.4, format="csr", random_state=2)
    K = sparse.kron(sparse.csr_array(S), sparse.csr_array(S2))
    ref = scsp.kron(S, S2, format="csr")
    assert K.shape == ref.shape
    assert K.nnz == ref.nnz
    np.testing.assert_allclose(np.asarray(K.todense()), ref.toarray(),
                               atol=1e-12)


@pytest.mark.slow
def test_kron_poisson_construction():
    """The classic kron(I,T)+kron(T,I) 2-D Laplacian assembly works
    natively (the pattern the reference's pde test builds via scipy)."""
    n = 8
    T = sparse.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(n, n),
                     format="csr")
    I = sparse.eye(n, format="csr")
    L = sparse.kron(I, T) + sparse.kron(T, I)
    Ts = scsp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(n, n))
    ref = scsp.kron(scsp.eye(n), Ts) + scsp.kron(Ts, scsp.eye(n))
    np.testing.assert_allclose(np.asarray(L.todense()), ref.toarray(),
                               atol=1e-12)


@pytest.mark.parametrize("k", [-2, 0, 3])
def test_tril_triu(S, k):
    A = sparse.csr_array(S)
    np.testing.assert_allclose(
        np.asarray(sparse.tril(A, k).todense()), scsp.tril(S, k).toarray(),
        atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(sparse.triu(A, k).todense()), scsp.triu(S, k).toarray(),
        atol=1e-12,
    )


def test_npz_roundtrip_ours_to_scipy(S):
    buf = io.BytesIO()
    sparse.save_npz(buf, sparse.csr_array(S))
    buf.seek(0)
    np.testing.assert_allclose(scsp.load_npz(buf).toarray(), S.toarray())


def test_npz_roundtrip_scipy_to_ours(S):
    buf = io.BytesIO()
    scsp.save_npz(buf, S)
    buf.seek(0)
    L = sparse.load_npz(buf)
    np.testing.assert_allclose(np.asarray(L.todense()), S.toarray())


def test_npz_csc_container(S):
    buf = io.BytesIO()
    scsp.save_npz(buf, S.tocsc())
    buf.seek(0)
    L = sparse.load_npz(buf)
    np.testing.assert_allclose(np.asarray(L.todense()), S.toarray())


def test_facade_uses_native_implementations():
    import inspect

    for fn in (sparse.kron, sparse.tril, sparse.triu, sparse.save_npz,
               sparse.load_npz):
        mod = inspect.getmodule(inspect.unwrap(fn)).__name__
        assert mod.startswith("legate_sparse_tpu"), (fn, mod)


def test_kron_tril_accept_dia_inputs():
    """eye/diags return dia_array by default; the free functions must
    accept any sparse format (scipy parity)."""
    I = sparse.eye(4)           # dia_array
    B = sparse.diags([1.0, 2.0], [0, 1], shape=(3, 3))  # dia_array
    K = sparse.kron(I, B)
    ref = scsp.kron(scsp.eye(4), scsp.diags([1.0, 2.0], [0, 1],
                                            shape=(3, 3)), format="csr")
    np.testing.assert_allclose(np.asarray(K.todense()), ref.toarray(),
                               atol=1e-12)
    T = sparse.tril(B)
    np.testing.assert_allclose(
        np.asarray(T.todense()),
        scsp.tril(scsp.diags([1.0, 2.0], [0, 1], shape=(3, 3))).toarray(),
        atol=1e-12,
    )


def test_npz_dia_container(S):
    buf = io.BytesIO()
    scsp.save_npz(buf, scsp.diags([np.ones(5)], [0]).todia())
    buf.seek(0)
    L = sparse.load_npz(buf)
    np.testing.assert_allclose(np.asarray(L.todense()), np.eye(5))


def test_save_npz_accepts_dia_and_bf16():
    import jax.numpy as jnp

    buf = io.BytesIO()
    sparse.save_npz(buf, sparse.eye(4))  # dia_array input
    buf.seek(0)
    np.testing.assert_allclose(scsp.load_npz(buf).toarray(), np.eye(4))
    # bf16 values persist bit-exact as raw 16-bit patterns plus a
    # dtype marker (compressed storage checkpoints at its true byte
    # size; tests/test_compressed_storage.py pins the round trip).
    # scipy sees the raw uint16 container — widen with
    # astype_storage(values="float32") before saving when scipy
    # interchange matters.
    A = sparse.diags([1.0, 2.0], [0, 1], shape=(3, 3), format="csr",
                     dtype=jnp.bfloat16)
    buf2 = io.BytesIO()
    sparse.save_npz(buf2, A)
    buf2.seek(0)
    L = sparse.load_npz(buf2)
    assert str(L.dtype) == "bfloat16"
    np.testing.assert_allclose(
        np.asarray(L.todense(), dtype=np.float32),
        np.asarray(A.todense(), dtype=np.float32)
    )
    buf2.seek(0)
    assert scsp.load_npz(buf2).dtype == np.uint16


# ---------------- stacking / random constructors ----------------

def test_spdiags_matches_scipy():
    data = np.array([[1, 2, 3, 4.0], [5, 6, 7, 8.0]])
    ours = sparse.spdiags(data, [0, -1], 4, 4, format="csr")
    theirs = scsp.spdiags(data, [0, -1], 4, 4).tocsr()
    np.testing.assert_allclose(ours.toscipy().toarray(), theirs.toarray())


def test_vstack_matches_scipy(rng):
    A = scsp.random(5, 7, density=0.4, random_state=0).tocsr()
    B = scsp.random(3, 7, density=0.5, random_state=1).tocsr()
    ours = sparse.vstack([sparse.csr_array(A), sparse.csr_array(B)])
    theirs = scsp.vstack([A, B]).tocsr()
    np.testing.assert_allclose(ours.toscipy().toarray(), theirs.toarray())


def test_hstack_matches_scipy(rng):
    A = scsp.random(5, 7, density=0.4, random_state=0).tocsr()
    B = scsp.random(5, 3, density=0.5, random_state=1).tocsr()
    ours = sparse.hstack([sparse.csr_array(A), sparse.csr_array(B)])
    theirs = scsp.hstack([A, B]).tocsr()
    np.testing.assert_allclose(ours.toscipy().toarray(), theirs.toarray())


def test_block_diag_matches_scipy(rng):
    A = scsp.random(4, 5, density=0.5, random_state=0).tocsr()
    B = scsp.random(3, 2, density=0.5, random_state=1).tocsr()
    ours = sparse.block_diag([sparse.csr_array(A), sparse.csr_array(B)])
    theirs = scsp.block_diag([A, B]).tocsr()
    np.testing.assert_allclose(ours.toscipy().toarray(), theirs.toarray())


def test_random_properties():
    A = sparse.random(50, 40, density=0.1, format="csr", rng=0)
    assert A.shape == (50, 40)
    assert A.nnz == round(0.1 * 50 * 40)
    dense = A.toscipy().toarray()
    assert ((dense >= 0) & (dense < 1)).all()


def test_spdiags_square_inference_and_int_input():
    ours = sparse.spdiags(np.array([[1, 2, 3, 4]]), [0])
    theirs = scsp.spdiags(np.array([[1, 2, 3, 4]]), [0])
    assert ours.shape == theirs.shape == (4, 4)
    y = np.asarray(ours.tocsr() @ np.ones(4, dtype=ours.dtype))
    np.testing.assert_allclose(y, theirs @ np.ones(4))


def test_random_legacy_kwargs():
    A = sparse.random(30, 30, density=0.1, format="csr", random_state=42)
    B = sparse.random(30, 30, density=0.1, format="csr",
                      data_rvs=lambda k: np.full(k, 2.5), rng=3)
    assert A.nnz == B.nnz == 90
    assert (np.asarray(B.data) == 2.5).all()


def test_hstack_non_canonical_inputs_not_mislabeled():
    # COO input with duplicate coordinates stays un-coalesced; hstack
    # must not stamp the result canonical (sum_duplicates would no-op).
    A = sparse.csr_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 3),
    )
    assert not A.has_canonical_format
    H = sparse.hstack([A, A])
    assert not H.has_canonical_format
    H.sum_duplicates()
    np.testing.assert_allclose(
        H.toscipy().toarray(),
        np.array([[0, 3.0, 0, 0, 3.0, 0], [0, 0, 0, 0, 0, 0]]),
    )


def test_modern_scipy_array_constructor_names():
    # scipy >= 1.11 sparray-era names must return PACKAGE arrays (not
    # fall through to host scipy types) and match scipy's values.
    import numpy as np
    import scipy.sparse as scsp

    import legate_sparse_tpu as lst

    A = lst.diags_array([1.0, 2.0, 3.0], offsets=0, shape=(3, 3))
    assert A.__class__.__module__.startswith("legate_sparse_tpu")
    np.testing.assert_allclose(np.asarray(A.todense()),
                               np.diag([1.0, 2.0, 3.0]))
    E = lst.eye_array(4, k=1)
    assert E.__class__.__module__.startswith("legate_sparse_tpu")
    np.testing.assert_allclose(np.asarray(E.todense()), np.eye(4, k=1))
    R = lst.random_array((10, 8), density=0.3,
                         random_state=np.random.default_rng(0))
    assert R.__class__.__module__.startswith("legate_sparse_tpu")
    assert R.shape == (10, 8) and 0 < R.nnz <= 80
    I = lst.identity(5)
    assert I.__class__.__module__.startswith("legate_sparse_tpu")
    np.testing.assert_allclose(np.asarray(I.todense()), np.eye(5))
