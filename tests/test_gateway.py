# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Multi-tenant admission gateway drills (docs/ENGINE.md,
docs/RESILIENCE.md).

The gateway's load-bearing contracts, each pinned here:

- **off == inert**: with ``LEGATE_SPARSE_TPU_GATEWAY`` unset, submit is
  a transparent inline dispatch — bit-for-bit the plain ``A.dot`` and
  zero ``gateway.*`` counter movement;
- **WFQ fairness**: batch formation follows virtual finish tags
  (weights 8:4:1), so queued interactive work always leads queued
  background work;
- **typed admission control**: token-bucket (``quota``), per-tenant
  queue bound (``queue_full``), backpressure eviction of the weakest
  request, deadline shedding at admit and at the flush point, breaker
  degraded mode — every rejection is a typed ``outcomes.Rejected``;
- **exactly-once + exact accounting + bitwise parity**, proven under
  composed random faults by the chaos drill
  (``resilience.chaos.run_drill``).
"""

import time

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

import legate_sparse_tpu as lst
from legate_sparse_tpu import obs, resilience
from legate_sparse_tpu.engine import (
    Engine, Gateway, QOS_CLASSES, QOS_WEIGHTS, get_gateway,
    reset_gateway,
)
from legate_sparse_tpu.obs import report as obs_report
from legate_sparse_tpu.resilience import chaos
from legate_sparse_tpu.resilience import deadline as rdeadline
from legate_sparse_tpu.resilience import faults as rfaults
from legate_sparse_tpu.resilience import policy as rpolicy
from legate_sparse_tpu.resilience.outcomes import Rejected
from legate_sparse_tpu.settings import settings

# One engine for the whole module: gateways are cheap, plans are not,
# and sharing the plan cache is exactly the production shape.
_ENG = Engine()


@pytest.fixture
def gw_on():
    """Gateway armed, restored after the test."""
    saved = settings.gateway
    settings.gateway = True
    yield settings
    settings.gateway = saved


_RESIL_KNOBS = (
    "resil", "resil_retries", "resil_backoff_ms", "resil_breaker_k",
    "resil_breaker_cooldown_ms",
)


@pytest.fixture
def armed(gw_on):
    """Gateway + resilience armed (the chaos-drill configuration)."""
    saved = {k: getattr(settings, k) for k in _RESIL_KNOBS}
    settings.resil = True
    settings.resil_backoff_ms = 0.0
    resilience.reset()
    yield settings
    for k, v in saved.items():
        setattr(settings, k, v)
    resilience.reset()


def _random_csr(n=400, density=0.03, seed=0):
    """Engine-eligible random CSR; ``sp.random`` draws EXACTLY
    ``int(density*n*n)`` nonzeros, so different seeds land in the same
    ``(rows_b, cols_b, nnz_b)`` bucket — the cross-matrix pack setup."""
    S = sp.random(n, n, density=density, format="csr",
                  random_state=np.random.default_rng(seed),
                  dtype=np.float32)
    return lst.csr_array(S)


def _tridiag(n=256):
    return lst.diags(
        [np.full(n, 4.0, np.float32), np.full(n - 1, -1.0, np.float32),
         np.full(n - 1, -1.0, np.float32)],
        [0, 1, -1], format="csr", dtype=np.float32)


def _x(n, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(n).astype(np.float32))


def _flush_only(engine=_ENG, **kw):
    """A deterministic gateway: no drain worker (timeout_ms=0), wide
    defaults; tests override the knob under drill."""
    base = dict(max_batch=64, queue_depth=128, tenant_quota=64,
                rate=0.0, burst=16.0, slack_ms=1.0, timeout_ms=0.0)
    base.update(kw)
    return Gateway(engine, **base)


def _delta(c0, c1, name):
    return int(c1.get(name, 0)) - int(c0.get(name, 0))


def _ref(A, x):
    """Reference for every QUEUED serve: the engine's single-request
    bucketed dispatch.  The packed/grouped batch paths are bit-for-bit
    this value (kernel contract); the plain ``A.dot`` may route a
    differently-rounding autotuned kernel and is the reference only
    for the inline paths."""
    return np.asarray(_ENG.matvec(A, x, _checked=True))


# ---------------------------------------------------------------------------
# off-by-default contract
# ---------------------------------------------------------------------------
def test_gateway_off_is_bit_for_bit_and_counter_inert():
    assert settings.gateway is False, "suite must run with GATEWAY unset"
    A = _random_csr(seed=3)
    x = _x(A.shape[1], seed=5)
    expect = np.asarray(A.dot(x))
    gw = Gateway(_ENG)
    c0 = obs.counters.snapshot("gateway.")
    fut = gw.submit(A, x, tenant="off", qos="interactive")
    assert fut.done(), "inert mode resolves inline, no queueing"
    assert np.array_equal(np.asarray(fut.result()), expect)
    c1 = obs.counters.snapshot("gateway.")
    assert c0 == c1, "gateway off must move no gateway.* counters"
    gw.shutdown()


def test_submit_validation_is_mode_independent():
    A = _random_csr(seed=3)
    gw = Gateway(_ENG)
    with pytest.raises(ValueError, match="unknown qos"):
        gw.submit(A, _x(A.shape[1]), qos="platinum")
    with pytest.raises(ValueError, match="does not match"):
        gw.submit(A, _x(A.shape[1] + 1))
    gw.shutdown()


def test_get_gateway_singleton_and_reset(gw_on):
    try:
        g1 = get_gateway()
        assert get_gateway() is g1
        reset_gateway()
        g2 = get_gateway()
        assert g2 is not g1
    finally:
        reset_gateway()


def test_submit_after_shutdown_raises(gw_on):
    gw = _flush_only()
    gw.shutdown()
    A = _random_csr(seed=3)
    with pytest.raises(RuntimeError, match="shut down"):
        gw.submit(A, _x(A.shape[1]))


# ---------------------------------------------------------------------------
# WFQ batch formation
# ---------------------------------------------------------------------------
def test_wfq_interactive_leads_background(gw_on):
    """Background arrives FIRST; WFQ still orders the batch by virtual
    finish tag, so all interactive requests lead."""
    A = _random_csr(seed=3)
    xs = [_x(A.shape[1], seed=s) for s in range(6)]
    gw = _flush_only()
    try:
        futs = []
        for i in range(3):
            futs.append(gw.submit(A, xs[i], tenant="bg",
                                  qos="background"))
        for i in range(3, 6):
            futs.append(gw.submit(A, xs[i], tenant="ia",
                                  qos="interactive"))
        with gw._cv:
            batch = gw._pop_batch_locked()
        assert [r.tenant for r in batch] == ["ia"] * 3 + ["bg"] * 3
        # Virtual-finish-tag math: start at the tenant's last finish,
        # advance by 1/weight.
        w_ia, w_bg = QOS_WEIGHTS["interactive"], QOS_WEIGHTS["background"]
        assert [r.vtag for r in batch[:3]] == [
            (k + 1) / w_ia for k in range(3)]
        assert [r.vtag for r in batch[3:]] == [
            (k + 1) / w_bg for k in range(3)]
        gw._dispatch(batch)
        for i, fut in enumerate(futs):
            assert np.array_equal(np.asarray(fut.result(timeout=30)),
                                  _ref(A, xs[i]))
    finally:
        gw.shutdown()


def test_qos_classes_are_the_eviction_ranking():
    assert QOS_CLASSES == ("interactive", "batch", "background")
    assert (QOS_WEIGHTS["interactive"] > QOS_WEIGHTS["batch"]
            > QOS_WEIGHTS["background"])


# ---------------------------------------------------------------------------
# typed admission control
# ---------------------------------------------------------------------------
def test_token_bucket_rejects_with_quota_reason(gw_on):
    A = _random_csr(seed=3)
    xs = [_x(A.shape[1], seed=s) for s in range(4)]
    gw = _flush_only(rate=0.001, burst=2.0)
    c0 = obs.counters.snapshot("gateway.")
    try:
        futs = [gw.submit(A, x, tenant="limited") for x in xs]
        for fut in futs[2:]:
            out = fut.result(timeout=5)
            assert isinstance(out, Rejected)
            assert out.reason == "quota"
            assert out.site == "gateway.admit"
            assert out.tenant == "limited"
        gw.flush()
        for i, fut in enumerate(futs[:2]):
            assert np.array_equal(np.asarray(fut.result(timeout=30)),
                                  _ref(A, xs[i]))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.rejected.quota") == 2
    # exact per-tenant accounting
    assert _delta(c0, c1, "gateway.tenant.limited.submitted") == 4
    assert _delta(c0, c1, "gateway.tenant.limited.served") == 2
    assert _delta(c0, c1, "gateway.tenant.limited.shed") == 2


def test_tenant_quota_rejects_noisy_tenant_only(gw_on):
    A = _random_csr(seed=3)
    xs = [_x(A.shape[1], seed=s) for s in range(6)]
    gw = _flush_only(tenant_quota=2)
    c0 = obs.counters.snapshot("gateway.")
    try:
        noisy = [gw.submit(A, x, tenant="noisy") for x in xs[:5]]
        calm = gw.submit(A, xs[5], tenant="calm", qos="interactive")
        for fut in noisy[2:]:
            out = fut.result(timeout=5)
            assert isinstance(out, Rejected)
            assert out.reason == "queue_full"
        gw.flush()
        assert np.array_equal(np.asarray(calm.result(timeout=30)),
                              _ref(A, xs[5]))
        for i, fut in enumerate(noisy[:2]):
            assert np.array_equal(np.asarray(fut.result(timeout=30)),
                                  _ref(A, xs[i]))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.rejected.queue_full") == 3
    assert _delta(c0, c1, "gateway.tenant.calm.shed") == 0


def test_backpressure_evicts_weakest_class(gw_on):
    """Queue full + stronger arrival: the queued background request is
    evicted (typed ``queue_full``), never the interactive ones."""
    A = _random_csr(seed=3)
    xs = [_x(A.shape[1], seed=s) for s in range(3)]
    gw = _flush_only(queue_depth=2)
    c0 = obs.counters.snapshot("gateway.")
    try:
        f_ia1 = gw.submit(A, xs[0], tenant="ia", qos="interactive")
        f_bg = gw.submit(A, xs[1], tenant="bg", qos="background")
        f_ia2 = gw.submit(A, xs[2], tenant="ia", qos="interactive")
        out = f_bg.result(timeout=5)
        assert isinstance(out, Rejected)
        assert out.reason == "queue_full"
        assert out.tenant == "bg"
        gw.flush()
        assert np.array_equal(np.asarray(f_ia1.result(timeout=30)),
                              _ref(A, xs[0]))
        assert np.array_equal(np.asarray(f_ia2.result(timeout=30)),
                              _ref(A, xs[2]))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.evicted") == 1


def test_backpressure_rejects_weak_incoming(gw_on):
    """Queue full of interactive work + background arrival: the
    incoming request IS the weakest and is the one rejected — queued
    strong work is never displaced by weaker traffic."""
    A = _random_csr(seed=3)
    xs = [_x(A.shape[1], seed=s) for s in range(3)]
    gw = _flush_only(queue_depth=2)
    try:
        strong = [gw.submit(A, x, tenant="ia", qos="interactive")
                  for x in xs[:2]]
        weak = gw.submit(A, xs[2], tenant="bg", qos="background")
        out = weak.result(timeout=5)
        assert isinstance(out, Rejected)
        assert out.reason == "queue_full"
        assert out.tenant == "bg"
        gw.flush()
        for i, fut in enumerate(strong):
            assert np.array_equal(np.asarray(fut.result(timeout=30)),
                                  _ref(A, xs[i]))
    finally:
        gw.shutdown()


def test_ineligible_matrix_served_inline(gw_on):
    """A structure-specialized matrix (banded -> DIA fast path) skips
    the queue entirely: inline service, ``gateway.inline`` counter."""
    A = _tridiag()
    x = _x(A.shape[1], seed=9)
    gw = _flush_only()
    c0 = obs.counters.snapshot("gateway.")
    try:
        fut = gw.submit(A, x, tenant="banded")
        assert fut.done()
        assert np.array_equal(np.asarray(fut.result()),
                              np.asarray(A.dot(x)))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.inline") == 1
    assert _delta(c0, c1, "gateway.tenant.banded.served") == 1


# ---------------------------------------------------------------------------
# deadline-aware batching (needs resil: deadline scopes)
# ---------------------------------------------------------------------------
def test_urgent_request_dispatches_immediately(armed):
    """A near-deadline request is never held for a fuller batch: its
    arrival seeds an immediate dispatch that also drains same-bucket
    queued work."""
    A = _random_csr(seed=3)
    x0, x1 = _x(A.shape[1], seed=0), _x(A.shape[1], seed=1)
    gw = _flush_only(slack_ms=10_000.0)
    c0 = obs.counters.snapshot("gateway.")
    try:
        f0 = gw.submit(A, x0, tenant="calm")          # no deadline
        assert not f0.done(), "queued, waiting for a batch"
        with rdeadline.scope(5_000.0):                # slack <= 10s
            f1 = gw.submit(A, x1, tenant="urgent",
                           qos="interactive")
        assert f0.done() and f1.done(), \
            "urgent arrival must dispatch NOW, taking batchmates along"
        assert np.array_equal(np.asarray(f0.result()), _ref(A, x0))
        assert np.array_equal(np.asarray(f1.result()), _ref(A, x1))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.dispatches") == 1
    assert _delta(c0, c1, "gateway.dispatched_requests") == 2


def test_expired_deadline_shed_at_admission(armed):
    A = _random_csr(seed=3)
    gw = _flush_only()
    try:
        with rdeadline.scope(0.0):
            fut = gw.submit(A, _x(A.shape[1]), tenant="storm")
        out = fut.result(timeout=5)
        assert isinstance(out, Rejected)
        assert out.reason == "deadline_shed"
        assert out.site == "gateway.admit"
        assert out.deadline_ms == 0.0
    finally:
        gw.shutdown()


def test_deadline_expiring_in_queue_shed_at_dispatch(armed):
    """A request that expires while queued is triaged at the flush
    point (site ``gateway.dispatch``), not served late."""
    A = _random_csr(seed=3)
    gw = _flush_only()          # slack_ms=1: 50ms budget is not urgent
    try:
        with rdeadline.scope(50.0):
            fut = gw.submit(A, _x(A.shape[1]), tenant="late")
        assert not fut.done()
        time.sleep(0.06)
        gw.flush()
        out = fut.result(timeout=5)
        assert isinstance(out, Rejected)
        assert out.reason == "deadline_shed"
        assert out.site == "gateway.dispatch"
    finally:
        gw.shutdown()


def test_breaker_degraded_mode(armed):
    """Dispatch breaker open: deferrable classes shed typed
    ``breaker``; interactive traffic degrades to inline service."""
    A = _random_csr(seed=3)
    x = _x(A.shape[1], seed=2)
    br = rpolicy.breaker("gateway.dispatch")
    for _ in range(settings.resil_breaker_k):
        br.record_failure()
    assert br.state == "open"
    gw = _flush_only()
    c0 = obs.counters.snapshot("gateway.")
    try:
        out = gw.submit(A, x, tenant="bt",
                        qos="batch").result(timeout=5)
        assert isinstance(out, Rejected)
        assert out.reason == "breaker"
        f_ia = gw.submit(A, x, tenant="ia", qos="interactive")
        assert f_ia.done()
        assert np.array_equal(np.asarray(f_ia.result()),
                              np.asarray(A.dot(x)))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.rejected.breaker") == 1
    assert _delta(c0, c1, "gateway.breaker_inline") == 1


# ---------------------------------------------------------------------------
# cross-matrix packing
# ---------------------------------------------------------------------------
def test_cross_matrix_batch_packs_one_dispatch(gw_on):
    """Two different matrices in one shape bucket pack into a single
    stacked dispatch (``gateway.packed``), bit-for-bit per request."""
    A1, A2 = _random_csr(seed=3), _random_csr(seed=4)
    assert A1.nnz == A2.nnz, "same density -> same nnz -> same bucket"
    xs = [_x(A1.shape[1], seed=s) for s in range(4)]
    mats = [A1, A2, A1, A2]
    gw = _flush_only(max_batch=4)
    c0 = obs.counters.snapshot("gateway.")
    try:
        futs = [gw.submit(M, x, tenant=f"t{i % 2}")
                for i, (M, x) in enumerate(zip(mats, xs))]
        # The 4th submit reached max_batch and dispatched in-thread.
        for fut, M, x in zip(futs, mats, xs):
            assert fut.done()
            assert np.array_equal(np.asarray(fut.result()),
                                  _ref(M, x))
    finally:
        gw.shutdown()
    c1 = obs.counters.snapshot("gateway.")
    assert _delta(c0, c1, "gateway.dispatches") == 1
    assert _delta(c0, c1, "gateway.packed") == 1
    assert _delta(c0, c1, "gateway.dispatched_requests") == 4


def test_same_matrix_batch_is_bitwise(gw_on):
    """Multiple requests against ONE matrix take the stacked-matmat
    group path; each column must equal the single-request dispatch."""
    A = _random_csr(seed=3)
    xs = [_x(A.shape[1], seed=s) for s in range(3)]
    gw = _flush_only()
    try:
        futs = [gw.submit(A, x, tenant="one") for x in xs]
        gw.flush()
        for fut, x in zip(futs, xs):
            assert np.array_equal(np.asarray(fut.result(timeout=30)),
                                  _ref(A, x))
    finally:
        gw.shutdown()


# ---------------------------------------------------------------------------
# chaos drill: composed faults under live multi-tenant load
# ---------------------------------------------------------------------------
def test_chaos_drill_requires_armed_system():
    with pytest.raises(RuntimeError, match="needs settings.gateway"):
        chaos.run_drill(None, tenants=[])


def test_chaos_drill_isolation_invariants(armed):
    """The acceptance drill: randomized faults from the closed catalog
    (admit/dispatch/engine sites) + a deadline-storm background tenant,
    composed under live load.  Invariants (chaos module docstring):
    exactly-once resolution, exact counter accounting, bitwise parity
    — AND the good tenant rides through untouched."""
    A_good, A_storm = _random_csr(seed=3), _random_csr(seed=4)
    xs_good = [_x(A_good.shape[1], seed=s) for s in range(3)]
    xs_storm = [_x(A_storm.shape[1], seed=s) for s in range(10, 13)]
    gw = _flush_only(max_batch=8)
    try:
        report = chaos.run_drill(
            gw,
            tenants=[
                {"name": "good", "qos": "interactive",
                 "A": A_good, "xs": xs_good},
                {"name": "storm", "qos": "background",
                 "A": A_storm, "xs": xs_storm, "deadline_ms": 0.0},
            ],
            rounds=4, seed=7)
    finally:
        gw.shutdown()
    assert report.ok(), report.violations
    assert report.submitted == 24
    assert report.served + report.shed + report.errors == 24
    assert report.faults_armed >= 4, "every round arms at least one"
    # Isolation: the storm tenant's expired flood and the injected
    # faults never cost the good tenant a single request.
    good = report.per_tenant["good"]
    assert good["submitted"] == 12
    assert good["served"] == 12
    assert good["shed"] == 0 and good["error"] == 0
    storm = report.per_tenant["storm"]
    assert storm["submitted"] == 12
    assert storm["shed"] >= 1, "a 0ms deadline storm must shed"
    # A drill leaves no armed state behind.
    assert not rfaults.armed()
    assert rpolicy.breaker("gateway.dispatch").state == "closed"


def test_chaos_drill_device_loss_recovery_under_load(armed):
    """ISSUE 15 satellite: the drill's seeded ``device_loss`` scenario
    runs the full recovery ladder (shrink -> reshard -> restore ->
    resume) while the round's gateway submissions are still queued.
    Invariants checked inside the scenario: exactly-once resolution,
    exact ``resil.recovery.*`` deltas per round, and scipy-differential
    parity of the recovered solution — any violation lands in
    ``report.violations``."""
    from legate_sparse_tpu.parallel import shard_csr

    dA = shard_csr(_tridiag(256))
    if dA.num_shards < 2:
        pytest.skip("needs >= 2 devices")
    A_good = _random_csr(seed=3)
    xs_good = [_x(A_good.shape[1], seed=s) for s in range(3)]
    gw = _flush_only(max_batch=8)
    try:
        report = chaos.run_drill(
            gw,
            tenants=[{"name": "good", "qos": "interactive",
                      "A": A_good, "xs": xs_good}],
            rounds=2, seed=11,
            device_loss={"A": dA, "b": np.ones(256, np.float32),
                         "rtol": 1e-8, "conv_test_iters": 5,
                         "ckpt_iters": 10})
    finally:
        gw.shutdown()
    assert report.ok(), report.violations
    assert report.recoveries == 2           # one recovery per round
    # The live load rode through the losses untouched.
    good = report.per_tenant["good"]
    assert good["served"] == good["submitted"] == 6
    assert good["shed"] == 0 and good["error"] == 0
    assert not rfaults.armed()


# ---------------------------------------------------------------------------
# ledger rendering
# ---------------------------------------------------------------------------
def test_gateway_ledger_renders_per_tenant_table(gw_on):
    A = _random_csr(seed=3)
    gw = _flush_only(tenant_quota=1)
    try:
        gw.submit(A, _x(A.shape[1], seed=0), tenant="render_a",
                  qos="interactive")
        gw.submit(A, _x(A.shape[1], seed=1), tenant="render_a")
        gw.flush()
    finally:
        gw.shutdown()
    table = obs_report.render_gateway_table(obs.counters.snapshot())
    assert "render_a" in table
    assert "submitted" in table and "queue_full" in table
    # and the empty-counters fallback is graceful
    assert "never engaged" in obs_report.render_gateway_table({})
