# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""GMRES tests (mirrors reference ``test_gmres_solve.py``)."""

import numpy as np

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg
from utils_test.gen import spd_system, random_dense


def test_gmres_solve():
    N = 200
    A_dense, x = spd_system(N, 0.1, 471014)
    A = sparse.csr_array(A_dense)
    y = A @ x
    x_pred, iters = linalg.gmres(A, y, tol=1e-10, restart=40, maxiter=4000)
    resid = np.linalg.norm(np.asarray(A @ x_pred) - np.asarray(y))
    assert resid < 1e-8 * np.linalg.norm(np.asarray(y)) + 1e-6


def test_gmres_nonsymmetric():
    N = 120
    rng = np.random.default_rng(5)
    A_dense = random_dense(N, N, 0.2, 3) + N * np.eye(N)
    A = sparse.csr_array(A_dense)
    x = rng.standard_normal(N)
    y = A @ x
    x_pred, _ = linalg.gmres(A, y, tol=1e-10, restart=30, maxiter=3000)
    np.testing.assert_allclose(np.asarray(x_pred), x, atol=1e-5)


def test_gmres_restrt_alias():
    N = 60
    A_dense, x = spd_system(N, 0.3, 11)
    A = sparse.csr_array(A_dense)
    y = A @ x
    x_pred, _ = linalg.gmres(A, y, tol=1e-10, restrt=20)
    resid = np.linalg.norm(np.asarray(A @ x_pred) - np.asarray(y))
    assert resid < 1e-6
