# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sync-free GMRES restart cycles (PR 2 tentpole).

The restart cycle — Arnoldi, progressive Givens QR of the Hessenberg,
triangular solve, solution update — runs as ONE traced program with no
host transfer anywhere in the cycle body; the driver's single
stacked-scalar fetch per cycle (``transfer.host_sync.gmres_conv``) is
the whole convergence cadence.  These tests pin (a) differential
agreement with scipy across f32/f64/c64 including restart boundaries,
(b) the zero-transfer-inside-a-cycle property through the obs
counters, for both ``gmres`` and ``dist_gmres``.
"""

import numpy as np
import pytest

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg
from legate_sparse_tpu.obs import counters

from utils_test.gen import random_dense


def _system(n, dtype, seed):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) * 0.1 + n * np.eye(n)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        A = A + 1j * rng.standard_normal((n, n)) * 0.1
    A = A.astype(dtype)
    x = rng.standard_normal(n).astype(dtype)
    return A, x, A @ x


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex64])
@pytest.mark.parametrize("restart", [1, 7, 40])
def test_gmres_differential_vs_scipy(dtype, restart):
    """Same solution as scipy's gmres on the same system at the same
    tolerance (both converge to the true x here, so the comparison is
    to x and to each other)."""
    import scipy.sparse as sp
    import scipy.sparse.linalg as ssl

    n = 80
    A_d, x_true, b = _system(n, dtype, 7)
    A = sparse.csr_array(A_d)
    x_pkg, _ = linalg.gmres(A, b, rtol=1e-6, restart=restart,
                            maxiter=2000)
    x_sp, info = ssl.gmres(sp.csr_matrix(A_d), b, rtol=1e-6,
                           restart=restart, maxiter=2000)
    assert info == 0
    tol = 2e-3 if np.dtype(dtype).itemsize <= 8 else 1e-6
    np.testing.assert_allclose(np.asarray(x_pkg), x_true, atol=tol,
                               rtol=tol)
    np.testing.assert_allclose(np.asarray(x_pkg), x_sp, atol=tol,
                               rtol=tol)


@pytest.mark.parametrize("restart", [79, 80, 200])
def test_gmres_restart_at_and_past_n(restart):
    """Restart boundary cases: restart == n-1, == n, and > n (clamped
    to n) — the cycle shapes the Givens QR must handle exactly."""
    n = 80
    A_d, x_true, b = _system(n, np.float64, 11)
    x_pkg, _ = linalg.gmres(sparse.csr_array(A_d), b, rtol=1e-10,
                            restart=restart, maxiter=1600)
    np.testing.assert_allclose(np.asarray(x_pkg), x_true, atol=1e-7)


def test_gmres_happy_breakdown_rank_deficient_cycle():
    """b lies in a tiny Krylov space (A = I + rank-1): the Arnoldi
    breaks down mid-cycle, leaving trailing zero columns in R — the
    guarded back-substitution must return the exact solution, like the
    host ``lstsq`` it replaced."""
    n = 50
    rng = np.random.default_rng(3)
    u = rng.standard_normal(n)
    A_d = np.eye(n) + np.outer(u, u) / n
    b = rng.standard_normal(n)
    x_pkg, _ = linalg.gmres(sparse.csr_array(A_d), b, rtol=1e-12,
                            restart=30, maxiter=600)
    np.testing.assert_allclose(np.asarray(A_d @ np.asarray(x_pkg)), b,
                               atol=1e-9)


def test_gmres_exact_x0_keeps_solution():
    """Converged at entry: the driver must keep x0 (beta < atol at
    cycle start) and report 0 iterations."""
    n = 40
    A_d, x_true, b = _system(n, np.float64, 5)
    x_pkg, iters = linalg.gmres(sparse.csr_array(A_d), b, x0=x_true,
                                rtol=1e-8, restart=10, maxiter=100)
    assert iters == 0
    np.testing.assert_allclose(np.asarray(x_pkg), x_true, atol=1e-12)


def test_gmres_preconditioned_matches_plain():
    """Right-preconditioned path (M inside the cycle) reaches the same
    solution."""
    n = 90
    A_d, x_true, b = _system(n, np.float64, 13)
    M = np.diag(1.0 / np.diag(A_d))
    x_pkg, _ = linalg.gmres(sparse.csr_array(A_d), b, M=M, rtol=1e-10,
                            restart=25, maxiter=2000)
    np.testing.assert_allclose(np.asarray(x_pkg), x_true, atol=1e-7)


def _transfer_deltas(before, after):
    keys = set(before) | set(after)
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in keys
            if k.startswith("transfer.")
            and after.get(k, 0) != before.get(k, 0)}


def test_gmres_cycle_is_host_sync_free():
    """The obs transfer counters assert the tentpole property: C full
    restart cycles perform exactly C convergence-cadence fetches and
    NOTHING else — no per-cycle Hessenberg transfer (the old
    ``transfer.host_sync.gmres_beta`` + host lstsq path is gone)."""
    n = 64
    rng = np.random.default_rng(2)
    A_d = (rng.standard_normal((n, n)) * 0.05 + np.eye(n)).astype(
        np.float32)
    A = sparse.csr_array(A_d)
    b = np.ones(n, np.float32)
    restart, cycles = 8, 5
    # Warm structure caches + compile outside the counted region.
    _ = linalg.gmres(A, b, rtol=0.0, atol=0.0, restart=restart,
                     maxiter=cycles * restart)

    before = counters.snapshot("transfer.")
    _, iters = linalg.gmres(A, b, rtol=0.0, atol=0.0, restart=restart,
                            maxiter=cycles * restart)
    deltas = _transfer_deltas(before, counters.snapshot("transfer."))
    assert iters == cycles * restart
    # rtol=atol=0 never converges, so no confirm sync: exactly one
    # cadence fetch per cycle and zero other transfer counters.
    assert deltas == {"transfer.host_sync.gmres_conv": cycles}, deltas


def test_dist_gmres_cycle_is_host_sync_free():
    """Same property through the distributed driver: per-cycle host
    syncs stay at one cadence fetch; shard uploads happen at setup
    only (their count must not scale with the cycle count)."""
    from legate_sparse_tpu.parallel import (dist_gmres, make_row_mesh,
                                            shard_csr)

    n = 64
    rng = np.random.default_rng(4)
    A_d = (rng.standard_normal((n, n)) * 0.05 + np.eye(n)).astype(
        np.float32)
    dA = shard_csr(sparse.csr_array(A_d), mesh=make_row_mesh(1))
    b = np.ones(n, np.float32)
    restart = 8

    def run(cycles):
        before = counters.snapshot("transfer.")
        _, iters = dist_gmres(dA, b, rtol=0.0, atol=0.0,
                              restart=restart,
                              maxiter=cycles * restart)
        assert iters == cycles * restart
        return _transfer_deltas(before, counters.snapshot("transfer."))

    run(2)                      # warm compiles/caches
    d2, d6 = run(2), run(6)
    assert d2.get("transfer.host_sync.gmres_conv") == 2
    assert d6.get("transfer.host_sync.gmres_conv") == 6
    # Everything else (shard uploads of b/x0 at setup) is cycle-count
    # independent: only the cadence counter may differ between runs.
    d2.pop("transfer.host_sync.gmres_conv")
    d6.pop("transfer.host_sync.gmres_conv")
    assert d2 == d6, (d2, d6)


def test_gmres_convergence_cadence_confirms_true_residual():
    """A solve that converges must still satisfy the TRUE residual
    (the Givens estimate alone can drift optimistic in f32): the
    driver's confirm sync guards it."""
    n = 120
    A_d, x_true, b = _system(n, np.float32, 17)
    x_pkg, iters = linalg.gmres(sparse.csr_array(A_d), b, rtol=1e-5,
                                restart=30, maxiter=3000)
    resid = np.linalg.norm(A_d @ np.asarray(x_pkg) - b)
    assert resid < 1e-5 * np.linalg.norm(b) * 10
    assert 0 < iters < 3000
